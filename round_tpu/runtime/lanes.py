"""Lane-batched instance serving: live consensus instances as ONE lane axis.

PR 5's host-wire roofline (PERF_MODEL.md) showed the host runtime is
round-DRIVER-bound, not wire-bound: the batched engine simulates thousands
of rounds/sec while the per-instance drivers decide ~25-45/sec, because
every live instance runs its own Python round loop with per-round jitted
dispatches.  Comm-closed rounds are the license to collapse that gap
("reducing asynchrony to synchronized rounds"): a whole round's traffic for
MANY instances is one batch operation, so this module inverts the driver's
control flow — the unit of work becomes "one round of L instances" instead
of "one instance's round".

Shape:
  * instances are LANES of the engine's batch axis
    (engine/executor.py LaneStep): one jitted mega-step — vmapped
    send/update over a ``[L, ...]`` state pytree with a ragged per-lane
    round vector + active mask — advances every ready instance per
    dispatch; instances at different rounds batch together when they share
    the round CLASS (``rounds[r % k]``), else bucket by class;
  * the Python host loop is reduced to draining FLAG_BATCH frames into
    per-lane ``[L, n, ...]`` mailboxes (the in-place PR-5 arrays grown a
    lane axis), launching the mega-step, and flushing per-lane sends —
    which coalesce ACROSS lanes into one container per peer per wave;
  * admission: instances join/retire lanes between dispatches with NO
    recompile (runtime/instances.py LaneTable pads to a small set of
    lane-count buckets; the compiled signature never changes mid-run).

Equivalence contract (tests/test_lanes.py): for the same seeds this driver
produces BYTE-IDENTICAL per-instance decisions to the per-instance
drivers — both trace exactly the same per-lane math
(engine/executor.py make_host_round_fns, PRNG derivation included), heard
sets match under the same fault schedule (chaos faults are per LOGICAL
frame, so lane packing never changes which frames fault), and
checkpoint/resume keeps the decision-log format of run_instance_loop.

Not supported here: live view changes (runtime/view.py — the sequential
loop remains the membership-change driver) and the
``send_when_catching_up=False`` experiment.
"""

from __future__ import annotations

import collections
import os
import pickle
import time as _time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.rounds import Round, RoundCtx
from round_tpu.engine.executor import (
    lane_decide, lane_sample_rows, lane_step,
)
from round_tpu.obs.metrics import METRICS
from round_tpu.obs.trace import TRACE
from round_tpu.runtime import codec
from round_tpu.runtime.host import (
    _UNDECIDED, AdaptiveTimeout, _save_decision_checkpoint, _schedule_value,
    _try_send_decision, decision_scalar, instance_io, pump_coerce_encode,
)
from round_tpu.runtime.instances import AdmissionControl, LaneTable
from round_tpu.runtime.log import get_logger
from round_tpu.runtime.oob import (
    FLAG_DECISION, FLAG_NACK, FLAG_NORMAL, FLAG_PROPOSE, FLAG_READ,
    FLAG_SNAP, FLAG_SUBSCRIBE, FLAG_TOO_LATE, FLAG_TXN,
    FLEET_MAX_INSTANCE, FLEET_MIN_INSTANCE, Tag,
)
from round_tpu.runtime.transport import RoundPump

log = get_logger("lanes")

# lanes.* vocabulary (docs/OBSERVABILITY.md).  host.* counters (rounds,
# sends, recvs, timeouts, decisions, malformed) are shared with the
# per-instance drivers — same names resolve to the same instruments — so
# dashboards see one host runtime regardless of driver.
_C_DISPATCH = METRICS.counter("lanes.dispatches")
_C_SEND_D = METRICS.counter("lanes.send_dispatches")
_C_UPD_D = METRICS.counter("lanes.update_dispatches")
_C_GO_D = METRICS.counter("lanes.go_dispatches")
_C_ADMIT = METRICS.counter("lanes.admitted")
_C_RETIRE = METRICS.counter("lanes.retired")
_C_LANE_OOB = METRICS.counter("lanes.oob_decisions")
_G_OCC = METRICS.gauge("lanes.occupancy")
_G_WIDTH = METRICS.gauge("lanes.width")
_H_IPD = METRICS.histogram(
    "lanes.instances_per_dispatch",
    (1, 2, 4, 8, 16, 32, 64, 128, 256, 512), unit="instances")
_C_ROUNDS = METRICS.counter("host.rounds")
_C_SENDS = METRICS.counter("host.sends")
_C_RECVS = METRICS.counter("host.recvs")
_C_TIMEOUTS = METRICS.counter("host.timeouts")
_C_MALFORMED = METRICS.counter("host.malformed")
_C_DECISIONS = METRICS.counter("host.decisions")
_C_CATCHUP = METRICS.counter("host.catch_ups")
# stash visibility (docs/OBSERVABILITY.md): capped eviction used to be
# SILENT, which read as frame loss in trace_view — now every evicted
# entry counts and the live depth is a gauge
_C_STASH_EVICT = METRICS.counter("lanes.stash_evictions")
_G_STASH_DEPTH = METRICS.gauge("lanes.stash_depth")
# client-serving vocabulary (runtime/fleet.py, docs/SERVING.md): the
# driver side of the fleet protocol — proposals accepted off the wire
# and decisions streamed back to clients/subscribers
_C_CLIENT_PROPS = METRICS.counter("lanes.client_proposals")
_C_CLIENT_STREAM = METRICS.counter("lanes.client_streams")
_G_CLIENT_QUEUE = METRICS.gauge("lanes.client_queue")
# overload vocabulary (docs/HOST_FAULT_MODEL.md "overload, shedding and
# quarantine"): every shed is accounted — shed_frames == nacks_sent +
# nacks_suppressed is the invariant the host-overload soak rung gates
_C_SHED_FRAMES = METRICS.counter("overload.shed_frames")
_C_SHED_INSTANCES = METRICS.counter("overload.shed_instances")
_C_NACKS_SENT = METRICS.counter("overload.nacks_sent")
_C_NACKS_SUPP = METRICS.counter("overload.nacks_suppressed")
_C_NACKS_SEEN = METRICS.counter("overload.nacks_seen")
_G_QUEUED = METRICS.gauge("overload.queued_bytes")
_G_SHEDDING = METRICS.gauge("overload.shedding")
# per-tenant overload vocabulary (docs/SERVING.md "per-tenant
# admission"): the same shed accounting NAMESPACED by the tenant id a
# client frame carries in Tag.call_stack — the fleet-autoscale soak rung
# gates shed_frames == nacks_sent + nacks_suppressed PER TENANT
_C_T_SHED_FRAMES = METRICS.counter("tenant.shed_frames")
_C_T_SHED_INSTANCES = METRICS.counter("tenant.shed_instances")
_C_T_NACKS_SENT = METRICS.counter("tenant.nacks_sent")
_C_T_NACKS_SUPP = METRICS.counter("tenant.nacks_suppressed")
_G_T_SHEDDING = METRICS.gauge("tenant.shedding")

_STASH_CAP = 4096  # same eviction discipline as InstanceMux._STASH_CAP
_DONE_CAP = 8192   # client-serving decision-bank cap (_retire_lane)

# per-class progress kinds (parsed once from Round.init_progress)
_P_TIMEOUT, _P_WAIT, _P_GOAHEAD, _P_SYNC = range(4)


class _ClassBox:
    """One round class's lane mailboxes: decoded payloads write IN PLACE
    into preallocated ``[L, n, ...]`` arrays + an ``[L, n]`` mask — the
    PR-5 _RoundMailbox grown a lane axis, and exactly the vals/mask the
    mega-step update consumes with ZERO restacking.  Rows are reset as
    lanes enter the class's round; the arrays live for the driver's
    lifetime, so the steady state allocates nothing."""

    __slots__ = ("n", "width", "treedef", "vals", "mask", "count", "_sig",
                 "on_malformed")

    def __init__(self, n: int, width: int, on_malformed=None):
        self.n, self.width = n, width
        self.treedef = None
        self.vals: List[np.ndarray] = []
        self.mask = np.zeros((width, n), dtype=bool)
        self.count = np.zeros((width,), dtype=np.int64)
        self._sig = None
        # structural-garbage sink: keeps the driver's malformed counters
        # in parity with _RoundMailbox.insert (host.malformed must read
        # the same whichever driver served the run)
        self.on_malformed = on_malformed

    def reset_row(self, lane: int, like: Any) -> None:
        leaves, treedef = jax.tree_util.tree_flatten(like)
        sig = (treedef, tuple((np.shape(x), np.asarray(x).dtype)
                              for x in leaves))
        if self._sig is None:
            self._sig = sig
            self.treedef = treedef
            self.vals = [
                np.zeros((self.width, self.n) + np.shape(x),
                         dtype=np.asarray(x).dtype)
                for x in leaves
            ]
        elif sig != self._sig:
            # payload shape is static per (algorithm, round class, n) —
            # a mismatch is a driver bug, not wire garbage
            raise RuntimeError(
                f"round-class payload signature changed mid-run: {sig} "
                f"!= {self._sig}")
        for a in self.vals:
            a[lane] = 0
        self.mask[lane] = False
        self.count[lane] = 0

    def insert(self, lane: int, sender: int, payload: Any) -> bool:
        """Write one sender's payload into (lane, sender); True when the
        lane's heard-set grew.  Structural garbage (wrong tree/leaf
        shape/dtype) drops per sender — same byzantine tolerance as
        _RoundMailbox.insert."""
        try:
            leaves = jax.tree_util.tree_flatten(payload)[0]
            if len(leaves) != len(self.vals):
                raise ValueError(
                    f"{len(leaves)} leaves != {len(self.vals)}")
            for slot, leaf in zip(self.vals, leaves):
                arr = np.asarray(leaf)
                if arr.shape != slot.shape[2:]:
                    raise ValueError(
                        f"leaf shape {arr.shape} != {slot.shape[2:]}")
                slot[lane, sender] = arr.astype(slot.dtype,
                                                casting="same_kind")
        except Exception as e:  # noqa: BLE001 — garbage must not kill us
            if self.mask[lane, sender]:
                self.mask[lane, sender] = False
                self.count[lane] -= 1
            for slot in self.vals:
                slot[lane, sender] = 0
            if self.on_malformed is not None:
                self.on_malformed(sender)
            log.debug("lane %d: dropping structurally-malformed payload "
                      "from %d: %s", lane, sender, e)
            return False
        if not self.mask[lane, sender]:
            self.mask[lane, sender] = True
            self.count[lane] += 1
            return True
        return False

    def values_mask(self):
        return (jax.tree_util.tree_unflatten(self.treedef, self.vals),
                self.mask)


class _IntakeQueue:
    """Client-proposal intake, namespaced by tenant: one FIFO deque per
    tenant plus a global arrival sequence.  The tenant-blind pop
    (``pop_fifo``) follows strict arrival order across every deque —
    byte-identical scheduling to the single pre-tenant deque — while the
    weighted-fair path pops one tenant's head in O(1) and meters queued
    BYTES per tenant, the unit TenantAdmission's watermark arithmetic
    runs in (runtime/instances.py, docs/SERVING.md)."""

    __slots__ = ("_q", "_bytes", "_len", "_seq")

    def __init__(self):
        self._q: Dict[int, collections.deque] = {}
        self._bytes: Dict[int, int] = {}
        self._len = 0
        self._seq = 0

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    def push(self, tenant: int, iid: int, io, sender: int,
             nbytes: int) -> None:
        q = self._q.get(tenant)
        if q is None:
            q = self._q[tenant] = collections.deque()
        q.append((self._seq, iid, io, sender, nbytes))
        self._seq += 1
        self._bytes[tenant] = self._bytes.get(tenant, 0) + int(nbytes)
        self._len += 1

    def append(self, item) -> None:
        """Legacy single-deque surface — a 3-tuple (iid, io, sender)
        lands in the tenant-0 deque (tests drive the admission path
        through this)."""
        iid, io, sender = item
        arr = io.get("initial_value") if isinstance(io, dict) else None
        self.push(0, iid, io, sender, int(getattr(arr, "nbytes", 0)))

    def bytes_by_tenant(self) -> Dict[int, int]:
        return dict(self._bytes)

    def tenants_queued(self) -> List[int]:
        return [t for t, q in self._q.items() if q]

    def queued(self, tenant: int) -> int:
        q = self._q.get(tenant)
        return len(q) if q else 0

    def _pop(self, tenant: int):
        q = self._q[tenant]
        _seq, iid, io, sender, nb = q.popleft()
        b = self._bytes.get(tenant, 0) - nb
        self._bytes[tenant] = b if b > 0 else 0
        if not q:
            del self._q[tenant]
            self._bytes.pop(tenant, None)
        self._len -= 1
        return iid, io, sender

    def pop_tenant(self, tenant: int):
        return self._pop(tenant)

    def pop_fifo(self):
        best_t = None
        best_seq = None
        for t, q in self._q.items():
            if q and (best_seq is None or q[0][0] < best_seq):
                best_t, best_seq = t, q[0][0]
        return (best_t,) + self._pop(best_t)

    def drain_tenant(self, tenant: int):
        out = []
        while self._q.get(tenant):
            out.append(self._pop(tenant))
        return out

    def items(self):
        """(iid, io, sender) over every queued proposal, any order."""
        for q in self._q.values():
            for _seq, iid, io, sender, _nb in q:
                yield iid, io, sender


class LaneDriver:
    """Drive up to ``lanes`` concurrent consensus instances of ONE replica
    as lanes of the engine's batch axis (module docstring).  The driver is
    single-threaded and owns the transport drain — the InstanceMux router
    thread and per-instance worker threads of the pipelined driver are
    replaced by mailbox routing inside the tick loop."""

    def __init__(
        self,
        algo: Algorithm,
        my_id: int,
        peers: Dict[int, Tuple[str, int]],
        transport,
        lanes: int = 16,
        timeout_ms: int = 300,
        seed: int = 0,
        base_value: int = 0,
        max_rounds: int = 32,
        nbr_byzantine: int = 0,
        value_schedule: str = "mixed",
        adaptive: Optional[AdaptiveTimeout] = None,
        wire: str = "binary",
        wait_cap_ms: int = 30_000,
        use_pump: bool = True,
        admission: Optional[AdmissionControl] = None,
        health=None,
        clients=None,
        rv=None,
        snap=None,
        kv=None,
        tenants=None,
    ):
        if wire not in ("binary", "pickle"):
            raise ValueError(f"wire must be 'binary' or 'pickle', "
                             f"got {wire!r}")
        self.algo = algo
        self.id = my_id
        self.n = len(peers)
        self.transport = transport
        self.timeout_ms = timeout_ms
        self.seed = seed
        self.base_value = base_value
        self.max_rounds = max_rounds
        self.value_schedule = value_schedule
        self.adaptive = adaptive
        self.wire = wire
        self.wait_cap_ms = wait_cap_ms
        if not 0 <= nbr_byzantine < self.n:
            raise ValueError(
                f"nbr_byzantine={nbr_byzantine} must be in [0, n={self.n})")
        self.nbr_byzantine = nbr_byzantine
        for pid, (host, port) in peers.items():
            if pid != my_id:
                transport.add_peer(pid, host, port)

        self.k = len(algo.rounds)
        self.table = LaneTable(lanes)
        self.L = self.table.width
        _G_WIDTH.set(self.L)
        n, L = self.n, self.L

        # batched lane state (numpy leaves, ALWAYS writable: admission
        # writes init rows in place between dispatches)
        self._treedef = None
        self._state: List[np.ndarray] = []
        self._sid = np.int32(my_id)
        self._seeds = np.zeros((L,), dtype=np.uint32)
        self._rr = np.zeros((L,), dtype=np.int32)

        # per-lane control plane
        self._inst = np.zeros((L,), dtype=np.int64)       # 0 = free slot
        self._live = np.zeros((L,), dtype=bool)
        self._need_send = np.zeros((L,), dtype=bool)
        self._waiting = np.zeros((L,), dtype=bool)
        self._dirty = np.zeros((L,), dtype=bool)
        self._deadline = np.full((L,), np.inf)
        self._t0 = np.zeros((L,))
        self._use_deadline = np.zeros((L,), dtype=bool)
        self._delegated = np.zeros((L,), dtype=bool)
        self._expected = np.full((L,), n, dtype=np.int64)
        # the RAW pre-quarantine threshold, kept for health blame
        # attribution (runtime/health.py note_round goal=)
        self._expected_raw = np.full((L,), n, dtype=np.int64)
        self._max_rnd = np.full((L, n), -1, dtype=np.int64)
        self._next_round = np.zeros((L,), dtype=np.int64)
        self._oob_done = np.zeros((L,), dtype=bool)
        self._pending: List[Dict[int, Dict[int, Any]]] = [
            {} for _ in range(L)]

        # per-class machinery
        self._boxes = [_ClassBox(n, L, on_malformed=self._note_malformed)
                       for _ in range(self.k)]
        self._steps: List[Optional[Any]] = [None] * self.k
        self._prog = [self._parse_progress(rnd) for rnd in algo.rounds]
        self._expected_static = [
            type(rnd).expected_nbr_messages is Round.expected_nbr_messages
            for rnd in algo.rounds
        ]
        self._decide_fn = None

        # wire plumbing (the PR-5 hot path, shared with HostRunner)
        self._scratch = codec.Scratch() if wire == "binary" else None
        self._sendb = (getattr(transport, "send_buffered", None)
                       if wire == "binary" else None)
        self._flushfn = (getattr(transport, "flush", None)
                         if wire == "binary" else None)
        if self._flushfn is None:
            self._sendb = None
        self._recv_many = getattr(transport, "recv_many", None)

        # NATIVE ROUND PUMP (native/transport.cpp rt_pump_*): the receive
        # state machine — batch split, codec-template parse, in-place
        # mailbox fill, arrival counts, deadlines, catch-up bookkeeping —
        # runs inside the transport event loop, and this driver blocks in
        # ONE pump.wait per wave instead of the 50 ms drain tick.  The
        # Python pump above stays as the A/B baseline and the automatic
        # fallback (no native support, ROUND_TPU_PUMP=0, tracing — the
        # per-frame send/recv trace vocabulary needs the Python path —
        # receiver-side chaos families, pickle wire, or a payload outside
        # the fixed-layout vocabulary).
        self._pump = None
        self._pump_send = False
        self._arm_specs = bytearray()
        self._arm_count = 0
        self._wave = bytearray()
        self._entries = bytearray()
        self._entry_count = 0
        self._goahead_armed: set = set()
        if (use_pump and wire == "binary" and not TRACE.enabled
                and os.environ.get("ROUND_TPU_PUMP", "1") != "0"):
            self._setup_pump()

        # instance-level bookkeeping
        self._done: Dict[int, Optional[np.ndarray]] = {}  # iid -> raw
        self._replied: Dict[Tuple[int, int], float] = {}
        self._enc_cache: Dict[int, bytes] = {}
        self._stash: Dict[int, List[Tuple[int, Tag, bytes]]] = {}
        self._stash_order: collections.deque = collections.deque()
        self._stash_count = 0  # LIVE stashed entries (the order deque may
        # carry stale ids for already-admitted instances; they age out in
        # the eviction loop — the cap gates on this count, not deque len)
        self._init_cache: Dict[Tuple, List[np.ndarray]] = {}
        self.malformed = 0
        self.timeouts = 0
        self.rounds_run = 0   # cumulative across every lane and instance
        self._trajectory: List[int] = []
        # overload hardening (docs/HOST_FAULT_MODEL.md): admission budget
        # + load shedding (None = the polite pre-overload world, zero
        # behavior change) and the peer-quarantine health scorer
        # (runtime/health.py; shrinks the round-progress threshold so a
        # quarantined peer stops pacing every round wave)
        self._admission = admission
        self._health = health
        self._stash_bytes = 0
        self._pending_bytes = 0   # live bytes across all lanes' pending
        self._nacked: Dict[Tuple[int, int], float] = {}
        self._pending_sizes: List[Dict[int, int]] = [{} for _ in range(L)]
        self.shed_frames = 0
        self.shed_instances = 0
        self.nacks_sent = 0
        self.nacks_suppressed = 0
        # fleet client protocol (runtime/fleet.py, docs/SERVING.md):
        # ``clients`` names transport sender ids OUTSIDE the consensus
        # group (the front-door router) whose frames speak
        # FLAG_PROPOSE / FLAG_SUBSCRIBE instead of the round protocol.
        # Proposals queue here until a lane frees; decisions stream back
        # to the proposer (and any subscriber) as FLAG_DECISION, with
        # FLAG_TOO_LATE for an instance that finished undecided and the
        # accounted FLAG_NACK while shedding.  Empty set = the
        # pre-fleet driver, byte-identical behavior.
        self._clients = frozenset(clients or ())
        # per-tenant weighted-fair admission (instances.TenantAdmission,
        # docs/SERVING.md "per-tenant admission"): client frames carry a
        # tenant id in Tag.call_stack; None = the tenant-blind driver,
        # byte-identical pre-tenant behavior (every frame lands in the
        # tenant-0 deque and pops in strict arrival order)
        self._tenants = tenants
        self._tenant_stats: Dict[int, Dict[str, int]] = {}
        self._proposals = _IntakeQueue()
        self._proposed: set = set()
        self._client_of: Dict[int, int] = {}
        self._subscribers: set = set()
        self.client_proposals = 0
        self.client_streams = 0
        # the canonical proposal shape/dtype (instance_io's contract for
        # this algorithm): client values are validated against it AT THE
        # PROTOCOL BOUNDARY — several algorithms' make_init_state happily
        # broadcasts an alien-shaped array, and the first admission
        # defines the driver's state-tree shapes, so an unvalidated
        # garbage proposal would poison the whole shard (or crash the
        # serve loop at the next jitted dispatch)
        self._io_proto = (np.asarray(instance_io(algo, 0)["initial_value"])
                          if self._clients else None)
        # RUNTIME VERIFICATION (round_tpu/rv, docs/RUNTIME_VERIFICATION
        # .md): ``rv`` is an rv.dump.RvConfig — compile the algorithm's
        # monitor program and fuse its per-lane verdict term into the
        # update mega-step (engine/executor.py LaneStep).  The carried
        # monitor state (prior decided mask + values for irrevocability,
        # peer-learned decisions for agreement, the instance's initial-
        # value matrix for validity) threads through the lane axis like
        # any other per-lane array.  None = monitors off, byte-identical
        # pre-rv behavior.
        self._rv = None
        self._rv_mon = None
        if rv is not None:
            from round_tpu.rv.compile import monitor_program
            from round_tpu.rv.dump import RvRuntime

            self._rv_mon = monitor_program(algo, n)
            if self._rv_mon is None:
                log.warning("node %d: rv requested but %s has no "
                            "decision plane to monitor; rv disabled",
                            my_id, type(algo).__name__)
            else:
                self._rv = RvRuntime(rv, node=my_id, n=n, seed=seed,
                                     max_rounds=max_rounds)
                (self._rv_prev_dec, self._rv_prev_val, self._rv_ext_dec,
                 self._rv_ext_val, self._rv_init) = self._rv_mon.zeros(L)
                self._rv_client_inst: set = set()
                self._rv_shed_lanes: set = set()
                self._rv_init_cache: Dict[int, np.ndarray] = {}
        # ROUND-CONSISTENT SNAPSHOTS (round_tpu/snap, docs/SNAPSHOTS.md):
        # ``snap`` is a snap.audit.SnapConfig — sample this replica's
        # per-lane state at round boundaries (deterministic policy, byte-
        # budgeted through the SAME admission control so audit traffic
        # can never starve serving) and, on the collector replica,
        # assemble cuts + run the batched full-state audit.  None =
        # snapshots off, byte-identical pre-snap behavior.
        self._snap = None
        if snap is not None:
            from round_tpu.snap.driver import SnapDriver

            self._snap = SnapDriver(
                snap, algo, node=my_id, n=n, seed=seed,
                max_rounds=max_rounds, transport=transport,
                value_schedule=value_schedule, base_value=base_value,
                admission=admission)
        # REPLICATED KV SERVING (round_tpu/kv, docs/KV.md): ``kv`` is a
        # kv.store.KVShard — decided client instances additionally apply
        # to its per-replica state machine IN DECISION ORDER, FLAG_READ
        # frames serve the three read grades (linearizable reads queue
        # behind their write barrier + one serve wave; lease/stale answer
        # inline), and FLAG_TXN rides the PROPOSE machinery with record
        # validation.  None = kv off, byte-identical pre-kv behavior.
        self._kv = kv
        self._kv_reads: List[Any] = []   # queued linearizable reads
        self._kv_wave = 0                # serve-loop wave counter
        self._kv_prev_rounds = 0         # lease-freshness deltas
        self._kv_prev_timeouts = 0

    # -- native pump setup -------------------------------------------------

    def _setup_pump(self) -> None:
        """Try to attach the native round pump: derive each round class's
        fixed-byte-layout template (host._payload_layouts — abstract
        eval_shape over the send, cached on the round objects),
        pre-allocate the class boxes and register every (lane, class)
        mailbox slot by pointer.  Any miss — transport without the pump
        surface, a payload outside the fixed-layout vocabulary — leaves
        the driver on the Python pump (the fallback contract)."""
        mk = getattr(self.transport, "enable_pump", None)
        if mk is None:
            return
        from round_tpu.runtime.host import _payload_layouts

        layouts = _payload_layouts(self.algo, self.id, self.n)
        if layouts is None:
            return  # outside the fixed-layout vocabulary
        pump = mk(self.L, self.n, self.k, self.nbr_byzantine)
        if pump is None:
            return
        for c, (exemplar, (tmpl, holes)) in enumerate(layouts):
            box = self._boxes[c]
            box.reset_row(0, exemplar)  # allocate [L, n, ...] + fix sig
            for a in box.vals:
                a[0] = 0
            for lane in range(self.L):
                pump.set_class(lane, c, tmpl, holes, box.vals,
                               lane_index=lane, mask=box.mask,
                               count=box.count, per_lane=True)
        self._pump = pump
        # the catch-up bookkeeping arrays are now SHARED with the native
        # side (max_rnd written per frame there, next_round recomputed on
        # future-round arrivals; Python keeps writing its own row/slot at
        # round advance — disjoint elements, monotone values)
        self._max_rnd = pump.max_rnd
        self._max_rnd.fill(-1)
        self._next_round = pump.next_round
        self._pump_send = bool(getattr(self.transport, "pump_send_ok",
                                       False))

    # -- static per-class progress ----------------------------------------

    def _parse_progress(self, rnd) -> Tuple[int, bool, int]:
        """(kind, strict, millis_or_k).  A round that keeps the Round-class
        default DELEGATES to the runner's configured timeout (fixed or
        adaptive) — the _round_progress rule of the per-instance driver."""
        p = rnd.init_progress
        if p is Round.init_progress:
            return (_P_TIMEOUT, False, -1)  # -1: resolve per lane at entry
        if p.is_timeout:
            return (_P_TIMEOUT, p.is_strict, int(p.timeout_millis))
        if p.is_go_ahead:
            return (_P_GOAHEAD, False, 0)
        if p.is_sync:
            return (_P_SYNC, True, int(p.k))
        return (_P_WAIT, p.is_strict, 0)

    # -- state pytree helpers ----------------------------------------------

    def _state_tree(self):
        return jax.tree_util.tree_unflatten(self._treedef, self._state)

    def _copy_back(self, tree) -> None:
        self._state = [np.array(x) for x in jax.tree_util.tree_leaves(tree)]

    def _state_row(self, lane: int):
        return jax.tree_util.tree_unflatten(
            self._treedef, [leaf[lane] for leaf in self._state])

    def _write_row(self, lane: int, leaves: List[np.ndarray]) -> None:
        for dst, src in zip(self._state, leaves):
            dst[lane] = src

    # -- admission ---------------------------------------------------------

    def _init_leaves(self, io) -> List[np.ndarray]:
        """Per-lane init state leaves for one instance's io pytree —
        cached by initial-value bytes (schedules draw from a tiny domain
        and clients re-propose the same values, so admission is an array
        write, not an eager trace).  The key carries dtype+shape: a
        client byte vector must never collide with a scalar whose raw
        bytes happen to match."""
        v = np.asarray(io["initial_value"])
        key = (v.dtype.str, v.shape, v.tobytes())
        if len(self._init_cache) >= 512:
            # scheduled values draw from a ~5-value domain, but client-
            # driven serving (serve()) proposes arbitrary values — a
            # long-lived shard must not cache one init state per
            # instance it ever served (the _nacked map discipline)
            self._init_cache.clear()
        got = self._init_cache.get(key)
        if got is None:
            ctx = RoundCtx(id=np.int32(self.id), n=self.n, r=np.int32(0))
            st = self.algo.make_init_state(ctx, io)
            got = [np.asarray(x) for x in jax.tree_util.tree_leaves(st)]
            if self._treedef is None:
                self._treedef = jax.tree_util.tree_structure(st)
                self._state = [
                    np.zeros((self.L,) + x.shape, dtype=x.dtype)
                    for x in got
                ]
            self._init_cache[key] = got
        return got

    def _rv_reset_lane(self, lane: int, inst: int, client_io) -> None:
        """Fresh monitor state for one admitted instance: no decision
        history, no peer decision heard, and the validity witness rows —
        the deterministic schedule matrix, or the client proposal
        broadcast to all n (the fleet's uniform-proposal contract)."""
        from round_tpu.rv.compile import schedule_init_values

        iid = inst & 0xFFFF
        self._rv_prev_dec[lane] = False
        self._rv_prev_val[lane] = 0
        self._rv_ext_dec[lane] = False
        self._rv_ext_val[lane] = 0
        self._rv_shed_lanes.discard(lane)
        if client_io is not None:
            self._rv_client_inst.add(iid)
            self._rv_init[lane] = np.asarray(
                client_io["initial_value"])[None]
        else:
            self._rv_client_inst.discard(iid)
            # the witness matrix is deterministic in (schedule, base,
            # inst) and the schedule draws from a ~5-value domain —
            # cache it like _init_leaves caches init states, so hot
            # admission does not rebuild n io pytrees per instance
            key = inst % 5 if self.value_schedule in ("mixed", "uniform") \
                else inst
            got = self._rv_init_cache.get(key)
            if got is None:
                if len(self._rv_init_cache) >= 64:
                    self._rv_init_cache.clear()
                got = schedule_init_values(
                    self.algo, self.n, self.value_schedule,
                    self.base_value, inst)
                self._rv_init_cache[key] = got
            self._rv_init[lane] = got

    def _rv_values(self, inst: int) -> List[int]:
        """The artifact ``values`` row: per-process scheduled proposals
        (client-proposed instances have no scalar schedule — the dump
        records zeros and the meta block carries the observed plane)."""
        if inst & 0xFFFF in getattr(self, "_rv_client_inst", ()):
            return [0] * self.n
        return [_schedule_value(self.value_schedule, self.base_value,
                                pid, inst) for pid in range(self.n)]

    def _admit(self, inst: int, io=None) -> None:
        iid = inst & 0xFFFF
        lane = self.table.admit(iid)
        if io is None:
            value = _schedule_value(self.value_schedule, self.base_value,
                                    self.id, inst)
            io = instance_io(self.algo, value)
            client_io = None
        else:
            client_io = io
        self._write_row(lane, self._init_leaves(io))
        self._inst[lane] = inst
        self._seeds[lane] = np.uint32(self.seed + inst)
        self._rr[lane] = 0
        self._live[lane] = True
        self._need_send[lane] = True
        self._waiting[lane] = False
        self._dirty[lane] = False
        self._oob_done[lane] = False
        if self._pump is not None:
            # maps iid -> lane natively and resets the shared catch-up
            # rows; frames for this instance now take the fast path
            self._pump.open_lane(lane, iid)
        self._max_rnd[lane] = -1
        self._max_rnd[lane, self.id] = 0
        self._next_round[lane] = 0
        self._pending[lane] = {}
        if self._rv is not None:
            self._rv_reset_lane(lane, inst, client_io)
        if self._snap is not None and client_io is not None:
            # the fleet's uniform-proposal contract: the client scalar
            # IS every pid's proposal row (artifact values + the
            # auditor's init reconstruction seed)
            self._snap.note_client_value(
                inst, decision_scalar(
                    np.asarray(client_io["initial_value"])))
        _C_ADMIT.inc()
        _G_OCC.set(self.table.occupancy)
        if TRACE.enabled:
            TRACE.emit("lane_admit", node=self.id, inst=iid, lane=lane)
        self._pending_bytes -= sum(self._pending_sizes[lane].values())
        self._pending_sizes[lane] = {}
        # replay start-skew traffic stashed before admission (the
        # defaultHandler lazy-join role) — it lands in pending[0].  The
        # order deque keeps its now-stale iid entries; eviction skips them
        replay = self._stash.pop(iid, [])
        self._stash_count -= len(replay)
        self._stash_bytes -= sum(len(r[2]) for r in replay)
        _G_STASH_DEPTH.set(self._stash_count)
        for got in replay:
            self._ingest(got)

    # -- wire in -----------------------------------------------------------

    def _note_malformed(self, sender: Optional[int] = None) -> None:
        self.malformed += 1
        _C_MALFORMED.inc()
        if self._health is not None and sender is not None:
            # hostile-frame rate is a quarantine signal (runtime/health.py)
            self._health.note_malformed(sender)

    def _loads(self, raw, sender: Optional[int] = None) -> Tuple[bool, Any]:
        if not raw:
            return True, None
        try:
            return True, codec.loads(raw)
        except Exception as e:  # noqa: BLE001 — any garbage must survive
            self._note_malformed(sender)
            log.debug("node %d: dropping malformed payload (%d bytes): %s",
                      self.id, len(raw), e)
            return False, None

    def _shed_frame(self, sender: int, iid: int,
                    tenant: Optional[int] = None) -> None:
        """Refuse one future-instance frame under load shedding: counted,
        and answered with a rate-limited FLAG_NACK so the sender can tell
        a shed from wire loss.  Accounting invariant (the host-overload
        soak rung gates it): every shed ticks exactly one of nacks_sent /
        nacks_suppressed.  Under per-tenant metering ``tenant`` is the
        client frame's Tag.call_stack byte (None = unattributed — peer
        sheds, or the tenant-blind driver) and the SAME invariant holds
        per tenant: tenant.shed_frames == tenant.nacks_sent +
        tenant.nacks_suppressed (the fleet-autoscale rung gates it); the
        NACK reply echoes the tenant id in call_stack so the router can
        attribute it without an inflight lookup."""
        self.shed_frames += 1
        _C_SHED_FRAMES.inc()
        ts = None
        if tenant is not None and self._tenants is not None:
            ts = self._tenant_stats.setdefault(
                tenant, {"shed_frames": 0, "shed_instances": 0,
                         "nacks_sent": 0, "nacks_suppressed": 0})
            ts["shed_frames"] += 1
            _C_T_SHED_FRAMES.inc()
            if TRACE.enabled:
                TRACE.emit("tenant_shed", node=self.id, inst=iid,
                           src=sender, tenant=tenant)
        now = _time.monotonic()
        if now - self._nacked.get((sender, iid), -1.0) <= 0.25:
            self.nacks_suppressed += 1
            _C_NACKS_SUPP.inc()
            if ts is not None:
                ts["nacks_suppressed"] += 1
                _C_T_NACKS_SUPP.inc()
            return
        if len(self._nacked) >= 8192:
            # the rate-limit map must not become its own overload vector
            # (cleared BEFORE the insert so the entry recorded for this
            # NACK survives to suppress its own repeats)
            self._nacked.clear()
        self._nacked[(sender, iid)] = now
        self.transport.send(sender, Tag(instance=iid, flag=FLAG_NACK,
                                        call_stack=tenant or 0))
        self.nacks_sent += 1
        _C_NACKS_SENT.inc()
        if ts is not None:
            ts["nacks_sent"] += 1
            _C_T_NACKS_SENT.inc()
        if TRACE.enabled:
            TRACE.emit("shed", node=self.id, inst=iid, src=sender)

    # -- fleet client protocol (runtime/fleet.py, docs/SERVING.md) ---------

    def _client_frame(self, sender: int, tag: Tag, raw) -> None:
        """One frame from a CLIENT peer (the fleet front door).  PROPOSE
        is idempotent — that is what makes the client's retry loop and
        its decision catch-up the same message: live/queued instances
        absorb it, completed ones answer with the (possibly re-)missed
        FLAG_DECISION / FLAG_TOO_LATE, and shedding answers with the
        accounted FLAG_NACK (the same shed_frames == nacks_sent +
        nacks_suppressed invariant as peer shedding)."""
        if tag.flag == FLAG_SUBSCRIBE:
            self._subscribers.add(sender)
            return
        if tag.flag == FLAG_READ:
            # the kv read verb (round_tpu/kv, docs/KV.md): lease/stale
            # grades answer inline from applied state, linearizable
            # reads queue behind their write barrier + one serve wave —
            # and SHED like proposals under admission pressure (lease/
            # stale stay served while shedding: they cost no lane)
            if self._kv is not None:
                self._kv_read_frame(sender, tag, raw)
            return
        if tag.flag == FLAG_TXN and self._kv is None:
            # the txn verb needs a kv shard to validate against
            self._note_malformed(sender)
            self.transport.send(sender, Tag(instance=tag.instance,
                                            flag=FLAG_TOO_LATE))
            return
        if tag.flag not in (FLAG_PROPOSE, FLAG_TXN):
            return  # decisions/NACKs are client->driver only downstream
        iid = tag.instance
        if not FLEET_MIN_INSTANCE <= iid <= FLEET_MAX_INSTANCE:
            # reserved-id proposals are refused at the UNTRUSTED shard
            # boundary too (the router enforces the same range): id 0
            # is the free-slot marker and 0xFF00.. belongs to view-
            # change consensus — a hostile client must not run data
            # rounds on a membership id
            self._note_malformed(sender)
            self.transport.send(sender,
                                Tag(instance=iid, flag=FLAG_TOO_LATE))
            return
        if iid in self._done:
            d = self._done[iid]
            if d is not None:
                _try_send_decision(self.transport, self._replied, sender,
                                   iid, d, enc_cache=self._enc_cache)
            else:
                self.transport.send(sender,
                                    Tag(instance=iid, flag=FLAG_TOO_LATE))
            return
        if self.table.lane_of(iid) is not None or iid in self._proposed:
            return  # running or queued: the retry is absorbed
        # the tenant id rides the otherwise-free call_stack byte on the
        # client verbs (runtime/oob.py); tenant-blind drivers fold every
        # frame into tenant 0 so the intake pops strict arrival order
        tenant = (tag.call_stack & 0xFF) if self._tenants is not None \
            else 0
        if self._tenants is not None \
                and self._tenants.is_shedding(tenant):
            # a hot tenant sheds against its OWN weighted share — before
            # the driver-wide budget is even consulted
            self._shed_frame(sender, iid, tenant=tenant)
            return
        if ((self._admission is not None and self._admission.shedding)
                or len(self._proposals) >= _STASH_CAP):
            self._shed_frame(
                sender, iid,
                tenant=tenant if self._tenants is not None else None)
            return
        ok, payload = self._loads(raw, sender)
        if not ok or payload is None:
            if payload is None and ok:
                self._note_malformed(sender)  # empty proposal: no value
            return
        arr = np.asarray(payload)
        proto = self._io_proto
        if arr.shape != proto.shape or not np.can_cast(
                arr.dtype, proto.dtype, casting="same_kind"):
            # a proposal that can never become THIS algorithm's initial
            # value: refuse with the give-up signal (a NACK would make
            # the client retry something unservable forever)
            self._note_malformed(sender)
            self.transport.send(sender,
                                Tag(instance=iid, flag=FLAG_TOO_LATE))
            return
        # own the bytes: decode is zero-copy into the receive drain
        # buffer, and a queued proposal outlives the drain (the
        # adopt_decision discipline)
        arr = (arr.astype(proto.dtype) if arr.dtype != proto.dtype
               else np.array(arr))
        if tag.flag == FLAG_TXN and not self._kv.is_txn_record(arr):
            # FLAG_TXN is PROPOSE's state machine plus payload
            # validation (runtime/oob.py): a non-transaction record
            # on the txn verb is refused with the give-up signal
            self._note_malformed(sender)
            self.transport.send(sender,
                                Tag(instance=iid, flag=FLAG_TOO_LATE))
            return
        if self._kv is not None:
            # register the write barrier for linearizable reads
            self._kv.note_propose(iid, arr)
        self._proposals.push(tenant, iid, {"initial_value": arr}, sender,
                             arr.nbytes)
        self._proposed.add(iid)
        self._client_of[iid] = sender
        self.client_proposals += 1
        _C_CLIENT_PROPS.inc()
        _G_CLIENT_QUEUE.set(len(self._proposals))
        if TRACE.enabled:
            TRACE.emit("client_propose", node=self.id, inst=iid,
                       src=sender)

    def _stream_decision(self, iid: int, decided: bool, raw) -> None:
        """Stream one completed instance to its proposer + subscribers:
        FLAG_DECISION with the raw decision, FLAG_TOO_LATE when it
        finished undecided (the value is unrecoverable — the client's
        give-up signal)."""
        targets = list(self._subscribers)
        c = self._client_of.pop(iid, None)
        if c is not None and c not in self._subscribers:
            targets.append(c)
        for t in targets:
            if decided and raw is not None:
                _try_send_decision(self.transport, self._replied, t, iid,
                                   raw, enc_cache=self._enc_cache)
            else:
                self.transport.send(t, Tag(instance=iid,
                                           flag=FLAG_TOO_LATE))
            self.client_streams += 1
            _C_CLIENT_STREAM.inc()

    # -- kv serving (round_tpu/kv, docs/KV.md) -----------------------------

    def _kv_read_frame(self, sender: int, tag: Tag, raw) -> None:
        """One FLAG_READ frame: lease/stale answer inline (no lane, no
        consensus — served even while shedding), linearizable reads
        queue behind their write barrier + one serve wave, and SHED with
        the same accounted NACK as proposals under admission pressure
        (Tag.instance carries the 16-bit read id for correlation)."""
        from round_tpu.kv import reads as _kvr

        req = _kvr.decode_read(bytes(raw) if raw is not None else b"")
        if req is None:
            self._note_malformed(sender)
            return
        if _kvr.serve_read(self._kv, sender, req["r"], req["k"],
                           req["g"], self.transport):
            return
        # linearizable reads cost a lane wave, so they shed per tenant
        # too (the kv key space is tenant-namespaced by the client's key
        # prefix; the read verb carries the tenant in call_stack)
        r_tenant = (tag.call_stack & 0xFF) if self._tenants is not None \
            else None
        if ((self._admission is not None and self._admission.shedding)
                or len(self._kv_reads) >= _STASH_CAP
                or (r_tenant is not None
                    and self._tenants.is_shedding(r_tenant))):
            self._shed_frame(sender, tag.instance, tenant=r_tenant)
            return
        self._kv.reads_lin += 1
        _kvr.C_READS[_kvr.GRADE_LIN].inc()
        self._kv_reads.append(_kvr.PendingRead(
            sender, req["r"], req["k"],
            self._kv.barrier_for(req["k"]), self._kv_wave))

    def _kv_tick(self) -> None:
        """One serve wave's kv work: advance the wave counter, feed the
        lease clock (a round wave that advanced by THRESHOLD — not
        deadline — heard a quorum inside one round trip; works on both
        the Python and native pumps, which never surface per-peer frames
        here), revoke the lease for good once the rv monitor has
        recorded any violation, and release queued linearizable reads
        whose write barrier drained at least one full wave ago."""
        from round_tpu.kv import reads as _kvr

        dr = self.rounds_run - self._kv_prev_rounds
        dt = self.timeouts - self._kv_prev_timeouts
        self._kv_prev_rounds = self.rounds_run
        self._kv_prev_timeouts = self.timeouts
        # the wave is a ROUND wave, not a serve-loop iteration: a
        # queued linearizable read must see actual round progress
        # before it answers (the read-index cost — this is what makes
        # a lease read an order of magnitude cheaper).  An idle lane
        # table runs no rounds, so idleness itself advances the wave:
        # per-link FIFO already ordered the read after every acked
        # write's apply, and there is nothing in flight to wait out.
        if dr > 0 or not self.table.occupancy:
            self._kv_wave += 1
        if dr > dt:
            self._kv.lease.note_quorum()
        if (self._rv is not None
                and getattr(self._rv, "violations", None)):
            self._kv.lease.revoke()
        if not self._kv_reads:
            return
        keep = []
        for pr in self._kv_reads:
            if pr.ready(self._kv.pending, self._kv_wave):
                seq, val = self._kv.answer(pr.key)
                self.transport.send(
                    pr.sender, _kvr.read_tag(pr.rid),
                    _kvr.encode_reply(pr.rid, _kvr.ST_OK, seq, val))
            else:
                keep.append(pr)
        self._kv_reads = keep

    def _kv_fail_reads(self) -> None:
        """Best-effort on a halt: refuse every queued linearizable read
        so clients fall to their retry/give-up path immediately."""
        from round_tpu.kv import reads as _kvr

        for pr in self._kv_reads:
            try:
                self.transport.send(
                    pr.sender, _kvr.read_tag(pr.rid),
                    _kvr.encode_reply(pr.rid, _kvr.ST_REFUSED, 0, b""))
            except Exception:  # noqa: BLE001 — the halt still propagates
                pass
        self._kv_reads = []

    def _ingest(self, got) -> None:
        sender, tag, raw = got
        if not 0 <= sender < self.n:
            if sender in self._clients:
                # fleet client protocol: the front-door router's frames
                # ride the same wire but are NOT round traffic
                self._client_frame(sender, tag, raw)
                return
            self.malformed += 1
            _C_MALFORMED.inc()
            return
        if self._kv is not None:
            # any peer frame is lease-freshness evidence (the Python
            # pump path; the native pump feeds note_quorum via _kv_tick)
            self._kv.lease.note_peer(sender)
        if tag.flag == FLAG_NACK:
            # a peer SHED our frame (admission overload, not wire loss):
            # purely informational — the protocol's own retransmission is
            # the retry, and the decision-reply path is the catch-up
            _C_NACKS_SEEN.inc()
            if TRACE.enabled:
                TRACE.emit("nack_seen", node=self.id, inst=tag.instance,
                           src=sender)
            return
        if tag.flag == FLAG_SNAP:
            # snapshot sample (round_tpu/snap): collector-side cut
            # assembly — never round traffic, never a lane mailbox.  A
            # non-collector receiving one drops it as wire noise.
            if self._snap is not None:
                self._snap.on_frame(sender, tag, raw)
            return
        iid = tag.instance
        lane = self.table.lane_of(iid)
        if lane is None:
            if tag.flag == FLAG_DECISION and self._rv is not None:
                # agreement over the decision bank: a peer's decision
                # for an instance we completed must match ours
                self._rv_check_done(iid, raw)
                return
            if tag.flag != FLAG_NORMAL:
                return
            if iid in self._done:
                # TooLate: answer a completed instance's traffic with its
                # decision (rate-limited; encode-once via the cache)
                d = self._done[iid]
                if d is not None:
                    _try_send_decision(self.transport, self._replied,
                                       sender, iid, d,
                                       enc_cache=self._enc_cache)
                return
            if self._admission is not None and self._admission.shedding:
                # load shedding: refuse the frame with an accounted NACK
                # instead of queueing unboundedly (module overload story)
                self._shed_frame(sender, iid)
                return
            # future instance: stash raw until admission (FIFO-capped —
            # garbage instance ids age out instead of pinning the stash;
            # stale order heads for admitted instances are skipped here)
            while self._stash_count >= _STASH_CAP and self._stash_order:
                old = self._stash_order.popleft()
                bucket = self._stash.get(old)
                if bucket:
                    ev = bucket.pop(0)
                    self._stash_count -= 1
                    self._stash_bytes -= len(ev[2])
                    _C_STASH_EVICT.inc()
                    if not bucket:
                        del self._stash[old]
            if not isinstance(got[2], bytes):
                got = (got[0], got[1], bytes(got[2]))
            self._stash.setdefault(iid, []).append(got)
            self._stash_order.append(iid)
            self._stash_count += 1
            self._stash_bytes += len(got[2])
            _G_STASH_DEPTH.set(self._stash_count)
            return
        if tag.flag == FLAG_DECISION:
            ok, p = self._loads(raw, sender)
            if ok and p is not None and self._rv is not None:
                # record the peer decision for the fused agreement term
                # and check the already-decided case NOW (the adoption
                # below overwrites the lane before the next wave)
                self._rv_note_ext(lane, p)
            adopted = (self.algo.adopt_decision(self._state_row(lane), p)
                       if ok else None)
            if adopted is not None:
                self._write_row(lane, [
                    np.asarray(x)
                    for x in jax.tree_util.tree_leaves(adopted)])
                self._oob_done[lane] = True
                _C_LANE_OOB.inc()
                if TRACE.enabled:
                    TRACE.emit("recv_decision", node=self.id, inst=iid,
                               round=int(self._rr[lane]), src=sender)
            return
        if tag.flag != FLAG_NORMAL:
            return
        if self._pump is not None:
            # pump mode: this frame reached Python because the fast path
            # could not prove it safe (stash replay at admission, or a
            # template miss — a legacy-pickle peer or byzantine bytes).
            # Run it through the native state machine; a current-round
            # template miss comes back -2 and takes the bilingual decode
            # + canonical re-insert below.
            rc = self._pump.feed(sender, tag, raw)
            if rc != -2:
                return
            self._pump_fallback_insert(lane, sender, raw)
            return
        r = int(self._rr[lane])
        if tag.round > self._max_rnd[lane, sender]:
            self._max_rnd[lane, sender] = tag.round
        if tag.round < r:
            return  # late: the round is communication-closed
        ok, payload = self._loads(raw, sender)
        if not ok:
            return
        if self._waiting[lane] and not self._use_deadline[lane]:
            # WaitForMessage/Sync cap is an IDLE cap: progress extends it
            self._deadline[lane] = _time.monotonic() + \
                self.wait_cap_ms / 1000.0
        if tag.round > r or not self._waiting[lane]:
            # future round — or current round but OUR send has not run yet
            # (the per-instance driver's transport queue plays this role:
            # frames received before the send land in the mailbox only
            # after reset): buffer, prefilled at round entry
            bucket = self._pending[lane].setdefault(tag.round, {})
            if sender not in bucket:
                sz = len(raw) if raw else 0
                self._pending_bytes += sz
                self._pending_sizes[lane][tag.round] = \
                    self._pending_sizes[lane].get(tag.round, 0) + sz
            bucket[sender] = payload
            if tag.round > r:
                if self.nbr_byzantine <= 0:
                    self._next_round[lane] = max(
                        int(self._next_round[lane]),
                        int(self._max_rnd[lane].max()))
                else:
                    srt = np.sort(self._max_rnd[lane])
                    self._next_round[lane] = max(
                        int(self._next_round[lane]),
                        int(srt[-(self.nbr_byzantine + 1)]))
            return
        grew = self._boxes[r % self.k].insert(lane, sender, payload)
        _C_RECVS.inc()
        if grew:
            self._dirty[lane] = True

    def _pump_fallback_insert(self, lane: int, sender: int, raw) -> None:
        """The bilingual slow path of pump mode: decode (codec or the
        restricted unpickler), coerce leaves to the slot dtypes with the
        mailbox's own same-kind cast rule, re-encode CANONICALLY and
        insert under the pump lock — byte-for-byte the _ClassBox.insert
        semantics, including the malformed-sender slot clear."""
        ok, payload = self._loads(raw, sender)
        if not ok:
            return
        box = self._boxes[int(self._rr[lane]) % self.k]
        try:
            enc = pump_coerce_encode(
                payload, [(s.shape[2:], s.dtype) for s in box.vals],
                box.treedef)
            rc = self._pump.insert(lane, sender, enc)
            if rc < 0:
                raise ValueError("canonical re-encode missed the template")
        except Exception as e:  # noqa: BLE001 — garbage must not kill us
            self._note_malformed(sender)
            self._pump.mark_malformed(lane, sender)
            log.debug("lane %d: dropping structurally-malformed payload "
                      "from %d: %s", lane, sender, e)
            return
        # host.recvs accounting rides the pump stats bank (rt_pump_insert
        # ticked fast/dup) — an inline inc here would double-count
        if rc == 1:
            self._dirty[lane] = True

    def _drain(self, timeout_ms: int) -> int:
        if self._recv_many is not None:
            got_list = self._recv_many(timeout_ms)
        else:
            got = self.transport.recv(timeout_ms)
            got_list = [got] if got is not None else []
        for got in got_list:
            self._ingest(got)
        return len(got_list)

    # -- send wave ---------------------------------------------------------

    def _send_wave(self) -> None:
        lanes = np.nonzero(self._need_send & self._live)[0]
        if lanes.size == 0:
            return
        if self._pump is not None:
            del self._wave[:]
            del self._entries[:]
            self._entry_count = 0
            del self._arm_specs[:]
            self._arm_count = 0
        shipped = 0
        for c in sorted({int(self._rr[l]) % self.k for l in lanes}):
            group = [int(l) for l in lanes if int(self._rr[l]) % self.k == c]
            shipped += self._send_class(c, group)
        if self._pump is not None:
            # arm BEFORE the frames hit the wire: a fast peer's reply can
            # only race into the lane's native pending buffer, never into
            # a torn mailbox.  Then ONE crossing ships the whole wave
            # (encode-once buffer + per-peer offsets, coalesced and sent
            # natively) — or the per-frame Python path under chaos, where
            # faults must keep applying per logical frame.
            if self._arm_count:
                self._pump.arm_specs(self._arm_specs, self._arm_count)
            if self._entry_count and self._pump_send:
                self._pump.flush(self._wave, self._entries,
                                 self._entry_count)
            elif shipped and self._sendb is not None:
                self._flushfn()
        elif shipped and self._sendb is not None:
            self._flushfn()

    def _send_class(self, c: int, group: List[int]) -> int:
        step = self._step(c)
        active = np.zeros((self.L,), dtype=bool)
        active[group] = True
        st, payload, dest = step.send(
            self._rr, self._sid, self._seeds, self._state_tree(), active)
        self._copy_back(st)
        _C_SEND_D.inc()
        _C_DISPATCH.inc()
        _H_IPD.observe(len(group))
        _G_OCC.set(self.table.occupancy)
        pl_leaves, pl_tree = jax.tree_util.tree_flatten(payload)
        pl_leaves = [np.asarray(x) for x in pl_leaves]
        dest_np = np.asarray(dest)
        now = _time.monotonic()
        shipped = 0
        for lane in group:
            shipped += self._begin_round(
                c, lane,
                jax.tree_util.tree_unflatten(
                    pl_tree, [x[lane] for x in pl_leaves]),
                dest_np[lane], now)
        return shipped

    def _begin_round(self, c: int, lane: int, payload_row, dest_row,
                     now: float) -> int:
        r = int(self._rr[lane])
        iid = int(self._inst[lane]) & 0xFFFF
        kind, strict, millis = self._prog[c]
        self._delegated[lane] = millis < 0 and kind == _P_TIMEOUT
        if self._delegated[lane]:
            millis = (self.adaptive.current_ms()
                      if self.adaptive is not None else self.timeout_ms)
        self._use_deadline[lane] = kind == _P_TIMEOUT
        if kind == _P_TIMEOUT:
            self._deadline[lane] = now + millis / 1000.0
            self._trajectory.append(int(millis))
        else:
            self._deadline[lane] = now + self.wait_cap_ms / 1000.0
        self._t0[lane] = now
        if self._expected_static[c]:
            self._expected[lane] = self.n
        else:
            ctx = RoundCtx(id=np.int32(self.id), n=self.n, r=np.int32(r))
            self._expected[lane] = int(np.asarray(
                self.algo.rounds[c].expected_nbr_messages(
                    ctx, self._state_row(lane))))
        self._expected_raw[lane] = min(self.n, int(self._expected[lane]))
        if self._health is not None:
            # quarantined peers are excused from the PROGRESS threshold
            # (they stop pacing the round wave); their frames, when they
            # arrive, still land in the mailbox and still count
            self._expected[lane] = self._health.effective_threshold(
                int(self._expected_raw[lane]))
        box = self._boxes[c]
        box.reset_row(lane, payload_row)
        self._pending_bytes -= self._pending_sizes[lane].pop(r, 0)
        for sender, payload in self._pending[lane].pop(r, {}).items():
            box.insert(lane, sender, payload)
        if TRACE.enabled:
            TRACE.emit("round_start", node=self.id, inst=iid, round=r)
        sent = 0
        if dest_row.any():
            if self._pump is not None and self._pump_send:
                # encode ONCE into the wave buffer; destinations become
                # 20-byte plan entries for the single rt_pump_flush
                # crossing at the end of the wave
                off = len(self._wave)
                codec.encode_into(payload_row, self._wave)
                ln = len(self._wave) - off
                tagw = Tag(instance=iid,
                           round=r).pack() & 0xFFFFFFFFFFFFFFFF
                for d in range(self.n):
                    if d == self.id or not dest_row[d]:
                        continue
                    self._entries += RoundPump._ENTRY.pack(d, tagw, off, ln)
                    self._entry_count += 1
                    sent += 1
            else:
                if self._scratch is not None:
                    wire = self._scratch.encode(payload_row)
                else:
                    wire = pickle.dumps(jax.tree_util.tree_map(
                        np.asarray, payload_row))
                tag = Tag(instance=iid, round=r)
                sendb = self._sendb
                for d in range(self.n):
                    if d == self.id or not dest_row[d]:
                        continue
                    if sendb is not None:
                        sendb(d, tag, wire)
                    else:
                        self.transport.send(
                            d, tag, wire if isinstance(wire, bytes)
                            else bytes(wire))
                    sent += 1
                    if TRACE.enabled:
                        TRACE.emit("send", node=self.id, inst=iid, round=r,
                                   dst=d, bytes=len(wire))
            if sent:
                _C_SENDS.inc(sent)
        if dest_row[self.id]:
            # self-delivery short-circuits the wire (Round.scala:114-117)
            box.insert(lane, self.id, payload_row)
        self._need_send[lane] = False
        self._waiting[lane] = True
        self._dirty[lane] = True
        if self._pump is not None:
            self._queue_arm(lane, r, c, kind, strict, millis)
        return sent

    def _queue_arm(self, lane: int, r: int, c: int, kind: int,
                   strict: bool, millis: int) -> None:
        """Append this lane's arm spec for the wave's single
        rt_pump_arm_many crossing: progress threshold / growth-wake
        flags / native deadline, mirroring _parse_progress semantics."""
        P = RoundPump
        thr, flags, dl, ext = 0, 0, 0, 0
        has_go = (self._steps[c] is not None
                  and self._steps[c].go is not None)
        if kind == _P_TIMEOUT:
            dl = int(millis)
            if has_go:
                flags |= P.F_GROWTH
            else:
                thr = min(self.n, int(self._expected[lane]))
            if strict:
                flags |= P.F_STRICT
        elif kind == _P_GOAHEAD:
            # arm applies the natively-buffered pending frames; the lane
            # is ready THIS tick (queued messages delivered, then update)
            self._goahead_armed.add(lane)
        elif kind == _P_SYNC:
            flags |= P.F_GROWTH | P.F_STRICT | P.F_EXTEND
            dl = ext = self.wait_cap_ms
        else:  # _P_WAIT
            flags |= P.F_EXTEND
            dl = ext = self.wait_cap_ms
            if has_go:
                flags |= P.F_GROWTH
            else:
                thr = min(self.n, int(self._expected[lane]))
            if strict:
                flags |= P.F_STRICT
        self._arm_specs += P._ARM.pack(lane, r, c, thr, flags, dl, ext,
                                       P.R_ROUND_END)
        self._arm_count += 1

    def _step(self, c: int):
        step = self._steps[c]
        if step is None:
            step = lane_step(self.algo.rounds[c], self.n, self.L,
                             self._sid, self._seeds, self._state_tree(),
                             monitor=self._rv_mon
                             if self._rv is not None else None)
            self._steps[c] = step
        return step

    # -- probe / update ----------------------------------------------------

    def _probe_go(self) -> Dict[int, np.ndarray]:
        """Batched FoldRound go probes: ONE dispatch per round class that
        has dirty waiting lanes — the per-receive probe of the reference
        amortized across the lane axis."""
        out: Dict[int, np.ndarray] = {}
        for c in range(self.k):
            step = self._steps[c]
            if step is None or step.go is None:
                continue
            lanes = [l for l in np.nonzero(self._waiting & self._dirty)[0]
                     if int(self._rr[l]) % self.k == c]
            if not lanes:
                continue
            vals, mask = self._boxes[c].values_mask()
            go = np.asarray(step.go(self._rr, self._sid, self._seeds,
                                    self._state_tree(), vals, mask))
            _C_GO_D.inc()
            _C_DISPATCH.inc()
            out[c] = go
        return out

    def _ready(self) -> Tuple[List[int], List[int]]:
        """(ready lanes to update, oob lanes to finish) this tick; marks
        timedout/expired per lane via self._lane_timedout."""
        now = _time.monotonic()
        go_by_class = self._probe_go()
        ready: List[int] = []
        oob: List[int] = []
        self._lane_timedout: Dict[int, Tuple[bool, bool]] = {}
        for lane in np.nonzero(self._waiting)[0]:
            lane = int(lane)
            if self._oob_done[lane]:
                oob.append(lane)
                continue
            c = int(self._rr[lane]) % self.k
            kind, strict, _millis = self._prog[c]
            step = self._steps[c]
            go = False
            if self._dirty[lane]:
                if step is not None and step.go is not None:
                    g = go_by_class.get(c)
                    go = bool(g[lane]) if g is not None else False
                else:
                    go = (self._boxes[c].count[lane]
                          >= min(self.n, int(self._expected[lane])))
                self._dirty[lane] = False
            timedout = expired = False
            if not go:
                if kind == _P_GOAHEAD:
                    go = True  # queued messages were delivered this tick
                elif kind == _P_SYNC and int(
                        (self._max_rnd[lane] >= self._rr[lane]).sum()
                ) >= self._prog[c][2] + self.nbr_byzantine:
                    go = True
                elif (self._next_round[lane] > self._rr[lane] + 1
                        and not strict):
                    timedout = True  # genuine round skew: fast-forward
                    _C_CATCHUP.inc()
                    if TRACE.enabled:
                        TRACE.emit(
                            "catch_up", node=self.id,
                            inst=int(self._inst[lane]) & 0xFFFF,
                            round=int(self._rr[lane]),
                            next_round=int(self._next_round[lane]))
                elif now >= self._deadline[lane]:
                    timedout = expired = True
                    self.timeouts += 1
                    _C_TIMEOUTS.inc()
                    if TRACE.enabled:
                        TRACE.emit(
                            "timeout", node=self.id,
                            inst=int(self._inst[lane]) & 0xFFFF,
                            round=int(self._rr[lane]),
                            kind=("deadline" if self._use_deadline[lane]
                                  else "wait_cap"),
                            heard=int(self._boxes[c].count[lane]))
            if go or timedout:
                ready.append(lane)
                self._lane_timedout[lane] = (timedout, expired)
        return ready, oob

    def _ready_pump(self) -> Tuple[List[int], List[int]]:
        """Pump-mode readiness: translate the consumed native reason bits
        (threshold / skew / deadline auto-disarm the lane atomically, so
        no frame joins a mailbox between the wait returning and the
        update dispatch) plus the Python-side probes (FoldRound go,
        Sync barriers) into the (ready, oob) lists of _ready."""
        ready: List[int] = []
        oob: List[int] = []
        self._lane_timedout = {}
        pump = self._pump
        reasons = pump.reasons
        P = RoundPump
        for lane in np.nonzero(self._waiting)[0]:
            lane = int(lane)
            if not self._live[lane]:
                continue
            if self._oob_done[lane]:
                pump.disarm(lane)
                oob.append(lane)
                continue
            if lane in self._goahead_armed:
                self._goahead_armed.discard(lane)
                pump.disarm(lane)
                ready.append(lane)
                self._lane_timedout[lane] = (False, False)
                continue
            rs = int(reasons[lane])
            if not rs:
                continue
            if rs & P.R_THRESH:
                ready.append(lane)
                self._lane_timedout[lane] = (False, False)
                continue
            if rs & P.R_SKEW:
                _C_CATCHUP.inc()
                if TRACE.enabled:
                    TRACE.emit(
                        "catch_up", node=self.id,
                        inst=int(self._inst[lane]) & 0xFFFF,
                        round=int(self._rr[lane]),
                        next_round=int(self._next_round[lane]))
                ready.append(lane)
                self._lane_timedout[lane] = (True, False)
                continue
            if rs & P.R_DEADLINE:
                self.timeouts += 1
                _C_TIMEOUTS.inc()
                if TRACE.enabled:
                    c = int(self._rr[lane]) % self.k
                    TRACE.emit(
                        "timeout", node=self.id,
                        inst=int(self._inst[lane]) & 0xFFFF,
                        round=int(self._rr[lane]),
                        kind=("deadline" if self._use_deadline[lane]
                              else "wait_cap"),
                        heard=int(self._boxes[
                            int(self._rr[lane]) % self.k].count[lane]))
                ready.append(lane)
                self._lane_timedout[lane] = (True, True)
                continue
            if rs & (P.R_GROWTH | P.R_POKE):
                self._dirty[lane] = True
        # FoldRound go probes (one batched dispatch per class) + Sync
        # barriers for the grown lanes
        go_by_class = self._probe_go()
        for lane in np.nonzero(self._waiting & self._dirty)[0]:
            lane = int(lane)
            if lane in self._lane_timedout or self._oob_done[lane] \
                    or not self._live[lane]:
                continue
            c = int(self._rr[lane]) % self.k
            kind, _strict, kparam = self._prog[c]
            step = self._steps[c]
            go = False
            if step is not None and step.go is not None:
                g = go_by_class.get(c)
                go = bool(g[lane]) if g is not None else False
            elif kind == _P_SYNC:
                go = int((self._max_rnd[lane] >= self._rr[lane]).sum()) \
                    >= kparam + self.nbr_byzantine
            self._dirty[lane] = False
            if go:
                pump.disarm(lane)
                ready.append(lane)
                self._lane_timedout[lane] = (False, False)
        return ready, oob

    def _update_wave(self, ready: List[int]) -> List[Tuple[int, bool]]:
        """One mega-step update per round class with ready lanes; returns
        [(lane, exited)].  With rv enabled the SAME dispatch also
        returns the monitor verdicts and the advanced carried monitor
        state — the fusion contract (no second dispatch, same
        lanes.update_dispatches count either way)."""
        out: List[Tuple[int, bool]] = []
        for c in sorted({int(self._rr[l]) % self.k for l in ready}):
            group = [l for l in ready if int(self._rr[l]) % self.k == c]
            active = np.zeros((self.L,), dtype=bool)
            active[group] = True
            vals, mask = self._boxes[c].values_mask()
            if self._rv is None:
                st, ex = self._step(c).update(
                    self._rr, self._sid, self._seeds, self._state_tree(),
                    vals, mask, active)
            else:
                old_dec = self._rv_prev_dec.copy()
                st, ex, ok, ndec, nval = self._step(c).update(
                    self._rr, self._sid, self._seeds, self._state_tree(),
                    vals, mask, active, self._rv_prev_dec,
                    self._rv_prev_val, self._rv_ext_dec,
                    self._rv_ext_val, self._rv_init)
                # owning copies: admission/oob paths write rows in place
                self._rv_prev_dec = np.array(ndec)
                self._rv_prev_val = np.array(nval)
            self._copy_back(st)
            ex = np.asarray(ex)
            _C_UPD_D.inc()
            _C_DISPATCH.inc()
            _H_IPD.observe(len(group))
            for lane in group:
                out.append((lane, bool(ex[lane])))
            if self._rv is not None:
                self._rv_after_wave(group, np.asarray(ok), old_dec)
        return out

    # -- runtime verification (round_tpu/rv) -------------------------------

    def _rv_after_wave(self, group: List[int], ok: np.ndarray,
                       old_dec: np.ndarray) -> None:
        """Consume one fused wave's verdicts: gossip newly-decided lanes
        (the agreement monitor's observability channel) and act on every
        tripped monitor per the configured policy."""
        rv = self._rv
        rv.note_checks(len(group) * self._rv_mon.n_monitors)
        if rv.cfg.gossip:
            for lane in group:
                if self._rv_prev_dec[lane] and not old_dec[lane]:
                    iid = int(self._inst[lane]) & 0xFFFF
                    val = self._rv_prev_val[lane]
                    for d in range(self.n):
                        if d != self.id:
                            _try_send_decision(
                                self.transport, self._replied, d, iid,
                                val, enc_cache=self._enc_cache)
        for lane in group:
            bad = np.nonzero(~ok[lane])[0]
            for fidx in bad:
                self._rv_violate(lane, int(fidx), "mega-step")

    def _rv_violate(self, lane: int, fidx: int, where: str) -> None:
        inst = int(self._inst[lane])
        label = self._rv_mon.labels[fidx]
        observed = {
            "decided": bool(self._rv_prev_dec[lane]),
            "decision": decision_scalar(self._rv_prev_val[lane]),
            "ext_decided": bool(self._rv_ext_dec[lane]),
            "ext_decision": decision_scalar(self._rv_ext_val[lane]),
        }
        # violate() RAISES RvViolation itself under the halt policy
        action = self._rv.violate(
            inst=inst, round_=int(self._rr[lane]), label=label,
            values=self._rv_values(inst), observed=observed, where=where)
        if action == "shed":
            self._rv_shed_lanes.add(lane)

    def _rv_check_oob(self, lane: int, row) -> None:
        """Eager verdicts on an oob-adopted lane (rv/compile.py
        eager_verdicts — the cold-path twin of the fused term)."""
        from round_tpu.rv.compile import eager_verdicts

        self._rv.note_checks(self._rv_mon.n_monitors)
        tripped, decided, decision = eager_verdicts(
            self._rv_mon, row, bool(self._rv_prev_dec[lane]),
            self._rv_prev_val[lane], bool(self._rv_ext_dec[lane]),
            self._rv_ext_val[lane], self._rv_init[lane])
        self._rv_prev_dec[lane] = decided
        self._rv_prev_val[lane] = decision
        for fidx in tripped:
            self._rv_violate(lane, int(fidx), "oob-adopt")

    def _rv_note_ext(self, lane: int, payload) -> None:
        """A FLAG_DECISION arrived for a LIVE lane: record the peer's
        decision for the fused agreement term, and — since the adoption
        below will overwrite the lane's state before the next wave —
        check the already-decided case at this site (the Python-path
        site both drivers share; HostRunner's equivalent lives in
        rv/compile.py InstanceMonitor)."""
        p = self._rv_mon
        try:
            v = np.asarray(payload, dtype=p.decision_dtype).reshape(
                p.decision_shape)
        except Exception:  # noqa: BLE001 — a garbage decision frame is
            return         # the adoption path's problem, not rv's
        self._rv_ext_dec[lane] = True
        self._rv_ext_val[lane] = v
        agree = p.slot_index("agreement")
        if agree is not None and self._rv_prev_dec[lane] \
                and not np.array_equal(v, self._rv_prev_val[lane]):
            self._rv_violate(lane, agree, "decision-adopt")

    def _rv_check_done(self, iid: int, raw) -> None:
        """A FLAG_DECISION arrived for a COMPLETED instance: the banked
        decision and the peer's must agree — the cold-path half of the
        agreement monitor."""
        banked = self._done.get(iid)
        if banked is None:
            return
        ok, payload = self._loads(raw)
        if not ok or payload is None:
            return
        p = self._rv_mon
        agree = p.slot_index("agreement")
        if agree is None:
            return
        try:
            v = np.asarray(payload, dtype=p.decision_dtype).reshape(
                p.decision_shape)
        except Exception:  # noqa: BLE001
            return
        if not np.array_equal(v, np.asarray(banked)):
            observed = {"decided": True,
                        "decision": decision_scalar(banked),
                        "ext_decision": decision_scalar(v)}
            # violate() raises under the halt policy; shed has no lane
            # to retire here (the instance already completed) — the
            # record and counters are the outcome
            self._rv.violate(
                inst=iid, round_=-1, label=p.labels[agree],
                values=self._rv_values(iid), observed=observed,
                where="decision-bank")

    # -- lane lifecycle ----------------------------------------------------

    def _observe_adaptive(self, lane: int, expired: bool,
                          timedout: bool) -> None:
        if self.adaptive is None or not self._delegated[lane]:
            return
        if expired:
            self.adaptive.observe(None, expired=True)
        elif not timedout:
            self.adaptive.observe(
                (_time.monotonic() - self._t0[lane]) * 1000.0,
                expired=False)

    def _retire_lane(self, lane: int, decided: bool, decision
                     ) -> Tuple[int, Optional[np.ndarray]]:
        """Release one finished lane — the loop-agnostic half of lane
        completion: record the raw decision in the TooLate/reply bank,
        retire the slot, tick the counters/traces.  Returns (inst, raw)
        so the caller (run's results list, serve's client streams) does
        its own bookkeeping."""
        inst = int(self._inst[lane])
        iid = inst & 0xFFFF
        raw = np.array(np.asarray(decision)) if decided else None
        self._done[iid] = raw
        if self._clients and len(self._done) > _DONE_CAP:
            # client-serving shards live indefinitely: the TooLate/
            # catch-up decision bank evicts oldest-first past the cap
            # (with its encode cache), the _init_cache discipline.  The
            # scheduled run() keeps the full bank — its size is bounded
            # by the run's own instance count, and crash-restart
            # laggards may legitimately ask for its oldest entries.
            while len(self._done) > _DONE_CAP:
                old = next(iter(self._done))
                del self._done[old]
                self._enc_cache.pop(old, None)
        if len(self._replied) > 8192:
            self._replied.clear()  # rate-limit map, same cap as _nacked
        if self._pump is not None:
            # retire the fast-path mapping: the instance's late traffic
            # flows to the inbox again, where the TooLate reply lives
            self._pump.close_lane(lane)
            self._goahead_armed.discard(lane)
        self.table.retire(iid)
        if self._snap is not None:
            # the proposal-row note dies with the instance (emission
            # only happens for live lanes, always before retire)
            self._snap.forget_value(iid)
        self._live[lane] = False
        self._waiting[lane] = False
        self._need_send[lane] = False
        self._pending[lane] = {}
        self._pending_bytes -= sum(self._pending_sizes[lane].values())
        self._pending_sizes[lane] = {}
        self._deadline[lane] = np.inf
        _C_RETIRE.inc()
        _G_OCC.set(self.table.occupancy)
        if decided:
            _C_DECISIONS.inc()
        if TRACE.enabled:
            TRACE.emit("decision", node=self.id, inst=iid,
                       round=int(self._rr[lane]), decided=decided,
                       value=(np.asarray(decision).tolist()
                              if decided else None))
            TRACE.emit("lane_retire", node=self.id, inst=iid, lane=lane,
                       decided=decided)
        return inst, raw

    def _finish_lane(self, lane: int, decided: bool, decision,
                     results: List[Optional[int]],
                     checkpoint_dir: Optional[str],
                     completed: set, instances: int) -> None:
        inst, _raw = self._retire_lane(lane, decided, decision)
        results[inst - 1] = decision_scalar(decision) if decided else None
        completed.add(inst)
        if checkpoint_dir is not None:
            step = 0
            while (step + 1) in completed:
                step += 1
            _save_decision_checkpoint(checkpoint_dir, results, step,
                                      instances)

    # -- the serving loop --------------------------------------------------

    def _admission_update(self) -> bool:
        """Re-evaluate the admission budget: live lanes × watermark over
        every byte this driver has QUEUED but not consumed — stash,
        per-lane pending buffers, and the native inbox backlog (the
        transport's backpressure level forces shedding regardless: that
        backlog is ours too)."""
        queued = (self._stash_bytes + self._pending_bytes
                  + int(getattr(self.transport, "inbox_bytes", 0)))
        shedding = self._admission.update(
            max(1, self.table.occupancy), queued,
            bool(getattr(self.transport, "backpressure", False)))
        _G_QUEUED.set(queued)
        _G_SHEDDING.set(1 if shedding else 0)
        return shedding

    def _snap_flush(self, force: bool = False) -> List[int]:
        """Snapshot housekeeping (round_tpu/snap): poll cut deadlines,
        run the batched audit dispatch, and translate the policy's shed
        verdicts into LIVE lanes (counted like every other shed; an
        instance that already completed has nothing left to retire).
        A halt-policy violation raises SnapViolation out of the flush
        itself — the caller's RvViolation discipline covers it."""
        if self._snap is None:
            return []
        lanes = []
        for iid in self._snap.flush(force=force):
            lane = self.table.lane_of(iid & 0xFFFF)
            if lane is not None and self._live[lane]:
                self.shed_instances += 1
                _C_SHED_INSTANCES.inc()
                lanes.append(lane)
        return lanes

    def _tick(self, deferring: bool) -> List[Tuple[int, bool, Any]]:
        """ONE serving tick, shared by the scheduled loop (run) and the
        client-driven loop (serve): ship the send wave, block in the
        pump wait (or the Python drain), translate readiness, run the
        update mega-steps and advance rounds.  Returns the lanes that
        finished this tick as (lane, decided, decision-row) — the caller
        owns their bookkeeping via _finish_lane / _retire_lane."""
        self._send_wave()
        if self._pump is not None:
            # ONE blocking native wait per wave: deadlines, progress
            # thresholds and skew are evaluated inside the event loop
            # with no GIL held — the 50 ms Python drain tick is gone.
            # Misc traffic (decisions, foreign instances, template
            # misses) interrupts the wait and drains via the inbox.
            # non-blocking when a lane needs immediate service: a
            # GoAhead lane, or a freshly-armed lane whose dirty flag
            # is set (self-delivery/prefill may ALREADY satisfy a go
            # probe or sync barrier, and the native side raises no
            # GROWTH wake for frames applied at arm — the probe in
            # _ready_pump must run this tick, not after a full wait)
            # while admission is DEFERRING pending work the wait must
            # stay short: a 2 s block would stretch every shed
            # deadline and admission re-check by the full wait
            nready, misc = self._pump.wait(
                0 if (self._goahead_armed
                      or bool(np.any(self._waiting & self._dirty)))
                else (50 if deferring else 2000))
            if nready < 0:
                raise RuntimeError(
                    "transport stopped under the lane driver")
            if misc or bool(
                    (self._pump.reasons & RoundPump.R_BACKPR).any()):
                # misc traffic — or the inbox crossed its byte high
                # watermark (R_BACKPR): drain NOW, that backlog is
                # what the admission budget sheds against
                self._drain(0)
            ready, oob = self._ready_pump()
        else:
            now = _time.monotonic()
            live_deadlines = self._deadline[self._waiting]
            if live_deadlines.size:
                wait_s = max(0.0, float(live_deadlines.min()) - now)
                timeout_ms = int(min(wait_s * 1000.0, 50.0))
            else:
                # no armed deadline: nothing to do but listen (an idle
                # serve loop, or a deferred-admission stall) — a short
                # bounded wait keeps shed deadlines and stop checks at
                # a 50 ms cadence without busy-spinning the drain
                timeout_ms = 50
            self._drain(timeout_ms)
            ready, oob = self._ready()
        finished: List[Tuple[int, bool, Any]] = []
        for lane in oob:
            # oob adoption skips the update (the per-instance driver
            # exits the accumulate loop without folding the mailbox)
            self.rounds_run += 1
            _C_ROUNDS.inc()
            row = self._state_row(lane)
            shed = False
            if self._rv is not None:
                # an adopted decision never reaches a fused wave: check
                # it eagerly (same verdict math — rv/compile.py) so an
                # adopted INVALID value still trips — and the shed
                # policy applies HERE too: an adopted violating
                # decision must not enter the log either
                self._rv_check_oob(lane, row)
                shed = lane in self._rv_shed_lanes
                self._rv_shed_lanes.discard(lane)
                if shed:
                    self.shed_instances += 1
                    _C_SHED_INSTANCES.inc()
            finished.append((lane, not shed,
                             np.asarray(self.algo.decision(row))))
        if not ready:
            return finished
        exits = self._update_wave(ready)
        finishing = []
        for lane, exited in exits:
            timedout, expired = self._lane_timedout.get(
                lane, (False, False))
            self._observe_adaptive(lane, expired, timedout)
            if self._health is not None:
                # one completed round wave of quarantine evidence:
                # heard peers decay/rejoin, unheard peers only accrue
                # score when the deadline actually EXPIRED
                c0 = int(self._rr[lane]) % self.k
                self._health.note_round(
                    np.nonzero(self._boxes[c0].mask[lane])[0], expired,
                    goal=int(self._expected_raw[lane]))
            self.rounds_run += 1
            _C_ROUNDS.inc()
            r = int(self._rr[lane])
            if TRACE.enabled:
                c = r % self.k
                TRACE.emit(
                    "round_end", node=self.id,
                    inst=int(self._inst[lane]) & 0xFFFF, round=r,
                    heard=int(self._boxes[c].count[lane]), n=self.n,
                    timedout=timedout, exited=exited,
                    wall_ms=round(
                        (_time.monotonic() - self._t0[lane]) * 1e3, 3))
            if self._snap is not None \
                    and self._snap.due(int(self._inst[lane]), r):
                # round boundary: sample the post-update state row off
                # the mega-step's copied-back leaves — zero extra
                # dispatches (engine/executor.py lane_sample_rows; the
                # deterministic policy decides, snap/sample.py).  The
                # due() pre-check keeps the per-lane row copies off the
                # (every_k-1)/every_k of rounds that would discard them.
                self._snap.after_round(
                    int(self._inst[lane]), r,
                    lane_sample_rows(self._state, lane))
            if exited or r + 1 >= self.max_rounds or (
                    self._rv is not None
                    and lane in self._rv_shed_lanes):
                # rv 'shed' policy: a lane whose monitor tripped retires
                # NOW, forced undecided below — a violating decision
                # must not enter the log or stream to a client
                finishing.append(lane)
            else:
                self._rr[lane] = r + 1
                self._max_rnd[lane, self.id] = r + 1
                self._next_round[lane] = max(
                    int(self._next_round[lane]), r + 1)
                self._waiting[lane] = False
                self._need_send[lane] = True
        if finishing:
            dec_fn = self._decide_fn
            if dec_fn is None:
                dec_fn = self._decide_fn = lane_decide(
                    self.algo, self.L, self._state_tree())
            decided_v, decision_v = dec_fn(self._state_tree())
            decided_v = np.asarray(decided_v)
            decision_v = np.asarray(decision_v)
            for lane in finishing:
                shed = (self._rv is not None
                        and lane in self._rv_shed_lanes)
                if shed:
                    self._rv_shed_lanes.discard(lane)
                    self.shed_instances += 1
                    _C_SHED_INSTANCES.inc()
                finished.append(
                    (lane, bool(decided_v[lane]) and not shed,
                     decision_v[lane]))
        return finished

    def _bank_pump_stats(self) -> None:
        if self._pump is None:
            return
        # fold the native fast-path stats into the unified metrics:
        # pump.* vocabulary plus host.recvs/host.malformed parity (a
        # message C++ ingested counts exactly like one Python did)
        d = self._pump.bank_metrics()
        _C_RECVS.inc(int(d[0] + d[1]))
        if d[6]:
            self.malformed += int(d[6])
            _C_MALFORMED.inc(int(d[6]))

    def _fill_stats(self, stats_out: Optional[Dict[str, int]]) -> None:
        if stats_out is None:
            return
        for key, v in (("timeouts", self.timeouts),
                       ("rounds_run", self.rounds_run),
                       ("malformed", self.malformed),
                       ("shed_frames", self.shed_frames),
                       ("shed_instances", self.shed_instances),
                       ("nacks_sent", self.nacks_sent),
                       ("nacks_suppressed", self.nacks_suppressed),
                       ("client_proposals", self.client_proposals),
                       ("client_streams", self.client_streams)):
            stats_out[key] = stats_out.get(key, 0) + v
        stats_out.setdefault("timeout_trajectory", []).extend(
            self._trajectory)
        if self._tenants is not None:
            # per-tenant shed accounting, keyed by tenant id: the
            # fleet-autoscale soak rung gates shed_frames ==
            # nacks_sent + nacks_suppressed for EVERY tenant here
            ten = stats_out.setdefault("tenants", {})
            for t, d in self._tenant_stats.items():
                agg = ten.setdefault(t, {})
                for k, v in d.items():
                    agg[k] = agg.get(k, 0) + v
        if self._health is not None:
            stats_out["quarantine"] = self._health.summary()
        if self._rv is not None:
            self._rv.fill_stats(stats_out)
        if self._snap is not None:
            self._snap.fill_stats(stats_out)
        if self._kv is not None:
            self._kv.fill_stats(stats_out)

    def run(self, instances: int, checkpoint_dir: Optional[str] = None,
            stats_out: Optional[Dict[str, int]] = None,
            linger_ms: int = 0,
            ) -> List[Optional[int]]:
        """Run ``instances`` consecutive consensus instances (numbered
        1..instances, the PerfTest2 schedule) with up to the lane width in
        flight; returns the per-instance decision log like
        run_instance_loop.  With ``checkpoint_dir``, the log is durably
        checkpointed as instances complete and an existing checkpoint
        RESUMES (completed instances are not re-run).  ``linger_ms``
        keeps answering laggards' retransmissions for that idle window
        after the schedule completes (host.serve_decisions, lane-driver
        form) — without it a replica whose deciding quorum excluded it
        can find every peer already exited (see _linger)."""
        results: List[Optional[int]] = [None] * instances
        completed: set = set()
        next_admit = 1
        if checkpoint_dir is not None:
            from round_tpu.runtime import checkpoint as _ckpt

            if _ckpt.exists(checkpoint_dir):
                like = np.full(instances, _UNDECIDED, dtype=np.int64)
                arr, step, meta = _ckpt.restore(checkpoint_dir, like)
                if (meta.get("kind") != "host-decision-log"
                        or meta.get("instances") != instances
                        or not 0 <= int(step) <= instances):
                    raise _ckpt.CheckpointError(
                        f"checkpoint at {checkpoint_dir} is not a host "
                        f"decision log for an {instances}-instance run: "
                        f"meta={meta}, step={step}")
                arr = np.asarray(arr)
                vector = getattr(self.algo, "payload_bytes",
                                 None) is not None
                for i in range(1, instances + 1):
                    v = int(arr[i - 1])
                    if v != _UNDECIDED:
                        # completed AND decided.  Scalar log values ARE
                        # the raw decision, so laggard replies stay
                        # adoptable across a resume; a vector algorithm's
                        # log holds digests a peer could only discard —
                        # store None (reply suppressed) instead
                        results[i - 1] = v
                        completed.add(i)
                        self._done[i & 0xFFFF] = (
                            None if vector else np.asarray(v))
                    elif i <= int(step):
                        # inside the contiguous prefix: completed but
                        # undecided — do not re-run (the sequential loop's
                        # restore semantics)
                        completed.add(i)
                        self._done[i & 0xFFFF] = None
                log.info("node %d: resumed %d completed instance(s) from "
                         "%s", self.id, len(completed), checkpoint_dir)
        try:
            self._run_loop(instances, checkpoint_dir, results, completed,
                           next_admit)
            if linger_ms > 0:
                self._linger(linger_ms)
        finally:
            # stats survive an rv-halt (the RvViolation propagates with
            # the violation record already banked)
            self._bank_pump_stats()
            self._fill_stats(stats_out)
        return results

    def _linger(self, linger_ms: int, max_ms: int = 120_000) -> None:
        """host.serve_decisions, lane-driver form: the decision-reply
        (TooLate) path only runs while something pumps the wire, so a
        batch replica that returns the moment ITS OWN log is full
        strands any peer whose deciding quorum excluded it — the
        straggler retransmits deadline-paced rounds into closed
        sockets until max_rounds burns (observed as a polite replica's
        None in the asymmetric-overload test, a scheduling lottery,
        not a wedge).  Keep ticking the now-empty lane table: _tick
        still drains frames, and a completed instance's NORMAL traffic
        is answered from the decision bank through the same reply path
        as during the run.  Every reply re-arms the idle window, so
        the linger outlasts the LAST laggard contact by ``linger_ms``,
        hard-capped at ``max_ms``."""
        window = linger_ms / 1000.0
        now = _time.monotonic()
        t_end = now + max_ms / 1000.0
        deadline = now + window
        mark = max(self._replied.values(), default=float("-inf"))
        while _time.monotonic() < min(deadline, t_end):
            self._tick(False)
            newest = max(self._replied.values(), default=float("-inf"))
            if newest > mark:
                mark = newest
                deadline = newest + window

    def _run_loop(self, instances: int, checkpoint_dir, results,
                  completed: set, next_admit: int) -> None:
        while len(completed) < instances:
            if self._admission is not None:
                self._admission_update()
            while next_admit <= instances and self.table.can_admit():
                if next_admit in completed:
                    next_admit += 1
                    continue
                if self._admission is not None \
                        and not self._admission.admit_ok():
                    now = _time.monotonic()
                    if self._admission.shed_started is None:
                        # defer first: overload is often a burst, and a
                        # deferred admission costs latency, not work
                        self._admission.shed_started = now
                        break
                    if (now - self._admission.shed_started) * 1000.0 \
                            < self._admission.shed_deadline_ms:
                        break
                    # deadline-shed: refused outright — an explicit
                    # undecided entry + counters, never an unbounded
                    # queue of deferred admissions (its traffic now gets
                    # the TooLate/NACK treatment, and peers that DID run
                    # it serve the decision reply if we ever need it).
                    # The expired deadline sheds the whole CURRENT
                    # backlog, legitimately: every deferred admission
                    # blocked at the same watermark crossing, so all of
                    # them have aged the full window — but the purge
                    # re-evaluation below ends the sweep the moment
                    # memory clears, and update() resets shed_started
                    # when the episode ends, so the NEXT burst gets a
                    # fresh defer-first window.  Only under continuously
                    # latched overload do later arrivals shed without
                    # their own grace — fail-fast with a NACK is the
                    # deliberate serving posture there, not an accident
                    inst = next_admit
                    completed.add(inst)
                    self._done[inst & 0xFFFF] = None
                    # purge the refused instance's stash NOW: its frames
                    # will never be replayed (it has no lane to join),
                    # and holding them would LATCH the byte budget above
                    # the watermark — shedding one instance must free
                    # its memory, or one burst sheds everything after it
                    purged = self._stash.pop(inst & 0xFFFF, [])
                    self._stash_count -= len(purged)
                    self._stash_bytes -= sum(len(r[2]) for r in purged)
                    _G_STASH_DEPTH.set(self._stash_count)
                    self.shed_instances += 1
                    self._admission.sheds += 1
                    _C_SHED_INSTANCES.inc()
                    if TRACE.enabled:
                        TRACE.emit("shed_instance", node=self.id,
                                   inst=inst)
                    next_admit += 1
                    # the purge may have drained the budget: re-evaluate
                    # NOW, so one transient burst sheds only as many
                    # instances as it takes to clear the watermark — not
                    # every admission pending when the deadline expired
                    self._admission_update()
                    continue
                self._admit(next_admit)
                next_admit += 1
            deferring = (self._admission is not None
                         and self._admission.shedding
                         and next_admit <= instances)
            for lane, decided, decision in self._tick(deferring):
                self._finish_lane(lane, decided, decision, results,
                                  checkpoint_dir, completed, instances)
            for lane in self._snap_flush():
                # snapshot 'shed' policy: the violating instance retires
                # undecided NOW (halt raised inside the flush; log did
                # nothing) — the rv shed discipline at cut granularity
                self._finish_lane(
                    lane, False,
                    np.asarray(self.algo.decision(self._state_row(lane))),
                    results, checkpoint_dir, completed, instances)
        if self._snap is not None:
            # end of the schedule: resolve every pending part-cut and
            # audit the tail (a final-cut halt raises from here)
            self._snap.flush(force=True)

    def _tenant_instance_shed(self, tenant: int) -> None:
        ts = self._tenant_stats.setdefault(
            tenant, {"shed_frames": 0, "shed_instances": 0,
                     "nacks_sent": 0, "nacks_suppressed": 0})
        ts["shed_instances"] += 1
        _C_T_SHED_INSTANCES.inc()

    def _tenant_update(self) -> None:
        """Re-evaluate the per-tenant watermarks over each tenant's
        queued intake bytes, and deadline-shed a tenant that stayed over
        its share: ONLY that tenant's backlog drains — its neighbours
        keep admitting (the weighted-fair isolation contract; contrast
        the global deadline shed below, which drains everything)."""
        shedding = self._tenants.update(
            self.table.width, self._proposals.bytes_by_tenant(),
            backpressure=(self._admission is not None
                          and self._admission.shedding))
        _G_T_SHEDDING.set(len(shedding))
        now = _time.monotonic()
        for t in sorted(shedding):
            if not self._proposals.queued(t):
                continue
            started = self._tenants.shed_started.get(t)
            if started is None:
                self._tenants.shed_started[t] = now
            elif (now - started) * 1000.0 \
                    >= self._tenants.shed_deadline_ms:
                for iid, _io, sender in self._proposals.drain_tenant(t):
                    self._proposed.discard(iid)
                    self._client_of.pop(iid, None)
                    self.shed_instances += 1
                    self._tenants.sheds += 1
                    _C_SHED_INSTANCES.inc()
                    self._tenant_instance_shed(t)
                    self._shed_frame(sender, iid, tenant=t)
                _G_CLIENT_QUEUE.set(len(self._proposals))

    def _admit_proposals(self) -> None:
        """Admit queued client proposals into free lanes, under the same
        admission defer/shed discipline as the scheduled loop.  With
        per-tenant metering (TenantAdmission) the admission ORDER is
        deficit-weighted round-robin across non-shedding tenants, so
        lane slots divide in weight proportion when tenants contend."""
        if self._tenants is not None:
            self._tenant_update()
        while self._proposals and self.table.can_admit():
            if self._admission is not None \
                    and not self._admission.admit_ok():
                now = _time.monotonic()
                if self._admission.shed_started is None:
                    # defer first: overload is often a burst, and a
                    # deferred proposal costs latency, not work
                    self._admission.shed_started = now
                elif (now - self._admission.shed_started) * 1000.0 \
                        >= self._admission.shed_deadline_ms:
                    # deadline-shed the deferred backlog: every
                    # queued proposal gets an accounted NACK (the
                    # client's cue to back off and retry) instead of
                    # aging in an unbounded queue
                    while self._proposals:
                        tenant, iid, _io, sender = \
                            self._proposals.pop_fifo()
                        self._proposed.discard(iid)
                        self._client_of.pop(iid, None)
                        self.shed_instances += 1
                        self._admission.sheds += 1
                        _C_SHED_INSTANCES.inc()
                        if self._tenants is not None:
                            self._tenant_instance_shed(tenant)
                            self._shed_frame(sender, iid, tenant=tenant)
                        else:
                            self._shed_frame(sender, iid)
                    _G_CLIENT_QUEUE.set(0)
                    self._admission_update()
                return
            if self._tenants is not None:
                t = self._tenants.next_tenant(
                    self._proposals.tenants_queued())
                if t is None:
                    return  # every queued tenant over budget: defer
                iid, io, sender = self._proposals.pop_tenant(t)
                self._tenants.note_admit(t)
            else:
                _t, iid, io, sender = self._proposals.pop_fifo()
            self._proposed.discard(iid)
            _G_CLIENT_QUEUE.set(len(self._proposals))
            if iid in self._done \
                    or self.table.lane_of(iid) is not None:
                continue
            try:
                self._admit(iid, io=io)
            except Exception:  # noqa: BLE001 — a garbage proposal
                # (wrong dtype/shape for the algorithm) must not
                # wedge the serving loop: counted, client told — and
                # the lane slot _admit claimed before failing is
                # RELEASED, or L garbage proposals would permanently
                # exhaust the table and wedge the shard
                if self.table.lane_of(iid) is not None:
                    self.table.retire(iid)
                self._note_malformed(sender)
                self._client_of.pop(iid, None)
                self.transport.send(
                    sender, Tag(instance=iid, flag=FLAG_TOO_LATE))

    def _rv_fail_clients(self) -> None:
        """Best-effort client notification on an rv halt: FLAG_TOO_LATE
        for every queued proposal and live client instance (queued kv
        reads are refused too, and the lease dies with the shard)."""
        if self._kv is not None:
            self._kv.lease.revoke()
            self._kv_fail_reads()
        try:
            for iid, _io, sender in list(self._proposals.items()):
                self.transport.send(
                    sender, Tag(instance=iid, flag=FLAG_TOO_LATE))
            for lane in np.nonzero(self._live)[0]:
                iid = int(self._inst[int(lane)]) & 0xFFFF
                c = self._client_of.get(iid)
                targets = set(self._subscribers)
                if c is not None:
                    targets.add(c)
                for t in targets:
                    self.transport.send(
                        t, Tag(instance=iid, flag=FLAG_TOO_LATE))
        except Exception:  # noqa: BLE001 — the halt still propagates
            pass

    def serve(self, idle_ms: int = 4000, max_ms: int = 600_000,
              stop=None, stats_out: Optional[Dict[str, int]] = None,
              ) -> Dict[int, Optional[int]]:
        """CLIENT-DRIVEN serving (the fleet tier, runtime/fleet.py):
        instead of a preset 1..instances schedule, instances are admitted
        from FLAG_PROPOSE frames sent by ``clients`` peers (the front
        door), each carrying the client's initial value; completed
        instances stream back as FLAG_DECISION / FLAG_TOO_LATE.  The
        same admission control applies — while shedding, proposals are
        refused with the accounted FLAG_NACK and the client's
        capped-backoff retry is the recovery path (docs/SERVING.md).

        Runs until ``stop()`` returns True, ``max_ms`` elapses, or the
        driver has been idle — no live lanes, no queued proposals, no
        finished work — for ``idle_ms``.  Returns {instance: scalar
        decision-log entry} for every instance served (None =
        finished undecided)."""
        results: Dict[int, Optional[int]] = {}
        try:
            self._serve_loop(results, idle_ms, max_ms, stop)
        finally:
            # stats survive an rv-halt (DriverServer.rv_summary reads
            # them after join)
            self._bank_pump_stats()
            self._fill_stats(stats_out)
        return results

    def _serve_loop(self, results: Dict[int, Optional[int]],
                    idle_ms: int, max_ms: int, stop) -> None:
        t_end = _time.monotonic() + max_ms / 1000.0
        last_active = _time.monotonic()
        while True:
            now = _time.monotonic()
            if now >= t_end or (stop is not None and stop()):
                break
            if self._rv is None:
                if self._admission is not None:
                    self._admission_update()
                self._admit_proposals()
                deferring = (self._admission is not None
                             and self._admission.shedding
                             and bool(self._proposals))
                finished = self._tick(deferring)
            else:
                from round_tpu.rv.dump import RvViolation

                try:
                    # admission replays stashed frames through _ingest,
                    # where a halt can trip too (decision-bank
                    # agreement) — the fail-fast handler must cover the
                    # whole serving step, not just the tick
                    if self._admission is not None:
                        self._admission_update()
                    self._admit_proposals()
                    deferring = (self._admission is not None
                                 and self._admission.shedding
                                 and bool(self._proposals))
                    finished = self._tick(deferring)
                except RvViolation:
                    # rv halt while client-serving: tell every proposer/
                    # subscriber their in-flight instances are dead
                    # (FLAG_TOO_LATE — the router resolves them
                    # undecided) instead of letting clients retry into
                    # a halted shard until their give-up budget burns
                    self._rv_fail_clients()
                    raise
            for lane, decided, decision in finished:
                inst, raw = self._retire_lane(lane, decided, decision)
                iid = inst & 0xFFFF
                results[iid] = (decision_scalar(decision) if decided
                                else None)
                if self._kv is not None:
                    # apply IN DECISION ORDER before the decision
                    # streams: a client that sees its ack must find
                    # every replica's read view already reflecting it
                    self._kv.on_decision(iid, decided, raw)
                self._stream_decision(iid, decided, raw)
            if self._kv is not None:
                self._kv_tick()
            if self._snap is not None:
                from round_tpu.rv.dump import RvViolation

                try:
                    shed_lanes = self._snap_flush()
                except RvViolation:
                    # snap halt while client-serving: same fail-fast
                    # contract as an rv halt — clients learn their
                    # in-flight instances are dead instead of retrying
                    # into a halted shard
                    self._rv_fail_clients()
                    raise
                for lane in shed_lanes:
                    inst, _raw = self._retire_lane(
                        lane, False, np.asarray(
                            self.algo.decision(self._state_row(lane))))
                    iid = inst & 0xFFFF
                    results[iid] = None
                    if self._kv is not None:
                        self._kv.on_decision(iid, False, None)
                    self._stream_decision(iid, False, None)
            if finished or self.table.occupancy or self._proposals:
                last_active = _time.monotonic()
            elif _time.monotonic() - last_active >= idle_ms / 1000.0:
                break
        if self._snap is not None:
            from round_tpu.rv.dump import RvViolation

            try:
                # end of serving: resolve pending part-cuts and audit
                # the tail
                self._snap.flush(force=True)
            except RvViolation:
                # a tail-cut halt keeps the fail-fast contract: any
                # still-queued client must not retry into a dead shard
                self._rv_fail_clients()
                raise


def run_instance_loop_lanes(
    algo: Algorithm,
    my_id: int,
    peers: Dict[int, Tuple[str, int]],
    transport,
    instances: int,
    lanes: int = 16,
    timeout_ms: int = 300,
    seed: int = 0,
    base_value: int = 0,
    max_rounds: int = 32,
    stats_out: Optional[Dict[str, int]] = None,
    nbr_byzantine: int = 0,
    value_schedule: str = "mixed",
    adaptive: Optional[AdaptiveTimeout] = None,
    checkpoint_dir: Optional[str] = None,
    wire: str = "binary",
    use_pump: bool = True,
    admission: Optional[AdmissionControl] = None,
    health=None,
    rv=None,
    snap=None,
    linger_ms: int = 0,
) -> List[Optional[int]]:
    """The lane-batched form of run_instance_loop: same schedule, same
    seeds, same decision-log shape — the work just flows through one
    vmapped mega-step per round class instead of one Python round loop per
    instance (module docstring).  Cross-checkable against the per-instance
    drivers byte-for-byte (tests/test_lanes.py).  ``use_pump=False`` pins
    the Python pump (the native-pump A/B baseline, tests/test_pump.py).
    ``admission``/``health`` opt in to the overload hardening
    (docs/HOST_FAULT_MODEL.md): load shedding + peer quarantine.  ``rv``
    (rv.dump.RvConfig) fuses the runtime-verification monitors into the
    mega-step (docs/RUNTIME_VERIFICATION.md).  ``snap``
    (snap.audit.SnapConfig) samples round-boundary state into
    round-consistent cuts and audits the full-state invariants
    (docs/SNAPSHOTS.md).  ``linger_ms`` answers laggards for an idle
    window after the schedule completes (LaneDriver._linger)."""
    driver = LaneDriver(
        algo, my_id, peers, transport, lanes=lanes, timeout_ms=timeout_ms,
        seed=seed, base_value=base_value, max_rounds=max_rounds,
        nbr_byzantine=nbr_byzantine, value_schedule=value_schedule,
        adaptive=adaptive, wire=wire, use_pump=use_pump,
        admission=admission, health=health, rv=rv, snap=snap,
    )
    return driver.run(instances, checkpoint_dir=checkpoint_dir,
                      stats_out=stats_out, linger_ms=linger_ms)
