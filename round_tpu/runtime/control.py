"""Model-driven fleet control plane: the capacity-model loop closed LIVE
(docs/SERVING.md "planet-scale control plane", PERF_MODEL.md "control
loop").

PR 11's fleet is capacity-fixed at spawn time: a load swing past the
fitted knee can only be shed (PR 10's watermark NACKs).  But the fitted
``dps(drivers, lanes, payload)`` surface (round_tpu/runtime/capacity.py,
CAPACITY_r03.json) PREDICTS how much fleet a given offered load needs —
so the SCALE-Sim-style discipline of validating the model against
measurement becomes a controller: watch the live knee signals, compare
them to the model, and resize the ring instead of shedding.

``FleetSupervisor`` owns that loop:

  * SIGNALS — windowed deltas read off the FleetRouter it supervises:
    offered rate (proposal deltas), achieved rate (resolution deltas),
    round-wall p99 vs the SLO (the router's decide latencies), NACK rate
    (shard shed pressure), and in-flight backlog.  No new wire traffic:
    the router already sees everything the controller needs.

  * DECISIONS — grow when offered load clears the model's headroom for
    the current fleet OR an SLO/NACK breach dwells (two+ consecutive
    windows: one bad window is noise, a dwell is a trend); shrink only
    under sustained slack against the model for the SMALLER fleet (the
    hysteresis gap keeps grow/shrink from oscillating around the knee),
    after a cooldown.  A breach while offered load is INSIDE the model's
    envelope is knee drift — the model is wrong, not the load — counted,
    banked as a live ``(drivers, lanes, payload, knee_dps)`` sample for
    the next ``capacity.fit`` refit (the r03 refit feeds on exactly
    these), and still answered by growing: measurement outranks model.

  * MOTION — a resize is a view move and is licensed like one: every
    grow/shrink passes ``rv/license.py`` (the machine-checked all-n
    proof envelope) BEFORE any ring change; a denial emits
    ``autoscale_refused``, ticks ``autoscale.refused`` AND the view
    subsystem's ``view.refused`` — never a silent move.  Growth spawns a
    DriverServer via the injected ``spawn`` hook and joins it to ONE
    region's inner ring (two-level ring: motion stays local); shrink
    removes the shard first — FleetRouter.remove_shard re-proposes its
    unresolved instances over the idempotent-PROPOSE primitive, zero
    decision loss (pinned byte-identical in tests/test_control.py) —
    and only then retires the process via the ``retire`` hook.

Every decision is BANKED (``decisions`` list: signals, model verdict,
license verdict, ring before/after) so the autoscale bench and the
fleet-autoscale soak rung can audit the trajectory: SLO held by scaling,
not shedding.
"""

from __future__ import annotations

import itertools
import time as _time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from round_tpu.obs.metrics import METRICS
from round_tpu.obs.trace import TRACE
from round_tpu.runtime.log import get_logger

log = get_logger("control")

# autoscale.* vocabulary (docs/OBSERVABILITY.md)
_C_STEPS = METRICS.counter("autoscale.steps")
_C_GROWS = METRICS.counter("autoscale.grows")
_C_SHRINKS = METRICS.counter("autoscale.shrinks")
_C_REFUSED = METRICS.counter("autoscale.refused")
_C_KNEE_DRIFT = METRICS.counter("autoscale.knee_drift")
_G_SHARDS = METRICS.gauge("autoscale.shards")
# an unlicensed resize is a refused view move: the SAME counter the
# ViewManager ticks (runtime/view.py), so the licensing dashboard sees
# supervisor refusals beside membership refusals
_C_VIEW_REFUSED = METRICS.counter("view.refused")


def _p99(samples: List[float]) -> Optional[float]:
    if not samples:
        return None
    s = sorted(samples)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


class FleetSupervisor:
    """Close the capacity loop over one FleetRouter (module docstring).

    Single-threaded like the router itself: the serving loop (loadgen's
    open-loop pump, apps/fleet.py's bench) calls ``maybe_step()`` once
    per wave, exactly as it calls ``router.pump()`` — the controller is
    one more timer on the same event loop, never a thread racing the
    ring.

    ``spawn(name) -> replicas`` must return a READY replica address
    list (an in-process DriverServer's ``start()``, or a subprocess
    that already binds its ports — apps/fleet.py provides both);
    ``retire(name)`` tears the shard down AFTER its instances migrated.
    """

    def __init__(self, router, *,
                 algo_name: str,
                 n: int,
                 spawn: Callable[[str], List[Tuple[str, int]]],
                 retire: Callable[[str], None],
                 model=None,
                 lanes: int = 16,
                 payload_bytes: int = 0,
                 read_frac: float = 0.0,
                 slo_ms: float = 2000.0,
                 min_shards: int = 1,
                 max_shards: int = 8,
                 license_registry=None,
                 license_solve: Optional[bool] = None,
                 region_fn: Optional[Callable[[int], str]] = None,
                 headroom: float = 0.85,
                 shrink_frac: float = 0.45,
                 window_s: float = 2.0,
                 dwell_steps: int = 2,
                 cooldown_s: float = 5.0,
                 step_interval_s: float = 0.5,
                 nack_rate_tol: float = 1.0,
                 min_p99_samples: int = 5,
                 shard_prefix: str = "a"):
        if min_shards < 1 or max_shards < min_shards:
            raise ValueError(
                f"need 1 <= min_shards <= max_shards, got "
                f"[{min_shards}, {max_shards}]")
        self.router = router
        self.algo_name = algo_name
        self.n = int(n)
        self.spawn = spawn
        self.retire = retire
        self.model = model
        self.lanes = lanes
        self.payload_bytes = payload_bytes
        self.read_frac = read_frac
        self.slo_ms = slo_ms
        self.min_shards = min_shards
        self.max_shards = max_shards
        if license_registry is None:
            from round_tpu.rv.license import ProofLicenseRegistry

            license_registry = ProofLicenseRegistry()
        self.license_registry = license_registry
        self.license_solve = license_solve
        self.region_fn = region_fn or (lambda i: "r0")
        self.headroom = headroom
        self.shrink_frac = shrink_frac
        self.window_s = window_s
        self.dwell_steps = dwell_steps
        self.cooldown_s = cooldown_s
        self.step_interval_s = step_interval_s
        self.nack_rate_tol = nack_rate_tol
        self.min_p99_samples = min_p99_samples
        self.shard_prefix = shard_prefix
        # the shards this supervisor is allowed to resize: seeded from
        # the ring it was handed, grown by every spawn
        self.owned: List[str] = list(router.ring.shards)
        self.spawned: List[str] = []
        self._next_idx = 0
        self.decisions: List[Dict[str, Any]] = []
        self.knee_samples: List[Dict[str, Any]] = []
        self.grows = 0
        self.shrinks = 0
        self.refused = 0
        self.knee_drifts = 0
        # signal windows
        self._samples: deque = deque()   # (t, proposals, resolved, nacks)
        self._lat_cursor = 0
        self._lat_window: deque = deque()  # (t, latency_ms)
        self._grow_dwell = 0
        self._shrink_dwell = 0
        self._last_step = 0.0
        self._cooldown_until = 0.0
        _G_SHARDS.set(len(self.owned))

    # -- signals -----------------------------------------------------------

    def _nack_total(self) -> int:
        return sum(h.get("nacks", 0)
                   for h in self.router.shard_health.values())

    def signals(self, now: float) -> Dict[str, Any]:
        """One window's worth of knee signals off the router: rates from
        the oldest in-window sample to now, p99 over the window's decide
        latencies."""
        lat = self.router.latency_ms
        for ms in itertools.islice(lat.values(), self._lat_cursor, None):
            self._lat_window.append((now, ms))
        self._lat_cursor = len(lat)
        horizon = now - self.window_s
        while self._lat_window and self._lat_window[0][0] < horizon:
            self._lat_window.popleft()
        self._samples.append((now, self.router.proposals,
                              len(self.router.results),
                              self._nack_total()))
        while len(self._samples) > 2 and self._samples[1][0] <= horizon:
            self._samples.popleft()
        t0, p0, r0, k0 = self._samples[0]
        dt = max(1e-6, now - t0)
        lats = [ms for _t, ms in self._lat_window]
        return {
            "offered_dps": (self.router.proposals - p0) / dt,
            "achieved_dps": (len(self.router.results) - r0) / dt,
            "nack_rate": (self._nack_total() - k0) / dt,
            "p99_ms": _p99(lats),
            "lat_samples": len(lats),
            "inflight": len(self.router._inflight),
        }

    def predicted_dps(self, drivers: int) -> Optional[float]:
        if self.model is None or drivers < 1:
            return None
        return float(self.model.predict_dps(
            drivers, self.lanes, payload_bytes=self.payload_bytes,
            read_frac=self.read_frac))

    # -- the control loop --------------------------------------------------

    def maybe_step(self, now: Optional[float] = None
                   ) -> Optional[Dict[str, Any]]:
        """Rate-limited ``step``: the serving loop calls this every
        wave; the controller actually evaluates once per
        ``step_interval_s``."""
        now = _time.monotonic() if now is None else now
        if now - self._last_step < self.step_interval_s:
            return None
        return self.step(now)

    def step(self, now: Optional[float] = None
             ) -> Optional[Dict[str, Any]]:
        """Evaluate the knee signals against the model and resize if the
        dwell/hysteresis discipline says so.  Returns the banked
        decision dict when a resize (or refusal) happened."""
        now = _time.monotonic() if now is None else now
        self._last_step = now
        _C_STEPS.inc()
        sig = self.signals(now)
        drivers = len(self.owned)
        pred = self.predicted_dps(drivers)
        p99 = sig["p99_ms"]
        breach_slo = (p99 is not None and p99 > self.slo_ms
                      and sig["lat_samples"] >= self.min_p99_samples)
        breach_nack = sig["nack_rate"] > self.nack_rate_tol
        breach = breach_slo or breach_nack
        over_model = (pred is not None
                      and sig["offered_dps"] > self.headroom * pred)
        if breach and pred is not None \
                and sig["offered_dps"] <= pred:
            # KNEE DRIFT: the model says this fleet holds the offered
            # load, the measurement disagrees — bank the live knee for
            # the refit; growth still answers the breach (measurement
            # outranks model)
            self.knee_drifts += 1
            _C_KNEE_DRIFT.inc()
            self.knee_samples.append({
                "drivers": drivers, "lanes": self.lanes,
                "payload_bytes": self.payload_bytes,
                "read_frac": self.read_frac,
                "knee_dps": sig["achieved_dps"],
                "why": "slo_breach" if breach_slo else "nack_rate",
                "predicted_dps": pred,
            })
        if breach or over_model:
            self._shrink_dwell = 0
            self._grow_dwell += 1
            if self._grow_dwell >= self.dwell_steps \
                    and now >= self._cooldown_until:
                reason = ("over_model" if over_model and not breach
                          else "slo_breach" if breach_slo
                          else "nack_rate")
                return self.grow(reason, now=now, signals=sig)
            return None
        pred_smaller = self.predicted_dps(drivers - 1)
        if (pred_smaller is not None and drivers > self.min_shards
                and sig["offered_dps"]
                < self.shrink_frac * pred_smaller
                and sig["inflight"] < self.lanes * drivers):
            self._grow_dwell = 0
            self._shrink_dwell += 1
            # shrink dwells twice as long as grow: spare capacity is
            # cheap, a flap back under load is not
            if self._shrink_dwell >= 2 * self.dwell_steps \
                    and now >= self._cooldown_until:
                return self.shrink("under_model", now=now, signals=sig)
            return None
        self._grow_dwell = 0
        self._shrink_dwell = 0
        return None

    # -- resize motion -----------------------------------------------------

    def _license(self):
        return self.license_registry.check(self.algo_name, self.n,
                                           solve=self.license_solve)

    def _bank(self, action: str, reason: str, now: float,
              signals: Optional[Dict[str, Any]], shard: Optional[str],
              region: Optional[str], before: int,
              lic) -> Dict[str, Any]:
        dec = {
            "t": now, "action": action, "reason": reason,
            "shard": shard, "region": region,
            "drivers_before": before, "drivers_after": len(self.owned),
            "predicted_dps": self.predicted_dps(len(self.owned)),
            "signals": dict(signals) if signals else None,
            "license": lic.to_json() if lic is not None else None,
        }
        self.decisions.append(dec)
        return dec

    def _refuse(self, action: str, reason: str, now: float,
                signals, lic) -> Dict[str, Any]:
        self.refused += 1
        _C_REFUSED.inc()
        _C_VIEW_REFUSED.inc()
        log.warning("autoscale %s REFUSED (%s): %s", action,
                    lic.status if lic is not None else "no-license",
                    lic.reason if lic is not None else reason)
        TRACE.emit("autoscale_refused", node=None, op=action,
                   n=self.n, status=lic.status if lic else "unlicensed",
                   reason=lic.reason if lic else reason)
        # refusals cool down too, or a standing breach re-asks the
        # prover every dwell
        self._cooldown_until = now + self.cooldown_s
        self._grow_dwell = 0
        self._shrink_dwell = 0
        return self._bank("refused", f"{action}:{reason}", now, signals,
                          None, None, len(self.owned), lic)

    def grow(self, reason: str = "manual", now: Optional[float] = None,
             signals: Optional[Dict[str, Any]] = None
             ) -> Optional[Dict[str, Any]]:
        """Spawn one DriverServer shard and join it to the ring —
        license first, ring change only on a grant."""
        now = _time.monotonic() if now is None else now
        if len(self.owned) >= self.max_shards:
            self._grow_dwell = 0
            return None  # at the fleet ceiling: shed is the only escape
        lic = self._license()
        if not lic.ok:
            return self._refuse("grow", reason, now, signals, lic)
        before = len(self.owned)
        name = f"{self.shard_prefix}{self._next_idx}"
        region = self.region_fn(self._next_idx)
        self._next_idx += 1
        replicas = self.spawn(name)
        self.router.add_shard(name, replicas, region=region)
        self.owned.append(name)
        self.spawned.append(name)
        self.grows += 1
        _C_GROWS.inc()
        _G_SHARDS.set(len(self.owned))
        self._grow_dwell = 0
        self._cooldown_until = now + self.cooldown_s
        log.info("autoscale grow -> %d shards (+%s in %s): %s",
                 len(self.owned), name, region, reason)
        TRACE.emit("autoscale_grow", node=None, shard=name,
                   region=region, shards=len(self.owned), reason=reason)
        return self._bank("grow", reason, now, signals, name, region,
                          before, lic)

    def shrink(self, reason: str = "manual",
               now: Optional[float] = None,
               signals: Optional[Dict[str, Any]] = None
               ) -> Optional[Dict[str, Any]]:
        """Retire the most recently spawned shard: licensed, then
        migrated (remove_shard re-proposes its unresolved instances —
        zero decision loss), then torn down."""
        now = _time.monotonic() if now is None else now
        if not self.spawned or len(self.owned) <= self.min_shards:
            self._shrink_dwell = 0
            return None  # only supervisor-spawned shards are victims
        lic = self._license()
        if not lic.ok:
            return self._refuse("shrink", reason, now, signals, lic)
        before = len(self.owned)
        name = self.spawned.pop()
        region = self.router.ring.region_of(name)
        migrated = self.router.remove_shard(name)
        self.owned.remove(name)
        self.retire(name)
        self.shrinks += 1
        _C_SHRINKS.inc()
        _G_SHARDS.set(len(self.owned))
        self._shrink_dwell = 0
        self._cooldown_until = now + self.cooldown_s
        log.info("autoscale shrink -> %d shards (-%s, %d migrated): %s",
                 len(self.owned), name, migrated, reason)
        TRACE.emit("autoscale_shrink", node=None, shard=name,
                   region=region, shards=len(self.owned),
                   migrated=migrated, reason=reason)
        dec = self._bank("shrink", reason, now, signals, name, region,
                         before, lic)
        dec["migrated"] = migrated
        return dec

    def summary(self) -> Dict[str, Any]:
        """The bench/soak banking surface."""
        return {
            "shards": len(self.owned),
            "grows": self.grows,
            "shrinks": self.shrinks,
            "refused": self.refused,
            "knee_drifts": self.knee_drifts,
            "decisions": list(self.decisions),
            "knee_samples": list(self.knee_samples),
        }
