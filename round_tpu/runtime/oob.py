"""Out-of-band messaging: Tag flags, the default handler, message recovery.

Reference parity: every packet carries an 8-byte Tag
``flag:1B | callStack:1B | instance:2B | round:4B`` (Tag.scala:22-25) whose
flag routes it — Normal/Dummy to the instance dispatcher, Error reserved,
anything else user-definable and routed to the Runtime's *defaultHandler*
(Runtime.scala:99-101, 151-155).  The PerfTest harness builds its decision
replay on exactly this: a normal message for an already-decided instance
makes the peer answer with a ``Decision``-flagged message (or ``TooLate`` if
evicted), and the laggard's defaultHandler records/stops accordingly
(PerfTest.scala:40-60, trySendDecision :86-100); a message for an unknown
*future* instance lazily starts it (PerfTest2.scala:72-110).

In the TPU build the hot path has no packets (the round exchange is the
fused kernel), but the *control plane* between pools keeps the reference's
message shape: ``Message = Tag + payload`` over a host-side ``LocalBus``.
``PoolNode`` wires an InstancePool to the bus with the reference's handler
semantics, replacing the round-1 direct-call ``recover_from`` with a
message-driven flow a real transport could carry unchanged (the Tag packs
to the same 8-byte layout).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from round_tpu.core.time import Instance
from round_tpu.runtime.instances import InstancePool, MAX_INSTANCE

# Flag space (Tag.scala:5-12): 0..2 reserved, >= 3 user-definable.
FLAG_NORMAL = 0
FLAG_DUMMY = 1
FLAG_ERROR = 2
# the PerfTest recovery protocol's user flags (PerfTest.scala:30-38)
FLAG_DECISION = 4
FLAG_TOO_LATE = 5
FLAG_RECOVERY = 6
# transport-internal frame coalescing (runtime/transport.py): the payload
# is a sequence of `u64 tag | u32 len | payload` sub-frames accumulated
# for one destination and flushed as ONE wire frame (the Netty
# write-coalescing role).  Split back into logical frames by header peek
# inside HostTransport.recv — this flag never reaches a HostRunner.
# 0xB7, far from the user-flag range apps allocate from (lock_manager
# already took 8/9; a collision here would make the transport shred an
# app's frames as containers).
FLAG_BATCH = 0xB7
# view-change catch-up (runtime/view.py): the reply a current-view replica
# sends to traffic stamped with an OLD epoch — payload is the serialized
# View (epoch + address list), the receiver adopts it and rewires
FLAG_VIEW = 7
# admission NACK (overload hardening, docs/HOST_FAULT_MODEL.md): the reply
# an overloaded replica sends instead of stashing a future-instance frame
# it cannot afford to hold — "your frame was SHED, not lost to the wire".
# Empty payload; the instance id in the Tag names what was refused.  The
# retry contract is the protocol's own retransmission: every live round
# re-sends, and a shed replica catches up via the decision-reply path once
# pressure clears, so a NACK never needs (or gets) an explicit client
# retry loop — it exists so shedding is ACCOUNTED (overload.* counters,
# trace_view classification) instead of indistinguishable from loss.
# 10: clear of lock_manager's 8/9 and the reserved 0..2 range.
FLAG_NACK = 10
# the fleet client protocol (runtime/fleet.py, docs/SERVING.md): frames
# from CLIENT peers — transport senders OUTSIDE the consensus group
# (LaneDriver(clients=...)), the front-door router's id space.
#   PROPOSE: "start instance tag.instance with this payload as the
#   initial value" — payload is a codec-encoded scalar (int32) or byte
#   vector (uint8[B], the LastVotingBytes workload).  Idempotent by
#   design, which is what makes it the retry AND the catch-up: re-sent
#   for a live instance it is ignored, for a completed one it is
#   answered with the FLAG_DECISION (or FLAG_TOO_LATE if undecided)
#   the client may have missed, and under admission shedding it gets
#   the accounted FLAG_NACK — the client backs off and retries
#   (FleetRouter's capped-backoff state machine).
#   SUBSCRIBE: "stream me every decision this driver completes from
#   now on" (empty payload; the sender id is the subscription).
# 11/12: clear of lock_manager's 8/9, FLAG_NACK 10, and FLAG_BATCH.
FLAG_PROPOSE = 11
FLAG_SUBSCRIBE = 12
# round-consistent snapshot samples (round_tpu/snap, docs/SNAPSHOTS.md):
# a replica's own per-lane state sampled at a ROUND BOUNDARY — the HO
# model's communication-closed rounds make a round-aligned cut a
# consistent global state BY CONSTRUCTION, so no Chandy-Lamport marker
# protocol rides the wire, only the samples themselves.  Payload is a
# codec-typed dict (runtime/codec.py — zero pickle, template-parseable
# like every hot frame): the state leaves, the instance's proposal row,
# and a blake2b digest of the canonical state encoding (divergence
# forensics).  Tag carries the coordinate: instance, round, and the view
# epoch in the callStack byte (a cut must never join samples across a
# membership change).  13: clear of lock_manager's 8/9, FLAG_NACK 10,
# the fleet pair 11/12, and FLAG_BATCH.
FLAG_SNAP = 13
# the KV serving verbs (round_tpu/kv, docs/KV.md): client frames beside
# the fleet pair, same untrusted-boundary discipline.
#   READ: "answer this key at this consistency grade" — payload is a
#   codec dict {r: read id, k: key bytes, g: grade} and the reply rides
#   the SAME flag back with {r, st, seq, v}.  Reads never occupy the
#   consensus instance-id space: Tag.instance carries the 16-bit read id
#   only so shedding can refuse one with the accounted FLAG_NACK
#   (linearizable reads queue a round-wave barrier, so under admission
#   pressure they are shed and NACK-accounted exactly like proposals;
#   lease/stale grades answer from applied state and stay cheap enough
#   to serve while shedding).
#   TXN: "propose this transaction-control record" — PROPOSE's exact
#   state machine (idempotent retry/catch-up, FLAG_DECISION stream,
#   accounted NACK under shedding) but the payload MUST decode as a KV
#   transaction record (kv/store.py: TXN/PREPARE/COMMIT/ABORT), so a
#   shard can refuse transaction verbs when KV serving is off and
#   account them separately (kv.txn_frames).
# 14/15: clear of lock_manager's 8/9, FLAG_NACK 10, the fleet pair
# 11/12, FLAG_SNAP 13 and FLAG_BATCH.
FLAG_READ = 14
FLAG_TXN = 15
# the serveable instance-id range for fleet clients: 0 is the lane
# driver's free-slot marker and 0xFF00.. is reserved for view-change
# consensus (runtime/view.py view_instance) — BOTH the trusted router
# (FleetRouter.propose) and the untrusted shard boundary
# (LaneDriver._client_frame) enforce it, so a hostile front-door peer
# cannot run data-plane rounds on a membership-consensus id.
FLEET_MIN_INSTANCE = 1
FLEET_MAX_INSTANCE = 0xFEFF


@dataclasses.dataclass(frozen=True)
class Tag:
    """8-byte packet header (Tag.scala:22-62).

    The ``call_stack`` byte — unused by this runtime's protocols, like the
    reference's — is REUSED by the view subsystem (runtime/view.py) to
    stamp the sender's view epoch (mod 256) onto every NORMAL frame, so a
    replica still running an old view is detected from its very first
    packet and answered with a FLAG_VIEW catch-up.  On the CLIENT verbs
    (FLAG_PROPOSE / FLAG_TXN / FLAG_READ / FLAG_NACK) the byte is free —
    no epoch rides there — and carries the TENANT id (0-255) for
    per-tenant weighted-fair admission (runtime/instances.py
    TenantAdmission, docs/SERVING.md): zero wire-format change."""

    instance: int
    round: int = 0
    flag: int = FLAG_NORMAL
    call_stack: int = 0

    def pack(self) -> int:
        """The reference's wire layout: flag byte 0, callStack byte 1,
        instance bytes 2-3, round bytes 4-7."""
        return (
            (self.flag & 0xFF)
            | (self.call_stack & 0xFF) << 8
            | (self.instance & 0xFFFF) << 16
            | (self.round & 0xFFFFFFFF) << 32
        )

    @classmethod
    def unpack(cls, word: int) -> "Tag":
        return cls(
            flag=word & 0xFF,
            call_stack=(word >> 8) & 0xFF,
            instance=(word >> 16) & 0xFFFF,
            round=(word >> 32) & 0xFFFFFFFF,
        )


@dataclasses.dataclass
class Message:
    """An out-of-band message: routed by tag.flag (Message.scala:15-80)."""

    sender: int
    tag: Tag
    payload: Any = None


class LocalBus:
    """Host-side point-to-point wire between nodes (the control-plane
    analogue of Runtime.sendMessage, Runtime.scala:138-143).  Delivery is
    explicit (``deliver``/``deliver_all``) so tests can reorder/drop —
    faults on the control plane, like the data plane's HO masks."""

    def __init__(self):
        self._nodes: Dict[int, "PoolNode"] = {}
        self._queues: Dict[int, List[Message]] = {}

    def register(self, node: "PoolNode") -> None:
        self._nodes[node.node_id] = node
        self._queues.setdefault(node.node_id, [])

    def send(self, to: int, msg: Message) -> None:
        if to in self._queues:  # unknown peers: dropped, like UDP
            self._queues[to].append(msg)

    def deliver(self, node_id: int, limit: Optional[int] = None) -> int:
        """Hand queued messages to the node's default handler; returns the
        number delivered.  A handler error (e.g. a reserved/unknown flag)
        must not discard the rest of the popped batch — the remaining
        messages are still delivered and the first error re-raised after."""
        q = self._queues.get(node_id, [])
        k = len(q) if limit is None else min(limit, len(q))
        batch, self._queues[node_id] = q[:k], q[k:]
        node = self._nodes[node_id]
        first_err: Optional[Exception] = None
        for m in batch:
            try:
                node.default_handler(m)
            except Exception as e:  # noqa: BLE001 - per-message isolation
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err
        return k

    def deliver_all(self) -> int:
        total = 0
        while any(self._queues.values()):
            for nid in list(self._queues):
                total += self.deliver(nid)
        return total


class PoolNode:
    """An InstancePool attached to the bus with the reference's
    defaultHandler semantics (PerfTest.scala:40-60, PerfTest2.scala:72-110).

    - normal-flag message for an instance we already decided → reply
      FLAG_DECISION with the value (trySendDecision);
    - normal-flag for an instance past our window that we no longer have →
      reply FLAG_TOO_LATE;
    - normal-flag for an unknown *future* instance → lazy join: start it
      via ``on_unknown_instance`` (PerfTest2's startInstance path);
    - FLAG_DECISION → record the decision, stop any local run of it;
    - FLAG_TOO_LATE → stop the local run (the value is unrecoverable here);
    - FLAG_RECOVERY → explicit ask: same answer path as a normal probe.
    """

    def __init__(
        self,
        node_id: int,
        pool: InstancePool,
        bus: LocalBus,
        on_unknown_instance: Optional[Callable[[int], None]] = None,
        on_decision: Optional[Callable[[int, Any], None]] = None,
    ):
        self.node_id = node_id
        self.pool = pool
        self.bus = bus
        self.on_unknown_instance = on_unknown_instance
        self.on_decision = on_decision
        self.version = 0  # highest instance id this node has opened
        bus.register(self)

    # -- outgoing ----------------------------------------------------------

    def note_opened(self, instance_id: int) -> None:
        iid = instance_id % MAX_INSTANCE
        if Instance.lt(self.version, iid):
            self.version = iid

    def ask_decision(self, peer: int, instance_id: int) -> None:
        """Ask a peer for an old instance's outcome (Recovery flag)."""
        self.bus.send(
            peer,
            Message(self.node_id, Tag(instance_id % MAX_INSTANCE,
                                      flag=FLAG_RECOVERY)),
        )

    def probe(self, peer: int, instance_id: int, round_: int = 0) -> None:
        """A normal protocol message that leaks to a peer's default handler
        (the implicit recovery trigger: the laggard's old traffic)."""
        self.bus.send(
            peer,
            Message(self.node_id, Tag(instance_id % MAX_INSTANCE, round_)),
        )

    # -- incoming ----------------------------------------------------------

    def default_handler(self, msg: Message) -> None:
        tag = msg.tag
        iid = tag.instance
        if tag.flag in (FLAG_NORMAL, FLAG_DUMMY, FLAG_RECOVERY):
            res = self.pool.get_decision(iid)
            if res is not None and res.value is not None:
                # only an actual decision is replayable (trySendDecision's
                # getDec match, PerfTest.scala:86-100); an instance that
                # *finished* undecided falls through to TooLate below
                self.bus.send(
                    msg.sender,
                    Message(self.node_id, Tag(iid, flag=FLAG_DECISION),
                            payload=res.value),
                )
            elif self.pool.is_running(iid):
                pass  # live instance: the data plane handles it
            elif res is not None or Instance.lt(iid, self.version):
                # finished-undecided here, or older than anything we kept:
                # unrecoverable from us
                self.bus.send(
                    msg.sender,
                    Message(self.node_id, Tag(iid, flag=FLAG_TOO_LATE)),
                )
            elif tag.flag != FLAG_RECOVERY and self.on_unknown_instance:
                # future instance: lazy join (PerfTest2.scala:72-83)
                self.on_unknown_instance(iid)
                self.note_opened(iid)
        elif tag.flag == FLAG_DECISION:
            self.pool.adopt_decision(iid, msg.payload)
            if self.on_decision:
                self.on_decision(iid, msg.payload)
        elif tag.flag == FLAG_TOO_LATE:
            self.pool.stop(iid)
        else:
            raise ValueError(f"unknown or error flag: {tag.flag}")
