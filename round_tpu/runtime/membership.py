"""Membership: Replica / Group / Directory (reference: runtime/Replicas.scala).

The reference keeps an immutable ``Group`` (pid -> network address) wrapped in
a lock-guarded ``Directory`` that supports add/remove/compact for dynamic
membership; TCP channels are rewired when the group changes
(TcpRuntime.scala:75-110) and ids are renamed to stay contiguous
(``renameReplica``, Replicas.scala:136-142).

Here the group is host-side metadata: an instance always executes over lanes
0..n-1 of the engine, and the Group maps those lane ids to stable replica
names/addresses.  Membership changes happen *between* instances (exactly the
reference's DynamicMembership pattern: consensus decides a membership op,
then the group is updated and the next instance runs over the new group) —
so a change is: mutate the Directory, then start new instances with the new
``group.size``.  Addresses are opaque to the simulator (the wire there is
the on-device exchange kernel); the host deployment path consumes them —
runtime/host.py + runtime/transport.py run one replica per OS process with
the id→(host, port) map as the peer table (the reference's Replica records,
Replicas.scala:9-18).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class Replica:
    """One group member: stable id + address (Replicas.scala:9-18)."""

    id: int
    address: str = ""
    port: int = 0

    def rename(self, new_id: int) -> "Replica":
        return Replica(new_id, self.address, self.port)


class Group:
    """Immutable membership indexed by contiguous ProcessID 0..n-1
    (Replicas.scala:20-131)."""

    def __init__(self, replicas: Sequence[Replica], check_contiguous: bool = True):
        self.replicas: Tuple[Replica, ...] = tuple(replicas)
        if check_contiguous:
            ids = [r.id for r in self.replicas]
            if ids != list(range(len(ids))):
                raise ValueError(f"replica ids must be 0..n-1, got {ids}")
        self._by_addr: Dict[Tuple[str, int], Replica] = {
            (r.address, r.port): r for r in self.replicas
        }

    @property
    def size(self) -> int:
        return len(self.replicas)

    def get(self, pid: int) -> Replica:
        return self.replicas[pid]

    def contains(self, pid: int) -> bool:
        return 0 <= pid < len(self.replicas)

    def inet_to_id(self, address: str, port: int) -> Optional[int]:
        """Address -> pid (Replicas.scala:74-80)."""
        r = self._by_addr.get((address, port))
        return r.id if r is not None else None

    def add(self, address: str, port: int = 0) -> "Group":
        """New group with one more replica at the next id."""
        return Group(self.replicas + (Replica(self.size, address, port),))

    def remove(self, pid: int) -> "Group":
        """New group without ``pid``, remaining ids renamed to 0..n-2
        (the compaction of renameReplica, Replicas.scala:136-142)."""
        if not self.contains(pid):
            raise KeyError(pid)
        kept = [r for r in self.replicas if r.id != pid]
        return Group([r.rename(i) for i, r in enumerate(kept)])

    def renaming_from(self, old: "Group") -> Dict[int, Optional[int]]:
        """Map each old pid to its new pid (None if removed) — what a
        decision log migration needs after a membership change."""
        out: Dict[int, Optional[int]] = {}
        for r in old.replicas:
            out[r.id] = self.inet_to_id(r.address, r.port)
        return out


class Directory:
    """Lock-guarded mutable view of the current Group
    (Replicas.scala:152-201)."""

    def __init__(self, group: Group):
        self._group = group
        self._lock = threading.Lock()

    @property
    def group(self) -> Group:
        with self._lock:
            return self._group

    @group.setter
    def group(self, g: Group) -> None:
        with self._lock:
            self._group = g

    @property
    def size(self) -> int:
        return self.group.size

    def add_replica(self, address: str, port: int = 0) -> Group:
        with self._lock:
            self._group = self._group.add(address, port)
            return self._group

    def remove_replica(self, pid: int) -> Group:
        with self._lock:
            self._group = self._group.remove(pid)
            return self._group


def local_group(n: int, base_port: int = 4444) -> Group:
    """A localhost group of n replicas (the shape of sample-conf.xml)."""
    return Group([Replica(i, "127.0.0.1", base_port + i) for i in range(n)])
