"""Checkpoint/restore of simulation state tensors + decision logs.

The reference has no framework-level checkpointing (SURVEY.md §5: the
closest is the batching example's snapshot/recovery); here it is native:
the process-state pytree is arrays, so a checkpoint is an .npz plus a JSON
manifest (step, instance, rng key, tree structure), and a host replica's
durable record additionally carries its decision log
(runtime/decisions.py) as a TSV — the artifact crash-restart recovery
resumes from (runtime/chaos.py, apps/host_replica.py --checkpoint-dir).

Durability discipline: every file is write-then-rename, so a crash (or a
SIGKILL from the chaos harness) mid-overwrite can never leave a valid
manifest pointing at a torn state.npz; the manifest additionally rides
inside the npz itself, so a crash BETWEEN the two renames (new state.npz,
stale manifest.json) restores the newer consistent pair instead of
pairing an old step watermark with new state.  Restore NEVER unpickles
(allow_pickle=False) and raises ``CheckpointError`` on every corruption
mode — truncated npz, missing/garbled manifest, leaf-count or treedef
mismatch — instead of restoring garbage or swapped fields.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from round_tpu.obs.metrics import METRICS
from round_tpu.obs.trace import TRACE
from round_tpu.runtime.decisions import DecisionLog


class CheckpointError(ValueError):
    """A checkpoint that must not be restored: missing, torn, or written
    for a different state shape.  Subclasses ValueError so pre-existing
    treedef-mismatch handlers keep working."""


def _corruption(msg: str) -> CheckpointError:
    """Build a CheckpointError for a DETECTED corruption, recording it on
    the observability surface (ckpt.errors counter + ckpt_error trace
    event).  Detection sites raise through this helper rather than the
    constructor counting, so re-constructed instances (unpickling across
    a process boundary, tests building synthetic errors, semantic
    kind-mismatch raises elsewhere) cannot inflate the corruption metric.
    """
    METRICS.counter("ckpt.errors").inc()
    if TRACE.enabled:
        TRACE.emit("ckpt_error", error=msg[:200])
    return CheckpointError(msg)


def save(path: str, state: Any, *, step: int = 0,
         meta: Optional[Dict] = None,
         decisions: Optional[DecisionLog] = None) -> None:
    """Write `state` (any pytree of arrays) + metadata.  `path` is a
    directory; contents: state.npz + manifest.json (+ decisions.tsv when
    a DecisionLog is supplied).  Every file is written atomically, and
    the manifest ALSO rides inside the npz — state and metadata then
    share ONE rename, so a crash landing between the individual file
    renames below still leaves a restorable, mutually-consistent pair
    (see restore)."""
    t0 = time.monotonic()
    os.makedirs(path, exist_ok=True)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    arrays = {f"leaf{i}": np.asarray(v) for i, v in enumerate(leaves)}
    manifest = {
        "step": int(step),
        "n_leaves": len(leaves),
        "treedef": str(treedef),
        "decisions": decisions is not None,
        "meta": meta or {},
    }
    arrays["__manifest__"] = np.frombuffer(
        json.dumps(manifest).encode(), dtype=np.uint8)
    # write-then-rename: a crash mid-overwrite must never leave a valid
    # manifest pointing at a torn state.npz
    tmp_npz = os.path.join(path, "state.npz.tmp")
    with open(tmp_npz, "wb") as fh:
        np.savez(fh, **arrays)
    os.replace(tmp_npz, os.path.join(path, "state.npz"))
    if decisions is not None:
        tmp_tsv = os.path.join(path, "decisions.tsv.tmp")
        decisions.dump_tsv(tmp_tsv)
        os.replace(tmp_tsv, os.path.join(path, "decisions.tsv"))
    tmp = os.path.join(path, "manifest.json.tmp")
    with open(tmp, "w") as fh:
        json.dump(manifest, fh)
    os.replace(tmp, os.path.join(path, "manifest.json"))
    METRICS.counter("ckpt.saves").inc()
    METRICS.histogram("ckpt.save_s").observe(time.monotonic() - t0)
    if TRACE.enabled:
        TRACE.emit("ckpt_save", step=int(step), path=path,
                   n_leaves=len(leaves))


def _read_manifest(path: str) -> Dict:
    mpath = os.path.join(path, "manifest.json")
    try:
        with open(mpath) as fh:
            return json.load(fh)
    except FileNotFoundError:
        # absence is not corruption: callers probe fresh directories
        # (exists() races aside) — keep it off the ckpt.errors metric
        raise CheckpointError(f"no checkpoint manifest at {mpath}") from None
    except (OSError, ValueError) as e:
        raise _corruption(f"unreadable checkpoint manifest "
                          f"{mpath}: {e}") from e


def restore(path: str, like: Any) -> Tuple[Any, int, Dict]:
    """Read a checkpoint written by `save`.  `like` supplies the pytree
    structure (same treedef as the saved state).  Returns
    (state, step, meta).  Raises CheckpointError (a ValueError) on any
    corruption: missing manifest, truncated/garbled state.npz, leaf
    count or treedef mismatch — never unpickles, never restores swapped
    fields."""
    manifest = _read_manifest(path)
    npz = os.path.join(path, "state.npz")
    try:
        # allow_pickle=False is load's default but the no-unpickling
        # guarantee is part of this function's contract — keep it explicit
        data = np.load(npz, allow_pickle=False)
        if "__manifest__" in data:
            embedded = json.loads(bytes(data["__manifest__"]).decode())
            if embedded != manifest:
                # a crash landed between save()'s state.npz and
                # manifest.json renames: the npz + its embedded manifest
                # are the newer CONSISTENT pair (one rename wrote both);
                # honoring the stale manifest.json would pair its old
                # step with the new state — an SMR restore would then
                # re-apply already-applied instances
                manifest = embedded
        leaves = [data[f"leaf{i}"] for i in range(manifest["n_leaves"])]
    except CheckpointError:
        raise
    except Exception as e:  # noqa: BLE001 — BadZipFile, zlib errors,
        # KeyError on missing members, OSError on truncation: every
        # corruption mode surfaces as one clean error class
        raise _corruption(
            f"corrupt or truncated checkpoint state at {npz}: {e}") from e
    _, treedef = jax.tree_util.tree_flatten(like)
    if treedef.num_leaves != len(leaves):
        raise _corruption(
            f"checkpoint has {len(leaves)} leaves, template has "
            f"{treedef.num_leaves}"
        )
    # leaf count alone lets a reordered pytree restore with fields swapped;
    # the recorded treedef string must match the template's exactly
    if manifest.get("treedef") is not None and manifest["treedef"] != str(treedef):
        raise _corruption(
            "checkpoint treedef does not match the restore template:\n"
            f"  saved:    {manifest['treedef']}\n"
            f"  template: {treedef}"
        )
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    METRICS.counter("ckpt.restores").inc()
    if TRACE.enabled:
        TRACE.emit("ckpt_restore", step=int(manifest["step"]), path=path)
    return state, manifest["step"], manifest.get("meta", {})


def restore_decisions(path: str) -> DecisionLog:
    """The decision log saved alongside a checkpoint (save(...,
    decisions=...)).  Raises CheckpointError when the checkpoint carries
    none."""
    manifest = _read_manifest(path)
    tsv = os.path.join(path, "decisions.tsv")
    if not manifest.get("decisions") or not os.path.exists(tsv):
        raise CheckpointError(f"checkpoint at {path} has no decision log")
    return DecisionLog.load_tsv(tsv)


def exists(path: str) -> bool:
    return os.path.exists(os.path.join(path, "manifest.json"))
