"""Checkpoint/restore of simulation state tensors.

The reference has no framework-level checkpointing (SURVEY.md §5: the
closest is the batching example's snapshot/recovery); here it is native:
the process-state pytree is arrays, so a checkpoint is an .npz plus a JSON
manifest (step, instance, rng key, tree structure).  Uses orbax when
available for large multi-host state; the .npz path has no dependencies.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np


def save(path: str, state: Any, *, step: int = 0, meta: Optional[Dict] = None) -> None:
    """Write `state` (any pytree of arrays) + metadata.  `path` is a
    directory; contents: state.npz + manifest.json."""
    os.makedirs(path, exist_ok=True)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    arrays = {f"leaf{i}": np.asarray(v) for i, v in enumerate(leaves)}
    # write-then-rename: a crash mid-overwrite must never leave a valid
    # manifest pointing at a torn state.npz
    tmp_npz = os.path.join(path, "state.npz.tmp")
    with open(tmp_npz, "wb") as fh:
        np.savez(fh, **arrays)
    os.replace(tmp_npz, os.path.join(path, "state.npz"))
    manifest = {
        "step": int(step),
        "n_leaves": len(leaves),
        "treedef": str(treedef),
        "meta": meta or {},
    }
    tmp = os.path.join(path, "manifest.json.tmp")
    with open(tmp, "w") as fh:
        json.dump(manifest, fh)
    os.replace(tmp, os.path.join(path, "manifest.json"))


def restore(path: str, like: Any) -> Tuple[Any, int, Dict]:
    """Read a checkpoint written by `save`.  `like` supplies the pytree
    structure (same treedef as the saved state).  Returns
    (state, step, meta)."""
    with open(os.path.join(path, "manifest.json")) as fh:
        manifest = json.load(fh)
    data = np.load(os.path.join(path, "state.npz"))
    leaves = [data[f"leaf{i}"] for i in range(manifest["n_leaves"])]
    _, treedef = jax.tree_util.tree_flatten(like)
    assert treedef.num_leaves == len(leaves), (
        f"checkpoint has {len(leaves)} leaves, template has "
        f"{treedef.num_leaves}"
    )
    # leaf count alone lets a reordered pytree restore with fields swapped;
    # the recorded treedef string must match the template's exactly
    if manifest.get("treedef") is not None and manifest["treedef"] != str(treedef):
        raise ValueError(
            "checkpoint treedef does not match the restore template:\n"
            f"  saved:    {manifest['treedef']}\n"
            f"  template: {treedef}"
        )
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    return state, manifest["step"], manifest.get("meta", {})


def exists(path: str) -> bool:
    return os.path.exists(os.path.join(path, "manifest.json"))
