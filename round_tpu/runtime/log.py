"""Leveled logging for the framework (the dzufferey.utils.Logger role:
-v/-q/--hide verbosity plumbing, utils/Options.scala:8-27 + logback.xml).

A thin layer over the stdlib: one `round_tpu` logger hierarchy, a
`configure(verbosity)` entry the CLIs share (each -v raises, each -q
lowers, mirroring the reference's flag semantics), and `hide(prefix)` for
the reference's --hide (suppress a component's output by name).

    from round_tpu.runtime.log import get_logger
    log = get_logger("engine")          # round_tpu.engine
    log.info("round %d", r)
"""

from __future__ import annotations

import logging
import sys
from typing import Optional

ROOT = "round_tpu"

_LEVELS = [logging.ERROR, logging.WARNING, logging.INFO, logging.DEBUG]


class _DynamicStderr:
    """Resolve sys.stderr at EMIT time: pytest's capture machinery (and
    anything else that swaps sys.stderr) keeps working, and a handler bound
    at first-configure time can never wedge logging onto a closed stream."""

    def __init__(self, explicit=None):
        self.explicit = explicit

    def write(self, s):
        (self.explicit or sys.stderr).write(s)

    def flush(self):
        f = self.explicit or sys.stderr
        if not getattr(f, "closed", False):
            f.flush()


def get_logger(component: Optional[str] = None) -> logging.Logger:
    name = ROOT if not component else f"{ROOT}.{component}"
    return logging.getLogger(name)


def configure(verbosity: int = 0, stream=None) -> logging.Logger:
    """verbosity 0 = warnings (the reference's default Notice-ish level),
    each +1 → info/debug, each -1 → errors only.  Re-configurable: each
    call replaces the handler (so a later stream= takes effect) and the
    default destination tracks the CURRENT sys.stderr."""
    root = logging.getLogger(ROOT)
    idx = max(0, min(len(_LEVELS) - 1, verbosity + 1))
    root.setLevel(_LEVELS[idx])
    for h in list(root.handlers):
        root.removeHandler(h)
    h = logging.StreamHandler(_DynamicStderr(stream))
    h.setFormatter(logging.Formatter("[%(levelname).1s %(name)s] %(message)s"))
    root.addHandler(h)
    root.propagate = False
    return root


_hidden: set = set()


def hide(component: str) -> None:
    """Suppress one component's output (--hide, Options.scala:11-13).
    Undone by unhide() or any configure_from_args() without the name."""
    _hidden.add(component)
    get_logger(component).setLevel(logging.CRITICAL + 1)


def unhide(component: str) -> None:
    _hidden.discard(component)
    get_logger(component).setLevel(logging.NOTSET)


def add_verbosity_flags(ap) -> None:
    """The shared CLI surface: -v/--verbose (repeatable), -q/--quiet
    (repeatable), --hide NAME (repeatable)."""
    ap.add_argument("-v", "--verbose", action="count", default=0)
    ap.add_argument("-q", "--quiet", action="count", default=0)
    ap.add_argument("--hide", action="append", default=[],
                    metavar="COMPONENT")


def configure_from_args(args) -> logging.Logger:
    root = configure(args.verbose - args.quiet)
    for c in list(_hidden):  # reconfiguration clears prior hides
        unhide(c)
    for c in args.hide:
        hide(c)
    return root
