"""Live cluster reconfiguration: versioned views, consensus-decided
membership ops, and the rewiring of a RUNNING host cluster.

Reference parity: example/DynamicMembership.scala:231-245 — the group runs
consensus on a MembershipOp; once decided, the Directory is mutated, ids
are renamed to stay contiguous (Replicas.scala:136-142) and the TCP
channels are rewired (TcpRuntime.scala:75-110).  The earlier reproduction
ran this flow at simulation level only (apps/dynamic_membership.py — "no
sockets to rewire"); this module is the missing runtime half:

  * ``View`` — a VERSIONED group: ``epoch`` (bumped once per applied op)
    + the immutable ``Group`` of runtime/membership.py.  The epoch rides
    every NORMAL frame in the Tag's otherwise-unused callStack byte
    (runtime/oob.py), so a replica still wired for an old view is detected
    from its first packet.

  * ``ViewManager`` — per-replica: (a) runs one consensus instance on the
    encoded op over the CURRENT view's wire (the same HostRunner +
    Algorithm machinery as the data plane, under a reserved high instance
    id), (b) applies the decided op ATOMICALLY — new Group with contiguous
    ids, ``HostTransport.rewire`` swaps the live peer table (unrelated
    channels untouched), epoch += 1 — and (c) answers old-epoch traffic
    with a FLAG_VIEW catch-up carrying the serialized view, which the
    stale replica adopts (rewire + epoch jump) without re-running the
    membership consensus it missed.

  * Op encoding — ``kind * 2^24 + arg`` with ADD(port) / REMOVE(pid),
    shared with the simulation path (apps/dynamic_membership.py imports
    these).  An ADD's address is ``(add_host, port)`` — localhost by
    default, the deployment shape of the multi-process harness.

A replica that discovers it was REMOVED (its address is absent from the
new group) sets ``removed`` and stops touching the wire; the host loop
exits it cleanly.  An ADDED replica is started against the post-add view
and joins via the existing decision-replay catch-up path
(apps/host_replica.py --join-wait holds it silent until the add actually
decides, so its future-epoch traffic cannot leak the view early).

Transport churn-tolerance underneath this lives in runtime/transport.py
(``rewire``, ``start_reconnect``) and native/transport.cpp; chaos faults
compose — runtime/chaos.py's FaultyTransport schedules are pure functions
of (seed, src, dst, round) and survive any number of reconnects.
"""

from __future__ import annotations

import dataclasses
import pickle
import time as _time
from typing import Any, Dict, List, Optional, Tuple

from round_tpu.obs.metrics import METRICS
from round_tpu.obs.trace import TRACE
from round_tpu.runtime.log import get_logger
from round_tpu.runtime.membership import Group, Replica
from round_tpu.runtime.oob import FLAG_VIEW, Tag

log = get_logger("view")

_C_CHANGES = METRICS.counter("view.changes")
_C_ADOPTS = METRICS.counter("view.adopts")
_C_STALE = METRICS.counter("view.stale_peers")
_C_REPLIES = METRICS.counter("view.replies")
# proof-licensed reconfiguration (round_tpu/rv/license.py,
# docs/MEMBERSHIP.md "proof-licensed resizing"): ops refused because no
# all-n proof licenses the target size, and ops that proceeded anyway
# (the --view-unlicensed-ok escape hatch, or decided elsewhere and
# adopted) leaving the replica flagged degraded
_C_REFUSED = METRICS.counter("view.refused")
_C_DEGRADED = METRICS.counter("view.degraded")

# -- the MembershipOp encoding (DynamicMembership.scala:217-229), shared
# with the simulation path: apps/dynamic_membership.py imports these -----
ADD, REMOVE = 1, 2


def encode(kind: int, arg: int) -> int:
    if not 0 <= arg < (1 << 24):
        raise ValueError(f"op arg must fit 24 bits, got {arg}")
    return kind * (1 << 24) + arg


def decode(op: int) -> Tuple[int, int]:
    return op // (1 << 24), op % (1 << 24)


# the view-change consensus runs under reserved HIGH instance ids so it can
# never collide with the data plane's 1..N sequence (tag.instance is 16
# bits; epoch e's change instance is 0xFF00 | (e+1 mod 256))
def view_instance(epoch: int) -> int:
    return 0xFF00 | ((epoch + 1) & 0xFF)


@dataclasses.dataclass(frozen=True)
class View:
    """A versioned membership: ``epoch`` counts applied ops, ``group``
    maps contiguous pids 0..n-1 to addresses (Replicas.scala:20-131)."""

    epoch: int
    group: Group

    @property
    def n(self) -> int:
        return self.group.size

    @property
    def epoch_byte(self) -> int:
        """The 8-bit stamp every NORMAL frame carries (Tag.call_stack).
        Mod-256 wrap is resolved by modular distance — see
        ``epoch_behind``."""
        return self.epoch & 0xFF

    def peers(self) -> Dict[int, Tuple[str, int]]:
        """The pid -> (host, port) table the transport and HostRunner
        consume."""
        return {r.id: (r.address, r.port) for r in self.group.replicas}

    def apply(self, kind: int, arg: int, add_host: str = "127.0.0.1"
              ) -> "View":
        """The next view under one decided op: ADD appends at the next id,
        REMOVE compacts ids to 0..n-2 (Replicas.scala:136-142)."""
        if kind == ADD:
            g = self.group.add(add_host, arg)
        elif kind == REMOVE:
            g = self.group.remove(arg)
        else:
            raise ValueError(f"unknown membership op kind {kind}")
        return View(self.epoch + 1, g)

    # -- wire form (FLAG_VIEW payload) -----------------------------------
    # plain builtins only: the restricted wire unpickler
    # (transport.wire_loads) refuses everything class-shaped

    def wire(self) -> Tuple[int, Tuple[Tuple[str, int], ...]]:
        return (self.epoch,
                tuple((r.address, r.port) for r in self.group.replicas))

    @classmethod
    def from_wire(cls, payload: Any) -> Optional["View"]:
        """Parse a FLAG_VIEW payload; None on anything malformed (the
        socket is unauthenticated — garbage must never raise)."""
        try:
            epoch, addrs = payload
            epoch = int(epoch)
            if epoch < 0 or not 0 < len(addrs) <= 0xFFFF:
                return None
            reps = [Replica(i, str(h), int(p))
                    for i, (h, p) in enumerate(addrs)]
            return cls(epoch, Group(reps))
        except Exception:  # noqa: BLE001 — malformed payloads are dropped
            return None


def epoch_behind(theirs: int, mine: int) -> bool:
    """True when the 8-bit epoch stamp ``theirs`` is BEHIND ``mine`` under
    mod-256 wraparound (modular distance < 128 ⇒ behind; epochs advance a
    handful of times per deployment, so 128 of headroom is vast)."""
    return 0 < ((mine - theirs) & 0xFF) < 128


class ViewManager:
    """One replica's live view state + the machinery that moves it.

    Three jobs (the DynamicMembership.scala flow on a real wire):
      * ``propose(kind, arg)``: run consensus on the op over the current
        view (every member proposes the same scripted op, so by validity
        the decision IS the op — the uniform schedule of the chaos
        harness) and apply it;
      * ``apply_op``: the atomic switch — new Group (contiguous renames),
        ``transport.rewire`` (live peer-table swap, unrelated channels
        kept), epoch += 1.  A replica whose own address vanished flags
        ``removed`` and leaves the wire alone;
      * the epoch guard HostRunner calls per NORMAL frame
        (``check_epoch``): stale peers get a rate-limited FLAG_VIEW reply
        with the serialized view; a peer AHEAD of us flags ``stale`` so
        the runner exits the instance and the host loop re-enters under
        whatever view the FLAG_VIEW catch-up delivers (``adopt_wire``).
    """

    def __init__(self, my_id: int, view: View, transport,
                 add_host: str = "127.0.0.1", license=None,
                 license_model: Optional[str] = None,
                 unlicensed_ok: bool = False):
        if not view.group.contains(my_id):
            raise ValueError(f"my_id={my_id} not in view of n={view.n}")
        self.my_id: Optional[int] = my_id
        self.view = view
        self.transport = transport
        self.add_host = add_host
        self.removed = False
        self.stale = False       # a peer was observed AHEAD of our epoch
        self.history: List[Tuple[int, int, int]] = []  # (epoch, kind, arg)
        # proof-licensed reconfiguration (rv/license.py
        # ProofLicenseRegistry + the serving protocol's name): with a
        # ``license``, propose() consults the parameterized-proof
        # registry BEFORE running the membership consensus — a resize
        # the all-n proofs do not cover is REFUSED (no op proposed), or,
        # under ``unlicensed_ok``, proceeds with this replica flagged
        # DEGRADED.  Ops decided elsewhere and adopted can only be
        # flagged, never refused (the group already moved).  None = the
        # pre-license world, zero behavior change.
        self.license = license
        self.license_model = license_model
        self.unlicensed_ok = unlicensed_ok
        self.degraded = False
        self.refusals: List[Dict[str, Any]] = []
        self._replied: Dict[int, float] = {}  # FLAG_VIEW rate limiter
        # encoded current view, cached per epoch: reply_view used to
        # re-serialize the SAME view for every stale peer it answered
        # (the per-peer re-encode audit of runtime/host.py)
        self._wire_cache: Optional[Tuple[int, bytes]] = None
        # optional observer (renames: {old_pid: new_pid | None}, new_n;
        # None = that member was removed) called
        # after every SURVIVING view move — apply_op and adopt_wire —
        # so per-peer state keyed by pid (runtime/health.py PeerHealth)
        # remaps through membership changes instead of silently scoring
        # the wrong peers.  Exceptions are swallowed: an observer must
        # never wedge a view change.  ``on_change`` is the original
        # single-slot hook (kept: host_replica assigns it directly);
        # ``add_observer`` registers any number of additional watchers —
        # the fleet router's shard-map rebalance (runtime/fleet.py)
        # composes with PeerHealth.resize on the same view move.
        self.on_change = None
        self._observers: List[Any] = []

    def add_observer(self, cb) -> None:
        """Register an additional (renames, n) observer beside
        ``on_change`` — every registered callback fires on every
        surviving view move, each isolated from the others' failures."""
        self._observers.append(cb)

    def _notify_change(self, renames: Dict[int, int], n: int) -> None:
        cbs = ([self.on_change] if self.on_change is not None else []) \
            + list(self._observers)
        for cb in cbs:
            try:
                cb(renames, n)
            except Exception:  # noqa: BLE001 — an observer must not kill
                log.warning("view on_change observer failed",
                            exc_info=True)  # the move (or its siblings)

    @property
    def epoch(self) -> int:
        return self.view.epoch

    @property
    def epoch_byte(self) -> int:
        return self.view.epoch_byte

    # -- the consensus-on-op path ----------------------------------------

    def propose(self, algo, kind: int, arg: int, *, seed: int = 0,
                timeout_ms: int = 300, max_rounds: int = 48,
                adaptive=None, foreign=None, prefill=None,
                ) -> Optional[Tuple[int, int]]:
        """Run ONE consensus instance on ``encode(kind, arg)`` over the
        current view's wire and apply the decision.  All members of the
        view must call this at the same point of their instance sequence
        (the --view-change script of apps/host_replica.py).  Returns the
        decided (kind, arg), or None when the instance timed out
        undecided — the view is then unchanged and the caller may retry
        or rely on the FLAG_VIEW catch-up if peers did decide."""
        import numpy as np

        from round_tpu.runtime.host import HostRunner

        if self.removed:
            return None
        if not self._license_gate(kind, arg):
            return None
        inst = view_instance(self.epoch)
        runner = HostRunner(
            algo, self.my_id, self.view.peers(), self.transport,
            instance_id=inst, timeout_ms=timeout_ms,
            seed=seed ^ (0x51E << 8) ^ self.epoch, adaptive=adaptive,
            foreign=foreign, prefill=prefill, view=self,
        )
        res = runner.run({"initial_value": np.int32(encode(kind, arg))},
                         max_rounds=max_rounds)
        if res.stale_view:
            # a FLAG_VIEW catch-up already moved us past this epoch —
            # the op (ours or another) was applied by adopt_wire
            return None
        if not res.decided:
            return None
        kind_d, arg_d = decode(int(np.asarray(res.decision)))
        self.apply_op(kind_d, arg_d)
        return kind_d, arg_d

    def _license_gate(self, kind: int, arg: int) -> bool:
        """The proof gate of propose(): True = proceed.  A non-licensed
        resize is refused (obs event ``view_refused`` + counter), or —
        under the explicit escape hatch — proceeds with the replica
        flagged degraded (``view_degraded``)."""
        if self.license is None:
            return True
        new_n = self.view.apply(kind, arg, add_host=self.add_host).n
        lic = self.license.check(self.license_model, new_n)
        if lic.ok:
            return True
        if not self.unlicensed_ok:
            _C_REFUSED.inc()
            self.refusals.append({
                "epoch": self.epoch, "kind": kind, "arg": arg,
                "n": new_n, "license": lic.to_json()})
            if TRACE.enabled:
                TRACE.emit("view_refused", node=self.my_id,
                           epoch=self.epoch,
                           op=("add" if kind == ADD else "remove"),
                           arg=arg, n=new_n, status=lic.status,
                           reason=lic.reason)
            log.warning("node %s: membership op REFUSED (n=%d %s): %s",
                        self.my_id, new_n, lic.status, lic.reason)
            return False
        self._flag_degraded(new_n, lic)
        return True

    def _flag_degraded(self, new_n: int, lic) -> None:
        self.degraded = True
        _C_DEGRADED.inc()
        if TRACE.enabled:
            TRACE.emit("view_degraded", node=self.my_id,
                       epoch=self.epoch, n=new_n, status=lic.status,
                       reason=lic.reason)
        log.warning("node %s: view move to n=%d is UNLICENSED (%s) — "
                    "proceeding degraded: %s", self.my_id, new_n,
                    lic.status, lic.reason)

    def _license_observe(self, new_n: int) -> None:
        """The adopt/apply-path check: an op already decided can only be
        FLAGGED (cache-only — never stall a committed move on a cold
        solver run)."""
        if self.license is None or self.degraded:
            return
        lic = self.license.check(self.license_model, new_n, solve=False)
        if not lic.ok:
            self._flag_degraded(new_n, lic)

    def apply_op(self, kind: int, arg: int) -> None:
        """Apply one DECIDED op atomically: group + ids + wire + epoch."""
        old = self.view
        new = old.apply(kind, arg, add_host=self.add_host)
        renaming = new.group.renaming_from(old.group)
        new_id = renaming.get(self.my_id)
        self.history.append((new.epoch, kind, arg))
        _C_CHANGES.inc()
        if TRACE.enabled:
            TRACE.emit("view_change", node=self.my_id, epoch=new.epoch,
                       op=("add" if kind == ADD else "remove"), arg=arg,
                       n=new.n, new_id=new_id)
        if new_id is None:
            # we were voted out: QUIESCE the wire — sever every channel
            # and empty the peer table so neither a late send nor the
            # reconnect loop dials back in (a removed replica redialing
            # with its stale id is exactly the channel-hijack the
            # handshake's listen-port check rejects; don't even try).
            # The host loop then exits this replica cleanly.
            self.removed = True
            self.view = new
            self.transport.rewire({})
            log.info("node %s: removed from the group at epoch %d",
                     self.my_id, new.epoch)
            self.my_id = None
            return
        # FAREWELL before the sever: pids this op removed get one
        # FLAG_VIEW with the new view while their channels still exist —
        # a removed replica that missed the remove decision (it was the
        # drop victim) learns of its exile immediately instead of
        # depending on the slower fallback (its redial reaching the
        # member that inherited its id).  Best-effort: the frame can
        # drop; the fallback remains.
        wire_view = pickle.dumps(new.wire())
        for old_pid, mapped in renaming.items():
            if mapped is None and old_pid != self.my_id:
                self.transport.send(
                    old_pid, Tag(instance=0, flag=FLAG_VIEW,
                                 call_stack=new.epoch_byte), wire_view)
        self.transport.rewire(new.peers(), my_id=new_id)
        self.my_id = new_id
        self.view = new
        self._replied.clear()
        self._notify_change(dict(renaming), new.n)

    # -- the epoch guard (HostRunner per-frame hook) ---------------------

    def check_epoch(self, sender: int, tag: Tag) -> bool:
        """True when the NORMAL frame's epoch stamp matches our view.  On
        mismatch the frame must be dropped: a stale peer's traffic is
        answered with a FLAG_VIEW catch-up; a peer AHEAD of us flags
        ``stale`` (the runner exits, the catch-up reply to OUR next stamped
        send completes the adoption)."""
        theirs = tag.call_stack & 0xFF
        mine = self.epoch_byte
        if theirs == mine:
            return True
        if epoch_behind(theirs, mine):
            _C_STALE.inc()
            self.reply_view(sender)
        else:
            if not self.stale and TRACE.enabled:
                TRACE.emit("view_stale", node=self.my_id,
                           epoch=self.epoch, observed=theirs)
            self.stale = True
        return False

    def reply_view(self, sender: int) -> bool:
        """Send the serialized current view to a stale peer, rate-limited
        per sender (the reply can drop; the peer's next stamped frame
        re-arms it — the trySendDecision discipline)."""
        now = _time.monotonic()
        if now - self._replied.get(sender, -1.0) <= 0.25:
            return False
        self._replied[sender] = now
        if self._wire_cache is None or self._wire_cache[0] != self.epoch:
            self._wire_cache = (self.epoch, pickle.dumps(self.view.wire()))
        self.transport.send(
            sender, Tag(instance=0, flag=FLAG_VIEW,
                        call_stack=self.epoch_byte),
            self._wire_cache[1],
        )
        _C_REPLIES.inc()
        if TRACE.enabled:
            TRACE.emit("view_reply", node=self.my_id, dst=sender,
                       epoch=self.epoch)
        return True

    def adopt_wire(self, payload: Any) -> bool:
        """Adopt a FLAG_VIEW catch-up: jump to the carried view (strictly
        newer epochs only), find our own pid by our address, rewire.  The
        membership consensus we missed is NOT re-run — the view is the
        state, exactly like a decision reply replaces re-running the
        instance.  Returns True when the view moved."""
        v = View.from_wire(payload)
        if v is None or v.epoch <= self.view.epoch or self.removed:
            return False
        my_addr = (None if self.my_id is None
                   else self.view.group.get(self.my_id))
        new_id = (None if my_addr is None
                  else v.group.inet_to_id(my_addr.address, my_addr.port))
        _C_ADOPTS.inc()
        if TRACE.enabled:
            TRACE.emit("view_adopt", node=self.my_id, epoch=v.epoch,
                       n=v.n, new_id=new_id)
        if new_id is None:
            self.removed = True
            self.view = v
            self.transport.rewire({})  # quiesce (see apply_op)
            self.my_id = None
            log.info("view catch-up: removed from the group at epoch %d",
                     v.epoch)
            return True
        # an adopted op is already committed group-wide: the license
        # check can only FLAG here (cache-only, never a solver stall)
        self._license_observe(v.n)
        old_view = self.view
        self.transport.rewire(v.peers(), my_id=new_id)
        self.my_id = new_id
        self.view = v
        self.stale = False
        self._replied.clear()
        # identity is ADDRESS here (the consensus we missed renamed pids):
        # remap per-peer state by looking each old member up in the new
        # group, exactly how our own new_id was found
        renames = {}
        for rep in old_view.group.replicas:
            # None = the member left the group: the observer must DROP
            # its state, not let an identity fallback leak it onto
            # whichever survivor inherits the pid
            renames[rep.id] = v.group.inet_to_id(rep.address, rep.port)
        self._notify_change(renames, v.n)
        return True


def parse_view_schedule(spec: str) -> Dict[int, Tuple[int, int]]:
    """Parse the --view-change script: ``INST:add=PORT`` / ``INST:remove=PID``
    entries, comma-separated — after data instance INST completes, the
    replica proposes that op (all replicas must carry the same script, the
    deployment-config analogue of the reference's scripted
    DynamicMembership driver).  Example: ``2:add=7005,4:remove=1``."""
    out: Dict[int, Tuple[int, int]] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        try:
            inst_s, op_s = part.split(":", 1)
            op_name, arg_s = op_s.split("=", 1)
            kind = {"add": ADD, "remove": REMOVE}[op_name.strip()]
            inst, arg = int(inst_s), int(arg_s)
        except (ValueError, KeyError):
            raise ValueError(
                f"bad --view-change entry {part!r}; want INST:add=PORT "
                f"or INST:remove=PID") from None
        if inst in out:
            raise ValueError(f"duplicate view change at instance {inst}")
        out[inst] = (kind, arg)
    return out
