"""Runtime services around the engine: instances, membership, SMR, recovery.

The reference's runtime (psync.runtime) multiplexes many concurrent protocol
instances over sockets — InstanceDispatcher routes packets by the 16-bit
instance id in the Tag, Algorithm pools InstanceHandlers, and the batching
example builds state-machine replication with decision logs and recovery on
top.  Here the same services are tensor-shaped:

  - many concurrent instances  = a batch axis (instances.py InstancePool)
  - the dispatcher             = host-side slot table keyed by instance id
  - membership (Group/Directory) = host-side replica table + per-instance
    group size (membership.py), updated between instances like the
    reference's consensus-on-membership example
  - SMR / batching             = ReplicatedStateMachine over a consensus
    algorithm with a device decision log + replay/recovery (smr.py)
  - live reconfiguration       = versioned View + ViewManager: membership
    ops decided by consensus over the real wire and applied to the
    RUNNING peer table with epoch-stamped traffic (view.py)
  - the wire codec             = typed binary payload serialization with
    a restricted-pickle fallback (codec.py; the Kryo registered-class
    role) feeding the coalesced zero-copy hot path of transport.py
"""

from round_tpu.runtime.checkpoint import restore as restore_checkpoint
from round_tpu.runtime.checkpoint import save as save_checkpoint
from round_tpu.runtime.config import Options, parse_args
from round_tpu.runtime.decisions import DecisionLog
from round_tpu.runtime.instances import InstancePool, InstanceResult
from round_tpu.runtime.membership import Directory, Group, Replica
from round_tpu.runtime.smr import ReplicatedStateMachine
from round_tpu.runtime.stats import Stats, stats
from round_tpu.runtime.view import View, ViewManager

__all__ = [
    "InstancePool",
    "InstanceResult",
    "Directory",
    "Group",
    "Replica",
    "View",
    "ViewManager",
    "ReplicatedStateMachine",
    "Options",
    "parse_args",
    "DecisionLog",
    "Stats",
    "stats",
    "save_checkpoint",
    "restore_checkpoint",
]
