"""Layered configuration: defaults < config file < CLI < programmatic.

Reference parity: psync.runtime.RuntimeOptions / RTOptions
(runtime/RuntimeOptions.scala:22-116) and the XML config parser
(runtime/Config.scala:6-27).  The reference declares options once and feeds
the XML file's <parameters> back through the same CLI parser
(RTOptions.processConFile, RuntimeOptions.scala:94-102); this keeps that
architecture: one dataclass of declared options, one parser, and file
contents re-applied through it.

Both the reference's XML shape (<config><peers><replica .../></peers>
<parameters><param name=... value=.../></parameters></config>) and plain
JSON are accepted.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import xml.etree.ElementTree as ET
from typing import Any, Dict, List, Optional, Sequence, Tuple

from round_tpu.runtime.membership import Group, Replica


@dataclasses.dataclass
class Options:
    """All engine/runtime knobs (AlgorithmOptions + RuntimeOptions merged —
    the reference splits them at RuntimeOptions.scala:22-67)."""

    # identity & group (reference: -id, peers list)
    id: int = 0
    peers: List[Tuple[str, int]] = dataclasses.field(default_factory=list)

    # algorithm options (AlgorithmOptions, RuntimeOptions.scala:22-37)
    timeout_ms: int = 10            # default round timeout (:33)
    max_phases: int = 64            # scan bound on phases
    nbr_byzantine: int = 0          # f for byzantine variants (:49)
    # NB the catch-up send policy (RuntimeOptions.scala:31-32,
    # sendWhenCatchingUp/delayFirstSend) lives on the HOST runner
    # (runtime/host.py HostRunner kwargs + apps/host_replica.py CLI
    # flags), not here: the lockstep engine path this record serves has
    # no per-replica send loop to apply it to

    # engine scale (the TPU-native axes; replaces workers/dispatch knobs)
    n: int = 4                      # group size
    scenarios: int = 1              # HO-scenario batch
    chunk: int = 0                  # scenario micro-batch (0 = all at once)
    seed: int = 0

    # multi-chip (replaces NIO/EPOLL + group options, Runtime.scala:35-41)
    scenario_shards: int = 1
    proc_shards: int = 1

    # observability
    stats: bool = False             # --stat (utils/Options.scala:16-25)
    log_file: str = ""              # decision TSV log (PerfTest --log)

    # benchmark driver knobs (PerfTest2 -a / -rt)
    algorithm: str = "otr"
    rate: int = 16                  # in-flight instances

    def group(self) -> Group:
        if self.peers:
            return Group([Replica(i, h, p) for i, (h, p) in enumerate(self.peers)])
        return Group([Replica(i) for i in range(self.n)])


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(add_help=False)
    p.add_argument("--conf", type=str, default=None,
                   help="config file (XML like the reference, or JSON)")
    p.add_argument("-id", "--id", dest="id", type=int)
    p.add_argument("-to", "--timeout", dest="timeout_ms", type=int)
    p.add_argument("--byzantine", dest="nbr_byzantine", type=int)
    p.add_argument("-n", dest="n", type=int)
    p.add_argument("--scenarios", type=int)
    p.add_argument("--chunk", type=int)
    p.add_argument("--seed", type=int)
    p.add_argument("--max-phases", dest="max_phases", type=int)
    p.add_argument("--scenario-shards", dest="scenario_shards", type=int)
    p.add_argument("--proc-shards", dest="proc_shards", type=int)
    p.add_argument("--stat", dest="stats", action="store_const", const=True)
    p.add_argument("--log", dest="log_file", type=str)
    p.add_argument("-a", "--algorithm", dest="algorithm", type=str)
    p.add_argument("-rt", "--rate", dest="rate", type=int)
    return p


def _apply(opts: Options, ns: argparse.Namespace) -> None:
    for f in dataclasses.fields(Options):
        v = getattr(ns, f.name, None)
        if v is not None:
            setattr(opts, f.name, v)


def parse_config_file(path: str) -> Tuple[List[Tuple[str, int]], List[str]]:
    """Returns (peers, extra CLI args).  XML: the reference's shape
    (Config.scala:6-27) — <replica address= port=/> entries plus
    <param name= value=/> re-fed as '--name value' args.  JSON: an object
    whose 'peers' is [[host, port], ...] and other keys are option names."""
    if path.endswith(".json"):
        with open(path) as fh:
            data = json.load(fh)
        peers = [tuple(p) for p in data.pop("peers", [])]
        args: List[str] = []
        for k, v in data.items():
            flag = f"--{k.replace('_', '-')}"
            if k in ("stats", "stat"):
                # the parser knows --stat only as a no-value flag; the
                # '--stats True' form would be silently dropped
                if v:
                    args.append("--stat")
            elif isinstance(v, bool):
                if v:
                    args.append(flag)
            else:
                args.extend([flag, str(v)])
        return peers, args
    root = ET.parse(path).getroot()
    peers = []
    for rep in root.iter("replica"):
        peers.append((rep.get("address", ""), int(rep.get("port", "0"))))
    args = []
    for param in root.iter("param"):
        name = param.get("name")
        value = param.get("value", "")
        args.append(f"--{name}")
        if value:
            args.append(value)
    return peers, args


def parse_args(argv: Sequence[str], base: Optional[Options] = None) -> Options:
    """CLI entry (RTOptions, RuntimeOptions.scala:69-116): --conf file
    contents are applied first, then the command line overrides them."""
    opts = base or Options()
    parser = _parser()
    ns, _ = parser.parse_known_args(list(argv))
    if ns.conf:
        peers, file_args = parse_config_file(ns.conf)
        if peers:
            opts.peers = peers
            opts.n = len(peers)
        fns, unused = parser.parse_known_args(file_args)
        if unused:
            import warnings

            warnings.warn(
                f"config file {ns.conf}: unrecognized options ignored: {unused}"
            )
        _apply(opts, fns)
    _apply(opts, ns)
    if opts.peers and opts.n != len(opts.peers):
        opts.n = len(opts.peers)
    return opts
