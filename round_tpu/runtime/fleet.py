"""Sharded serving fabric: a consistent-hash front door over a fleet of
lane drivers (docs/SERVING.md).

One LaneDriver process saturates around L=64 lanes on a small box
(PERF_MODEL.md "lane amortization"); serving more concurrent instances
than one driver can hold means a FLEET.  "Reducing asynchrony to
synchronized rounds" licenses the batching at fleet grain exactly as it
did at lane grain: a round wave per shard is one batched exchange, so
the front door coalesces client traffic ACROSS shards per wave the same
way `runtime/lanes.py` coalesces sends across lanes.

Three pieces:

  * ``ShardMap`` — a consistent-hash ring over STABLE shard names
    (vnode-replicated, blake2b-keyed so placement is identical across
    processes and runs).  Stable names — not pids — because live
    membership changes RENAME pids (runtime/view.py REMOVE compacts
    ids); a ring keyed by pid would reshuffle every key on every
    rename, which defeats the point of consistent hashing.

  * ``DriverServer`` — ONE shard: an n-replica consensus group served
    in-process (one thread per replica, the apps/host_perftest measure()
    shape), every replica's LaneDriver in client-serving mode
    (``LaneDriver.serve``: FLAG_PROPOSE intake, FLAG_DECISION streams,
    accounted FLAG_NACK under admission shedding).  The fleet CLI
    (apps/fleet.py) runs one DriverServer per OS process.

  * ``FleetRouter`` — the client tier, promoted out of the ad-hoc
    HostBus/host_replica entry points into a real protocol:

      propose   — route the instance to its ring owner and ship the
                  client value to EVERY replica of that shard (uniform
                  proposals: by validity the decision is the value, so
                  any quorum of the shard decides identically) over the
                  FLAG_BATCH wire, coalesced per wave;
      subscribe — ask a shard to stream every decision it completes;
      decisions — FLAG_DECISION frames stream back as instances decide
                  (first replica to answer wins; duplicates counted);
      NACK-retry — a FLAG_NACK reply (the shard is shedding,
                  docs/HOST_FAULT_MODEL.md) schedules a capped-backoff
                  re-propose; ``give_up`` retries exhausts into a
                  ``FleetGiveUp`` entry instead of silent loss.  The
                  same re-propose is the DECISION catch-up: PROPOSE is
                  idempotent, and a completed instance answers it with
                  the decision the client may have missed — so one
                  timer covers lost proposals, lost decisions, and
                  shed frames.

Rebalance (the migration story): shard membership changes arrive via a
``ViewManager`` observer (``FleetRouter.view_observer``, the same
on_change surface PeerHealth.resize rides) or directly through
``add_shard``/``remove_shard``.  The ring moves only the departed
shard's arc; in-flight instances are STICKY to the shard that already
holds them (their decision stream is live) unless that shard LEFT — a
removed shard's unresolved instances are re-proposed to their new
owners, and the idempotent-PROPOSE catch-up path makes that migration
exact: a new owner that never saw the instance runs it (uniform value
⇒ same decision), one that did answers from its decision bank.  No
decision is lost either way (pinned by tests/test_fleet.py against an
unrebalanced control, byte-identical logs).
"""

from __future__ import annotations

import bisect
import dataclasses
import threading
import time as _time
from hashlib import blake2b
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from round_tpu.obs.metrics import METRICS
from round_tpu.obs.trace import TRACE
from round_tpu.runtime import codec
from round_tpu.runtime.log import get_logger
from round_tpu.runtime.oob import (
    FLAG_DECISION, FLAG_NACK, FLAG_PROPOSE, FLAG_READ, FLAG_SUBSCRIBE,
    FLAG_TOO_LATE, FLAG_TXN, FLEET_MAX_INSTANCE, FLEET_MIN_INSTANCE, Tag,
)

log = get_logger("fleet")

# fleet.* vocabulary (docs/OBSERVABILITY.md)
_C_PROPOSALS = METRICS.counter("fleet.proposals")
_C_DECISIONS = METRICS.counter("fleet.decisions")
_C_UNDECIDED = METRICS.counter("fleet.undecided")
_C_DUPS = METRICS.counter("fleet.dup_decisions")
_C_NACKS = METRICS.counter("fleet.nacks")
_C_RETRIES = METRICS.counter("fleet.nack_retries")
_C_REPROPOSE = METRICS.counter("fleet.reproposals")
_C_GIVE_UPS = METRICS.counter("fleet.give_ups")
_C_REBALANCES = METRICS.counter("fleet.rebalances")
_C_MIGRATIONS = METRICS.counter("fleet.migrations")
_G_INFLIGHT = METRICS.gauge("fleet.inflight")
_G_SHARDS = METRICS.gauge("fleet.shards")
_H_DECIDE_MS = METRICS.histogram(
    "fleet.decide_ms", (1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000,
                        5000), unit="ms")

# the data-plane instance id space (shared with the shard boundary's
# own enforcement in LaneDriver._client_frame — see runtime/oob.py)
MIN_INSTANCE = FLEET_MIN_INSTANCE
MAX_FLEET_INSTANCE = FLEET_MAX_INSTANCE


class FleetGiveUp(RuntimeError):
    """The router exhausted its capped-backoff retries for an instance
    (every attempt was NACKed or went unanswered) — the client-visible
    overload error, never silent loss."""


def _h64(data: bytes) -> int:
    return int.from_bytes(blake2b(data, digest_size=8).digest(), "big")


class ShardMap:
    """Consistent-hash ring over stable shard names.

    ``vnodes`` replicas per shard smooth the arc sizes (64 keeps the
    max/min key-share spread under ~2x at 4 shards; the balance test
    pins it).  Hashing is blake2b — deterministic across processes, so
    every router and every test computes the same placement
    (PYTHONHASHSEED never participates)."""

    def __init__(self, shards=(), vnodes: int = 64):
        self.vnodes = vnodes
        self._shards: List[str] = []
        self._ring: List[Tuple[int, str]] = []
        for s in shards:
            self.add(s)

    @property
    def shards(self) -> List[str]:
        return sorted(self._shards)

    def __len__(self) -> int:
        return len(self._shards)

    def add(self, shard: str) -> None:
        if shard in self._shards:
            raise ValueError(f"shard {shard!r} already in the ring")
        self._shards.append(shard)
        for v in range(self.vnodes):
            self._ring.append((_h64(f"{shard}#{v}".encode()), shard))
        self._ring.sort()

    def remove(self, shard: str) -> None:
        self._shards.remove(shard)
        self._ring = [(h, s) for h, s in self._ring if s != shard]

    def owner(self, instance_id: int) -> str:
        """The shard owning this instance id: first vnode clockwise of
        the key's hash (wrapping)."""
        if not self._ring:
            raise ValueError("empty shard ring")
        h = _h64(int(instance_id).to_bytes(8, "big"))
        i = bisect.bisect_right(self._ring, (h, "￿"))
        if i == len(self._ring):
            i = 0
        return self._ring[i][1]

    def owner_key(self, key: bytes) -> str:
        """The shard owning this BYTE key (the kv data plane routes by
        key, not instance id, so every write of a key lands in one
        shard's decision stream — docs/KV.md)."""
        if not self._ring:
            raise ValueError("empty shard ring")
        h = _h64(bytes(key))
        i = bisect.bisect_right(self._ring, (h, "￿"))
        if i == len(self._ring):
            i = 0
        return self._ring[i][1]


class TwoLevelRing:
    """Region → shard two-level consistent-hash ring (docs/SERVING.md
    "planet-scale control plane").

    The outer ring consistent-hashes over REGION names (a supervisor-
    owned shard group each); every region owns an inner ShardMap over
    its shards.  Routing is two cheap hash hops — outer pick, inner
    pick — and rebalance motion is LOCAL BY CONSTRUCTION: adding or
    removing a shard changes one region's inner ring only, so keys in
    every other region cannot move (the reshuffle a flat ring pays on
    every membership change is confined to one region's arc).  With a
    single region the outer hop is a constant and the inner ShardMap
    hashes exactly like the flat ring did — placement is byte-identical
    to the pre-region fleet, which is what keeps every existing test
    and banked artifact valid.

    Same interface as ShardMap (``shards``/``owner``/``owner_key``/
    ``add``/``remove``/``__len__``) plus the region surface
    (``regions``/``region_of``); ``add`` grows a ``region=`` keyword
    that defaults to one flat region."""

    def __init__(self, vnodes: int = 64, region_vnodes: int = 64):
        self.vnodes = vnodes
        self.region_vnodes = region_vnodes
        self._outer: List[Tuple[int, str]] = []
        self._inner: Dict[str, ShardMap] = {}
        self._region_of: Dict[str, str] = {}

    @property
    def shards(self) -> List[str]:
        return sorted(self._region_of)

    @property
    def regions(self) -> List[str]:
        return sorted(self._inner)

    def region_of(self, shard: str) -> str:
        return self._region_of[shard]

    def __len__(self) -> int:
        return len(self._region_of)

    def add(self, shard: str, region: str = "r0") -> None:
        if shard in self._region_of:
            raise ValueError(f"shard {shard!r} already in the ring")
        if region not in self._inner:
            self._inner[region] = ShardMap(vnodes=self.vnodes)
            for v in range(self.region_vnodes):
                self._outer.append(
                    (_h64(f"region:{region}#{v}".encode()), region))
            self._outer.sort()
        self._inner[region].add(shard)
        self._region_of[shard] = region

    def remove(self, shard: str) -> None:
        region = self._region_of.pop(shard)
        self._inner[region].remove(shard)
        if not len(self._inner[region]):
            # an empty region must leave the outer ring too, or its arc
            # would route keys into a ring with no owner
            del self._inner[region]
            self._outer = [(h, r) for h, r in self._outer if r != region]

    def _region_for(self, h: int) -> str:
        i = bisect.bisect_right(self._outer, (h, "￿"))
        if i == len(self._outer):
            i = 0
        return self._outer[i][1]

    def owner(self, instance_id: int) -> str:
        """Two hash hops: the key's outer arc names the region, the
        region's inner ring names the shard."""
        if not self._outer:
            raise ValueError("empty shard ring")
        h = _h64(int(instance_id).to_bytes(8, "big"))
        return self._inner[self._region_for(h)].owner(instance_id)

    def owner_key(self, key: bytes) -> str:
        if not self._outer:
            raise ValueError("empty shard ring")
        h = _h64(bytes(key))
        return self._inner[self._region_for(h)].owner_key(key)


@dataclasses.dataclass
class _InFlight:
    """One proposed-but-unresolved instance in the router."""

    inst: int
    payload: bytes              # encoded client value (re-sent verbatim)
    shard: str
    t_first: float              # latency is measured from the FIRST send
    t_last: float               # last (re)propose — paces the catch-up
    retries: int = 0            # NACK-scheduled re-proposes so far
    reproposals: int = 0        # timer-scheduled catch-up re-sends
    next_retry: float = 0.0     # 0 = not in backoff
    txn: bool = False           # ship under FLAG_TXN (kv transactions)
    tenant: int = 0             # rides Tag.call_stack on the client verbs
    # DISTINCT (shard, replica) pairs that answered FLAG_TOO_LATE: the
    # instance resolves undecided only when every replica of its
    # CURRENT shard said so — a single undecided replica re-answering
    # successive re-proposes must not outvote a sibling that decides
    # (and a migration implicitly resets the tally: old-shard entries
    # no longer match)
    too_late_from: set = dataclasses.field(default_factory=set)


class FleetRouter:
    """The fleet front door: one client-side transport link per shard,
    a consistent-hash ring over the shard names, and the
    propose/subscribe/NACK-retry state machine (module docstring).

    Single-threaded by design — the caller (loadgen, apps/fleet.py)
    drives ``pump()`` as its event loop, exactly as the lane driver's
    tick loop drives its transport.  ``transport_factory(client_id)``
    exists for tests; the default builds a real HostTransport per
    shard."""

    def __init__(self, *, proto: str = "tcp",
                 nack_backoff_ms: float = 25.0,
                 nack_backoff_cap_ms: float = 1000.0,
                 give_up: int = 12,
                 repropose_ms: float = 2000.0,
                 repropose_cap_ms: float = 30_000.0,
                 max_reproposals: int = 30,
                 transport_factory: Optional[Callable] = None):
        self.proto = proto
        self.nack_backoff_ms = nack_backoff_ms
        self.nack_backoff_cap_ms = nack_backoff_cap_ms
        self.give_up = give_up
        self.repropose_ms = repropose_ms
        self.repropose_cap_ms = repropose_cap_ms
        self.max_reproposals = max_reproposals
        self._transport_factory = transport_factory
        # region → shard two-level ring: one flat region unless
        # ``add_shard(..., region=)`` says otherwise (placement is then
        # byte-identical to the old flat ShardMap)
        self.ring = TwoLevelRing()
        self._links: Dict[str, Any] = {}       # shard -> transport
        self._link_n: Dict[str, int] = {}      # shard -> group size
        self._inflight: Dict[int, _InFlight] = {}
        self.results: Dict[int, Optional[int]] = {}
        self.errors: Dict[int, str] = {}
        self.latency_ms: Dict[int, float] = {}
        self.decide_t: Dict[int, float] = {}
        self.proposals = 0       # lifetime count (the supervisor's
        self.nack_retries = 0    # offered-rate signal reads its deltas)
        self.give_ups = 0
        self.dup_decisions = 0
        self.migrations = 0
        self.reproposals = 0
        # per-tenant attribution (docs/SERVING.md "per-tenant
        # admission"): which tenant proposed each instance, and the
        # NACK/give-up tallies the isolation pin + loadgen report read
        self.tenant_of: Dict[int, int] = {}
        self.tenant_nacks: Dict[int, int] = {}
        self.tenant_give_ups: Dict[int, int] = {}
        # per-shard health counters (docs/SERVING.md "shard rv status"):
        # an rv-halted shard drains as a TOO_LATE burst + undecided
        # resolutions, which is how the router — which never sees the
        # shard's process — observes runtime-verification trouble
        self.shard_health: Dict[str, Dict[str, int]] = {}
        # the kv read verb (round_tpu/kv): FLAG_READ frames and the
        # NACKs of shed reads route to whoever registered here (the
        # KVClient); the router stays kv-agnostic otherwise
        self.on_read_reply: Optional[Callable] = None
        self.on_read_nack: Optional[Callable] = None

    # -- shard membership --------------------------------------------------

    def _make_link(self, replicas: List[Tuple[str, int]]):
        n = len(replicas)
        if self._transport_factory is not None:
            tr = self._transport_factory(n)
        else:
            from round_tpu.runtime.transport import HostTransport

            tr = HostTransport(n, 0, proto=self.proto)
        for j, (host, port) in enumerate(replicas):
            tr.add_peer(j, host, port)
        return tr

    def add_shard(self, name: str, replicas: List[Tuple[str, int]],
                  region: str = "r0") -> None:
        """Join one shard (a DriverServer's replica address list) under a
        STABLE name and claim its arc of ``region``'s inner ring.
        In-flight instances stay with their current shard (their
        decision stream is live) — only NEW proposals land on the new
        arcs, and only keys inside ``region`` can move at all (the
        two-level ring's locality guarantee)."""
        self.ring.add(name, region=region)
        self._links[name] = self._make_link(replicas)
        self._link_n[name] = len(replicas)
        _G_SHARDS.set(len(self.ring))
        _C_REBALANCES.inc()
        if TRACE.enabled:
            TRACE.emit("fleet_rebalance", node=None, op="add", shard=name,
                       region=region, shards=len(self.ring))

    def remove_shard(self, name: str) -> int:
        """Drop one shard from the ring and MIGRATE its unresolved
        instances: each is re-proposed to its new ring owner — the
        idempotent-PROPOSE catch-up makes the move exact (a new owner
        that already served the instance answers from its decision
        bank; one that never saw it runs it).  Returns the number of
        migrated instances."""
        self.ring.remove(name)
        link = self._links.pop(name, None)
        self._link_n.pop(name, None)
        if link is not None:
            link.close()
        _G_SHARDS.set(len(self.ring))
        _C_REBALANCES.inc()
        moved = 0
        for f in list(self._inflight.values()):
            if f.shard != name:
                continue
            if not len(self.ring):
                # the LAST shard left: nowhere to migrate — resolve the
                # instance as an explicit give-up (client-visible),
                # never a half-torn router or silent loss
                self._give_up(f, "last shard removed from the ring")
                continue
            f.shard = self.ring.owner(f.inst)
            f.next_retry = 0.0
            self._send_propose(f)
            moved += 1
        if moved:
            self.migrations += moved
            _C_MIGRATIONS.inc(moved)
        if TRACE.enabled:
            TRACE.emit("fleet_rebalance", node=None, op="remove",
                       shard=name, shards=len(self.ring), migrated=moved)
        self._flush()
        return moved

    def view_observer(self, names_by_pid: Dict[int, str]):
        """Adapt this router to a ViewManager ``add_observer`` slot: the
        fleet's own membership runs through the SAME consensus-decided
        view moves as everything else (runtime/view.py).  ``names_by_pid``
        maps the view's member pids to stable shard names; a member that
        maps to None in the view's renames LEFT the fleet — its shard is
        removed and its in-flight instances migrate.  JOINS are NOT
        inferred here: a renames dict names old pids only, so a freshly
        ADDed member carries no name/address the observer could resolve
        — bringing a new shard up is an operator action (deploy the
        DriverServer, then ``add_shard(name, addrs)``), and only then
        does the ring hand it keys."""
        def on_change(renames: Dict[int, Optional[int]], n: int) -> None:
            next_names: Dict[int, str] = {}
            for old_pid, new_pid in renames.items():
                name = names_by_pid.get(old_pid)
                if name is None:
                    continue
                if new_pid is None:
                    if name in self._links:
                        self.remove_shard(name)
                else:
                    next_names[new_pid] = name
            names_by_pid.clear()
            names_by_pid.update(next_names)

        return on_change

    # -- the client protocol ----------------------------------------------

    def _encode_value(self, value) -> bytes:
        arr = np.asarray(value)
        if arr.ndim == 0 and arr.dtype.kind in "iu":
            arr = arr.astype(np.int32)
        return codec.encode(arr)

    def propose(self, instance_id: int, value, *,
                shard: Optional[str] = None, txn: bool = False,
                tenant: int = 0) -> None:
        """Route one instance to its ring owner and ship the proposal to
        every replica of that shard (coalesced; ``pump``/``flush`` ships
        the wave).  ``value`` is the client's initial value — a scalar
        for the int-domain protocols, a uint8[B] vector for the byte-
        payload workload.  ``shard`` overrides the ring placement (the
        kv data plane routes by KEY via ``ring.owner_key``, so every
        write of a key shares one decision stream); ``txn`` ships the
        proposal under FLAG_TXN — same state machine, but the shard
        validates the payload as a kv transaction record; ``tenant``
        (0-255) namespaces the instance under per-tenant weighted-fair
        admission — it rides the otherwise-free Tag.call_stack byte on
        every (re)propose, zero wire-format change."""
        inst = int(instance_id)
        if not MIN_INSTANCE <= inst <= MAX_FLEET_INSTANCE:
            raise ValueError(
                f"instance id {inst} outside the serveable range "
                f"[{MIN_INSTANCE}, {MAX_FLEET_INSTANCE}]")
        if not 0 <= int(tenant) <= 0xFF:
            raise ValueError(f"tenant id {tenant} outside [0, 255]")
        if inst in self._inflight or inst in self.results:
            raise ValueError(f"instance {inst} already proposed")
        if shard is not None and shard not in self._links:
            raise ValueError(f"unknown shard {shard!r}")
        now = _time.monotonic()
        f = _InFlight(inst=inst, payload=self._encode_value(value),
                      shard=shard if shard is not None
                      else self.ring.owner(inst),
                      t_first=now, t_last=now, txn=txn,
                      tenant=int(tenant))
        self._inflight[inst] = f
        self.proposals += 1
        if f.tenant:
            self.tenant_of[inst] = f.tenant
        _C_PROPOSALS.inc()
        _G_INFLIGHT.set(len(self._inflight))
        self._send_propose(f)
        if TRACE.enabled:
            TRACE.emit("fleet_propose", node=None, inst=inst,
                       shard=f.shard)

    def _send_propose(self, f: _InFlight) -> None:
        link = self._links.get(f.shard)
        if link is None:
            return  # shard gone mid-flight; rebalance re-routes it
        tag = Tag(instance=f.inst & 0xFFFF,
                  flag=FLAG_TXN if f.txn else FLAG_PROPOSE,
                  call_stack=f.tenant)
        sendb = getattr(link, "send_buffered", None)
        for j in range(self._link_n[f.shard]):
            if sendb is not None:
                sendb(j, tag, f.payload)
            else:
                link.send(j, tag, f.payload)
        f.t_last = _time.monotonic()

    def shard_n(self, shard: str) -> int:
        """Replica count of one shard (the kv client's majority rule)."""
        return self._link_n[shard]

    def send_read(self, shard: str, replica: int, rid: int,
                  payload: bytes, tenant: int = 0) -> bool:
        """Ship one FLAG_READ frame to a single replica of ``shard``
        (round_tpu/kv three-grade reads) and flush immediately — read
        latency is the product here, so reads never wait for the next
        proposal wave's coalesce.  ``tenant`` rides Tag.call_stack so
        linearizable reads meter against the tenant's share too."""
        from round_tpu.kv.reads import read_tag

        link = self._links.get(shard)
        if link is None:
            return False
        tag = dataclasses.replace(read_tag(rid),
                                  call_stack=int(tenant) & 0xFF)
        sendb = getattr(link, "send_buffered", None)
        if sendb is not None:
            sendb(replica, tag, payload)
            fl = getattr(link, "flush", None)
            if fl is not None:
                fl()
        else:
            link.send(replica, tag, payload)
        return True

    def subscribe(self, shard: Optional[str] = None) -> None:
        """Ask ``shard`` (default: all) to stream EVERY decision it
        completes to this router, not just the ones it proposed."""
        for name in ([shard] if shard else list(self._links)):
            link = self._links[name]
            for j in range(self._link_n[name]):
                link.send(j, Tag(instance=0, flag=FLAG_SUBSCRIBE))

    def _flush(self) -> None:
        for link in self._links.values():
            fl = getattr(link, "flush", None)
            if fl is not None:
                fl()

    def _resolve(self, inst: int, value: Optional[int],
                 latency_anchor: Optional[float]) -> None:
        f = self._inflight.pop(inst, None)
        if f is None:
            return
        self.results[inst] = value
        now = _time.monotonic()
        self.decide_t[inst] = now
        if latency_anchor is not None:
            ms = (now - latency_anchor) * 1000.0
            self.latency_ms[inst] = ms
            _H_DECIDE_MS.observe(ms)
        _G_INFLIGHT.set(len(self._inflight))

    def _on_frame(self, shard: str, got) -> None:
        sender, tag, raw = got
        inst = tag.instance
        if tag.flag == FLAG_READ:
            # a kv read reply (the payload carries the full read id);
            # routed whole to the registered client, never resolved here
            if self.on_read_reply is not None:
                self.on_read_reply(shard, sender, tag, raw)
            return
        if tag.flag == FLAG_DECISION:
            if inst not in self._inflight:
                if inst in self.results:
                    self.dup_decisions += 1
                    _C_DUPS.inc()
                return
            try:
                value = codec.loads(bytes(raw))
            except Exception:  # noqa: BLE001 — a garbled decision frame
                return         # is dropped; the catch-up re-asks
            from round_tpu.runtime.host import decision_scalar

            f = self._inflight[inst]
            self._resolve(inst, decision_scalar(value), f.t_first)
            _C_DECISIONS.inc()
            if TRACE.enabled:
                TRACE.emit("fleet_decision", node=None, inst=inst,
                           shard=shard, src=sender)
            return
        if tag.flag == FLAG_NACK:
            f = self._inflight.get(inst)
            if f is None:
                # not a write of ours: a SHED READ NACKs back with the
                # 16-bit read id in Tag.instance (kv/reads.py read_tag) —
                # hand it to the kv client's retry machinery.  The id
                # spaces can collide in their low 16 bits; an in-flight
                # write always wins the ambiguity (reads self-heal on
                # their own retry timer regardless)
                if self.on_read_nack is not None:
                    self.on_read_nack(shard, inst)
                return
            _C_NACKS.inc()
            if f.tenant:
                self.tenant_nacks[f.tenant] = \
                    self.tenant_nacks.get(f.tenant, 0) + 1
            if TRACE.enabled:
                TRACE.emit("fleet_nack", node=None, inst=inst,
                           shard=shard, src=sender)
            self.shard_health.setdefault(
                shard, {"too_late": 0, "nacks": 0, "undecided": 0}
            )["nacks"] += 1
            if f.next_retry > 0:
                return  # already backing off; one NACK per window counts
            if f.retries >= self.give_up:
                self._give_up(f, "NACKed past the retry cap")
                return
            backoff = min(self.nack_backoff_ms * (2.0 ** f.retries),
                          self.nack_backoff_cap_ms)
            f.retries += 1
            self.nack_retries += 1
            _C_RETRIES.inc()
            f.next_retry = _time.monotonic() + backoff / 1000.0
            return
        if tag.flag == FLAG_TOO_LATE:
            # this replica finished the instance UNDECIDED (or shed it
            # past recovery): keep asking — a sibling replica may still
            # decide — and record the undecided outcome honestly only
            # once EVERY replica of the current shard has said so
            h = self.shard_health.setdefault(
                shard, {"too_late": 0, "nacks": 0, "undecided": 0})
            h["too_late"] += 1
            f = self._inflight.get(inst)
            if f is None:
                return
            f.too_late_from.add((shard, sender))
            n_shard = self._link_n.get(f.shard, 1)
            if sum(1 for s, _r in f.too_late_from
                   if s == f.shard) >= n_shard:
                self._resolve(inst, None, None)
                _C_UNDECIDED.inc()
                h["undecided"] += 1
            return

    def _give_up(self, f: _InFlight, why: str) -> None:
        log.warning("fleet: giving up on instance %d (shard %s): %s "
                    "(%d retries, %d reproposals)", f.inst, f.shard, why,
                    f.retries, f.reproposals)
        self._inflight.pop(f.inst, None)
        self.results[f.inst] = None
        self.errors[f.inst] = why
        self.give_ups += 1
        if f.tenant:
            self.tenant_give_ups[f.tenant] = \
                self.tenant_give_ups.get(f.tenant, 0) + 1
        _C_GIVE_UPS.inc()
        _G_INFLIGHT.set(len(self._inflight))
        if TRACE.enabled:
            TRACE.emit("fleet_give_up", node=None, inst=f.inst,
                       shard=f.shard, retries=f.retries,
                       reproposals=f.reproposals)

    def pump(self, timeout_ms: int = 50) -> int:
        """ONE router wave: drain every shard link, fire due NACK-retries
        and re-propose timers, flush the coalesced proposals.  Returns
        the number of frames handled — the caller's idle signal."""
        handled = 0
        now = _time.monotonic()
        per_link = max(0, timeout_ms) // max(1, len(self._links)) \
            if self._links else 0
        for name, link in list(self._links.items()):
            rm = getattr(link, "recv_many", None)
            if rm is not None:
                got_list = rm(int(per_link))
            else:
                got = link.recv(int(per_link))
                got_list = [got] if got is not None else []
            for got in got_list:
                self._on_frame(name, got)
            handled += len(got_list)
        # timers: NACK backoff expiries re-propose; silent instances past
        # repropose_ms re-ask (the decision catch-up — a lost PROPOSE,
        # a lost DECISION and a shed frame all heal through this)
        for f in list(self._inflight.values()):
            if f.next_retry > 0 and now >= f.next_retry:
                f.next_retry = 0.0
                self._send_propose(f)
            elif f.next_retry == 0 \
                    and (now - f.t_last) * 1000.0 >= min(
                        self.repropose_ms * (1.5 ** f.reproposals),
                        self.repropose_cap_ms):
                # EXPONENTIAL catch-up pacing: under a deep backlog (a
                # saturation blast queues thousands behind lanes), a
                # fixed-period re-ask floods the shards with wire noise
                # proportional to queue depth — and worse, exhausts the
                # give-up budget on instances that are QUEUED, not
                # lost.  Backed-off re-asks make the budget span ~10+
                # minutes while a genuinely lost frame still heals in
                # one repropose_ms
                if f.reproposals >= self.max_reproposals:
                    self._give_up(f, "unanswered past the re-propose cap")
                    continue
                f.reproposals += 1
                self.reproposals += 1
                _C_REPROPOSE.inc()
                self._send_propose(f)
        self._flush()
        return handled

    def status(self) -> Dict[str, Any]:
        """The router's shard-status surface (docs/SERVING.md "shard rv
        status"): per-shard health counters beside the fleet totals.  A
        shard whose driver rv-halted shows as a too_late burst with
        undecided resolutions — the router's view of a runtime-
        verification stop it cannot observe directly."""
        return {
            "shards": {name: dict(self.shard_health.get(
                name, {"too_late": 0, "nacks": 0, "undecided": 0}))
                for name in self.ring.shards},
            "inflight": len(self._inflight),
            "decided": sum(1 for v in self.results.values()
                           if v is not None),
            "undecided": sum(1 for v in self.results.values()
                             if v is None),
            "give_ups": self.give_ups,
            "nack_retries": self.nack_retries,
            "reproposals": self.reproposals,
            "migrations": self.migrations,
            "regions": {r: [s for s in self.ring.shards
                            if self.ring.region_of(s) == r]
                        for r in self.ring.regions},
            "tenant_nacks": dict(self.tenant_nacks),
            "tenant_give_ups": dict(self.tenant_give_ups),
        }

    def raise_if_gave_up(self) -> None:
        """Surface give-ups as the client-visible error (docs/SERVING.md
        NACK-retry contract): silent loss is never an outcome."""
        if self.give_ups:
            worst = sorted(self.errors.items())[:5]
            raise FleetGiveUp(
                f"{self.give_ups} instance(s) exhausted their retry "
                f"budget; first failures: {worst}")

    def drain(self, deadline_s: float, idle_ms: float = 0.0,
              stop: Optional[Callable[[], bool]] = None) -> bool:
        """Pump until every in-flight instance resolves (True), the
        deadline passes, or — with ``idle_ms`` > 0 — nothing has been
        heard from any shard for that long.  The loadgen interleaves
        its own arrivals with pump() instead of using this."""
        t_end = _time.monotonic() + deadline_s
        last_heard = _time.monotonic()
        while self._inflight and _time.monotonic() < t_end:
            if stop is not None and stop():
                return False
            if self.pump(50) > 0:
                last_heard = _time.monotonic()
            elif idle_ms > 0 and (_time.monotonic() - last_heard) \
                    * 1000.0 >= idle_ms:
                return False
        return not self._inflight

    def close(self) -> None:
        for link in self._links.values():
            try:
                link.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self._links.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class DriverServer:
    """One fleet shard: n replica threads, each a client-serving
    LaneDriver over its own HostTransport (the in-process consensus
    group of host_perftest.measure, grown the fleet client surface).
    The client id every replica accepts is ``n`` — the id space right
    above the group, where the router's transports live."""

    def __init__(self, algo, n: int = 3, lanes: int = 16,
                 timeout_ms: int = 300, seed: int = 0,
                 max_rounds: int = 32, proto: str = "tcp",
                 idle_ms: int = 8000, max_ms: int = 600_000,
                 use_pump: bool = True,
                 admission_bytes_per_lane: int = 0,
                 shed_deadline_ms: int = 250,
                 adaptive_cap_ms: int = 0,
                 ports: Optional[List[int]] = None,
                 rv=None, snap=None, kv=None,
                 tenants: Optional[Dict[int, float]] = None,
                 tenant_bytes_per_lane: int = 64 << 10):
        from round_tpu.runtime.chaos import alloc_ports
        from round_tpu.runtime.transport import HostTransport

        self.algo = algo
        self.n = n
        self.lanes = lanes
        self.timeout_ms = timeout_ms
        self.seed = seed
        self.max_rounds = max_rounds
        self.idle_ms = idle_ms
        self.max_ms = max_ms
        self.use_pump = use_pump
        self.admission_bytes_per_lane = admission_bytes_per_lane
        self.shed_deadline_ms = shed_deadline_ms
        self.adaptive_cap_ms = adaptive_cap_ms
        # runtime verification (round_tpu/rv): the rv.dump.RvConfig the
        # shard's LaneDrivers serve under; a 'halt' violation surfaces
        # through errors/join() and the router's too_late drain
        self.rv = rv
        # round-consistent snapshots (round_tpu/snap): the SnapConfig
        # every replica of this shard serves under — ONE shared config,
        # so cfg.collector names the replica (pid) that assembles and
        # audits the shard's cuts (the in-shard collector deployment;
        # banked .snapcut files feed apps/snap_cli.py offline)
        self.snap = snap
        # replicated key-value serving (round_tpu/kv): a kv.store.KvConfig
        # turns every replica into a KV shard member — decisions apply to
        # a per-replica KVState, FLAG_READ serves the three grades,
        # FLAG_TXN validates transaction records (docs/KV.md)
        self.kv = kv
        # per-tenant weighted-fair admission (runtime/instances.py
        # TenantAdmission, docs/SERVING.md): tenant id -> weight; every
        # replica meters its client intake per tenant so a hot tenant
        # sheds against its own share.  None = the tenant-blind shard.
        self.tenants = dict(tenants) if tenants else None
        self.tenant_bytes_per_lane = tenant_bytes_per_lane
        if ports is None:
            ports = alloc_ports(n)
        elif len(ports) != n:
            raise ValueError(f"{len(ports)} ports for n={n} replicas")
        self.replicas = [("127.0.0.1", p) for p in ports]
        self._transports = [HostTransport(i, ports[i], proto=proto)
                            for i in range(n)]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.results: List[Dict[int, Optional[int]]] = [{} for _ in
                                                        range(n)]
        self.stats: List[Dict[str, Any]] = [{} for _ in range(n)]
        self.errors: Dict[int, BaseException] = {}

    def _run_replica(self, i: int) -> None:
        from round_tpu.runtime.instances import (AdmissionControl,
                                                 TenantAdmission)
        from round_tpu.runtime.lanes import LaneDriver

        peers = {j: self.replicas[j] for j in range(self.n)}
        admission = None
        if self.admission_bytes_per_lane > 0:
            admission = AdmissionControl(
                high_bytes_per_lane=self.admission_bytes_per_lane,
                shed_deadline_ms=self.shed_deadline_ms)
        tenant_admission = None
        if self.tenants is not None:
            tenant_admission = TenantAdmission(
                bytes_per_lane=self.tenant_bytes_per_lane,
                weights=self.tenants,
                shed_deadline_ms=self.shed_deadline_ms)
        adaptive = None
        if self.adaptive_cap_ms > 0:
            # the deployed serving posture (PR 10's overload arms): EWMA
            # deadlines track the box's real round latency, so a loaded
            # fleet stretches its deadlines instead of failing phases
            from round_tpu.runtime.host import AdaptiveTimeout

            adaptive = AdaptiveTimeout(cap_ms=self.adaptive_cap_ms,
                                       seed=self.seed * 31 + i)
        kv_shard = None
        if self.kv is not None:
            from round_tpu.kv.store import KVShard

            kv_shard = KVShard(self.kv, node=i, n=self.n,
                               timeout_ms=self.timeout_ms)
        try:
            driver = LaneDriver(
                self.algo, i, peers, self._transports[i],
                lanes=self.lanes, timeout_ms=self.timeout_ms,
                seed=self.seed, max_rounds=self.max_rounds,
                value_schedule="uniform", use_pump=self.use_pump,
                admission=admission, adaptive=adaptive,
                clients={self.n}, rv=self.rv, snap=self.snap,
                kv=kv_shard, tenants=tenant_admission,
            )
            self.results[i] = driver.serve(
                idle_ms=self.idle_ms, max_ms=self.max_ms,
                stop=self._stop.is_set, stats_out=self.stats[i])
        except Exception as e:  # noqa: BLE001 — surfaced by join()
            self.errors[i] = e
            raise

    def rv_summary(self) -> Dict[str, Any]:
        """Aggregate rv status across this shard's replicas (the
        apps/fleet.py serve/bench output surface)."""
        viols = [v for st in self.stats
                 for v in st.get("rv_violations", [])]
        return {
            "enabled": self.rv is not None,
            "checks": sum(st.get("rv_checks", 0) for st in self.stats),
            "violations": viols,
            "artifacts": sorted({a for st in self.stats
                                 for a in st.get("rv_artifacts", [])}),
            "halted": sorted(
                i for i, e in self.errors.items()
                if type(e).__name__ == "RvViolation"),
        }

    def kv_summary(self) -> Dict[str, Any]:
        """Aggregate kv status across this shard's replicas (the
        apps/kv.py serve/bench output surface)."""
        return {
            "enabled": self.kv is not None,
            "applied": sum(st.get("kv_applied", 0) for st in self.stats),
            "reads_lin": sum(st.get("kv_reads_lin", 0)
                             for st in self.stats),
            "reads_lease": sum(st.get("kv_reads_lease", 0)
                               for st in self.stats),
            "reads_stale": sum(st.get("kv_reads_stale", 0)
                               for st in self.stats),
            "lease_refused": sum(st.get("kv_lease_refused", 0)
                                 for st in self.stats),
            "lease_barrier": sum(st.get("kv_lease_barrier", 0)
                                 for st in self.stats),
            "lease_grants": sum(st.get("kv_lease_grants", 0)
                                for st in self.stats),
            "txn_frames": sum(st.get("kv_txn_frames", 0)
                              for st in self.stats),
            "txn_commits": sum(st.get("kv_txn_commits", 0)
                               for st in self.stats),
            "txn_aborts": sum(st.get("kv_txn_aborts", 0)
                              for st in self.stats),
        }

    def tenant_summary(self) -> Dict[str, Any]:
        """Aggregate per-tenant shed accounting across this shard's
        replicas (the fleet-autoscale soak rung gates shed_frames ==
        nacks_sent + nacks_suppressed per tenant over exactly this)."""
        by_tenant: Dict[int, Dict[str, int]] = {}
        for st in self.stats:
            for t, d in st.get("tenants", {}).items():
                agg = by_tenant.setdefault(int(t), {})
                for k, v in d.items():
                    agg[k] = agg.get(k, 0) + v
        return {"enabled": self.tenants is not None,
                "weights": dict(self.tenants or {}),
                "by_tenant": by_tenant}

    def snap_summary(self) -> Dict[str, Any]:
        """Aggregate snapshot status across this shard's replicas (the
        apps/fleet.py serve/bench output surface; non-collector
        replicas contribute sample counts only)."""
        return {
            "enabled": self.snap is not None,
            "samples": sum(st.get("snap_samples", 0)
                           for st in self.stats),
            "cuts": sum(st.get("snap_cuts", 0) for st in self.stats),
            "cuts_audited": sum(st.get("snap_cuts_audited", 0)
                                for st in self.stats),
            "partial_cuts": sum(st.get("snap_partial_cuts", 0)
                                for st in self.stats),
            "violations": [v for st in self.stats
                           for v in st.get("snap_violations", [])],
            "divergences": [d for st in self.stats
                            for d in st.get("snap_divergences", [])],
            "artifacts": sorted({a for st in self.stats
                                 for a in st.get("snap_artifacts", [])}),
            "halted": sorted(
                i for i, e in self.errors.items()
                if type(e).__name__ == "SnapViolation"),
        }

    def start(self) -> List[Tuple[str, int]]:
        for i in range(self.n):
            t = threading.Thread(target=self._run_replica, args=(i,),
                                 name=f"fleet-replica-{i}", daemon=True)
            self._threads.append(t)
            t.start()
        return self.replicas

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout_s: float = 120.0) -> None:
        t_end = _time.monotonic() + timeout_s
        for t in self._threads:
            t.join(timeout=max(0.1, t_end - _time.monotonic()))
        alive = [t.name for t in self._threads if t.is_alive()]
        for tr in self._transports:
            try:
                tr.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        if self.errors:
            raise RuntimeError(f"fleet replicas failed: {self.errors}")
        if alive:
            raise RuntimeError(f"fleet replicas wedged: {alive}")

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        try:
            self.join()
        except RuntimeError:
            if exc[0] is None:
                raise
