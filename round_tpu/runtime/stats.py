"""Named counters and phase timers with a shutdown report.

Reference parity: psync.utils.Stats (utils/Stats.scala:7-98) + the --stat
shutdown-hook report (utils/Options.scala:16-25).  The reference uses these
to profile the CL reducer phases (logic/CL.scala:199-261); here they wrap
both the verifier pipeline and the engine (compile vs run time).
"""

from __future__ import annotations

import atexit
import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, Tuple


class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._timers: Dict[str, Tuple[int, float]] = {}  # name -> (calls, total_s)
        self.enabled = False

    def counter(self, name: str, delta: int = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + delta

    @contextmanager
    def timer(self, name: str) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.monotonic()
        try:
            yield
        finally:
            dt = time.monotonic() - t0
            with self._lock:
                calls, total = self._timers.get(name, (0, 0.0))
                self._timers[name] = (calls + 1, total + dt)

    def report(self) -> str:
        with self._lock:
            lines = ["# stats"]
            for name in sorted(self._counters):
                lines.append(f"counter {name}: {self._counters[name]}")
            for name in sorted(self._timers):
                calls, total = self._timers[name]
                lines.append(
                    f"timer {name}: {total:.3f}s over {calls} calls "
                    f"({1000 * total / max(calls, 1):.2f} ms/call)"
                )
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()

    def enable(self, report_at_exit: bool = True) -> None:
        """--stat: start collecting; print the report at interpreter exit
        (the reference's shutdown hook, utils/Options.scala:16-25)."""
        self.enabled = True
        if report_at_exit and not getattr(self, "_hooked", False):
            atexit.register(lambda: print(self.report()))
            self._hooked = True


# module-level singleton, like the reference's Stats object
stats = Stats()
