"""Named counters and phase timers with a shutdown report (legacy shim).

Reference parity: psync.utils.Stats (utils/Stats.scala:7-98) + the --stat
shutdown-hook report (utils/Options.scala:16-25).

The implementation moved to ``round_tpu.obs.metrics``: ``Stats`` is now a
facade over the typed metrics registry (counter / gauge / histogram with
JSON + Prometheus snapshots), so the verifier pipeline, the engines and
the host runtime share exactly ONE counters/timers surface.  This module
re-exports the same names — the API and the --stat report format are
unchanged.
"""

from __future__ import annotations

from round_tpu.obs.metrics import METRICS, Stats, stats  # noqa: F401
