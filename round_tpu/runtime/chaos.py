"""Chaos layer for the host runtime: deterministic wire-fault injection
and the crash-restart cluster driver.

The simulated engines carry their whole fault model as data
(engine/scenarios.py HO families); the real multi-process path
(runtime/host.py over runtime/transport.py) had none — it was only ever
exercised on a clean localhost wire.  This module closes that gap:

* ``FaultPlan`` — a seed-driven schedule of wire faults per
  (src, dst, round), SHARING the engines' counter-based link hash
  (scenarios.link_bernoulli: murmur3 fmix32 over
  ``idx·GOLD + salt0 ^ (r·RMIX + salt1)``, probabilities quantized to
  1/256).  ``FaultPlan(seed=s, drop=p)`` drops exactly the links
  ``scenarios.omission(n, p, impl="hash")`` drops for ``PRNGKey(s)`` —
  pinned by tests/test_chaos.py — so one fault mix can run against both
  the fused engine and a real process cluster and the decisions diffed.
  The extra families (duplicate / reorder / delay / truncate / garbage)
  draw from the same hash under distinct stream constants: one seed, six
  independent, REPLAYABLE schedules.

* ``FaultyTransport`` — a wrapper implementing the HostTransport surface
  (send/recv/add_peer/stop/close/dropped) that applies a FaultPlan:
  sender-side faults (drop, crash-silence, partition, duplicate,
  truncate, garbage bytes) perturb ``send``; receiver-side faults
  (delay, reorder) hold packets back in ``recv``.  Only FLAG_NORMAL
  data-plane frames are perturbed — the decision-reply control plane IS
  the recovery machinery under test and keeps the wire semantics of the
  underlying transport.

* ``run_chaos_cluster`` — the crash-restart driver: n ``host_replica``
  OS processes with a chaos spec, optionally SIGKILLing one replica
  after it has durably checkpointed ``crash_after`` instances and
  restarting it from the checkpoint (runtime/checkpoint.py).  Shared by
  tests/test_chaos.py and the tools/soak.py ``host-chaos`` rotation
  slot, which diffs the surviving decision logs byte-for-byte against a
  clean run.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, Optional

from round_tpu.engine.scenarios import (
    LINK_GOLD,
    host_key_salts,
    host_link_u32,
    mix32_host,
)
from round_tpu.obs.metrics import METRICS
from round_tpu.obs.trace import TRACE
from round_tpu.runtime.oob import FLAG_NORMAL

# Stream constants: each fault family draws an independent Bernoulli from
# the one link hash by folding its stream into the round salt.  DROP is
# stream 0 so the drop schedule is BIT-IDENTICAL to the engines'
# scenarios.omission hash mask for the same seed.
STREAM_DROP = 0x00000000
STREAM_DUP = 0x5D0F00D1
STREAM_REORDER = 0x6C1E55A7
STREAM_DELAY = 0x7D2EAA93
STREAM_TRUNCATE = 0x8E3F0189
STREAM_GARBAGE = 0x9F4F56B5
_PARTITION_SALT = 0x9A87  # matches scenarios.partition's fold-in constant

# The value-adversary streams (equivocation / stale replay) live in
# round_tpu/byz/adversary.py: value faults are SCHEDULED here (explicit
# [T, n, n] plans from v2 fuzz artifacts), never hash-drawn per send.

#: Native-round-pump compatibility, DECLARED per fault surface — the
#: silent-composition gate: ``enable_pump`` refuses unless every ACTIVE
#: surface of this transport is explicitly declared True here.  A new
#: fault family added without a declaration therefore falls back to the
#: Python pump instead of silently bypassing its injection semantics.
#: Sender-side byte-stream families are safe (the native receiver sees
#: exactly the faulted frames); receiver-side hold/release families are
#: not (natively-ingested frames would skip this wrapper's recv());
#: value-fault families start UNPROVEN: the forged frames are
#: well-formed and would template-ingest, but the zero-copy pinned-
#: mailbox interaction has no parity pin yet, so they keep the Python
#: pump (pump.fast_frames stays 0 — tests/test_byz.py).
PUMP_COMPAT = {
    "drop": True, "dup": True, "truncate": True, "garbage": True,
    "crash": True, "partition": True, "schedule": True,
    "delay": False, "reorder": False,
    "value": False,
}


def _p8(p: float) -> int:
    """Probability → 8-bit threshold, exactly link_bernoulli's clamp: any
    p > 0 keeps at least 1/256 (a lossy schedule must stay lossy)."""
    return max(1, round(p * 256.0)) if p > 0 else 0


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Seed-driven per-(src, dst, round) wire-fault schedule.

    Families and parameterisation mirror engine/scenarios.py:
      drop          — scenarios.omission(n, drop): iid per-link loss;
      crash_round   — scenarios.crash_at: from this round on, this
                      replica's sends are swallowed (-1 = never; the
                      process-level analogue is run_chaos_cluster's
                      SIGKILL);
      partition     — scenarios.partition: two seed-drawn halves cannot
                      talk until heal_round;
      dup/reorder/delay/truncate/garbage — wire-level families with no
                      HO-mask counterpart (an HO set cannot express a
                      duplicated or corrupted payload; the reference
                      tolerates these via InstanceHandler.scala:392-399,
                      which is exactly the machinery they exercise).
    """

    seed: int = 0
    drop: float = 0.0
    dup: float = 0.0
    reorder: float = 0.0
    reorder_hold_ms: int = 60
    delay: float = 0.0
    delay_ms: int = 40
    truncate: float = 0.0
    garbage: float = 0.0
    crash_round: int = -1
    heal_round: int = 0  # partition active while r < heal_round

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Build a plan from a compact CLI spec:
        ``drop=0.2,reorder=0.15,dup=0.05,seed=7`` (keys are the dataclass
        fields; ints and floats inferred).  Unknown keys are an error —
        a typo'd family must not silently run fault-free."""
        kwargs: Dict[str, object] = {}
        fields = {f.name: f.type for f in dataclasses.fields(cls)}
        for part in filter(None, (p.strip() for p in spec.split(","))):
            if "=" not in part:
                raise ValueError(f"chaos spec entry {part!r} is not key=value")
            key, val = part.split("=", 1)
            key = key.strip().replace("-", "_")
            if key not in fields:
                raise ValueError(
                    f"unknown chaos family/field {key!r}; known: "
                    f"{sorted(fields)}")
            kwargs[key] = (int(val) if fields[key] == "int"
                           or fields[key] is int else float(val))
        return cls(**kwargs)

    def spec(self) -> str:
        """The canonical round-trippable spec string (non-default fields)."""
        out = []
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v != f.default:
                out.append(f"{f.name}={v}")
        return ",".join(out)


class FaultyTransport:
    """A HostTransport/HostBus-surface wrapper applying a FaultPlan.

    Fault decisions are pure functions of (seed, src, dst, round): two
    runs over the same plan see the same schedule (delivery TIMING of
    delayed packets is wall-clock, the schedule of which packets fault is
    not).  `injected` counts every applied fault for assertions and
    stats.  Non-NORMAL (control-plane) frames pass through untouched."""

    def __init__(self, inner, plan: FaultPlan, n: int, schedule=None,
                 value_plan=None, protocol: Optional[str] = None,
                 rounds_per_phase: Optional[int] = None):
        self.inner = inner
        self.plan = plan
        self.n = n
        self._salt0, self._salt1 = host_key_salts(plan.seed)
        self.injected: Dict[str, int] = {}
        self._held: list = []   # (release_t, seq, got) min-heap
        self._seq = itertools.count()
        # explicit-schedule mode (the fuzzer's replay surface): a
        # [T, n, n] bool DELIVER tensor — schedule[r, dst, src] — REPLACES
        # the hash-derived families wholesale (rounds >= T clamp to the
        # last row, matching engine/scenarios.from_schedule).  Purely
        # sender-side, so the native round pump stays safe to engage.
        self.schedule = None
        if schedule is not None:
            import numpy as np

            sched = np.asarray(schedule, dtype=bool)
            if sched.ndim != 3 or sched.shape[1] != sched.shape[2] \
                    or sched.shape[0] < 1:
                raise ValueError(
                    f"schedule must be [T, n, n] bool, got {sched.shape}")
            if sched.shape[1] != n:
                raise ValueError(
                    f"schedule n={sched.shape[1]} != transport n={n}")
            self.schedule = sched
        # scheduled VALUE-fault families (round_tpu/byz): an explicit
        # [T, n, n] int32 substitution plan — plan[r, dst, src] is
        # VP_NONE (truthful), VP_STALE (replay this sender's previous
        # transmission of the round class) or v >= 0 (re-encode the frame
        # claiming value v through the protocol's lie model).  Purely
        # sender-side: the frame on the wire IS the forged frame, so an
        # engine equivocation finding replays byte-equivalently here.
        self.value_plan = None
        self.protocol = protocol
        self._rpp = max(1, int(rounds_per_phase or 1))
        # stale-replay memory: per round class, the LAST truthful payload
        # bytes actually sent at an earlier round (the engine's carried
        # (ever-sent, last-sent) pair, in byte form) + the in-round cache
        self._class_prev: Dict[int, bytes] = {}
        self._class_cur: Dict[int, tuple] = {}
        self._class_inst: Optional[int] = None
        if value_plan is not None:
            import numpy as np

            vp = np.asarray(value_plan, dtype=np.int32)
            if vp.ndim != 3 or vp.shape[1] != vp.shape[2]:
                raise ValueError(
                    f"value plan must be [T, n, n] int32, got {vp.shape}")
            if vp.shape[1] != n:
                raise ValueError(
                    f"value plan n={vp.shape[1]} != transport n={n}")
            if protocol is None:
                raise ValueError(
                    "value_plan needs the protocol name (lie-model and "
                    "round-class resolution)")
            self.value_plan = vp

    @classmethod
    def from_schedule_file(cls, inner, path: str) -> "FaultyTransport":
        """Explicit per-(src, dst, round) schedule from a fuzz artifact
        (round_tpu/fuzz/replay.py schema) instead of hash-derived
        families — the constructor that turns a minimized engine finding
        into a deterministic host-wire regression: the SAME link events
        the engine mask suppressed are dropped on the real wire, and (v2
        artifacts) the SAME value-substitution events are forged into
        the outgoing frames (delivery equivalence pinned by
        tests/test_fuzz.py; value equivalence by tests/test_byz.py)."""
        from round_tpu.fuzz.replay import (
            load_artifact,
            schedule_from_artifact,
            value_plan_from_artifact,
        )

        art = load_artifact(path)
        vplan = value_plan_from_artifact(art)
        rpp = None
        if vplan is not None:
            from round_tpu.apps.selector import select

            rpp = select(art["protocol"]).rounds_per_phase
        return cls(inner, FaultPlan(seed=int(art.get("seed", 0))),
                   n=int(art["n"]),
                   schedule=schedule_from_artifact(art),
                   value_plan=vplan, protocol=art["protocol"],
                   rounds_per_phase=rpp)

    # -- the seeded link hash ----------------------------------------------

    def _u32(self, stream: int, src: int, dst: int, r: int) -> int:
        return host_link_u32(self._salt0, self._salt1, r, src, dst,
                             self.n, stream)

    def _event(self, stream: int, src: int, dst: int, r: int,
               p: float) -> bool:
        p8 = _p8(p)
        return p8 > 0 and (self._u32(stream, src, dst, r) & 0xFF) < p8

    def _side(self, node: int) -> int:
        """Seed-drawn partition side, constant per node (the
        scenarios.partition per-scenario split role)."""
        return mix32_host(node * LINK_GOLD + self._salt0
                          + _PARTITION_SALT) & 1

    def _count(self, family: str, src: int, dst: int, r: int,
               inst: int) -> None:
        """Record one injected fault: the per-transport `injected` dict
        (assertions), the unified chaos.* counter, and — when tracing —
        a typed `fault` event carrying the (src, dst, round, instance)
        coordinates tools/trace_view.py correlates against the timeouts
        and catch-ups the fault caused downstream."""
        self.injected[family] = self.injected.get(family, 0) + 1
        METRICS.counter(f"chaos.{family}").inc()
        if TRACE.enabled:
            TRACE.emit("fault", node=self.inner.id, family=family,
                       src=src, dst=dst, round=r, inst=inst)

    # -- scheduled value faults (round_tpu/byz) ----------------------------

    def _note_sent(self, r: int, inst: int, payload: bytes) -> None:
        """Advance the per-round-class stale memory: ``_class_prev[k]``
        holds the last truthful payload bytes this sender transmitted at
        a round STRICTLY earlier than the current one (the byte twin of
        the engine's carried (ever-sent, last-sent) pair).  An instance
        change — or a round restart, for callers that re-tag — resets
        it: a fresh instance has no stale history (so a new instance
        whose first send lands on the SAME round number as the previous
        instance's last send cannot inherit its payload)."""
        if inst != self._class_inst:
            self._class_prev.clear()
            self._class_cur.clear()
            self._class_inst = inst
        k = r % self._rpp
        cur = self._class_cur.get(k)
        if cur is not None:
            if cur[0] == r:
                return  # same round, same payload: one entry per round
            if cur[0] > r:  # rounds restarted without an instance tag
                self._class_prev.clear()
                self._class_cur.clear()
            else:
                self._class_prev[k] = cur[1]
        self._class_cur[k] = (r, bytes(payload))

    def _value_fault(self, to: int, r: int, inst: int,
                     payload: bytes) -> bytes:
        """Apply the scheduled value op for (r, to): forge the frame
        claiming the planned value through the protocol's lie model
        (byz/lies.py — decode, lie, re-encode: well-formed by
        construction), or substitute the sender's previous transmission
        of this round class (stale replay).  Undecodable/empty frames
        pass through untouched — a lie needs a well-formed truth to
        forge."""
        vp = self.value_plan
        src = self.inner.id
        vn, T = vp.shape[1], vp.shape[0]
        if not (0 <= src < vn and 0 <= to < vn):
            return payload
        op = int(vp[min(r, T - 1), to, src])
        if op == -1:
            return payload
        k = r % self._rpp
        if op == -2:  # VP_STALE
            prev = self._class_prev.get(k)
            if prev is None:
                return payload  # nothing sent earlier: truthful
            self._count("byz_stale", src, to, r, inst)
            return prev
        if not payload:
            return payload
        from round_tpu.byz.lies import forge_payload
        from round_tpu.runtime import codec

        try:
            obj = codec.loads(bytes(payload))
            forged = codec.encode(forge_payload(self.protocol, k, obj, op))
        except Exception:  # noqa: BLE001 — an unforgeable frame (foreign
            # codec, control payload riding FLAG_NORMAL) stays truthful;
            # the adversary only forges what it can parse
            return payload
        self._count("byz_equivocate", src, to, r, inst)
        return forged

    # -- HostTransport surface ---------------------------------------------

    @property
    def id(self):
        return self.inner.id

    @property
    def port(self):
        return self.inner.port

    @property
    def dropped(self):
        return self.inner.dropped

    @property
    def closed(self):
        return self.inner.closed

    def add_peer(self, peer_id, host, port):
        return self.inner.add_peer(peer_id, host, port)

    def remove_peer(self, peer_id):
        return self.inner.remove_peer(peer_id)

    def connected(self, peer_id):
        return self.inner.connected(peer_id)

    def start_reconnect(self, **kw):
        return self.inner.start_reconnect(**kw)

    @property
    def reconnects(self):
        return self.inner.reconnects

    def active_surfaces(self):
        """The fault surfaces this transport actually applies — the
        inputs of the PUMP_COMPAT capability check."""
        out = []
        p = self.plan
        if self.schedule is not None:
            out.append("schedule")
        else:
            if p.drop > 0:
                out.append("drop")
            if p.dup > 0:
                out.append("dup")
            if p.truncate > 0:
                out.append("truncate")
            if p.garbage > 0:
                out.append("garbage")
            if p.crash_round >= 0:
                out.append("crash")
            if p.heal_round > 0:
                out.append("partition")
        # the receiver-side hold/release families apply in recv()
        # REGARDLESS of schedule mode (_maybe_hold consults only the
        # plan), so they stay declared even when an explicit schedule
        # turned the sender-side hash families off — a schedule+delay
        # transport must refuse the pump like any delay plan
        if p.delay > 0:
            out.append("delay")
        if p.reorder > 0:
            out.append("reorder")
        if self.value_plan is not None:
            out.append("value")
        return out

    def enable_pump(self, L, n, k, nbz=0):
        """Native-round-pump pass-through, gated by the EXPLICIT
        capability map (PUMP_COMPAT): the pump engages only when every
        active fault surface is declared pump-compatible.  Sender-side
        byte families (drop, crash, partition, dup, truncate, garbage,
        explicit schedules) are — faults apply in send/send_buffered
        before the wire, so the native receiver sees exactly the faulted
        frame stream.  The receiver-side hold/release families (delay,
        reorder) are not — frames the native pump ingests would bypass
        this wrapper's recv().  VALUE-fault plans are declared
        incompatible until a zero-copy parity pin exists (PUMP_COMPAT),
        so a value-schedule run falls back to the Python pump
        (pump.fast_frames stays 0) rather than silently bypassing
        injection.  The pump SEND path is never offered here (no
        ``pump_send_ok``): sends must keep flowing through send_buffered
        so faults stay per logical frame."""
        if not all(PUMP_COMPAT.get(s, False)
                   for s in self.active_surfaces()):
            return None
        f = getattr(self.inner, "enable_pump", None)
        return None if f is None else f(L, n, k, nbz)

    def rewire(self, peers, my_id=None):
        """View-change pass-through (runtime/view.py): the live peer table
        swap happens on the inner transport; the fault schedules COMPOSE
        with churn by construction — every family is a pure function of
        (seed, src, dst, round), so reconnects and renames change which
        physical channel carries a frame, never whether it faults.  Only
        ``n`` (the sender-range/partition-side domain) tracks the group."""
        out = self.inner.rewire(peers, my_id=my_id)
        self.n = len(peers)
        return out

    def stop(self):
        return self.inner.stop()

    def close(self):
        return self.inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _send_faults(self, to, tag, payload):
        """Apply the sender-side fault families to ONE logical frame.
        Returns (deliver, payload, dup): the frame's fate is a pure
        function of (seed, src, dst, round) regardless of HOW it then
        travels — a direct send and a coalesced batch member fault
        IDENTICALLY, which is what keeps per-(seed,src,dst,round)
        schedules framing-invariant (pinned by tests/test_chaos.py)."""
        plan, src = self.plan, self.inner.id
        r, inst = tag.round, tag.instance
        if self.value_plan is not None:
            # stale-replay memory advances on every SEND attempt (the
            # engine's prev carry updates on the dest mask, not on
            # delivery — a round whose frames all drop still refreshes
            # the sender's last-sent payload)
            self._note_sent(r, inst, payload)
        if self.schedule is not None:
            # explicit schedule: one lookup decides the frame's fate; the
            # hash families are OFF in this mode.  Out-of-range peers
            # pass through — bounded by the SCHEDULE's own group size,
            # not self.n, which rewire() retargets on view churn (a
            # schedule pins a fixed-n world; members past it are unfaulted
            # rather than an IndexError killing the sender).
            sn = self.schedule.shape[1]
            if not (0 <= src < sn and 0 <= to < sn):
                return True, payload, False
            T = self.schedule.shape[0]
            if not self.schedule[min(r, T - 1), to, src]:
                self._count("drop", src, to, r, inst)
                return False, payload, False
            if self.value_plan is not None:
                payload = self._value_fault(to, r, inst, payload)
            return True, payload, False
        if 0 <= plan.crash_round <= r:
            self._count("crash_mute", src, to, r, inst)
            return False, payload, False  # swallowed: crashed = silent
        if r < plan.heal_round and self._side(src) != self._side(to):
            self._count("partition", src, to, r, inst)
            return False, payload, False
        if self._event(STREAM_DROP, src, to, r, plan.drop):
            self._count("drop", src, to, r, inst)
            return False, payload, False  # silent loss, UDP-style
        if self.value_plan is not None:
            # a standalone value plan composes with the hash families:
            # lies apply only to frames the omission families deliver
            payload = self._value_fault(to, r, inst, payload)
        if payload and self._event(STREAM_TRUNCATE, src, to, r,
                                   plan.truncate):
            u = self._u32(STREAM_TRUNCATE, src, to, r)
            payload = payload[: (u >> 8) % len(payload)]
            self._count("truncate", src, to, r, inst)
        if self._event(STREAM_GARBAGE, src, to, r, plan.garbage):
            u = self._u32(STREAM_GARBAGE, src, to, r)
            payload = (u.to_bytes(4, "big") * (1 + (u >> 8) % 16))
            self._count("garbage", src, to, r, inst)
        dup = self._event(STREAM_DUP, src, to, r, plan.dup)
        if dup:
            self._count("dup", src, to, r, inst)
        return True, payload, dup

    def send(self, to, tag, payload: bytes = b"") -> bool:
        if tag.flag != FLAG_NORMAL:
            return self.inner.send(to, tag, payload)
        deliver, payload, dup = self._send_faults(to, tag, payload)
        if not deliver:
            return True
        ok = self.inner.send(to, tag, payload)
        if dup:
            self.inner.send(to, tag, payload)
        return ok

    def send_buffered(self, to, tag, payload=b"") -> bool:
        """The coalescing surface (runtime/transport.py): faults apply
        PER LOGICAL FRAME before the frame joins its destination batch,
        so a batch member drops/corrupts/duplicates exactly when its
        direct-send twin would (duplicates ride the same batch)."""
        inner_sb = getattr(self.inner, "send_buffered", None)
        if inner_sb is None:
            return self.send(to, tag, payload)
        if tag.flag != FLAG_NORMAL:
            return inner_sb(to, tag, payload)
        deliver, payload, dup = self._send_faults(to, tag, payload)
        if not deliver:
            return True
        ok = inner_sb(to, tag, payload)
        if dup:
            inner_sb(to, tag, payload)
        return ok

    def flush(self, to=None) -> int:
        f = getattr(self.inner, "flush", None)
        return 0 if f is None else f(to)

    def _maybe_hold(self, got):
        """Receiver-side families: None when the packet was held back."""
        sender, tag, _raw = got
        if tag.flag != FLAG_NORMAL or not (0 <= sender < self.n):
            return got
        plan, dst, r = self.plan, self.inner.id, tag.round
        hold_ms = 0
        if self._event(STREAM_DELAY, sender, dst, r, plan.delay):
            hold_ms += plan.delay_ms
            self._count("delay", sender, dst, r, tag.instance)
        if self._event(STREAM_REORDER, sender, dst, r, plan.reorder):
            hold_ms += plan.reorder_hold_ms
            self._count("reorder", sender, dst, r, tag.instance)
        if hold_ms <= 0:
            return got
        heapq.heappush(
            self._held,
            (time.monotonic() + hold_ms / 1000.0, next(self._seq), got),
        )
        return None

    def recv(self, timeout_ms: int):
        deadline = time.monotonic() + max(timeout_ms, 0) / 1000.0
        while True:
            now = time.monotonic()
            if self._held and self._held[0][0] <= now:
                return heapq.heappop(self._held)[2]
            remaining = deadline - now
            if remaining <= 0:
                # final non-blocking poll keeps recv(0) drain semantics
                got = self.inner.recv(0)
                if got is None:
                    return None
                return self._maybe_hold(got)
            wait = remaining
            if self._held:
                wait = min(wait, self._held[0][0] - now)
            got = self.inner.recv(max(0, int(wait * 1000)))
            if got is None:
                continue  # deadline or a held release came due
            got = self._maybe_hold(got)
            if got is not None:
                return got

    def recv_many(self, timeout_ms: int):
        """Batched-drain surface: repeated recv() so the receiver-side
        hold/release schedules (delay, reorder) apply per logical frame
        exactly as they do frame-by-frame."""
        out = []
        got = self.recv(timeout_ms)
        while got is not None:
            out.append(got)
            got = self.recv(0)
        return out


# ---------------------------------------------------------------------------
# Crash-restart cluster driver (host_replica subprocesses)
# ---------------------------------------------------------------------------


def alloc_ports(n: int):
    """n free localhost ports (bind-then-close; the shared copy — also
    used by apps/host_perftest.py and the cluster tests)."""
    import socket

    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def cluster_env() -> Dict[str, str]:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # persistent jit cache: the clean run warms it for the chaos run (and
    # the restarted replica re-pays only a disk load, not a compile)
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(repo, ".jax_cache"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    return env


def _checkpoint_step(ckpt_dir: str) -> int:
    """step recorded in a checkpoint manifest, -1 when absent/torn."""
    try:
        with open(os.path.join(ckpt_dir, "manifest.json")) as fh:
            return int(json.load(fh).get("step", -1))
    except (OSError, ValueError):
        return -1


def run_chaos_cluster(
    workdir: str,
    n: int = 3,
    instances: int = 6,
    *,
    algo: str = "otr",
    chaos: Optional[str] = None,
    crash_replica: Optional[int] = None,
    crash_after: int = 2,
    crash_wait_s: float = 60.0,
    timeout_ms: int = 250,
    max_rounds: int = 32,
    value_schedule: str = "uniform",
    seed: int = 0,
    adaptive: bool = False,
    proto: str = "tcp",
    join_timeout: float = 150.0,
    linger_ms: int = 8000,
    trace: bool = False,
):
    """Run an n-process host cluster to completion, optionally under a
    chaos spec and one forced crash-restart.

    With ``crash_replica`` set, that replica is SIGKILLed once its
    durable checkpoint records >= ``crash_after`` completed instances
    (or after ``crash_wait_s``, whichever first) and immediately
    restarted with the same argv — recovery must come from the
    checkpoint plus the peers' decision-replay protocol.  The OTHER
    replicas get ``--linger-ms`` so they outlive the restart: a replica
    whose peers all exit before its interpreter even comes back up has
    nobody left to serve the decision replies catch-up depends on
    (host.serve_decisions).

    With ``trace``, every replica records a round-level event trace and a
    metrics snapshot (apps/host_replica.py --trace / --metrics-json into
    ``workdir/trace-<i>.jsonl`` / ``workdir/metrics-<i>.json``); the
    returned dict then also carries ``trace_files`` / ``metrics_files``
    for tools/trace_view.py to merge and correlate.

    Returns a dict with per-replica ``decisions`` (from the summary JSON
    line), ``log_bytes`` (the byte-exact instance→value decision-log TSV
    each replica wrote), ``outs`` (full summary JSONs) and ``restarts``.
    """
    os.makedirs(workdir, exist_ok=True)
    ports = alloc_ports(n)
    peer_arg = ",".join(f"127.0.0.1:{p}" for p in ports)
    env = cluster_env()

    def argv(i: int):
        a = [sys.executable, "-m", "round_tpu.apps.host_replica",
             "--id", str(i), "--peers", peer_arg, "--algo", algo,
             "--instances", str(instances),
             "--timeout-ms", str(timeout_ms),
             "--max-rounds", str(max_rounds),
             "--seed", str(seed), "--proto", proto,
             "--value-schedule", value_schedule,
             "--decision-log", os.path.join(workdir, f"decisions-{i}.tsv"),
             "--checkpoint-dir", os.path.join(workdir, f"ckpt-{i}")]
        if chaos:
            a += ["--chaos", chaos]
        if trace:
            a += ["--trace", os.path.join(workdir, f"trace-{i}.jsonl"),
                  "--metrics-json", os.path.join(workdir,
                                                 f"metrics-{i}.json")]
        if adaptive:
            a += ["--adaptive-timeout"]
        if (crash_replica is not None and i != crash_replica
                and linger_ms > 0):
            a += ["--linger-ms", str(linger_ms)]
        return a

    def launch(i: int):
        return subprocess.Popen(argv(i), stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True, env=env)

    procs = {i: launch(i) for i in range(n)}
    restarts = 0
    try:
        if crash_replica is not None:
            ckpt = os.path.join(workdir, f"ckpt-{crash_replica}")
            t_end = time.monotonic() + crash_wait_s
            while (time.monotonic() < t_end
                   and _checkpoint_step(ckpt) < crash_after
                   and procs[crash_replica].poll() is None):
                time.sleep(0.05)
            if procs[crash_replica].poll() is None:
                # SIGKILL, not terminate: the point is an unclean death
                procs[crash_replica].send_signal(signal.SIGKILL)
                procs[crash_replica].wait(timeout=30)
                restarts += 1
                procs[crash_replica] = launch(crash_replica)
        outs = {}
        for i, p in enumerate(procs.values()):
            stdout, stderr = p.communicate(timeout=join_timeout)
            if p.returncode != 0:
                raise RuntimeError(
                    f"replica {i} failed (rc={p.returncode}): "
                    f"{stderr[-2000:]}")
            outs[i] = json.loads(stdout.strip().splitlines()[-1])
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                try:
                    p.communicate(timeout=10)
                except Exception:  # noqa: BLE001 - best-effort reap
                    pass
    log_bytes = {}
    for i in range(n):
        with open(os.path.join(workdir, f"decisions-{i}.tsv"), "rb") as fh:
            log_bytes[i] = fh.read()
    out = {
        "decisions": {i: outs[i].get("decisions") for i in outs},
        "log_bytes": log_bytes,
        "outs": outs,
        "restarts": restarts,
    }
    if trace:
        out["trace_files"] = {
            i: os.path.join(workdir, f"trace-{i}.jsonl") for i in range(n)}
        out["metrics_files"] = {
            i: os.path.join(workdir, f"metrics-{i}.json") for i in range(n)}
    return out
