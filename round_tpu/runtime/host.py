"""Host multi-process round execution: one OS process per replica.

This is the deployment shape of the reference — n JVMs, one per ProcessID,
full TCP mesh, the InstanceHandler loop driving init → send → accumulate →
update per round (InstanceHandler.scala:164-258) — rebuilt on the native
transport (native/transport.cpp via runtime/transport.py).

The SAME algorithm classes the TPU engine runs (core/algorithm.py Round
DSL) run here unchanged: their send/update are per-lane pure functions, so
one process evaluates them for its own lane on CPU scalars while the
simulator vmaps them over [scenario, lane] axes on the chip.  That is the
framework's deployment story: simulate at scale on TPU, deploy the
identical protocol code process-per-replica.

Round discipline (benign model):
  * send: evaluate SendSpec, unicast payload bytes per selected dest
    (self-delivery short-circuits the wire, Round.scala:114-117);
  * accumulate: block on the transport inbox until every live peer was
    heard or the round timeout fires (Progress.timeout,
    InstanceHandler.scala:197-245);
  * early messages for future rounds are buffered, late ones dropped
    (the pendingMessages priority queue role, InstanceHandler.scala:68-72);
  * update: fold the mailbox; `exitAtEndOfRound` ends the run.

Payloads cross the wire pickled (the Kryo role; same trust model as the
reference — replicas deserialize only from their own group).
"""

from __future__ import annotations

import dataclasses
import pickle
import time as _time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.rounds import RoundCtx
from round_tpu.ops.mailbox import Mailbox
from round_tpu.runtime.log import get_logger
from round_tpu.runtime.oob import FLAG_NORMAL, Message, Tag
from round_tpu.runtime.transport import HostTransport

log = get_logger("host")


@dataclasses.dataclass
class HostResult:
    state: Any
    decided: bool
    decision: Any
    rounds_run: int
    dropped_messages: int


class HostRunner:
    """Run one replica of an Algorithm instance over the host transport.

    `peers` maps every node id (including ours) to (host, port).  The run is
    an instance in the reference sense: `instance_id` tags every packet and
    foreign-instance packets are handed to `default_handler` (or dropped)."""

    def __init__(
        self,
        algo: Algorithm,
        my_id: int,
        peers: Dict[int, Tuple[str, int]],
        transport: HostTransport,
        instance_id: int = 1,
        timeout_ms: int = 200,
        seed: int = 0,
        default_handler=None,
    ):
        self.algo = algo
        self.id = my_id
        self.n = len(peers)
        self.transport = transport
        self.instance_id = instance_id & 0xFFFF
        self.timeout_ms = timeout_ms
        self.seed = seed
        self.default_handler = default_handler
        for pid, (host, port) in peers.items():
            if pid != my_id:
                transport.add_peer(pid, host, port)
        # round -> {sender: payload}; early messages wait here
        self._pending: Dict[int, Dict[int, Any]] = {}

    def _ctx(self, r: int) -> RoundCtx:
        rng = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(self.seed), r), self.id
        )
        return RoundCtx(id=np.int32(self.id), n=self.n, r=np.int32(r),
                        rng=rng)

    def run(self, io: Any, max_rounds: int = 64) -> HostResult:
        algo = self.algo
        state = algo.make_init_state(self._ctx(0), io)
        rounds = algo.rounds
        exited = False
        r = 0
        while r < max_rounds and not exited:
            rnd = rounds[r % len(rounds)]
            ctx = self._ctx(r)
            state = rnd.pre(ctx, state)  # round-var resets (executor.py:85)
            spec = rnd.send(ctx, state)
            dest = np.asarray(spec.dest_mask)
            payload_np = jax.tree_util.tree_map(np.asarray, spec.payload)
            wire = pickle.dumps(payload_np)
            for d in range(self.n):
                if d == self.id or not dest[d]:
                    continue
                self.transport.send(
                    d, Tag(instance=self.instance_id, round=r), wire
                )

            # -- accumulate (InstanceHandler.scala:197-245) ---------------
            inbox: Dict[int, Any] = dict(self._pending.pop(r, {}))
            if dest[self.id]:
                inbox[self.id] = payload_np  # self-delivery off the wire
            deadline = _time.monotonic() + self.timeout_ms / 1000.0
            expected = rnd.expected_nbr_messages(ctx, state)
            while len(inbox) < min(self.n, int(expected)):
                left_ms = int((deadline - _time.monotonic()) * 1000)
                if left_ms <= 0:
                    break
                got = self.transport.recv(left_ms)
                if got is None:
                    break
                sender, tag, raw = got
                if tag.instance != self.instance_id or tag.flag != FLAG_NORMAL:
                    if self.default_handler is not None:
                        self.default_handler(Message(
                            sender=sender, tag=tag,
                            payload=pickle.loads(raw) if raw else None,
                        ))
                    continue
                if tag.round < r:
                    continue  # late: the round is communication-closed
                payload = pickle.loads(raw)
                if tag.round > r:
                    self._pending.setdefault(tag.round, {})[sender] = payload
                    continue
                inbox[sender] = payload

            # -- update ---------------------------------------------------
            mbox = self._mailbox(inbox, payload_np)
            state = rnd.update(ctx, state, mbox)
            exited = bool(np.asarray(ctx._exit))
            log.debug("node %d round %d: heard %d/%d%s", self.id, r,
                      len(inbox), self.n, " exit" if exited else "")
            r += 1

        decided = bool(np.asarray(algo.decided(state)))
        decision = np.asarray(algo.decision(state))
        return HostResult(
            state=state, decided=decided, decision=decision, rounds_run=r,
            dropped_messages=self.transport.dropped,
        )

    def _mailbox(self, inbox: Dict[int, Any], like: Any) -> Mailbox:
        """Stack per-sender payloads into the [n, ...] arrays + mask the
        Round DSL's update expects (the dense-mailbox view of the wire)."""
        leaves_like, treedef = jax.tree_util.tree_flatten(like)
        stacked = [
            np.zeros((self.n,) + np.shape(l), dtype=np.asarray(l).dtype)
            for l in leaves_like
        ]
        mask = np.zeros((self.n,), dtype=bool)
        for sender, payload in inbox.items():
            leaves = jax.tree_util.tree_flatten(payload)[0]
            for slot, leaf in zip(stacked, leaves):
                slot[sender] = leaf
            mask[sender] = True
        values = jax.tree_util.tree_unflatten(treedef, stacked)
        return Mailbox(values, np.asarray(mask))
