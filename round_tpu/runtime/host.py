"""Host multi-process round execution: one OS process per replica.

This is the deployment shape of the reference — n JVMs, one per ProcessID,
full TCP mesh, the InstanceHandler loop driving init → send → accumulate →
update per round (InstanceHandler.scala:164-258) — rebuilt on the native
transport (native/transport.cpp via runtime/transport.py).

The SAME algorithm classes the TPU engine runs (core/algorithm.py Round
DSL) run here unchanged: their send/update are per-lane pure functions, so
one process evaluates them for its own lane on CPU scalars while the
simulator vmaps them over [scenario, lane] axes on the chip.  That is the
framework's deployment story: simulate at scale on TPU, deploy the
identical protocol code process-per-replica.

Round discipline (benign model):
  * send: evaluate SendSpec, unicast payload bytes per selected dest
    (self-delivery short-circuits the wire, Round.scala:114-117);
  * accumulate: block on the transport inbox until every live peer was
    heard or the round timeout fires (Progress.timeout,
    InstanceHandler.scala:197-245);
  * early messages for future rounds are buffered, late ones dropped
    (the pendingMessages priority queue role, InstanceHandler.scala:68-72);
  * update: fold the mailbox; `exitAtEndOfRound` ends the run.

Payloads cross the wire pickled (the Kryo role; same trust model as the
reference — replicas deserialize only from their own group).
"""

from __future__ import annotations

import dataclasses
import pickle
import time as _time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.rounds import RoundCtx
from round_tpu.ops.mailbox import Mailbox
from round_tpu.runtime.log import get_logger
from round_tpu.runtime.oob import FLAG_NORMAL, Message, Tag
from round_tpu.runtime.transport import HostTransport

log = get_logger("host")


@dataclasses.dataclass
class HostResult:
    state: Any
    decided: bool
    decision: Any
    rounds_run: int
    dropped_messages: int


def run_instance_loop(
    algo: Algorithm,
    my_id: int,
    peers: Dict[int, Tuple[str, int]],
    transport: HostTransport,
    instances: int,
    timeout_ms: int = 300,
    seed: int = 0,
    base_value: int = 0,
    max_rounds: int = 32,
) -> List[Optional[int]]:
    """The PerfTest2 loop (PerfTest2.scala:19-110): `instances` consecutive
    consensus instances over one transport, with start-skew stashing —
    NORMAL messages tagged with a FUTURE instance are buffered and
    prefilled into that instance's runner (the defaultHandler lazy-join
    role); traffic for completed instances is dropped (TooLate).  Initial
    values follow the deterministic schedule (base_value + id·7 + inst)
    mod 5, so runs are reproducible across replicas and modes.

    Returns the per-instance decision log (None where undecided)."""
    stash: Dict[int, Dict[int, Dict[int, Any]]] = {}
    current = {"inst": 0}

    def foreign(sender, tag, payload):
        if tag.instance <= current["inst"]:
            return
        stash.setdefault(tag.instance, {}).setdefault(
            tag.round, {})[sender] = payload

    decisions: List[Optional[int]] = []
    for inst in range(1, instances + 1):
        current["inst"] = inst
        runner = HostRunner(
            algo, my_id, peers, transport, instance_id=inst,
            timeout_ms=timeout_ms, seed=seed + inst,
            foreign=foreign, prefill=stash.pop(inst, None),
        )
        value = (base_value + my_id * 7 + inst) % 5
        res = runner.run({"initial_value": np.int32(value)},
                         max_rounds=max_rounds)
        decisions.append(
            int(np.asarray(res.decision)) if res.decided else None
        )
    return decisions


class HostRunner:
    """Run one replica of an Algorithm instance over the host transport.

    `peers` maps every node id (including ours) to (host, port).  The run is
    an instance in the reference sense: `instance_id` tags every packet.
    Foreign-instance NORMAL packets go to the `foreign` sink when one is
    set (the consecutive-instance driver's stash — see __init__), else
    with other-flag traffic to `default_handler` (or are dropped)."""

    def __init__(
        self,
        algo: Algorithm,
        my_id: int,
        peers: Dict[int, Tuple[str, int]],
        transport: HostTransport,
        instance_id: int = 1,
        timeout_ms: int = 200,
        seed: int = 0,
        default_handler=None,
        foreign=None,
        prefill: Optional[Dict[int, Dict[int, Any]]] = None,
    ):
        self.algo = algo
        self.id = my_id
        self.n = len(peers)
        self.transport = transport
        self.instance_id = instance_id & 0xFFFF
        self.timeout_ms = timeout_ms
        self.seed = seed
        self.default_handler = default_handler
        # sink for NORMAL messages of other instances: a consecutive-
        # instance driver (PerfTest2's loop) stashes them and prefills the
        # next runner — without it, start-skew between replicas drops the
        # fast node's round-0 send and the slow node burns a full timeout
        # every instance (the reference solves this with defaultHandler's
        # lazy join, PerfTest2.scala:72-110)
        self.foreign = foreign
        for pid, (host, port) in peers.items():
            if pid != my_id:
                transport.add_peer(pid, host, port)
        # round -> {sender: payload}; early messages wait here
        self._pending: Dict[int, Dict[int, Any]] = dict(prefill or {})

    def _ctx(self, r: int) -> RoundCtx:
        """Context for eager hooks (expected_nbr_messages).  No rng: the
        per-round key is derived INSIDE the jitted round functions — two
        eager fold-ins per round would dominate host-round latency."""
        return RoundCtx(id=np.int32(self.id), n=self.n, r=np.int32(r))

    def _round_fns(self, rnd):
        """Jitted (pre+send, update) for one Round at this group size —
        eager per-op dispatch (including the per-round PRNG fold-in)
        dominates host-round latency otherwise.  The cache lives ON the
        round object so every instance over the same Algorithm (the
        PerfTest2 loop) reuses the compiled pair."""
        cached = getattr(rnd, "_host_jit", None)
        if cached is not None and cached[0] == self.n:
            return cached[1], cached[2]
        n = self.n

        def mk_ctx(rr, sid, seed):
            rng = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(seed), rr), sid
            )
            return RoundCtx(id=sid, n=n, r=rr, rng=rng)

        def f_send(rr, sid, seed, state):
            ctx = mk_ctx(rr, sid, seed)
            st = rnd.pre(ctx, state)
            spec = rnd.send(ctx, st)
            return st, spec.payload, spec.dest_mask

        def f_update(rr, sid, seed, state, vals, mask):
            ctx = mk_ctx(rr, sid, seed)
            st2 = rnd.update(ctx, state, Mailbox(vals, mask))
            return st2, ctx._exit

        fns = (jax.jit(f_send), jax.jit(f_update))
        rnd._host_jit = (n, *fns)
        return fns

    def run(self, io: Any, max_rounds: int = 64) -> HostResult:
        algo = self.algo
        state = algo.make_init_state(self._ctx(0), io)
        rounds = algo.rounds
        exited = False
        r = 0
        while r < max_rounds and not exited:
            rnd = rounds[r % len(rounds)]
            rr, sid = np.int32(r), np.int32(self.id)
            seed = np.uint32(self.seed)
            f_send, f_update = self._round_fns(rnd)
            state, payload, dest_mask = f_send(rr, sid, seed, state)
            dest = np.asarray(dest_mask)
            payload_np = jax.tree_util.tree_map(np.asarray, payload)
            wire = pickle.dumps(payload_np)
            for d in range(self.n):
                if d == self.id or not dest[d]:
                    continue
                self.transport.send(
                    d, Tag(instance=self.instance_id, round=r), wire
                )

            # -- accumulate (InstanceHandler.scala:197-245) ---------------
            inbox: Dict[int, Any] = dict(self._pending.pop(r, {}))
            if dest[self.id]:
                inbox[self.id] = payload_np  # self-delivery off the wire
            deadline = _time.monotonic() + self.timeout_ms / 1000.0
            expected = rnd.expected_nbr_messages(self._ctx(r), state)
            while len(inbox) < min(self.n, int(expected)):
                left_ms = int((deadline - _time.monotonic()) * 1000)
                if left_ms <= 0:
                    break
                got = self.transport.recv(left_ms)
                if got is None:
                    break
                sender, tag, raw = got
                if tag.instance != self.instance_id or tag.flag != FLAG_NORMAL:
                    if tag.flag == FLAG_NORMAL and self.foreign is not None:
                        self.foreign(sender, tag,
                                     pickle.loads(raw) if raw else None)
                    elif self.default_handler is not None:
                        self.default_handler(Message(
                            sender=sender, tag=tag,
                            payload=pickle.loads(raw) if raw else None,
                        ))
                    continue
                if tag.round < r:
                    continue  # late: the round is communication-closed
                payload = pickle.loads(raw)
                if tag.round > r:
                    self._pending.setdefault(tag.round, {})[sender] = payload
                    continue
                inbox[sender] = payload

            # -- update ---------------------------------------------------
            mbox = self._mailbox(inbox, payload_np)
            state, exit_flag = f_update(
                rr, sid, seed, state, mbox.values, mbox.mask,
            )
            exited = bool(np.asarray(exit_flag))
            log.debug("node %d round %d: heard %d/%d%s", self.id, r,
                      len(inbox), self.n, " exit" if exited else "")
            r += 1

        decided = bool(np.asarray(algo.decided(state)))
        decision = np.asarray(algo.decision(state))
        return HostResult(
            state=state, decided=decided, decision=decision, rounds_run=r,
            dropped_messages=self.transport.dropped,
        )

    def _mailbox(self, inbox: Dict[int, Any], like: Any) -> Mailbox:
        """Stack per-sender payloads into the [n, ...] arrays + mask the
        Round DSL's update expects (the dense-mailbox view of the wire)."""
        leaves_like, treedef = jax.tree_util.tree_flatten(like)
        stacked = [
            np.zeros((self.n,) + np.shape(l), dtype=np.asarray(l).dtype)
            for l in leaves_like
        ]
        mask = np.zeros((self.n,), dtype=bool)
        for sender, payload in inbox.items():
            leaves = jax.tree_util.tree_flatten(payload)[0]
            for slot, leaf in zip(stacked, leaves):
                slot[sender] = leaf
            mask[sender] = True
        values = jax.tree_util.tree_unflatten(treedef, stacked)
        return Mailbox(values, np.asarray(mask))
