"""Host multi-process round execution: one OS process per replica.

This is the deployment shape of the reference — n JVMs, one per ProcessID,
full TCP mesh, the InstanceHandler loop driving init → send → accumulate →
update per round (InstanceHandler.scala:164-258) — rebuilt on the native
transport (native/transport.cpp via runtime/transport.py).

The SAME algorithm classes the TPU engine runs (core/algorithm.py Round
DSL) run here unchanged: their send/update are per-lane pure functions, so
one process evaluates them for its own lane on CPU scalars while the
simulator vmaps them over [scenario, lane] axes on the chip.  That is the
framework's deployment story: simulate at scale on TPU, deploy the
identical protocol code process-per-replica.

Round discipline (benign model, full Progress semantics —
InstanceHandler.scala:164-353):
  * send: evaluate SendSpec, unicast payload bytes per selected dest
    (self-delivery short-circuits the wire, Round.scala:114-117);
  * accumulate: honor the round's Progress policy (core/progress.py):
      - Timeout(ms): block until goAhead or the deadline; STRICT additionally
        refuses round-skew catch-up until the deadline;
      - WaitForMessage: no deadline — only goAhead (or, non-strict,
        catch-up) ends the round;
      - Sync(k): block until k processes are observed at >= this round
        (the benign form of the byzantine synchronizer barrier,
        InstanceHandler.scala:277-287);
      - GoAhead: the round ends after delivering pending messages.
    goAhead = expected_nbr_messages reached (plain rounds,
    Round.scala:60-66) or the per-receive go_ahead probe (FoldRound) —
    the fine-grained control LastVotingEvent uses;
  * benign catch-up (InstanceHandler.scala:289-301): the max round observed
    from any peer pulls this replica forward — skewed rounds fast-forward
    one at a time (send, deliver pending, update with didTimeout) without
    burning their timeouts;
  * early messages for future rounds are buffered, late ones dropped
    (the pendingMessages priority queue role, InstanceHandler.scala:68-72);
  * update: fold the mailbox; `exitAtEndOfRound` ends the run.

Deviation from the reference: a WaitForMessage/Sync round that makes no
progress for `wait_cap_ms` (default 30 s) is force-timed-out with a warning
— the reference blocks forever (buffer.take()), which an unattended
deployment of THIS framework must not.

Payloads cross the wire in the binary codec (runtime/codec.py — the Kryo
registered-class-codec role; same trust model as the reference: replicas
deserialize only from their own group, and the tagged pickle fallback
stays behind the restricted unpickler).  The send path encodes ONCE per
round into a pooled scratch, coalesces per-destination frames into
FLAG_BATCH containers flushed at the round boundary, and the receive
path drains every queued frame in one native call; the mailbox is
assembled IN PLACE into preallocated [n, ...] arrays (_RoundMailbox).
``HostRunner(wire="pickle")`` keeps the seed path alive as the A/B
baseline (apps/perf_ab.py).
"""

from __future__ import annotations

import collections
import dataclasses
import pickle
import queue as _queue
import threading
import time as _time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.progress import Progress
from round_tpu.core.rounds import Round, RoundCtx
from round_tpu.obs.metrics import METRICS, MS_BUCKETS
from round_tpu.obs.trace import TRACE
from round_tpu.ops.mailbox import Mailbox
from round_tpu.runtime import codec
from round_tpu.runtime.log import get_logger
from round_tpu.runtime.oob import (
    FLAG_DECISION, FLAG_NACK, FLAG_NORMAL, FLAG_SNAP, FLAG_VIEW, Message,
    Tag,
)
from round_tpu.runtime.transport import HostTransport, RoundPump

log = get_logger("host")

# unified metrics (obs/metrics.py; names in docs/OBSERVABILITY.md).  The
# instruments are module-level so the per-event cost is one lock-guarded
# add — no registry lookup on the hot path.
_C_ROUNDS = METRICS.counter("host.rounds")
_C_TIMEOUTS = METRICS.counter("host.timeouts")
_C_SENDS = METRICS.counter("host.sends")
_C_RECVS = METRICS.counter("host.recvs")
_C_MALFORMED = METRICS.counter("host.malformed")
_C_DECISIONS = METRICS.counter("host.decisions")
_C_OOB = METRICS.counter("host.oob_decisions")
_C_REPLIES = METRICS.counter("host.decision_replies")
_C_CATCHUP = METRICS.counter("host.catch_ups")
# overload vocabulary shared with runtime/lanes.py (same name = same
# instrument): NACKs observed from overloaded peers — purely diagnostic,
# the protocol's own retransmission is the retry
_C_NACKS_SEEN = METRICS.counter("overload.nacks_seen")
_H_ROUND_MS = METRICS.histogram("host.round_ms", MS_BUCKETS, unit="ms")
_G_DEADLINE = METRICS.gauge("host.deadline_ms")
_C_MUX_ROUTED = METRICS.counter("mux.routed")
_C_MUX_STASHED = METRICS.counter("mux.stashed")

# serializes jit-trio builds so thread-mode replicas sharing an Algorithm
# compile each round class once (see HostRunner._round_fns)
_JIT_BUILD_LOCK = threading.Lock()

# queue sentinel broadcast by InstanceMux._loop when the router thread
# dies: endpoints must RAISE, not starve into round timeouts (ADVICE.md
# round-5 finding)
_ROUTER_DOWN = object()


@dataclasses.dataclass
class HostResult:
    state: Any
    decided: bool
    decision: Any
    rounds_run: int
    dropped_messages: int
    # wire messages discarded as garbage: undeserializable payloads,
    # out-of-range sender ids, wrong payload structure.  The reference
    # swallows deserialization errors and keeps running when byzantine
    # replicas are configured (InstanceHandler.scala:392-399); this runner
    # ALWAYS tolerates them — one garbage datagram on the unauthenticated
    # socket must never kill a replica.
    malformed_messages: int = 0
    # rounds that ended by deadline expiry rather than goAhead — the
    # throughput diagnostic (every one burns a full round timeout)
    timeouts: int = 0
    # the deadline (ms) each timeout-governed round actually waited on —
    # with an AdaptiveTimeout this is the convergence trajectory (starts
    # at the backoff cap, shrinks toward the observed round latency);
    # with a fixed timeout it is flat
    timeout_trajectory: List[int] = dataclasses.field(default_factory=list)
    # the instance was INTERRUPTED by a view move (runtime/view.py): the
    # ViewManager adopted a newer view (or discovered our removal) while
    # this instance ran over the old wire — the caller re-enters under the
    # new view instead of trusting a decision reached across the boundary
    stale_view: bool = False


class AdaptiveTimeout:
    """EWMA round-latency estimator with exponential backoff, jitter and
    a cap — the adaptive replacement for a fixed `timeout_ms` (the
    reference drives InstanceHandler deadlines from a static
    RuntimeOptions.timeout; an unattended deployment needs the deadline
    to TRACK the wire).

    Discipline:
      * starts at `cap_ms` (pessimistic: a fresh replica knows nothing
        about the wire, and a too-short first deadline burns rounds);
      * every round that completes by goAhead feeds its latency into an
        EWMA; the working deadline converges to `margin` x EWMA, floored
        and capped;
      * every round that EXPIRES backs the deadline off exponentially
        (`backoff` x current, capped) — loss and stalls push it up fast;
      * deterministic seeded jitter (±`jitter` fraction, murmur3 over the
        observation counter) desynchronizes replicas so their deadlines
        do not fire in lockstep.

    One instance may be shared across consecutive/concurrent instances of
    a replica (the host loops do): the estimator models the WIRE, which
    does not reset between consensus instances.  Thread-safety relies on
    the GIL (float stores); races only jitter the estimate."""

    def __init__(self, cap_ms: int = 2000, floor_ms: int = 10,
                 alpha: float = 0.3, margin: float = 3.0,
                 backoff: float = 2.0, jitter: float = 0.1, seed: int = 0):
        if not 0 < alpha <= 1:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if not 0 < floor_ms <= cap_ms:
            raise ValueError(
                f"need 0 < floor_ms <= cap_ms, got {floor_ms}, {cap_ms}")
        self.cap_ms, self.floor_ms = cap_ms, floor_ms
        self.alpha, self.margin = alpha, margin
        self.backoff, self.jitter, self.seed = backoff, jitter, seed
        self._ewma: Optional[float] = None
        self._current = float(cap_ms)
        self._obs = 0

    def current_ms(self) -> int:
        """The deadline to use for the next timeout-governed round."""
        return max(1, int(round(self._current)))

    @property
    def ewma_ms(self) -> Optional[float]:
        return self._ewma

    def observe(self, latency_ms: Optional[float], expired: bool) -> None:
        """Feed one round outcome: its wall latency when it completed by
        goAhead (expired=False), or a deadline expiry (expired=True,
        latency ignored — an expired round's wall time measures the
        deadline, not the wire)."""
        from round_tpu.engine.scenarios import mix32_host

        self._obs += 1
        if expired:
            target = self._current * self.backoff
        else:
            if latency_ms is None:
                return
            self._ewma = (latency_ms if self._ewma is None else
                          self.alpha * latency_ms
                          + (1.0 - self.alpha) * self._ewma)
            target = self.margin * self._ewma
        if self.jitter > 0:
            u = mix32_host(self._obs * 0x9E3779B9 + self.seed)
            frac = ((u & 0xFFFF) / 0xFFFF * 2.0 - 1.0) * self.jitter
            target *= 1.0 + frac
        self._current = min(max(target, float(self.floor_ms)),
                            float(self.cap_ms))


def _schedule_value(value_schedule: str, base_value: int, my_id: int,
                    inst: int) -> int:
    """The deterministic per-instance proposal schedule of the host loops.

    "mixed" (default, the PerfTest2 shape): (base + id·7 + inst) mod 5 —
    replicas propose DISTINCT values, so agreement is non-trivial but the
    decided value is fault-schedule-dependent.  "uniform": (base + inst)
    mod 5 for every replica — by validity the decision is then invariant
    under ANY fault schedule, which is what lets the chaos harness diff a
    faulty run's decision log byte-for-byte against a clean run's."""
    if value_schedule == "uniform":
        return (base_value + inst) % 5
    if value_schedule != "mixed":
        raise ValueError(
            f"value_schedule must be 'mixed' or 'uniform', "
            f"got {value_schedule!r}")
    return (base_value + my_id * 7 + inst) % 5


def instance_io(algo: Algorithm, value: int) -> Dict[str, Any]:
    """The io pytree for one instance's scheduled proposal ``value``.

    Scalar-domain algorithms get the PerfTest2 shape ({"initial_value":
    int32}); a byte-payload algorithm (models/lastvoting.LastVotingBytes,
    detected by its ``payload_bytes`` attribute) gets a deterministic
    uint8[B] vector expanded from the value — distinct values map to
    distinct vectors (agreement stays non-trivial under the "mixed"
    schedule) and equal values to equal vectors (the "uniform" schedule
    stays fault-invariant by validity).  This is what lets the KB-payload
    wire-fraction workload (PERF_MODEL.md) run through the SAME host
    loops as the scalar protocols."""
    b = getattr(algo, "payload_bytes", None)
    if b is None:
        return {"initial_value": np.int32(value)}
    vec = ((np.arange(b, dtype=np.int64) * 131 + value * 31 + 7) % 256)
    return {"initial_value": vec.astype(np.uint8)}


def decision_scalar(decision) -> int:
    """Collapse a decision to the int the decision logs store: scalar
    decisions pass through unchanged (the seed behavior); a VECTOR
    decision (LastVotingBytes) becomes a 7-byte blake2s digest — equal
    vectors hash equal across replicas, and the digest fits the
    checkpoint's int64 array with the _UNDECIDED sentinel unreachable.
    Replies to laggards must ship the RAW decision, not this digest
    (callers keep the raw array beside the log for that)."""
    arr = np.asarray(decision)
    if arr.ndim == 0:
        return int(arr)
    import hashlib

    return int.from_bytes(
        hashlib.blake2s(arr.tobytes(), digest_size=7).digest(), "big")


def _try_send_decision(transport, replied: Dict[Tuple[int, int], float],
                       sender: int, instance: int, decision,
                       enc_cache: Optional[Dict[int, bytes]] = None) -> bool:
    """THE TooLate / trySendDecision reply (PerfTest.scala:40-60), shared
    by the sequential loop's foreign sink and the pipelined mux: answer a
    completed instance's late traffic with its decision, rate-limited per
    (sender, instance) — the reply itself can drop on UDP, so the
    laggard's next retransmission re-arms it.  True iff a reply actually
    went out (rate-limited/undecided calls return False, so reply
    accounting counts wire sends, not answerable packets).

    ``enc_cache`` ({instance: wire bytes}) makes the encode once-per-
    instance: without it every laggard probe — and every DESTINATION peer
    in the linger loop — re-serialized the same decision payload (the
    per-peer re-encode audit of this module; see also ViewManager.
    reply_view)."""
    if decision is None:
        return False
    now = _time.monotonic()
    if now - replied.get((sender, instance), -1.0) <= 0.25:
        return False
    replied[(sender, instance)] = now
    wire = enc_cache.get(instance) if enc_cache is not None else None
    if wire is None:
        wire = codec.encode(np.asarray(decision))
        if enc_cache is not None:
            enc_cache[instance] = wire
    transport.send(sender, Tag(instance=instance, flag=FLAG_DECISION), wire)
    _C_REPLIES.inc()
    if TRACE.enabled:
        TRACE.emit("decision_reply", node=getattr(transport, "id", None),
                   inst=instance, dst=sender)
    return True


class MuxEndpoint:
    """One instance's view of the shared transport: sends pass through,
    receives come from the instance's routed queue."""

    def __init__(self, mux: "InstanceMux", instance_id: int):
        self._mux = mux
        self._q = mux._queues[instance_id & 0xFFFF]

    def add_peer(self, pid, host, port):
        self._mux.transport.add_peer(pid, host, port)

    def send(self, dest, tag, payload):
        return self._mux.transport.send(dest, tag, payload)

    def recv(self, timeout_ms: int):
        try:
            if timeout_ms <= 0:
                got = self._q.get_nowait()
            else:
                got = self._q.get(timeout=timeout_ms / 1000.0)
        except _queue.Empty:
            return None
        if got is _ROUTER_DOWN:
            # re-arm for any later recv on this endpoint, then surface the
            # router failure instead of starving into None decisions
            self._q.put(_ROUTER_DOWN)
            raise RuntimeError(
                "InstanceMux router thread died"
            ) from self._mux.failure
        return got

    def recv_many(self, timeout_ms: int):
        """Drain every routed frame currently queued (the HostRunner
        batched-drain surface over a mux queue)."""
        out = []
        got = self.recv(timeout_ms)
        while got is not None:
            out.append(got)
            got = self.recv(0)
        return out

    def send_buffered(self, dest, tag, payload):
        t = self._mux.transport
        f = getattr(t, "send_buffered", None)
        if f is None:  # bare test doubles: degrade to a direct send
            return t.send(dest, tag, bytes(payload))
        return f(dest, tag, payload)

    def flush(self, to=None):
        f = getattr(self._mux.transport, "flush", None)
        return 0 if f is None else f(to)

    @property
    def dropped(self):
        return self._mux.transport.dropped


class InstanceMux:
    """Tag-routed demultiplexer over ONE HostTransport — the host-side
    InstanceDispatcher (InstanceDispatcher.scala:9-90): a single recv-loop
    thread routes packets to per-instance endpoints, so `rate` instances
    run CONCURRENTLY over one socket mesh (the reference's in-flight
    PerfTest2 rate / processPool shape; the sequential loop runs them one
    at a time).

    Routing rules (the dispatcher + defaultHandler split):
      * a registered instance's traffic → its queue (HostRunner consumes
        through a MuxEndpoint facade).  Routing is by TAG HEADER PEEK
        only — payload bytes are never decoded here (they stay raw
        memoryviews from the transport's batched drain until the owning
        runner's _loads), and a whole drain is routed under one lock
        acquisition;
      * NORMAL traffic for a COMPLETED instance → rate-limited
        FLAG_DECISION reply with that instance's decision (the TooLate /
        trySendDecision path, PerfTest.scala:40-60);
      * NORMAL traffic for a FUTURE instance → stashed raw and replayed
        into its queue at register time (the lazy-join role);
      * anything else is dropped (the reference's unknown-instance drop).
    """

    _STASH_CAP = 4096  # total stashed packets: when full the OLDEST entry
    # is evicted FIFO, so garbage tagged with never-registering instance
    # ids ages out instead of permanently exhausting the stash (the
    # unauthenticated-socket hardening discipline of this module)

    def __init__(self, transport: HostTransport):
        self.transport = transport
        self._lock = threading.Lock()
        self._queues: Dict[int, Any] = {}
        # native round pump (run_instance_loop_pipelined pump mode): the
        # router stays the shared-inbox drainer, but a frame routed to a
        # lane-bound instance's queue must WAKE that lane's runner out of
        # rt_pump_wait_lane — rt_pump_poke is that nudge
        self.pump: Optional[RoundPump] = None
        self._lanes: Dict[int, int] = {}   # iid -> pump lane
        self._stash: Dict[int, List[Tuple[int, Tag, bytes]]] = {}
        self._stash_order: collections.deque = collections.deque()
        self._decisions: Dict[int, Optional[np.ndarray]] = {}
        self._replied: Dict[Tuple[int, int], float] = {}
        self._enc_cache: Dict[int, bytes] = {}  # instance -> encoded
        # decision wire bytes (encode once, reply to every laggard/peer
        # with the shared buffer)
        self._stop = False
        # set when the router thread dies on an unexpected exception; every
        # endpoint raises and run_instance_loop_pipelined re-raises
        self.failure: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def register(self, instance_id: int) -> MuxEndpoint:
        iid = instance_id & 0xFFFF
        with self._lock:
            q = _queue.Queue()
            self._queues[iid] = q
            for got in self._stash.pop(iid, []):
                q.put(got)
            # purge the replayed instance from the eviction order too, or
            # its stale entries would inflate the cap check and evict LIVE
            # buckets long before the stash is actually full
            self._stash_order = collections.deque(
                x for x in self._stash_order if x != iid)
            if self.failure is not None:
                # the router is already dead: a fresh endpoint must fail
                # fast, not wait out its whole run on an unserviced queue
                q.put(_ROUTER_DOWN)
        return MuxEndpoint(self, iid)

    def bind_lane(self, instance_id: int, lane: int) -> None:
        """Route rt_pump_poke nudges for this instance to ``lane``."""
        with self._lock:
            self._lanes[instance_id & 0xFFFF] = lane

    def unbind_lane(self, instance_id: int) -> None:
        with self._lock:
            self._lanes.pop(instance_id & 0xFFFF, None)

    def complete(self, instance_id: int,
                 decision: Optional[np.ndarray]) -> None:
        iid = instance_id & 0xFFFF
        with self._lock:
            self._queues.pop(iid, None)
            self._lanes.pop(iid, None)
            self._decisions[iid] = decision

    def close(self) -> None:
        self._stop = True
        self._thread.join(timeout=5)

    def _loop(self) -> None:
        try:
            self._loop_body()
        except BaseException as e:  # noqa: BLE001 — a dying router thread
            # must not be silent: record the failure and wake every
            # endpoint so in-flight instances raise instead of starving
            # into timeout-by-timeout None decisions (ADVICE.md round-5)
            self.failure = e
            log.error("InstanceMux router thread died: %r", e)
            METRICS.counter("mux.router_deaths").inc()
            if TRACE.enabled:
                TRACE.emit("mux_router_died",
                           node=getattr(self.transport, "id", None),
                           error=repr(e))
            with self._lock:
                for q in self._queues.values():
                    q.put(_ROUTER_DOWN)

    def _loop_body(self) -> None:
        # batched drain when the transport offers it: every queued frame
        # in one native call, routed (by tag header peek — payloads are
        # never decoded here) under ONE lock acquisition per drain instead
        # of one per packet
        recv_many = getattr(self.transport, "recv_many", None)
        while not self._stop:
            if recv_many is not None:
                got_list = recv_many(50)
            else:
                got = self.transport.recv(50)
                got_list = [got] if got is not None else []
            if not got_list:
                continue
            replies: List[Tuple[int, int, Any]] = []
            pokes: set = set()
            with self._lock:
                # routing decision and stash append under ONE acquisition:
                # a lookup in one critical section + append in another
                # would race register() replaying the stash in between,
                # silently losing the packet
                for got in got_list:
                    sender, tag, _raw = got
                    iid = tag.instance
                    q = self._queues.get(iid)
                    if q is not None:
                        q.put(got)
                        _C_MUX_ROUTED.inc()
                        lane = self._lanes.get(iid)
                        if lane is not None:
                            pokes.add(lane)
                    elif iid in self._decisions:
                        if tag.flag == FLAG_NORMAL:
                            replies.append(
                                (sender, iid, self._decisions[iid]))
                    elif tag.flag == FLAG_NORMAL:
                        while len(self._stash_order) >= self._STASH_CAP:
                            old = self._stash_order.popleft()
                            bucket = self._stash.get(old)
                            if bucket:
                                bucket.pop(0)
                                if not bucket:
                                    del self._stash[old]
                        if not isinstance(got[2], bytes):
                            # stash entries are LONG-LIVED (until the
                            # instance registers); a memoryview here would
                            # pin its whole drain copy — own the bytes
                            got = (got[0], got[1], bytes(got[2]))
                        self._stash.setdefault(iid, []).append(got)
                        self._stash_order.append(iid)
                        _C_MUX_STASHED.inc()
            pump = self.pump
            if pump is not None:
                for lane in pokes:
                    pump.poke(lane)
            for sender, iid, reply_with in replies:
                if reply_with is not None:
                    _try_send_decision(self.transport, self._replied,
                                       sender, iid, reply_with,
                                       enc_cache=self._enc_cache)


def run_instance_loop_pipelined(
    algo: Algorithm,
    my_id: int,
    peers: Dict[int, Tuple[str, int]],
    transport: HostTransport,
    instances: int,
    rate: int = 8,
    timeout_ms: int = 300,
    seed: int = 0,
    base_value: int = 0,
    max_rounds: int = 32,
    stats_out: Optional[Dict[str, int]] = None,
    nbr_byzantine: int = 0,
    value_schedule: str = "mixed",
    adaptive: Optional["AdaptiveTimeout"] = None,
    wire: str = "binary",
    pump: bool = True,
) -> List[Optional[int]]:
    """The PerfTest2 loop with `rate` instances IN FLIGHT (the reference's
    `-rt` rate + InstanceDispatcher shape): a sliding window of concurrent
    HostRunners over one InstanceMux.  An instance burning a round
    timeout no longer stalls the pipeline — the win is largest on lossy
    transports, where the sequential loop serializes every burned
    deadline.  Same value schedule and seeds as run_instance_loop, so the
    two modes are cross-checkable.

    With ``pump`` (and a pump-capable binary-wire transport), each
    in-flight instance occupies one NATIVE pump lane (_make_mux_pump):
    its frames are parsed/ingested in the C event loop, its runner blocks
    in rt_pump_wait_lane, and the router thread — still the shared-inbox
    drainer for out-of-band traffic — nudges the lane with rt_pump_poke
    when it routes to that instance's endpoint queue.  ``pump=False``
    pins the Python-pump baseline (the A/B arm of tests/test_pump.py)."""
    if rate < 1:
        raise ValueError(f"rate must be >= 1, got {rate}")
    import os as _os

    mux = InstanceMux(transport)
    pump_states = None
    if (pump and wire == "binary" and not TRACE.enabled
            and _os.environ.get("ROUND_TPU_PUMP", "1") != "0"):
        pump_states = _make_mux_pump(transport, algo, my_id, len(peers),
                                     nbr_byzantine, rate)
    if pump_states is not None:
        mux.pump = pump_states[0].pump
    decisions: List[Optional[int]] = [None] * instances
    errors: List[Tuple[int, BaseException]] = []
    stats_lock = threading.Lock()
    sem = threading.Semaphore(rate)
    lane_pool: collections.deque = collections.deque(range(rate))
    threads: List[threading.Thread] = []

    def worker(inst: int, ep: MuxEndpoint,
               ps: Optional[_RunnerPumpState]) -> None:
        try:
            runner = HostRunner(
                algo, my_id, peers, ep, instance_id=inst,
                timeout_ms=timeout_ms, seed=seed + inst,
                nbr_byzantine=nbr_byzantine, adaptive=adaptive,
                wire=wire, pump_state=ps,
            )
            value = _schedule_value(value_schedule, base_value, my_id, inst)
            res = runner.run(instance_io(algo, value),
                             max_rounds=max_rounds)
            if ps is not None:
                # retire the lane BEFORE complete(): frames for this
                # instance flow to the inbox again, where the router's
                # TooLate decision-reply path answers them
                ps.pump.close_lane(ps.lane)
                mux.unbind_lane(inst)
            d = decision_scalar(res.decision) if res.decided else None
            decisions[inst - 1] = d
            mux.complete(
                inst, np.asarray(res.decision) if res.decided else None)
            if stats_out is not None:
                with stats_lock:
                    for k, v in (("timeouts", res.timeouts),
                                 ("rounds_run", res.rounds_run),
                                 ("malformed", res.malformed_messages)):
                        stats_out[k] = stats_out.get(k, 0) + v
                    stats_out.setdefault("timeout_trajectory", []).extend(
                        res.timeout_trajectory)
        except BaseException as e:  # noqa: BLE001 — a worker-thread error
            # must FAIL the run like the sequential path's would, not
            # silently become a None decision; complete() so peer
            # retransmissions stop queueing against a dead instance
            with stats_lock:
                errors.append((inst, e))
            mux.complete(inst, None)
        finally:
            if ps is not None:
                ps.pump.close_lane(ps.lane)   # idempotent
                with stats_lock:
                    lane_pool.append(ps.lane)
            sem.release()

    try:
        for inst in range(1, instances + 1):
            sem.acquire()
            ps = None
            if pump_states is not None:
                with stats_lock:
                    # the semaphore bounds in-flight workers by rate, and
                    # every worker returns its lane before releasing, so
                    # the pool is never empty here
                    ps = pump_states[lane_pool.popleft()]
                mux.bind_lane(inst, ps.lane)
            # register BEFORE the runner exists: a fast peer's first
            # message may arrive the instant our previous one completes
            ep = mux.register(inst)
            t = threading.Thread(target=worker, args=(inst, ep, ps))
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
    finally:
        mux.close()
        if pump_states is not None:
            mux.pump = None
            pump_states[0].close()  # banks pump stats + detaches once
    if mux.failure is not None:
        # the router thread died: every None in `decisions` is starvation,
        # not a protocol outcome — fail the run (ADVICE.md round-5)
        raise RuntimeError(
            "InstanceMux router thread died mid-run"
        ) from mux.failure
    if errors:
        inst, err = errors[0]
        raise RuntimeError(
            f"{len(errors)} pipelined instance(s) failed, first: "
            f"instance {inst}"
        ) from err
    return decisions


def run_instance_loop(
    algo: Algorithm,
    my_id: int,
    peers: Dict[int, Tuple[str, int]],
    transport: HostTransport,
    instances: int,
    timeout_ms: int = 300,
    seed: int = 0,
    base_value: int = 0,
    max_rounds: int = 32,
    stats_out: Optional[Dict[str, int]] = None,
    send_when_catching_up: bool = True,
    delay_first_send_ms: int = -1,
    nbr_byzantine: int = 0,
    value_schedule: str = "mixed",
    adaptive: Optional[AdaptiveTimeout] = None,
    checkpoint_dir: Optional[str] = None,
    view=None,
    view_schedule: Optional[Dict[int, Tuple[int, int]]] = None,
    wire: str = "binary",
    pump: bool = True,
    health=None,
    rv=None,
    snap=None,
) -> List[Optional[int]]:
    """The PerfTest2 loop (PerfTest2.scala:19-110): `instances` consecutive
    consensus instances over one transport, with start-skew stashing —
    NORMAL messages tagged with a FUTURE instance are buffered and
    prefilled into that instance's runner (the defaultHandler lazy-join
    role); traffic for completed instances is dropped (TooLate).  Initial
    values follow the deterministic schedule (base_value + id·7 + inst)
    mod 5 (or the fault-invariant "uniform" schedule — _schedule_value),
    so runs are reproducible across replicas and modes.

    With `checkpoint_dir`, the decision list is DURABLY checkpointed
    after every instance (runtime/checkpoint.py atomic npz + manifest +
    decision-log TSV), and a fresh call over an existing checkpoint
    RESUMES: restored instances are not re-run, and the first live
    instance catches up over the wire via the peers' completed-instance
    FLAG_DECISION replies (the lazy-join/decision-replay machinery).
    This is the crash-restart story: SIGKILL a replica mid-run, start it
    again with the same arguments, and its final decision log must be
    byte-identical to a never-crashed run (tests/test_chaos.py).

    With a ``view`` (runtime/view.py ViewManager), every instance runs
    over the view's CURRENT group (pid + peer table re-read each
    instance), and ``view_schedule`` — {data instance -> (kind, arg)} —
    makes this replica propose that membership op by consensus right
    after the instance completes (the DynamicMembership.scala:231-245
    flow; all members carry the same script).  Schedule entries whose
    epoch has already been applied (a late joiner handed a post-change
    view at launch) are skipped.  An instance interrupted by a view move
    (stale_view) is re-run on the new wire; a replica that discovers its
    own removal returns its decision log immediately with the remaining
    entries undecided — the CALLER exits it cleanly.

    Returns the per-instance decision log (None where undecided)."""
    stash: Dict[int, Dict[int, Dict[int, Any]]] = {}
    current = {"inst": 0}
    decisions: List[Optional[int]] = []
    # instance -> raw decision array: laggard replies must carry the value
    # a peer can ADOPT — for vector-decision algorithms (LastVotingBytes)
    # the log stores the digest (decision_scalar), which is not adoptable
    raw_decisions: Dict[int, np.ndarray] = {}
    replied: Dict[Tuple[int, int], float] = {}
    enc_cache: Dict[int, bytes] = {}
    start = 1
    if checkpoint_dir is not None:
        from round_tpu.runtime import checkpoint as _ckpt

        if _ckpt.exists(checkpoint_dir):
            like = np.full(instances, _UNDECIDED, dtype=np.int64)
            arr, step, meta = _ckpt.restore(checkpoint_dir, like)
            if (meta.get("kind") != "host-decision-log"
                    or meta.get("instances") != instances
                    or not 0 <= int(step) <= instances):
                raise _ckpt.CheckpointError(
                    f"checkpoint at {checkpoint_dir} is not a host decision "
                    f"log for an {instances}-instance run: meta={meta}, "
                    f"step={step}")
            arr = np.asarray(arr)
            decisions = [None if int(v) == _UNDECIDED else int(v)
                         for v in arr[: int(step)]]
            start = int(step) + 1
            log.info("node %d: resumed %d decided instance(s) from %s, "
                     "continuing at instance %d", my_id,
                     sum(d is not None for d in decisions),
                     checkpoint_dir, start)

    def foreign(sender, tag, payload):
        if tag.instance <= current["inst"]:
            # traffic for a COMPLETED instance: instead of dropping it
            # (TooLate), reply with that instance's decision out-of-band —
            # the lagging replica adopts it and exits instead of burning a
            # timeout (PerfTest.scala:40-60, trySendDecision; essential on
            # UDP where the round-4 decision broadcast can simply drop).
            # RATE-LIMITED, not one-shot: the reply itself can drop on UDP,
            # so the laggard's next retransmission re-arms it
            idx = tag.instance - 1
            if 0 <= idx < len(decisions):
                reply = raw_decisions.get(tag.instance)
                if reply is None and getattr(algo, "payload_bytes",
                                             None) is None:
                    # scalar-decision log values ARE the raw decision
                    # (checkpoint-resumed instances have no raw entry);
                    # a vector algorithm's log holds digests, which a
                    # laggard cannot adopt — better no reply than a
                    # garbage one it must discard every round
                    reply = decisions[idx]
                if reply is not None:
                    _try_send_decision(transport, replied, sender,
                                       tag.instance, reply,
                                       enc_cache=enc_cache)
            return
        stash.setdefault(tag.instance, {}).setdefault(
            tag.round, {})[sender] = payload

    # NATIVE ROUND PUMP (native/transport.cpp): one state for ALL the
    # loop's consecutive runners — class mailboxes registered once, one
    # pump lane re-opened per instance.  The Python pump stays the
    # baseline/fallback: views (epoch guards), the catch-up-send
    # experiment and per-frame tracing all live there, and a transport
    # without the surface (bare doubles, receiver-side chaos families,
    # ROUND_TPU_PUMP=0, stale .so) simply returns None.
    import os as _os

    pump_state = None
    if (pump and wire == "binary" and view is None
            and send_when_catching_up and not TRACE.enabled
            and _os.environ.get("ROUND_TPU_PUMP", "1") != "0"):
        pump_state = _make_runner_pump(transport, algo, my_id,
                                       len(peers), nbr_byzantine)
    # runtime-verification setup (round_tpu/rv): one RvRuntime + monitor
    # program for the whole loop, one HostRv per instance inside the body
    rv_state = None
    if rv is not None:
        from round_tpu.rv.compile import monitor_program
        from round_tpu.rv.dump import RvRuntime

        program = monitor_program(algo, len(peers))
        if program is None:
            log.warning("node %d: rv requested but %s has no decision "
                        "plane to monitor; rv disabled", my_id,
                        type(algo).__name__)
        else:
            rv_state = (RvRuntime(rv, node=my_id, n=len(peers),
                                  seed=seed, max_rounds=max_rounds),
                        program, rv)
    # snapshot setup (round_tpu/snap): ONE SnapDriver for the whole loop
    # — the emitter, the collector's part-cut state and the audit jit
    # cache all outlive any single instance (the pump-state discipline)
    snap_state = None
    if snap is not None:
        from round_tpu.snap.driver import SnapDriver

        snap_state = SnapDriver(
            snap, algo, node=my_id, n=len(peers), seed=seed,
            max_rounds=max_rounds, transport=transport,
            value_schedule=value_schedule, base_value=base_value,
            view=view)
    try:
        return _run_instance_loop_body(
            algo, my_id, peers, transport, instances, timeout_ms, seed,
            base_value, max_rounds, stats_out, send_when_catching_up,
            delay_first_send_ms, nbr_byzantine, value_schedule, adaptive,
            checkpoint_dir, view, view_schedule, wire, pump_state,
            decisions, raw_decisions, replied, enc_cache, stash, current,
            foreign, start, health, rv_state, snap_state)
    finally:
        if rv_state is not None:
            # stats survive an rv-halt (the lane driver's discipline):
            # the exit-3 summary must carry the violation record, not
            # just the artifact path on the exception
            rv_state[0].fill_stats(stats_out)
        if snap_state is not None:
            snap_state.fill_stats(stats_out)
        if pump_state is not None:
            pump_state.close()


def _run_instance_loop_body(
    algo, my_id, peers, transport, instances, timeout_ms, seed,
    base_value, max_rounds, stats_out, send_when_catching_up,
    delay_first_send_ms, nbr_byzantine, value_schedule, adaptive,
    checkpoint_dir, view, view_schedule, wire, pump_state,
    decisions, raw_decisions, replied, enc_cache, stash, current,
    foreign, start, health=None, rv_state=None, snap_state=None,
) -> List[Optional[int]]:
    # ordered view-change schedule: entry i moves the group from epoch i
    # to i+1, so a replica only PROPOSES an entry its own epoch has not
    # yet passed (a late joiner launched with a post-change view skips
    # the entries that produced it)
    sched_order = sorted(view_schedule) if view_schedule else []
    for inst in range(start, instances + 1):
        current["inst"] = inst
        for _attempt in range(4):
            vid, vpeers = my_id, peers
            if view is not None:
                if view.removed:
                    break
                vid, vpeers = view.my_id, view.view.peers()
            inst_rv = None
            if rv_state is not None:
                from round_tpu.rv.compile import (
                    HostRv, schedule_init_values,
                )

                rv_runtime, rv_program, rv_cfg = rv_state
                nn = len(vpeers)
                inst_rv = HostRv(
                    rv_runtime, rv_program, inst,
                    schedule_init_values(algo, nn, value_schedule,
                                         base_value, inst),
                    [_schedule_value(value_schedule, base_value, pid,
                                     inst) for pid in range(nn)],
                    gossip=rv_cfg.gossip)
            runner = HostRunner(
                algo, vid, vpeers, transport, instance_id=inst,
                timeout_ms=timeout_ms, seed=seed + inst,
                foreign=foreign, prefill=stash.pop(inst, None),
                send_when_catching_up=send_when_catching_up,
                # start skew is a per-run experiment: only the first
                # instance is delayed (the reference sleeps at instance
                # start, and the point is skewING the replica, not
                # slowing every instance)
                delay_first_send_ms=(delay_first_send_ms
                                     if inst == 1 else -1),
                nbr_byzantine=nbr_byzantine,
                adaptive=adaptive,
                view=view,
                wire=wire,
                pump_state=pump_state,
                health=health,
                rv=inst_rv,
                snap=snap_state,
            )
            value = _schedule_value(value_schedule, base_value, vid, inst)
            res = runner.run(instance_io(algo, value),
                             max_rounds=max_rounds)
            if view is not None and res.stale_view and not res.decided \
                    and not view.removed:
                # the view moved under this instance: clear the stale
                # latch and re-run it over the NEW wire (bounded retries;
                # epochs advance a handful of times per deployment)
                view.stale = False
                continue
            break
        if view is not None and view.removed:
            # voted out: undecided placeholders for the un-run tail keep
            # the decision-log length schedule-shaped for the harness
            decisions.extend([None] * (instances - len(decisions)))
            break
        if res.decided:
            decisions.append(decision_scalar(res.decision))
            raw_decisions[inst] = np.asarray(res.decision)
        else:
            decisions.append(None)
        if checkpoint_dir is not None:
            _save_decision_checkpoint(checkpoint_dir, decisions, inst,
                                      instances)
        if stats_out is not None:
            # cumulative diagnostics across instances (timeouts is the
            # throughput one: every entry burned a full round deadline)
            for k, v in (("timeouts", res.timeouts),
                         ("rounds_run", res.rounds_run),
                         ("malformed", res.malformed_messages)):
                stats_out[k] = stats_out.get(k, 0) + v
            # concatenated per-round deadlines across instances: with an
            # adaptive estimator this is the convergence trajectory
            stats_out.setdefault("timeout_trajectory", []).extend(
                res.timeout_trajectory)
            if health is not None:
                stats_out["quarantine"] = health.summary()
        if view is not None and view_schedule and inst in view_schedule \
                and view.epoch == sched_order.index(inst):
            # the scripted membership change: consensus on the op over
            # the CURRENT view, applied to the live wire on decision
            # (runtime/view.py).  An undecided outcome leaves the view
            # unchanged — if peers DID decide, their next stamped frames
            # trigger the FLAG_VIEW catch-up and the next instance re-runs
            # on the adopted view.
            from round_tpu.runtime.view import view_instance

            kind, arg = view_schedule[inst]
            view.propose(
                algo, kind, arg, seed=seed, timeout_ms=timeout_ms,
                max_rounds=max_rounds, adaptive=adaptive, foreign=foreign,
                prefill=stash.pop(view_instance(view.epoch), None),
            )
            view.stale = False  # any mid-change staleness was resolved
            # by propose/adopt; the next data instance starts fresh
    if snap_state is not None:
        # end of the schedule: resolve pending part-cuts and audit the
        # tail (a final-cut halt raises from here, the lanes discipline)
        snap_state.flush(force=True)
    # rv stats are banked by run_instance_loop's finally (they must
    # survive an rv-halt raising out of this body)
    return decisions


def serve_decisions(transport, decisions: List[Optional[int]],
                    idle_ms: int = 4000, contact_idle_ms: int = 2000,
                    max_ms: int = 120_000, adoptable: bool = True) -> int:
    """Linger after a completed instance loop, answering peers' NORMAL
    traffic with FLAG_DECISION replies (the trySendDecision machinery)
    until the wire has been idle for `idle_ms` (hard cap `max_ms`).

    The recovery protocol NEEDS this when replicas are short-lived CLI
    processes: a crash-restarted replica catches up from its peers'
    decision replies, but the reference's processes are long-running
    services — ours exit when their own loop ends, and a replica whose
    restart latency exceeds the peers' remaining run time finds nobody
    left to answer (observed as a starved None on the last instance in
    the chaos soak).  Two-phase idle clock: the full `idle_ms` window
    only has to cover the laggard's silent RESTART latency; once the
    laggard is seen working its FINAL instance (it retransmits every
    round and adopts the reply within one), the re-armed window shrinks
    to `contact_idle_ms` so a finished laggard releases this replica
    quickly.  Earlier-instance traffic does NOT shrink the window —
    stale pre-crash packets drained at linger start must not collapse
    the restart window.  Returns the number of replies sent.

    ``adoptable=False`` lingers WITHOUT replying (the idle clock still
    runs): callers whose decision entries are digests rather than raw
    decisions (a vector-decision algorithm's log, decision_scalar) must
    not ship values a laggard's adopt_decision can only discard."""
    replied: Dict[Tuple[int, int], float] = {}
    enc_cache: Dict[int, bytes] = {}
    served = 0
    t_end = _time.monotonic() + max_ms / 1000.0
    window = idle_ms / 1000.0
    deadline = _time.monotonic() + window
    while _time.monotonic() < min(deadline, t_end):
        got = transport.recv(100)
        if got is None:
            continue
        sender, tag, _raw = got
        if (adoptable and tag.flag == FLAG_NORMAL
                and 1 <= tag.instance <= len(decisions)
                and decisions[tag.instance - 1] is not None):
            if _try_send_decision(transport, replied, sender, tag.instance,
                                  decisions[tag.instance - 1],
                                  enc_cache=enc_cache):
                served += 1
            if tag.instance == len(decisions):
                window = min(window, contact_idle_ms / 1000.0)
            deadline = _time.monotonic() + window
    return served


# undecided sentinel in checkpointed decision arrays (decisions are small
# non-negative protocol values; the sentinel is unreachable)
_UNDECIDED = -(1 << 62)


def _save_decision_checkpoint(checkpoint_dir: str,
                              decisions: List[Optional[int]],
                              step: int, instances: int) -> None:
    """Durably record the decision list after an instance completes:
    atomic npz (fixed [instances] int64, _UNDECIDED where undecided) +
    manifest + the canonical decision-log TSV (runtime/decisions.py) —
    a SIGKILL between instances loses at most the in-flight instance,
    which the restarted loop re-runs/recovers over the wire."""
    from round_tpu.runtime import checkpoint as _ckpt
    from round_tpu.runtime.decisions import DecisionLog

    arr = np.full(instances, _UNDECIDED, dtype=np.int64)
    for k, d in enumerate(decisions):
        if d is not None:
            arr[k] = d
    _ckpt.save(
        checkpoint_dir, arr, step=step,
        meta={"kind": "host-decision-log", "instances": instances},
        decisions=DecisionLog.from_values(decisions),
    )


class _RoundMailbox:
    """One round's mailbox, assembled IN PLACE: decoded payloads write
    directly into preallocated ``[n, ...]`` per-round arrays + mask — the
    exact buffers the jitted update consumes — replacing the per-message
    dict insert + per-probe restack of the old path (a FoldRound's
    go-probe used to re-flatten and re-stack the whole inbox on EVERY
    received message).  The arrays are REUSED across rounds (reset zeros
    them), so the steady state allocates nothing.

    ``legacy=True`` keeps the seed behavior byte-for-byte (dict inbox,
    stacked per values_mask call) — the "old path" arm of the wire A/B
    (apps/perf_ab.py).

    A payload that decoded fine but has the WRONG SHAPE for this round
    (tree structure, leaf count, leaf shape/dtype) is byzantine garbage —
    dropped per sender + counted via the runner, never a crash (the
    deserialize-failure tolerance of InstanceHandler.scala:392-399
    extended to the structural layer the codec does not check)."""

    __slots__ = ("runner", "legacy", "n", "treedef", "stacked", "mask",
                 "like", "count_arr", "_sig", "_inbox", "pinned")

    def __init__(self, runner: "HostRunner", legacy: bool):
        self.runner = runner
        self.legacy = legacy
        self.n = runner.n
        # pinned = the native pump holds raw pointers into stacked/mask/
        # count_arr: a signature change (which would REALLOCATE them) is
        # a driver bug, not wire input — fail loudly, never dangle
        self.pinned = False
        self.treedef = None
        self.stacked: List[np.ndarray] = []
        self.mask = np.zeros((self.n,), dtype=bool)
        self.like = None
        # the heard count lives in a shareable int64 cell: the native
        # round pump registers it by pointer and increments it with no
        # GIL held (runtime/transport.py RoundPump.set_class); the Python
        # pump updates the same cell, so `count` reads one source of
        # truth either way
        self.count_arr = np.zeros((1,), dtype=np.int64)
        self._sig = None
        self._inbox: Dict[int, Any] = {}

    @property
    def count(self) -> int:
        return int(self.count_arr[0])

    def reset(self, like: Any) -> None:
        """Arm for a new round whose payload exemplar is ``like`` (the
        just-computed send payload: every peer runs the same round class,
        so its shape IS the mailbox slot shape)."""
        self.like = like
        self.count_arr[0] = 0
        if self.legacy:
            self._inbox = {}
            return
        leaves, treedef = jax.tree_util.tree_flatten(like)
        sig = (treedef, tuple((np.shape(l), np.asarray(l).dtype)
                              for l in leaves))
        if sig != self._sig:
            if self.pinned and self._sig is not None:
                raise RuntimeError(
                    f"payload signature changed under a pump-registered "
                    f"mailbox: {sig} != {self._sig}")
            self._sig = sig
            self.treedef = treedef
            self.stacked = [
                np.zeros((self.n,) + np.shape(l),
                         dtype=np.asarray(l).dtype)
                for l in leaves
            ]
            self.mask = np.zeros((self.n,), dtype=bool)
        else:
            for a in self.stacked:
                a.fill(0)
            self.mask.fill(False)

    def insert(self, sender: int, payload: Any) -> bool:
        """Write one sender's payload into its slot; True when the round's
        heard-set grew (duplicates overwrite, structural garbage drops)."""
        if self.legacy:
            grew = sender not in self._inbox
            self._inbox[sender] = payload
            if grew:
                self.count_arr[0] += 1
            return True  # legacy semantics: structure checked at stacking
        try:
            leaves = jax.tree_util.tree_flatten(payload)[0]
            if len(leaves) != len(self.stacked):
                raise ValueError(
                    f"{len(leaves)} leaves != {len(self.stacked)}")
            for slot, leaf in zip(self.stacked, leaves):
                arr = np.asarray(leaf)
                if arr.shape != slot.shape[1:]:
                    raise ValueError(
                        f"leaf shape {arr.shape} != {slot.shape[1:]}")
                slot[sender] = arr.astype(slot.dtype, casting="same_kind")
        except Exception as e:  # noqa: BLE001 — garbage must not kill us
            r = self.runner
            r.malformed += 1
            _C_MALFORMED.inc()
            if self.mask[sender]:
                self.mask[sender] = False
                self.count_arr[0] -= 1
            for slot in self.stacked:
                slot[sender] = 0  # a half-written slot must not leak
            log.debug("node %d: dropping structurally-malformed payload "
                      "from %d: %s", r.id, sender, e)
            return False
        if not self.mask[sender]:
            self.mask[sender] = True
            self.count_arr[0] += 1
            return True
        return False  # duplicate: overwritten, heard-set unchanged

    def senders(self) -> List[int]:
        if self.legacy:
            return sorted(int(s) for s in self._inbox)
        return [int(i) for i in np.nonzero(self.mask)[0]]

    def values_mask(self):
        """The (values pytree, mask) pair the jitted update/go-probe
        consume.  Binary mode: zero-work (the arrays already ARE the
        mailbox).  Legacy mode: stack now, exactly like the seed did."""
        if self.legacy:
            m = self.runner._mailbox(self._inbox, self.like)
            return m.values, m.mask
        return jax.tree_util.tree_unflatten(self.treedef, self.stacked), \
            self.mask


def pump_coerce_encode(payload, slot_specs, treedef) -> bytes:
    """The SHARED coercion rule of the pump-mode bilingual slow path
    (HostRunner._pump_coerce_insert and LaneDriver._pump_fallback_insert
    must never drift apart — they gate the same equivalence contract):
    flatten, validate leaf count + shapes against ``slot_specs``
    [(shape, dtype), ...], cast same-kind into the slot dtypes (astype
    copies into a fresh C-contiguous array and — unlike
    ascontiguousarray — keeps 0-d payloads 0-d), and re-encode
    canonically.  Raises on any structural mismatch; the caller applies
    its driver's malformed semantics."""
    leaves = jax.tree_util.tree_flatten(payload)[0]
    if len(leaves) != len(slot_specs):
        raise ValueError(f"{len(leaves)} leaves != {len(slot_specs)}")
    coerced = []
    for (shape, dtype), leaf in zip(slot_specs, leaves):
        arr = np.asarray(leaf)
        if arr.shape != shape:
            raise ValueError(f"leaf shape {arr.shape} != {shape}")
        coerced.append(arr.astype(dtype, casting="same_kind", copy=True))
    return codec.encode(jax.tree_util.tree_unflatten(treedef, coerced))


class _RunnerPumpState:
    """Native-pump plumbing SHARED by the consecutive HostRunners of one
    instance loop: the one-lane RoundPump, per-round-class in-place
    mailboxes registered by pointer once per loop (not per instance), and
    the reusable send-wave buffers.  Built by _make_runner_pump; None
    anywhere in the chain keeps the Python pump."""

    __slots__ = ("pump", "send_ok", "boxes", "wave", "entries",
                 "entry_count", "lane", "mux")

    def __init__(self, pump: RoundPump, transport,
                 boxes: Dict[int, "_RoundMailbox"],
                 lane: int = 0, mux: bool = False):
        self.pump = pump
        self.send_ok = bool(getattr(transport, "pump_send_ok", False))
        self.boxes = boxes
        self.wave = bytearray()
        self.entries = bytearray()
        self.entry_count = 0
        # pump lane this runner occupies (the sequential loop always
        # lane 0; the pipelined mux hands each in-flight instance its
        # own lane) and the wait discipline that goes with it: mux=True
        # blocks in rt_pump_wait_lane — the single-waiter rt_pump_wait
        # consumes EVERY lane's reason bits, which is exactly wrong with
        # concurrent runner threads — and treats R_POKE as the router's
        # "your endpoint queue has traffic" nudge.
        self.lane = lane
        self.mux = mux

    def close(self) -> None:
        """Bank the native fast-path stats into the unified metrics
        (pump.* + host.recvs/host.malformed parity) and detach the pump
        so the plain inbox path owns the wire again (serve_decisions,
        next loop)."""
        d = self.pump.bank_metrics()
        if d[0] or d[1]:
            _C_RECVS.inc(int(d[0] + d[1]))
        if d[6]:
            # out-of-range-sender drops the event loop counted natively:
            # host.malformed must read the same whichever pump served
            _C_MALFORMED.inc(int(d[6]))
        self.pump.close()


def _payload_layouts(algo: Algorithm, my_id: int, n: int):
    """Per-round-class (payload exemplar, codec template) for the native
    pump, or None when any class's payload is outside the fixed-layout
    vocabulary.  Shapes come from ``jax.eval_shape`` over the un-jitted
    send (ABSTRACT tracing, ~ms — an eager evaluation here cost 240 ms of
    process startup, half a 40-instance loop's wall): payload shapes are
    a fixed point across rounds (the lax.scan carry contract roundlint
    enforces), and the template's hole CONTENT is never compared, so
    zero-filled exemplars template identically to live traffic.  Cached
    on the round objects (keyed by n), like the jitted trios."""
    layouts = []
    ctx = RoundCtx(id=np.int32(my_id), n=n, r=np.int32(0))
    state0 = None
    for rnd in algo.rounds:
        cached = getattr(rnd, "_pump_layout", None)
        if cached is not None and cached[0] == n:
            if cached[1] is None:
                return None
            layouts.append(cached[1])
            continue
        from round_tpu.engine.executor import make_host_round_fns

        if state0 is None:
            state0 = algo.make_init_state(ctx, instance_io(algo, 0))
        raw_send, _u, _g = make_host_round_fns(rnd, n)
        try:
            _st, payload, _d = jax.eval_shape(
                raw_send, np.int32(0), np.int32(my_id), np.uint32(0),
                state0)
        except Exception:  # noqa: BLE001 — an untraceable send keeps the
            # Python pump (never break a working driver for a fast path)
            rnd._pump_layout = (n, None)
            return None
        exemplar = jax.tree_util.tree_map(
            lambda s: np.zeros(s.shape, dtype=s.dtype), payload)
        lay = codec.array_layout(exemplar)
        if lay is None:
            rnd._pump_layout = (n, None)
            return None
        rnd._pump_layout = (n, (exemplar, lay))
        layouts.append((exemplar, lay))
    return layouts


def _make_runner_pump(transport, algo: Algorithm, my_id: int, n: int,
                      nbr_byzantine: int) -> Optional[_RunnerPumpState]:
    """Attach the native round pump for a sequential instance loop, or
    None (Python-pump fallback) when the transport has no pump surface or
    a round class's payload is outside the fixed-byte-layout vocabulary.
    Each class's payload exemplar is computed EAGERLY (one un-jitted send
    on the init state — payload shapes are a fixed point across rounds,
    the lax.scan carry contract roundlint enforces), its codec template
    derived, and the class mailboxes registered by pointer."""
    mk = getattr(transport, "enable_pump", None)
    if mk is None:
        return None
    layouts = _payload_layouts(algo, my_id, n)
    if layouts is None:
        return None  # outside the fixed-layout vocabulary
    pump = mk(1, n, len(algo.rounds), nbr_byzantine)
    if pump is None:
        return None
    import types as _types

    stub = _types.SimpleNamespace(n=n, id=my_id, malformed=0)
    boxes: Dict[int, _RoundMailbox] = {}
    for c, (exemplar, (tmpl, holes)) in enumerate(layouts):
        box = _RoundMailbox(stub, legacy=False)
        box.reset(exemplar)  # allocate [n, ...] arrays + fix the sig
        for a in box.stacked:
            a.fill(0)
        box.count_arr[0] = 0
        box.pinned = True
        pump.set_class(0, c, tmpl, holes, box.stacked, mask=box.mask,
                       count=box.count_arr, per_lane=False)
        boxes[c] = box
    return _RunnerPumpState(pump, transport, boxes)


def _make_mux_pump(transport, algo: Algorithm, my_id: int, n: int,
                   nbr_byzantine: int, rate: int
                   ) -> Optional[List[_RunnerPumpState]]:
    """The pipelined-mux form of _make_runner_pump: ONE native pump with
    ``rate`` lanes, each in-flight instance occupying its own lane with
    its own per-class mailboxes (registered per (lane, class) — the
    runner's plain ``[n, ...]`` arrays, not the LaneDriver's ``[L, n,
    ...]`` boxes, because each mux runner still thinks per-instance).
    Runners block in rt_pump_wait_lane; the router thread stays the
    shared-inbox drainer and nudges a lane with rt_pump_poke when it
    routes out-of-band traffic to that lane's endpoint queue.  Returns
    one _RunnerPumpState per lane, or None for the Python-pump world."""
    mk = getattr(transport, "enable_pump", None)
    if mk is None:
        return None
    layouts = _payload_layouts(algo, my_id, n)
    if layouts is None:
        return None  # outside the fixed-layout vocabulary
    pump = mk(rate, n, len(algo.rounds), nbr_byzantine)
    if pump is None:
        return None
    import types as _types

    states: List[_RunnerPumpState] = []
    for lane in range(rate):
        stub = _types.SimpleNamespace(n=n, id=my_id, malformed=0)
        boxes: Dict[int, _RoundMailbox] = {}
        for c, (exemplar, (tmpl, holes)) in enumerate(layouts):
            box = _RoundMailbox(stub, legacy=False)
            box.reset(exemplar)
            for a in box.stacked:
                a.fill(0)
            box.count_arr[0] = 0
            box.pinned = True
            pump.set_class(lane, c, tmpl, holes, box.stacked,
                           mask=box.mask, count=box.count_arr,
                           per_lane=False)
            boxes[c] = box
        states.append(_RunnerPumpState(pump, transport, boxes,
                                       lane=lane, mux=True))
    return states


class HostRunner:
    """Run one replica of an Algorithm instance over the host transport.

    `peers` maps every node id (including ours) to (host, port).  The run is
    an instance in the reference sense: `instance_id` tags every packet.
    Foreign-instance NORMAL packets go to the `foreign` sink when one is
    set (the consecutive-instance driver's stash — see __init__), else
    with other-flag traffic to `default_handler` (or are dropped)."""

    def __init__(
        self,
        algo: Algorithm,
        my_id: int,
        peers: Dict[int, Tuple[str, int]],
        transport: HostTransport,
        instance_id: int = 1,
        timeout_ms: int = 200,
        seed: int = 0,
        default_handler=None,
        foreign=None,
        prefill: Optional[Dict[int, Dict[int, Any]]] = None,
        wait_cap_ms: int = 30_000,
        send_when_catching_up: bool = True,
        delay_first_send_ms: int = -1,
        nbr_byzantine: int = 0,
        adaptive: Optional[AdaptiveTimeout] = None,
        view=None,
        wire: str = "binary",
        pump_state: Optional["_RunnerPumpState"] = None,
        health=None,
        rv=None,
        snap=None,
    ):
        self.algo = algo
        self.id = my_id
        self.n = len(peers)
        self.transport = transport
        self.instance_id = instance_id & 0xFFFF
        self.timeout_ms = timeout_ms
        self.wait_cap_ms = wait_cap_ms
        # wire mode: "binary" (the hot path — codec payloads, per-peer
        # frame coalescing with a round-boundary flush, preallocated
        # in-place mailbox) or "pickle" (the seed path, kept as the A/B
        # baseline: pickle.dumps + one native send per message + dict
        # inbox).  RECEIVING is always bilingual — codec.loads routes on
        # the first byte — so mixed-mode clusters interoperate.
        if wire not in ("binary", "pickle"):
            raise ValueError(f"wire must be 'binary' or 'pickle', "
                             f"got {wire!r}")
        self.wire = wire
        self._scratch = codec.Scratch() if wire == "binary" else None
        self._sendb = (getattr(transport, "send_buffered", None)
                       if wire == "binary" else None)
        self._flushfn = (getattr(transport, "flush", None)
                         if wire == "binary" else None)
        if self._flushfn is None:
            # buffering without a flush would queue every hot-path frame
            # forever: the pair resolves TOGETHER or not at all
            self._sendb = None
        self._recv_many = getattr(transport, "recv_many", None)
        self._mbox = _RoundMailbox(self, legacy=(wire == "pickle"))
        # native round pump plumbing (run_instance_loop builds ONE
        # _RunnerPumpState for all its consecutive runners; None = the
        # Python pump below, which stays the A/B baseline and fallback).
        # Views and the catch-up-send experiment keep the Python pump:
        # epoch stamping/guarding and send suppression live there.
        self._ps = (pump_state if wire == "binary" and view is None
                    and send_when_catching_up else None)
        # adaptive round deadline (EWMA + backoff, see AdaptiveTimeout):
        # replaces the fixed timeout_ms for every round that DELEGATES its
        # Progress to the runner (the RuntimeOptions role); rounds that
        # declare their own Progress.timeout keep it — the algorithm knows
        # better than the estimator
        self.adaptive = adaptive
        # catch-up send policy (RuntimeOptions.scala:31-37 +
        # InstanceHandler.scala:169-177): when a round is entered during
        # catch-up (a peer was observed ahead of it), sending its messages
        # is optional — they arrive communication-closed-late at peers that
        # have moved on.  Default ON like the reference.
        self.send_when_catching_up = send_when_catching_up
        # stagger this replica's first send (delayFirstSend, used by the
        # reference's tests to force start skew)
        self.delay_first_send_ms = delay_first_send_ms
        self.suppressed_sends = 0   # rounds whose send was skipped
        # f for the byzantine catch-up rule (InstanceHandler.scala:302-307):
        # with f > 0 the catch-up target is the (f+1)-th highest observed
        # round, so up to f lying peers cannot drag this replica forward
        if not 0 <= nbr_byzantine < self.n:
            raise ValueError(
                f"nbr_byzantine={nbr_byzantine} must be in [0, n={self.n})")
        self.nbr_byzantine = nbr_byzantine
        # view subsystem hook (runtime/view.py ViewManager): stamps the
        # view epoch onto outgoing NORMAL tags, guards incoming ones, and
        # routes FLAG_VIEW catch-ups; None = the epoch-less single-view
        # world every pre-view deployment ran in
        self.view = view
        self.seed = seed
        self.default_handler = default_handler
        # sink for NORMAL messages of other instances: a consecutive-
        # instance driver (PerfTest2's loop) stashes them and prefills the
        # next runner — without it, start-skew between replicas drops the
        # fast node's round-0 send and the slow node burns a full timeout
        # every instance (the reference solves this with defaultHandler's
        # lazy join, PerfTest2.scala:72-110)
        self.foreign = foreign
        # peer quarantine scorer (runtime/health.py PeerHealth): shared
        # across consecutive runners like AdaptiveTimeout — a peer's
        # health does not reset between instances.  None = the polite
        # pre-overload world (zero behavior change).
        self._health = health
        # runtime-verification monitor (round_tpu/rv compile.HostRv, one
        # per instance): the Python-path equivalent of the lane driver's
        # fused monitor term — per-round verdicts after every update,
        # the agreement check at the FLAG_DECISION adoption sites, and
        # decision gossip on decide.  None = monitors off (zero behavior
        # change).
        self._rv = rv
        self._rv_replied: Dict[Tuple[int, int], float] = {}
        # round-consistent snapshot hook (round_tpu/snap SnapDriver,
        # shared across the loop's consecutive runners like the pump
        # state): post-update round-boundary samples, FLAG_SNAP frame
        # routing, and — on the collector replica — the periodic cut
        # audit flush.  None = snapshots off (zero behavior change).
        self._snap = snap
        self._snap_shed = False
        self.malformed = 0
        self.timeouts = 0   # rounds ended by deadline expiry (diagnostics)
        self._trajectory: List[int] = []   # per-round deadline used (ms)
        self._delegated_timeout = False    # set by _round_progress
        for pid, (host, port) in peers.items():
            if pid != my_id:
                transport.add_peer(pid, host, port)
        # round -> {sender: payload}; early messages wait here
        self._pending: Dict[int, Dict[int, Any]] = dict(prefill or {})

    def _loads(self, raw) -> Tuple[bool, Any]:
        """Deserialize a wire payload, tolerating garbage: any failure
        counts the message malformed and the caller drops it
        (InstanceHandler.scala:392-399 semantics, applied unconditionally).
        Codec frames decode zero-copy (runtime/codec.py — array leaves are
        views into the receive buffer); anything else goes through the
        RESTRICTED unpickler (transport.wire_loads): numpy/builtin
        payloads only, so a crafted __reduce__ gadget cannot execute code
        — an exception guard alone would run the attacker's payload before
        catching anything."""
        if not raw:
            return True, None
        try:
            return True, codec.loads(raw)
        except Exception as e:  # noqa: BLE001 — any garbage must be survivable
            self.malformed += 1
            _C_MALFORMED.inc()
            log.debug("node %d: dropping malformed payload (%d bytes): %s",
                      self.id, len(raw), e)
            return False, None

    def _progress_goal(self, expected) -> int:
        """The round-PROGRESS threshold: the protocol's expected message
        count capped at n, with quarantined peers excused
        (runtime/health.py) — they stop pacing the round wave; their
        frames, when they DO arrive, still land in the mailbox and still
        count toward the protocol's own quorums (which are computed
        inside the jitted update over the full mailbox, untouched)."""
        goal = min(self.n, int(expected))
        if self._health is not None:
            goal = self._health.effective_threshold(goal)
        return goal

    def _ctx(self, r: int) -> RoundCtx:
        """Context for eager hooks (expected_nbr_messages).  No rng: the
        per-round key is derived INSIDE the jitted round functions — two
        eager fold-ins per round would dominate host-round latency."""
        return RoundCtx(id=np.int32(self.id), n=self.n, r=np.int32(r))

    def _round_fns(self, rnd, state):
        """Jitted (pre+send, update, go-probe) for one Round at this group
        size — eager per-op dispatch (including the per-round PRNG fold-in)
        dominates host-round latency otherwise.  The cache lives ON the
        round object so every instance over the same Algorithm (the
        PerfTest2 loop) reuses the compiled trio.  ``state`` is the live
        state pytree, used as the exemplar for the under-lock warm-up
        compile (see _build_round_fns).

        The go-probe is the per-receive Progress of the reference
        (InstanceHandler.scala:383-400): for a FoldRound it evaluates
        ``go_ahead`` over the current masked mailbox, which is how
        LastVotingEvent's fine-grained conditions (coord majority,
        non-coord immediate goAhead) run host-side; plain Rounds fall back
        to the expected_nbr_messages count (Round.scala:60-66)."""
        cached = getattr(rnd, "_host_jit", None)
        if cached is not None and cached[0] == self.n:
            return cached[1], cached[2], cached[3]
        # double-checked module lock: thread-mode replicas share the
        # Algorithm object and reach round 0 within milliseconds of each
        # other — an unlocked check-then-set would have every thread
        # trace+compile its own trio (n-way duplicate work; the cache
        # still converged but the 'compile once per process' claim was
        # false)
        with _JIT_BUILD_LOCK:
            cached = getattr(rnd, "_host_jit", None)
            if cached is not None and cached[0] == self.n:
                return cached[1], cached[2], cached[3]
            return self._build_round_fns(rnd, state)

    def _build_round_fns(self, rnd, state):
        # the raw per-lane functions are SHARED with the lane-batched
        # driver (engine/executor.py make_host_round_fns): byte-identical
        # lane-batched decisions depend on both drivers tracing exactly
        # the same math, PRNG derivation included
        from round_tpu.engine.executor import make_host_round_fns

        n = self.n
        raw_send, raw_update, raw_go = make_host_round_fns(rnd, n)
        f_go = jax.jit(raw_go) if raw_go is not None else None
        fns = (jax.jit(raw_send), jax.jit(raw_update), f_go)
        # jax.jit is LAZY: trace+compile NOW, under the build lock, on
        # exemplar args (results discarded) — returning un-traced wrappers
        # would let every replica thread race into its own duplicate
        # trace+compile at first call, which is exactly what the lock
        # exists to prevent
        rr0, sid0, seed0 = np.int32(0), np.int32(self.id), np.uint32(0)
        st0, payload0, _dm = fns[0](rr0, sid0, seed0, state)
        payload_np = jax.tree_util.tree_map(np.asarray, payload0)
        mbox = self._mailbox({}, payload_np)
        # warm f_update/f_go on the POST-send state st0 — that is the state
        # the real loop passes them; a pre() that changes a leaf's
        # dtype/weak-type would otherwise make this exemplar signature one
        # that never recurs, and the first real call would race into
        # duplicate compiles outside the lock after all
        fns[1](rr0, sid0, seed0, st0, mbox.values, mbox.mask)
        if f_go is not None:
            f_go(rr0, sid0, seed0, st0, mbox.values, mbox.mask)
        jax.block_until_ready(st0)
        rnd._host_jit = (n, *fns)
        return fns

    def _pump_coerce_insert(self, mbox: "_RoundMailbox", sender: int,
                            raw) -> None:
        """Pump-mode bilingual slow path: a frame that missed the native
        template (legacy-pickle peer, byzantine bytes) decodes here, gets
        coerced to the slot dtypes with the mailbox's same-kind cast rule
        and re-inserted CANONICALLY under the pump lock — byte-for-byte
        the _RoundMailbox.insert semantics."""
        ok, payload = self._loads(raw)
        if not ok:
            if self._health is not None:
                self._health.note_malformed(sender)
            return
        pump = self._ps.pump
        try:
            enc = pump_coerce_encode(
                payload, [(s.shape[1:], s.dtype) for s in mbox.stacked],
                mbox.treedef)
            if pump.insert(self._ps.lane, sender, enc) < 0:
                raise ValueError("canonical re-encode missed the template")
        except Exception as e:  # noqa: BLE001 — garbage must not kill us
            self.malformed += 1
            _C_MALFORMED.inc()
            if self._health is not None:
                self._health.note_malformed(sender)
            pump.mark_malformed(self._ps.lane, sender)
            log.debug("node %d: dropping structurally-malformed payload "
                      "from %d: %s", self.id, sender, e)
        # host.recvs accounting rides the pump stats bank (rt_pump_insert
        # ticked fast/dup) — an inline inc here would double-count

    def _pump_round(self, r, rr, sid, seed, state, payload_np, dest, f_go,
                    max_rnd):
        """One round's send + accumulate through the native pump: reset/
        prefill/self-deliver the class mailbox while DISARMED, arm (which
        applies natively-buffered pending frames for this round), ship
        the whole send fan-out in one rt_pump_flush crossing, then block
        in rt_pump_wait until goAhead / deadline / skew / misc.  Returns
        the accumulate outcome tuple of the Python path (plus the raw
        expected-message count, for quarantine blame attribution)."""
        P = RoundPump
        ps = self._ps
        pump = ps.pump
        lane = ps.lane
        rounds = self.algo.rounds
        ci = r % len(rounds)
        rnd = rounds[ci]
        mbox = ps.boxes[ci]
        mbox.reset(payload_np)
        for _sender, _payload in self._pending.pop(r, {}).items():
            mbox.insert(_sender, _payload)
        if dest[self.id]:
            # self-delivery is never suppressed (Round.scala:114-117)
            mbox.insert(self.id, payload_np)
        prog = self._round_progress(rnd)
        use_deadline = prog.is_timeout
        if use_deadline:
            _G_DEADLINE.set(prog.timeout_millis)
        expected = rnd.expected_nbr_messages(self._ctx(r), state)
        t0 = _time.monotonic()
        timedout = deadline_expired = False
        oob_decided = False

        # -- arm ------------------------------------------------------------
        thr, flags, dl, ext = 0, 0, 0, 0
        if not prog.is_go_ahead:
            if f_go is not None or prog.is_sync:
                flags |= P.F_GROWTH
            else:
                thr = self._progress_goal(expected)
            if prog.is_strict or prog.is_sync:
                flags |= P.F_STRICT
            if use_deadline:
                dl = int(prog.timeout_millis)
            else:
                dl = ext = self.wait_cap_ms
                flags |= P.F_EXTEND
        # a zero threshold with no growth wake is an already-satisfied
        # quorum (expected <= 0): same instant-end semantics as GoAhead
        instant = prog.is_go_ahead or (thr <= 0 and not flags)
        if instant:
            pump.arm(lane, r, ci, 0, 0, 0, 0)  # applies pending only
        else:
            pump.arm(lane, r, ci, thr, flags, dl, ext)

        # -- send (after arm: a fast peer's reply races only into the
        # native pending buffer, never into a torn mailbox) ---------------
        sent = 0
        if ps.send_ok:
            del ps.wave[:]
            del ps.entries[:]
            ps.entry_count = 0
            codec.encode_into(payload_np, ps.wave)
            ln = len(ps.wave)
            tagw = Tag(instance=self.instance_id,
                       round=r).pack() & 0xFFFFFFFFFFFFFFFF
            for d in range(self.n):
                if d == self.id or not dest[d]:
                    continue
                ps.entries += P._ENTRY.pack(d, tagw, 0, ln)
                ps.entry_count += 1
                sent += 1
            if sent:
                pump.flush(ps.wave, ps.entries, ps.entry_count)
        else:
            # chaos wrapper in the way: faults apply per logical frame on
            # the send_buffered surface, exactly like the Python pump
            wire = self._scratch.encode(payload_np)
            tag = Tag(instance=self.instance_id, round=r)
            for d in range(self.n):
                if d == self.id or not dest[d]:
                    continue
                if self._sendb is not None:
                    self._sendb(d, tag, wire)
                else:
                    self.transport.send(d, tag, bytes(wire))
                sent += 1
            if sent and self._sendb is not None:
                self._flushfn()
        if sent:
            _C_SENDS.inc(sent)

        # -- accumulate -----------------------------------------------------
        def go_ahead() -> bool:
            if f_go is not None:
                vals, mask = mbox.values_mask()
                return bool(np.asarray(
                    f_go(rr, sid, seed, state, vals, mask)))
            return mbox.count >= self._progress_goal(expected)

        def drain_misc() -> None:
            nonlocal state, oob_decided
            while True:
                if self._recv_many is not None:
                    got_list = self._recv_many(0)
                else:
                    got = self.transport.recv(0)
                    got_list = [got] if got is not None else []
                if not got_list:
                    return
                for got in got_list:
                    sender, tg, raw = got
                    if not 0 <= sender < self.n:
                        self.malformed += 1
                        _C_MALFORMED.inc()
                        continue
                    if tg.instance == self.instance_id \
                            and tg.flag == FLAG_NORMAL:
                        if pump.feed(sender, tg, raw) == -2:
                            self._pump_coerce_insert(mbox, sender, raw)
                    elif tg.flag == FLAG_DECISION \
                            and tg.instance == self.instance_id:
                        ok, p = self._loads(raw)
                        if ok and p is not None and self._rv is not None:
                            # agreement check before adoption (see the
                            # Python-pump ingest site)
                            self._rv.on_decision_frame(state, p, r)
                        adopted = (self.algo.adopt_decision(state, p)
                                   if ok else None)
                        if adopted is not None:
                            state = adopted
                            oob_decided = True
                            _C_OOB.inc()
                            if TRACE.enabled:
                                TRACE.emit("recv_decision", node=self.id,
                                           inst=self.instance_id, round=r,
                                           src=sender)
                    elif tg.flag == FLAG_NACK:
                        _C_NACKS_SEEN.inc()
                        if TRACE.enabled:
                            TRACE.emit("nack_seen", node=self.id,
                                       inst=tg.instance, src=sender)
                    elif tg.flag == FLAG_SNAP and self._snap is not None:
                        # snapshot sample routed off the pump's misc
                        # path (round_tpu/snap) — the Python ingest
                        # site's twin
                        self._snap.on_frame(sender, tg, raw)
                    elif tg.flag == FLAG_NORMAL and self.foreign is not None:
                        ok, p = self._loads(raw)
                        if ok:
                            self.foreign(sender, tg, p)
                    elif self.default_handler is not None:
                        ok, p = self._loads(raw)
                        if ok:
                            self.default_handler(Message(
                                sender=sender, tag=tg, payload=p))

        if instant:
            # queued frames were applied at arm; one misc sweep mirrors
            # the Python path's pre-update drain, then the round ends
            if ps.mux:
                # mux mode: the router thread owns the shared inbox; our
                # misc traffic is whatever it routed to the endpoint
                # queue (drain unconditionally — a nowait queue poll)
                pump.wait_lane(lane, 0)
                drain_misc()
            else:
                _n, misc = pump.wait(0)
                if misc:
                    drain_misc()
            pump.disarm(lane)
            return (state, mbox, prog, use_deadline, t0, timedout,
                    deadline_expired, oob_decided, expected)

        if flags & P.F_GROWTH:
            # initial probe, mirroring the Python loop's dirty=True first
            # iteration: prefill/self-delivery/natively-applied pending
            # may ALREADY satisfy the go condition or sync barrier, and
            # the native side raises no GROWTH wake for frames applied at
            # arm — without this a satisfied round would sit out its
            # whole deadline
            go = f_go is not None and go_ahead()
            if not go and prog.is_sync and int((max_rnd >= r).sum()) \
                    >= prog.k + self.nbr_byzantine:
                go = True
            if go:
                pump.disarm(lane)
                return (state, mbox, prog, use_deadline, t0, timedout,
                        deadline_expired, oob_decided, expected)

        if ps.mux:
            # frames routed to the endpoint queue between our rounds
            # (while this lane was disarmed) raised pokes we may have
            # consumed at arm: one nowait sweep closes the race
            drain_misc()
            if oob_decided:
                # same discipline as every oob exit below: stop native
                # mailbox writes before Python touches the mailbox (the
                # wait loop is skipped, so IT can't disarm for us)
                pump.disarm(lane)

        while not oob_decided:
            if ps.mux:
                # per-lane wait: rt_pump_wait_lane consumes only THIS
                # lane's reason bits (the global rt_pump_wait would
                # steal every concurrent runner's wakes); R_POKE is the
                # router's out-of-band nudge — our endpoint queue has
                # traffic (FLAG_DECISION, template misses) to drain
                rs = pump.wait_lane(lane, 10_000)
                if rs < 0:
                    break  # transport stopped under us
                if rs & P.R_POKE:
                    drain_misc()
                    if oob_decided:
                        pump.disarm(lane)
                        break
            else:
                nready, misc = pump.wait(10_000)
                if nready < 0:
                    break  # transport stopped; unwind like a timeout
                if misc:
                    drain_misc()
                    if oob_decided:
                        pump.disarm(lane)
                        break
                rs = int(pump.reasons[lane])
            if rs & P.R_THRESH:
                break
            if rs & P.R_DEADLINE:
                timedout = True
                deadline_expired = True
                self.timeouts += 1
                _C_TIMEOUTS.inc()
                if TRACE.enabled:
                    TRACE.emit(
                        "timeout", node=self.id, inst=self.instance_id,
                        round=r,
                        deadline_ms=(int(prog.timeout_millis)
                                     if use_deadline else self.wait_cap_ms),
                        kind="deadline" if use_deadline else "wait_cap",
                        heard=mbox.count)
                if not use_deadline:
                    log.warning(
                        "node %d round %d: %s was idle for %d ms; forcing "
                        "timeout (the reference would block forever)",
                        self.id, r, prog, self.wait_cap_ms)
                break
            if rs & P.R_SKEW:
                timedout = True
                _C_CATCHUP.inc()
                if TRACE.enabled:
                    TRACE.emit("catch_up", node=self.id,
                               inst=self.instance_id, round=r,
                               next_round=int(pump.next_round[lane]))
                break
            if rs & P.R_GROWTH:
                go = f_go is not None and go_ahead()
                if not go and prog.is_sync and int(
                        (max_rnd >= r).sum()) \
                        >= prog.k + self.nbr_byzantine:
                    go = True
                if go:
                    pump.disarm(lane)
                    break
        return (state, mbox, prog, use_deadline, t0, timedout,
                deadline_expired, oob_decided, expected)

    def _round_progress(self, rnd) -> Progress:
        """The round's declared Progress policy; a round that keeps the
        Round-class default delegates to the runner's configured timeout
        (the RuntimeOptions role) — fixed `timeout_ms`, or the live
        AdaptiveTimeout estimate when one is configured.  Sets
        `_delegated_timeout` so the run loop knows whether this round's
        outcome should feed the estimator."""
        p = rnd.init_progress
        if p is Round.init_progress:
            self._delegated_timeout = True
            if self.adaptive is not None:
                return Progress.timeout(self.adaptive.current_ms())
            return Progress.timeout(self.timeout_ms)
        self._delegated_timeout = False
        return p

    def run(self, io: Any, max_rounds: int = 64) -> HostResult:
        algo = self.algo
        state = algo.make_init_state(self._ctx(0), io)
        rounds = algo.rounds
        exited = False
        r = 0
        # view interrupt: the ViewManager MOVED (a FLAG_VIEW catch-up was
        # adopted, or our removal discovered) — this instance runs over a
        # stale wire and must hand control back to the host loop.  Merely
        # OBSERVING a peer ahead (view.stale) does NOT interrupt: the
        # catch-up reply to our next stamped send is already on its way,
        # and bailing before ingesting it would burn the host loop's
        # bounded re-runs without ever adopting the new view
        epoch0 = self.view.epoch if self.view is not None else 0

        def view_int() -> bool:
            v = self.view
            return v is not None and (v.removed or v.epoch != epoch0)
        # benign catch-up state (InstanceHandler.scala:289-301): highest
        # round observed per peer; their max pulls this replica forward.
        # In pump mode the array is the SHARED native row — the event
        # loop writes peers' claims with no GIL held, this side only ever
        # writes its own element
        if self._ps is not None:
            for box in self._ps.boxes.values():
                box.runner = self
            self._ps.pump.open_lane(self._ps.lane, self.instance_id)
            max_rnd = self._ps.pump.max_rnd[self._ps.lane]
        else:
            max_rnd = np.full(self.n, -1, dtype=np.int64)
        max_rnd[self.id] = 0
        next_round = 0
        if self.delay_first_send_ms > 0:
            # delayFirstSend (InstanceHandler.scala:169-171): sleep before
            # the instance's first round — start-skew injection
            _time.sleep(self.delay_first_send_ms / 1000.0)
        while r < max_rounds and not exited:
            rnd = rounds[r % len(rounds)]
            if TRACE.enabled:
                TRACE.emit("round_start", node=self.id,
                           inst=self.instance_id, round=r)
            rr, sid = np.int32(r), np.int32(self.id)
            seed = np.uint32(self.seed)
            f_send, f_update, f_go = self._round_fns(rnd, state)
            # the send TRANSITION always runs (it is part of the round's
            # state semantics); whether the messages go out is the policy
            state, payload, dest_mask = f_send(rr, sid, seed, state)
            dest = np.asarray(dest_mask)
            payload_np = jax.tree_util.tree_map(np.asarray, payload)
            if self._ps is not None:
                # NATIVE PUMP round (native/transport.cpp rt_pump_*):
                # mailbox reset + prefill + self-delivery while the
                # lane is disarmed, ONE arm (applies natively-buffered
                # pending), one flush crossing for the whole send
                # fan-out, then ONE blocking wait per wake — the
                # per-message recv loop below is the Python-pump
                # baseline arm of the A/B (apps/host_perftest --ab-pump)
                (state, mbox, prog, use_deadline, t0, timedout,
                 deadline_expired, oob_decided, expected) = self._pump_round(
                    r, rr, sid, seed, state, payload_np, dest, f_go,
                    max_rnd)
            else:
                # catching up = a peer was observed past this round
                # (InstanceHandler.scala:176: msg pending ⇒ only send when
                # sendWhenCatchingUp); our messages would arrive
                # communication-closed-late at peers already beyond r
                sending = self.send_when_catching_up or next_round <= r
                # the view epoch rides the otherwise-unused callStack byte of
                # every NORMAL frame (runtime/view.py; 0 in the epoch-less
                # world, which IS epoch 0's stamp — fully backwards-compatible)
                cs = self.view.epoch_byte if self.view is not None else 0
                if sending:
                    # encode ONCE per round into the pooled scratch (binary)
                    # or a pickle bytes (legacy); every destination ships the
                    # same buffer.  Binary sends coalesce into per-peer
                    # FLAG_BATCH frames, flushed at the end of the send loop —
                    # the round boundary of comm-closure makes this safe.
                    if self._scratch is not None:
                        wire = self._scratch.encode(payload_np)
                    else:
                        wire = pickle.dumps(payload_np)
                    tag = Tag(instance=self.instance_id, round=r, call_stack=cs)
                    sendb = self._sendb
                    sent = 0
                    for d in range(self.n):
                        if d == self.id or not dest[d]:
                            continue
                        if sendb is not None:
                            sendb(d, tag, wire)
                        else:
                            self.transport.send(
                                d, tag, wire if isinstance(wire, bytes)
                                else bytes(wire))
                        sent += 1
                        if TRACE.enabled:
                            TRACE.emit("send", node=self.id,
                                       inst=self.instance_id, round=r, dst=d,
                                       bytes=len(wire))
                    if sent:
                        if sendb is not None:  # __init__ guarantees flush too
                            self._flushfn()
                        _C_SENDS.inc(sent)
                else:
                    self.suppressed_sends += 1

                # -- accumulate (InstanceHandler.scala:164-353) ---------------
                mbox = self._mbox
                mbox.reset(payload_np)
                for _sender, _payload in self._pending.pop(r, {}).items():
                    mbox.insert(_sender, _payload)
                if dest[self.id]:
                    # self-delivery is NEVER suppressed: a replica's message to
                    # itself cannot be communication-closed-late, and dropping
                    # it would starve the full-mailbox go-ahead probe on every
                    # suppressed round — the knob suppresses WIRE sends only
                    mbox.insert(self.id, payload_np)
                prog = self._round_progress(rnd)
                block = prog.is_strict       # strict: no catch-up early-exit
                use_deadline = prog.is_timeout
                t0 = _time.monotonic()
                deadline = t0 + (prog.timeout_millis if use_deadline
                                 else self.wait_cap_ms) / 1000.0
                if use_deadline:
                    _G_DEADLINE.set(prog.timeout_millis)
                expected = rnd.expected_nbr_messages(self._ctx(r), state)
                timedout = False
                # deadline_expired ⊂ timedout: the catch-up fast-forward break
                # also flags timedout but is round SKEW, not wire latency — only
                # a true expiry may back the adaptive estimator off
                deadline_expired = False

                def go_ahead() -> bool:
                    if f_go is not None:
                        vals, mask = mbox.values_mask()
                        return bool(np.asarray(
                            f_go(rr, sid, seed, state, vals, mask)
                        ))
                    return mbox.count >= self._progress_goal(expected)

                oob_decided = False

                def ingest(got, extend_deadline=True, buffer_only=False) -> bool:
                    """Route one received packet; True when THIS round's inbox
                    grew.  Shared by the blocking accumulate loop and the
                    GoAhead pre-update drain.  With buffer_only, a
                    current-round message is dropped instead of joining the
                    inbox (it is late-for-the-quorum; under the default policy
                    it would have been read next round and dropped as late, so
                    this keeps the frontier drain behavior-neutral for the
                    current round's update)."""
                    nonlocal state, deadline, next_round, oob_decided
                    sender, tag, raw = got
                    if self.view is not None:
                        # the view guard runs BEFORE the sender-range check:
                        # after a REMOVE shrinks n, a stale replica's old pid
                        # can be >= n (it dials the member that inherited its
                        # id, or — when the last pid was removed — anyone),
                        # and dropping it as malformed would starve it of the
                        # FLAG_VIEW catch-up forever.  Neither path indexes a
                        # sender-sized structure: adoption validates the
                        # payload structurally, and the reply rides the stale
                        # peer's own inbound channel (by_peer), so an
                        # arbitrary sender id is safe — at worst a garbage
                        # frame reflects one rate-limited ~100-byte reply.
                        if tag.flag == FLAG_VIEW:
                            # catch-up from a peer ahead of our view: adopt
                            # (rewire + epoch jump); view_int() then ends this
                            # instance so the host loop re-enters on the new
                            # wire
                            ok, p = self._loads(raw)
                            if ok:
                                self.view.adopt_wire(p)
                            return False
                        if (tag.flag == FLAG_NORMAL
                                and not self.view.check_epoch(sender, tag)):
                            # cross-epoch data traffic is DROPPED, never
                            # folded: a stale peer was just answered with
                            # FLAG_VIEW; an ahead peer flagged us stale
                            return False
                    if not 0 <= sender < self.n:
                        # protocol garbage on the unauthenticated socket: an
                        # out-of-range id would corrupt every downstream
                        # sender-indexed structure (stash, mailbox stacking)
                        self.malformed += 1
                        _C_MALFORMED.inc()
                        return False
                    if tag.instance != self.instance_id or tag.flag != FLAG_NORMAL:
                        if (tag.flag == FLAG_DECISION
                                and tag.instance == self.instance_id):
                            # out-of-band decision recovery (PerfTest.scala:
                            # 40-60): a peer that already decided replies to
                            # our late traffic with the value — adopt and exit
                            # instead of burning this round's timeout
                            ok, p = self._loads(raw)
                            if ok and p is not None \
                                    and self._rv is not None:
                                # the agreement term's cold site: check
                                # BEFORE adoption overwrites the state
                                # the conflict lives in
                                self._rv.on_decision_frame(state, p, r)
                            adopted = (self.algo.adopt_decision(state, p)
                                       if ok else None)
                            if adopted is not None:
                                state = adopted
                                oob_decided = True
                                _C_OOB.inc()
                                if TRACE.enabled:
                                    TRACE.emit("recv_decision", node=self.id,
                                               inst=self.instance_id, round=r,
                                               src=sender)
                        elif tag.flag == FLAG_NACK:
                            # a peer SHED our frame under admission overload
                            # (runtime/lanes.py _shed_frame): accounted, not
                            # actionable — the protocol's own retransmission
                            # is the retry, the decision-reply path the
                            # catch-up
                            _C_NACKS_SEEN.inc()
                            if TRACE.enabled:
                                TRACE.emit("nack_seen", node=self.id,
                                           inst=tag.instance, src=sender)
                        elif tag.flag == FLAG_SNAP \
                                and self._snap is not None:
                            # snapshot sample (round_tpu/snap): the
                            # collector joins it into a cut — never
                            # round traffic, any instance's coordinate
                            self._snap.on_frame(sender, tag, raw)
                        elif tag.flag == FLAG_NORMAL and self.foreign is not None:
                            ok, p = self._loads(raw)
                            if ok:
                                self.foreign(sender, tag, p)
                        elif self.default_handler is not None:
                            ok, p = self._loads(raw)
                            if ok:
                                self.default_handler(Message(
                                    sender=sender, tag=tag, payload=p,
                                ))
                        return False
                    if tag.round > max_rnd[sender]:
                        max_rnd[sender] = tag.round
                    if tag.round < r:
                        return False  # late: the round is communication-closed
                    ok, payload = self._loads(raw)
                    if not ok:
                        if self._health is not None:
                            self._health.note_malformed(sender)
                        if TRACE.enabled:
                            TRACE.emit("malformed", node=self.id,
                                       inst=self.instance_id, round=tag.round,
                                       src=sender)
                        return False
                    if extend_deadline and not use_deadline:
                        # the wait cap is an IDLE cap: any same-instance
                        # message is progress and extends the deadline
                        deadline = _time.monotonic() + self.wait_cap_ms / 1000.0
                    if tag.round > r:
                        self._pending.setdefault(tag.round, {})[sender] = payload
                        if self.nbr_byzantine <= 0:
                            # benign catch-up: the furthest peer sets the target
                            next_round = max(next_round, int(max_rnd.max()))
                        else:
                            # byzantine catch-up (InstanceHandler.scala:302-307):
                            # drop the f highest claims — a target needs f+1
                            # attestations, so lying peers cannot drag us ahead
                            srt = np.sort(max_rnd)
                            next_round = max(
                                next_round, int(srt[-(self.nbr_byzantine + 1)]))
                        return False
                    if buffer_only:
                        return False  # post-quorum same-round: same fate as
                        # arriving next round under the default policy (late)
                    grew = mbox.insert(sender, payload)
                    _C_RECVS.inc()
                    if TRACE.enabled:
                        TRACE.emit("recv", node=self.id, inst=self.instance_id,
                                   round=r, src=sender)
                    return grew

                dirty = True  # inbox changed since the last go probe
                while not prog.is_go_ahead and not oob_decided \
                        and not view_int():
                    if dirty and go_ahead():
                        break
                    dirty = False
                    if prog.is_sync and int((max_rnd >= r).sum()) \
                            >= prog.k + self.nbr_byzantine:
                        # sync(k) barrier: f of the attestations may be lies,
                        # so the barrier needs k + f (computeSync,
                        # InstanceHandler.scala:279-287)
                        break
                    if next_round > r + 1 and not block:
                        # genuine round skew: a peer is MORE than one round
                        # ahead, so this round's window is over — fast-forward
                        # (counts as TO, :245).  A one-round lead is normal
                        # pipelining (the peer finished the round we are in and
                        # sent its next message, which can overtake a slower
                        # peer's current-round packet on another socket);
                        # breaking on it would truncate rounds to partial
                        # mailboxes microseconds before completion — measured
                        # 20x throughput loss on the PerfTest2 harness — and a
                        # 1-round-behind replica self-heals within one round
                        # timeout anyway.
                        timedout = True
                        _C_CATCHUP.inc()
                        if TRACE.enabled:
                            TRACE.emit("catch_up", node=self.id,
                                       inst=self.instance_id, round=r,
                                       next_round=int(next_round))
                        break
                    left_ms = int((deadline - _time.monotonic()) * 1000)
                    if left_ms <= 0:
                        timedout = True
                        deadline_expired = True
                        self.timeouts += 1
                        _C_TIMEOUTS.inc()
                        if TRACE.enabled:
                            TRACE.emit(
                                "timeout", node=self.id, inst=self.instance_id,
                                round=r,
                                deadline_ms=(int(prog.timeout_millis)
                                             if use_deadline
                                             else self.wait_cap_ms),
                                kind="deadline" if use_deadline else "wait_cap",
                                heard=mbox.count)
                        if not use_deadline:
                            log.warning(
                                "node %d round %d: %s was idle for "
                                "%d ms; forcing timeout (the reference would "
                                "block forever)", self.id, r, prog,
                                self.wait_cap_ms)
                        break
                    got = self.transport.recv(left_ms)
                    if got is None:
                        continue  # re-check the deadline
                    if ingest(got):
                        dirty = True
                if (prog.is_go_ahead or not self.send_when_catching_up) \
                        and not oob_decided:
                    # ONE non-blocking drain, two roles.  (a) A GoAhead round
                    # delivers messages ALREADY QUEUED in the transport before
                    # updating (the reference delivers pending messages before
                    # ending the round, InstanceHandler.scala:219-231):
                    # same-round into the inbox, future rounds into the
                    # buffer.  (b) The catch-up send policy needs the FRONTIER
                    # visible: ingestion normally stops at the quorum break,
                    # so a replica replaying a long backlog never sees the
                    # rounds ahead (the reference's one-message-at-a-time loop
                    # reads ahead by construction) — future rounds land in the
                    # pending buffer and push next_round forward.  In role (b)
                    # alone, post-quorum same-round payloads are DROPPED
                    # (buffer_only): under the default policy they would have
                    # been read next round and dropped as late, so the knob
                    # stays behavior-neutral for the current round's update.
                    # recv_many pulls EVERY queued frame in one batched native
                    # drain (transport.recv_many); transports without it (bare
                    # test doubles) fall back to the per-frame poll
                    while True:
                        if self._recv_many is not None:
                            got_list = self._recv_many(0)
                        else:
                            got = self.transport.recv(0)
                            got_list = [got] if got is not None else []
                        if not got_list:
                            break
                        for got in got_list:
                            ingest(got, extend_deadline=False,
                                   buffer_only=not prog.is_go_ahead)
                        if oob_decided or view_int():
                            break

            if use_deadline:
                self._trajectory.append(int(prog.timeout_millis))
            if self.adaptive is not None and self._delegated_timeout:
                adapted = False
                if deadline_expired:
                    self.adaptive.observe(None, expired=True)
                    adapted = True
                elif not timedout:
                    # goAhead/oob completion: the round's wall time IS the
                    # wire latency sample (skew fast-forwards teach nothing)
                    self.adaptive.observe(
                        (_time.monotonic() - t0) * 1000.0, expired=False)
                    adapted = True
                if adapted and TRACE.enabled:
                    ew = self.adaptive.ewma_ms
                    TRACE.emit("adaptive", node=self.id,
                               inst=self.instance_id, round=r,
                               expired=deadline_expired,
                               deadline_ms=self.adaptive.current_ms(),
                               ewma_ms=None if ew is None
                               else round(ew, 3))

            # -- update ---------------------------------------------------
            if view_int():
                # view boundary: do NOT fold the partial old-epoch mailbox
                # (a decision reached across the boundary could be over
                # the wrong group) — hand back undecided-so-far, the host
                # loop re-runs the instance on the new wire
                exited = True
            elif oob_decided:
                exited = True
            else:
                vals, mask = mbox.values_mask()
                state, exit_flag = f_update(
                    rr, sid, seed, state, vals, mask,
                )
                exited = bool(np.asarray(exit_flag))
            if self._rv is not None and not view_int():
                # runtime verification: the post-update verdict vector
                # (rv/compile.py HostRv — same labels/order as the lane
                # driver's fused term).  halt raises out of the runner;
                # shed is resolved after the loop (forced undecided).
                self._rv.after_update(state, r)
                if self._rv.gossip and self._rv.just_decided:
                    # decision gossip — the agreement monitor's
                    # observability channel: peers learn this decision
                    # while their own lanes are still live
                    for d in range(self.n):
                        if d != self.id:
                            _try_send_decision(
                                self.transport, self._rv_replied, d,
                                self.instance_id, self._rv.mon.prev_val)
            if self._snap is not None and not view_int() \
                    and not oob_decided \
                    and self._snap.due(self.instance_id, r):
                # round boundary: sample the post-update state (the
                # deterministic policy decides — snap/sample.py; an
                # oob-adopted exit skipped the update, so its round has
                # no boundary state to sample and the cut tolerates the
                # gap like any missing contributor).  due() first: the
                # leaf flatten/asarray extraction stays off the
                # (every_k-1)/every_k of rounds that would discard it.
                self._snap.after_round(
                    self.instance_id, r,
                    [np.asarray(x)
                     for x in jax.tree_util.tree_leaves(state)])
            if self._snap is not None:
                # collector housekeeping (no-op elsewhere): audit
                # assembled cuts; halt raises out of the runner here,
                # shed of the CURRENT instance forces it undecided below
                for iid in self._snap.flush():
                    # cut coordinates are 16-bit (the Tag's instance
                    # field), so the RUNNER'S id masks for the compare
                    if iid == self.instance_id & 0xFFFF:
                        self._snap_shed = True
            if self._health is not None:
                # one completed round wave of quarantine evidence: heard
                # peers decay/rejoin, unheard peers accrue timeout score
                # only when the deadline actually EXPIRED (a goAhead round
                # that didn't need peer p teaches nothing about p)
                self._health.note_round(
                    mbox.senders(), deadline_expired,
                    goal=min(self.n, int(expected)))
            _C_ROUNDS.inc()
            wall_ms = (_time.monotonic() - t0) * 1000.0
            _H_ROUND_MS.observe(wall_ms)
            if TRACE.enabled:
                # ho = the senders heard this round — the HO set of the
                # model, which is what trace_view merges across replicas
                TRACE.emit("round_end", node=self.id, inst=self.instance_id,
                           round=r, heard=mbox.count, n=self.n,
                           ho=mbox.senders(),
                           timedout=timedout, exited=exited,
                           oob=oob_decided, wall_ms=round(wall_ms, 3))
            log.debug("node %d round %d: heard %d/%d%s%s", self.id, r,
                      mbox.count, self.n, " TO" if timedout else "",
                      " exit" if exited else "")
            r += 1
            max_rnd[self.id] = r
            next_round = max(next_round, r)

        decided = bool(np.asarray(algo.decided(state)))
        if view_int():
            # never report a decision across a view boundary (see above)
            decided = False
        if self._rv is not None and self._rv.shed:
            # rv 'shed' policy: a violating instance is reported
            # undecided — its decision must not enter the log
            decided = False
        if self._snap_shed:
            # snapshot 'shed' policy (the collector replica's verdict):
            # an instance whose cut violated a full-state invariant is
            # reported undecided — same discipline as the rv shed
            decided = False
        decision = np.asarray(algo.decision(state))
        if decided:
            _C_DECISIONS.inc()
        if TRACE.enabled:
            TRACE.emit("decision", node=self.id, inst=self.instance_id,
                       round=r, decided=decided,
                       value=decision.tolist() if decided else None)
        return HostResult(
            state=state, decided=decided, decision=decision, rounds_run=r,
            dropped_messages=self.transport.dropped,
            malformed_messages=self.malformed,
            timeouts=self.timeouts,
            timeout_trajectory=list(self._trajectory),
            stale_view=view_int(),
        )

    def _mailbox(self, inbox: Dict[int, Any], like: Any) -> Mailbox:
        """Stack per-sender payloads into the [n, ...] arrays + mask the
        Round DSL's update expects (the dense-mailbox view of the wire).

        A payload that unpickled fine but has the WRONG SHAPE for this
        round (tree structure, leaf count, leaf shape/dtype) is byzantine
        garbage too — dropped per sender + counted, never a crash (the
        deserialize-failure tolerance of InstanceHandler.scala:392-399
        extended to the structural layer pickle does not check)."""
        leaves_like, treedef = jax.tree_util.tree_flatten(like)
        stacked = [
            np.zeros((self.n,) + np.shape(l), dtype=np.asarray(l).dtype)
            for l in leaves_like
        ]
        mask = np.zeros((self.n,), dtype=bool)
        for sender, payload in inbox.items():
            try:
                leaves = jax.tree_util.tree_flatten(payload)[0]
                if len(leaves) != len(stacked):
                    raise ValueError(
                        f"{len(leaves)} leaves != {len(stacked)}")
                for slot, leaf in zip(stacked, leaves):
                    arr = np.asarray(leaf)
                    if arr.shape != slot.shape[1:]:
                        raise ValueError(
                            f"leaf shape {arr.shape} != {slot.shape[1:]}")
                    slot[sender] = arr.astype(slot.dtype, casting="same_kind")
            except Exception as e:  # noqa: BLE001 — garbage must not kill us
                self.malformed += 1
                _C_MALFORMED.inc()
                mask[sender] = False
                log.debug("node %d: dropping structurally-malformed payload "
                          "from %d: %s", self.id, sender, e)
                continue
            mask[sender] = True
        values = jax.tree_util.tree_unflatten(treedef, stacked)
        return Mailbox(values, np.asarray(mask))
