"""Host TCP transport over the native library (native/transport.cpp).

This is the real multi-process deployment path: the reference runs one JVM
per replica with Netty TCP channels between them (TcpRuntime.scala:27-232);
here each OS process owns a `HostTransport` backed by the C++ poll-loop
library, and messages keep the reference's shape — the 8-byte Tag of
runtime/oob.py (flag | callStack | instance | round, Tag.scala:22-25)
followed by payload bytes.

The same `Message` objects that flow over the in-process `LocalBus`
(runtime/oob.py) travel here unchanged: `HostBus` implements the LocalBus
surface (send/deliver) over sockets, so a `PoolNode` — decision replay,
lazy join, recovery — works across real processes too.  The lockstep
round-execution path on top of this lives in runtime/host.py.

Hot-path framing (the Netty-tuning parity of the reference: pooled
buffers, registered-class codec, write coalescing):

  * payloads are encoded by the binary codec (runtime/codec.py), not
    pickle — `wire_loads` stays as the tagged fallback decoder;
  * `send_buffered`/`flush` coalesce the frames of one round into ONE
    FLAG_BATCH container per destination (one native send per peer per
    flush, regardless of frame count);
  * `recv` drains the native inbox in ONE ctypes call
    (rt_node_recv_many), copies the whole drain once, and splits
    containers into logical frames by header peek — payload slices are
    memoryviews, never re-copied.

Fault injection does NOT live here: wrap a HostTransport in
runtime/chaos.py's `FaultyTransport` (same send/recv surface) for
deterministic seed-driven drop/duplicate/reorder/delay/corruption
schedules — the host-path analogue of engine/scenarios.py.
"""

from __future__ import annotations

import collections
import ctypes
import os
import pickle
import struct
import subprocess
import threading
import time as _time
from typing import Dict, List, Optional, Tuple

import numpy as np

from round_tpu.obs.metrics import METRICS
from round_tpu.obs.trace import TRACE
from round_tpu.runtime.oob import FLAG_BATCH, Message, Tag

# wire-level instruments (one lock-guarded add per message on a path that
# is already a syscall): the transport's own view of traffic, below the
# runner's semantic host.sends/host.recvs
_C_WIRE_SENT = METRICS.counter("wire.sent_msgs")
_C_WIRE_SENT_B = METRICS.counter("wire.sent_bytes")
_C_WIRE_RECV = METRICS.counter("wire.recv_msgs")
_C_WIRE_RECV_B = METRICS.counter("wire.recv_bytes")
# frame-coalescing instruments (docs/OBSERVABILITY.md): logical frames
# that traveled inside FLAG_BATCH container frames, and the container
# payload bytes — wire.sent_msgs/recv_msgs keep counting LOGICAL frames,
# so batches/frames is the coalescing factor
_C_BATCHES = METRICS.counter("wire.batches")
_C_BATCH_FRAMES = METRICS.counter("wire.batch_frames")
_C_BATCH_BYTES = METRICS.counter("wire.batch_bytes")
# churn instruments (the view subsystem's wire half, runtime/view.py):
# reconnects = channels re-established by the auto-reconnect loop,
# rewires = peer-table swaps applied by a view change
_C_WIRE_RECONNECT = METRICS.counter("wire.reconnects")
_C_WIRE_REWIRE = METRICS.counter("wire.rewires")
# overload instruments (docs/HOST_FAULT_MODEL.md "overload, shedding and
# quarantine"): backpressure = rising edges of the bounded native inbox's
# byte high watermark; peer_pauses = send paths paused after consecutive
# send failures; backpressure_drops = frames dropped-with-count while a
# peer's send path is paused (bounded memory instead of unbounded retry)
_C_BACKPRESSURE = METRICS.counter("wire.backpressure")
_C_PEER_PAUSES = METRICS.counter("wire.peer_pauses")
_C_BP_DROPS = METRICS.counter("wire.backpressure_drops")

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_lib = None
_lib_lock = threading.Lock()


class _WireUnpickler(pickle.Unpickler):
    """Restricted unpickler for wire payloads: numpy array/scalar
    RECONSTRUCTION and plain builtin containers ONLY.  A stock
    pickle.loads on attacker bytes EXECUTES attacker code (a __reduce__
    gadget) before any exception guard can contain it — so the
    byzantine-garbage tolerance of the host path starts here, by refusing
    to even look up classes outside the payload vocabulary.  (The
    reference's Kryo is similarly a registered-class deserializer, not
    arbitrary-code.)

    The allowlist is EXACT (module, name) pairs, not module prefixes: the
    numpy namespace itself contains exec gadgets
    (numpy.testing._private.utils.runstring is literally exec;
    numpy.ctypeslib.load_library loads arbitrary shared objects), so a
    prefix match would reopen the hole this class closes."""

    _ALLOWED = frozenset({
        ("numpy", "ndarray"),
        ("numpy", "dtype"),
        ("numpy.core.multiarray", "_reconstruct"),
        ("numpy.core.multiarray", "scalar"),
        ("numpy._core.multiarray", "_reconstruct"),
        ("numpy._core.multiarray", "scalar"),
    })

    def find_class(self, module, name):
        # bytearray is deliberately ABSENT: no legitimate wire payload
        # pickles one (numpy array states are bytes), and a hostile
        # pickle could otherwise build an ndarray BACKED by a bytearray
        # inside a reference cycle — the GC then deallocates the
        # bytearray while its buffer is still exported, an unraisable
        # SystemError per frame (found by fuzz/hostile.py)
        if module == "builtins" and name in (
                "complex", "frozenset", "set", "slice", "range"):
            return super().find_class(module, name)
        if (module, name) in self._ALLOWED:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"wire payload references forbidden class {module}.{name}"
        )


# opcodes that construct buffer-backed objects WITHOUT any class lookup
# (protocol 5): find_class can't see them, so they are refused by opcode
# pre-scan.  A hostile BYTEARRAY8 stream can otherwise build an ndarray
# BACKED by a bytearray inside a memo cycle — the GC then deallocates the
# bytearray while its buffer is still exported, an unraisable SystemError
# per frame (found by fuzz/hostile.py).
_FORBIDDEN_PICKLE_OPS = frozenset(
    {"BYTEARRAY8", "NEXT_BUFFER", "READONLY_BUFFER"})


def wire_loads(raw: bytes):
    """pickle.loads restricted to the wire-payload vocabulary (see
    _WireUnpickler); raises pickle.UnpicklingError on anything else.
    The stream is opcode-scanned (pickletools.genops — parse only, zero
    execution) BEFORE the unpickler runs: buffer-constructing opcodes
    bypass find_class entirely and are rejected here."""
    import io

    if b"\x96" in raw or b"\x97" in raw or b"\x98" in raw:
        # cheap prefilter: the three forbidden opcodes are these exact
        # bytes, so a clean frame (no 0x96/0x97/0x98 anywhere, the vast
        # majority) skips the pure-Python genops walk entirely; a hit —
        # possibly a false positive inside string/bytes data — pays the
        # exact opcode-level scan
        import pickletools

        try:
            for op, _arg, _pos in pickletools.genops(raw):
                if op.name in _FORBIDDEN_PICKLE_OPS:
                    raise pickle.UnpicklingError(
                        f"wire payload uses forbidden opcode {op.name}")
        except pickle.UnpicklingError:
            raise
        except Exception as e:  # noqa: BLE001 — unparseable stream
            raise pickle.UnpicklingError(
                f"unparseable pickle stream: {e}") from e
    return _WireUnpickler(io.BytesIO(raw)).load()


def _load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        # ROUND_TPU_NATIVE_SO points at a prebuilt alternative library
        # (the sanitizer builds: `make san` -> libroundnet-tsan.so /
        # libroundnet-asan.so) and skips the default build entirely
        override = os.environ.get("ROUND_TPU_NATIVE_SO")
        if override:
            lib = ctypes.CDLL(override)
        else:
            # cross-PROCESS build lock: replicas start concurrently (one
            # OS process each) and must not race `make` writing the same
            # .so
            import fcntl

            os.makedirs(os.path.join(_NATIVE_DIR, "_build"),
                        exist_ok=True)
            with open(os.path.join(_NATIVE_DIR, "_build", ".lock"),
                      "w") as lk:
                fcntl.flock(lk, fcntl.LOCK_EX)
                # build only the transport library: the sat solver
                # binary is an unrelated target and must not gate (or
                # slow) replica startup
                subprocess.run(
                    ["make", "-s", "_build/libroundnet.so"],
                    cwd=_NATIVE_DIR, check=True, capture_output=True,
                )
            lib = ctypes.CDLL(
                os.path.join(_NATIVE_DIR, "_build", "libroundnet.so")
            )
        lib.rt_node_create.restype = ctypes.c_void_p
        lib.rt_node_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.rt_node_create_udp.restype = ctypes.c_void_p
        lib.rt_node_create_udp.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.rt_node_create_tls.restype = ctypes.c_void_p
        lib.rt_node_create_tls.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p
        ]
        lib.rt_node_port.restype = ctypes.c_int
        lib.rt_node_port.argtypes = [ctypes.c_void_p]
        lib.rt_node_add_peer.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int
        ]
        lib.rt_node_remove_peer.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rt_node_set_id.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rt_node_connected.restype = ctypes.c_int
        lib.rt_node_connected.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rt_node_connect.restype = ctypes.c_int
        lib.rt_node_connect.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int
        ]
        lib.rt_node_send.restype = ctypes.c_int
        # POINTER(c_char), not c_char_p: flush() passes the per-dest batch
        # bytearray via from_buffer (no bytes copy); plain bytes still
        # convert implicitly
        lib.rt_node_send.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_char), ctypes.c_int,
        ]
        lib.rt_node_recv.restype = ctypes.c_int
        lib.rt_node_recv.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_char_p, ctypes.c_int,
            ctypes.c_int,
        ]
        lib.rt_node_recv_many.restype = ctypes.c_int
        lib.rt_node_recv_many.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.rt_node_dropped.restype = ctypes.c_uint64
        lib.rt_node_dropped.argtypes = [ctypes.c_void_p]
        # bounded-inbox / backpressure API (overload hardening; tolerate
        # a stale prebuilt .so — the surface then reports no backpressure
        # and the default caps stay native-side)
        try:
            lib.rt_node_backpressure.restype = ctypes.c_int
            lib.rt_node_backpressure.argtypes = [ctypes.c_void_p]
            lib.rt_node_inbox_bytes.restype = ctypes.c_uint64
            lib.rt_node_inbox_bytes.argtypes = [ctypes.c_void_p]
            lib.rt_node_set_inbox_limits.restype = ctypes.c_int
            lib.rt_node_set_inbox_limits.argtypes = [
                ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
                ctypes.c_longlong, ctypes.c_longlong,
            ]
            lib._has_bp = True
        except AttributeError:  # pragma: no cover - stale prebuilt .so
            lib._has_bp = False
        # native per-peer send-pause API (the pump-flush mirror of the
        # Python-surface pause below; same stale-.so tolerance)
        try:
            lib.rt_node_send_pause_stats.restype = ctypes.c_int
            lib.rt_node_send_pause_stats.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_ulonglong)]
            lib.rt_node_set_send_pause.restype = ctypes.c_int
            lib.rt_node_set_send_pause.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
            lib._has_pause = True
        except AttributeError:  # pragma: no cover - stale prebuilt .so
            lib._has_pause = False
        lib.rt_node_stop.argtypes = [ctypes.c_void_p]
        lib.rt_node_destroy.argtypes = [ctypes.c_void_p]
        # round pump API (native round state machine; tolerate an older
        # .so without it — enable_pump then reports unavailable and the
        # drivers keep the Python pump)
        try:
            lib.rt_pump_enable.restype = ctypes.c_int
            lib.rt_pump_enable.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p,
            ]
            lib.rt_pump_disable.argtypes = [ctypes.c_void_p]
            lib.rt_pump_set_class.restype = ctypes.c_int
            lib.rt_pump_set_class.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_char), ctypes.c_int,
                ctypes.POINTER(ctypes.c_char), ctypes.c_int,
                ctypes.POINTER(ctypes.c_char), ctypes.c_int,
                ctypes.c_void_p, ctypes.c_void_p,
            ]
            lib.rt_pump_open_lane.restype = ctypes.c_int
            lib.rt_pump_open_lane.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
            lib.rt_pump_close_lane.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.rt_pump_arm.restype = ctypes.c_int
            lib.rt_pump_arm.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_longlong,
                ctypes.c_int, ctypes.c_longlong, ctypes.c_uint32,
                ctypes.c_int, ctypes.c_int, ctypes.c_uint8,
            ]
            lib.rt_pump_arm_many.restype = ctypes.c_int
            lib.rt_pump_arm_many.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_char), ctypes.c_int]
            lib.rt_pump_disarm.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.rt_pump_wait.restype = ctypes.c_int
            lib.rt_pump_wait.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
            ]
            lib.rt_pump_wait_lane.restype = ctypes.c_int
            lib.rt_pump_wait_lane.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
            lib.rt_pump_poke.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.rt_pump_feed.restype = ctypes.c_int
            lib.rt_pump_feed.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_char), ctypes.c_int,
            ]
            lib.rt_pump_insert.restype = ctypes.c_int
            lib.rt_pump_insert.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_char), ctypes.c_int,
            ]
            lib.rt_pump_mark_malformed.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
            lib.rt_pump_flush.restype = ctypes.c_int
            lib.rt_pump_flush.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_char),
                ctypes.POINTER(ctypes.c_char), ctypes.c_int, ctypes.c_int,
                ctypes.c_void_p,
            ]
            lib._has_pump = True
        except AttributeError:  # pragma: no cover - stale prebuilt .so
            lib._has_pump = False
        _lib = lib
        return lib


def native_available() -> bool:
    """True when the native transport library builds/loads in this
    environment — the skip-not-fail guard for toolchain-less CI boxes
    (tests skip native-path suites instead of failing tier-1)."""
    try:
        _load()
        return True
    except Exception:  # noqa: BLE001 — missing toolchain, broken gcc, ...
        return False


class HostTransport:
    """One node of the host runtime: a listening socket + lazy outbound
    connections, sending/receiving Tag+payload frames.

    `port=0` binds an ephemeral port (read it back from `.port` — the test
    harness pattern; fixed ports mirror the reference's XML peer lists,
    Config.scala:6-27).

    `proto="udp"` switches to the datagram transport — the reference's
    default perf transport shape (UdpRuntime.scala:19-96): drop-tolerant,
    no reconnect state, one datagram per message (payloads over ~64 KiB
    fail at send).

    `proto="tls"` runs the framed TCP protocol inside TLS — the
    reference's TCP_SSL mode (TcpRuntime.scala:143-158).  Pass PEM paths
    via `cert_file`/`key_file`, or leave both None for a per-process
    SELF-SIGNED pair (the reference's SelfSignedCertificate fallback,
    RuntimeOptions.scala:51-67).  Matching the reference's insecure-trust
    default for self-signed deployments, peers do NOT verify certificate
    chains: TLS provides channel privacy/integrity, not authentication."""

    def __init__(self, node_id: int, port: int = 0, proto: str = "tcp",
                 cert_file: Optional[str] = None,
                 key_file: Optional[str] = None):
        if proto not in ("tcp", "udp", "tls"):
            raise ValueError(f"proto must be tcp, udp or tls, got {proto!r}")
        self._lib = _load()
        self.id = node_id
        self.proto = proto
        if proto == "tls":
            if (cert_file is None) != (key_file is None):
                raise ValueError("supply both cert_file and key_file, "
                                 "or neither (self-signed fallback)")
            if cert_file is None:
                cert_file, key_file = _self_signed_pair()
            self._node = self._lib.rt_node_create_tls(
                node_id, port, cert_file.encode(), key_file.encode(),
            )
        else:
            create = (self._lib.rt_node_create_udp if proto == "udp"
                      else self._lib.rt_node_create)
            self._node = create(node_id, port)
        if not self._node:
            raise OSError(f"could not bind node {node_id} on port {port}"
                          + (" (TLS: libssl or certificate unavailable)"
                             if proto == "tls" else ""))
        self.port = self._lib.rt_node_port(self._node)
        self._buf = ctypes.create_string_buffer(1 << 20)
        self.closed = False  # set once recv observes the stopped node
        # logical frames already pulled off the native inbox (a batched
        # drain copies EVERY queued native message out in one ctypes call
        # and splits FLAG_BATCH containers by header peek; payload slices
        # are memoryviews into that one immutable copy — zero per-frame
        # copies).  deque ops are atomic under the GIL; concurrent recv
        # callers interleave exactly like they did on the native inbox.
        self._rx: collections.deque = collections.deque()
        # per-destination coalescing buffers: send_buffered() accumulates
        # `u64 tag | u32 len | payload` entries, flush() ships each as ONE
        # FLAG_BATCH wire frame (the Netty write-coalescing role;
        # comm-closure makes round-boundary flushing safe).  The size cap
        # bounds a batch (UDP: a datagram must hold it); the LATENCY cap
        # is structural — HostRunner flushes at every round boundary.
        self.batch_cap = (48 << 10) if proto == "udp" else (1 << 20)
        self._out: Dict[int, list] = {}  # dest -> [bytearray, frame count]
        self._out_lock = threading.Lock()
        # live peer table mirror (pid -> (host, port)): the native layer
        # keeps its own map, but rewire() needs to DIFF old vs new and the
        # reconnect loop needs something to iterate — one lock guards both
        self._peers: Dict[int, Tuple[str, int]] = {}
        self._peers_lock = threading.Lock()
        self.reconnects = 0           # channels re-established by the loop
        self._reconn_stop: Optional[threading.Event] = None
        self._reconn_thread: Optional[threading.Thread] = None
        self._on_reconnect = None     # churn observer (start_reconnect)
        # serializes rewire() against the reconnect loop's dials: a dial
        # that READS a pid's address before rewire and INSTALLS the
        # channel after it would permanently wire that pid to the old
        # replica (observed: a renamed replica's reconnect thread redialed
        # severed peers mid-rewire and resurrected the pre-change mapping)
        self._churn_lock = threading.Lock()
        self._pump: Optional["RoundPump"] = None
        # overload hardening (docs/HOST_FAULT_MODEL.md): per-peer send
        # PAUSE — after `pause_after` consecutive send failures to one
        # peer, sends to it drop-with-count for `pause_ms` instead of
        # re-dialing on every frame (the reconnect loop keeps probing in
        # the background; a successful send or reconnect resumes
        # immediately).  The bookkeeping is deliberately UNLOCKED:
        # every individual dict op is GIL-atomic, and the worst a racing
        # pair of senders can do is under-count a consecutive failure or
        # briefly clear a just-installed pause — either delays the pause
        # by a frame, never corrupts state.  Taking _out_lock here would
        # put a lock acquisition on every hot-path send for a pathology
        # that only matters while a peer is already dead.  The native
        # pump-flush path keeps its own mirror of this pause (transport
        # .cpp send_msg), folded into the same counters by the drain
        # path's _poll_backpressure.
        self.pause_after = 16
        self.pause_ms = 250
        self._send_fails: Dict[int, int] = {}
        self._paused_until: Dict[int, float] = {}
        self.backpressure_events = 0   # rising edges observed (wire.
        self._bp_last = False          # backpressure counts the same)
        self._np_pauses = 0            # native send-pause counters last
        self._np_drops = 0             # folded into METRICS (drain path)

    # the native rt_pump_flush send path may be used on THIS transport —
    # but only while its Python send surface is the stock one: a fault
    # wrapper (chaos.FaultyTransport does not re-export this property), a
    # subclass override, or a monkey-patched send/send_buffered (the
    # loss-injecting test doubles) must keep seeing every frame, so the
    # drivers then stay on the per-frame send_buffered surface
    @property
    def pump_send_ok(self) -> bool:
        return ("send" not in self.__dict__
                and "send_buffered" not in self.__dict__
                and type(self).send_buffered is HostTransport.send_buffered
                and type(self).send is HostTransport.send)

    def enable_pump(self, L: int, n: int, k: int,
                    nbz: int = 0) -> Optional["RoundPump"]:
        """Attach (or reconfigure) the native round pump: L lanes over n
        processes and k round classes.  Returns None — and callers keep
        the Python pump — when the native side lacks the pump API (stale
        prebuilt .so) or ``ROUND_TPU_PUMP=0`` disables it."""
        if os.environ.get("ROUND_TPU_PUMP", "1") == "0":
            return None
        if not self._node or not getattr(self._lib, "_has_pump", False):
            return None
        if self._pump is not None:
            self._pump.close()
        self._pump = RoundPump(self, L, n, k, nbz)
        return self._pump

    def disable_pump(self) -> None:
        if self._pump is not None:
            self._pump.close()
            self._pump = None

    # -- overload / backpressure surface -----------------------------------

    @property
    def backpressure(self) -> bool:
        """True while the native inbox sits above its byte high watermark
        (the level form of the pump's kReadyBackpr reason bit)."""
        if not self._node or not getattr(self._lib, "_has_bp", False):
            return False
        return bool(self._lib.rt_node_backpressure(self._node))

    @property
    def inbox_bytes(self) -> int:
        if not self._node or not getattr(self._lib, "_has_bp", False):
            return 0
        return int(self._lib.rt_node_inbox_bytes(self._node))

    def set_inbox_limits(self, max_msgs: int = 0, max_bytes: int = 0,
                         high: int = 0, low: int = 0) -> bool:
        """Configure the bounded native inbox (0 keeps a value).  The
        ladder low <= high <= max_bytes is enforced natively."""
        if not self._node or not getattr(self._lib, "_has_bp", False):
            return False
        return self._lib.rt_node_set_inbox_limits(
            self._node, max_msgs, max_bytes, high, low) == 0

    def _poll_backpressure(self) -> bool:
        """Edge-detect the native backpressure level into the
        ``wire.backpressure`` counter, and fold the NATIVE send-pause
        counters (pump-flush sends to a dead peer pause inside
        transport.cpp's send_msg) into the shared ``wire.peer_pauses`` /
        ``wire.backpressure_drops`` vocabulary (called from the drain
        path — the only place these can change without us noticing)."""
        cur = self.backpressure
        if cur and not self._bp_last:
            self.backpressure_events += 1
            _C_BACKPRESSURE.inc()
            if TRACE.enabled:
                TRACE.emit("wire_backpressure", node=self.id,
                           inbox_bytes=self.inbox_bytes)
        self._bp_last = cur
        if self._node and getattr(self._lib, "_has_pause", False):
            out = (ctypes.c_ulonglong * 2)()
            self._lib.rt_node_send_pause_stats(self._node, out)
            dp = int(out[0]) - self._np_pauses
            dd = int(out[1]) - self._np_drops
            if dp > 0:
                _C_PEER_PAUSES.inc(dp)
            if dd > 0:
                _C_BP_DROPS.inc(dd)
            self._np_pauses, self._np_drops = int(out[0]), int(out[1])
        return cur

    def _send_paused(self, dest: int) -> bool:
        """True while dest's send path is paused (caller holds _out_lock
        or tolerates a stale read — a stray frame either way)."""
        until = self._paused_until.get(dest)
        if until is None:
            return False
        if _time.monotonic() >= until:
            self._paused_until.pop(dest, None)
            # probe posture past expiry: ONE failed send re-engages the
            # pause (a success clears the count via _note_send)
            self._send_fails[dest] = self.pause_after - 1
            return False
        return True

    def _note_send(self, dest: int, ok: bool) -> None:
        if ok:
            if self._send_fails.pop(dest, 0):
                self._paused_until.pop(dest, None)
            return
        fails = self._send_fails.get(dest, 0) + 1
        self._send_fails[dest] = fails
        if fails >= self.pause_after and dest not in self._paused_until:
            self._paused_until[dest] = _time.monotonic() + self.pause_ms / 1e3
            _C_PEER_PAUSES.inc()
            if TRACE.enabled:
                TRACE.emit("peer_pause", node=self.id, dst=dest,
                           fails=fails, pause_ms=self.pause_ms)

    def resume_peer(self, dest: int) -> None:
        """Clear a peer's send pause (a successful reconnect proves it is
        back — called by the reconnect loop; the NATIVE mirror clears
        itself on any successful dial)."""
        self._send_fails.pop(dest, None)
        self._paused_until.pop(dest, None)

    def set_send_pause(self, after: int = 0, ms: int = 0) -> bool:
        """Configure the NATIVE per-peer send pause (0 keeps a value);
        the Python-surface ``pause_after``/``pause_ms`` fields above are
        an independent mirror guarding the Python send entry points."""
        if not self._node or not getattr(self._lib, "_has_pause", False):
            return False
        return self._lib.rt_node_set_send_pause(self._node, after, ms) == 0

    def add_peer(self, peer_id: int, host: str, port: int) -> None:
        if not self._node:
            return  # closed: nothing to register on
        with self._peers_lock:
            self._peers[peer_id] = (host, port)
        self._lib.rt_node_add_peer(
            self._node, peer_id, host.encode(), port
        )

    def remove_peer(self, peer_id: int) -> None:
        """Forget a peer: sever its channel and drop its address.  The
        reconnect loop stops dialing it; sends to it fail."""
        if not self._node:
            return
        with self._peers_lock:
            self._peers.pop(peer_id, None)
        self._lib.rt_node_remove_peer(self._node, peer_id)

    def connected(self, peer_id: int) -> bool:
        """True when a live channel to the peer exists (UDP: when its
        address is registered — datagrams have no channel state)."""
        if not self._node:
            return False
        return bool(self._lib.rt_node_connected(self._node, peer_id))

    def rewire(self, peers: Dict[int, Tuple[str, int]],
               my_id: Optional[int] = None) -> Dict[str, int]:
        """Swap the live peer table to ``peers`` (pid -> (host, port), our
        own entry skipped) on a RUNNING node — the wire half of a view
        change (TcpRuntime.scala:75-110 rewiring when the group changes).

        Unchanged (pid, address) pairs keep their connections; added peers
        are registered (the reconnect loop or the next send dials them);
        removed pids are severed; a pid whose address changed — which is
        what an id-compaction rename looks like from the outside
        (Replicas.scala:136-142) — is severed and re-registered so the
        fresh channel handshakes under the NEW ids.  ``my_id`` renames
        this node itself — and that severs EVERY existing channel, even to
        address-unchanged peers: their inbound attribution of this node
        was fixed by the handshake at connect time, so a kept channel
        would stamp our frames with the OLD id forever (observed as one
        renamed replica wire-isolated after a remove: its traffic folded
        into another pid's mailbox slot and its catch-up replies routed to
        that other replica).  Returns the {added, removed, readdressed,
        rehandshaked} counts for callers' trace events."""
        stats = {"added": 0, "removed": 0, "readdressed": 0,
                 "rehandshaked": 0}
        if not self._node:
            return stats
        self._churn_lock.acquire()
        try:
            return self._rewire_locked(peers, my_id, stats)
        finally:
            self._churn_lock.release()

    def _rewire_locked(self, peers, my_id, stats):
        renamed = my_id is not None and my_id != self.id
        if renamed:
            self._lib.rt_node_set_id(self._node, my_id)
            self.id = my_id
        with self._peers_lock:
            old = dict(self._peers)
        me = self.id
        for pid in old:
            if pid not in peers or pid == me:
                self.remove_peer(pid)
                stats["removed"] += 1
        for pid, (host, port) in peers.items():
            if pid == me:
                continue
            cur = old.get(pid)
            if cur == (host, port) and not renamed:
                continue
            if cur is not None:
                # sever before re-registering: the old channel either
                # points at a DIFFERENT replica now (readdressed pid) or
                # carries our OLD handshake id (we were renamed) — both
                # mis-attribute every frame sent on them
                self._lib.rt_node_remove_peer(self._node, pid)
                stats["rehandshaked" if cur == (host, port)
                      else "readdressed"] += 1
            else:
                stats["added"] += 1
            self.add_peer(pid, host, port)
        _C_WIRE_REWIRE.inc()
        if TRACE.enabled:
            TRACE.emit("wire_rewire", node=self.id, **stats)
        return stats

    def start_reconnect(self, period_ms: int = 200, backoff: float = 2.0,
                        max_backoff_ms: int = 3200,
                        connect_timeout_ms: int = 250,
                        on_reconnect=None) -> None:
        """Start the periodic auto-reconnect loop: every ``period_ms`` each
        registered peer without a live channel is re-dialed, failures
        backing off exponentially per peer up to ``max_backoff_ms`` (the
        reference redials dead peers on a period, TcpRuntime.scala:
        162-211; without this a peer that only ever LISTENS — it has no
        send to piggyback the redial on — stays dark forever after a
        restart).  Idempotent; stop()/close() ends the loop."""
        if self._reconn_thread is not None and self._reconn_thread.is_alive():
            return
        # optional churn observer (pid -> None), e.g. PeerHealth.
        # note_reconnect: reconnect churn is a health signal
        self._on_reconnect = on_reconnect
        self._reconn_stop = threading.Event()
        self._reconn_thread = threading.Thread(
            target=self._reconnect_loop,
            args=(self._reconn_stop, period_ms / 1000.0, backoff,
                  max_backoff_ms / 1000.0, connect_timeout_ms),
            daemon=True,
        )
        self._reconn_thread.start()

    def _reconnect_loop(self, stop: threading.Event, period: float,
                        backoff: float, max_wait: float,
                        connect_timeout_ms: int) -> None:
        next_try: Dict[int, float] = {}
        wait: Dict[int, float] = {}
        while not stop.wait(period):
            if not self._node or self.closed:
                return
            with self._peers_lock:
                peers = list(self._peers)
            now = _time.monotonic()
            for pid in peers:
                # per-peer churn-lock scope: the check-then-dial must not
                # SPAN a rewire (it would install a channel to the pid's
                # pre-rewire address), but rewire may interleave between
                # peers — a dial blocks it for at most connect_timeout_ms
                with self._churn_lock:
                    with self._peers_lock:
                        if pid not in self._peers:
                            continue  # rewired away since the snapshot
                    if self.connected(pid):
                        next_try.pop(pid, None)
                        wait.pop(pid, None)
                        continue
                    if now < next_try.get(pid, 0.0):
                        continue
                    node = self._node
                    if not node:
                        return
                    ok = self._lib.rt_node_connect(
                        node, pid, connect_timeout_ms) == 0
                if ok:
                    self.reconnects += 1
                    _C_WIRE_RECONNECT.inc()
                    self.resume_peer(pid)  # a live channel ends the pause
                    cb = self._on_reconnect
                    if cb is not None:
                        try:
                            cb(pid)
                        except Exception:  # noqa: BLE001 — an observer
                            pass           # must never kill the loop
                    if TRACE.enabled:
                        TRACE.emit("wire_reconnect", node=self.id, dst=pid)
                    next_try.pop(pid, None)
                    wait.pop(pid, None)
                else:
                    w = min(max_wait, wait.get(pid, period) * backoff)
                    wait[pid] = w
                    next_try[pid] = _time.monotonic() + w

    def send(self, to: int, tag: Tag, payload: bytes = b"") -> bool:
        """False when the peer is unreachable (reconnect is retried on the
        next send, TcpRuntime.scala:162-211 semantics)."""
        if not self._node:
            return False  # closed: a racing late send must not deref the
            # freed native node (crash-restart teardown hardening)
        if self._send_paused(to):
            _C_BP_DROPS.inc()
            return False
        rc = self._lib.rt_node_send(
            self._node, to, tag.pack() & 0xFFFFFFFFFFFFFFFF, bytes(payload)
            if not isinstance(payload, bytes) else payload, len(payload),
        )
        self._note_send(to, rc == 0)
        if rc == 0:
            _C_WIRE_SENT.inc()
            _C_WIRE_SENT_B.inc(len(payload))
        return rc == 0

    # -- frame coalescing (the hot-path send of runtime/host.py) -----------

    def send_buffered(self, to: int, tag: Tag, payload=b"") -> bool:
        """Queue one logical frame for ``to``; it travels inside the next
        flush()'s FLAG_BATCH container (one native send + one syscall for
        every frame queued to that destination since the last flush).
        ``payload`` may be any bytes-like (the hot path hands the SAME
        scratch memoryview to every destination — encode once, copy once
        per destination, no intermediate bytes objects).  A buffer that
        would outgrow ``batch_cap`` is flushed first (UDP: a datagram must
        carry the whole batch).  Returns False when the node is closed."""
        if not self._node:
            return False
        if self._send_paused(to):
            # bounded-memory discipline: a paused peer's frames drop with
            # a count instead of accumulating (re-dial on every frame is
            # exactly what the pause exists to stop)
            _C_BP_DROPS.inc()
            return False
        entry_len = 12 + len(payload)
        with self._out_lock:
            ent = self._out.get(to)
            if ent is None:
                ent = self._out[to] = [bytearray(), 0]
            if ent[1] and len(ent[0]) + entry_len > self.batch_cap:
                self._flush_one(to, ent)
            ent[0] += _BATCH_HDR.pack(tag.pack() & 0xFFFFFFFFFFFFFFFF,
                                      len(payload))
            ent[0] += payload
            ent[1] += 1
        return True

    def flush(self, to: Optional[int] = None) -> int:
        """Ship every buffered frame (or only ``to``'s) as FLAG_BATCH
        container frames — the round-boundary call of HostRunner.  Returns
        the number of logical frames flushed."""
        if not self._node:
            return 0
        total = 0
        with self._out_lock:
            for dest, ent in (self._out.items() if to is None
                              else [(to, self._out.get(to))]):
                if ent is None or not ent[1]:
                    continue
                total += ent[1]
                self._flush_one(dest, ent)
        return total

    def _flush_one(self, dest: int, ent: list) -> None:
        """Send one destination's batch (caller holds _out_lock — sends
        are serialized per destination, preserving frame order).  A
        single queued frame ships as a PLAIN frame — the container only
        pays for itself from two frames up (a sequential round queues
        exactly one frame per peer; the pipelined window and
        retransmission bursts are what coalesce).  The container tag
        carries the frame count in its round field (a recv-side sanity
        cross-check and a free stat)."""
        buf, count = ent
        if count == 1:
            subtag, ln = _BATCH_HDR.unpack_from(buf, 0)
            rc = self._lib.rt_node_send(
                self._node, dest, subtag,
                (ctypes.c_char * ln).from_buffer(buf, 12), ln,
            )
            if rc == 0:
                _C_WIRE_SENT.inc()
                _C_WIRE_SENT_B.inc(ln)
        else:
            tag = Tag(instance=0, round=count, flag=FLAG_BATCH)
            rc = self._lib.rt_node_send(
                self._node, dest, tag.pack() & 0xFFFFFFFFFFFFFFFF,
                (ctypes.c_char * len(buf)).from_buffer(buf), len(buf),
            )
            if rc == 0:
                _C_WIRE_SENT.inc(count)
                _C_WIRE_SENT_B.inc(len(buf) - 12 * count)
                _C_BATCHES.inc()
                _C_BATCH_FRAMES.inc(count)
                _C_BATCH_BYTES.inc(len(buf))
        self._note_send(dest, rc == 0)
        ent[0] = bytearray()
        ent[1] = 0

    # -- receive -----------------------------------------------------------

    def recv(self, timeout_ms: int) -> Optional[Tuple[int, Tag, bytes]]:
        """One logical frame: (sender, tag, payload).  Payloads of frames
        that traveled in a batched drain are memoryviews into the drain's
        single copy (compare equal to bytes; hand to np.frombuffer for
        zero-copy decode)."""
        rx = self._rx
        while True:
            try:
                return rx.popleft()
            except IndexError:
                pass
            if not self._fill(timeout_ms):
                return None
            timeout_ms = 0  # only loop again for an all-garbage drain

    def recv_many(self, timeout_ms: int) -> List[Tuple[int, Tag, bytes]]:
        """Every logical frame currently available, in one batched native
        drain (plus anything already split): the HostRunner/mux drain
        primitive.  Waits up to ``timeout_ms`` only when nothing is
        pending; an empty list means timeout/closed."""
        rx = self._rx
        if not rx:
            self._fill(timeout_ms)
        elif self._node:
            self._fill(0)  # opportunistic: append what is already queued
        out = list(rx)
        rx.clear()
        return out

    def _fill(self, timeout_ms: int) -> bool:
        """One native batched drain into the rx deque: EVERY queued native
        message copies out in ONE ctypes call, FLAG_BATCH containers are
        split by header peek (memoryview slices — payload bytes are never
        re-copied).  False when nothing arrived (timeout/closed)."""
        if not self._node:
            return False
        # edge-count wire.backpressure BEFORE the drain: the pop path
        # clears the level at the low watermark, so polling after would
        # never observe the rising edge it exists to record
        self._poll_backpressure()
        nb = ctypes.c_int()
        k = self._lib.rt_node_recv_many(
            self._node, self._buf, len(self._buf), timeout_ms,
            ctypes.byref(nb),
        )
        if k == 0:
            return False
        if k == -3:  # node stopped: no more messages will ever arrive
            self.closed = True
            return False
        if k == -2:  # grow and retry (message stays queued, so retry with
            # timeout 0: it is returned immediately — a full-timeout retry
            # would let one logical recv block up to 2x the requested
            # deadline and skew HostRunner's round accounting)
            self._buf = ctypes.create_string_buffer(len(self._buf) * 4)
            return self._fill(0)
        mv = memoryview(ctypes.string_at(self._buf, nb.value))
        rx = self._rx
        off = 0
        frames = payload_b = 0
        for _ in range(k):
            from_id, tagw, ln = _RECV_HDR.unpack_from(mv, off)
            off += 16
            payload = mv[off:off + ln]
            off += ln
            word = _to_signed64(tagw)
            if (word & 0xFF) == FLAG_BATCH:
                n_sub = self._split_batch(from_id, payload, rx)
                frames += n_sub
                payload_b += len(payload) - 12 * n_sub
            else:
                rx.append((from_id, Tag.unpack(word), payload))
                frames += 1
                payload_b += ln
        if frames:
            _C_WIRE_RECV.inc(frames)
            _C_WIRE_RECV_B.inc(payload_b)
        return True

    @staticmethod
    def _split_batch(from_id: int, mv, rx) -> int:
        """Split one FLAG_BATCH container into logical frames by header
        peek (no payload copy — sub-slices of the drain's memoryview).
        A malformed container (truncated header/length from a byzantine
        peer; honest senders can't produce one) keeps its parseable
        prefix and drops the rest — the per-message garbage tolerance of
        this wire, applied at the framing layer."""
        off, end = 0, len(mv)
        n = 0
        while off + 12 <= end:
            subtag, ln = _BATCH_HDR.unpack_from(mv, off)
            off += 12
            if off + ln > end:
                METRICS.counter("wire.batch_malformed").inc()
                return n
            rx.append((from_id, Tag.unpack(_to_signed64(subtag)),
                       mv[off:off + ln]))
            off += ln
            n += 1
        if off != end:  # trailing bytes shorter than a sub-frame header
            METRICS.counter("wire.batch_malformed").inc()
        return n

    @property
    def dropped(self) -> int:
        if not self._node:
            return 0  # closed (see send)
        return int(self._lib.rt_node_dropped(self._node))

    def stop(self) -> None:
        """Stop the node without freeing it: blocked recv() calls in other
        threads return None (and flag `closed`) so they can unwind before
        close() frees the native object.  Idempotent."""
        self._stop_reconnect()
        if self._node:
            self._lib.rt_node_stop(self._node)

    def _stop_reconnect(self) -> None:
        if self._reconn_stop is not None:
            self._reconn_stop.set()
        t = self._reconn_thread
        if t is not None and t.is_alive():
            t.join(timeout=5)
        self._reconn_thread = None

    def close(self) -> None:
        """Free the node.  Callers with receiver threads must stop() and
        join them first (tests/test_host.py::test_lock_manager_service is
        the pattern)."""
        self._stop_reconnect()
        if self._node:
            self.disable_pump()
            self._lib.rt_node_stop(self._node)
            self._lib.rt_node_destroy(self._node)
            self._node = None
            self.closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RoundPump:
    """Python handle on the NATIVE round pump (native/transport.cpp
    rt_pump_*): the per-round receive state machine — FLAG_BATCH split,
    codec-template parse, in-place mailbox fill, arrival bitmasks,
    deadline bookkeeping — runs inside the transport event loop with no
    GIL held, and the driver blocks in ONE call (`wait`) per round wave.

    The pump exposes SHARED numpy buffers by pointer: ``max_rnd`` [L, n]
    and ``next_round`` [L] (the catch-up bookkeeping the drivers used to
    maintain per message), and ``stats`` (folded into the ``pump.*``
    metrics by :meth:`bank_metrics`).  Mailbox buffers are registered per
    (lane, round-class) via :meth:`set_class` — they are the drivers' own
    preallocated arrays, written natively only while the lane is ARMED.

    Obtain one via ``HostTransport.enable_pump`` (or through
    ``FaultyTransport``, which delegates when its fault plan has no
    receiver-side families).  ``None`` from those calls means the pump is
    unavailable (older .so, ``ROUND_TPU_PUMP=0``) and callers keep the
    Python pump — the automatic-fallback contract."""

    # arm flags (native kPump*)
    F_GROWTH, F_EXTEND, F_STRICT = 1, 2, 4
    # ready reasons (native kReady*)
    R_THRESH, R_GROWTH, R_SKEW, R_DEADLINE, R_POKE = 1, 2, 4, 8, 16
    R_BACKPR = 32  # inbox byte high watermark: the waiter must drain
    R_ROUND_END = R_THRESH | R_SKEW | R_DEADLINE  # default auto-disarm set

    _ARM = struct.Struct("<iiiqIiiB")
    _ENTRY = struct.Struct("<iQII")
    _LEAF = struct.Struct("<QI")
    _HOLE = struct.Struct("<III")

    def __init__(self, transport: "HostTransport", L: int, n: int, k: int,
                 nbz: int = 0):
        self._tr = transport
        self._lib = transport._lib
        self.L, self.n, self.k, self.nbz = L, n, k, nbz
        self.max_rnd = np.full((L, n), -1, dtype=np.int64)
        self.next_round = np.zeros((L,), dtype=np.int64)
        self.stats = np.zeros(16, dtype=np.uint64)
        self._banked = np.zeros(16, dtype=np.uint64)
        self.reasons = np.zeros(L, dtype=np.uint8)
        self._misc = ctypes.c_int()
        self._flush_stats = np.zeros(5, dtype=np.uint64)
        # registered mailbox arrays, pinned against GC: the native side
        # holds RAW pointers into them for the pump's lifetime
        self._pinned: list = []
        rc = self._lib.rt_pump_enable(
            transport._node, L, n, k, nbz,
            self.max_rnd.ctypes.data, self.next_round.ctypes.data,
            self.stats.ctypes.data)
        if rc != 0:
            raise OSError(f"rt_pump_enable failed (rc={rc})")

    def _node(self):
        node = self._tr._node
        if not node:
            raise RuntimeError("transport closed under the pump")
        return node

    def set_class(self, lane: int, cls: int, template: bytes, holes,
                  leaf_arrays, lane_index: int = 0, mask=None,
                  count=None, per_lane: bool = False) -> None:
        """Register one (lane, class) slot.  ``leaf_arrays`` are the
        driver's preallocated mailbox arrays in tree_flatten leaf order —
        ``[n, ...]`` (per_lane=False: a per-instance runner's own
        mailbox, mask ``[n]``, count ``[1]``) or ``[L, n, ...]``
        (per_lane=True: the lane driver's class box, row ``lane_index``,
        mask ``[L, n]``, count ``[L]``)."""
        leaves = bytearray()
        for arr in leaf_arrays:
            row_nbytes = arr.nbytes // arr.shape[0]
            if per_lane:
                base = arr.ctypes.data + lane_index * row_nbytes
                nbytes = row_nbytes // self.n
            else:
                base = arr.ctypes.data
                nbytes = row_nbytes
            leaves += self._LEAF.pack(base, nbytes)
        hb = bytearray()
        for off, nbytes, leaf in holes:
            hb += self._HOLE.pack(off, nbytes, leaf)
        if per_lane:
            mask_addr = mask.ctypes.data + lane_index * mask.shape[1]
            count_addr = count.ctypes.data + lane_index * 8
        else:
            mask_addr = mask.ctypes.data
            count_addr = count.ctypes.data
        self._pinned.append((mask, count, tuple(leaf_arrays)))
        rc = self._lib.rt_pump_set_class(
            self._node(), lane, cls,
            (ctypes.c_char * len(template)).from_buffer_copy(template),
            len(template),
            (ctypes.c_char * len(hb)).from_buffer(hb), len(hb) // 12,
            (ctypes.c_char * len(leaves)).from_buffer(leaves),
            len(leaves) // 12, mask_addr, count_addr)
        if rc != 0:
            raise ValueError("rt_pump_set_class rejected the registration")

    def open_lane(self, lane: int, iid: int) -> None:
        self.max_rnd[lane] = -1
        self.next_round[lane] = 0
        self._lib.rt_pump_open_lane(self._node(), lane, iid & 0xFFFF)

    def close_lane(self, lane: int) -> None:
        self._lib.rt_pump_close_lane(self._node(), lane)

    def arm(self, lane: int, rnd: int, cls: int, threshold: int,
            flags: int = 0, deadline_ms: int = 0, extend_ms: int = 0,
            auto_disarm: Optional[int] = None) -> None:
        self._lib.rt_pump_arm(
            self._node(), lane, rnd, cls, threshold, flags, deadline_ms,
            extend_ms,
            self.R_ROUND_END if auto_disarm is None else auto_disarm)

    def arm_specs(self, specs: bytearray, count: int) -> None:
        """Batched arm — one crossing per send wave.  ``specs`` is
        ``count`` packed ``_ARM`` records (lane, round, cls, threshold,
        flags, deadline_ms, extend_ms, auto_disarm)."""
        rc = self._lib.rt_pump_arm_many(
            self._node(), (ctypes.c_char * len(specs)).from_buffer(specs),
            count)
        if rc != 0:
            raise ValueError("rt_pump_arm_many rejected a spec")

    def disarm(self, lane: int) -> None:
        self._lib.rt_pump_disarm(self._node(), lane)

    def wait(self, timeout_ms: int) -> Tuple[int, bool]:
        """Block until a lane is ready, misc inbox traffic arrived, or
        the timeout; reasons land in ``self.reasons`` (consumed bits —
        round-ending reasons disarm atomically).  Returns
        (ready_lane_count, misc).  -3 (node stopped) returns (-1, False)
        so callers unwind."""
        rc = self._lib.rt_pump_wait(
            self._node(), self.reasons.ctypes.data, timeout_ms,
            ctypes.byref(self._misc))
        if rc == -3:
            return -1, False
        return rc, bool(self._misc.value)

    def wait_lane(self, lane: int, timeout_ms: int) -> int:
        """Single-lane wait (mux runners): the lane's consumed reason
        bits, 0 on timeout, -3 once the node stopped."""
        return self._lib.rt_pump_wait_lane(self._node(), lane, timeout_ms)

    def poke(self, lane: int) -> None:
        self._lib.rt_pump_poke(self._node(), lane)

    def feed(self, sender: int, tag: Tag, raw) -> int:
        """Run one frame through the native state machine from Python
        (stash replay, inbox-fallback re-routing): 1 consumed, 0 not
        pump-routable, -2 template miss at the armed current round."""
        b = raw if isinstance(raw, bytes) else bytes(raw)
        return self._lib.rt_pump_feed(
            self._node(), sender, tag.pack() & 0xFFFFFFFFFFFFFFFF,
            b, len(b))

    def insert(self, lane: int, sender: int, encoded: bytes) -> int:
        """Template-checked canonical insert under the pump lock (the
        bilingual fallback after a Python decode): 1 grew, 0 duplicate,
        -1 structural mismatch."""
        return self._lib.rt_pump_insert(
            self._node(), lane, sender, encoded, len(encoded))

    def mark_malformed(self, lane: int, sender: int) -> None:
        self._lib.rt_pump_mark_malformed(self._node(), lane, sender)

    def flush(self, base, entries: bytearray, count: int) -> int:
        """Ship one send wave: ``entries`` = ``count`` packed ``_ENTRY``
        records (dest, tag, off, len) into ``base`` (the wave's
        encode-once buffer).  One ctypes crossing coalesces per-peer
        FLAG_BATCH containers and does every syscall natively; wire.*
        counters are fed from the returned stats."""
        node = self._tr._node
        if not node:
            return 0
        frames = self._lib.rt_pump_flush(
            node, (ctypes.c_char * len(base)).from_buffer(base),
            (ctypes.c_char * len(entries)).from_buffer(entries), count,
            self._tr.batch_cap, self._flush_stats.ctypes.data)
        st = self._flush_stats
        if frames > 0:
            _C_WIRE_SENT.inc(int(st[0]))
            _C_WIRE_SENT_B.inc(int(st[1]))
            if st[2]:
                _C_BATCHES.inc(int(st[2]))
                _C_BATCH_FRAMES.inc(int(st[3]))
                _C_BATCH_BYTES.inc(int(st[4]))
        return frames

    # -- observability ------------------------------------------------------

    _STAT_NAMES = (
        "pump.fast_frames", "pump.dup_frames", "pump.pending_buffered",
        "pump.pending_applied", "pump.fallbacks", "pump.late_drops",
        "pump.malformed", "pump.waits", "pump.ready_wakes",
        "pump.misc_wakes", "pump.batches_split", "pump.batch_malformed",
    )

    def delta(self) -> np.ndarray:
        """Native stat deltas since the last bank_metrics() call."""
        return (self.stats - self._banked).astype(np.int64)

    def bank_metrics(self) -> np.ndarray:
        """Fold the native stat deltas into the unified ``pump.*``
        counters (docs/OBSERVABILITY.md); returns the deltas."""
        d = self.delta()
        for i, name in enumerate(self._STAT_NAMES):
            if d[i]:
                METRICS.counter(name).inc(int(d[i]))
        self._banked = self.stats.copy()
        return d

    def close(self) -> None:
        node = self._tr._node
        if node:
            self._lib.rt_pump_disable(node)


def _to_signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


# batched-drain record header (native/transport.cpp rt_node_recv_many:
# i32 from | u64 tag | u32 len, memcpy'd field-by-field — little-endian
# standard sizes match the x86-64 layout exactly) and the FLAG_BATCH
# sub-frame header (u64 tag | u32 len)
_RECV_HDR = struct.Struct("<iQI")
_BATCH_HDR = struct.Struct("<QI")


_SELF_SIGNED: Optional[Tuple[str, str]] = None
_self_signed_lock = threading.Lock()


def _self_signed_pair() -> Tuple[str, str]:
    """Generate (once per process) a self-signed cert+key for TLS mode —
    the reference's SelfSignedCertificate fallback (TcpRuntime.scala:
    143-149).  Uses the openssl CLI (the runtime library is present in
    this environment, its dev headers are not)."""
    global _SELF_SIGNED
    with _self_signed_lock:
        if _SELF_SIGNED is not None:
            return _SELF_SIGNED
        import tempfile

        d = tempfile.mkdtemp(prefix="round_tpu_tls_")
        cert, key = os.path.join(d, "cert.pem"), os.path.join(d, "key.pem")
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", key, "-out", cert, "-days", "2",
             "-subj", "/CN=round_tpu"],
            check=True, capture_output=True,
        )
        _SELF_SIGNED = (cert, key)
        return _SELF_SIGNED


class HostBus:
    """LocalBus surface over HostTransport: Message objects (runtime/oob.py)
    cross process boundaries with their Tag on the wire and the payload in
    the binary wire codec (runtime/codec.py; the Kryo role,
    utils/serialization in the reference — pytree payloads on the hot path
    never come through here; this is the control plane: decisions, probes,
    recovery).  Delivery decodes codec AND legacy pickle frames
    (codec.loads auto-detects), so mixed-version peers interoperate."""

    def __init__(self, transport: HostTransport):
        self.transport = transport
        self.node = None  # PoolNode, set by register()
        self.malformed = 0  # garbage wire payloads dropped (never a crash)

    def register(self, node) -> None:
        self.node = node
        node.bus = self

    def send(self, to: int, msg: Message) -> None:
        from round_tpu.runtime import codec

        self.transport.send(to, msg.tag, codec.encode(msg.payload))

    def deliver(self, node_id: Optional[int] = None,
                limit: Optional[int] = None, timeout_ms: int = 0) -> int:
        """Drain received messages into the registered node's
        default_handler (LocalBus.deliver semantics: a handler error does
        not discard the rest of the batch).  `node_id` is accepted for
        LocalBus signature compatibility — a HostBus has exactly one node."""
        from round_tpu.runtime import codec

        count = 0
        first_err: Optional[Exception] = None
        while limit is None or count < limit:
            got = self.transport.recv(timeout_ms if count == 0 else 0)
            if got is None:
                break
            from_id, tag, raw = got
            try:
                payload = codec.loads(raw) if raw else None
            except Exception:  # noqa: BLE001 — a garbage datagram on the
                # unauthenticated socket must never kill the control plane
                # (InstanceHandler.scala:392-399 tolerance); wire_loads also
                # refuses code-execution gadget classes outright
                self.malformed += 1
                continue
            count += 1
            try:
                self.node.default_handler(
                    Message(tag=tag, sender=from_id, payload=payload)
                )
            except Exception as e:  # noqa: BLE001 - per-message isolation
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err
        return count
