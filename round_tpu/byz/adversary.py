"""The value-adversary fault model as tensors + the engine hook.

Omission families answer "which links deliver"; the value adversary
answers "what a delivered frame CLAIMS".  Three primitives:

  * ``value_events`` — the ONE counter-hash formula deciding, per
    (round, src, dst), whether a byzantine-value sender substitutes its
    payload toward that destination and with which claimed value.
    Same murmur3 link hash as every other family
    (scenarios.link_bernoulli's mix) under two dedicated streams, so one
    (salt0, salt1) pair yields schedules independent of the omission
    families.  Per-destination draws make EQUIVOCATION the base case:
    the same sender in the same round claims different values to
    different receivers.

  * ``value_plan`` — the explicit ``[T, n, n] int32`` substitution plan
    (``plan[r, dst, src]``): ``VP_NONE`` = truthful, ``VP_STALE`` =
    replay the sender's previous transmission of this round class,
    ``v >= 0`` = claim value ``v``.  Bit-identical to what the hash
    formula draws (the row_sampler/row_schedule pin of PR 8, extended to
    the value dimension) — the form fuzz/minimize.py delta-debugs,
    fuzz/replay.py exports, and runtime/chaos.py replays on real wire.

  * ``ValueAdversary`` — the engine hook: executor.run_phases hands it
    the round's truthful payload tensor and it returns the per-receiver
    mailbox values, all inside the SAME jitted vmapped evaluation (fuzz
    throughput stays batched-dispatch-bound).  Stale replay carries each
    round class's last actually-SENT payload in the scan carry
    (``prev``), mirroring the host wire's per-class byte cache: a class
    never sent yet replays nothing (truthful delivery), identically on
    both worlds.
"""

from __future__ import annotations

import functools as _functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from round_tpu.byz.lies import LieFn, generic_lie
from round_tpu.engine import scenarios
from round_tpu.utils.tree import tree_where

# Value-adversary stream constants: per-(round, link) draws from the one
# counter-based link hash, disjoint from the omission/silence/wire
# streams (scenarios / runtime/chaos.py / fuzz/genome.py STREAM_BYZ).
STREAM_BYZ_VAL = 0xA53F9C71    # substitute? draws (per round, link)
STREAM_BYZ_STALE = 0xC3D21B85  # stale-replay draws
STREAM_BYZ_FACE = 0xD7E84A2D   # which FACE (vA/vB) each link hears

# explicit-plan opcodes (plan[r, dst, src])
VP_NONE = -1   # truthful delivery
VP_STALE = -2  # replay the sender's previous send of this round class


def _link_u32(salt0, salt1, r, n: int, stream: int) -> jnp.ndarray:
    """[n(recv), n(send)] uint32 — the counter link hash at round r under
    ``stream`` (the jnp twin of scenarios.host_link_u32, full matrix)."""
    i = jnp.arange(n, dtype=jnp.uint32)
    idx = i[:, None] * jnp.uint32(n) + i[None, :]
    z = idx * jnp.uint32(scenarios.LINK_GOLD) + jnp.asarray(salt0).astype(
        jnp.uint32)
    z = z ^ (jnp.asarray(r).astype(jnp.uint32)
             * jnp.uint32(scenarios.LINK_RMIX)
             + jnp.asarray(salt1).astype(jnp.uint32)
             + jnp.uint32(stream))
    return scenarios._mix32(z)


def lie_pair(salt0, salt1, num_values: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The genome's TWO-FACED lie palette ``(vA, vB)``: one pair of
    claimed values per (salt0, salt1), spanning the value domain.  A
    hash-mode adversary only ever claims one of these two — the classic
    split-brain equivocation shape (side A hears vA, side B hears vB),
    and the shape quorum-steering attacks on digest protocols need: the
    same face stays consistent across a phase's rounds, so a forged
    prepare certificate can actually assemble.  (Explicit plans keep
    full per-event generality — this narrows the SEARCH space, not the
    replay format.)"""
    m = jnp.uint32(max(1, num_values))
    a = scenarios._mix32(jnp.asarray(salt0).astype(jnp.uint32)
                         ^ jnp.uint32(STREAM_BYZ_VAL))
    b = scenarios._mix32(jnp.asarray(salt1).astype(jnp.uint32)
                         + jnp.uint32(STREAM_BYZ_VAL))
    return (a % m).astype(jnp.int32), (b % m).astype(jnp.int32)


def value_events(byz_value, equiv_p8, stale_p8, salt0, salt1, r, n: int,
                 num_values: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The round-r value-fault draws: ``(sub_v, stale)`` with
    ``sub_v [n(recv), n(send)] int32`` (claimed value, VP_NONE where
    truthful) and ``stale [n, n] bool``.  Equivocation wins over stale
    (the two events are disjoint by construction); the diagonal is never
    substituted (a process cannot lie to itself — the engines'
    self-delivery convention)."""
    byz = jnp.asarray(byz_value)
    eye = jnp.eye(n, dtype=bool)
    u = _link_u32(salt0, salt1, r, n, STREAM_BYZ_VAL)
    equiv = (byz[None, :]
             & ((u & jnp.uint32(0xFF))
                < jnp.asarray(equiv_p8).astype(jnp.uint32))
             & ~eye)
    # the FACE each (src, dst) link hears is ROUND-INDEPENDENT (drawn at
    # r=0 under its own stream): an equivocator tells each peer ONE
    # consistent story, so the per-round draws only gate WHETHER it lies
    # this round — the shape that lets forged quorum certificates
    # actually assemble across a phase's rounds
    va, vb = lie_pair(salt0, salt1, num_values)
    face = (_link_u32(salt0, salt1, 0, n, STREAM_BYZ_FACE)
            >> jnp.uint32(8)) & jnp.uint32(1)
    v = jnp.where(face.astype(bool), va, vb)
    sub_v = jnp.where(equiv, v, jnp.int32(VP_NONE))
    u2 = _link_u32(salt0, salt1, r, n, STREAM_BYZ_STALE)
    stale = (byz[None, :] & ~equiv
             & ((u2 & jnp.uint32(0xFF))
                < jnp.asarray(stale_p8).astype(jnp.uint32))
             & ~eye)
    return sub_v, stale


def _plan_fn(n: int, rounds: int, num_values: int):
    def materialize(byz_value, equiv_p8, stale_p8, salt0, salt1):
        def one(r):
            sub_v, stale = value_events(
                byz_value, equiv_p8, stale_p8, salt0, salt1, r, n,
                num_values)
            return jnp.where(stale, jnp.int32(VP_STALE), sub_v)

        return jax.vmap(one)(jnp.arange(rounds, dtype=jnp.int32))

    return materialize


@_functools.lru_cache(maxsize=None)
def _jitted_plan_fn(n: int, rounds: int, num_values: int):
    return jax.jit(_plan_fn(n, rounds, num_values))


def value_plan(row, rounds: int, num_values: int) -> np.ndarray:
    """Materialize one genome row dict's value-fault fields into the
    explicit ``[rounds, n, n] int32`` substitution plan — bit-identical
    to the draws ``hash_adversary`` makes (pinned by tests/test_byz.py,
    the value-dimension twin of genome.row_schedule)."""
    n = int(np.asarray(row["byz_value"]).shape[-1])
    out = _jitted_plan_fn(n, rounds, num_values)(
        jnp.asarray(row["byz_value"]), jnp.asarray(row["equiv_p8"]),
        jnp.asarray(row["stale_p8"]), jnp.asarray(row["salt0"]),
        jnp.asarray(row["salt1"]))
    return np.asarray(out)


class ValueAdversary:
    """The engine hook: per-round payload substitution, fused into the
    jitted round step (engine/executor.py run_round).

    ``events_fn(r) -> (sub_v [n, n] int32, stale [n, n] bool)`` supplies
    the round's draws (hash- or plan-backed); ``lie`` is the protocol's
    lie model (byz/lies.py), dispatched on the STATIC round-class index.
    ``apply`` turns the round's truthful ``[n(send), ...]`` payload tree
    into per-receiver ``[n(recv), n(send), ...]`` mailbox values and
    advances the per-class (valid, payload) stale carry."""

    def __init__(self, n: int, rounds_per_phase: int,
                 events_fn: Callable[[Any], Tuple[jnp.ndarray, jnp.ndarray]],
                 lie: Optional[LieFn] = None):
        self.n = n
        self.k = max(1, rounds_per_phase)
        self.events_fn = events_fn
        self.lie = lie or generic_lie

    def init_prev(self, payload_zero) -> Tuple[jnp.ndarray, Any]:
        """Fresh stale carry for ONE round class: (ever-sent [n] bool,
        last-sent payload zeros)."""
        return (jnp.zeros((self.n,), dtype=bool),
                jax.tree_util.tree_map(jnp.zeros_like, payload_zero))

    def apply(self, j: int, r, payload, dest, prev):
        """One round's substitution.  ``j`` = static round-class index,
        ``r`` = traced round number, ``payload`` the truthful
        ``[n(send), ...]`` tree, ``dest [n(send), n]`` the send mask
        (whether the sender transmitted at all this round), ``prev`` the
        class's stale carry.  Returns (values [n(recv), n(send), ...],
        new prev)."""
        n = self.n
        valid, prev_payload = prev
        sub_v, stale = self.events_fn(r)
        vmax = jnp.maximum(sub_v, 0)

        lie = self.lie

        def lie_one(p_i, v_i):
            return lie(j, p_i, v_i)

        # [n_recv, n_send, ...]: inner vmap over senders, outer over the
        # per-receiver claimed-value rows — equivocation is exactly the
        # outer axis varying
        lied = jax.vmap(lambda vrow: jax.vmap(lie_one)(payload, vrow))(vmax)

        sel_equiv = sub_v >= 0
        sel_stale = stale & valid[None, :]

        def mix(l_lied, l_truth, l_prev):
            extra = l_truth.ndim - 1
            se = sel_equiv.reshape(sel_equiv.shape + (1,) * extra)
            ss = sel_stale.reshape(sel_stale.shape + (1,) * extra)
            truth = jnp.broadcast_to(l_truth[None], (n,) + l_truth.shape)
            prevb = jnp.broadcast_to(l_prev[None], (n,) + l_prev.shape)
            return jnp.where(se, l_lied, jnp.where(ss, prevb, truth))

        values = jax.tree_util.tree_map(
            lambda a, b, c: mix(a, jnp.asarray(b), jnp.asarray(c)),
            lied, payload, prev_payload)

        sent = jnp.any(jnp.asarray(dest), axis=1)
        new_prev = (valid | sent, tree_where(sent, payload, prev_payload))
        return values, new_prev


def hash_adversary(n: int, rounds_per_phase: int, byz_value, equiv_p8,
                   stale_p8, salt0, salt1, num_values: int,
                   lie: Optional[LieFn] = None) -> ValueAdversary:
    """Hash-mode adversary over (possibly traced) genome leaves — what
    the vmapped population evaluation builds per candidate."""
    def events(r):
        return value_events(byz_value, equiv_p8, stale_p8, salt0, salt1,
                            r, n, num_values)

    return ValueAdversary(n, rounds_per_phase, events, lie=lie)


def plan_adversary(n: int, rounds_per_phase: int, plan,
                   lie: Optional[LieFn] = None) -> ValueAdversary:
    """Explicit-plan adversary (``plan [T, n, n] int32``, VP_* opcodes).
    Rounds past the plan clamp to the LAST row — the from_schedule
    convention, shared with the host wire's lookup."""
    plan = jnp.asarray(plan, jnp.int32)
    T = plan.shape[0]

    def events(r):
        row = plan[jnp.minimum(jnp.asarray(r), T - 1)]
        return (jnp.where(row >= 0, row, jnp.int32(VP_NONE)),
                row == jnp.int32(VP_STALE))

    return ValueAdversary(n, rounds_per_phase, events, lie=lie)


def plan_is_trivial(plan) -> bool:
    """True when the plan holds no substitution events at all."""
    return bool(np.all(np.asarray(plan) == VP_NONE))
