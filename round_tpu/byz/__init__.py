"""round_tpu.byz — the Byzantine VALUE-adversary engine.

PR 8's fault genome is omission-shaped (crashes, drops, partitions,
byzantine *silence*); PR 9 proves ``n > Kf`` envelopes whose hard case is
the adversary that LIES.  This package closes the gap:

  * ``lies``      — per-protocol lie models: how a compromised sender
                    forges a well-formed payload claiming value ``v``
                    (digest-consistent for the PBFT family).  ONE
                    function per protocol, applied by the jitted engine
                    AND the host wire, so lies are bit-identical across
                    both worlds.
  * ``adversary`` — the value-fault tensors (membership mask +
                    equivocation / stale-replay thresholds), the
                    counter-hash event formula (per-(round, src, dst)
                    draws under dedicated streams — equivocation IS
                    per-destination divergence), the explicit
                    ``[T, n, n]`` substitution-plan materializer, and
                    the engine hook ``ValueAdversary`` that
                    executor.run_phases fuses into the update step.
  * ``crosscheck``— the proof/fuzzer cross-check harness: in-envelope
                    sweeps must find ZERO safety violations; past-envelope
                    sweeps of benign-model protocols must find (and
                    minimize, and bank) one.  The banked counterexamples
                    live in tests/regressions/ (the LastVoting
                    commit-round coordinator equivocation, the OTR
                    early-victim split) and double as the rv-under-lies
                    fixtures of tests/test_byz.py.
"""

from round_tpu.byz.adversary import (  # noqa: F401
    STREAM_BYZ_STALE,
    STREAM_BYZ_VAL,
    ValueAdversary,
    hash_adversary,
    plan_adversary,
    value_events,
    value_plan,
)
from round_tpu.byz.lies import LIE_MODELS, forge_payload, lie_for  # noqa: F401
