"""Lie models: how a value adversary forges one round payload.

A lie model is ONE pure function

    lie(k, payload, v) -> forged payload (same pytree structure/shapes)

where ``k`` is the STATIC round-class index (``r % rounds_per_phase``),
``payload`` is a SINGLE sender's payload pytree for that class (per-lane
shapes — scalars for OTR/LastVoting, dicts for the PBFT family) and ``v``
is the claimed value (scalar, traced or concrete).  The same function is

  * vmapped over (receiver, sender) by the jitted engine
    (byz/adversary.py ValueAdversary) — equivocation is just different
    ``v`` per destination in the same round;
  * applied to the DECODED wire payload by the host chaos layer
    (runtime/chaos.py FaultyTransport value-fault families), then
    re-encoded — so an engine finding replays byte-equivalently on real
    sockets (the receiver decodes the identical forged values).

The default ``generic_lie`` claims ``v`` in every leaf (ints -> v, bools
-> v & 1) — "corrupted but well-formed": the bytes parse, the dtypes and
shapes are honest, only the VALUES lie.  Protocols that carry integrity
checks get smarter models: the PBFT forgeries recompute the digest of
the lied request, so the lie survives the receiver's
``MessageDigest.isEqual`` recheck — the attack the byzantine literature
actually means by equivocation.

Everything here must stay jit-safe (jnp only, Python dispatch only on
the static ``k``): the engine traces these functions inside the vmapped
population evaluation.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax.numpy as jnp
import numpy as np

LieFn = Callable[[int, Any, Any], Any]


def _claim(leaf, v):
    """One leaf claiming value ``v``: dtype/shape-preserving broadcast."""
    leaf = jnp.asarray(leaf)
    v = jnp.asarray(v)
    if leaf.dtype == jnp.bool_:
        out = (v.astype(jnp.int32) % 2).astype(jnp.bool_)
    else:
        out = v.astype(leaf.dtype)
    return jnp.broadcast_to(out, leaf.shape)


def generic_lie(k: int, payload: Any, v) -> Any:
    """Claim ``v`` in every leaf of the payload (the protocol-agnostic
    forgery).  For value-broadcast protocols (OTR's x, LastVoting's
    vote/x rounds) this IS the classic equivocation: different
    destinations hear different well-formed values."""
    import jax

    del k
    return jax.tree_util.tree_map(lambda leaf: _claim(leaf, v), payload)


def pbft_lie(k: int, payload: Any, v) -> Any:
    """Digest-consistent forgery for the 3-phase Bcp (models/pbft.py):
    the lied request ships with the digest OF THE LIE, so the receiver's
    recheck passes and the lie enters the quorum counting — silence or a
    torn (req, digest) pair would be caught like a failed
    MessageDigest.isEqual and degrade to omission."""
    from round_tpu.models.pbft import digest

    v32 = jnp.asarray(v, jnp.int32)
    if k == 0:  # pre-prepare: {"req", "dig"}
        return {"req": _claim(payload["req"], v32),
                "dig": _claim(payload["dig"], digest(v32))}
    if k == 1:  # prepare: {"dig", "ok"} — claim a valid matching digest
        return {"dig": _claim(payload["dig"], digest(v32)),
                "ok": jnp.broadcast_to(jnp.asarray(True),
                                       jnp.shape(payload["ok"]))}
    # commit: bare digest scalar
    return _claim(payload, digest(v32))


def pbft_vc_lie(k: int, payload: Any, v) -> Any:
    """The PbftViewChange forgery (6-round phases).  View/next-view
    fields stay TRUTHFUL — a lied view number fails the receivers'
    same-view filters and collapses to omission; the interesting
    adversary lies about the VALUE while staying protocol-coherent."""
    from round_tpu.models.pbft import digest

    v32 = jnp.asarray(v, jnp.int32)
    if k == 0:  # pre-prepare: {"req", "dig", "view"}
        return {"req": _claim(payload["req"], v32),
                "dig": _claim(payload["dig"], digest(v32)),
                "view": payload["view"]}
    if k == 1:  # prepare: {"dig", "ok", "view"}
        return {"dig": _claim(payload["dig"], digest(v32)),
                "ok": jnp.broadcast_to(jnp.asarray(True),
                                       jnp.shape(payload["ok"])),
                "view": payload["view"]}
    if k == 2:  # commit: {"dig", "view"}
        return {"dig": _claim(payload["dig"], digest(v32)),
                "view": payload["view"]}
    if k == 3:  # view-change: {"nv", "pr", "pv"} — a forged certificate
        return {"nv": payload["nv"],
                "pr": _claim(payload["pr"], v32),
                "pv": payload["pv"]}
    if k == 4:  # view-change-ack: {"nv", "ackd"} — garbage ack digests
        return {"nv": payload["nv"],
                "ackd": _claim(payload["ackd"], digest(v32))}
    # new-view: {"nv", "sel"} — the equivocating new primary
    return {"nv": payload["nv"], "sel": _claim(payload["sel"], v32)}


#: protocol (selector name) -> lie model; anything absent gets the
#: generic value-claim forgery.  Keyed on the ARTIFACT protocol string so
#: engine and host resolve the identical model.
LIE_MODELS: Dict[str, LieFn] = {
    "pbft": pbft_lie,
    "pbft-vc": pbft_vc_lie,
    "pbftvc": pbft_vc_lie,
}


def lie_for(protocol: str) -> LieFn:
    return LIE_MODELS.get((protocol or "").lower(), generic_lie)


def forge_payload(protocol: str, k: int, payload: Any, v: int) -> Any:
    """HOST-side forgery: apply the protocol's lie model to a DECODED
    wire payload (numpy leaves) and return a numpy pytree with the
    ORIGINAL dtypes/shapes — what runtime/chaos.py re-encodes.  The
    engine applies the same jnp function under vmap; equal inputs give
    equal forged values, which is the engine<->host replay fidelity
    contract (tests/test_byz.py pins it)."""
    import jax

    lied = lie_for(protocol)(k, payload, int(v))
    return jax.tree_util.tree_map(
        lambda orig, new: np.asarray(new).astype(
            np.asarray(orig).dtype, copy=False).reshape(
            np.shape(orig)),
        payload, lied)
