"""The proof/fuzzer cross-check: envelopes as falsifiable runtime claims.

PR 9 proves quorum lemmas under a declared ``n > K·f`` envelope; this
harness turns each proof into something the fuzzer can FALSIFY.  Per
protocol it runs two evolved-adversary sweeps:

  * IN-envelope — the adversary the proof admits.  For a BENIGN-model
    protocol (OTR, LastVoting: ``adversary_model == "benign"``) that is
    the full omission/crash/partition genome with the value-adversary
    family capped at ZERO liars; for a BYZANTINE-model protocol (the
    PBFT family) the cap is the proved ``f_max = (n-1)//K`` and the
    sweep is SEEDED with liar genomes (byz/adversary.py equivocation,
    stale replay, well-formed corruption) so the search starts inside
    the adversary class rather than having to rediscover it.  The claim:
    ZERO safety violations over at least ``min_schedules`` evaluated
    schedules.  A hit here means the proof and the engine disagree —
    the cross-check's whole reason to exist — so the sweep stops on it
    and reports the offending genome.

  * PAST-envelope — one notch beyond what the proof covers: a benign
    protocol faces ONE value adversary (a liar is outside its fault
    model at any f), a byzantine protocol is shrunk to ``n = K·f`` (the
    classic n = 3f boundary).  For benign protocols the claim is that
    the search FINDS a safety violation and ddmin banks a 1-minimal
    equivocation counterexample (fuzz/minimize.py shrinks over lie
    events exactly as it shrinks dropped links).

The byzantine past-envelope sweep claims LIVENESS damage, not a safety
counterexample, and the asymmetry is the measured headline: the 3-phase
commit's ``> 2n/3`` quorums stay safe under equivocation at ANY f —
two conflicting quorums intersect in ``> n/3`` senders, more than the
liars, so an honest process would have had to broadcast two digests in
one round — while what ``n > 3f`` buys is termination-with-a-decision.
The fuzzer demonstrates both halves: in-envelope PBFT decides through
its liars; at ``n = 3f`` the evolved equivocator drives honest lanes
into null-decide/undecided mass, and no safety violation exists to be
found (tests/test_byz.py pins the sweep, docs/FUZZING.md the claim).

Counters (OBSERVABILITY.md): ``byz.sweeps``, ``byz.sweep_schedules``,
``byz.violations``, ``byz.counterexamples`` — the harness half of the
``byz.*`` vocabulary; the host wire's injection half is
``chaos.byz_equivocate`` / ``chaos.byz_stale`` (runtime/chaos.py).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from round_tpu.fuzz import genome
from round_tpu.fuzz import minimize as fmin
from round_tpu.fuzz import replay as freplay
from round_tpu.fuzz.objectives import safety_violated
from round_tpu.fuzz.search import FuzzTarget, make_target, search
from round_tpu.obs.metrics import METRICS
from round_tpu.rv.license import parse_envelope

_C_SWEEPS = METRICS.counter("byz.sweeps")
_C_SCHEDULES = METRICS.counter("byz.sweep_schedules")
_C_VIOLATIONS = METRICS.counter("byz.violations")
_C_BANKED = METRICS.counter("byz.counterexamples")

#: compiled-evaluator reuse across sweep arms (ISSUE 14 throughput
#: satellite): make_target jit-compiles a fresh genome evaluator per
#: call, and a crosscheck used to pay that compile THREE times for a
#: benign protocol (in-envelope sweep, past-envelope sweep at the same
#: (n, horizon, seed), and the banking target) — compile wall that the
#: time-boxed soak rung counted against schedules/sec (4-8k vs the
#: benign pipelines' 16-55k).  The cache is keyed by everything baked
#: into the trace; entries are few (protocol x n x horizon x seed).
_TARGET_CACHE: Dict[tuple, FuzzTarget] = {}


def cached_target(protocol: str, n: int, horizon: int,
                  seed: int = 0) -> FuzzTarget:
    """make_target with the compiled evaluator memoized (default values/
    value_domain only — exactly the sweep()/crosscheck() call shape)."""
    key = (protocol, n, horizon, seed)
    t = _TARGET_CACHE.get(key)
    if t is None:
        # FIFO cap: a long soak draws a fresh seed per rotation; the
        # cache must bound the compiled executables it keeps alive
        if len(_TARGET_CACHE) >= 16:
            _TARGET_CACHE.pop(next(iter(_TARGET_CACHE)))
        t = make_target(protocol, n, horizon, seed=seed)
        _TARGET_CACHE[key] = t
    return t


def early_victim_split():
    """Predicate: all lanes decide, exactly ONE lane (the victim)
    disagrees, and the victim decided STRICTLY BEFORE every other lane.
    The host-deterministic counterexample shape for the rv-under-lies
    workout: on real wire the victim's decision precedes any honest
    decision gossip, so a monitor on the victim observes the conflict
    from a position no catch-up adoption can erase (tests/test_byz.py;
    the otr_equivocation_victim.json regression)."""

    def pred(out):
        dec = np.asarray(out["decided"])
        val = np.asarray(out["decision"])
        dr = np.asarray(out["decided_round"])
        P, n = dec.shape
        ok = np.zeros(P, dtype=bool)
        for p in range(P):
            if not dec[p].all():
                continue
            vals, counts = np.unique(val[p], return_counts=True)
            if len(vals) != 2 or counts.min() != 1:
                continue
            victim = int(np.flatnonzero(
                val[p] == vals[np.argmin(counts)])[0])
            others = np.delete(np.arange(n), victim)
            ok[p] = dr[p, victim] < dr[p, others].min()
        return ok

    pred.__name__ = "early_victim_split()"
    return pred


def adversary_budget(algo, n: int) -> tuple:
    """(f_env, in_cap): the proved fault budget at n, and how many VALUE
    adversaries the in-envelope sweep may breed — ``f_env`` for a
    byzantine-model protocol, 0 for a benign one (a liar is outside the
    benign model at any f; core/algorithm.py Algorithm.adversary_model)."""
    envelope = getattr(algo, "fault_envelope", None)
    if not envelope:
        raise ValueError(
            f"{type(algo).__name__} declares no fault_envelope; the "
            "cross-check needs one (core/algorithm.py)")
    f_env = max(0, (n - 1) // parse_envelope(envelope))
    byz = getattr(algo, "adversary_model", "benign") == "byzantine"
    return f_env, (f_env if byz else 0)


def liar_rows(n: int, horizon: int, liars: int, seed: int = 0,
              count: int = 8) -> List[Dict[str, np.ndarray]]:
    """Hand-picked seed genomes with the liar set already in place:
    ``liars`` equivocators at high intensity over fresh salts.  The
    search's selection pressure can then explore FACES (salt rerolls
    move lie_pair and the per-link face draw) instead of having to
    evolve the family from zero across a flat fitness landscape —
    essential for past-envelope sweeps where every benign schedule
    scores identically."""
    rows = []
    for c in range(count):
        rng = np.random.default_rng((seed << 8) ^ c)
        pop = genome.seed_population(int(rng.integers(2**31)), 1, n,
                                     horizon)
        row = {f: np.asarray(getattr(pop, f)[0]) for f in genome._FIELDS}
        bv = np.zeros(n, dtype=bool)
        bv[rng.choice(n, size=min(liars, n), replace=False)] = True
        row["byz_value"] = bv
        row["equiv_p8"] = np.int32(rng.integers(96, genome.P8_CAP + 1))
        # stale replay on a minority of seeds: the families compose, but
        # equivocation is the primary past-envelope weapon
        row["stale_p8"] = np.int32(rng.integers(0, 49) if c % 4 == 3
                                   else 0)
        rows.append(row)
    return rows


@dataclasses.dataclass
class SweepResult:
    """One evolved-adversary sweep at a fixed (protocol, n, liar cap)."""

    protocol: str
    n: int
    in_envelope: bool
    f_env: int                      # proved fault budget at this n
    value_cap: int                  # liars the gene pool may hold
    evaluated: int
    generations: int
    schedules_per_sec: float
    wall_s: float
    violation: bool                 # any safety hit over the sweep
    best_outcome: Dict[str, float]
    timeboxed: bool = False         # time_box_s expired before budget
    best_row: Optional[Dict[str, np.ndarray]] = None

    def record(self) -> Dict[str, Any]:
        """The SOAK.jsonl-shaped summary (no arrays)."""
        return {
            "protocol": self.protocol, "n": self.n,
            "in_envelope": self.in_envelope, "f_env": self.f_env,
            "value_cap": self.value_cap, "evaluated": self.evaluated,
            "generations": self.generations,
            "schedules_per_sec": round(self.schedules_per_sec, 1),
            "wall_s": round(self.wall_s, 2),
            "violation": self.violation,
            "timeboxed": self.timeboxed,
            "best_outcome": self.best_outcome,
        }


def _default_horizon(n: int) -> int:
    """The sweep horizon: 12 rounds for every realistic n (make_target
    rounds it up to whole phases, so 3-round Bcp and 6-round
    PbftViewChange both land on 12)."""
    return 4 * max(1, min(3, n))


def sweep(protocol: str, n: int, *, in_envelope: bool,
          min_schedules: int = 10_000, pop_size: int = 512,
          horizon: Optional[int] = None, seed: int = 0,
          time_box_s: Optional[float] = None,
          log_fn: Optional[Callable[[str], None]] = None) -> SweepResult:
    """One envelope sweep.  In-envelope: the proof's adversary (benign →
    value family OFF; byzantine → ``f_env`` liars, liar-seeded), run to
    ``min_schedules`` unless a safety hit falsifies the proof first.
    Past-envelope: one value adversary past the proof (benign → 1 liar;
    byzantine callers pass the shrunk ``n = K·f`` and get ``f_env + 1``
    liars), stopped at the first safety hit."""
    target = cached_target(protocol, n,
                           horizon if horizon is not None
                           else _default_horizon(n), seed=seed)
    f_env, in_cap = adversary_budget(target.algo, n)
    # past-envelope: one notch beyond the proof — a benign protocol
    # faces its FIRST liar (in_cap 0 -> 1), a byzantine one gets one
    # liar past the (possibly zero, at n = K·f) proved budget
    cap = in_cap if in_envelope else in_cap + 1
    seeds = (liar_rows(n, target.horizon, cap, seed=seed)
             if cap > 0 else None)
    generations = max(1, -(-min_schedules // pop_size))
    t0 = time.perf_counter()
    res = search(target, pop_size=pop_size, generations=generations,
                 seed=seed, stop_when=safety_violated(), value_cap=cap,
                 seed_rows=seeds, time_box_s=time_box_s, log_fn=log_fn)
    wall = time.perf_counter() - t0
    hit = bool(res.best_outcome and
               (res.best_outcome["agreement_viol"]
                + res.best_outcome["validity_viol"]) > 0)
    _C_SWEEPS.inc()
    _C_SCHEDULES.inc(res.evaluated)
    if hit:
        _C_VIOLATIONS.inc()
    return SweepResult(
        protocol=protocol, n=n, in_envelope=in_envelope, f_env=f_env,
        value_cap=cap, evaluated=res.evaluated,
        generations=res.generations,
        schedules_per_sec=res.schedules_per_sec, wall_s=wall,
        violation=hit, best_outcome=res.best_outcome,
        timeboxed=(time_box_s is not None and not hit
                   and res.evaluated < min_schedules
                   and wall >= time_box_s),
        best_row=res.best_row if hit else None)


def bank_counterexample(target: FuzzTarget, row: Dict[str, np.ndarray],
                        path: Optional[str] = None, *,
                        host_record: bool = False, timeout_ms: int = 400,
                        meta: Optional[Dict[str, Any]] = None,
                        log_fn: Optional[Callable[[str], None]] = None
                        ) -> Dict[str, Any]:
    """Minimize a safety-violating genome to a 1-minimal (schedule,
    value plan) pair and bank it as a v2 artifact: ddmin over dropped
    links AND lie events (fuzz/minimize.py), the 1-minimality
    postcondition verified, the engine outcome recorded (and the
    host-wire outcome with ``host_record`` — an in-process socket
    cluster with the forged frames on the real wire)."""
    pred = safety_violated()
    mr = fmin.minimize(target, row, pred, log_fn=log_fn)
    assert fmin.verify_one_minimal(target, mr.schedule, pred,
                                   value_plan=mr.value_plan), \
        "ddmin postcondition failed: result is not 1-minimal"
    art = freplay.make_artifact(
        protocol=target.name, schedule=mr.schedule,
        values=target.init_values, seed=target.seed,
        value_plan=mr.value_plan,
        meta={"objective": "safety_violated()",
              "value_events": {"initial": mr.value_initial,
                               "minimal": mr.value_final},
              "dropped_links": {"initial": mr.dropped_initial,
                                "minimal": mr.dropped_final},
              **(meta or {})})
    art["expected"]["engine"] = freplay.replay_engine(art)
    if host_record:
        art["expected"]["host"] = freplay.replay_host_threads(
            art, timeout_ms=timeout_ms)
    if path:
        freplay.dump_artifact(path, art)
    _C_BANKED.inc()
    return art


@dataclasses.dataclass
class CrosscheckResult:
    """In + past envelope sweeps for one protocol, with the claims
    evaluated.  ``ok`` is the cross-check verdict: the proof's envelope
    held in-envelope AND the past-envelope sweep behaved as its model
    predicts (benign: safety counterexample found; byzantine: none
    exists, the liars' damage is liveness-shaped)."""

    protocol: str
    inside: SweepResult
    past: SweepResult
    min_schedules: int
    artifact: Optional[Dict[str, Any]] = None
    artifact_path: Optional[str] = None
    evaluator_reused: bool = False      # past arm ran on the in arm's jit

    @property
    def in_ok(self) -> bool:
        """True when the in-envelope claim HELD: no safety violation,
        over the full schedule budget — or over however many schedules
        the wall-clock box allowed (a time-box cutoff is an unfinished
        sweep, not a falsified proof; ``inside.timeboxed`` records it,
        and callers that need the full budget — the acceptance test —
        assert ``inside.evaluated >= N`` themselves)."""
        return (not self.inside.violation
                and (self.inside.evaluated >= self.min_schedules
                     or self.inside.timeboxed))

    @property
    def past_ok(self) -> bool:
        """Benign model: the expected safety break was found (or the
        time box expired before the search could finish looking — an
        unfinished sweep is inconclusive, not a refuted claim; the
        acceptance tests assert ``past.violation`` and the banked
        artifact directly).  Byzantine model: NO safety break exists to
        find, so any hit fails regardless of the box."""
        if self._expect_safety_break():
            return self.past.violation or self.past.timeboxed
        return not self.past.violation

    def _expect_safety_break(self) -> bool:
        from round_tpu.apps.selector import select

        return getattr(select(self.protocol), "adversary_model",
                       "benign") == "benign"

    @property
    def ok(self) -> bool:
        return self.in_ok and self.past_ok

    def record(self) -> Dict[str, Any]:
        rec = {
            "protocol": self.protocol, "ok": self.ok,
            "in_ok": self.in_ok, "past_ok": self.past_ok,
            "expect_past_safety_break": self._expect_safety_break(),
            "evaluator_reused": self.evaluator_reused,
            "inside": self.inside.record(), "past": self.past.record(),
        }
        if self.artifact is not None:
            rec["artifact"] = {
                "path": self.artifact_path,
                "value_subs": len(self.artifact.get("value_subs", [])),
                "stale_subs": len(self.artifact.get("stale_subs", [])),
                "drops": len(self.artifact.get("drops", [])),
            }
        return rec


def crosscheck(protocol: str, n: int, *, min_schedules: int = 10_000,
               pop_size: int = 512, seed: int = 0,
               time_box_s: Optional[float] = None,
               bank_dir: Optional[str] = None,
               host_record: bool = False,
               log_fn: Optional[Callable[[str], None]] = None
               ) -> CrosscheckResult:
    """The full cross-check for one protocol: in-envelope sweep at
    ``n``, past-envelope sweep (benign → same n + 1 liar; byzantine →
    shrunk to ``n = K·f`` with the liar budget one past the shrunk
    envelope), and — when the past-envelope sweep finds the expected
    safety violation — a minimized counterexample banked under
    ``bank_dir`` as ``<protocol>_equivocation_<n>.json``."""
    from round_tpu.apps.selector import select

    algo = select(protocol)
    inside = sweep(protocol, n, in_envelope=True,
                   min_schedules=min_schedules, pop_size=pop_size,
                   seed=seed, time_box_s=time_box_s, log_fn=log_fn)
    if getattr(algo, "adversary_model", "benign") == "byzantine":
        # shrink to the classic boundary n = K·f (n > K·f just fails)
        k = parse_envelope(algo.fault_envelope)
        f_env, _ = adversary_budget(algo, n)
        n_past = k * max(1, f_env)
    else:
        n_past = n
    past = sweep(protocol, n_past, in_envelope=False,
                 min_schedules=min_schedules, pop_size=pop_size,
                 seed=seed, time_box_s=time_box_s, log_fn=log_fn)
    out = CrosscheckResult(protocol=protocol, inside=inside, past=past,
                           min_schedules=min_schedules,
                           # benign protocols keep (n, horizon): the past
                           # sweep reran on the in sweep's compiled
                           # evaluator instead of paying a second trace
                           evaluator_reused=n_past == n)
    if past.violation and past.best_row is not None and bank_dir:
        # the banking target must match the past sweep's exactly — the
        # winning row's hash draws are (n, horizon, value_domain)-keyed
        # (cached_target: this IS the past sweep's compiled target)
        target = cached_target(protocol, n_past, _default_horizon(n_past),
                               seed=seed)
        path = os.path.join(
            bank_dir, f"{protocol}_equivocation_{n_past}.json")
        out.artifact = bank_counterexample(
            target, past.best_row, path, host_record=host_record,
            meta={"crosscheck": {"n_in": n, "n_past": n_past,
                                 "search_seed": seed}},
            log_fn=log_fn)
        out.artifact_path = path
    return out
