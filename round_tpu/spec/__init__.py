"""Specification layer: properties and invariants as masked tensor reductions.

The reference's spec DSL (Specs.scala:8-41, SpecHelper init/old, the Domain
forall/exists/filter stubs of Algorithm.scala:91-95) exists to *prove*
algorithms offline via SMT.  Here the same formulas are *checked* — evaluated
exactly, per round, over every lane of every simulated scenario, by compiling
quantifiers to vmapped reductions over the state tensors.  (The offline
proving pipeline lives in round_tpu.verification.)

Quantifier mapping:
    P.forall(f)        -> all over a vmapped lane axis
    P.exists(f)        -> any
    P.filter(f).size   -> sum of the predicate mask (Cardinality)
    V.exists(f)        -> any over an explicit candidate-value axis
    S.exists(f)        -> any over the HO rows (set-domain witnesses)
    init(x) / old(x)   -> reads of the init / previous-round snapshot tensors
"""

from round_tpu.spec.dsl import (
    Env,
    ProcDomain,
    ProcView,
    SetView,
    Spec,
    TrivialSpec,
    ValueDomain,
    implies,
)
from round_tpu.spec.check import SpecReport, check_trace, replay_ho

__all__ = [
    "Env",
    "ProcDomain",
    "ProcView",
    "SetView",
    "Spec",
    "TrivialSpec",
    "ValueDomain",
    "implies",
    "SpecReport",
    "check_trace",
    "replay_ho",
]
