"""Trace checking: evaluate a Spec over recorded execution traces.

This is the runtime half of the reference's verification story: instead of
discharging VCs to an SMT solver (Verifier.scala:234-276), the batched
simulator records every round's state and the checker evaluates the spec
formulas *exactly* on each step — over all scenarios at once.  The BASELINE
"invariant parity" metric is this module agreeing with the JVM semantics.

Conventions:
  - a trace is the pytree of states stacked over rounds: leaves [T, n, ...]
    (produced by running the engine with ``record_fn=lambda s, d, r: s``);
  - ``old`` at step t is the state at t-1 (the init state at t=0);
  - the HO matrix per step is replayed from the scenario key (the engine's
    samplers are deterministic functions of (key, r): replay_ho).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from round_tpu.spec.dsl import Env, Spec, SpecFieldError

# The decision-plane property slots a single replica can check EXACTLY
# over its own observations — the live monitor compiler
# (round_tpu/rv/compile.py) compiles precisely these into the fused
# per-lane verdict term.  Matched case-insensitively against Spec
# property names; defined HERE so the compiler and the snapshot auditor
# (round_tpu/snap/audit.py) share one labeling instead of re-deriving it.
WIRE_MONITORS = ("agreement", "validity", "irrevocability")
# property names that are LIVENESS claims: meaningful only at the end of
# a run (check_trace final_properties) — never on a mid-run state, so
# the snapshot auditor must exclude them or false-positive on every
# not-yet-decided cut
_LIVENESS_NAMES = frozenset({"termination"})


def formula_scope(kind: str, name: str) -> str:
    """The live/offline/final classification every formula consumer
    shares (rv/compile.py monitor compiler, snap/audit.py cut auditor):

      live    — decision-plane properties with an exact locally-checkable
                per-replica form (WIRE_MONITORS);
      final   — liveness properties, meaningful only at the end of a run;
      offline — full-state formulas (invariants, safety_predicate,
                round_invariants, remaining safety properties): only a
                consistent GLOBAL state — a recorded trace, or a
                round-aligned snapshot cut — can evaluate them.
    """
    if kind == "property":
        low = name.lower()
        if low in WIRE_MONITORS:
            return "live"
        if low in _LIVENESS_NAMES:
            return "final"
    return "offline"


def formula_label(f, fallback: str) -> str:
    """Human-readable name for a spec formula: named properties keep their
    name; plain methods/functions use their qualname; lambdas fall back to
    the structural position (e.g. ``invariants[1]``)."""
    name = getattr(f, "__qualname__", "") or getattr(f, "__name__", "")
    if not name or "<lambda>" in name:
        return fallback
    return f"{fallback} ({name})"


@dataclasses.dataclass(frozen=True)
class SpecFormula:
    """One enumerated spec formula: the label is EXACTLY the string the
    trace checker attaches to an evaluation error / report row.

    kind ∈ {"invariant", "property", "safety_predicate",
    "round_invariant"}; ``name`` is the property name for properties (the
    Spec's own naming), the structural position otherwise; ``group`` is
    the round index for round_invariants (else -1); ``scope`` is the
    live/offline/final classification of ``formula_scope`` — computed
    ONCE here so rv/compile.py and snap/audit.py cannot drift apart on
    which formulas the wire covers."""

    label: str
    kind: str
    name: str
    formula: Any
    group: int = -1
    scope: str = "offline"


def spec_formulas(spec: Spec) -> Tuple["SpecFormula", ...]:
    """THE shared formula enumeration: every formula a Spec carries, in a
    fixed order, under the labels ``check_trace`` reports.

    Both the offline trace checker (check_trace below) and the live
    runtime-verification monitor compiler (round_tpu/rv/compile.py)
    enumerate through here — so an edited Spec cannot desync the offline
    report's labels/ordering from the jitted monitors' verdict vector.
    Order: invariants, properties, safety_predicate, round_invariants
    (group-major)."""
    out = []
    for i, f in enumerate(spec.invariants):
        out.append(SpecFormula(
            formula_label(f, f"invariants[{i}]"), "invariant",
            f"invariants[{i}]", f,
            scope=formula_scope("invariant", f"invariants[{i}]")))
    for name, f in spec.properties:
        out.append(SpecFormula(
            f"property {name!r}", "property", name, f,
            scope=formula_scope("property", name)))
    if spec.safety_predicate is not None:
        f = spec.safety_predicate
        out.append(SpecFormula(
            formula_label(f, "safety_predicate"), "safety_predicate",
            "safety_predicate", f,
            scope=formula_scope("safety_predicate", "safety_predicate")))
    for j, group in enumerate(spec.round_invariants):
        for m, f in enumerate(group):
            out.append(SpecFormula(
                formula_label(f, f"round_invariants[{j}][{m}]"),
                "round_invariant", f"round_invariants[{j}][{m}]", f,
                group=j,
                scope=formula_scope("round_invariant",
                                    f"round_invariants[{j}][{m}]")))
    return tuple(out)


def _eval_formula(f, env, label):
    """Evaluate one formula, re-raising SpecFieldError with the formula's
    name attached — a typo'd state field names the formula instead of
    surfacing as a bare AttributeError from inside the vmap/jit stack."""
    try:
        return jnp.asarray(f(env))
    except SpecFieldError as e:
        raise e.with_formula(label) from None


def replay_ho(key: jax.Array, ho_sampler, rounds: int) -> jnp.ndarray:
    """Recompute the [T, n, n] HO schedule an engine run drew from ``key``.

    Matches the engine's key discipline (executor.run_phases: the scenario
    key splits into (ho_key, upd_key) and ho_key is passed unchanged with the
    round number folded in by the sampler)."""
    ho_key, _ = jax.random.split(key)
    return jax.vmap(lambda r: ho_sampler(ho_key, r))(
        jnp.arange(rounds, dtype=jnp.int32)
    )


@dataclasses.dataclass
class SpecReport:
    """Per-step spec evaluation over one trace (or, vmapped, a batch).

    invariant_held: [T, n_inv] bool — invariant i holds at step t.
    any_invariant:  [T] bool — some invariant of the chain holds at t
                    (all-True is the expected steady state; vacuously True
                    when the spec has no invariants).
    properties:     name -> [T] bool per-step evaluation.
    safety_ok:      [T] bool — safety_predicate holds at t (True if absent).
    final_properties: name -> bool at the last step (e.g. Termination).
    """

    invariant_held: jnp.ndarray
    any_invariant: jnp.ndarray
    properties: Dict[str, jnp.ndarray]
    safety_ok: jnp.ndarray
    final_properties: Dict[str, jnp.ndarray]
    round_invariant_ok: Optional[jnp.ndarray] = None  # [T, n_groups], True
    # where a group doesn't apply to the step's phase-round

    def all_safety_properties_hold(self) -> jnp.ndarray:
        """Conjunction over steps of every property except Termination
        (which is a liveness property, meaningful only at the end)."""
        ok = jnp.asarray(True)
        for name, vals in self.properties.items():
            if name.lower() == "termination":
                continue
            ok = ok & jnp.all(vals)
        return ok


def cut_env(state: Any, n: int, r: int, init0: Any = None) -> Env:
    """The evaluation context of ONE round-aligned global snapshot (a
    round_tpu/snap cut): the [n, ...] state stamped round ``r`` is the
    POST-state of round r — check_trace's step t=r — so formulas see
    ``env.r = r + 1``.  No ``old`` (the previous round's state was not
    sampled) and no ``ho`` (the HO matrix is not reconstructible from a
    cut); formulas that reach for either are not cut-evaluable and the
    callers classify them out (check_cut below / snap/audit.py)."""
    return Env(state=state, n=n, old=None, init0=init0,
               ho=None, r=jnp.asarray(r, dtype=jnp.int32) + 1)


def check_cut(spec: Spec, state: Any, n: int, r: int,
              init0: Any = None, rounds_per_phase: int = 1
              ) -> Dict[str, Any]:
    """Evaluate the OFFLINE formulas of ``spec`` on ONE cut — the eager
    reference twin of the batched snapshot auditor (snap/audit.py pins
    its jitted vmapped verdicts against this, formula for formula).

    Returns {label: bool | None}: None marks a formula that is not
    cut-evaluable (it needs ``old``, the HO matrix, or an init snapshot
    that was not provided).  The invariant chain is reported as ONE
    entry, ``"invariants (chain)"`` — the DISJUNCTION over the chain,
    matching check_trace's ``any_invariant`` steady-state expectation
    (a single invariant being false is normal chain progress; NO
    invariant holding is the violation) — and only when every chain
    member is cut-evaluable (a partial disjunction would be weaker than
    the spec's).  ``safety_predicate`` constrains the executing round's
    HO and is never cut-evaluable.  Round-invariant group j applies iff
    ``r % rounds_per_phase == j`` (True elsewhere), the check_trace
    phase arithmetic."""
    enum = spec_formulas(spec)
    # numpy-leaf cuts (the collector stacks host arrays) must lift to
    # jnp: quantifier bodies index state rows by a vmapped tracer
    state = jax.tree_util.tree_map(jnp.asarray, state)
    if init0 is not None:
        init0 = jax.tree_util.tree_map(jnp.asarray, init0)
    env = cut_env(state, n, r, init0=init0)
    out: Dict[str, Any] = {}

    def _try(e):
        try:
            return bool(jnp.asarray(_eval_formula(e.formula, env,
                                                  e.label)))
        except (ValueError, SpecFieldError):
            # "no previous-round snapshot" / "no HO matrix" / "no init
            # snapshot" / a field the sampled state does not carry —
            # not cut-evaluable, by construction not a violation
            return None

    inv = [e for e in enum if e.kind == "invariant"]
    if inv:
        vals = [_try(e) for e in inv]
        out["invariants (chain)"] = (None if any(v is None for v in vals)
                                     else any(vals))
    for e in enum:
        if e.kind == "property" and e.scope == "offline":
            out[e.label] = _try(e)
        elif e.kind == "round_invariant":
            if r % rounds_per_phase == e.group:
                out[e.label] = _try(e)
            else:
                out[e.label] = True  # group does not apply to this round
    return out


def _shift_old(trace: Any, init_state: Any) -> Any:
    """old[t] = trace[t-1], old[0] = init_state."""
    return jax.tree_util.tree_map(
        lambda x, i: jnp.concatenate([i[None], x[:-1]], axis=0), trace, init_state
    )


def check_trace(
    spec: Spec,
    trace: Any,
    init_state: Any,
    n: int,
    ho: Optional[jnp.ndarray] = None,
    rounds_per_phase: int = 1,
    jit: bool = True,
) -> SpecReport:
    """Evaluate ``spec`` at every step of one recorded trace.

    Round convention: the engine records the *post*-state of round t, which
    is the reference's pre-state of round t+1 — so formulas see
    ``env.r = t + 1`` (the reference states phase invariants at phase
    boundaries, i.e. where env.r % rounds_per_phase == 0).

    ``spec.round_invariants[j]`` (extra invariants holding after phase round
    j; Specs.scala:14) is evaluated only at steps with t % k == j and
    reported True elsewhere.

    Args:
      spec: the Spec to check.
      trace: state pytree stacked over rounds, leaves [T, n, ...].
      init_state: the round-0 initial state, leaves [n, ...].
      n: number of processes.
      ho: optional [T, n, n] HO schedule — ho[t] is the matrix round t
        executed against (required if formulas use p.HO or the set domain;
        see replay_ho).  The safety_predicate is evaluated against ho[t]
        with the *pre*-state round number (env.r = t) since it constrains
        the round being executed.
      rounds_per_phase: the algorithm's phase length (for round_invariants
        and the phase arithmetic in formulas).
    """
    leaves = jax.tree_util.tree_leaves(trace)
    T = leaves[0].shape[0]
    old_trace = _shift_old(trace, init_state)
    rs = jnp.arange(1, T + 1, dtype=jnp.int32)
    k = rounds_per_phase
    # the ONE formula enumeration (labels + order), shared with the live
    # monitor compiler (round_tpu/rv/compile.py) — see spec_formulas
    enum = spec_formulas(spec)
    inv_refs = [e for e in enum if e.kind == "invariant"]
    prop_refs = [e for e in enum if e.kind == "property"]
    safety_ref = next(
        (e for e in enum if e.kind == "safety_predicate"), None)
    rinv_refs = [e for e in enum if e.kind == "round_invariant"]

    def at_step(state_t, old_t, ho_t, r_t):
        env = Env(state=state_t, n=n, old=old_t, init0=init_state, ho=ho_t, r=r_t)
        inv = (
            jnp.stack([
                _eval_formula(e.formula, env, e.label) for e in inv_refs
            ])
            if inv_refs
            else jnp.ones((0,), dtype=bool)
        )
        props = {
            e.name: _eval_formula(e.formula, env, e.label)
            for e in prop_refs
        }
        if safety_ref is not None:
            pre_env = Env(
                state=old_t, n=n, old=None, init0=init_state, ho=ho_t, r=r_t - 1
            )
            safe = _eval_formula(safety_ref.formula, pre_env,
                                 safety_ref.label)
        else:
            safe = jnp.asarray(True)
        if spec.round_invariants:
            phase_round = (r_t - 1) % k
            rinv = jnp.stack(
                [
                    jnp.where(
                        phase_round == j,
                        jnp.all(jnp.stack([
                            _eval_formula(e.formula, env, e.label)
                            for e in rinv_refs if e.group == j
                        ]))
                        if group
                        else jnp.asarray(True),
                        True,
                    )
                    for j, group in enumerate(spec.round_invariants)
                ]
            )
        else:
            rinv = None
        return inv, props, safe, rinv

    def run():
        if ho is None:
            return jax.vmap(lambda s, o, r: at_step(s, o, None, r))(
                trace, old_trace, rs
            )
        return jax.vmap(at_step)(trace, old_trace, ho, rs)

    inv, props, safe, rinv = (jax.jit(run) if jit else run)()
    any_inv = (
        jnp.any(inv, axis=1) if inv.shape[1] > 0 else jnp.ones((T,), dtype=bool)
    )
    return SpecReport(
        invariant_held=inv,
        any_invariant=any_inv,
        properties=props,
        safety_ok=safe,
        final_properties={k_: v[-1] for k_, v in props.items()},
        round_invariant_ok=rinv,
    )
