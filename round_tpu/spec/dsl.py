"""The spec DSL: quantified formulas over process state, as JAX reductions.

Users write specs almost verbatim from the reference
(e.g. Otr.scala:94-120):

    def agreement(e):
        P = e.P
        return P.forall(lambda i: P.forall(lambda j: implies(
            i.decided & j.decided, i.decision == j.decision)))

Each formula is a function of an Env — the evaluation context holding the
current state, the previous-round snapshot (``old``), the initial snapshot
(``init``), and the round's HO matrix.  Quantifiers evaluate by vmapping the
body over a fresh lane axis, so nesting composes and everything stays jit-
compatible (one fused reduction per formula).

View semantics (reference: SpecHelper, Specs.scala:21-28):
    i.x          — field x of process i (any field of the state pytree)
    i.id         — i's ProcessID
    i.HO         — i's heard-of set this round (SetView over the HO row)
    i.old.x      — x at the previous step   (old(i.x))
    i.init.x     — x at initialization      (init(i.x))

State fields named ``old``, ``init``, ``id`` or ``HO`` would shadow these
accessors; the framework's algorithms avoid those names.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def implies(a, b):
    """``a ==> b`` (SpecHelper.BoolOps, Specs.scala:22-24)."""
    return jnp.logical_or(jnp.logical_not(a), b)


class SpecFieldError(AttributeError):
    """A spec formula referenced a state field that does not exist.

    Raised by the Env accessors (``i.x``, ``i.old.x``, ``i.init.x``) instead
    of the bare ``AttributeError``/tracer ``KeyError`` that used to surface
    from deep inside ``check_trace``'s vmap/jit stack.  Carries the missing
    field, the fields that do exist, and — once the checker attaches it via
    :meth:`with_formula` — the formula being evaluated."""

    def __init__(self, field, available, where="state", formula=None):
        self.field = field
        self.available = tuple(available)
        self.where = where
        self.formula = formula
        at = f" (while evaluating {formula})" if formula else ""
        super().__init__(
            f"spec formula references unknown {where} field {field!r}{at}; "
            f"the state pytree has fields: {', '.join(self.available) or '<none>'}"
        )

    def with_formula(self, name: str) -> "SpecFieldError":
        """A copy of this error naming the formula it came from (the trace
        checker and the static linter both use this to anchor the report)."""
        return SpecFieldError(self.field, self.available, self.where, name)


def _state_fields(state) -> tuple:
    """Best-effort field names of a state pytree (flax.struct dataclass in
    this codebase; fall back to non-private instance attrs)."""
    if dataclasses.is_dataclass(state):
        return tuple(f.name for f in dataclasses.fields(state))
    if isinstance(state, dict):
        return tuple(state)
    return tuple(k for k in vars(state) if not k.startswith("_")) \
        if hasattr(state, "__dict__") else ()


def _field(state, name, where):
    """getattr with the friendly error (dict states get the same message
    instead of a tracer KeyError)."""
    if isinstance(state, dict):
        try:
            return state[name]
        except KeyError:
            raise SpecFieldError(name, _state_fields(state), where) from None
    try:
        return getattr(state, name)
    except AttributeError:
        raise SpecFieldError(name, _state_fields(state), where) from None


class _Snapshot:
    """Field accessor over a state snapshot at a fixed lane index."""

    __slots__ = ("_state", "_idx", "_where")

    def __init__(self, state, idx, where="state"):
        self._state = state
        self._idx = idx
        self._where = where

    def __getattr__(self, name):
        if name.startswith("__"):
            raise AttributeError(name)
        return _field(self._state, name, self._where)[self._idx]


class ProcView:
    """One process's view of the world inside a quantifier body."""

    __slots__ = ("_env", "_idx")

    def __init__(self, env: "Env", idx):
        self._env = env
        self._idx = idx

    @property
    def id(self):
        return self._idx

    @property
    def HO(self) -> "SetView":
        ho = self._env.ho
        if ho is None:
            raise ValueError("this Env carries no HO matrix (pass ho= to Env)")
        return SetView(ho[self._idx])

    @property
    def old(self) -> _Snapshot:
        if self._env.old is None:
            raise ValueError("this Env carries no previous-round snapshot")
        return _Snapshot(self._env.old, self._idx, where="old-snapshot")

    @property
    def init(self) -> _Snapshot:
        if self._env.init0 is None:
            raise ValueError("this Env carries no init snapshot")
        return _Snapshot(self._env.init0, self._idx, where="init-snapshot")

    def __getattr__(self, name):
        if name.startswith("__"):
            raise AttributeError(name)
        return _field(self._env.state, name, "state")[self._idx]

    def __eq__(self, other):
        if isinstance(other, ProcView):
            return self._idx == other._idx
        return self._idx == other

    def __ne__(self, other):
        return jnp.logical_not(self.__eq__(other))

    __hash__ = None


class SetView:
    """A set of processes as an [n] membership mask (HO sets, filter results).

    Mirrors the set operations the reference specs use: size (Cardinality),
    contains (∈), == (extensional equality), ∪/∩/⊆.
    """

    __slots__ = ("mask",)

    def __init__(self, mask: jnp.ndarray):
        self.mask = mask

    @property
    def size(self) -> jnp.ndarray:
        return jnp.sum(self.mask.astype(jnp.int32))

    def contains(self, p) -> jnp.ndarray:
        idx = p._idx if isinstance(p, ProcView) else p
        return self.mask[idx]

    def subset_of(self, other: "SetView") -> jnp.ndarray:
        return jnp.all(implies(self.mask, other.mask))

    def __eq__(self, other):
        if isinstance(other, SetView):
            return jnp.all(self.mask == other.mask)
        return NotImplemented

    def __ne__(self, other):
        return jnp.logical_not(self.__eq__(other))

    def __and__(self, other):
        return SetView(self.mask & other.mask)

    def __or__(self, other):
        return SetView(self.mask | other.mask)

    __hash__ = None


class ProcDomain:
    """The process domain ``P`` (Algorithm.scala:91-95 Domain ops)."""

    def __init__(self, env: "Env"):
        self._env = env

    def _over_lanes(self, f: Callable[[ProcView], Any]) -> jnp.ndarray:
        env = self._env
        return jax.vmap(lambda i: f(ProcView(env, i)))(
            jnp.arange(env.n, dtype=jnp.int32)
        )

    def forall(self, f) -> jnp.ndarray:
        return jnp.all(self._over_lanes(f))

    def exists(self, f) -> jnp.ndarray:
        return jnp.any(self._over_lanes(f))

    def filter(self, f) -> SetView:
        return SetView(self._over_lanes(f))

    def count(self, f) -> jnp.ndarray:
        return self.filter(f).size


class ValueDomain:
    """A finite value domain ``V`` with explicit witness candidates.

    The reference's ``Domain[Int].exists`` quantifies over the full (infinite)
    type and relies on the solver to find witnesses; the on-device checker
    quantifies over an explicit candidate array.  For the consensus specs the
    candidates are the current/initial estimates — any satisfying value must
    occur in the state (e.g. a value held by >2n/3 processes is some lane's
    x), so checking over them is exact.
    """

    def __init__(self, candidates: jnp.ndarray):
        self.candidates = jnp.asarray(candidates).reshape(-1)

    def exists(self, f) -> jnp.ndarray:
        return jnp.any(jax.vmap(f)(self.candidates))

    def forall(self, f) -> jnp.ndarray:
        return jnp.all(jax.vmap(f)(self.candidates))


class SetDomain:
    """The domain ``S`` of process sets, witnessed by the round's HO rows.

    Sound for specs of the shape ``S.exists(s => P.forall(p => p.HO == s &&
    ...))`` (OTR's goodRound, Otr.scala:95): any witness equal to every HO
    row must itself be an HO row.
    """

    def __init__(self, env: "Env"):
        self._env = env

    def exists(self, f) -> jnp.ndarray:
        env = self._env
        if env.ho is None:
            raise ValueError("set domain needs an HO matrix in the Env")
        return jnp.any(
            jax.vmap(lambda i: f(SetView(env.ho[i])))(
                jnp.arange(env.n, dtype=jnp.int32)
            )
        )


@dataclasses.dataclass
class Env:
    """Evaluation context for one (state, old, init, HO) snapshot.

    Leaves of ``state``/``old``/``init0`` are [n, ...] (one trace step, one
    scenario); the checker vmaps formula evaluation over rounds/scenarios.
    """

    state: Any
    n: int
    old: Any = None
    init0: Any = None
    ho: Optional[jnp.ndarray] = None
    r: Any = 0

    @property
    def P(self) -> ProcDomain:
        return ProcDomain(self)

    @property
    def S(self) -> SetDomain:
        return SetDomain(self)

    def values(self, *arrays) -> ValueDomain:
        """Value domain whose candidates are the concatenation of the given
        arrays (e.g. ``e.values(e.state.x)``)."""
        return ValueDomain(jnp.concatenate([jnp.reshape(a, (-1,)) for a in arrays]))

    def proc(self, idx) -> ProcView:
        """View a specific process (e.g. the current phase's coordinator —
        the spec-only ``coord`` of LastVoting.scala:17)."""
        return ProcView(self, jnp.asarray(idx, dtype=jnp.int32))


Formula = Callable[[Env], jnp.ndarray]


class Spec:
    """Mirror of the reference Spec trait (Specs.scala:9-19).

    Fields (all optional, all formulas are ``Env -> bool scalar``):
      safety_predicate: network assumption required for safety (checked as a
        precondition on each round's HO; e.g. BenOr needs majority HO).
      liveness_predicate: per-phase-in-the-invariant-chain "magic round"
        conditions.
      invariants: the invariant chain; the checker reports which (if any)
        holds at each step.
      round_invariants: per-round-in-phase extra invariants.
      properties: named properties; safety ones are checked at every step,
        Termination-style ones at the end of the run.
    """

    safety_predicate: Optional[Formula] = None
    liveness_predicate: Sequence[Formula] = ()
    invariants: Sequence[Formula] = ()
    round_invariants: Sequence[Sequence[Formula]] = ()
    properties: Sequence[Tuple[str, Formula]] = ()


class TrivialSpec(Spec):
    """No constraints (Specs.scala:37-41)."""
