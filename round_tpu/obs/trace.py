"""Structured round-level event tracing: ring buffer + JSONL export.

The host runtime and the CLIs emit TYPED events — round start/end, the
senders heard, wire send/recv, timeout fired, AdaptiveTimeout adjustment,
checkpoint save/restore, chaos fault injection, decision — into a
fixed-capacity ring buffer (a bounded deque: old events age out instead of
growing the process).  ``tools/trace_view.py`` merges multi-replica JSONL
dumps by (instance, round) and cross-references chaos fault events against
the timeouts/catch-ups they caused.

Zero-cost-when-disabled contract: every instrumentation site guards with

    if TRACE.enabled:
        TRACE.emit("round_end", inst=i, round=r, heard=k)

so a disabled tracer costs ONE attribute load per site — no kwargs dict,
no event object, no lock (tests/test_obs.py pins the disabled path to
zero allocations).  ``emit`` itself also early-returns on ``enabled`` so
an unguarded call site is merely slower, never wrong.

Event record shape (one JSON object per line in the export):

    {"t": <unix seconds>, "ev": "<type>", "node": <replica id>, ...}

``t`` is wall-clock (time.time) so traces from different OS processes
merge into one timeline without a shared monotonic epoch; per-round
latencies come from the ``wall_ms`` field of round_end events, which IS
measured monotonically by the emitter.  The full event vocabulary is
documented in docs/OBSERVABILITY.md.

Batched wire paths (runtime/transport.py coalesced frames, the mux's
drained routing loop) emit per LOGICAL frame, not per container — a
trace consumer never sees framing, only protocol events, so
tools/trace_view.py's fault correlation is framing-invariant (the same
property tests/test_chaos.py pins for the chaos schedules).
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional

SCHEMA_VERSION = 1


class Tracer:
    """Bounded structured-event recorder.

    Thread-safe by construction: the ring is a ``deque(maxlen=capacity)``
    and CPython's deque.append is atomic, so emitters on the InstanceMux
    router thread, replica worker threads and the main loop share one
    tracer without a lock on the hot path."""

    __slots__ = ("enabled", "node", "capacity", "_buf")

    def __init__(self, capacity: int = 65536, node: Optional[int] = None,
                 enabled: bool = False):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.enabled = enabled
        self.node = node
        self.capacity = capacity
        self._buf: collections.deque = collections.deque(maxlen=capacity)

    # -- control -----------------------------------------------------------

    def enable(self, node: Optional[int] = None,
               capacity: Optional[int] = None) -> "Tracer":
        """Start recording.  ``node`` stamps a default replica id onto
        events that do not carry their own; ``capacity`` resizes the ring
        (dropping nothing already recorded unless it shrinks)."""
        if node is not None:
            self.node = node
        if capacity is not None and capacity != self.capacity:
            if capacity < 1:
                raise ValueError(f"capacity must be >= 1, got {capacity}")
            self.capacity = capacity
            self._buf = collections.deque(self._buf, maxlen=capacity)
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        self._buf.clear()

    # -- recording ---------------------------------------------------------

    def emit(self, ev: str, **fields: Any) -> None:
        """Record one event.  Call sites on hot paths must guard with
        ``if TRACE.enabled:`` (see module docstring); the early return
        here only protects unguarded cold-path callers."""
        if not self.enabled:
            return
        rec: Dict[str, Any] = {"t": time.time(), "ev": ev}
        if self.node is not None and "node" not in fields:
            rec["node"] = self.node
        rec.update(fields)
        self._buf.append(rec)

    # -- reading / export --------------------------------------------------

    def __len__(self) -> int:
        return len(self._buf)

    def events(self) -> List[Dict[str, Any]]:
        """The recorded events, oldest first (a copy; emitters may keep
        appending)."""
        return list(self._buf)

    def dump_jsonl(self, path: str) -> int:
        """Write the buffer as JSONL (write-then-rename, the repo's
        durability discipline — a killed process never leaves a torn
        trace that breaks the merge tooling).  Returns the event count."""
        evs = self.events()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            for rec in evs:
                fh.write(json.dumps(rec, default=_jsonable) + "\n")
        os.replace(tmp, path)
        return len(evs)


def _jsonable(x):
    """numpy scalars and arrays ride into traces from jax-adjacent code;
    coerce rather than crash the dump."""
    if hasattr(x, "item") and getattr(x, "ndim", None) == 0:
        return x.item()
    if hasattr(x, "tolist"):
        return x.tolist()
    return str(x)


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    """Read one trace file back.  Tolerates a trailing half-written line
    (a crashed replica's last event) — every parseable record is kept."""
    out: List[Dict[str, Any]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn tail
    return out


def merge(traces: Iterable[List[Dict[str, Any]]]) -> List[Dict[str, Any]]:
    """Merge multiple replicas' event lists into one timeline ordered by
    wall-clock ``t`` (ties keep per-replica order — sort is stable)."""
    allev: List[Dict[str, Any]] = []
    for tr in traces:
        allev.extend(tr)
    allev.sort(key=lambda e: e.get("t", 0.0))
    return allev


# The process-wide tracer: instrumented modules import this singleton and
# guard emits on its `enabled` flag; CLIs enable it from --trace.
TRACE = Tracer()
