"""Observability: round-level structured tracing + unified typed metrics.

The HO model makes "who heard whom in round r" the fundamental unit of
execution (PAPERS.md: reducing asynchrony to synchronized rounds), so the
debugging abstraction is round-granular too:

* ``obs.trace`` — a low-overhead structured event tracer (ring buffer,
  JSONL export, strictly zero-cost when disabled) emitting typed events:
  round start/end, messages heard, send/recv at the transport, timeout
  fired + AdaptiveTimeout adjustment, checkpoint save/restore, chaos
  fault injection, decision.  ``tools/trace_view.py`` merges multi-replica
  traces by (instance, round) and cross-references chaos faults against
  the timeouts they caused.

* ``obs.metrics`` — a typed registry (counter / gauge / histogram with
  fixed buckets) with JSON and Prometheus-text snapshots.  The legacy
  ``runtime.stats`` counters/timers surface (the reference's
  utils/Stats.scala + --stat shutdown report) is implemented on top of
  it, so there is exactly one counters/timers surface in the tree.

Event schema and metric names are documented in docs/OBSERVABILITY.md.
"""

from round_tpu.obs.metrics import (  # noqa: F401
    METRICS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Stats,
    stats,
)
from round_tpu.obs.trace import TRACE, Tracer, load_jsonl  # noqa: F401
