"""Typed metrics registry: counters, gauges, fixed-bucket histograms.

ONE counters/timers surface for the whole tree (ROADMAP north star: a
metrics surface soak/bench/watch tooling can bank uniformly):

* ``Counter`` / ``Gauge`` / ``Histogram`` — typed instruments, created
  get-or-create through a ``MetricsRegistry``;
* ``MetricsRegistry.snapshot()`` / ``to_json()`` — machine-readable
  snapshots (what tools/soak.py appends to SOAK.jsonl records and the
  CLIs write behind ``--metrics-json``);
* ``MetricsRegistry.to_prometheus()`` — Prometheus text exposition, so a
  production deployment scrapes the same registry;
* ``Stats`` — the legacy counters/timers API (reference parity:
  psync.utils.Stats, utils/Stats.scala:7-98, + the --stat shutdown-hook
  report, utils/Options.scala:16-25) reimplemented ON TOP of the
  registry.  ``runtime/stats.py`` re-exports it, so existing callers and
  the --stat report format are unchanged while the storage is unified.

``METRICS`` is the process-wide registry; instrumented modules reach it
directly.  Instruments are always-on (a lock-guarded int add per event on
paths that are already wire- or ms-scale); the *legacy* ``Stats`` surface
keeps its opt-in ``enabled`` gate because the reference's --stat is
opt-in.  Hot paths that process message BATCHES (the coalesced wire,
runtime/transport.py) increment once per batch with a delta, not once
per message — the instrument cost must not scale with the coalescing
factor.  The full name vocabulary (host.*, wire.* incl. the batch/codec
family, mux.*, chaos.*, view.*, ckpt.*, engine.*) lives in
docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import atexit
import json
import os
import re
import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

# timer histograms (seconds) — compile/run/save latencies from sub-ms to
# minutes
TIME_BUCKETS_S: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0, 300.0)
# round/deadline latencies (milliseconds) on the host path
MS_BUCKETS: Tuple[float, ...] = (
    1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000)


class Counter:
    """Monotonic event count."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, delta: int = 1) -> None:
        with self._lock:
            self._v += delta

    def reset(self) -> None:
        with self._lock:
            self._v = 0

    @property
    def value(self) -> int:
        return self._v


class Gauge:
    """Last-written value (deadline in force, density, rounds/sec).

    Tracks whether it was ever written: a gauge legitimately reading 0.0
    (a mailbox floor of zero is the most alarming value such a gauge
    exists to report) must stay distinguishable from one never set —
    compact snapshots drop only the never-written."""

    __slots__ = ("name", "_v", "_touched", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0
        self._touched = False
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)
            self._touched = True

    def inc(self, delta: float = 1.0) -> None:
        with self._lock:
            self._v += delta
            self._touched = True

    def reset(self) -> None:
        with self._lock:
            self._v = 0.0
            self._touched = False

    @property
    def touched(self) -> bool:
        return self._touched

    @property
    def value(self) -> float:
        return self._v


class Histogram:
    """Fixed-bucket histogram (cumulative le-buckets + count + sum —
    the Prometheus shape).  ``unit`` documents what ``observe`` receives
    ("s" for timers, "ms" for round latencies); the --stat report prints
    unit=="s" histograms in the reference's timer line format."""

    __slots__ = ("name", "unit", "buckets", "_counts", "_count", "_sum",
                 "_lock")

    def __init__(self, name: str, buckets: Sequence[float] = TIME_BUCKETS_S,
                 unit: str = "s"):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(f"buckets must be ascending, got {buckets!r}")
        self.name = name
        self.unit = unit
        self.buckets = tuple(float(b) for b in buckets)
        self._counts = [0] * len(self.buckets)
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    break

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * len(self.buckets)
            self._count = 0
            self._sum = 0.0

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(le, cumulative_count), ..., (inf, count)] — Prometheus-style."""
        out, acc = [], 0
        with self._lock:
            for b, c in zip(self.buckets, self._counts):
                acc += c
                out.append((b, acc))
            out.append((float("inf"), self._count))
        return out


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return "round_tpu_" + _PROM_BAD.sub("_", name)


class MetricsRegistry:
    """Get-or-create instrument store with JSON / Prometheus snapshots."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}

    # -- instrument creation (get-or-create; type clashes are bugs) -------

    def counter(self, name: str) -> Counter:
        with self._lock:
            self._check_free(name, self._counters)
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            self._check_free(name, self._gauges)
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str,
                  buckets: Sequence[float] = TIME_BUCKETS_S,
                  unit: str = "s") -> Histogram:
        with self._lock:
            self._check_free(name, self._hists)
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name, buckets, unit)
            elif (h.buckets != tuple(float(b) for b in buckets)
                  or h.unit != unit):
                # same contract as _check_free: a shape clash is a bug —
                # silently returning the existing histogram would file
                # (say) seconds observations into millisecond buckets
                raise ValueError(
                    f"histogram {name!r} already registered with "
                    f"buckets={h.buckets} unit={h.unit!r}; got "
                    f"buckets={tuple(buckets)} unit={unit!r}")
            return h

    def _check_free(self, name: str, own: Dict) -> None:
        for d in (self._counters, self._gauges, self._hists):
            if d is not own and name in d:
                raise ValueError(
                    f"metric {name!r} already registered as a different "
                    f"type")

    # -- timers (sugar over seconds histograms) ---------------------------

    @contextmanager
    def timer(self, name: str) -> Iterator[None]:
        h = self.histogram(name, TIME_BUCKETS_S, unit="s")
        t0 = time.monotonic()
        try:
            yield
        finally:
            h.observe(time.monotonic() - t0)

    # -- snapshots ---------------------------------------------------------

    def snapshot(self, compact: bool = False) -> Dict:
        """Plain-dict view.  ``compact`` drops zero counters/empty
        histograms — the shape soak/bench records embed."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._hists)
        out: Dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, c in sorted(counters.items()):
            if compact and c.value == 0:
                continue
            out["counters"][name] = c.value
        for name, g in sorted(gauges.items()):
            if compact and not g.touched:
                continue
            out["gauges"][name] = g.value
        for name, h in sorted(hists.items()):
            if compact and h.count == 0:
                continue
            out["histograms"][name] = {
                "unit": h.unit,
                "count": h.count,
                "sum": round(h.sum, 6),
                "buckets": [[le if le != float("inf") else "+Inf", n]
                            for le, n in h.cumulative()],
            }
        return out

    def to_json(self, compact: bool = False) -> str:
        return json.dumps(self.snapshot(compact=compact))

    def dump_json(self, path: str, compact: bool = False) -> None:
        """Atomic snapshot file (the --metrics-json artifact)."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(self.to_json(compact=compact))
        os.replace(tmp, path)

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (one scrape body)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._hists)
        lines: List[str] = []
        for name, c in sorted(counters.items()):
            p = _prom_name(name)
            lines += [f"# TYPE {p} counter", f"{p} {c.value}"]
        for name, g in sorted(gauges.items()):
            p = _prom_name(name)
            lines += [f"# TYPE {p} gauge", f"{p} {g.value}"]
        for name, h in sorted(hists.items()):
            p = _prom_name(name)
            lines.append(f"# TYPE {p} histogram")
            for le, n in h.cumulative():
                le_s = "+Inf" if le == float("inf") else repr(le)
                lines.append(f'{p}_bucket{{le="{le_s}"}} {n}')
            lines.append(f"{p}_sum {h.sum}")
            lines.append(f"{p}_count {h.count}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every instrument IN PLACE.  Deliberately not a dict
        clear: instrumented modules cache instrument objects at import
        (runtime/host.py's _C_ROUNDS etc.), and clearing would orphan
        those — they would keep counting into objects no snapshot ever
        reads while fresh lookups returned different zeros."""
        with self._lock:
            for c in self._counters.values():
                c.reset()
            for g in self._gauges.values():
                g.reset()
            for h in self._hists.values():
                h.reset()


# The process-wide registry.
METRICS = MetricsRegistry()


class Stats:
    """Named counters and phase timers with a shutdown report — the
    legacy surface (utils/Stats.scala:7-98 + the --stat shutdown-hook
    report, utils/Options.scala:16-25), now a facade over a
    MetricsRegistry so counters/timers live in the one unified store.

    A fresh ``Stats()`` owns a private registry (test isolation); the
    module singleton ``stats`` shares the process-wide ``METRICS``, so
    --stat reports and --metrics-json snapshots read the same numbers."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.enabled = False

    def counter(self, name: str, delta: int = 1) -> None:
        if not self.enabled:
            return
        self.registry.counter(name).inc(delta)

    @contextmanager
    def timer(self, name: str) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        with self.registry.timer(name):
            yield

    def report(self) -> str:
        """The reference's report format: counters then timers, sorted.
        Timers are every seconds-unit histogram in the registry — the
        unified surface means registry timers recorded elsewhere (engine
        compile/run, checkpoint save) appear here too.  Compact snapshot:
        zeroed/never-touched instruments stay out of the report, which is
        both the reference's behavior and what makes reset() (zero in
        place, see MetricsRegistry.reset) read as a clean slate."""
        snap = self.registry.snapshot(compact=True)
        lines = ["# stats"]
        for name, v in snap["counters"].items():
            lines.append(f"counter {name}: {v}")
        for name, h in snap["histograms"].items():
            if h["unit"] != "s":
                continue
            calls, total = h["count"], h["sum"]
            lines.append(
                f"timer {name}: {total:.3f}s over {calls} calls "
                f"({1000 * total / max(calls, 1):.2f} ms/call)"
            )
        return "\n".join(lines)

    def reset(self) -> None:
        self.registry.reset()

    def enable(self, report_at_exit: bool = True) -> None:
        """--stat: start collecting; print the report at interpreter exit
        (the reference's shutdown hook, utils/Options.scala:16-25)."""
        self.enabled = True
        if report_at_exit and not getattr(self, "_hooked", False):
            atexit.register(lambda: print(self.report()))
            self._hooked = True


# module-level singleton, like the reference's Stats object — backed by
# the process-wide registry (the "exactly one counters/timers surface")
stats = Stats(registry=METRICS)
