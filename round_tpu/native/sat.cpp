// rtsat — a small CDCL SAT solver used as the native core of the framework's
// SMT backend (round_tpu.verify.solver).
//
// Role parity with the reference (PSync): the reference discharges SMT
// queries by piping SMT-LIB to an external C++ solver binary (z3/cvc4,
// utils/SmtSolver.scala:14-26).  This build has no external solver, so the
// framework ships its own native core: the Python side lowers ground
// first-order queries to CNF (Tseitin) plus theory checking (EUF congruence
// closure + linear integer arithmetic) and drives this binary over a pipe
// with DIMACS in / model or UNSAT out.
//
// Features: two-watched-literal propagation, first-UIP clause learning,
// VSIDS-style activity with decay, Luby restarts, learned-clause reduction.
//
// Protocol (batch):
//   stdin:  DIMACS CNF ("p cnf <nvars> <nclauses>", clauses 0-terminated;
//           lines starting with 'c' ignored)
//   stdout: "s SATISFIABLE\nv <lit>* 0\n"  or  "s UNSATISFIABLE\n"
// Exit code: 10 sat, 20 unsat (minisat convention).
//
// Protocol (incremental, `rtsat -i`) — the DPLL(T) driver in
// round_tpu.verify.solver keeps one process per query and feeds theory
// blocking clauses between solves, so learned clauses/activities persist
// instead of re-solving the CNF from scratch each round:
//   "p cnf <n> <m>"  init (once), then <m> clause lines
//   "s"              solve; replies "r sat\nv <lit>* 0\n" or "r unsat\n"
//   "a <lit>* 0"     add a clause at level 0
//   "q"              quit

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

typedef int Lit;  // +v / -v, 1-based DIMACS style

struct Clause {
  std::vector<Lit> lits;
  bool learned;
  double activity;
};

struct Solver {
  int nvars = 0;
  std::vector<Clause> clauses;
  // watches[lit-index] -> clause indices; lit index: 2*v + (sign?1:0)
  std::vector<std::vector<int>> watches;
  std::vector<signed char> assigns;  // 0 unset, +1 true, -1 false (per var)
  std::vector<int> level;            // decision level per var
  std::vector<int> reason;           // clause index or -1, per var
  std::vector<Lit> trail;
  std::vector<int> trail_lim;        // trail index at each decision level
  std::vector<double> activity;      // per var
  double var_inc = 1.0;
  double cla_inc = 1.0;
  std::vector<char> seen;
  size_t qhead = 0;
  long conflicts = 0;

  static int widx(Lit l) { return 2 * std::abs(l) + (l < 0 ? 1 : 0); }

  void init(int n) {
    nvars = n;
    watches.assign(2 * n + 2, {});
    assigns.assign(n + 1, 0);
    level.assign(n + 1, 0);
    reason.assign(n + 1, -1);
    activity.assign(n + 1, 0.0);
    seen.assign(n + 1, 0);
  }

  signed char value(Lit l) const {
    signed char a = assigns[std::abs(l)];
    return l > 0 ? a : (signed char)(-a);
  }

  int decision_level() const { return (int)trail_lim.size(); }

  void enqueue(Lit l, int why) {
    int v = std::abs(l);
    assigns[v] = l > 0 ? 1 : -1;
    level[v] = decision_level();
    reason[v] = why;
    trail.push_back(l);
  }

  bool add_clause(std::vector<Lit> ls, bool learned) {
    if (!learned) {
      // top-level simplification: dedup, drop clauses with both polarities
      std::vector<Lit> out;
      for (Lit l : ls) {
        bool dup = false, taut = false;
        for (Lit o : out) {
          if (o == l) dup = true;
          if (o == -l) taut = true;
        }
        if (taut) return true;
        if (!dup && value(l) != -1) {
          if (value(l) == 1) return true;  // already satisfied at level 0
          out.push_back(l);
        }
      }
      ls.swap(out);
    }
    if (ls.empty()) return false;  // conflict at level 0
    if (ls.size() == 1) {
      if (value(ls[0]) == -1) return false;
      if (value(ls[0]) == 0) enqueue(ls[0], -1);
      return true;
    }
    int ci = (int)clauses.size();
    clauses.push_back({std::move(ls), learned, 0.0});
    watches[widx(clauses[ci].lits[0])].push_back(ci);
    watches[widx(clauses[ci].lits[1])].push_back(ci);
    return true;
  }

  // returns conflicting clause index or -1
  int propagate() {
    while (qhead < trail.size()) {
      Lit p = trail[qhead++];  // p is true; visit clauses watching -p
      std::vector<int>& ws = watches[widx(-p)];
      size_t i = 0, j = 0;
      int confl = -1;
      for (; i < ws.size(); ++i) {
        int ci = ws[i];
        Clause& c = clauses[ci];
        // ensure c.lits[0] is the other watch
        if (c.lits[0] == -p) std::swap(c.lits[0], c.lits[1]);
        if (value(c.lits[0]) == 1) {
          ws[j++] = ci;
          continue;
        }
        // find a new literal to watch
        bool found = false;
        for (size_t k = 2; k < c.lits.size(); ++k) {
          if (value(c.lits[k]) != -1) {
            std::swap(c.lits[1], c.lits[k]);
            watches[widx(c.lits[1])].push_back(ci);
            found = true;
            break;
          }
        }
        if (found) continue;  // moved to another watch list
        ws[j++] = ci;
        if (value(c.lits[0]) == -1) {
          confl = ci;
          ++i;
          for (; i < ws.size(); ++i) ws[j++] = ws[i];
          break;
        }
        enqueue(c.lits[0], ci);
      }
      ws.resize(j);
      if (confl != -1) return confl;
    }
    return -1;
  }

  void bump_var(int v) {
    activity[v] += var_inc;
    if (activity[v] > 1e100) {
      for (int x = 1; x <= nvars; ++x) activity[x] *= 1e-100;
      var_inc *= 1e-100;
    }
  }

  void analyze(int confl, std::vector<Lit>& learnt, int& bt_level) {
    learnt.clear();
    learnt.push_back(0);  // placeholder for the asserting literal
    int counter = 0;
    Lit p = 0;
    int idx = (int)trail.size() - 1;
    do {
      Clause& c = clauses[confl];
      for (size_t k = (p == 0 ? 0 : 1); k < c.lits.size(); ++k) {
        Lit q = c.lits[k];
        int v = std::abs(q);
        if (!seen[v] && level[v] > 0) {
          seen[v] = 1;
          bump_var(v);
          if (level[v] == decision_level())
            ++counter;
          else
            learnt.push_back(q);
        }
      }
      // pick next literal from trail
      while (!seen[std::abs(trail[idx])]) --idx;
      p = trail[idx];
      confl = reason[std::abs(p)];
      seen[std::abs(p)] = 0;
      --counter;
    } while (counter > 0);
    learnt[0] = -p;
    // find backtrack level
    bt_level = 0;
    if (learnt.size() > 1) {
      size_t maxi = 1;
      for (size_t k = 2; k < learnt.size(); ++k)
        if (level[std::abs(learnt[k])] > level[std::abs(learnt[maxi])]) maxi = k;
      std::swap(learnt[1], learnt[maxi]);
      bt_level = level[std::abs(learnt[1])];
    }
    for (Lit l : learnt) seen[std::abs(l)] = 0;
  }

  void backtrack(int lvl) {
    if (decision_level() <= lvl) return;
    int lim = trail_lim[lvl];
    for (int i = (int)trail.size() - 1; i >= lim; --i)
      assigns[std::abs(trail[i])] = 0;
    trail.resize(lim);
    trail_lim.resize(lvl);
    qhead = trail.size();
  }

  int pick_branch() {
    int best = 0;
    double best_a = -1.0;
    for (int v = 1; v <= nvars; ++v)
      if (assigns[v] == 0 && activity[v] > best_a) {
        best = v;
        best_a = activity[v];
      }
    return best;
  }

  void reduce_learned() {
    // drop half of the learned clauses with lowest activity (not locked)
    std::vector<int> order;
    for (int i = 0; i < (int)clauses.size(); ++i)
      if (clauses[i].learned && !clauses[i].lits.empty())  // skip tombstones
        order.push_back(i);
    if (order.size() < 2000) return;
    // simple partial sort by activity
    std::sort(order.begin(), order.end(), [&](int a, int b) {
      return clauses[a].activity < clauses[b].activity;
    });
    std::vector<char> drop(clauses.size(), 0);
    for (size_t i = 0; i < order.size() / 2; ++i) {
      int ci = order[i];
      bool locked = false;
      for (Lit l : clauses[ci].lits)
        if (reason[std::abs(l)] == ci && value(l) == 1) locked = true;
      if (!locked && clauses[ci].lits.size() > 2) drop[ci] = 1;
    }
    for (auto& wl : watches) {
      size_t j = 0;
      for (size_t i = 0; i < wl.size(); ++i)
        if (!drop[wl[i]]) wl[j++] = wl[i];
      wl.resize(j);
    }
    for (size_t i = 0; i < clauses.size(); ++i)
      if (drop[i]) clauses[i].lits.clear();  // tombstone (indices stay stable)
  }

  static long luby(long i) {
    long k = 1;
    while ((1L << k) - 1 < i + 1) ++k;
    while ((1L << k) - 1 != i + 1) {
      --k;
      i = i - ((1L << k) - 1);
    }
    return 1L << (k - 1);
  }

  // returns 1 sat, 0 unsat
  int solve() {
    if (propagate() != -1) return 0;
    long restart_n = 0;
    long conflict_budget = 100 * luby(restart_n);
    std::vector<Lit> learnt;
    for (;;) {
      int confl = propagate();
      if (confl != -1) {
        ++conflicts;
        clauses[confl].activity += cla_inc;
        if (decision_level() == 0) return 0;
        int bt;
        analyze(confl, learnt, bt);
        backtrack(bt);
        if (learnt.size() == 1) {
          enqueue(learnt[0], -1);
        } else {
          int ci = (int)clauses.size();
          clauses.push_back({learnt, true, cla_inc});
          watches[widx(learnt[0])].push_back(ci);
          watches[widx(learnt[1])].push_back(ci);
          enqueue(learnt[0], ci);
        }
        var_inc /= 0.95;
        cla_inc /= 0.999;
        if (cla_inc > 1e20) {  // rescale, mirroring the var-activity bump
          for (auto& c : clauses)
            if (c.learned) c.activity *= 1e-20;
          cla_inc *= 1e-20;
        }
        if (--conflict_budget <= 0) {
          backtrack(0);
          ++restart_n;
          conflict_budget = 100 * luby(restart_n);
          reduce_learned();
        }
      } else {
        int v = pick_branch();
        if (v == 0) return 1;  // all assigned
        trail_lim.push_back((int)trail.size());
        enqueue(-v, -1);  // negative-first polarity
      }
    }
  }
};

int run_incremental() {
  Solver s;
  bool ok = true;
  bool inited = false;
  char* line = nullptr;
  size_t cap = 0;
  ssize_t len;
  std::vector<Lit> cur;
  while ((len = getline(&line, &cap, stdin)) >= 0) {
    char* p = line;
    while (*p == ' ' || *p == '\t') ++p;
    if (*p == 'q') break;
    if (*p == 'c' || *p == '\n' || *p == '\0') continue;
    if (*p == 'p') {
      while (*p && *p != ' ') ++p;
      while (*p == ' ') ++p;
      while (*p && *p != ' ') ++p;  // skip "cnf"
      long nv = strtol(p, &p, 10);
      strtol(p, &p, 10);  // clause count: informational
      s.init((int)nv);
      inited = true;
      continue;
    }
    if (*p == 's') {
      if (!inited) return 1;
      if (ok && s.solve()) {
        printf("r sat\nv ");
        for (int v = 1; v <= s.nvars; ++v)
          printf("%d ", s.assigns[v] >= 0 ? v : -v);
        printf("0\n");
      } else {
        ok = false;  // level-0 conflict: all later solves stay unsat
        printf("r unsat\n");
      }
      fflush(stdout);
      continue;
    }
    if (*p == 'a') ++p;  // "a <lits> 0" — also accept bare clause lines
    if (!inited) return 1;
    s.backtrack(0);
    cur.clear();
    for (;;) {
      long l = strtol(p, &p, 10);
      if (l == 0) break;
      cur.push_back((Lit)l);
    }
    if (!s.add_clause(cur, false)) ok = false;
  }
  free(line);
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc > 1 && strcmp(argv[1], "-i") == 0) return run_incremental();
  // read all of stdin
  std::vector<char> buf;
  {
    char tmp[1 << 16];
    size_t n;
    while ((n = fread(tmp, 1, sizeof tmp, stdin)) > 0)
      buf.insert(buf.end(), tmp, tmp + n);
    buf.push_back('\0');
  }
  Solver s;
  char* p = buf.data();
  long nv = 0, nc = 0;
  std::vector<Lit> cur;
  bool ok = true;
  while (*p) {
    while (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r') ++p;
    if (!*p) break;
    if (*p == 'c') {
      while (*p && *p != '\n') ++p;
      continue;
    }
    if (*p == 'p') {
      // p cnf nv nc
      while (*p && *p != ' ') ++p;
      while (*p == ' ') ++p;
      while (*p && *p != ' ') ++p;  // skip "cnf"
      nv = strtol(p, &p, 10);
      nc = strtol(p, &p, 10);
      (void)nc;
      s.init((int)nv);
      continue;
    }
    long l = strtol(p, &p, 10);
    if (l == 0) {
      if (!s.add_clause(cur, false)) ok = false;
      cur.clear();
    } else {
      cur.push_back((Lit)l);
    }
  }
  if (!cur.empty() && !s.add_clause(cur, false)) ok = false;

  if (ok && s.solve()) {
    printf("s SATISFIABLE\nv ");
    for (int v = 1; v <= s.nvars; ++v)
      printf("%d ", s.assigns[v] >= 0 ? v : -v);  // unset → true, arbitrary
    printf("0\n");
    return 10;
  }
  printf("s UNSATISFIABLE\n");
  return 20;
}
