// Host message transport: the native runtime piece of round_tpu.
//
// Reference parity: psync's runtime moves 8-byte-Tag + payload packets over
// Netty TCP channels with length-field framing and a connection handshake
// (TcpRuntime.scala:27-232, Tag.scala:22-25, Message.scala:15-80).  This is
// the same wire discipline as a self-contained C++ library driven from
// Python over ctypes (runtime/transport.py):
//
//   frame     := u32_be length | u64_be tag | payload bytes
//   handshake := u32_be node id | u32_be listen port, sent by the
//                connecting side first (the reference sends "host:port";
//                id + listen port is the same information under the
//                Directory's id->address map, Replicas.scala:74-80).  The
//                listen port matters under LIVE RECONFIGURATION
//                (runtime/view.py): ids are renamed to stay contiguous
//                when the group changes, so an id alone no longer proves
//                identity — a removed replica redialing with its stale id
//                would hijack the by_peer slot of whichever CURRENT
//                member inherited that id ("newest channel wins" routes
//                its traffic to the wrong node).  The acceptor therefore
//                validates the advertised listen port against its peer
//                table and closes mismatched channels as stale.
//
// Differences from the reference, by design: 4-byte length framing instead
// of 2 (no 64 KiB payload cap), connect-on-demand from either side instead
// of the lower-id-connects rule (duplicate channels are harmless: both are
// read, sends use the newest), and a poll(2) event loop thread instead of
// epoll/NIO event-loop groups (peer counts here are small).
//
// UDP mode (rt_node_create_udp) mirrors the reference's default perf
// transport (UdpRuntime.scala:19-96): one datagram socket per node, packet
// := u32_be sender id | u64_be tag | payload (datagram boundaries replace
// the length field; the explicit sender id replaces the TCP handshake under
// the same trust model), drop-tolerant by construction — no reconnect, no
// delivery guarantee, payloads capped at one datagram (~64 KiB).
//
// Threading model (one object = one node):
//   * one event-loop thread owns ALL socket reads + accepts (poll loop),
//   * senders write from their calling thread under a per-connection mutex
//     (full-duplex sockets: concurrent read from the loop is safe),
//   * received messages land in a mutex+condvar inbox drained by
//     rt_node_recv (the InstanceHandler's ArrayBlockingQueue analogue,
//     InstanceHandler.scala:45).

// TLS mode (rt_node_create_tls) is the reference's TCP_SSL
// (TcpRuntime.scala:143-158, RuntimeOptions.scala:51-67): the same framed
// protocol inside a TLS channel.  libssl is loaded with dlopen/dlsym — this
// build environment ships the OpenSSL 3 runtime but not its headers — and
// certificates are PEM paths supplied by the caller (runtime/transport.py
// generates a self-signed pair when none is given, the SelfSignedCertificate
// fallback of the reference).  Like the reference's insecure-trust default
// for self-signed deployments, peers do not verify the certificate chain
// (OpenSSL's SSL_VERIFY_NONE default) — TLS here provides channel privacy
// and integrity, not peer authentication.

#include <algorithm>
#include <arpa/inet.h>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <dlfcn.h>
#include <fcntl.h>
#include <map>
#include <memory>
#include <mutex>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// libssl via dlopen (no OpenSSL headers in this environment)
// ---------------------------------------------------------------------------

constexpr int kSslFiletypePem = 1;
constexpr int kSslErrorWantRead = 2;
constexpr int kSslErrorWantWrite = 3;

struct TlsApi {
  void *lib = nullptr;
  const void *(*TLS_method)() = nullptr;
  void *(*SSL_CTX_new)(const void *) = nullptr;
  void (*SSL_CTX_free)(void *) = nullptr;
  int (*SSL_CTX_use_certificate_chain_file)(void *, const char *) = nullptr;
  int (*SSL_CTX_use_PrivateKey_file)(void *, const char *, int) = nullptr;
  void *(*SSL_new)(void *) = nullptr;
  void (*SSL_free)(void *) = nullptr;
  int (*SSL_set_fd)(void *, int) = nullptr;
  void (*SSL_set_accept_state)(void *) = nullptr;
  void (*SSL_set_connect_state)(void *) = nullptr;
  int (*SSL_read)(void *, void *, int) = nullptr;
  int (*SSL_write)(void *, const void *, int) = nullptr;
  int (*SSL_get_error)(const void *, int) = nullptr;
  bool ok = false;
};

const TlsApi &tls_api() {
  static TlsApi api = [] {
    TlsApi a;
    a.lib = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (!a.lib) a.lib = dlopen("libssl.so", RTLD_NOW | RTLD_GLOBAL);
    if (!a.lib) return a;
    auto sym = [&](const char *name) { return dlsym(a.lib, name); };
    a.TLS_method = reinterpret_cast<const void *(*)()>(sym("TLS_method"));
    a.SSL_CTX_new =
        reinterpret_cast<void *(*)(const void *)>(sym("SSL_CTX_new"));
    a.SSL_CTX_free = reinterpret_cast<void (*)(void *)>(sym("SSL_CTX_free"));
    a.SSL_CTX_use_certificate_chain_file =
        reinterpret_cast<int (*)(void *, const char *)>(
            sym("SSL_CTX_use_certificate_chain_file"));
    a.SSL_CTX_use_PrivateKey_file =
        reinterpret_cast<int (*)(void *, const char *, int)>(
            sym("SSL_CTX_use_PrivateKey_file"));
    a.SSL_new = reinterpret_cast<void *(*)(void *)>(sym("SSL_new"));
    a.SSL_free = reinterpret_cast<void (*)(void *)>(sym("SSL_free"));
    a.SSL_set_fd = reinterpret_cast<int (*)(void *, int)>(sym("SSL_set_fd"));
    a.SSL_set_accept_state =
        reinterpret_cast<void (*)(void *)>(sym("SSL_set_accept_state"));
    a.SSL_set_connect_state =
        reinterpret_cast<void (*)(void *)>(sym("SSL_set_connect_state"));
    a.SSL_read =
        reinterpret_cast<int (*)(void *, void *, int)>(sym("SSL_read"));
    a.SSL_write = reinterpret_cast<int (*)(void *, const void *, int)>(
        sym("SSL_write"));
    a.SSL_get_error =
        reinterpret_cast<int (*)(const void *, int)>(sym("SSL_get_error"));
    a.ok = a.TLS_method && a.SSL_CTX_new && a.SSL_CTX_free &&
           a.SSL_CTX_use_certificate_chain_file &&
           a.SSL_CTX_use_PrivateKey_file && a.SSL_new && a.SSL_free &&
           a.SSL_set_fd && a.SSL_set_accept_state && a.SSL_set_connect_state &&
           a.SSL_read && a.SSL_write && a.SSL_get_error;
    return a;
  }();
  return api;
}

struct Msg {
  int from;
  uint64_t tag;
  std::vector<uint8_t> payload;
};

struct Conn {
  int fd = -1;
  int peer = -1;                  // -1 until the handshake id arrives
  std::vector<uint8_t> rbuf;      // read accumulator (frames + handshake)
  bool handshaked = false;
  std::mutex wmu;                 // serializes writes from sender threads
  // TLS state: `ssl` is the channel; an SSL object is NOT safe for
  // concurrent SSL_read/SSL_write, so smu serializes the event loop's
  // reads against sender-thread writes (plaintext conns never take it)
  void *ssl = nullptr;
  std::mutex smu;

  ~Conn() {
    if (ssl) tls_api().SSL_free(ssl);
  }
};

// SSL_write with a NONBLOCKING fd: retry WANT_READ/WANT_WRITE with a short
// poll until done or the deadline (TLS handshakes piggyback on the first
// write — connect-state conns handshake here).  Caller holds c.smu.
bool ssl_write_all(Conn &c, const uint8_t *p, size_t len, int timeout_ms) {
  const TlsApi &api = tls_api();
  auto deadline =
      std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
  size_t off = 0;
  while (off < len) {
    int k = api.SSL_write(c.ssl, p + off, static_cast<int>(len - off));
    if (k > 0) {
      off += static_cast<size_t>(k);
      continue;
    }
    int err = api.SSL_get_error(c.ssl, k);
    if (err != kSslErrorWantRead && err != kSslErrorWantWrite) return false;
    if (std::chrono::steady_clock::now() >= deadline) return false;
    pollfd pfd{c.fd, static_cast<short>(
        err == kSslErrorWantRead ? POLLIN : POLLOUT), 0};
    poll(&pfd, 1, 50);
  }
  return true;
}

bool write_all(int fd, const uint8_t *p, size_t len) {
  while (len > 0) {
    ssize_t k = ::send(fd, p, len, MSG_NOSIGNAL);
    if (k < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += k;
    len -= static_cast<size_t>(k);
  }
  return true;
}

void put_u32(std::vector<uint8_t> &b, uint32_t v) {
  b.push_back(v >> 24); b.push_back(v >> 16); b.push_back(v >> 8);
  b.push_back(v);
}

uint32_t get_u32(const uint8_t *p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

uint64_t get_u64(const uint8_t *p) {
  return (uint64_t(get_u32(p)) << 32) | get_u32(p + 4);
}

struct Node {
  int id;
  int listen_port = 0;            // resolved at bind; advertised in hellos
  int listen_fd = -1;             // TCP listen socket, or the UDP socket
  bool udp = false;
  bool tls = false;
  void *ssl_ctx = nullptr;        // shared SSL_CTX (server + client roles)
  int wake_pipe[2] = {-1, -1};    // poke the poll loop on shutdown/connect
  std::thread loop;
  bool running = false;

  std::mutex mu;                               // guards conns + peer_addr
  std::vector<std::shared_ptr<Conn>> conns;
  std::map<int, std::shared_ptr<Conn>> by_peer;
  std::map<int, std::pair<std::string, int>> peer_addr;
  std::map<int, sockaddr_in> peer_sa;          // UDP: resolved at add_peer

  std::mutex inbox_mu;
  std::condition_variable inbox_cv;
  std::deque<Msg> inbox;
  size_t max_inbox = 1 << 16;     // drop + count when full (bufferSize
  size_t dropped = 0;             // semantics, InstanceHandler.scala:85-90)
  static constexpr uint32_t kMaxFrame = 64u << 20;  // sane frame-size cap:
                                  // a larger claimed len closes the
                                  // connection (protocol violation)
  bool recv_stopped = false;      // recv returns -3 once stopped, so
                                  // blocked receiver threads can unwind
                                  // BEFORE the node is destroyed

  ~Node() {
    stop();
    if (ssl_ctx) tls_api().SSL_CTX_free(ssl_ctx);
  }

  void stop() {
    {
      std::lock_guard<std::mutex> l(mu);
      if (!running) return;
      running = false;
    }
    {
      std::lock_guard<std::mutex> l(inbox_mu);
      recv_stopped = true;
    }
    inbox_cv.notify_all();
    if (wake_pipe[1] >= 0) { uint8_t b = 0; (void)!write(wake_pipe[1], &b, 1); }
    if (loop.joinable()) loop.join();
    // close each fd under ITS write mutex without holding `mu` (senders
    // take wmu then possibly mu, so mu->wmu nesting here could deadlock)
    std::vector<std::shared_ptr<Conn>> snapshot;
    {
      std::lock_guard<std::mutex> l(mu);
      snapshot = conns;
    }
    for (auto &c : snapshot) {
      std::lock_guard<std::mutex> lw(c->wmu);
      if (c->fd >= 0) { close(c->fd); c->fd = -1; }
    }
    std::lock_guard<std::mutex> l(mu);
    conns.clear(); by_peer.clear();
    if (listen_fd >= 0) { close(listen_fd); listen_fd = -1; }
    for (int i = 0; i < 2; ++i)
      if (wake_pipe[i] >= 0) { close(wake_pipe[i]); wake_pipe[i] = -1; }
    inbox_cv.notify_all();
  }

  void enqueue(Msg &&m) {
    {
      std::lock_guard<std::mutex> l(inbox_mu);
      if (inbox.size() >= max_inbox) { ++dropped; return; }
      inbox.push_back(std::move(m));
    }
    inbox_cv.notify_one();
  }

  // parse as many complete frames as rbuf holds; false = protocol
  // violation, the caller must close the connection
  bool drain(Conn &c) {
    size_t off = 0;
    bool ok = true;
    for (;;) {
      if (!c.handshaked) {
        if (c.rbuf.size() - off < 8) break;
        int peer = static_cast<int>(get_u32(c.rbuf.data() + off));
        uint32_t lport = get_u32(c.rbuf.data() + off + 4);
        if (lport == 0 || lport > 65535) { ok = false; break; }
        c.peer = peer;
        c.handshaked = true;
        off += 8;
        std::lock_guard<std::mutex> l(mu);
        auto ad = peer_addr.find(peer);
        if (ad != peer_addr.end() &&
            ad->second.second != static_cast<int>(lport)) {
          // the dialer claims an id our peer table assigns to a DIFFERENT
          // address: a stale replica from before a rename/remove (see the
          // handshake comment at the top) — close, do NOT install it as
          // the id's channel.  A peer we have no address for is accepted
          // as before (asymmetric add_peer deployments).
          ok = false;
          break;
        }
        by_peer[c.peer] = nullptr;  // placeholder; fixed below under lock
        for (auto &sp : conns)
          if (sp.get() == &c) by_peer[c.peer] = sp;
        continue;
      }
      if (c.rbuf.size() - off < 4) break;
      uint32_t len = get_u32(c.rbuf.data() + off);
      // cap the claimed frame size: the listen port is unauthenticated,
      // and an unbounded len would buffer rbuf without limit (advisor r02,
      // medium)
      if (len > kMaxFrame) { ok = false; break; }
      // size_t-widen before the addition: `4 + len` in 32-bit wraps for
      // len >= 0xFFFFFFFC and would pass this check while the 64-bit
      // iterator math below overruns rbuf (advisor r02, medium)
      if (c.rbuf.size() - off < 4 + static_cast<size_t>(len)) break;
      if (len < 8) { off += 4 + len; continue; }  // malformed: skip frame
      Msg m;
      m.from = c.peer;
      m.tag = get_u64(c.rbuf.data() + off + 4);
      m.payload.assign(c.rbuf.begin() + off + 12,
                       c.rbuf.begin() + off + 4 + len);
      enqueue(std::move(m));
      off += 4 + len;
    }
    if (off > 0) c.rbuf.erase(c.rbuf.begin(), c.rbuf.begin() + off);
    return ok;
  }

  // UDP event loop: one socket, datagram = whole message
  void udp_loop_body() {
    std::vector<uint8_t> tmp(1 << 16);
    while (true) {
      pollfd pfds[2] = {{listen_fd, POLLIN, 0}, {wake_pipe[0], POLLIN, 0}};
      {
        std::lock_guard<std::mutex> l(mu);
        if (!running) return;
      }
      int rc = poll(pfds, 2, 200);
      if (rc < 0 && errno != EINTR) return;
      {
        std::lock_guard<std::mutex> l(mu);
        if (!running) return;
      }
      if (rc <= 0) continue;
      if (pfds[1].revents & POLLIN) {
        uint8_t b;
        while (read(wake_pipe[0], &b, 1) > 0) {}
      }
      if (!(pfds[0].revents & POLLIN)) continue;
      for (;;) {  // drain every queued datagram before re-polling
        ssize_t got = recvfrom(listen_fd, tmp.data(), tmp.size(),
                               MSG_DONTWAIT, nullptr, nullptr);
        if (got < 0) break;
        if (got < 12) continue;  // malformed datagram: drop
        Msg m;
        m.from = static_cast<int>(get_u32(tmp.data()));
        m.tag = get_u64(tmp.data() + 4);
        m.payload.assign(tmp.data() + 12, tmp.data() + got);
        enqueue(std::move(m));
      }
    }
  }

  bool udp_send(int peer, uint64_t tag, const uint8_t *payload, int len) {
    // one datagram per message; 12-byte header, kernel caps the rest
    if (len < 0 || len > 65507 - 12) return false;
    std::vector<uint8_t> pkt;
    pkt.reserve(12 + len);
    put_u32(pkt, static_cast<uint32_t>(id));
    put_u32(pkt, static_cast<uint32_t>(tag >> 32));
    put_u32(pkt, static_cast<uint32_t>(tag & 0xFFFFFFFFu));
    pkt.insert(pkt.end(), payload, payload + len);
    // sendto under `mu`: excludes stop() closing (and the fd number being
    // reused) mid-send — the UDP analogue of the TCP per-connection write
    // mutex.  The address was resolved once at add_peer, and MSG_DONTWAIT
    // keeps a full send buffer a DROP (UDP semantics), so the critical
    // section is short and never blocks the event loop.
    std::lock_guard<std::mutex> l(mu);
    auto sa = peer_sa.find(peer);
    if (sa == peer_sa.end() || listen_fd < 0) return false;
    ssize_t sent = sendto(
        listen_fd, pkt.data(), pkt.size(), MSG_DONTWAIT,
        reinterpret_cast<sockaddr *>(&sa->second), sizeof(sa->second));
    return sent == static_cast<ssize_t>(pkt.size()) ||
           (sent < 0 && (errno == EAGAIN || errno == EWOULDBLOCK ||
                         errno == ECONNREFUSED));
  }

  void loop_body() {
    if (udp) return udp_loop_body();
    std::vector<uint8_t> tmp(1 << 16);
    while (true) {
      std::vector<pollfd> pfds;
      std::vector<std::shared_ptr<Conn>> snapshot;
      {
        std::lock_guard<std::mutex> l(mu);
        if (!running) return;
        pfds.push_back({listen_fd, POLLIN, 0});
        pfds.push_back({wake_pipe[0], POLLIN, 0});
        for (auto &c : conns)
          if (c->fd >= 0) {
            pfds.push_back({c->fd, POLLIN, 0});
            snapshot.push_back(c);
          }
      }
      int rc = poll(pfds.data(), pfds.size(), 200);
      if (rc < 0 && errno != EINTR) return;
      {
        std::lock_guard<std::mutex> l(mu);
        if (!running) return;
      }
      if (rc <= 0) continue;
      if (pfds[1].revents & POLLIN) {
        uint8_t b;
        while (read(wake_pipe[0], &b, 1) > 0) {}
      }
      if (pfds[0].revents & POLLIN) {
        int fd = accept(listen_fd, nullptr, nullptr);
        if (fd >= 0) {
          int one = 1;
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          auto c = std::make_shared<Conn>();
          c->fd = fd;
          if (tls) {
            // nonblocking + server-side SSL; the handshake completes
            // inside the SSL_read calls of the read path
            fcntl(fd, F_SETFL, O_NONBLOCK);
            const TlsApi &api = tls_api();
            c->ssl = api.SSL_new(ssl_ctx);
            if (!c->ssl) { close(fd); continue; }
            api.SSL_set_fd(c->ssl, fd);
            api.SSL_set_accept_state(c->ssl);
          }
          std::lock_guard<std::mutex> l(mu);
          conns.push_back(c);
        }
      }
      for (size_t k = 0; k < snapshot.size(); ++k) {
        if (!(pfds[2 + k].revents & (POLLIN | POLLHUP | POLLERR))) continue;
        auto &c = snapshot[k];
        bool healthy = true;
        if (tls) {
          // drain every decrypted byte available now; WANT_READ = done.
          // try_lock: a sender thread may hold smu for seconds inside
          // ssl_write_all (slow peer) — blocking here would stall reads
          // for EVERY connection; skipping leaves the bytes queued in the
          // kernel and POLLIN re-fires on the next loop iteration
          const TlsApi &api = tls_api();
          std::unique_lock<std::mutex> ls(c->smu, std::try_to_lock);
          if (!ls.owns_lock()) continue;
          for (;;) {
            int got = api.SSL_read(c->ssl, tmp.data(),
                                   static_cast<int>(tmp.size()));
            if (got > 0) {
              c->rbuf.insert(c->rbuf.end(), tmp.data(), tmp.data() + got);
              continue;
            }
            int err = api.SSL_get_error(c->ssl, got);
            if (err == kSslErrorWantRead || err == kSslErrorWantWrite) break;
            healthy = false;  // clean shutdown, EOF, or protocol error
            break;
          }
          if (healthy) healthy = drain(*c);
        } else {
          ssize_t got = recv(c->fd, tmp.data(), tmp.size(), 0);
          healthy = got > 0;
          if (healthy) {
            c->rbuf.insert(c->rbuf.end(), tmp.data(), tmp.data() + got);
            healthy = drain(*c);  // false: frame-size protocol violation
          }
        }
        if (!healthy) {
          {
            // exclude senders mid-write before closing: otherwise the fd
            // number can be reused by a new accept and write_all would
            // send a frame down the wrong socket
            std::lock_guard<std::mutex> lw(c->wmu);
            close(c->fd);
            c->fd = -1;
          }
          std::lock_guard<std::mutex> l(mu);
          if (c->handshaked) {
            auto it = by_peer.find(c->peer);
            if (it != by_peer.end() && it->second == c) by_peer.erase(it);
          }
          continue;
        }
      }
      // compact closed connections
      std::lock_guard<std::mutex> l(mu);
      conns.erase(
          std::remove_if(conns.begin(), conns.end(),
                         [](const std::shared_ptr<Conn> &c) {
                           return c->fd < 0;
                         }),
          conns.end());
    }
  }

  std::shared_ptr<Conn> connect_to(int peer, int timeout_ms = 10'000) {
    std::pair<std::string, int> addr;
    int my_id;
    {
      std::lock_guard<std::mutex> l(mu);
      auto it = by_peer.find(peer);
      if (it != by_peer.end() && it->second && it->second->fd >= 0)
        return it->second;
      auto ad = peer_addr.find(peer);
      if (ad == peer_addr.end()) return nullptr;
      addr = ad->second;
      my_id = id;  // snapshot under mu: rt_node_set_id may rename us
    }
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    std::string port = std::to_string(addr.second);
    if (getaddrinfo(addr.first.c_str(), port.c_str(), &hints, &res) != 0)
      return nullptr;
    int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    // nonblocking connect bounded by timeout_ms: a blocking connect(2) to
    // an unreachable host stalls in SYN retries for seconds — the
    // reconnect loop (rt_node_connect callers) must never hang the caller
    // on a peer that is simply still dead
    bool ok = fd >= 0;
    if (ok) {
      fcntl(fd, F_SETFL, O_NONBLOCK);
      int rc = ::connect(fd, res->ai_addr, res->ai_addrlen);
      if (rc != 0 && errno == EINPROGRESS) {
        pollfd pfd{fd, POLLOUT, 0};
        ok = poll(&pfd, 1, timeout_ms) > 0;
        if (ok) {
          int err = 0;
          socklen_t elen = sizeof(err);
          ok = getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen) == 0 &&
               err == 0;
        }
      } else {
        ok = rc == 0;
      }
    }
    freeaddrinfo(res);
    if (!ok) {
      if (fd >= 0) close(fd);
      return nullptr;
    }
    if (!tls) {
      // restore blocking mode: write_all treats EAGAIN as a dead socket
      // (TLS conns stay nonblocking — ssl_write_all handles WANT_*)
      fcntl(fd, F_SETFL, fcntl(fd, F_GETFL) & ~O_NONBLOCK);
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    auto c = std::make_shared<Conn>();
    c->fd = fd;
    c->peer = peer;
    c->handshaked = true;  // outbound: we know who we dialed
    // handshake: our id + listen port first (TcpRuntime.scala:357-368's
    // client hello); in TLS mode the hello travels INSIDE the channel
    // (the first ssl_write_all also drives the TLS handshake, client
    // role)
    std::vector<uint8_t> hello;
    put_u32(hello, static_cast<uint32_t>(my_id));
    put_u32(hello, static_cast<uint32_t>(listen_port));
    bool sent;
    if (tls) {
      const TlsApi &api = tls_api();
      c->ssl = api.SSL_new(ssl_ctx);
      if (!c->ssl) { close(fd); return nullptr; }
      api.SSL_set_fd(c->ssl, fd);
      api.SSL_set_connect_state(c->ssl);
      std::lock_guard<std::mutex> ls(c->smu);
      sent = ssl_write_all(*c, hello.data(), hello.size(), 10'000);
    } else {
      sent = write_all(fd, hello.data(), hello.size());
    }
    if (!sent) {
      close(fd);
      c->fd = -1;
      return nullptr;
    }
    {
      std::lock_guard<std::mutex> l(mu);
      conns.push_back(c);
      by_peer[peer] = c;
    }
    if (wake_pipe[1] >= 0) { uint8_t b = 0; (void)!write(wake_pipe[1], &b, 1); }
    return c;
  }

  // Sever the live connection to `peer` (if any) without touching its
  // address entry: shutdown(2) from this thread, the event loop reaps the
  // fd on its next read error (the same no-close-outside-the-loop
  // discipline as the send failure path — closing here could hand the fd
  // number to a concurrent accept while the loop still polls it).
  void drop_conn(int peer) {
    std::shared_ptr<Conn> c;
    {
      std::lock_guard<std::mutex> l(mu);
      auto it = by_peer.find(peer);
      if (it == by_peer.end() || !it->second) return;
      c = it->second;
      by_peer.erase(it);
    }
    std::lock_guard<std::mutex> lw(c->wmu);
    if (c->fd >= 0) shutdown(c->fd, SHUT_RDWR);
    if (wake_pipe[1] >= 0) { uint8_t b = 0; (void)!write(wake_pipe[1], &b, 1); }
  }

  bool send_msg(int peer, uint64_t tag, const uint8_t *payload, int len) {
    if (udp) return udp_send(peer, tag, payload, len);
    // mirror the receiver's frame cap: an oversized frame would report
    // send success while the peer severs the link as a protocol violation
    if (len < 0 || static_cast<uint32_t>(len) > kMaxFrame - 8) return false;
    auto c = connect_to(peer);
    if (!c) return false;
    std::vector<uint8_t> frame;
    frame.reserve(12 + len);
    put_u32(frame, static_cast<uint32_t>(8 + len));
    put_u32(frame, static_cast<uint32_t>(tag >> 32));
    put_u32(frame, static_cast<uint32_t>(tag & 0xFFFFFFFFu));
    frame.insert(frame.end(), payload, payload + len);
    std::lock_guard<std::mutex> l(c->wmu);
    if (c->fd < 0) return false;
    bool wrote;
    if (tls) {
      std::lock_guard<std::mutex> ls(c->smu);
      wrote = c->fd >= 0 &&
              ssl_write_all(*c, frame.data(), frame.size(), 10'000);
    } else {
      wrote = write_all(c->fd, frame.data(), frame.size());
    }
    if (!wrote) {
      // connection died mid-write: drop it, caller may retry (reconnect
      // semantics of TcpRuntime.scala:162-211).  TLS write DEADLINES leave
      // a live socket behind (the peer is slow, not gone) with a
      // half-written frame — no read error will ever reap it.  shutdown()
      // (NOT close) from this sender thread: the event loop may hold the
      // fd in an in-flight poll snapshot, and closing here would let the
      // fd number be reused by a concurrent connect while the loop still
      // reads the old SSL object through it.  shutdown makes the loop's
      // next SSL_read fail, and the REAPER (loop thread) does the close.
      if (tls && c->fd >= 0) shutdown(c->fd, SHUT_RDWR);
      std::lock_guard<std::mutex> l2(mu);
      auto it = by_peer.find(peer);
      if (it != by_peer.end() && it->second == c) by_peer.erase(it);
      return false;
    }
    return true;
  }
};

}  // namespace

extern "C" {

static void *node_create(int id, int listen_port, bool udp,
                         void *tls_ctx = nullptr) {
  auto *n = new Node();
  n->id = id;
  n->udp = udp;
  n->tls = tls_ctx != nullptr;   // before the loop thread starts: an early
  n->ssl_ctx = tls_ctx;          // accept must already take the TLS path
  n->listen_fd = socket(AF_INET, udp ? SOCK_DGRAM : SOCK_STREAM, 0);
  if (n->listen_fd < 0) { delete n; return nullptr; }
  int one = 1;
  setsockopt(n->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_ANY);
  sa.sin_port = htons(static_cast<uint16_t>(listen_port));
  if (bind(n->listen_fd, reinterpret_cast<sockaddr *>(&sa), sizeof(sa)) != 0 ||
      (!udp && listen(n->listen_fd, 64) != 0) || pipe(n->wake_pipe) != 0) {
    close(n->listen_fd);
    delete n;
    return nullptr;
  }
  // the wake pipe is drained with a read loop: it MUST be non-blocking or
  // the drain blocks the event loop once empty
  fcntl(n->wake_pipe[0], F_SETFL, O_NONBLOCK);
  fcntl(n->wake_pipe[1], F_SETFL, O_NONBLOCK);
  {
    // resolve the bound port once (listen_port==0 binds ephemeral); it is
    // advertised in every outbound hello as this node's wire identity
    sockaddr_in bound{};
    socklen_t blen = sizeof(bound);
    if (getsockname(n->listen_fd, reinterpret_cast<sockaddr *>(&bound),
                    &blen) == 0)
      n->listen_port = ntohs(bound.sin_port);
  }
  n->running = true;
  n->loop = std::thread([n] { n->loop_body(); });
  return n;
}

void *rt_node_create(int id, int listen_port) {
  return node_create(id, listen_port, false);
}

// The reference's default perf transport shape (UdpRuntime.scala:19-96):
// datagram socket, drop-tolerant, one packet per message.
void *rt_node_create_udp(int id, int listen_port) {
  return node_create(id, listen_port, true);
}

// TCP_SSL (TcpRuntime.scala:143-158): the framed protocol inside TLS.
// cert/key are PEM paths (the Python layer generates a self-signed pair
// when the caller supplies none).  Returns nullptr when libssl is
// unavailable or the certificate does not load.
void *rt_node_create_tls(int id, int listen_port, const char *cert_pem,
                         const char *key_pem) {
  const TlsApi &api = tls_api();
  if (!api.ok) return nullptr;
  void *ctx = api.SSL_CTX_new(api.TLS_method());
  if (!ctx) return nullptr;
  if (api.SSL_CTX_use_certificate_chain_file(ctx, cert_pem) != 1 ||
      api.SSL_CTX_use_PrivateKey_file(ctx, key_pem, kSslFiletypePem) != 1) {
    api.SSL_CTX_free(ctx);
    return nullptr;
  }
  // on failure node_create already deleted the Node, whose destructor
  // freed ctx — freeing it here again would be a double free
  return node_create(id, listen_port, false, ctx);
}

int rt_node_port(void *node) {
  auto *n = static_cast<Node *>(node);
  sockaddr_in sa{};
  socklen_t len = sizeof(sa);
  if (getsockname(n->listen_fd, reinterpret_cast<sockaddr *>(&sa), &len) != 0)
    return -1;
  return ntohs(sa.sin_port);
}

void rt_node_add_peer(void *node, int peer_id, const char *host, int port) {
  auto *n = static_cast<Node *>(node);
  sockaddr_in sa{};
  bool have_sa = false;
  if (n->udp) {
    // resolve ONCE here, not per datagram (the send path is hot and must
    // not do synchronous DNS); resolution happens outside the node lock
    sa.sin_family = AF_INET;
    sa.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host, &sa.sin_addr) == 1) {
      have_sa = true;
    } else {
      addrinfo hints{}, *res = nullptr;
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_DGRAM;
      if (getaddrinfo(host, nullptr, &hints, &res) == 0) {
        sa.sin_addr = reinterpret_cast<sockaddr_in *>(res->ai_addr)->sin_addr;
        freeaddrinfo(res);
        have_sa = true;
      }
    }
  }
  std::lock_guard<std::mutex> l(n->mu);
  n->peer_addr[peer_id] = {host, port};
  if (have_sa) n->peer_sa[peer_id] = sa;
}

// Forget a peer: sever its live connection and drop its address entry.
// Sends to it fail from now on; the listen socket still ACCEPTS from it
// (the unauthenticated-socket trust model is unchanged — the epoch stamp
// in the Tag is what rejects a removed replica's traffic semantically).
void rt_node_remove_peer(void *node, int peer_id) {
  auto *n = static_cast<Node *>(node);
  if (!n->udp) n->drop_conn(peer_id);
  std::lock_guard<std::mutex> l(n->mu);
  n->peer_addr.erase(peer_id);
  n->peer_sa.erase(peer_id);
}

// Rename this node (Replicas.scala:136-142 renameReplica, the wire half):
// future outbound handshakes and UDP sender headers carry the new id.
// Peers holding connections handshaked under the OLD id keep attributing
// in-flight frames to it until those channels are dropped — which is why
// a view change that renames ids severs and re-dials the affected
// channels (runtime/transport.py rewire) and stamps traffic with the view
// epoch so stale attribution is detected, not trusted.
void rt_node_set_id(void *node, int new_id) {
  auto *n = static_cast<Node *>(node);
  std::lock_guard<std::mutex> l(n->mu);
  n->id = new_id;
}

// 1 when a live channel to `peer` exists (UDP: when its address is
// registered — datagram sockets have no connection state), else 0.
int rt_node_connected(void *node, int peer_id) {
  auto *n = static_cast<Node *>(node);
  std::lock_guard<std::mutex> l(n->mu);
  if (n->udp) return n->peer_sa.count(peer_id) ? 1 : 0;
  auto it = n->by_peer.find(peer_id);
  return (it != n->by_peer.end() && it->second && it->second->fd >= 0)
             ? 1 : 0;
}

// Dial `peer` now (bounded by timeout_ms) without sending anything:
// the reconnect-loop primitive (runtime/transport.py drives period +
// backoff).  0 = a channel exists (already or freshly connected),
// -1 = could not connect.  UDP nodes are always "connected".
int rt_node_connect(void *node, int peer_id, int timeout_ms) {
  auto *n = static_cast<Node *>(node);
  if (n->udp) {
    std::lock_guard<std::mutex> l(n->mu);
    return n->peer_sa.count(peer_id) ? 0 : -1;
  }
  return n->connect_to(peer_id, timeout_ms) ? 0 : -1;
}

int rt_node_send(void *node, int peer_id, uint64_t tag,
                 const uint8_t *payload, int len) {
  auto *n = static_cast<Node *>(node);
  return n->send_msg(peer_id, tag, payload, len) ? 0 : -1;
}

// Returns payload length (>= 0) with *from/*tag filled, -1 on timeout,
// -2 if buf is too small (message stays queued; call again bigger),
// -3 once the node was stopped (rt_node_stop) and the inbox is empty.
int rt_node_recv(void *node, int *from, uint64_t *tag, uint8_t *buf,
                 int buflen, int timeout_ms) {
  auto *n = static_cast<Node *>(node);
  std::unique_lock<std::mutex> l(n->inbox_mu);
  n->inbox_cv.wait_for(l, std::chrono::milliseconds(timeout_ms),
                       [n] { return !n->inbox.empty() || n->recv_stopped; });
  if (n->inbox.empty()) return n->recv_stopped ? -3 : -1;
  Msg &m = n->inbox.front();
  if (static_cast<int>(m.payload.size()) > buflen) return -2;
  *from = m.from;
  *tag = m.tag;
  std::memcpy(buf, m.payload.data(), m.payload.size());
  int len = static_cast<int>(m.payload.size());
  n->inbox.pop_front();
  return len;
}

// Batched drain: pack EVERY queued message (up to buflen) into buf as
// consecutive records
//
//   i32 from | u64 tag | u32 len | payload[len]        (native endianness)
//
// waiting up to timeout_ms for the first one.  One ctypes call + one
// Python-side copy replaces a copy-out call per message — the hot-path
// receive of runtime/transport.py (messages stay queued when they don't
// fit, so a partial drain just means another call).  *nbytes gets the
// total bytes packed.  Returns the number of messages packed, 0 on
// timeout, -2 if the FIRST message cannot fit buflen (call again with a
// bigger buf), -3 once the node was stopped and the inbox is empty.
int rt_node_recv_many(void *node, uint8_t *buf, int buflen, int timeout_ms,
                      int *nbytes) {
  auto *n = static_cast<Node *>(node);
  *nbytes = 0;
  std::unique_lock<std::mutex> l(n->inbox_mu);
  n->inbox_cv.wait_for(l, std::chrono::milliseconds(timeout_ms),
                       [n] { return !n->inbox.empty() || n->recv_stopped; });
  if (n->inbox.empty()) return n->recv_stopped ? -3 : 0;
  constexpr size_t kHdr = sizeof(int32_t) + sizeof(uint64_t) +
                          sizeof(uint32_t);
  size_t off = 0;
  int count = 0;
  while (!n->inbox.empty()) {
    Msg &m = n->inbox.front();
    size_t need = kHdr + m.payload.size();
    if (off + need > static_cast<size_t>(buflen)) {
      if (count == 0) return -2;  // first message alone overflows the buf
      break;                      // the rest stays queued for the next call
    }
    int32_t from = m.from;
    uint64_t tag = m.tag;
    uint32_t len = static_cast<uint32_t>(m.payload.size());
    std::memcpy(buf + off, &from, sizeof(from));
    std::memcpy(buf + off + 4, &tag, sizeof(tag));
    std::memcpy(buf + off + 12, &len, sizeof(len));
    if (len) std::memcpy(buf + off + kHdr, m.payload.data(), len);
    off += need;
    ++count;
    n->inbox.pop_front();
  }
  *nbytes = static_cast<int>(off);
  return count;
}

// Stop the node (event loop joined, sockets closed, blocked rt_node_recv
// calls return -3) WITHOUT freeing it: lets receiver threads unwind before
// rt_node_destroy.  Idempotent.
void rt_node_stop(void *node) {
  static_cast<Node *>(node)->stop();
}

uint64_t rt_node_dropped(void *node) {
  auto *n = static_cast<Node *>(node);
  std::lock_guard<std::mutex> l(n->inbox_mu);
  return n->dropped;
}

void rt_node_destroy(void *node) {
  auto *n = static_cast<Node *>(node);
  n->stop();
  delete n;
}

}  // extern "C"
