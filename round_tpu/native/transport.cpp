// Host message transport: the native runtime piece of round_tpu.
//
// Reference parity: psync's runtime moves 8-byte-Tag + payload packets over
// Netty TCP channels with length-field framing and a connection handshake
// (TcpRuntime.scala:27-232, Tag.scala:22-25, Message.scala:15-80).  This is
// the same wire discipline as a self-contained C++ library driven from
// Python over ctypes (runtime/transport.py):
//
//   frame     := u32_be length | u64_be tag | payload bytes
//   handshake := u32_be node id | u32_be listen port, sent by the
//                connecting side first (the reference sends "host:port";
//                id + listen port is the same information under the
//                Directory's id->address map, Replicas.scala:74-80).  The
//                listen port matters under LIVE RECONFIGURATION
//                (runtime/view.py): ids are renamed to stay contiguous
//                when the group changes, so an id alone no longer proves
//                identity — a removed replica redialing with its stale id
//                would hijack the by_peer slot of whichever CURRENT
//                member inherited that id ("newest channel wins" routes
//                its traffic to the wrong node).  The acceptor therefore
//                validates the advertised listen port against its peer
//                table and closes mismatched channels as stale.
//
// Differences from the reference, by design: 4-byte length framing instead
// of 2 (no 64 KiB payload cap), connect-on-demand from either side instead
// of the lower-id-connects rule (duplicate channels are harmless: both are
// read, sends use the newest), and a poll(2) event loop thread instead of
// epoll/NIO event-loop groups (peer counts here are small).
//
// UDP mode (rt_node_create_udp) mirrors the reference's default perf
// transport (UdpRuntime.scala:19-96): one datagram socket per node, packet
// := u32_be sender id | u64_be tag | payload (datagram boundaries replace
// the length field; the explicit sender id replaces the TCP handshake under
// the same trust model), drop-tolerant by construction — no reconnect, no
// delivery guarantee, payloads capped at one datagram (~64 KiB).
//
// Threading model (one object = one node):
//   * one event-loop thread owns ALL socket reads + accepts (poll loop),
//   * senders write from their calling thread under a per-connection mutex
//     (full-duplex sockets: concurrent read from the loop is safe),
//   * received messages land in a mutex+condvar inbox drained by
//     rt_node_recv (the InstanceHandler's ArrayBlockingQueue analogue,
//     InstanceHandler.scala:45).

// TLS mode (rt_node_create_tls) is the reference's TCP_SSL
// (TcpRuntime.scala:143-158, RuntimeOptions.scala:51-67): the same framed
// protocol inside a TLS channel.  libssl is loaded with dlopen/dlsym — this
// build environment ships the OpenSSL 3 runtime but not its headers — and
// certificates are PEM paths supplied by the caller (runtime/transport.py
// generates a self-signed pair when none is given, the SelfSignedCertificate
// fallback of the reference).  Like the reference's insecure-trust default
// for self-signed deployments, peers do not verify the certificate chain
// (OpenSSL's SSL_VERIFY_NONE default) — TLS here provides channel privacy
// and integrity, not peer authentication.

// NATIVE ROUND PUMP (rt_pump_*): the per-round wire state machine, moved
// out of Python.  PERF_MODEL.md's host-wire roofline showed rounds are
// GIL/scheduler-convoy-bound — the wire work is ~2% of round wall, but
// every received message used to wake a Python thread.  The pump runs the
// RECEIVER side of a communication-closed round inside this event loop:
// FLAG_BATCH containers are split here, payloads are matched against a
// per-(lane, round-class) codec TEMPLATE (runtime/codec.py emits a fixed
// byte layout per payload signature: every structural byte — tags, dtype
// codes, dims, counts, dict keys — is static, only array data varies), and
// matching frames memcpy their array leaves straight into the mailbox
// buffers Python registered BY POINTER (the in-place [n, ...] / [L, n, ...]
// arrays of runtime/host.py::_RoundMailbox / runtime/lanes.py::_ClassBox),
// updating the shared arrival bitmask + count.  Python blocks in ONE call,
// rt_pump_wait, which returns only when some lane crossed its progress
// threshold, its (adaptive) deadline expired, round skew demands catch-up,
// or non-fast-path traffic landed in the regular inbox (misc).  Frames the
// fast path cannot prove safe — unknown instances, non-NORMAL flags,
// template mismatches (legacy-pickle peers, byzantine garbage) — fall back
// to the inbox for the bilingual Python path, so mixed clusters
// interoperate and garbage tolerance is unchanged.  Symmetrically,
// rt_pump_flush ships a whole send wave (encode-once scratch + per-peer
// offset plan) with per-destination FLAG_BATCH coalescing in one ctypes
// crossing.  Ownership discipline: Python writes a lane's mailbox buffers
// only while the lane is DISARMED (reset/self-delivery/prefill before
// rt_pump_arm, update after); while armed, all writes happen here under
// the pump mutex — the two sides never race on the shared buffers.

#include <algorithm>
#include <arpa/inet.h>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <dlfcn.h>
#include <fcntl.h>
#include <map>
#include <memory>
#include <mutex>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// libssl via dlopen (no OpenSSL headers in this environment)
// ---------------------------------------------------------------------------

constexpr int kSslFiletypePem = 1;
constexpr int kSslErrorWantRead = 2;
constexpr int kSslErrorWantWrite = 3;

struct TlsApi {
  void *lib = nullptr;
  const void *(*TLS_method)() = nullptr;
  void *(*SSL_CTX_new)(const void *) = nullptr;
  void (*SSL_CTX_free)(void *) = nullptr;
  int (*SSL_CTX_use_certificate_chain_file)(void *, const char *) = nullptr;
  int (*SSL_CTX_use_PrivateKey_file)(void *, const char *, int) = nullptr;
  void *(*SSL_new)(void *) = nullptr;
  void (*SSL_free)(void *) = nullptr;
  int (*SSL_set_fd)(void *, int) = nullptr;
  void (*SSL_set_accept_state)(void *) = nullptr;
  void (*SSL_set_connect_state)(void *) = nullptr;
  int (*SSL_read)(void *, void *, int) = nullptr;
  int (*SSL_write)(void *, const void *, int) = nullptr;
  int (*SSL_get_error)(const void *, int) = nullptr;
  bool ok = false;
};

const TlsApi &tls_api() {
  static TlsApi api = [] {
    TlsApi a;
    a.lib = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (!a.lib) a.lib = dlopen("libssl.so", RTLD_NOW | RTLD_GLOBAL);
    if (!a.lib) return a;
    auto sym = [&](const char *name) { return dlsym(a.lib, name); };
    a.TLS_method = reinterpret_cast<const void *(*)()>(sym("TLS_method"));
    a.SSL_CTX_new =
        reinterpret_cast<void *(*)(const void *)>(sym("SSL_CTX_new"));
    a.SSL_CTX_free = reinterpret_cast<void (*)(void *)>(sym("SSL_CTX_free"));
    a.SSL_CTX_use_certificate_chain_file =
        reinterpret_cast<int (*)(void *, const char *)>(
            sym("SSL_CTX_use_certificate_chain_file"));
    a.SSL_CTX_use_PrivateKey_file =
        reinterpret_cast<int (*)(void *, const char *, int)>(
            sym("SSL_CTX_use_PrivateKey_file"));
    a.SSL_new = reinterpret_cast<void *(*)(void *)>(sym("SSL_new"));
    a.SSL_free = reinterpret_cast<void (*)(void *)>(sym("SSL_free"));
    a.SSL_set_fd = reinterpret_cast<int (*)(void *, int)>(sym("SSL_set_fd"));
    a.SSL_set_accept_state =
        reinterpret_cast<void (*)(void *)>(sym("SSL_set_accept_state"));
    a.SSL_set_connect_state =
        reinterpret_cast<void (*)(void *)>(sym("SSL_set_connect_state"));
    a.SSL_read =
        reinterpret_cast<int (*)(void *, void *, int)>(sym("SSL_read"));
    a.SSL_write = reinterpret_cast<int (*)(void *, const void *, int)>(
        sym("SSL_write"));
    a.SSL_get_error =
        reinterpret_cast<int (*)(const void *, int)>(sym("SSL_get_error"));
    a.ok = a.TLS_method && a.SSL_CTX_new && a.SSL_CTX_free &&
           a.SSL_CTX_use_certificate_chain_file &&
           a.SSL_CTX_use_PrivateKey_file && a.SSL_new && a.SSL_free &&
           a.SSL_set_fd && a.SSL_set_accept_state && a.SSL_set_connect_state &&
           a.SSL_read && a.SSL_write && a.SSL_get_error;
    return a;
  }();
  return api;
}

struct Msg {
  int from;
  uint64_t tag;
  std::vector<uint8_t> payload;
};

// ---------------------------------------------------------------------------
// round pump (see the file-top comment)
// ---------------------------------------------------------------------------

constexpr uint8_t kFlagNormal = 0x00;
constexpr uint8_t kFlagBatch = 0xB7;   // runtime/oob.py FLAG_BATCH

// arm() flags
constexpr uint32_t kPumpGrowth = 1;    // wake on every accepted frame
                                       // (FoldRound go-probes, Sync
                                       // barriers re-check in Python)
constexpr uint32_t kPumpExtend = 2;    // progress extends the deadline
                                       // (the WaitForMessage idle cap)
constexpr uint32_t kPumpStrict = 4;    // no round-skew fast-forward

// ready reason bits (rt_pump_wait reasons_out)
constexpr uint8_t kReadyThresh = 1;    // count >= progress threshold
constexpr uint8_t kReadyGrowth = 2;    // heard-set / attestation progress
constexpr uint8_t kReadySkew = 4;      // next_round > round + 1
constexpr uint8_t kReadyDeadline = 8;  // armed deadline expired
constexpr uint8_t kReadyPoke = 16;     // rt_pump_poke (mux router nudge)
constexpr uint8_t kReadyBackpr = 32;   // inbox crossed its byte high
                                       // watermark: the waiter must drain
                                       // (never in the auto-disarm set —
                                       // backpressure is not a round end)

// stats slots (shared u64[16] registered at enable; Python folds deltas
// into the pump.* metrics vocabulary, docs/OBSERVABILITY.md)
enum {
  kStFast = 0,         // template-matched inserts that grew the heard set
  kStDup = 1,          // duplicate overwrites (heard set unchanged)
  kStPending = 2,      // future-round frames buffered natively
  kStApplied = 3,      // buffered frames applied at arm
  kStFallback = 4,     // frames handed to the inbox (template miss)
  kStLate = 5,         // communication-closed-late drops
  kStMalformed = 6,    // out-of-range sender drops
  kStWaits = 7,        // rt_pump_wait calls
  kStWakesReady = 8,   // waits returning with >= 1 ready lane
  kStWakesMisc = 9,    // waits returning with inbox traffic
  kStBatchSplit = 10,  // FLAG_BATCH containers split natively
  kStBatchMalformed = 11,  // containers with a truncated tail
};

struct PumpHole {
  uint32_t off, len, leaf;
};

struct PumpLeafDst {
  uint8_t *base = nullptr;  // mailbox row base; slot = base + sender*nbytes
  uint32_t nbytes = 0;
};

struct PumpSlot {
  std::vector<uint8_t> tmpl;      // exemplar encoding; holes = array data
  std::vector<PumpHole> holes;    // ascending, non-overlapping
  std::vector<PumpLeafDst> leaves;
  uint8_t *mask = nullptr;        // [n] bool, shared with Python
  long long *count = nullptr;     // &count[lane], shared with Python
};

struct PumpLane {
  int iid = -1;
  bool open_ = false;
  bool armed = false;
  long long round_ = 0;
  int cls = 0;
  long long threshold = 0;        // 0 = never ready by count
  uint32_t flags = 0;
  uint8_t auto_disarm = 0;        // reasons that end the round: reporting
                                  // one of these disarms atomically, so no
                                  // frame can join the mailbox between the
                                  // wait returning and the jitted update
  bool has_deadline = false;
  std::chrono::steady_clock::time_point deadline{};
  int extend_ms = 0;
  uint8_t ready = 0;
  // future-round frames buffered raw per (round, sender); applied (with
  // the full template check) when Python arms that round — the native
  // form of the drivers' `_pending` dicts.  Bounded like the stash.
  std::map<long long, std::map<int, std::vector<uint8_t>>> pending;
  size_t pending_frames = 0;
  std::vector<PumpSlot> slots;    // [k] round classes
};

constexpr size_t kPumpPendingCap = 4096;  // per lane

struct Pump {
  std::mutex mu;
  std::condition_variable cv;
  int L = 0, n = 0, k = 0, nbz = 0;
  std::vector<PumpLane> lanes;
  std::vector<int32_t> iid2lane;       // [65536], -1 = not mapped
  long long *max_rnd = nullptr;        // [L, n] shared with Python
  long long *next_round = nullptr;     // [L] shared with Python
  unsigned long long *stats = nullptr; // [16] shared with Python
  std::atomic<bool> misc{false};       // inbox gained a frame
  std::atomic<bool> stopped{false};
  uint64_t bp_seen = 0;                // backpressure edges already
                                       // reported (guarded by mu)

  void configure(int L_, int n_, int k_, int nbz_, long long *mr,
                 long long *nr, unsigned long long *st) {
    std::lock_guard<std::mutex> l(mu);
    L = L_; n = n_; k = k_; nbz = nbz_;
    max_rnd = mr; next_round = nr; stats = st;
    lanes.assign(L, PumpLane{});
    for (auto &ln : lanes) ln.slots.resize(k);
    iid2lane.assign(1 << 16, -1);
    misc.store(false);
    stopped.store(false);
  }

  // template match + in-place leaf copy; 1 = heard set grew, 0 = duplicate
  // overwrite, -1 = template mismatch (caller falls back to Python)
  int slot_insert(PumpSlot &s, int from, const uint8_t *p, size_t len) {
    if (s.tmpl.empty() || len != s.tmpl.size()) return -1;
    size_t pos = 0;
    for (const auto &h : s.holes) {
      if (h.off > pos &&
          std::memcmp(p + pos, s.tmpl.data() + pos, h.off - pos) != 0)
        return -1;
      pos = h.off + h.len;
    }
    if (pos < len &&
        std::memcmp(p + pos, s.tmpl.data() + pos, len - pos) != 0)
      return -1;
    for (const auto &h : s.holes) {
      const PumpLeafDst &lf = s.leaves[h.leaf];
      std::memcpy(lf.base + static_cast<size_t>(from) * lf.nbytes,
                  p + h.off, h.len);
    }
    if (!s.mask[from]) {
      s.mask[from] = 1;
      ++*s.count;
      return 1;
    }
    return 0;
  }

  void recompute_next_round(int lane_i) {
    long long *mr = max_rnd + static_cast<size_t>(lane_i) * n;
    long long v;
    if (nbz <= 0) {
      v = mr[0];
      for (int i = 1; i < n; ++i) v = std::max(v, mr[i]);
    } else {
      // byzantine catch-up: the (f+1)-th highest claim — f liars cannot
      // drag the lane forward (InstanceHandler.scala:302-307)
      std::vector<long long> row(mr, mr + n);
      std::nth_element(row.begin(), row.begin() + (n - 1 - nbz), row.end());
      v = row[n - 1 - nbz];
    }
    if (v > next_round[lane_i]) next_round[lane_i] = v;
  }

  // caller holds mu.  kind: 0 = wire (template miss -> inbox fallback,
  // return false), 1 = feed from Python (template miss -> return -2, the
  // caller decodes + re-encodes canonically + rt_pump_insert).
  // Returns: 1 consumed, 0 not pump-routable (unknown iid / non-NORMAL),
  // -2 template miss at the armed current round.
  int route_locked(int from, uint64_t tagw, const uint8_t *p, size_t len) {
    if ((tagw & 0xFF) != kFlagNormal) return 0;
    int iid = static_cast<int>((tagw >> 16) & 0xFFFF);
    long long r = static_cast<long long>((tagw >> 32) & 0xFFFFFFFFull);
    int lane_i = iid2lane[iid];
    if (lane_i < 0) return 0;  // unknown instance: stash/TooLate in Python
    PumpLane &ln = lanes[lane_i];
    if (from < 0 || from >= n) {
      // protocol garbage on the unauthenticated socket: an out-of-range
      // id would corrupt every sender-indexed structure
      ++stats[kStMalformed];
      return 1;
    }
    long long *mr = max_rnd + static_cast<size_t>(lane_i) * n;
    if (r > mr[from]) mr[from] = r;
    if (r < ln.round_) {
      ++stats[kStLate];
      return 1;  // late: the round is communication-closed
    }
    bool accepted = false;
    uint8_t newly = 0;
    if (r > ln.round_ || !ln.armed) {
      auto &mp = ln.pending[r];
      auto it = mp.find(from);
      if (it != mp.end()) {
        it->second.assign(p, p + len);  // latest-wins, like the dicts
        accepted = true;
      } else if (ln.pending_frames < kPumpPendingCap) {
        mp.emplace(from, std::vector<uint8_t>(p, p + len));
        ++ln.pending_frames;
        accepted = true;
      }
      if (accepted) ++stats[kStPending];
      if (r > ln.round_) {
        recompute_next_round(lane_i);
        if (ln.armed && !(ln.flags & kPumpStrict) &&
            next_round[lane_i] > ln.round_ + 1)
          newly |= kReadySkew;
      }
    } else {
      int rc = slot_insert(ln.slots[ln.cls], from, p, len);
      if (rc < 0) {
        // the WIRE path counts kStFallback (deliver_one_locked) — not
        // here, or the rt_pump_feed retry of the same frame would count
        // it twice
        return -2;
      }
      accepted = true;
      if (rc == 1) {
        ++stats[kStFast];
        if (ln.threshold > 0 && *ln.slots[ln.cls].count >= ln.threshold)
          newly |= kReadyThresh;
      } else {
        ++stats[kStDup];
      }
    }
    if (accepted && ln.armed) {
      if (ln.flags & kPumpGrowth) newly |= kReadyGrowth;
      if ((ln.flags & kPumpExtend) && ln.extend_ms > 0) {
        ln.deadline = std::chrono::steady_clock::now() +
                      std::chrono::milliseconds(ln.extend_ms);
        ln.has_deadline = true;
      }
    }
    if (newly) {
      ln.ready |= newly;
      cv.notify_all();
    }
    return 1;
  }
};

struct Conn {
  int fd = -1;
  int peer = -1;                  // -1 until the handshake id arrives
  std::vector<uint8_t> rbuf;      // read accumulator (frames + handshake)
  bool handshaked = false;
  std::mutex wmu;                 // serializes writes from sender threads
  // TLS state: `ssl` is the channel; an SSL object is NOT safe for
  // concurrent SSL_read/SSL_write, so smu serializes the event loop's
  // reads against sender-thread writes (plaintext conns never take it)
  void *ssl = nullptr;
  std::mutex smu;

  ~Conn() {
    if (ssl) tls_api().SSL_free(ssl);
  }
};

// SSL_write with a NONBLOCKING fd: retry WANT_READ/WANT_WRITE with a short
// poll until done or the deadline (TLS handshakes piggyback on the first
// write — connect-state conns handshake here).  Caller holds c.smu.
bool ssl_write_all(Conn &c, const uint8_t *p, size_t len, int timeout_ms) {
  const TlsApi &api = tls_api();
  auto deadline =
      std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
  size_t off = 0;
  while (off < len) {
    int k = api.SSL_write(c.ssl, p + off, static_cast<int>(len - off));
    if (k > 0) {
      off += static_cast<size_t>(k);
      continue;
    }
    int err = api.SSL_get_error(c.ssl, k);
    if (err != kSslErrorWantRead && err != kSslErrorWantWrite) return false;
    if (std::chrono::steady_clock::now() >= deadline) return false;
    pollfd pfd{c.fd, static_cast<short>(
        err == kSslErrorWantRead ? POLLIN : POLLOUT), 0};
    poll(&pfd, 1, 50);
  }
  return true;
}

bool write_all(int fd, const uint8_t *p, size_t len) {
  while (len > 0) {
    ssize_t k = ::send(fd, p, len, MSG_NOSIGNAL);
    if (k < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += k;
    len -= static_cast<size_t>(k);
  }
  return true;
}

void put_u32(std::vector<uint8_t> &b, uint32_t v) {
  b.push_back(v >> 24); b.push_back(v >> 16); b.push_back(v >> 8);
  b.push_back(v);
}

uint32_t get_u32(const uint8_t *p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

uint64_t get_u64(const uint8_t *p) {
  return (uint64_t(get_u32(p)) << 32) | get_u32(p + 4);
}

struct Node {
  int id;
  int listen_port = 0;            // resolved at bind; advertised in hellos
  int listen_fd = -1;             // TCP listen socket, or the UDP socket
  bool udp = false;
  bool tls = false;
  void *ssl_ctx = nullptr;        // shared SSL_CTX (server + client roles)
  int wake_pipe[2] = {-1, -1};    // poke the poll loop on shutdown/connect
  std::thread loop;
  bool running = false;

  std::mutex mu;                               // guards conns + peer_addr
  std::vector<std::shared_ptr<Conn>> conns;
  std::map<int, std::shared_ptr<Conn>> by_peer;
  std::map<int, std::pair<std::string, int>> peer_addr;
  std::map<int, sockaddr_in> peer_sa;          // UDP: resolved at add_peer

  // per-peer send PAUSE (overload hardening, the native mirror of
  // runtime/transport.py's Python-surface pause — the pump's
  // rt_pump_flush sends land HERE, so without it a dead peer is
  // re-dialed on every round flush): after `pause_after` consecutive
  // send_msg failures to one peer, sends to it drop-with-count for
  // `pause_ms` instead of dialing.  A successful dial (send path OR the
  // reconnect loop's rt_node_connect) clears the pause.  Guarded by mu;
  // the counters are atomics so the Python drain path can fold them
  // into wire.peer_pauses / wire.backpressure_drops lock-free.
  int pause_after = 16;
  int pause_ms = 250;
  std::map<int, int> send_fails;
  std::map<int, std::chrono::steady_clock::time_point> send_pause;
  std::atomic<uint64_t> send_pauses{0};
  std::atomic<uint64_t> send_pause_drops{0};

  std::mutex inbox_mu;
  std::condition_variable inbox_cv;
  std::deque<Msg> inbox;
  size_t max_inbox = 1 << 16;     // drop + count when full (bufferSize
  size_t dropped = 0;             // semantics, InstanceHandler.scala:85-90)
  // BOUNDED inbox bytes + backpressure watermarks (overload hardening,
  // docs/HOST_FAULT_MODEL.md): the message-count cap alone let 65536
  // near-64 MiB frames queue ~4 TiB — the byte cap makes the inbox a
  // fixed-memory structure (drop + count beyond it, like the count cap),
  // and the high/low watermarks raise a BACKPRESSURE signal the drivers
  // drain on (kReadyBackpr reason bit / rt_node_backpressure) well
  // before anything is dropped.
  size_t inbox_bytes = 0;                       // guarded by inbox_mu
  size_t max_inbox_bytes = 256ull << 20;        // hard drop cap
  size_t bp_high = 32ull << 20;                 // raise backpressure
  size_t bp_low = 8ull << 20;                   // clear backpressure
  std::atomic<bool> backpressure{false};
  std::atomic<uint64_t> bp_edges{0};            // rising-edge counter
  static constexpr uint32_t kMaxFrame = 64u << 20;  // sane frame-size cap:
                                  // a larger claimed len closes the
                                  // connection (protocol violation)
  bool recv_stopped = false;      // recv returns -3 once stopped, so
                                  // blocked receiver threads can unwind
                                  // BEFORE the node is destroyed

  // round pump: allocated once at first rt_pump_enable, torn down only in
  // ~Node (the event loop reads `pump_on` without the node lock, so the
  // object must outlive any loop iteration that observed it enabled)
  Pump *pump = nullptr;
  std::atomic<bool> pump_on{false};

  ~Node() {
    stop();
    delete pump;
    if (ssl_ctx) tls_api().SSL_CTX_free(ssl_ctx);
  }

  void stop() {
    {
      std::lock_guard<std::mutex> l(mu);
      if (!running) return;
      running = false;
    }
    {
      std::lock_guard<std::mutex> l(inbox_mu);
      recv_stopped = true;
    }
    inbox_cv.notify_all();
    if (pump) {
      pump->stopped.store(true);
      pump->cv.notify_all();  // blocked rt_pump_wait callers unwind
    }
    if (wake_pipe[1] >= 0) { uint8_t b = 0; (void)!write(wake_pipe[1], &b, 1); }
    if (loop.joinable()) loop.join();
    // close each fd under ITS write mutex without holding `mu` (senders
    // take wmu then possibly mu, so mu->wmu nesting here could deadlock)
    std::vector<std::shared_ptr<Conn>> snapshot;
    {
      std::lock_guard<std::mutex> l(mu);
      snapshot = conns;
    }
    for (auto &c : snapshot) {
      std::lock_guard<std::mutex> lw(c->wmu);
      if (c->fd >= 0) { close(c->fd); c->fd = -1; }
    }
    std::lock_guard<std::mutex> l(mu);
    conns.clear(); by_peer.clear();
    if (listen_fd >= 0) { close(listen_fd); listen_fd = -1; }
    for (int i = 0; i < 2; ++i)
      if (wake_pipe[i] >= 0) { close(wake_pipe[i]); wake_pipe[i] = -1; }
    inbox_cv.notify_all();
  }

  // caller holds inbox_mu: account one popped message and clear the
  // backpressure flag once the drain reaches the low watermark
  void note_popped_locked(size_t nbytes) {
    inbox_bytes -= nbytes;
    if (backpressure.load(std::memory_order_relaxed) &&
        inbox_bytes <= bp_low)
      backpressure.store(false, std::memory_order_release);
  }

  void enqueue(Msg &&m) {
    {
      std::lock_guard<std::mutex> l(inbox_mu);
      if (inbox.size() >= max_inbox ||
          inbox_bytes + m.payload.size() > max_inbox_bytes) {
        ++dropped;
        return;
      }
      inbox_bytes += m.payload.size();
      inbox.push_back(std::move(m));
      if (!backpressure.load(std::memory_order_relaxed) &&
          inbox_bytes >= bp_high) {
        // rising edge: flag it (rt_node_backpressure level) and count it
        // (bp_edges — rt_pump_wait translates unseen edges into the
        // kReadyBackpr reason bit on armed lanes).  The pump mutex is
        // NOT taken here: deliver() already holds it when it calls
        // enqueue, and the misc notify below wakes any waiter anyway.
        backpressure.store(true, std::memory_order_release);
        bp_edges.fetch_add(1, std::memory_order_release);
      }
    }
    inbox_cv.notify_one();
    if (pump_on.load(std::memory_order_acquire)) {
      // misc traffic (decisions, foreign instances, template-miss
      // fallbacks) must interrupt a blocked rt_pump_wait: the Python side
      // drains the inbox on the misc flag
      pump->misc.store(true);
      pump->cv.notify_all();
    }
  }

  // frame delivery: the pump fast path when enabled (FLAG_BATCH containers
  // split HERE so sub-frames route without a Python wakeup), the plain
  // inbox otherwise.  Runs on the event-loop thread.  pump_on is
  // RE-CHECKED under the pump mutex: rt_pump_disable clears the flag and
  // then takes/releases that mutex, so once disable returns no event-loop
  // write can touch the Python-owned mailbox buffers (they are about to
  // be freed) — without the re-check a thread that loaded pump_on just
  // before the clear could still memcpy into freed memory.
  void deliver(int from, uint64_t tag, const uint8_t *p, size_t len) {
    if (pump_on.load(std::memory_order_acquire)) {
      std::unique_lock<std::mutex> l(pump->mu);
      if (pump_on.load(std::memory_order_relaxed)) {
        if ((tag & 0xFF) == kFlagBatch) {
          // sub-frame header: u64 tag | u32 len, little-endian
          // (runtime/transport.py _BATCH_HDR) — memcpy is exact on
          // x86-64
          ++pump->stats[kStBatchSplit];
          size_t off = 0;
          while (off + 12 <= len) {
            uint64_t sub;
            uint32_t l2;
            std::memcpy(&sub, p + off, 8);
            std::memcpy(&l2, p + off + 8, 4);
            off += 12;
            if (off + l2 > len) {
              ++pump->stats[kStBatchMalformed];
              return;  // truncated container: keep the parseable prefix
            }
            deliver_one_locked(from, sub, p + off, l2);
            off += l2;
          }
          if (off != len) ++pump->stats[kStBatchMalformed];
          return;
        }
        deliver_one_locked(from, tag, p, len);
        return;
      }
      // disabled while we waited for the mutex: fall through to the inbox
    }
    Msg m;
    m.from = from;
    m.tag = tag;
    m.payload.assign(p, p + len);
    enqueue(std::move(m));
  }

  // caller holds pump->mu
  void deliver_one_locked(int from, uint64_t tag, const uint8_t *p,
                          size_t len) {
    int rc = pump->route_locked(from, tag, p, len);
    if (rc == 1) return;
    if (rc == -2) ++pump->stats[kStFallback];  // wire-path template miss
    // non-NORMAL / unknown instance / template miss: the bilingual
    // Python path owns it (enqueue sets the misc wake)
    Msg m;
    m.from = from;
    m.tag = tag;
    m.payload.assign(p, p + len);
    enqueue(std::move(m));
  }

  // parse as many complete frames as rbuf holds; false = protocol
  // violation, the caller must close the connection
  bool drain(Conn &c) {
    size_t off = 0;
    bool ok = true;
    for (;;) {
      if (!c.handshaked) {
        if (c.rbuf.size() - off < 8) break;
        int peer = static_cast<int>(get_u32(c.rbuf.data() + off));
        uint32_t lport = get_u32(c.rbuf.data() + off + 4);
        if (lport == 0 || lport > 65535) { ok = false; break; }
        c.peer = peer;
        c.handshaked = true;
        off += 8;
        std::lock_guard<std::mutex> l(mu);
        auto ad = peer_addr.find(peer);
        if (ad != peer_addr.end() &&
            ad->second.second != static_cast<int>(lport)) {
          // the dialer claims an id our peer table assigns to a DIFFERENT
          // address: a stale replica from before a rename/remove (see the
          // handshake comment at the top) — close, do NOT install it as
          // the id's channel.  A peer we have no address for is accepted
          // as before (asymmetric add_peer deployments).
          ok = false;
          break;
        }
        by_peer[c.peer] = nullptr;  // placeholder; fixed below under lock
        for (auto &sp : conns)
          if (sp.get() == &c) by_peer[c.peer] = sp;
        continue;
      }
      if (c.rbuf.size() - off < 4) break;
      uint32_t len = get_u32(c.rbuf.data() + off);
      // cap the claimed frame size: the listen port is unauthenticated,
      // and an unbounded len would buffer rbuf without limit (advisor r02,
      // medium)
      if (len > kMaxFrame) { ok = false; break; }
      // size_t-widen before the addition: `4 + len` in 32-bit wraps for
      // len >= 0xFFFFFFFC and would pass this check while the 64-bit
      // iterator math below overruns rbuf (advisor r02, medium)
      if (c.rbuf.size() - off < 4 + static_cast<size_t>(len)) break;
      if (len < 8) { off += 4 + len; continue; }  // malformed: skip frame
      deliver(c.peer, get_u64(c.rbuf.data() + off + 4),
              c.rbuf.data() + off + 12, len - 8);
      off += 4 + len;
    }
    if (off > 0) c.rbuf.erase(c.rbuf.begin(), c.rbuf.begin() + off);
    return ok;
  }

  // UDP event loop: one socket, datagram = whole message
  void udp_loop_body() {
    std::vector<uint8_t> tmp(1 << 16);
    while (true) {
      pollfd pfds[2] = {{listen_fd, POLLIN, 0}, {wake_pipe[0], POLLIN, 0}};
      {
        std::lock_guard<std::mutex> l(mu);
        if (!running) return;
      }
      int rc = poll(pfds, 2, 200);
      if (rc < 0 && errno != EINTR) return;
      {
        std::lock_guard<std::mutex> l(mu);
        if (!running) return;
      }
      if (rc <= 0) continue;
      if (pfds[1].revents & POLLIN) {
        uint8_t b;
        while (read(wake_pipe[0], &b, 1) > 0) {}
      }
      if (!(pfds[0].revents & POLLIN)) continue;
      for (;;) {  // drain every queued datagram before re-polling
        ssize_t got = recvfrom(listen_fd, tmp.data(), tmp.size(),
                               MSG_DONTWAIT, nullptr, nullptr);
        if (got < 0) break;
        if (got < 12) continue;  // malformed datagram: drop
        deliver(static_cast<int>(get_u32(tmp.data())),
                get_u64(tmp.data() + 4), tmp.data() + 12,
                static_cast<size_t>(got) - 12);
      }
    }
  }

  bool udp_send(int peer, uint64_t tag, const uint8_t *payload, int len) {
    // one datagram per message; 12-byte header, kernel caps the rest
    if (len < 0 || len > 65507 - 12) return false;
    std::vector<uint8_t> pkt;
    pkt.reserve(12 + len);
    put_u32(pkt, static_cast<uint32_t>(id));
    put_u32(pkt, static_cast<uint32_t>(tag >> 32));
    put_u32(pkt, static_cast<uint32_t>(tag & 0xFFFFFFFFu));
    pkt.insert(pkt.end(), payload, payload + len);
    // sendto under `mu`: excludes stop() closing (and the fd number being
    // reused) mid-send — the UDP analogue of the TCP per-connection write
    // mutex.  The address was resolved once at add_peer, and MSG_DONTWAIT
    // keeps a full send buffer a DROP (UDP semantics), so the critical
    // section is short and never blocks the event loop.
    std::lock_guard<std::mutex> l(mu);
    auto sa = peer_sa.find(peer);
    if (sa == peer_sa.end() || listen_fd < 0) return false;
    ssize_t sent = sendto(
        listen_fd, pkt.data(), pkt.size(), MSG_DONTWAIT,
        reinterpret_cast<sockaddr *>(&sa->second), sizeof(sa->second));
    return sent == static_cast<ssize_t>(pkt.size()) ||
           (sent < 0 && (errno == EAGAIN || errno == EWOULDBLOCK ||
                         errno == ECONNREFUSED));
  }

  void loop_body() {
    if (udp) return udp_loop_body();
    std::vector<uint8_t> tmp(1 << 16);
    while (true) {
      std::vector<pollfd> pfds;
      std::vector<std::shared_ptr<Conn>> snapshot;
      {
        std::lock_guard<std::mutex> l(mu);
        if (!running) return;
        pfds.push_back({listen_fd, POLLIN, 0});
        pfds.push_back({wake_pipe[0], POLLIN, 0});
        for (auto &c : conns)
          if (c->fd >= 0) {
            pfds.push_back({c->fd, POLLIN, 0});
            snapshot.push_back(c);
          }
      }
      int rc = poll(pfds.data(), pfds.size(), 200);
      if (rc < 0 && errno != EINTR) return;
      {
        std::lock_guard<std::mutex> l(mu);
        if (!running) return;
      }
      if (rc <= 0) continue;
      if (pfds[1].revents & POLLIN) {
        uint8_t b;
        while (read(wake_pipe[0], &b, 1) > 0) {}
      }
      if (pfds[0].revents & POLLIN) {
        int fd = accept(listen_fd, nullptr, nullptr);
        if (fd >= 0) {
          int one = 1;
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          auto c = std::make_shared<Conn>();
          c->fd = fd;
          if (tls) {
            // nonblocking + server-side SSL; the handshake completes
            // inside the SSL_read calls of the read path
            fcntl(fd, F_SETFL, O_NONBLOCK);
            const TlsApi &api = tls_api();
            c->ssl = api.SSL_new(ssl_ctx);
            if (!c->ssl) { close(fd); continue; }
            api.SSL_set_fd(c->ssl, fd);
            api.SSL_set_accept_state(c->ssl);
          }
          std::lock_guard<std::mutex> l(mu);
          conns.push_back(c);
        }
      }
      for (size_t k = 0; k < snapshot.size(); ++k) {
        if (!(pfds[2 + k].revents & (POLLIN | POLLHUP | POLLERR))) continue;
        auto &c = snapshot[k];
        bool healthy = true;
        if (tls) {
          // drain every decrypted byte available now; WANT_READ = done.
          // try_lock: a sender thread may hold smu for seconds inside
          // ssl_write_all (slow peer) — blocking here would stall reads
          // for EVERY connection; skipping leaves the bytes queued in the
          // kernel and POLLIN re-fires on the next loop iteration
          const TlsApi &api = tls_api();
          std::unique_lock<std::mutex> ls(c->smu, std::try_to_lock);
          if (!ls.owns_lock()) continue;
          for (;;) {
            int got = api.SSL_read(c->ssl, tmp.data(),
                                   static_cast<int>(tmp.size()));
            if (got > 0) {
              c->rbuf.insert(c->rbuf.end(), tmp.data(), tmp.data() + got);
              continue;
            }
            int err = api.SSL_get_error(c->ssl, got);
            if (err == kSslErrorWantRead || err == kSslErrorWantWrite) break;
            healthy = false;  // clean shutdown, EOF, or protocol error
            break;
          }
          if (healthy) healthy = drain(*c);
        } else {
          ssize_t got = recv(c->fd, tmp.data(), tmp.size(), 0);
          healthy = got > 0;
          if (healthy) {
            c->rbuf.insert(c->rbuf.end(), tmp.data(), tmp.data() + got);
            healthy = drain(*c);  // false: frame-size protocol violation
          }
        }
        if (!healthy) {
          {
            // exclude senders mid-write before closing: otherwise the fd
            // number can be reused by a new accept and write_all would
            // send a frame down the wrong socket
            std::lock_guard<std::mutex> lw(c->wmu);
            close(c->fd);
            c->fd = -1;
          }
          std::lock_guard<std::mutex> l(mu);
          if (c->handshaked) {
            auto it = by_peer.find(c->peer);
            if (it != by_peer.end() && it->second == c) by_peer.erase(it);
          }
          continue;
        }
      }
      // compact closed connections
      std::lock_guard<std::mutex> l(mu);
      conns.erase(
          std::remove_if(conns.begin(), conns.end(),
                         [](const std::shared_ptr<Conn> &c) {
                           return c->fd < 0;
                         }),
          conns.end());
    }
  }

  std::shared_ptr<Conn> connect_to(int peer, int timeout_ms = 10'000) {
    std::pair<std::string, int> addr;
    int my_id;
    {
      std::lock_guard<std::mutex> l(mu);
      auto it = by_peer.find(peer);
      if (it != by_peer.end() && it->second && it->second->fd >= 0)
        return it->second;
      auto ad = peer_addr.find(peer);
      if (ad == peer_addr.end()) return nullptr;
      addr = ad->second;
      my_id = id;  // snapshot under mu: rt_node_set_id may rename us
    }
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    std::string port = std::to_string(addr.second);
    if (getaddrinfo(addr.first.c_str(), port.c_str(), &hints, &res) != 0)
      return nullptr;
    int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    // nonblocking connect bounded by timeout_ms: a blocking connect(2) to
    // an unreachable host stalls in SYN retries for seconds — the
    // reconnect loop (rt_node_connect callers) must never hang the caller
    // on a peer that is simply still dead
    bool ok = fd >= 0;
    if (ok) {
      fcntl(fd, F_SETFL, O_NONBLOCK);
      int rc = ::connect(fd, res->ai_addr, res->ai_addrlen);
      if (rc != 0 && errno == EINPROGRESS) {
        pollfd pfd{fd, POLLOUT, 0};
        ok = poll(&pfd, 1, timeout_ms) > 0;
        if (ok) {
          int err = 0;
          socklen_t elen = sizeof(err);
          ok = getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen) == 0 &&
               err == 0;
        }
      } else {
        ok = rc == 0;
      }
    }
    if (ok) {
      // LOCALHOST SELF-CONNECT guard: dialing a not-yet-listening port
      // on 127.0.0.1 can land a TCP *simultaneous open* when the kernel
      // assigns our ephemeral source port equal to the destination port
      // — the socket connects to ITSELF, the handshake below echoes
      // back, and the "channel" is cached as live while the real peer
      // stays unreachable forever (observed: a fleet router dialing
      // shard replicas during their ~5 s interpreter startup wedged a
      // whole shard).  getsockname == getpeername is the signature.
      sockaddr_in self{}, peer_sa{};
      socklen_t slen = sizeof(self), plen = sizeof(peer_sa);
      if (getsockname(fd, reinterpret_cast<sockaddr *>(&self),
                      &slen) == 0 &&
          getpeername(fd, reinterpret_cast<sockaddr *>(&peer_sa),
                      &plen) == 0 &&
          self.sin_port == peer_sa.sin_port &&
          self.sin_addr.s_addr == peer_sa.sin_addr.s_addr)
        ok = false;
    }
    freeaddrinfo(res);
    if (!ok) {
      if (fd >= 0) close(fd);
      return nullptr;
    }
    if (!tls) {
      // restore blocking mode: write_all treats EAGAIN as a dead socket
      // (TLS conns stay nonblocking — ssl_write_all handles WANT_*)
      fcntl(fd, F_SETFL, fcntl(fd, F_GETFL) & ~O_NONBLOCK);
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    auto c = std::make_shared<Conn>();
    c->fd = fd;
    c->peer = peer;
    c->handshaked = true;  // outbound: we know who we dialed
    // handshake: our id + listen port first (TcpRuntime.scala:357-368's
    // client hello); in TLS mode the hello travels INSIDE the channel
    // (the first ssl_write_all also drives the TLS handshake, client
    // role)
    std::vector<uint8_t> hello;
    put_u32(hello, static_cast<uint32_t>(my_id));
    put_u32(hello, static_cast<uint32_t>(listen_port));
    bool sent;
    if (tls) {
      const TlsApi &api = tls_api();
      c->ssl = api.SSL_new(ssl_ctx);
      if (!c->ssl) { close(fd); return nullptr; }
      api.SSL_set_fd(c->ssl, fd);
      api.SSL_set_connect_state(c->ssl);
      std::lock_guard<std::mutex> ls(c->smu);
      sent = ssl_write_all(*c, hello.data(), hello.size(), 10'000);
    } else {
      sent = write_all(fd, hello.data(), hello.size());
    }
    if (!sent) {
      close(fd);
      c->fd = -1;
      return nullptr;
    }
    {
      std::lock_guard<std::mutex> l(mu);
      conns.push_back(c);
      by_peer[peer] = c;
      // a successful dial proves the peer is back: clear its send pause
      // (covers both the send path and the reconnect loop's probes)
      send_fails.erase(peer);
      send_pause.erase(peer);
    }
    if (wake_pipe[1] >= 0) { uint8_t b = 0; (void)!write(wake_pipe[1], &b, 1); }
    return c;
  }

  // Sever the live connection to `peer` (if any) without touching its
  // address entry: shutdown(2) from this thread, the event loop reaps the
  // fd on its next read error (the same no-close-outside-the-loop
  // discipline as the send failure path — closing here could hand the fd
  // number to a concurrent accept while the loop still polls it).
  void drop_conn(int peer) {
    std::shared_ptr<Conn> c;
    {
      std::lock_guard<std::mutex> l(mu);
      auto it = by_peer.find(peer);
      if (it == by_peer.end() || !it->second) return;
      c = it->second;
      by_peer.erase(it);
    }
    std::lock_guard<std::mutex> lw(c->wmu);
    if (c->fd >= 0) shutdown(c->fd, SHUT_RDWR);
    if (wake_pipe[1] >= 0) { uint8_t b = 0; (void)!write(wake_pipe[1], &b, 1); }
  }

  // Consecutive-failure bookkeeping for the send pause; mu must be held.
  void note_send_fail_locked(int peer) {
    int f = ++send_fails[peer];
    if (f >= pause_after && !send_pause.count(peer)) {
      send_pause[peer] = std::chrono::steady_clock::now() +
                         std::chrono::milliseconds(pause_ms);
      send_pauses.fetch_add(1, std::memory_order_relaxed);
    }
  }

  bool send_msg(int peer, uint64_t tag, const uint8_t *payload, int len) {
    if (udp) return udp_send(peer, tag, payload, len);
    // mirror the receiver's frame cap: an oversized frame would report
    // send success while the peer severs the link as a protocol violation
    if (len < 0 || static_cast<uint32_t>(len) > kMaxFrame - 8) return false;
    {
      // paused peer: drop-with-count instead of dialing (bounded-memory
      // discipline — the reconnect loop keeps probing in the background)
      std::lock_guard<std::mutex> lp(mu);
      auto it = send_pause.find(peer);
      if (it != send_pause.end()) {
        if (std::chrono::steady_clock::now() < it->second) {
          send_pause_drops.fetch_add(1, std::memory_order_relaxed);
          return false;
        }
        send_pause.erase(it);
        // probe posture past expiry: ONE failed dial re-engages the
        // pause (resetting to zero would put the flush back on the
        // dial treadmill for a full pause_after streak per window); a
        // success still clears the count entirely
        send_fails[peer] = pause_after - 1;
      }
    }
    // the send path is the round hot path (one rt_pump_flush per wave):
    // bound the dial far below connect_to's reconnect-loop default so a
    // black-holed peer (SYNs dropped, no RST) cannot stall a flush for
    // seconds — the failed dial feeds the pause, so the steady-state
    // cost of a dead peer is one bounded dial per pause window
    auto c = connect_to(peer, /*timeout_ms=*/250);
    if (!c) {
      std::lock_guard<std::mutex> lp(mu);
      note_send_fail_locked(peer);
      return false;
    }
    std::vector<uint8_t> frame;
    frame.reserve(12 + len);
    put_u32(frame, static_cast<uint32_t>(8 + len));
    put_u32(frame, static_cast<uint32_t>(tag >> 32));
    put_u32(frame, static_cast<uint32_t>(tag & 0xFFFFFFFFu));
    frame.insert(frame.end(), payload, payload + len);
    std::lock_guard<std::mutex> l(c->wmu);
    if (c->fd < 0) {
      std::lock_guard<std::mutex> l2(mu);
      note_send_fail_locked(peer);
      return false;
    }
    bool wrote;
    if (tls) {
      std::lock_guard<std::mutex> ls(c->smu);
      wrote = c->fd >= 0 &&
              ssl_write_all(*c, frame.data(), frame.size(), 10'000);
    } else {
      wrote = write_all(c->fd, frame.data(), frame.size());
    }
    if (!wrote) {
      // connection died mid-write: drop it, caller may retry (reconnect
      // semantics of TcpRuntime.scala:162-211).  TLS write DEADLINES leave
      // a live socket behind (the peer is slow, not gone) with a
      // half-written frame — no read error will ever reap it.  shutdown()
      // (NOT close) from this sender thread: the event loop may hold the
      // fd in an in-flight poll snapshot, and closing here would let the
      // fd number be reused by a concurrent connect while the loop still
      // reads the old SSL object through it.  shutdown makes the loop's
      // next SSL_read fail, and the REAPER (loop thread) does the close.
      if (tls && c->fd >= 0) shutdown(c->fd, SHUT_RDWR);
      std::lock_guard<std::mutex> l2(mu);
      auto it = by_peer.find(peer);
      if (it != by_peer.end() && it->second == c) by_peer.erase(it);
      note_send_fail_locked(peer);
      return false;
    }
    {
      std::lock_guard<std::mutex> l2(mu);
      send_fails.erase(peer);
    }
    return true;
  }
};

}  // namespace

extern "C" {

static void *node_create(int id, int listen_port, bool udp,
                         void *tls_ctx = nullptr) {
  auto *n = new Node();
  n->id = id;
  n->udp = udp;
  n->tls = tls_ctx != nullptr;   // before the loop thread starts: an early
  n->ssl_ctx = tls_ctx;          // accept must already take the TLS path
  n->listen_fd = socket(AF_INET, udp ? SOCK_DGRAM : SOCK_STREAM, 0);
  if (n->listen_fd < 0) { delete n; return nullptr; }
  int one = 1;
  setsockopt(n->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_ANY);
  sa.sin_port = htons(static_cast<uint16_t>(listen_port));
  if (bind(n->listen_fd, reinterpret_cast<sockaddr *>(&sa), sizeof(sa)) != 0 ||
      (!udp && listen(n->listen_fd, 64) != 0) || pipe(n->wake_pipe) != 0) {
    close(n->listen_fd);
    delete n;
    return nullptr;
  }
  // the wake pipe is drained with a read loop: it MUST be non-blocking or
  // the drain blocks the event loop once empty
  fcntl(n->wake_pipe[0], F_SETFL, O_NONBLOCK);
  fcntl(n->wake_pipe[1], F_SETFL, O_NONBLOCK);
  {
    // resolve the bound port once (listen_port==0 binds ephemeral); it is
    // advertised in every outbound hello as this node's wire identity
    sockaddr_in bound{};
    socklen_t blen = sizeof(bound);
    if (getsockname(n->listen_fd, reinterpret_cast<sockaddr *>(&bound),
                    &blen) == 0)
      n->listen_port = ntohs(bound.sin_port);
  }
  n->running = true;
  n->loop = std::thread([n] { n->loop_body(); });
  return n;
}

void *rt_node_create(int id, int listen_port) {
  return node_create(id, listen_port, false);
}

// The reference's default perf transport shape (UdpRuntime.scala:19-96):
// datagram socket, drop-tolerant, one packet per message.
void *rt_node_create_udp(int id, int listen_port) {
  return node_create(id, listen_port, true);
}

// TCP_SSL (TcpRuntime.scala:143-158): the framed protocol inside TLS.
// cert/key are PEM paths (the Python layer generates a self-signed pair
// when the caller supplies none).  Returns nullptr when libssl is
// unavailable or the certificate does not load.
void *rt_node_create_tls(int id, int listen_port, const char *cert_pem,
                         const char *key_pem) {
  const TlsApi &api = tls_api();
  if (!api.ok) return nullptr;
  void *ctx = api.SSL_CTX_new(api.TLS_method());
  if (!ctx) return nullptr;
  if (api.SSL_CTX_use_certificate_chain_file(ctx, cert_pem) != 1 ||
      api.SSL_CTX_use_PrivateKey_file(ctx, key_pem, kSslFiletypePem) != 1) {
    api.SSL_CTX_free(ctx);
    return nullptr;
  }
  // on failure node_create already deleted the Node, whose destructor
  // freed ctx — freeing it here again would be a double free
  return node_create(id, listen_port, false, ctx);
}

int rt_node_port(void *node) {
  auto *n = static_cast<Node *>(node);
  sockaddr_in sa{};
  socklen_t len = sizeof(sa);
  if (getsockname(n->listen_fd, reinterpret_cast<sockaddr *>(&sa), &len) != 0)
    return -1;
  return ntohs(sa.sin_port);
}

void rt_node_add_peer(void *node, int peer_id, const char *host, int port) {
  auto *n = static_cast<Node *>(node);
  sockaddr_in sa{};
  bool have_sa = false;
  if (n->udp) {
    // resolve ONCE here, not per datagram (the send path is hot and must
    // not do synchronous DNS); resolution happens outside the node lock
    sa.sin_family = AF_INET;
    sa.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host, &sa.sin_addr) == 1) {
      have_sa = true;
    } else {
      addrinfo hints{}, *res = nullptr;
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_DGRAM;
      if (getaddrinfo(host, nullptr, &hints, &res) == 0) {
        sa.sin_addr = reinterpret_cast<sockaddr_in *>(res->ai_addr)->sin_addr;
        freeaddrinfo(res);
        have_sa = true;
      }
    }
  }
  std::lock_guard<std::mutex> l(n->mu);
  n->peer_addr[peer_id] = {host, port};
  if (have_sa) n->peer_sa[peer_id] = sa;
}

// Forget a peer: sever its live connection and drop its address entry.
// Sends to it fail from now on; the listen socket still ACCEPTS from it
// (the unauthenticated-socket trust model is unchanged — the epoch stamp
// in the Tag is what rejects a removed replica's traffic semantically).
void rt_node_remove_peer(void *node, int peer_id) {
  auto *n = static_cast<Node *>(node);
  if (!n->udp) n->drop_conn(peer_id);
  std::lock_guard<std::mutex> l(n->mu);
  n->peer_addr.erase(peer_id);
  n->peer_sa.erase(peer_id);
}

// Rename this node (Replicas.scala:136-142 renameReplica, the wire half):
// future outbound handshakes and UDP sender headers carry the new id.
// Peers holding connections handshaked under the OLD id keep attributing
// in-flight frames to it until those channels are dropped — which is why
// a view change that renames ids severs and re-dials the affected
// channels (runtime/transport.py rewire) and stamps traffic with the view
// epoch so stale attribution is detected, not trusted.
void rt_node_set_id(void *node, int new_id) {
  auto *n = static_cast<Node *>(node);
  std::lock_guard<std::mutex> l(n->mu);
  n->id = new_id;
}

// 1 when a live channel to `peer` exists (UDP: when its address is
// registered — datagram sockets have no connection state), else 0.
int rt_node_connected(void *node, int peer_id) {
  auto *n = static_cast<Node *>(node);
  std::lock_guard<std::mutex> l(n->mu);
  if (n->udp) return n->peer_sa.count(peer_id) ? 1 : 0;
  auto it = n->by_peer.find(peer_id);
  return (it != n->by_peer.end() && it->second && it->second->fd >= 0)
             ? 1 : 0;
}

// Dial `peer` now (bounded by timeout_ms) without sending anything:
// the reconnect-loop primitive (runtime/transport.py drives period +
// backoff).  0 = a channel exists (already or freshly connected),
// -1 = could not connect.  UDP nodes are always "connected".
int rt_node_connect(void *node, int peer_id, int timeout_ms) {
  auto *n = static_cast<Node *>(node);
  if (n->udp) {
    std::lock_guard<std::mutex> l(n->mu);
    return n->peer_sa.count(peer_id) ? 0 : -1;
  }
  return n->connect_to(peer_id, timeout_ms) ? 0 : -1;
}

int rt_node_send(void *node, int peer_id, uint64_t tag,
                 const uint8_t *payload, int len) {
  auto *n = static_cast<Node *>(node);
  return n->send_msg(peer_id, tag, payload, len) ? 0 : -1;
}

// Returns payload length (>= 0) with *from/*tag filled, -1 on timeout,
// -2 if buf is too small (message stays queued; call again bigger),
// -3 once the node was stopped (rt_node_stop) and the inbox is empty.
int rt_node_recv(void *node, int *from, uint64_t *tag, uint8_t *buf,
                 int buflen, int timeout_ms) {
  auto *n = static_cast<Node *>(node);
  std::unique_lock<std::mutex> l(n->inbox_mu);
  n->inbox_cv.wait_for(l, std::chrono::milliseconds(timeout_ms),
                       [n] { return !n->inbox.empty() || n->recv_stopped; });
  if (n->inbox.empty()) return n->recv_stopped ? -3 : -1;
  Msg &m = n->inbox.front();
  if (static_cast<int>(m.payload.size()) > buflen) return -2;
  *from = m.from;
  *tag = m.tag;
  std::memcpy(buf, m.payload.data(), m.payload.size());
  int len = static_cast<int>(m.payload.size());
  n->note_popped_locked(m.payload.size());
  n->inbox.pop_front();
  return len;
}

// Batched drain: pack EVERY queued message (up to buflen) into buf as
// consecutive records
//
//   i32 from | u64 tag | u32 len | payload[len]        (native endianness)
//
// waiting up to timeout_ms for the first one.  One ctypes call + one
// Python-side copy replaces a copy-out call per message — the hot-path
// receive of runtime/transport.py (messages stay queued when they don't
// fit, so a partial drain just means another call).  *nbytes gets the
// total bytes packed.  Returns the number of messages packed, 0 on
// timeout, -2 if the FIRST message cannot fit buflen (call again with a
// bigger buf), -3 once the node was stopped and the inbox is empty.
int rt_node_recv_many(void *node, uint8_t *buf, int buflen, int timeout_ms,
                      int *nbytes) {
  auto *n = static_cast<Node *>(node);
  *nbytes = 0;
  std::unique_lock<std::mutex> l(n->inbox_mu);
  n->inbox_cv.wait_for(l, std::chrono::milliseconds(timeout_ms),
                       [n] { return !n->inbox.empty() || n->recv_stopped; });
  if (n->inbox.empty()) return n->recv_stopped ? -3 : 0;
  constexpr size_t kHdr = sizeof(int32_t) + sizeof(uint64_t) +
                          sizeof(uint32_t);
  size_t off = 0;
  int count = 0;
  while (!n->inbox.empty()) {
    Msg &m = n->inbox.front();
    size_t need = kHdr + m.payload.size();
    if (off + need > static_cast<size_t>(buflen)) {
      if (count == 0) return -2;  // first message alone overflows the buf
      break;                      // the rest stays queued for the next call
    }
    int32_t from = m.from;
    uint64_t tag = m.tag;
    uint32_t len = static_cast<uint32_t>(m.payload.size());
    std::memcpy(buf + off, &from, sizeof(from));
    std::memcpy(buf + off + 4, &tag, sizeof(tag));
    std::memcpy(buf + off + 12, &len, sizeof(len));
    if (len) std::memcpy(buf + off + kHdr, m.payload.data(), len);
    off += need;
    ++count;
    n->note_popped_locked(m.payload.size());
    n->inbox.pop_front();
  }
  *nbytes = static_cast<int>(off);
  return count;
}

// Stop the node (event loop joined, sockets closed, blocked rt_node_recv
// calls return -3) WITHOUT freeing it: lets receiver threads unwind before
// rt_node_destroy.  Idempotent.
void rt_node_stop(void *node) {
  static_cast<Node *>(node)->stop();
}

uint64_t rt_node_dropped(void *node) {
  auto *n = static_cast<Node *>(node);
  std::lock_guard<std::mutex> l(n->inbox_mu);
  return n->dropped;
}

// 1 while the inbox sits above its byte high watermark (cleared once a
// drain reaches the low watermark) — the level form of the kReadyBackpr
// reason bit, for pump-less callers and harness assertions.
int rt_node_backpressure(void *node) {
  return static_cast<Node *>(node)->backpressure.load() ? 1 : 0;
}

unsigned long long rt_node_inbox_bytes(void *node) {
  auto *n = static_cast<Node *>(node);
  std::lock_guard<std::mutex> l(n->inbox_mu);
  return n->inbox_bytes;
}

// Configure the bounded-inbox caps and backpressure watermarks; any
// argument <= 0 keeps the current value.  Requires lo <= hi <= max_bytes
// (rejected with -1, the caps must stay a coherent ladder).
int rt_node_set_inbox_limits(void *node, long long max_msgs,
                             long long max_bytes, long long hi,
                             long long lo) {
  auto *n = static_cast<Node *>(node);
  std::lock_guard<std::mutex> l(n->inbox_mu);
  size_t mm = max_msgs > 0 ? static_cast<size_t>(max_msgs) : n->max_inbox;
  size_t mb = max_bytes > 0 ? static_cast<size_t>(max_bytes)
                            : n->max_inbox_bytes;
  size_t h = hi > 0 ? static_cast<size_t>(hi) : n->bp_high;
  size_t lw = lo > 0 ? static_cast<size_t>(lo) : n->bp_low;
  if (lw > h || h > mb) return -1;
  n->max_inbox = mm;
  n->max_inbox_bytes = mb;
  n->bp_high = h;
  n->bp_low = lw;
  // re-evaluate the level against the new ladder so a tightened
  // watermark takes effect without waiting for the next enqueue
  if (!n->backpressure.load() && n->inbox_bytes >= n->bp_high) {
    n->backpressure.store(true);
    n->bp_edges.fetch_add(1);
  } else if (n->backpressure.load() && n->inbox_bytes <= n->bp_low) {
    n->backpressure.store(false);
  }
  return 0;
}

// Per-peer send-pause counters: out[0] = pauses engaged, out[1] = frames
// dropped while paused.  The Python drain path diffs these into the
// shared wire.peer_pauses / wire.backpressure_drops counters so pump-path
// drops are accounted in the same vocabulary as Python-surface drops.
int rt_node_send_pause_stats(void *node, unsigned long long *out) {
  auto *n = static_cast<Node *>(node);
  out[0] = n->send_pauses.load(std::memory_order_relaxed);
  out[1] = n->send_pause_drops.load(std::memory_order_relaxed);
  return 0;
}

// Configure the native send pause (any argument <= 0 keeps the value).
int rt_node_set_send_pause(void *node, int after, int ms) {
  auto *n = static_cast<Node *>(node);
  std::lock_guard<std::mutex> l(n->mu);
  if (after > 0) n->pause_after = after;
  if (ms > 0) n->pause_ms = ms;
  return 0;
}

void rt_node_destroy(void *node) {
  auto *n = static_cast<Node *>(node);
  n->stop();
  delete n;
}

// ---------------------------------------------------------------------------
// round pump API (see the file-top comment).  All pointers passed here are
// Python-owned numpy buffers that MUST outlive the pump (the Python
// wrapper, runtime/transport.py RoundPump, pins them).
// ---------------------------------------------------------------------------

// Enable (or reconfigure) the pump: L lanes, n processes, k round classes,
// nbz byzantine tolerance for the catch-up rule.  max_rnd = int64[L*n],
// next_round = int64[L], stats = u64[16].  Reconfiguring drops all lane
// state; callers do it only between runs (no concurrent waiters).
int rt_pump_enable(void *node, int L, int n, int k, int nbz,
                   long long *max_rnd, long long *next_round,
                   unsigned long long *stats) {
  auto *nd = static_cast<Node *>(node);
  if (L <= 0 || n <= 0 || k <= 0 || nbz < 0 || nbz >= n) return -1;
  nd->pump_on.store(false, std::memory_order_release);
  if (!nd->pump) nd->pump = new Pump();
  nd->pump->configure(L, n, k, nbz, max_rnd, next_round, stats);
  {
    // frames that arrived BEFORE the pump existed are sitting in the
    // inbox with no misc flag: seed it, or the first armed round would
    // burn its whole deadline blind to them (observed: exactly one
    // burned deadline per replica in process mode, where peers start
    // seconds apart and the early ones' traffic predates the enable)
    std::lock_guard<std::mutex> l(nd->inbox_mu);
    if (!nd->inbox.empty()) nd->pump->misc.store(true);
  }
  nd->pump_on.store(true, std::memory_order_release);
  return 0;
}

// Disable the fast path: frames flow to the inbox again.  Lane state and
// registered buffers are retired (a later enable reconfigures).  The
// mutex acquisition after the clear FENCES in-flight deliveries: the
// event loop re-checks pump_on under the same mutex, so once this
// returns no native write can touch the (about to be freed) Python
// mailbox buffers.
void rt_pump_disable(void *node) {
  auto *nd = static_cast<Node *>(node);
  nd->pump_on.store(false, std::memory_order_release);
  if (nd->pump) {
    { std::lock_guard<std::mutex> l(nd->pump->mu); }
    nd->pump->cv.notify_all();
  }
}

// Register one (lane, class) slot: the payload TEMPLATE (tlen bytes, the
// codec encoding of the class's exemplar payload), its holes (packed
// u32 off | u32 len | u32 leaf, ascending), the leaf destinations (packed
// u64 base_ptr | u32 nbytes), and the lane's shared mask/count.  Returns
// 0, or -1 on a malformed registration (overlapping/oversized holes,
// hole/leaf size mismatch).
int rt_pump_set_class(void *node, int lane, int cls, const uint8_t *tmpl,
                      int tlen, const uint8_t *holes, int nholes,
                      const uint8_t *leaves, int nleaves, uint8_t *mask,
                      long long *count) {
  auto *nd = static_cast<Node *>(node);
  Pump *P = nd->pump;
  if (!P) return -1;
  std::lock_guard<std::mutex> l(P->mu);
  if (lane < 0 || lane >= P->L || cls < 0 || cls >= P->k || tlen < 0)
    return -1;
  PumpSlot s;
  s.tmpl.assign(tmpl, tmpl + tlen);
  s.leaves.resize(nleaves);
  for (int i = 0; i < nleaves; ++i) {
    uint64_t base;
    uint32_t nb;
    std::memcpy(&base, leaves + i * 12, 8);
    std::memcpy(&nb, leaves + i * 12 + 8, 4);
    s.leaves[i].base = reinterpret_cast<uint8_t *>(base);
    s.leaves[i].nbytes = nb;
  }
  uint32_t prev_end = 0;
  s.holes.resize(nholes);
  for (int i = 0; i < nholes; ++i) {
    PumpHole h;
    std::memcpy(&h.off, holes + i * 12, 4);
    std::memcpy(&h.len, holes + i * 12 + 4, 4);
    std::memcpy(&h.leaf, holes + i * 12 + 8, 4);
    if (h.off < prev_end ||
        static_cast<uint64_t>(h.off) + h.len > static_cast<uint64_t>(tlen) ||
        h.leaf >= static_cast<uint32_t>(nleaves) ||
        h.len != s.leaves[h.leaf].nbytes)
      return -1;
    prev_end = h.off + h.len;
    s.holes[i] = h;
  }
  s.mask = mask;
  s.count = count;
  P->lanes[lane].slots[cls] = std::move(s);
  return 0;
}

// Map instance id -> lane.  Python resets the shared max_rnd/next_round
// rows BEFORE opening; pending/ready state is cleared here.
int rt_pump_open_lane(void *node, int lane, int iid) {
  auto *nd = static_cast<Node *>(node);
  Pump *P = nd->pump;
  if (!P) return -1;
  std::lock_guard<std::mutex> l(P->mu);
  if (lane < 0 || lane >= P->L || iid < 0 || iid >= (1 << 16)) return -1;
  PumpLane &ln = P->lanes[lane];
  if (ln.iid >= 0 && P->iid2lane[ln.iid] == lane) P->iid2lane[ln.iid] = -1;
  ln.iid = iid;
  ln.open_ = true;
  ln.armed = false;
  ln.round_ = 0;
  ln.ready = 0;
  ln.has_deadline = false;
  ln.pending.clear();
  ln.pending_frames = 0;
  P->iid2lane[iid] = lane;
  return 0;
}

// Retire the lane: its instance's frames flow to the inbox again (the
// TooLate decision-reply path in Python).
void rt_pump_close_lane(void *node, int lane) {
  auto *nd = static_cast<Node *>(node);
  Pump *P = nd->pump;
  if (!P) return;
  std::lock_guard<std::mutex> l(P->mu);
  if (lane < 0 || lane >= P->L) return;
  PumpLane &ln = P->lanes[lane];
  if (ln.iid >= 0 && P->iid2lane[ln.iid] == lane) P->iid2lane[ln.iid] = -1;
  ln.iid = -1;
  ln.open_ = false;
  ln.armed = false;
  ln.ready = 0;
  ln.has_deadline = false;
  ln.pending.clear();
  ln.pending_frames = 0;
}

namespace {

// caller holds P->mu.  The arm transition: adopt the round, apply the
// natively-buffered pending frames (full template check — a mismatch goes
// to the inbox for the bilingual Python path), then evaluate readiness.
void pump_arm_locked(Node *nd, Pump *P, int lane, long long round, int cls,
                     long long threshold, uint32_t flags, int deadline_ms,
                     int extend_ms, uint8_t auto_disarm) {
  PumpLane &ln = P->lanes[lane];
  ln.round_ = round;
  ln.cls = cls;
  ln.threshold = threshold;
  ln.flags = flags;
  ln.auto_disarm = auto_disarm;
  ln.extend_ms = extend_ms;
  ln.ready = 0;
  ln.armed = true;
  if (deadline_ms > 0) {
    ln.deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(deadline_ms);
    ln.has_deadline = true;
  } else {
    ln.has_deadline = false;
  }
  // garbage-collect pending rounds the lane has moved past
  while (!ln.pending.empty() && ln.pending.begin()->first < round) {
    ln.pending_frames -= ln.pending.begin()->second.size();
    ln.pending.erase(ln.pending.begin());
  }
  auto it = ln.pending.find(round);
  if (it != ln.pending.end()) {
    for (auto &kv : it->second) {
      ++P->stats[kStApplied];
      int rc = P->slot_insert(ln.slots[cls], kv.first, kv.second.data(),
                              kv.second.size());
      if (rc == 1) {
        ++P->stats[kStFast];
      } else if (rc == 0) {
        ++P->stats[kStDup];
      } else {
        // legacy-pickle / structurally-alien payload: Python decodes it
        ++P->stats[kStFallback];
        Msg m;
        m.from = kv.first;
        m.tag = (static_cast<uint64_t>(round) << 32) |
                (static_cast<uint64_t>(ln.iid & 0xFFFF) << 16);
        m.payload = std::move(kv.second);
        nd->enqueue(std::move(m));
      }
    }
    ln.pending_frames -= it->second.size();
    ln.pending.erase(it);
  }
  uint8_t newly = 0;
  if (ln.threshold > 0 && ln.slots[cls].count &&
      *ln.slots[cls].count >= ln.threshold)
    newly |= kReadyThresh;
  if (!(flags & kPumpStrict) && P->next_round[lane] > round + 1)
    newly |= kReadySkew;
  if (newly) {
    ln.ready |= newly;
    P->cv.notify_all();
  }
}

}  // namespace

// Arm one lane for (round, cls).  Python has already reset the mailbox
// row, inserted self-delivery/prefill, and set count accordingly.
int rt_pump_arm(void *node, int lane, long long round, int cls,
                long long threshold, uint32_t flags, int deadline_ms,
                int extend_ms, uint8_t auto_disarm) {
  auto *nd = static_cast<Node *>(node);
  Pump *P = nd->pump;
  if (!P) return -1;
  std::lock_guard<std::mutex> l(P->mu);
  if (lane < 0 || lane >= P->L || cls < 0 || cls >= P->k) return -1;
  pump_arm_locked(nd, P, lane, round, cls, threshold, flags, deadline_ms,
                  extend_ms, auto_disarm);
  return 0;
}

// Batched arm: one ctypes crossing per send WAVE (the lane driver arms up
// to L lanes per wave).  specs = packed records of
//   i32 lane | i32 round | i32 cls | i64 threshold | u32 flags |
//   i32 deadline_ms | i32 extend_ms | u8 auto_disarm        (33 bytes)
int rt_pump_arm_many(void *node, const uint8_t *specs, int count) {
  auto *nd = static_cast<Node *>(node);
  Pump *P = nd->pump;
  if (!P) return -1;
  std::lock_guard<std::mutex> l(P->mu);
  for (int i = 0; i < count; ++i) {
    const uint8_t *p = specs + static_cast<size_t>(i) * 33;
    int32_t lane, round32, cls, dl, ext;
    int64_t thr;
    uint32_t flags;
    uint8_t ad;
    std::memcpy(&lane, p, 4);
    std::memcpy(&round32, p + 4, 4);
    std::memcpy(&cls, p + 8, 4);
    std::memcpy(&thr, p + 12, 8);
    std::memcpy(&flags, p + 20, 4);
    std::memcpy(&dl, p + 24, 4);
    std::memcpy(&ext, p + 28, 4);
    ad = p[32];
    if (lane < 0 || lane >= P->L || cls < 0 || cls >= P->k) return -1;
    pump_arm_locked(nd, P, lane, round32, cls, thr, flags, dl, ext, ad);
  }
  return 0;
}

// Disarm: after this returns, the event loop buffers the lane's frames as
// pending instead of writing its mailbox — Python may read/reset freely.
void rt_pump_disarm(void *node, int lane) {
  auto *nd = static_cast<Node *>(node);
  Pump *P = nd->pump;
  if (!P) return;
  std::lock_guard<std::mutex> l(P->mu);
  if (lane < 0 || lane >= P->L) return;
  P->lanes[lane].armed = false;
  P->lanes[lane].ready = 0;
  P->lanes[lane].has_deadline = false;
}

// THE blocking wait: returns when >= 1 lane is ready (reasons_out[lane]
// gets the reason bits, which are consumed; auto_disarm reasons disarm
// atomically), when misc inbox traffic arrived (*misc_out = 1, flag
// consumed), on timeout (0 with *misc_out = 0), or -3 once the node
// stopped.  Lane deadlines are evaluated HERE against steady_clock — no
// Python-side polling tick exists in pump mode.
int rt_pump_wait(void *node, uint8_t *reasons_out, int timeout_ms,
                 int *misc_out) {
  auto *nd = static_cast<Node *>(node);
  Pump *P = nd->pump;
  *misc_out = 0;
  if (!P) return -1;
  auto t_end = std::chrono::steady_clock::now() +
               std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
  std::unique_lock<std::mutex> l(P->mu);
  ++P->stats[kStWaits];
  for (;;) {
    if (P->stopped.load()) return -3;
    // inbox backpressure edge -> kReadyBackpr on every armed lane: the
    // waiter must drain the inbox NOW, not after a full deadline (the
    // bit is never in auto_disarm, so the round itself keeps running)
    uint64_t bpe = nd->bp_edges.load(std::memory_order_acquire);
    if (bpe != P->bp_seen) {
      P->bp_seen = bpe;
      for (int i = 0; i < P->L; ++i)
        if (P->lanes[i].armed) P->lanes[i].ready |= kReadyBackpr;
    }
    auto now = std::chrono::steady_clock::now();
    bool have_dl = false;
    std::chrono::steady_clock::time_point min_dl{};
    for (int i = 0; i < P->L; ++i) {
      PumpLane &ln = P->lanes[i];
      if (!ln.armed || !ln.has_deadline) continue;
      if (now >= ln.deadline) {
        ln.ready |= kReadyDeadline;
        ln.has_deadline = false;  // report an expiry exactly once
      } else if (!have_dl || ln.deadline < min_dl) {
        min_dl = ln.deadline;
        have_dl = true;
      }
    }
    int nready = 0;
    for (int i = 0; i < P->L; ++i)
      if (P->lanes[i].ready) ++nready;
    bool misc = P->misc.load();
    if (nready > 0 || misc) {
      for (int i = 0; i < P->L; ++i) {
        PumpLane &ln = P->lanes[i];
        reasons_out[i] = ln.ready;
        if (ln.ready) {
          if (ln.ready & ln.auto_disarm) {
            ln.armed = false;
            ln.has_deadline = false;
          }
          ln.ready = 0;
        }
      }
      if (misc) {
        P->misc.store(false);
        *misc_out = 1;
        ++P->stats[kStWakesMisc];
      }
      if (nready) ++P->stats[kStWakesReady];
      return nready;
    }
    if (now >= t_end) {
      std::memset(reasons_out, 0, P->L);
      return 0;
    }
    auto wake_t = t_end;
    if (have_dl && min_dl < wake_t) wake_t = min_dl;
    P->cv.wait_until(l, wake_t);
  }
}

// Single-lane wait (per-instance runners multiplexed over one transport):
// returns the lane's reason bits (consumed; auto_disarm honored), 0 on
// timeout, -3 once stopped.  Does NOT consume the misc flag — a router
// thread owns the inbox in that deployment and pokes lanes explicitly.
int rt_pump_wait_lane(void *node, int lane, int timeout_ms) {
  auto *nd = static_cast<Node *>(node);
  Pump *P = nd->pump;
  if (!P || lane < 0 || lane >= P->L) return -1;
  auto t_end = std::chrono::steady_clock::now() +
               std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
  std::unique_lock<std::mutex> l(P->mu);
  PumpLane &ln = P->lanes[lane];
  for (;;) {
    if (P->stopped.load()) return -3;
    // backpressure edge: the FIRST lane waiter to observe it gets the
    // bit (draining the shared inbox is a global act — one drainer
    // suffices; in the mux deployment the router thread is the primary
    // drainer and this bit is advisory)
    uint64_t bpe = nd->bp_edges.load(std::memory_order_acquire);
    if (bpe != P->bp_seen) {
      P->bp_seen = bpe;
      if (ln.armed) ln.ready |= kReadyBackpr;
    }
    auto now = std::chrono::steady_clock::now();
    if (ln.armed && ln.has_deadline && now >= ln.deadline) {
      ln.ready |= kReadyDeadline;
      ln.has_deadline = false;
    }
    if (ln.ready) {
      int r = ln.ready;
      if (ln.ready & ln.auto_disarm) {
        ln.armed = false;
        ln.has_deadline = false;
      }
      ln.ready = 0;
      return r;
    }
    if (now >= t_end) return 0;
    auto wake_t = t_end;
    if (ln.armed && ln.has_deadline && ln.deadline < wake_t)
      wake_t = ln.deadline;
    P->cv.wait_until(l, wake_t);
  }
}

// Nudge one lane's waiter (kReadyPoke): the mux router thread queued
// out-of-band traffic for that lane's runner.
void rt_pump_poke(void *node, int lane) {
  auto *nd = static_cast<Node *>(node);
  Pump *P = nd->pump;
  if (!P) return;
  std::lock_guard<std::mutex> l(P->mu);
  if (lane < 0 || lane >= P->L) return;
  P->lanes[lane].ready |= kReadyPoke;
  P->cv.notify_all();
}

// Feed one frame from Python (stash replay at admission, inbox-fallback
// re-routing): the same state machine as the wire path, but a template
// miss at the armed current round returns -2 instead of re-queuing to the
// inbox (the caller decodes and uses rt_pump_insert).  Returns 1 consumed,
// 0 not pump-routable, -2 template miss.
int rt_pump_feed(void *node, int from, uint64_t tag, const uint8_t *buf,
                 int len) {
  auto *nd = static_cast<Node *>(node);
  Pump *P = nd->pump;
  if (!P) return 0;
  std::lock_guard<std::mutex> l(P->mu);
  return P->route_locked(from, tag, buf, len);
}

// Canonical insert under the pump lock (the Python fallback path after
// decoding a legacy/pickle payload and re-encoding it in slot dtypes):
// 1 = grew, 0 = duplicate overwrite, -1 = template mismatch (structurally
// alien payload — the caller marks the sender malformed).
int rt_pump_insert(void *node, int lane, int sender, const uint8_t *buf,
                   int len) {
  auto *nd = static_cast<Node *>(node);
  Pump *P = nd->pump;
  if (!P) return -1;
  std::lock_guard<std::mutex> l(P->mu);
  if (lane < 0 || lane >= P->L || sender < 0 || sender >= P->n) return -1;
  PumpLane &ln = P->lanes[lane];
  int rc = P->slot_insert(ln.slots[ln.cls], sender, buf,
                          static_cast<size_t>(len));
  if (rc < 0) return -1;
  uint8_t newly = 0;
  if (rc == 1 && ln.armed) {
    ++P->stats[kStFast];
    if (ln.threshold > 0 && *ln.slots[ln.cls].count >= ln.threshold)
      newly |= kReadyThresh;
  } else if (rc == 0) {
    ++P->stats[kStDup];  // host.recvs parity: banked like wire dups
  }
  if (ln.armed && (ln.flags & kPumpGrowth)) newly |= kReadyGrowth;
  if (newly) {
    ln.ready |= newly;
    P->cv.notify_all();
  }
  return rc;
}

// Structural-garbage semantics of the Python mailboxes: clear the
// sender's heard bit, zero its slots (a half-written slot must not leak).
void rt_pump_mark_malformed(void *node, int lane, int sender) {
  auto *nd = static_cast<Node *>(node);
  Pump *P = nd->pump;
  if (!P) return;
  std::lock_guard<std::mutex> l(P->mu);
  if (lane < 0 || lane >= P->L || sender < 0 || sender >= P->n) return;
  PumpLane &ln = P->lanes[lane];
  PumpSlot &s = ln.slots[ln.cls];
  if (s.mask && s.mask[sender]) {
    s.mask[sender] = 0;
    --*s.count;
  }
  for (const auto &lf : s.leaves)
    std::memset(lf.base + static_cast<size_t>(sender) * lf.nbytes, 0,
                lf.nbytes);
}

// Ship one send WAVE in a single ctypes crossing: entries reference the
// encode-once scratch (base) as packed records
//   i32 dest | u64 tag | u32 off | u32 len                   (20 bytes)
// and coalesce per destination into FLAG_BATCH containers (byte-identical
// framing to runtime/transport.py send_buffered/flush: one entry ships
// PLAIN, containers carry the frame count in the tag's round field,
// batch_cap bounds a container).  stats_out u64[5] gets
// {frames, payload_bytes, batches, batch_frames, batch_bytes} for the
// Python-side wire.* counters.  Returns logical frames sent.
int rt_pump_flush(void *node, const uint8_t *base, const uint8_t *entries,
                  int count, int batch_cap,
                  unsigned long long *stats_out) {
  auto *nd = static_cast<Node *>(node);
  for (int i = 0; i < 5; ++i) stats_out[i] = 0;
  // dest -> accumulated `u64 tag | u32 len | payload` entries + count
  std::map<int, std::pair<std::vector<uint8_t>, int>> out;
  auto flush_one = [&](int dest, std::pair<std::vector<uint8_t>, int> &e) {
    std::vector<uint8_t> &buf = e.first;
    int cnt = e.second;
    if (cnt <= 0) return;
    if (cnt == 1) {
      uint64_t subtag;
      uint32_t ln;
      std::memcpy(&subtag, buf.data(), 8);
      std::memcpy(&ln, buf.data() + 8, 4);
      if (nd->send_msg(dest, subtag, buf.data() + 12, ln)) {
        stats_out[0] += 1;
        stats_out[1] += ln;
      }
    } else {
      uint64_t tag = (static_cast<uint64_t>(cnt) << 32) |
                     static_cast<uint64_t>(kFlagBatch);
      if (nd->send_msg(dest, tag, buf.data(),
                       static_cast<int>(buf.size()))) {
        stats_out[0] += cnt;
        stats_out[1] += buf.size() - 12ull * cnt;
        stats_out[2] += 1;
        stats_out[3] += cnt;
        stats_out[4] += buf.size();
      }
    }
    buf.clear();
    e.second = 0;
  };
  for (int i = 0; i < count; ++i) {
    const uint8_t *p = entries + static_cast<size_t>(i) * 20;
    int32_t dest;
    uint64_t tag;
    uint32_t off, len;
    std::memcpy(&dest, p, 4);
    std::memcpy(&tag, p + 4, 8);
    std::memcpy(&off, p + 12, 4);
    std::memcpy(&len, p + 16, 4);
    auto &e = out[dest];
    if (e.second > 0 &&
        e.first.size() + 12ull + len > static_cast<uint64_t>(batch_cap))
      flush_one(dest, e);
    size_t at = e.first.size();
    e.first.resize(at + 12 + len);
    std::memcpy(e.first.data() + at, &tag, 8);
    std::memcpy(e.first.data() + at + 8, &len, 4);
    std::memcpy(e.first.data() + at + 12, base + off, len);
    e.second += 1;
  }
  for (auto &kv : out) flush_one(kv.first, kv.second);
  return static_cast<int>(stats_out[0]);
}

}  // extern "C"
