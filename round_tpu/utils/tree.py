"""Small pytree helpers used across the engine."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def tree_where(cond, on_true: Any, on_false: Any) -> Any:
    """Elementwise select between two identically-shaped pytrees.

    ``cond`` broadcasts against each leaf from the left (a ``[n]`` lane mask
    selects whole per-lane subtrees)."""

    def _sel(t, f):
        c = cond
        # right-pad cond's shape so it broadcasts over trailing value dims
        extra = t.ndim - jnp.ndim(c)
        if extra > 0:
            c = jnp.reshape(c, jnp.shape(c) + (1,) * extra)
        return jnp.where(c, t, f)

    return jax.tree_util.tree_map(_sel, on_true, on_false)


def tree_stack(trees):
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def tree_select_lane(tree: Any, idx) -> Any:
    return jax.tree_util.tree_map(lambda x: x[idx], tree)
