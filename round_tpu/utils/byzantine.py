"""Byzantine-tolerant round synchronization (f < n/3).

The reference's PessimisticByzantineSynchronizer
(utils/PessimisticByzantineSynchronizer.scala:16-67) wraps an EventRound[A]
into an EventRound[Option[A]] that ALWAYS broadcasts — None where the inner
round had nothing to send — so that every correct process receives n-f
countable messages per round and can synchronize despite byzantine silence.

In the lockstep engine the two halves of that contract split cleanly:

  - the *message* side is `SynchronizedRound`: every lane broadcasts
    (defined?, payload, dest-row); the inner round's mailbox is rebuilt from
    the defined mask, so padding is visible on the wire exactly like the
    reference's Option[A];
  - the *timing* side (count n-f before progressing, short/long timeouts) is
    an HO-family constraint: run under `scenarios.sync_k_filter(base, n - f)`
    so every receiver hears at least n-f processes per round — the mask
    encoding of `nMsg > nf` (PessimisticByzantineSynchronizer.scala:52-58).

Payload corruption by byzantine senders is a separate adversary transform
(`corrupt_payloads`), mirroring the runtime's tolerance of garbage messages
(InstanceHandler.scala:392-399): correctness must come from the algorithm's
quorums, never from trusting a payload.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from round_tpu.core.rounds import Round, RoundCtx, SendSpec
from round_tpu.ops.mailbox import Mailbox


class SynchronizedRound(Round):
    """Wrap a Round so every lane always broadcasts (None-padded payloads).

    The wire payload is {"defined": bool per receiver, "value": inner
    payload}; since SendSpec carries one payload per sender, the inner
    round's per-destination mask rides along as the ``dest_row`` field and
    each receiver reads its own column — semantically identical to
    Some/None padding per destination."""

    def __init__(self, inner: Round):
        self.inner = inner

    def pre(self, ctx: RoundCtx, state):
        return self.inner.pre(ctx, state)

    def send(self, ctx: RoundCtx, state) -> SendSpec:
        spec = self.inner.send(ctx, state)
        wrapped = {"value": spec.payload, "dest_row": spec.dest_mask}
        return SendSpec(wrapped, jnp.ones((ctx.n,), dtype=bool))

    def update(self, ctx: RoundCtx, state, mbox: Mailbox):
        defined = jnp.take(mbox.values["dest_row"], ctx.id, axis=1)
        inner_mbox = Mailbox(mbox.values["value"], mbox.mask & defined)
        return self.inner.update(ctx, state, inner_mbox)


def synchronize(rounds) -> tuple:
    """Wrap every round of a phase (the wrapRound helper of
    byzantine/test/Consensus.scala:48-54)."""
    return tuple(SynchronizedRound(r) for r in rounds)


def corrupt_payloads(
    payload_fn: Callable[[jax.Array, Any], Any], f: int
) -> Callable:
    """Build an adversary transform: (base_key, round_key, payload_tree, n)
    -> payload_tree with the first-drawn f byzantine lanes' payloads replaced
    by ``payload_fn(round_key, original)``.  Compose with the engine via
    AdversarialRound below."""

    def transform(base_key, round_key, payload, n):
        kb = jax.random.fold_in(base_key, 0xB12)
        byz = jax.random.permutation(kb, n) < f  # same draw as
        # scenarios.byzantine_silence so mask- and payload-adversaries agree:
        # the byz *set* comes from the un-folded scenario key (round-invariant),
        # only the garbage values vary per round via round_key
        def corrupt_leaf(leaf):
            garbage = payload_fn(round_key, leaf)
            mask = byz.reshape((n,) + (1,) * (leaf.ndim - 1))
            return jnp.where(mask, garbage, leaf)

        return jax.tree_util.tree_map(corrupt_leaf, payload)

    return transform


class AdversarialRound(Round):
    """Apply a payload-corruption transform to what byzantine lanes send.

    The transform runs receiver-side on the shared payload tensor (the wire
    is a tensor; corrupting the sender's slot corrupts what everyone hears —
    byzantine *equivocation* additionally needs per-receiver values, modeled
    by the mask families in engine.scenarios.byzantine_silence)."""

    def __init__(self, inner: Round, transform, key: jax.Array):
        self.inner = inner
        self.transform = transform
        self.key = key

    def pre(self, ctx: RoundCtx, state):
        return self.inner.pre(ctx, state)

    def send(self, ctx: RoundCtx, state) -> SendSpec:
        return self.inner.send(ctx, state)

    def update(self, ctx: RoundCtx, state, mbox: Mailbox):
        rk = jax.random.fold_in(self.key, ctx.r)
        values = self.transform(self.key, rk, mbox.values, ctx.n)
        return self.inner.update(ctx, state, Mailbox(values, mbox.mask))
