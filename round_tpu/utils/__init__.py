from round_tpu.utils.tree import tree_where, tree_stack, tree_select_lane

__all__ = ["tree_where", "tree_stack", "tree_select_lane"]
