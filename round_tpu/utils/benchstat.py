"""On-device benchmark summaries shared by bench.py and apps/ladder.py.

Timing discipline (round-1 verdict): on this platform block_until_ready can
return before a computation completes, so timed regions must end at a
device→host transfer — but transferring raw [S, n] outputs costs ~1 s over
the dev tunnel.  These O(1)-size reductions force the full computation while
keeping the transfer negligible.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np


def decided_summary(
    decided: jnp.ndarray,
    dec_round: jnp.ndarray,
    max_rounds: int,
    decision: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, ...]:
    """(decided count, decided-round histogram[, decision checksum]).

    dec_round is -1 for undecided lanes; they are binned at `max_rounds` and
    sliced off the histogram.  The checksum (when a decision array is given)
    makes the summary depend on the decided *values*, not just the flags."""
    cnt = jnp.sum(decided.astype(jnp.int32))
    hist = jnp.bincount(
        jnp.where(decided, dec_round, max_rounds).reshape(-1),
        length=max_rounds + 1,
    )[:max_rounds]
    if decision is None:
        return cnt, hist
    checksum = jnp.sum(jnp.where(decided, decision, 0).astype(jnp.int32))
    return cnt, hist, checksum


def p50_from_hist(hist: np.ndarray) -> float:
    """Median bin of a histogram (-1 when empty)."""
    hist = np.asarray(hist)
    total = int(hist.sum())
    if total == 0:
        return -1.0
    return float(np.searchsorted(np.cumsum(hist), (total + 1) // 2))


def speed_extra(
    best: float,
    rounds: int,
    cnt,
    hist,
    lanes: int,
    p50_key: str = "decided_round_p50",
) -> dict:
    """The shared stats block: throughput + decision health from an
    on-device (count, histogram) summary.  `p50_key` names the histogram's
    unit ("decided_phase_p50" when the engine reports phase indices)."""
    return {
        "rounds_per_sec": round(rounds / best, 3),
        "wall_s_per_run": round(best, 4),
        "rounds_per_run": rounds,
        "frac_lanes_decided": round(float(cnt) / lanes, 4),
        p50_key: p50_from_hist(hist),
    }
