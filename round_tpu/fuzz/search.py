"""The generational loop: the batched engine as a search engine.

One generation = ONE jitted vmapped dispatch evaluating the whole
population as engine scenario lanes (1k-10k candidate schedules per
dispatch; the Python between dispatches is selection bookkeeping over
numpy arrays).  Coverage cells — which (round, link-pattern, phase)
signatures a schedule exercises — are computed inside the same dispatch;
a global coverage map feeds a novelty bonus so the population keeps
probing new failure shapes instead of collapsing onto the first one.
"""

from __future__ import annotations

import dataclasses
import time
import warnings
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from round_tpu.engine.executor import LocalTopology, init_lanes, run_phases
from round_tpu.fuzz import genome, objectives
from round_tpu.obs.metrics import METRICS
from round_tpu.obs.trace import TRACE

# coverage quantization: per round, 3 min-mailbox buckets (below n/3 /
# below 2n/3 / quorum-safe) x 4 delivered-link-density quarters.  The
# min-mailbox bucket is the quorum-risk diagnostic (fast.mix_ho_stats'
# heard_min); density separates sparse surgical schedules from blankets.
_MH_BUCKETS = 3
_DB_BUCKETS = 4
CELLS_PER_ROUND = _MH_BUCKETS * _DB_BUCKETS


@dataclasses.dataclass
class FuzzTarget:
    """One protocol wired for batched genome evaluation.

    `evaluate(pop)` runs every genome as an engine lane; co-resident
    `evaluate_schedules(schedules)` runs explicit [K, T, n, n] HO
    schedules (the minimizer's oracle) through the SAME engine + key
    discipline, so genome-eval and schedule-eval are bit-comparable.
    Both return numpy outcome dicts (decided/decision/decided_round +
    objective components + per-candidate coverage bits).

    VALUE adversaries (round_tpu/byz): the genome's byz_value/equiv_p8/
    stale_p8 fields drive per-(round, src, dst) payload substitution
    through the protocol's lie model, fused into the same jitted vmapped
    evaluation; ``evaluate_schedules(scheds, value_plans=...)`` is the
    explicit-plan twin.  ``value_domain`` is the claimed-value range
    (proposals plus one fabricable non-proposal, so validity attacks are
    expressible); safety objectives are scoped to HONEST lanes.
    """

    name: str
    algo: Any
    n: int
    horizon: int                       # rounds simulated (phases * k)
    phases: int
    rounds_per_phase: int
    init_values: np.ndarray            # [n] proposals
    seed: int
    value_domain: int = 0              # claimed-value range for lies
    _eval: Callable = dataclasses.field(repr=False, default=None)
    _eval_sched: Dict[Any, Callable] = dataclasses.field(
        repr=False, default_factory=dict)

    @property
    def n_cells(self) -> int:
        return self.horizon * CELLS_PER_ROUND

    @property
    def lie(self):
        from round_tpu.byz.lies import lie_for

        return lie_for(self.name)

    # -- batched evaluation -------------------------------------------------

    def evaluate(self, pop: genome.Population) -> Dict[str, np.ndarray]:
        sev = genome.severity(pop, self.horizon)
        # the population device buffers are DONATED (make_target's
        # donate_argnums): they are freshly staged from the numpy
        # Population each call and never read back, so XLA may reuse them
        # for outputs instead of allocating a second population footprint
        # per dispatch (ISSUE 14 throughput satellite)
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            out = self._eval(*[jnp.asarray(x) for x in pop.leaves()],
                             jnp.asarray(sev, jnp.float32))
        METRICS.counter("fuzz.dispatches").inc()
        METRICS.counter("fuzz.candidates").inc(pop.size)
        res = {k: np.asarray(v) for k, v in out.items()}
        res["severity"] = sev
        return res

    def evaluate_schedules(self, schedules: np.ndarray,
                           value_plans: Optional[np.ndarray] = None
                           ) -> Dict[str, np.ndarray]:
        """Outcomes of explicit deliver schedules [K, T, n, n] bool, each
        optionally paired with a value-substitution plan [K, T, n, n]
        int32 (byz/adversary.py opcodes).  K is padded up to a power of
        two (repeating the last row) so the minimizer's shrinking batches
        hit a handful of compiled shapes instead of one per K."""
        schedules = np.asarray(schedules, dtype=bool)
        K, T = schedules.shape[0], schedules.shape[1]
        if T != self.horizon:
            raise ValueError(
                f"schedule length {T} != target horizon {self.horizon}")
        if value_plans is not None:
            value_plans = np.asarray(value_plans, dtype=np.int32)
            if value_plans.shape != schedules.shape:
                raise ValueError(
                    f"value plans {value_plans.shape} != schedules "
                    f"{schedules.shape}")
        K_pad = 1 << max(0, (K - 1).bit_length())
        if K_pad != K:
            pad = np.repeat(schedules[-1:], K_pad - K, axis=0)
            schedules = np.concatenate([schedules, pad], axis=0)
            if value_plans is not None:
                vpad = np.repeat(value_plans[-1:], K_pad - K, axis=0)
                value_plans = np.concatenate([value_plans, vpad], axis=0)
        key = (K_pad, value_plans is not None)
        fn = self._eval_sched.get(key)
        if fn is None:
            # schedules/plans are the big buffers here ([K, T, n, n]);
            # they are staged fresh from numpy per call, so donate them
            fn = jax.jit(
                self._make_schedule_eval(with_plan=value_plans is not None),
                donate_argnums=(0,) if value_plans is None else (0, 1))
            self._eval_sched[key] = fn
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            if value_plans is None:
                out = fn(jnp.asarray(schedules))
            else:
                out = fn(jnp.asarray(schedules), jnp.asarray(value_plans))
        METRICS.counter("fuzz.dispatches").inc()
        METRICS.counter("fuzz.candidates").inc(int(schedules.shape[0]))
        return {k: np.asarray(v)[:K] for k, v in out.items()}

    # -- construction helpers ----------------------------------------------

    def _run_one(self, sampler, adversary=None):
        topo = LocalTopology(self.n)
        io = {"initial_value": jnp.asarray(self.init_values)}
        state0 = init_lanes(self.algo, io, self.n, topo)
        key = jax.random.PRNGKey(self.seed)
        st, done, dround, _ = run_phases(
            self.algo, state0, key, sampler, self.phases, topo,
            adversary=adversary)
        return st, done, dround

    def _outcome(self, st, done, dround, honest=None, claimed_fn=None):
        decided = self.algo.decided(st)
        decision = jnp.asarray(self.algo.decision(st))
        # lie-sourced decisions are valid inputs (objectives.lane_objectives
        # extra_valid); claimed_fn(decision) -> [P, n] bool marks them
        extra_valid = None if claimed_fn is None else claimed_fn(decision)
        obj = objectives.lane_objectives(
            decided, decision, dround,
            jnp.asarray(self.init_values), self.horizon, honest=honest,
            null_value=getattr(self.algo, "decision_null", None),
            extra_valid=extra_valid)
        return {
            "decided": decided,
            "decision": decision,
            "decided_round": dround,
            **obj,
        }

    def _coverage_bits(self, sampler) -> jnp.ndarray:
        """[horizon * CELLS_PER_ROUND] bool — which cells this schedule
        exercises.  The round index carries the phase (r % k) implicitly;
        the per-round pattern class is (min-mailbox bucket, density
        quarter)."""
        n = self.n

        def cell(r):
            ho = sampler(None, r)
            heard = jnp.sum(ho.astype(jnp.int32), axis=1)       # [n]
            mh = jnp.min(heard)
            links = jnp.sum(ho.astype(jnp.int32))
            mh_b = jnp.where(mh * 3 <= n, 0,
                             jnp.where(mh * 3 <= 2 * n, 1, 2))
            db = jnp.clip((links * _DB_BUCKETS) // (n * n + 1), 0,
                          _DB_BUCKETS - 1)
            return jax.nn.one_hot(mh_b * _DB_BUCKETS + db,
                                  CELLS_PER_ROUND, dtype=jnp.bool_)

        bits = jax.vmap(cell)(jnp.arange(self.horizon, dtype=jnp.int32))
        return bits.reshape(-1)

    def _make_genome_eval(self):
        from round_tpu.byz.adversary import hash_adversary, lie_pair

        lie = self.lie

        def one(crashed, crash_round, side, heal_round, rotate_down, p8,
                salt0, salt1, byz, byz_value, equiv_p8, stale_p8):
            samp = genome.row_sampler(
                self.n, crashed, crash_round, side, heal_round,
                rotate_down, p8, salt0, salt1, byz)
            adv = hash_adversary(
                self.n, self.rounds_per_phase, byz_value, equiv_p8,
                stale_p8, salt0, salt1, self.value_domain, lie=lie)
            st, done, dround = self._run_one(samp, adversary=adv)
            return st, done, dround, self._coverage_bits(samp)

        def ev(crashed, crash_round, side, heal_round, rotate_down, p8,
               salt0, salt1, byz, byz_value, equiv_p8, stale_p8, sev):
            st, done, dround, cov = jax.vmap(one)(
                crashed, crash_round, side, heal_round, rotate_down, p8,
                salt0, salt1, byz, byz_value, equiv_p8, stale_p8)
            # honest = cannot lie: safety objectives are scoped to
            # non-value-adversary lanes (objectives.lane_objectives)
            honest = ~(byz_value
                       & ((equiv_p8 > 0) | (stale_p8 > 0))[:, None])
            # an active equivocator's two faces (adversary.lie_pair) are
            # lie-sourced inputs: deciding one is not a validity bug
            equiv_active = (jnp.any(byz_value, axis=1) & (equiv_p8 > 0))
            va, vb = lie_pair(salt0, salt1, self.value_domain)

            def claimed(decision):
                hit = ((decision == va[:, None])
                       | (decision == vb[:, None]))
                return equiv_active[:, None] & hit

            out = self._outcome(st, done, dround, honest=honest,
                                claimed_fn=claimed)
            out["coverage"] = cov
            # the combined objective rides the same dispatch (the ISSUE's
            # "lane scores computed inside the jitted step")
            out["score"] = objectives.combined_score(
                out, sev, self.horizon)
            return out

        return ev

    def _make_schedule_eval(self, with_plan: bool = False):
        from round_tpu.byz.adversary import VP_NONE, plan_adversary

        lie = self.lie

        def one(sched, plan=None):
            samp = lambda key, r: sched[  # noqa: E731
                jnp.minimum(r, sched.shape[0] - 1)]
            adv = None
            if plan is not None:
                adv = plan_adversary(self.n, self.rounds_per_phase, plan,
                                     lie=lie)
            st, done, dround = self._run_one(samp, adversary=adv)
            return st, done, dround

        def ev(schedules):
            st, done, dround = jax.vmap(one)(schedules)
            return self._outcome(st, done, dround)

        def ev_plan(schedules, plans):
            st, done, dround = jax.vmap(one)(schedules, plans)
            # honest = senders the plan never substitutes for
            honest = ~jnp.any(plans != VP_NONE, axis=(1, 2))

            def claimed(decision):
                # lie-sourced decisions are valid inputs, matching the
                # genome path's extra_valid semantics: a decision equal
                # to ANY value the plan claims (>= 0 entries) is excused
                # from the validity count — without this, ddmin through
                # the plan evaluator would score phantom validity
                # violations the genome evaluator never saw
                sub = plans[:, :, :, :, None]
                hit = (sub == decision[:, None, None, None, :]) & (sub >= 0)
                return jnp.any(hit, axis=(1, 2, 3))

            return self._outcome(st, done, dround, honest=honest,
                                 claimed_fn=claimed)

        return ev_plan if with_plan else ev


def make_target(algo_name: str, n: int, horizon: int, seed: int = 0,
                values: Optional[np.ndarray] = None,
                algo_options: Optional[dict] = None,
                value_domain: Optional[int] = None) -> FuzzTarget:
    """Build a FuzzTarget for a selector-registered protocol.

    `horizon` is rounded UP to whole phases.  Default proposals are the
    "mixed" shape (i % 4 + distinctness) so agreement is non-trivial; pass
    `values` to pin them (they are recorded in exported artifacts).
    ``value_domain`` bounds the values a lie can claim (default: the
    proposal range plus ONE fabricable non-proposal, so equivocation and
    validity attacks are both in the search space)."""
    from round_tpu.apps.selector import select

    algo = select(algo_name, algo_options or {})
    k = algo.rounds_per_phase
    phases = max(1, -(-horizon // k))
    if values is None:
        values = (np.arange(n, dtype=np.int32) % 4).astype(np.int32)
    else:
        values = np.asarray(values, dtype=np.int32)
        if values.shape != (n,):
            raise ValueError(f"values must be [n={n}], got {values.shape}")
    if value_domain is None:
        value_domain = int(values.max(initial=0)) + 2
    t = FuzzTarget(name=algo_name, algo=algo, n=n, horizon=phases * k,
                   phases=phases, rounds_per_phase=k,
                   init_values=values, seed=seed,
                   value_domain=int(value_domain))
    # every genome leaf + the severity vector is donated: evaluate()
    # stages them fresh from numpy per generation and never reads them
    # back, so the dispatch runs without a second population allocation
    t._eval = jax.jit(t._make_genome_eval(),
                      donate_argnums=tuple(range(len(genome._FIELDS) + 1)))
    return t


# ---------------------------------------------------------------------------
# The generational loop
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FuzzResult:
    target: FuzzTarget
    population: genome.Population
    outcome: Dict[str, np.ndarray]      # last generation's batched outcome
    best_row: Dict[str, np.ndarray]     # best genome ever seen
    best_score: float
    best_outcome: Dict[str, float]      # its scalar objective components
    coverage_map: np.ndarray            # [n_cells] bool, global
    generations: int
    evaluated: int
    wall_s: float
    history: List[Dict[str, float]]

    @property
    def schedules_per_sec(self) -> float:
        return self.evaluated / self.wall_s if self.wall_s > 0 else 0.0


def _scalar_outcome(out: Dict[str, np.ndarray], i: int) -> Dict[str, float]:
    return {
        "undecided": float(out["undecided"][i]),
        "decide_round": int(out["decide_round"][i]),
        "agreement_viol": int(out["agreement_viol"][i]),
        "validity_viol": int(out["validity_viol"][i]),
        "score": float(out["score"][i]),
        "severity": float(out["severity"][i]),
    }


def search(target: FuzzTarget, pop_size: int, generations: int, *,
           seed: int = 0, elite_frac: float = 0.125, tournament: int = 3,
           novelty_weight: float = 0.5, time_box_s: Optional[float] = None,
           stop_when: Optional[Callable[[Dict[str, np.ndarray]],
                                        np.ndarray]] = None,
           value_cap: Optional[int] = None,
           seed_rows: Optional[List[Dict[str, np.ndarray]]] = None,
           log_fn: Optional[Callable[[str], None]] = None) -> FuzzResult:
    """Evolve `pop_size` fault schedules for up to `generations`
    generations (or until `time_box_s` wall-clock runs out, or some
    candidate satisfies `stop_when` — a fuzz/objectives predicate).

    Selection pressure = combined objective score + novelty_weight x the
    fraction of a candidate's coverage cells the global map had not seen
    before this generation.  Elites survive verbatim; the rest of the next
    generation is family-block crossover of tournament winners plus
    per-family point mutations.

    ``value_cap`` bounds the byzantine-VALUE membership mutation can
    reach (genome.mutate): None (default) keeps the family OFF — the
    PR-8 benign pipeline, whose callers export drops-only artifacts and
    never thread value plans — so value adversaries are strictly
    OPT-IN (`value_cap >= 1`, or `genome.value_cap_default(n)` for the
    envelope cap; byz/crosscheck.py and `fuzz_cli --value-cap` do).
    ``seed_rows`` splices hand-picked genomes over the seed population's
    head (the cross-check harness seeds the past-envelope sweep with the
    adversary class under test so the search starts INSIDE it).
    """
    rng = np.random.default_rng(seed)
    pop = genome.seed_population(seed, pop_size, target.n, target.horizon)
    if seed_rows:
        rows = [genome._fill_value_fields(dict(r)) for r in seed_rows]
        for i, row in enumerate(rows[:pop.size]):
            for f in genome._FIELDS:
                getattr(pop, f)[i] = np.asarray(row[f])
    n_elite = max(1, int(pop_size * elite_frac))
    coverage = np.zeros(target.n_cells, dtype=bool)
    best_score, best_row, best_out = -np.inf, None, None
    history: List[Dict[str, float]] = []
    evaluated = 0
    t0 = time.perf_counter()
    gen = 0
    out = None
    for gen in range(1, generations + 1):
        out = target.evaluate(pop)
        evaluated += pop.size
        METRICS.counter("fuzz.generations").inc()

        cov = out["coverage"]                       # [P, C] bool
        new_cells = (cov & ~coverage[None, :]).sum(axis=1)
        novelty = new_cells / max(1, CELLS_PER_ROUND)
        coverage |= cov.any(axis=0)
        METRICS.gauge("fuzz.coverage_cells").set(int(coverage.sum()))

        score = out["score"].astype(np.float64)
        sel_score = score + novelty_weight * novelty

        gi = int(np.argmax(score))
        if score[gi] > best_score:
            best_score = float(score[gi])
            best_row = pop.row(gi)
            best_out = _scalar_outcome(out, gi)
        rec = {
            "gen": gen,
            "best": round(float(score.max()), 4),
            "mean": round(float(score.mean()), 4),
            "best_ever": round(best_score, 4),
            "coverage_cells": int(coverage.sum()),
            "new_cells": int(new_cells.sum()),
        }
        history.append(rec)
        if TRACE.enabled:
            TRACE.emit("fuzz_gen", **rec)
        if log_fn:
            log_fn(f"gen {gen}: best {rec['best']} mean {rec['mean']} "
                   f"coverage {rec['coverage_cells']}/{target.n_cells}")

        hit = stop_when is not None and bool(np.any(stop_when(out)))
        out_of_time = (time_box_s is not None
                       and time.perf_counter() - t0 > time_box_s)
        if hit or out_of_time or gen == generations:
            break

        # -- selection ------------------------------------------------------
        order = np.argsort(-sel_score)
        elites = pop.take(order[:n_elite])
        n_child = pop_size - n_elite
        # tournament over the whole population, novelty included
        cand = rng.integers(0, pop_size, (2, n_child, tournament))
        pa = cand[0][np.arange(n_child),
                     np.argmax(sel_score[cand[0]], axis=1)]
        pb = cand[1][np.arange(n_child),
                     np.argmax(sel_score[cand[1]], axis=1)]
        children = genome.mutate(
            rng, genome.crossover(rng, pop, pa, pb), target.horizon,
            value_cap=0 if value_cap is None else value_cap)
        pop = genome.Population(**{
            f: np.concatenate([getattr(elites, f), getattr(children, f)])
            for f in genome._FIELDS})

    wall = time.perf_counter() - t0
    return FuzzResult(
        target=target, population=pop, outcome=out,
        best_row=best_row, best_score=best_score, best_outcome=best_out,
        coverage_map=coverage, generations=gen, evaluated=evaluated,
        wall_s=wall, history=history)
