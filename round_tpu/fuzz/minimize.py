"""Delta-debugging a winning schedule down to a minimal reproducer.

Three shrinking stages, all batching EVERY candidate of an iteration into
one engine dispatch (FuzzTarget.evaluate / evaluate_schedules — the
minimizer never runs one candidate at a time):

  1. genome-level: drop or halve whole fault families (omission off,
     partition healed earlier, fewer crashed processes, byz cleared,
     value adversary cleared / de-intensified...) while the predicate
     still reproduces — big strides first;
  2. link-level ddmin: materialize the explicit [T, n, n] deliver
     schedule and re-enable chunks of dropped (round, dst, src) link
     events, halving chunk size down to singletons;
  3. VALUE-event ddmin (round_tpu/byz): materialize the explicit
     [T, n, n] substitution plan and remove chunks of (round, dst, src,
     claimed-value) equivocation/stale events the same way — the result
     is 1-MINIMAL over BOTH event kinds: re-enabling any single dropped
     link or retracting any single lie loses the finding (verified by
     one final batched pass).

The minimal (schedule, value plan) pair is what fuzz/replay.py exports:
small artifacts that name exactly the links that matter and exactly the
lies that matter.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

import numpy as np

from round_tpu.byz.adversary import VP_NONE, plan_is_trivial
from round_tpu.fuzz import genome
from round_tpu.fuzz.search import FuzzTarget
from round_tpu.obs.metrics import METRICS
from round_tpu.obs.trace import TRACE

Predicate = Callable[[Dict[str, np.ndarray]], np.ndarray]


@dataclasses.dataclass
class MinimizeResult:
    schedule: np.ndarray            # [T, n, n] bool deliver — minimal
    outcome: Dict[str, np.ndarray]  # its engine outcome (per-process)
    dropped_initial: int            # dropped off-diagonal link events before
    dropped_final: int              # ... and after shrinking
    genome_row: Dict[str, np.ndarray]   # the family-shrunk genome
    iterations: int
    value_plan: Optional[np.ndarray] = None  # [T, n, n] int32, or None
    value_initial: int = 0          # substitution events before shrinking
    value_final: int = 0            # ... and after


def _family_candidates(row: Dict[str, np.ndarray]) -> List[Dict]:
    """Simplification moves, simplest-first: each candidate removes or
    halves one fault family of the genome."""
    cands = []

    def variant(**patch):
        c = {k: np.array(v, copy=True) for k, v in row.items()}
        c.update({k: np.asarray(v) for k, v in patch.items()})
        return c

    if row["p8"] > 0:
        cands.append(variant(p8=np.int32(0)))
        cands.append(variant(p8=np.int32(int(row["p8"]) // 2)))
    if row["crashed"].any():
        cands.append(variant(crashed=np.zeros_like(row["crashed"])))
        fewer = np.array(row["crashed"], copy=True)
        fewer[np.argmax(fewer)] = False
        cands.append(variant(crashed=fewer))
    if row["heal_round"] > 0:
        cands.append(variant(heal_round=np.int32(0),
                             side=np.zeros_like(row["side"])))
        cands.append(variant(heal_round=np.int32(
            int(row["heal_round"]) // 2)))
    if row["rotate_down"] > 0:
        cands.append(variant(rotate_down=np.int32(0)))
    if row["byz"].any():
        cands.append(variant(byz=np.zeros_like(row["byz"])))
    if row["byz_value"].any():
        # value adversary off entirely, then fewer liars, then gentler
        cands.append(variant(byz_value=np.zeros_like(row["byz_value"]),
                             equiv_p8=np.int32(0), stale_p8=np.int32(0)))
        fewer = np.array(row["byz_value"], copy=True)
        fewer[np.argmax(fewer)] = False
        cands.append(variant(byz_value=fewer))
        if row["stale_p8"] > 0:
            cands.append(variant(stale_p8=np.int32(0)))
        if row["equiv_p8"] > 0:
            cands.append(variant(equiv_p8=np.int32(
                int(row["equiv_p8"]) // 2)))
    return cands


def shrink_genome(target: FuzzTarget, row: Dict[str, np.ndarray],
                  predicate: Predicate, max_iters: int = 32
                  ) -> Dict[str, np.ndarray]:
    """Greedy family-level shrink to a fixed point: per iteration, batch
    every one-family simplification into one dispatch and adopt the FIRST
    (simplest-first order) that still reproduces."""
    row = genome._fill_value_fields(
        {k: np.asarray(v) for k, v in row.items()})
    for _ in range(max_iters):
        cands = _family_candidates(row)
        if not cands:
            break
        pop = genome.Population.from_rows(cands)
        ok = predicate(target.evaluate(pop))
        METRICS.counter("fuzz.minimize_dispatches").inc()
        hit = np.flatnonzero(ok)
        if hit.size == 0:
            break
        row = cands[int(hit[0])]
    return row


def _dropped_events(schedule: np.ndarray) -> np.ndarray:
    """[D, 3] int (r, dst, src) of every OFF-diagonal undelivered link
    event — the atoms ddmin shrinks over (self-delivery is pinned True by
    the engine convention and never counted)."""
    miss = ~schedule
    T, n, _ = schedule.shape
    eye = np.eye(n, dtype=bool)
    miss = miss & ~eye[None, :, :]
    return np.argwhere(miss)


def _with_events(base: np.ndarray, events: np.ndarray) -> np.ndarray:
    """Full-delivery schedule with exactly `events` (r, dst, src) dropped."""
    out = np.ones_like(base)
    if events.size:
        out[events[:, 0], events[:, 1], events[:, 2]] = False
    return out


def value_events_of(plan: Optional[np.ndarray]) -> np.ndarray:
    """[E, 4] int (r, dst, src, op) of every substitution event of a
    value plan (op >= 0 claimed value, op == VP_STALE stale replay) —
    the atoms the value ddmin shrinks over."""
    if plan is None:
        return np.zeros((0, 4), dtype=np.int64)
    coords = np.argwhere(np.asarray(plan) != VP_NONE)
    ops = np.asarray(plan)[coords[:, 0], coords[:, 1], coords[:, 2]]
    return np.concatenate([coords, ops[:, None]], axis=1)


def plan_with_events(shape, events: np.ndarray) -> np.ndarray:
    """Truthful plan with exactly ``events`` (r, dst, src, op) applied."""
    out = np.full(shape, VP_NONE, dtype=np.int32)
    if events.size:
        out[events[:, 0], events[:, 1], events[:, 2]] = events[:, 3]
    return out


def _ddmin(events: np.ndarray, rebuild, oracle, max_batch: int,
           max_iters: int, iters0: int = 0):
    """The shared complement-testing loop: repeatedly try REMOVING chunks
    of ``events`` (rebuild(kept_events) -> candidate; oracle(stack) ->
    [K] bool reproduces), halving chunk size to 1.  Returns (events,
    iterations)."""
    chunk = max(1, events.shape[0] // 2)
    iters = iters0
    while iters < max_iters:
        D = events.shape[0]
        if D == 0:
            break
        chunk = min(chunk, D)
        starts = list(range(0, D, chunk))
        adopted = False
        for b in range(0, len(starts), max_batch):
            if iters >= max_iters:
                break
            window = starts[b:b + max_batch]
            keep_sets = [np.concatenate([events[:s], events[s + chunk:]])
                         for s in window]
            ok = oracle(np.stack([rebuild(k) for k in keep_sets]))
            METRICS.counter("fuzz.minimize_dispatches").inc()
            iters += 1
            hit = np.flatnonzero(ok)
            if hit.size:
                events = keep_sets[int(hit[0])]
                adopted = True
                break
        if adopted:
            continue  # retry at the same granularity over the new set
        if chunk == 1:
            break
        chunk = max(1, chunk // 2)
    return events, iters


def shrink_schedule(target: FuzzTarget, schedule: np.ndarray,
                    predicate: Predicate, max_batch: int = 64,
                    max_iters: int = 200,
                    value_plan: Optional[np.ndarray] = None) -> tuple:
    """Link-level ddmin: repeatedly try re-ENABLING chunks of the dropped
    link events (complement testing, chunk size halving to 1), batching
    all of an iteration's candidates into one dispatch.  A fixed
    ``value_plan`` rides along on every candidate (the oracle evaluates
    links UNDER the lies in force).  Returns (schedule, outcome,
    iterations) with the schedule 1-minimal under the predicate."""
    schedule = np.asarray(schedule, dtype=bool)

    def oracle(cands):
        vp = None if value_plan is None else np.repeat(
            value_plan[None], cands.shape[0], axis=0)
        return predicate(target.evaluate_schedules(cands, vp))

    events, iters = _ddmin(
        _dropped_events(schedule),
        lambda kept: _with_events(schedule, kept), oracle,
        max_batch, max_iters)
    minimal = _with_events(schedule, events)
    vp1 = None if value_plan is None else value_plan[None]
    out = target.evaluate_schedules(minimal[None], vp1)
    outcome = {k: v[0] for k, v in out.items()}
    return minimal, outcome, iters


def shrink_value_plan(target: FuzzTarget, schedule: np.ndarray,
                      value_plan: np.ndarray, predicate: Predicate,
                      max_batch: int = 64, max_iters: int = 200) -> tuple:
    """VALUE-event ddmin over a fixed schedule: remove chunks of
    substitution events while the predicate still reproduces.  Returns
    (plan, outcome, iterations), 1-minimal over the lie events."""
    schedule = np.asarray(schedule, dtype=bool)
    value_plan = np.asarray(value_plan, dtype=np.int32)
    K_shape = value_plan.shape

    def oracle(plans):
        scheds = np.repeat(schedule[None], plans.shape[0], axis=0)
        return predicate(target.evaluate_schedules(scheds, plans))

    events, iters = _ddmin(
        value_events_of(value_plan),
        lambda kept: plan_with_events(K_shape, kept), oracle,
        max_batch, max_iters)
    minimal = plan_with_events(K_shape, events)
    out = target.evaluate_schedules(schedule[None], minimal[None])
    outcome = {k: v[0] for k, v in out.items()}
    return minimal, outcome, iters


def verify_one_minimal(target: FuzzTarget, schedule: np.ndarray,
                       predicate: Predicate,
                       value_plan: Optional[np.ndarray] = None) -> bool:
    """True iff re-enabling ANY single dropped link — and retracting ANY
    single value-substitution event — loses the finding: one batched
    pass over all singles per event kind (the ddmin postcondition)."""
    schedule = np.asarray(schedule, dtype=bool)
    events = _dropped_events(schedule)
    if events.shape[0]:
        cands = []
        for i in range(events.shape[0]):
            keep = np.delete(events, i, axis=0)
            cands.append(_with_events(schedule, keep))
        vp = None if value_plan is None else np.repeat(
            value_plan[None], len(cands), axis=0)
        ok = predicate(target.evaluate_schedules(np.stack(cands), vp))
        if bool(np.any(ok)):
            return False
    vev = value_events_of(value_plan)
    if vev.shape[0]:
        plans = []
        for i in range(vev.shape[0]):
            keep = np.delete(vev, i, axis=0)
            plans.append(plan_with_events(value_plan.shape, keep))
        scheds = np.repeat(schedule[None], len(plans), axis=0)
        ok = predicate(target.evaluate_schedules(scheds, np.stack(plans)))
        if bool(np.any(ok)):
            return False
    return True


def minimize(target: FuzzTarget, row: Dict[str, np.ndarray],
             predicate: Predicate,
             log_fn: Optional[Callable[[str], None]] = None
             ) -> MinimizeResult:
    """The full pipeline: family shrink -> materialize -> link ddmin ->
    value-event ddmin.

    Raises ValueError if `row` does not reproduce under `predicate` to
    begin with (a minimizer fed a non-finding would silently 'minimize'
    to the empty schedule)."""
    row = genome._fill_value_fields(
        {k: np.asarray(v) for k, v in row.items()})
    pop = genome.Population.from_rows([row])
    if not bool(predicate(target.evaluate(pop))[0]):
        raise ValueError(
            f"genome does not reproduce under {getattr(predicate, '__name__', predicate)!r}; "
            "nothing to minimize")
    shrunk = shrink_genome(target, row, predicate)
    sched0 = genome.row_schedule(shrunk, target.horizon)
    vplan0 = genome.row_value_plan(shrunk, target.horizon,
                                   target.value_domain)
    has_values = not plan_is_trivial(vplan0)
    vp_arg = vplan0 if has_values else None
    d0 = int(_dropped_events(sched0).shape[0])
    v0 = int(value_events_of(vp_arg).shape[0])
    minimal, outcome, iters = shrink_schedule(
        target, sched0, predicate, value_plan=vp_arg)
    vplan = vp_arg
    if has_values:
        vplan, outcome, it2 = shrink_value_plan(
            target, minimal, vp_arg, predicate)
        iters += it2
        if plan_is_trivial(vplan):
            vplan = None
    d1 = int(_dropped_events(minimal).shape[0])
    v1 = int(value_events_of(vplan).shape[0])
    if log_fn:
        log_fn(f"minimized: {d0} -> {d1} dropped link events, "
               f"{v0} -> {v1} value events ({iters} ddmin iterations)")
    if TRACE.enabled:
        TRACE.emit("fuzz_minimize", dropped_initial=d0, dropped_final=d1,
                   value_initial=v0, value_final=v1, iterations=iters)
    return MinimizeResult(
        schedule=minimal, outcome=outcome, dropped_initial=d0,
        dropped_final=d1, genome_row=shrunk, iterations=iters,
        value_plan=vplan, value_initial=v0, value_final=v1)
