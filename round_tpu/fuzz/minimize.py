"""Delta-debugging a winning schedule down to a minimal reproducer.

Two shrinking stages, both batching EVERY candidate of an iteration into
one engine dispatch (FuzzTarget.evaluate / evaluate_schedules — the
minimizer never runs one candidate at a time):

  1. genome-level: drop or halve whole fault families (omission off,
     partition healed earlier, fewer crashed processes, byz cleared...)
     while the predicate still reproduces — big strides first;
  2. link-level ddmin: materialize the explicit [T, n, n] deliver
     schedule and re-enable chunks of dropped (round, dst, src) link
     events, halving chunk size down to singletons.  The result is
     1-MINIMAL: re-enabling any single remaining dropped link loses the
     finding (verified by one final batched pass).

The minimal schedule is what fuzz/replay.py exports: small artifacts that
name exactly the links that matter.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

import numpy as np

from round_tpu.fuzz import genome
from round_tpu.fuzz.search import FuzzTarget
from round_tpu.obs.metrics import METRICS
from round_tpu.obs.trace import TRACE

Predicate = Callable[[Dict[str, np.ndarray]], np.ndarray]


@dataclasses.dataclass
class MinimizeResult:
    schedule: np.ndarray            # [T, n, n] bool deliver — minimal
    outcome: Dict[str, np.ndarray]  # its engine outcome (per-process)
    dropped_initial: int            # dropped off-diagonal link events before
    dropped_final: int              # ... and after shrinking
    genome_row: Dict[str, np.ndarray]   # the family-shrunk genome
    iterations: int


def _family_candidates(row: Dict[str, np.ndarray]) -> List[Dict]:
    """Simplification moves, simplest-first: each candidate removes or
    halves one fault family of the genome."""
    cands = []

    def variant(**patch):
        c = {k: np.array(v, copy=True) for k, v in row.items()}
        c.update({k: np.asarray(v) for k, v in patch.items()})
        return c

    if row["p8"] > 0:
        cands.append(variant(p8=np.int32(0)))
        cands.append(variant(p8=np.int32(int(row["p8"]) // 2)))
    if row["crashed"].any():
        cands.append(variant(crashed=np.zeros_like(row["crashed"])))
        fewer = np.array(row["crashed"], copy=True)
        fewer[np.argmax(fewer)] = False
        cands.append(variant(crashed=fewer))
    if row["heal_round"] > 0:
        cands.append(variant(heal_round=np.int32(0),
                             side=np.zeros_like(row["side"])))
        cands.append(variant(heal_round=np.int32(
            int(row["heal_round"]) // 2)))
    if row["rotate_down"] > 0:
        cands.append(variant(rotate_down=np.int32(0)))
    if row["byz"].any():
        cands.append(variant(byz=np.zeros_like(row["byz"])))
    return cands


def shrink_genome(target: FuzzTarget, row: Dict[str, np.ndarray],
                  predicate: Predicate, max_iters: int = 32
                  ) -> Dict[str, np.ndarray]:
    """Greedy family-level shrink to a fixed point: per iteration, batch
    every one-family simplification into one dispatch and adopt the FIRST
    (simplest-first order) that still reproduces."""
    row = {k: np.asarray(v) for k, v in row.items()}
    for _ in range(max_iters):
        cands = _family_candidates(row)
        if not cands:
            break
        pop = genome.Population.from_rows(cands)
        ok = predicate(target.evaluate(pop))
        METRICS.counter("fuzz.minimize_dispatches").inc()
        hit = np.flatnonzero(ok)
        if hit.size == 0:
            break
        row = cands[int(hit[0])]
    return row


def _dropped_events(schedule: np.ndarray) -> np.ndarray:
    """[D, 3] int (r, dst, src) of every OFF-diagonal undelivered link
    event — the atoms ddmin shrinks over (self-delivery is pinned True by
    the engine convention and never counted)."""
    miss = ~schedule
    T, n, _ = schedule.shape
    eye = np.eye(n, dtype=bool)
    miss = miss & ~eye[None, :, :]
    return np.argwhere(miss)


def _with_events(base: np.ndarray, events: np.ndarray) -> np.ndarray:
    """Full-delivery schedule with exactly `events` (r, dst, src) dropped."""
    out = np.ones_like(base)
    if events.size:
        out[events[:, 0], events[:, 1], events[:, 2]] = False
    return out


def shrink_schedule(target: FuzzTarget, schedule: np.ndarray,
                    predicate: Predicate, max_batch: int = 64,
                    max_iters: int = 200) -> tuple:
    """Link-level ddmin: repeatedly try re-ENABLING chunks of the dropped
    link events (complement testing, chunk size halving to 1), batching
    all of an iteration's candidates into one dispatch.  Returns
    (schedule, outcome, iterations) with the schedule 1-minimal under the
    predicate."""
    schedule = np.asarray(schedule, dtype=bool)
    events = _dropped_events(schedule)
    chunk = max(1, events.shape[0] // 2)
    iters = 0
    while iters < max_iters:
        D = events.shape[0]
        if D == 0:
            break
        chunk = min(chunk, D)
        # candidate per chunk = all events EXCEPT that chunk (re-enabled),
        # evaluated in batches of max_batch so EVERY chunk gets tried at
        # this granularity before giving up on it
        starts = list(range(0, D, chunk))
        adopted = False
        for b in range(0, len(starts), max_batch):
            if iters >= max_iters:
                break
            window = starts[b:b + max_batch]
            keep_sets = [np.concatenate([events[:s], events[s + chunk:]])
                         for s in window]
            cands = np.stack([_with_events(schedule, k)
                              for k in keep_sets])
            ok = predicate(target.evaluate_schedules(cands))
            METRICS.counter("fuzz.minimize_dispatches").inc()
            iters += 1
            hit = np.flatnonzero(ok)
            if hit.size:
                events = keep_sets[int(hit[0])]
                adopted = True
                break
        if adopted:
            continue  # retry at the same granularity over the new set
        if chunk == 1:
            break
        chunk = max(1, chunk // 2)
    minimal = _with_events(schedule, events)
    out = target.evaluate_schedules(minimal[None])
    outcome = {k: v[0] for k, v in out.items()}
    return minimal, outcome, iters


def verify_one_minimal(target: FuzzTarget, schedule: np.ndarray,
                       predicate: Predicate) -> bool:
    """True iff re-enabling ANY single dropped link loses the finding —
    one batched pass over all singles (the ddmin postcondition)."""
    events = _dropped_events(np.asarray(schedule, dtype=bool))
    if events.shape[0] == 0:
        return True
    cands = []
    for i in range(events.shape[0]):
        keep = np.delete(events, i, axis=0)
        cands.append(_with_events(schedule, keep))
    ok = predicate(target.evaluate_schedules(np.stack(cands)))
    return not bool(np.any(ok))


def minimize(target: FuzzTarget, row: Dict[str, np.ndarray],
             predicate: Predicate,
             log_fn: Optional[Callable[[str], None]] = None
             ) -> MinimizeResult:
    """The full pipeline: family shrink -> materialize -> link ddmin.

    Raises ValueError if `row` does not reproduce under `predicate` to
    begin with (a minimizer fed a non-finding would silently 'minimize'
    to the empty schedule)."""
    pop = genome.Population.from_rows([row])
    if not bool(predicate(target.evaluate(pop))[0]):
        raise ValueError(
            f"genome does not reproduce under {getattr(predicate, '__name__', predicate)!r}; "
            "nothing to minimize")
    shrunk = shrink_genome(target, row, predicate)
    sched0 = genome.row_schedule(shrunk, target.horizon)
    d0 = int(_dropped_events(sched0).shape[0])
    minimal, outcome, iters = shrink_schedule(target, sched0, predicate)
    d1 = int(_dropped_events(minimal).shape[0])
    if log_fn:
        log_fn(f"minimized: {d0} -> {d1} dropped link events "
               f"({iters} ddmin iterations)")
    if TRACE.enabled:
        TRACE.emit("fuzz_minimize", dropped_initial=d0, dropped_final=d1,
                   iterations=iters)
    return MinimizeResult(
        schedule=minimal, outcome=outcome, dropped_initial=d0,
        dropped_final=d1, genome_row=shrunk, iterations=iters)
