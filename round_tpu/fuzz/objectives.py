"""Spec-derived lane objectives: how much a fault schedule hurt.

Every score is computed INSIDE the jitted evaluation step over the whole
[P]-candidate batch (fuzz/search.py calls `lane_objectives` from within the
same `jax.jit` that ran the engine), so scoring adds zero extra dispatches.
The objective catalog is the runtime mirror of the reference's Spec
properties (Specs.scala:9-19) — the same formulas `spec/check.py` evaluates
over traces, reduced to per-candidate scalars:

  undecided        — Termination's failure mass: fraction of processes
                     undecided at the horizon;
  decide_round     — rounds-to-decide: the LAST process's decision round
                     (horizon where undecided) — decision delay;
  agreement_viol   — Agreement's margin: # unordered pairs of decided
                     processes with differing decisions (>0 = SAFETY BUG);
  validity_viol    — Validity's slack: # decided processes whose decision
                     is no process's initial value (>0 = SAFETY BUG).

Arbitrary spec/dsl.py formulas plug in through `spec_holds` (formula-as-
objective): any ``Env -> bool`` property evaluates vmapped over the final
state batch, so a protocol's own Spec drives the search without
re-stating it.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp

from round_tpu.spec.dsl import Env

# weight of a safety violation in the combined score: any schedule that
# BREAKS agreement/validity must dominate every schedule that merely
# degrades liveness, whatever the liveness terms add up to
SAFETY_WEIGHT = 100.0


def lane_objectives(decided: jnp.ndarray, decision: jnp.ndarray,
                    decided_round: jnp.ndarray, init_values: jnp.ndarray,
                    horizon: int,
                    honest: jnp.ndarray = None,
                    null_value=None,
                    extra_valid: jnp.ndarray = None) -> Dict[str, jnp.ndarray]:
    """Per-candidate objective components from a batched engine outcome.

    Args (all leading axis [P]): decided [P, n] bool, decision [P, n],
    decided_round [P, n] int32 (-1 = never), init_values [n] (the
    proposals — Validity's witness set), horizon = rounds simulated.
    Returns a dict of [P] arrays (floats/int32) — jit-safe.

    ``honest`` ([P, n] bool, default all-True) scopes the SAFETY terms to
    non-byzantine lanes — the byzantine-consensus reading of the Spec
    (round_tpu/byz): a value adversary's in-engine lane state is a
    fiction (a real liar has no honest state to judge), so agreement is
    counted over honest PAIRS and validity over honest deciders.  The
    witness set stays ALL proposals — a liar's declared initial value is
    a legitimate input, its wire forgeries are not.  Liveness terms stay
    global: a liar that stalls everyone still scores.

    ``null_value`` (Algorithm.decision_null) marks an explicit
    abort/null decision the protocol's contract permits (the PBFT
    family's decide(null)): null deciders leave the SAFETY terms —
    agreement is over pairs of non-null deciders, validity over non-null
    decisions — but still count as decided for the liveness terms (the
    instance terminated; mass-null is liveness damage only through
    decide_round, exactly the reference Spec's reading).

    ``extra_valid`` ([P, n] bool) widens Validity's witness set per
    candidate: True where the lane's decision is a value an ACTIVE liar
    claimed on the wire (round_tpu/byz).  A lie-sourced value is an
    INPUT to the system — a byzantine PBFT primary fabricating a request
    that every honest replica then accepts is correct protocol behavior,
    not a validity bug; the violation Validity still catches is a value
    nobody (honest or lying) ever introduced.  Agreement is unaffected:
    two honest deciders splitting over the liar's two faces is the
    violation the cross-check hunts.
    """
    und = 1.0 - jnp.mean(decided.astype(jnp.float32), axis=1)
    dr = jnp.where(decided_round < 0, horizon, decided_round)
    decide_round = jnp.max(dr, axis=1).astype(jnp.int32)

    if honest is None:
        hdec = decided
    else:
        hdec = decided & jnp.asarray(honest)
    if null_value is not None:
        hdec = hdec & (decision != jnp.asarray(null_value))
    both = hdec[:, :, None] & hdec[:, None, :]
    diff = decision[:, :, None] != decision[:, None, :]
    agreement_viol = (jnp.sum((both & diff).astype(jnp.int32), axis=(1, 2))
                      // 2)

    valid = jnp.any(
        decision[:, :, None] == init_values[None, None, :], axis=2)
    if extra_valid is not None:
        valid = valid | jnp.asarray(extra_valid)
    validity_viol = jnp.sum((hdec & ~valid).astype(jnp.int32), axis=1)

    return {
        "undecided": und,
        "decide_round": decide_round,
        "agreement_viol": agreement_viol,
        "validity_viol": validity_viol,
    }


def combined_score(obj: Dict[str, jnp.ndarray], severity: jnp.ndarray,
                   horizon: int,
                   severity_weight: float = 0.25) -> jnp.ndarray:
    """The scalar the search maximizes: liveness damage (undecided mass +
    normalized decision delay) + safety violations at SAFETY_WEIGHT, minus
    a small severity rent (genome.severity) so sparse schedules win ties —
    the evolutionary pre-echo of fuzz/minimize.py."""
    viol = (obj["agreement_viol"] + obj["validity_viol"]) > 0
    return (obj["undecided"]
            + obj["decide_round"].astype(jnp.float32) / max(1, horizon)
            + SAFETY_WEIGHT * viol.astype(jnp.float32)
            - severity_weight * jnp.asarray(severity, jnp.float32))


def kv_stream_viol(decided: jnp.ndarray, decision: jnp.ndarray,
                   record_value) -> jnp.ndarray:
    """[P] int32 — the KV decision-stream invariant (round_tpu/kv,
    docs/KV.md) as a lane objective: under the serving path every
    replica of an instance proposes the SAME client record (the router
    fans one lvb payload out to the whole group), so any decided lane
    whose decision differs from that record is a PHANTOM APPLY — a
    per-key state machine executing a record no client ever wrote.

    This is Validity with a singleton witness set, which also subsumes
    Agreement on the instance: if every decider must equal the record,
    any two deciders must equal each other.  It gets its own objective
    (rather than reusing ``validity_viol`` with pinned values) because
    the KV reading is the invariant the kv/lin.py history checker
    enforces post-hoc — the fuzzer hunts the same bug pre-hoc, and a
    hit here is the engine-level root cause of a ``non-linearizable``
    history verdict."""
    bad = decided & (decision != jnp.asarray(record_value))
    return jnp.sum(bad.astype(jnp.int32), axis=1)


def spec_holds(formula: Callable[[Env], Any], state: Any, n: int
               ) -> jnp.ndarray:
    """[P] bool — evaluate one spec/dsl.py formula on every candidate's
    final state (check_trace's per-step evaluation, batched over the
    population axis instead of the round axis).  Compose the result into a
    custom score, or use it as a minimizer predicate."""
    return jax.vmap(lambda st: jnp.asarray(formula(Env(state=st, n=n))))(
        state)


# ---------------------------------------------------------------------------
# Minimizer predicates (host-side, over numpy outcome dicts)
# ---------------------------------------------------------------------------
#
# A predicate maps the batched outcome of candidate schedules to a [K] bool
# "does this candidate still reproduce the finding" — fuzz/minimize.py's
# oracle.  They work on the numpy outcome dict fuzz/search.FuzzTarget
# returns so the same predicate drives search early-stops, shrinking and
# artifact verification.


def undecided_at_horizon(min_lanes: int = 1):
    """≥ min_lanes processes still undecided when the horizon hits."""
    import numpy as np

    def pred(out):
        return (~np.asarray(out["decided"])).sum(axis=1) >= min_lanes

    pred.__name__ = f"undecided_at_horizon(min_lanes={min_lanes})"
    return pred


def decision_delayed(min_round: int):
    """Decision delay: the last decider's round ≥ min_round (undecided
    counts as the horizon)."""
    import numpy as np

    def pred(out):
        return np.asarray(out["decide_round"]) >= min_round

    pred.__name__ = f"decision_delayed(min_round={min_round})"
    return pred


def safety_violated():
    """Agreement or validity broken — the jackpot predicate."""
    import numpy as np

    def pred(out):
        return (np.asarray(out["agreement_viol"])
                + np.asarray(out["validity_viol"])) > 0

    pred.__name__ = "safety_violated()"
    return pred


def kv_stream_violated(record_value: int):
    """The KV decision-stream invariant (``kv_stream_viol``) as a
    minimizer predicate: some decided lane applied a record that is not
    the uniformly-proposed client record.  Drives the kv fuzz arm's
    search stop, ddmin shrinking and artifact verification with ONE
    oracle, like the rv/byz arms."""
    import numpy as np

    def pred(out):
        bad = (np.asarray(out["decided"])
               & (np.asarray(out["decision"]) != record_value))
        return bad.sum(axis=1) > 0

    pred.__name__ = f"kv_stream_violated(record={record_value})"
    return pred
