"""Hostile-wire fuzz gate: structure-aware frame mutation against every
layer of the receive path.

The serving tier's sockets are unauthenticated: any peer (or anything
that can reach the port) can ship arbitrary bytes.  The runtime's
contract — built up through PR 5's codec, PR 7's native pump and this
PR's overload hardening — is that hostile bytes cost the receiver
NOTHING but a counter tick: no crash, no wedge, no mailbox corruption,
no memory growth, and decisions identical to a run where the hostile
peer said nothing.  This module is the gate that keeps that contract
true: a seeded, structure-aware mutator built on the PR-5 codec golden
bytes (tests/test_codec.py) hammers

  * the Python codec (``codec.loads``) and the RESTRICTED unpickler
    (``transport.wire_loads``) — ``fuzz_codec``;
  * the FLAG_BATCH container splitter (``HostTransport._split_batch``)
    — ``fuzz_split``;
  * the C round-pump template parser (``rt_pump_feed`` /
    ``rt_pump_insert`` via a live native node) — ``fuzz_pump``;

with byte-level operators that know WHERE the structural bytes live
(``codec.array_layout`` yields the template/hole map, so tag bytes,
dtype codes, counts and dims are corrupted surgically, not just
sprayed): truncation, tag/dtype/count corruption, oversized dims,
container-split lies (lying sub-frame lengths, zero-length frames,
truncated headers), splices, bit flips, pickle-gadget payloads against
the restricted unpickler, and replayed/corrupted tag words.

Accounting contract (the invariant the gate asserts): every injected
frame is either CONSUMED (decoded to a value / split into sub-frames /
ingested by the pump) or REJECTED — and every rejection ticks
``wire.hostile_rejected`` here, on top of whatever layer-local counter
(``wire.batch_malformed``, ``host.malformed``, pump malformed marks)
the production path already keeps.  ``frames == consumed + rejected``
with nothing unaccounted, or the gate fails.

The cluster-level form — a live group member blasting mutated frames
while the survivors' decision logs must stay byte-identical to a run
where it stays silent — lives in tests/test_overload.py, riding
``-m fuzz``/``-m slow`` alongside the ≥10k-frame arm (the tier-1 form
of the gate is the accounting smoke).
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from round_tpu.obs.metrics import METRICS
from round_tpu.runtime import codec
from round_tpu.runtime.oob import FLAG_BATCH, FLAG_NORMAL, Tag

_C_HOSTILE = METRICS.counter("wire.hostile_rejected")

_BATCH_HDR = struct.Struct("<QI")   # the golden FLAG_BATCH sub-frame
# header (tests/test_codec.py test_golden_batch_framing_pinned)

# benign pickle-gadget sentinel: the restricted unpickler must REFUSE the
# payload before this is ever called — a set flag is a gate failure
GADGET_FIRED = {"count": 0}


def _gadget():  # pragma: no cover - firing IS the failure
    GADGET_FIRED["count"] += 1
    return None


class _Gadget:
    def __reduce__(self):
        return (_gadget, ())


def exemplar_payloads() -> List[Any]:
    """Clean wire payloads shaped like real round traffic (the codec
    golden-bytes vocabulary: dict/tuple/list containers, every hot
    dtype, scalars, strings, bytes) — the mutation corpus."""
    return [
        {"x": np.arange(4, dtype=np.int32), "y": np.float64(2.5)},
        {"vote": np.int64(3), "ts": np.int32(7),
         "bits": np.zeros(8, dtype=np.uint8)},
        (np.ones((2, 3), dtype=np.float32), np.bool_(True)),
        [np.int8(-1), np.uint16(9), np.float16(0.5)],
        np.arange(16, dtype=np.int64),
        {"k": "value", "b": b"\x00\x01\x02", "n": None},
        np.complex64(1 + 2j),
        {"nested": {"deep": (np.int32(1), [np.uint32(2)])}},
    ]


class HostileMutator:
    """Seeded structure-aware frame mutator.  ``next_frame()`` yields
    (mutated_bytes, operator_name); ``next_container()`` the FLAG_BATCH
    container form.  Deterministic per seed — a failing frame is
    reproducible from (seed, index) alone."""

    def __init__(self, seed: int = 0,
                 corpus: Optional[List[Any]] = None):
        self.rng = np.random.default_rng(seed)
        self.corpus = corpus if corpus is not None else exemplar_payloads()
        self._clean = [codec.encode(p) for p in self.corpus]
        # structural-byte maps where the layout is fixed (array_layout
        # golden contract: template == encoding, holes == raw data)
        self._structs: List[Tuple[bytes, List[Tuple[int, int, int]]]] = []
        for p in self.corpus:
            lay = codec.array_layout(p)
            self._structs.append(lay if lay is not None else None)
        self._ops: List[Tuple[str, Callable[[bytes], bytes]]] = [
            ("truncate", self._op_truncate),
            ("bitflip", self._op_bitflip),
            ("tag_corrupt", self._op_tag),
            ("struct_corrupt", self._op_struct),
            ("count_huge", self._op_count),
            ("dim_oversize", self._op_dim),
            ("splice", self._op_splice),
            ("append_garbage", self._op_append),
            ("random_bytes", self._op_random),
            ("pickle_gadget", self._op_gadget),
        ]

    # -- byte operators ----------------------------------------------------

    def _pick(self) -> bytes:
        return self._clean[int(self.rng.integers(len(self._clean)))]

    def _op_truncate(self, b: bytes) -> bytes:
        if len(b) < 2:
            return b""
        return b[: int(self.rng.integers(1, len(b)))]

    def _op_bitflip(self, b: bytes) -> bytes:
        if not b:
            return b
        out = bytearray(b)
        for _ in range(int(self.rng.integers(1, 9))):
            i = int(self.rng.integers(len(out)))
            out[i] ^= 1 << int(self.rng.integers(8))
        return bytes(out)

    def _op_tag(self, b: bytes) -> bytes:
        """Corrupt the FIRST byte — the node tag the decoder routes on:
        half the time to a VALID-but-wrong codec tag (0xA0..0xAF, the
        structurally-confusing case), else to anything."""
        if not b:
            return b
        out = bytearray(b)
        if self.rng.random() < 0.5:
            out[0] = int(self.rng.integers(0xA0, 0xB0))
        else:
            out[0] = int(self.rng.integers(256))
        return bytes(out)

    def _op_struct(self, b: bytes) -> bytes:
        """Corrupt a STRUCTURAL byte (outside the array-data holes):
        dtype codes, ndim, dims, counts, key lengths — the bytes the C
        parser memcmps.  Falls back to bitflip when this clean frame has
        no fixed layout."""
        idx = self._clean.index(b) if b in self._clean else -1
        lay = self._structs[idx] if idx >= 0 else None
        if lay is None:
            return self._op_bitflip(b)
        tmpl, holes = lay
        in_hole = np.zeros(len(tmpl), dtype=bool)
        for off, nbytes, _leaf in holes:
            in_hole[off:off + nbytes] = True
        cand = np.nonzero(~in_hole)[0]
        if not len(cand):
            return self._op_bitflip(b)
        out = bytearray(b)
        i = int(cand[int(self.rng.integers(len(cand)))])
        out[i] = int(self.rng.integers(256))
        return bytes(out)

    def _op_count(self, b: bytes) -> bytes:
        """Rewrite a container count / string length field to a huge
        value — the classic length-lie allocation attack."""
        out = bytearray(b)
        for i, t in enumerate(out[:-4]):
            if t in (codec.T_DICT, codec.T_TUPLE, codec.T_LIST):
                out[i + 1:i + 5] = int(
                    self.rng.integers(1 << 16, 1 << 31)
                ).to_bytes(4, "little")
                return bytes(out)
        return self._op_bitflip(b)

    def _op_dim(self, b: bytes) -> bytes:
        """Oversize an ARRAY dim (a 4-GiB claim against a 30-byte frame)
        or its ndim byte (> _MAX_NDIM must be refused)."""
        out = bytearray(b)
        for i, t in enumerate(out[:-2]):
            if t == codec.T_ARRAY:
                if self.rng.random() < 0.3:
                    out[i + 2] = int(self.rng.integers(9, 256))  # ndim
                elif i + 7 <= len(out):
                    out[i + 3:i + 7] = int(
                        self.rng.integers(1 << 20, 1 << 32)
                    ).to_bytes(4, "little")
                return bytes(out)
        return self._op_bitflip(b)

    def _op_splice(self, b: bytes) -> bytes:
        other = self._pick()
        i = int(self.rng.integers(max(1, len(b))))
        j = int(self.rng.integers(max(1, len(other))))
        return b[:i] + other[j:]

    def _op_append(self, b: bytes) -> bytes:
        return b + self.rng.bytes(int(self.rng.integers(1, 64)))

    def _op_random(self, b: bytes) -> bytes:
        return self.rng.bytes(int(self.rng.integers(0, 96)))

    def _op_gadget(self, b: bytes) -> bytes:
        """A pickle stream whose __reduce__ would fire a sentinel: the
        restricted unpickler (transport.wire_loads) must refuse it
        BEFORE any code runs.  Half raw, half behind the codec's
        T_PICKLE fallback tag."""
        raw = pickle.dumps(_Gadget())
        if self.rng.random() < 0.5:
            return raw
        return bytes([codec.T_PICKLE]) + raw

    # -- frame / container generators -------------------------------------

    def next_frame(self) -> Tuple[bytes, str]:
        name, op = self._ops[int(self.rng.integers(len(self._ops)))]
        return op(self._pick()), name

    def next_container(self) -> Tuple[bytes, str]:
        """A FLAG_BATCH container with 1..4 sub-frames, then one
        container-level lie: a lying sub-frame length (points past the
        end), a zero-length frame, a truncated trailing header, or a
        mutated sub-payload."""
        frames = []
        for _ in range(int(self.rng.integers(1, 5))):
            body = self._pick()
            tag = Tag(instance=int(self.rng.integers(1, 8)),
                      round=int(self.rng.integers(0, 16)),
                      flag=FLAG_NORMAL)
            frames.append(_BATCH_HDR.pack(
                tag.pack() & 0xFFFFFFFFFFFFFFFF, len(body)) + body)
        buf = bytearray(b"".join(frames))
        kind = ["len_lie", "zero_len", "trunc_hdr", "sub_mutate"][
            int(self.rng.integers(4))]
        if kind == "len_lie" and len(buf) >= 12:
            buf[8:12] = int(self.rng.integers(1 << 16, 1 << 31)
                            ).to_bytes(4, "little")
        elif kind == "zero_len" and len(buf) >= 12:
            buf[8:12] = (0).to_bytes(4, "little")
        elif kind == "trunc_hdr":
            buf += self.rng.bytes(int(self.rng.integers(1, 12)))
        else:
            frame, _n = self.next_frame()
            tag = Tag(instance=1, round=0, flag=FLAG_NORMAL)
            buf += _BATCH_HDR.pack(tag.pack() & 0xFFFFFFFFFFFFFFFF,
                                   len(frame)) + frame
        return bytes(buf), f"container_{kind}"


def _account(stats: Dict[str, Any], op: str, rejected: bool) -> None:
    key = "rejected" if rejected else "consumed"
    stats[key] += 1
    stats["by_op"].setdefault(op, [0, 0])[0 if rejected else 1] += 1
    if rejected:
        _C_HOSTILE.inc()


def fuzz_codec(frames: int = 2000, seed: int = 0) -> Dict[str, Any]:
    """Hammer ``codec.loads`` (which routes non-codec bytes through the
    restricted unpickler) with mutated frames.  Gate: every frame either
    decodes or raises a CLEAN exception (never a crash/hang), the
    pickle-gadget sentinel never fires, and frames == consumed +
    rejected."""
    mut = HostileMutator(seed)
    stats: Dict[str, Any] = {"frames": frames, "consumed": 0,
                             "rejected": 0, "by_op": {}}
    fired0 = GADGET_FIRED["count"]
    for _ in range(frames):
        frame, op = mut.next_frame()
        try:
            codec.loads(frame)
        except Exception:  # noqa: BLE001 — ANY clean raise is a reject
            _account(stats, op, True)
        else:
            _account(stats, op, False)
    stats["gadget_fired"] = GADGET_FIRED["count"] - fired0
    stats["accounted"] = stats["consumed"] + stats["rejected"] == frames
    stats["ok"] = stats["accounted"] and stats["gadget_fired"] == 0
    return stats


def fuzz_split(containers: int = 1000, seed: int = 0) -> Dict[str, Any]:
    """Hammer the FLAG_BATCH splitter with lying containers, then run
    every recovered sub-frame through the codec.  Gate: the splitter
    never raises, never yields a frame extending past the container, and
    containers == consumed + rejected (rejected = the splitter dropped a
    lying suffix, visible via wire.batch_malformed)."""
    from round_tpu.runtime.transport import HostTransport

    mut = HostileMutator(seed)
    malformed = METRICS.counter("wire.batch_malformed")
    stats: Dict[str, Any] = {"frames": containers, "consumed": 0,
                             "rejected": 0, "by_op": {}, "sub_frames": 0,
                             "sub_decoded": 0}
    for _ in range(containers):
        cont, op = mut.next_container()
        rx: List[Tuple[int, Tag, memoryview]] = []
        before = malformed.value
        n = HostTransport._split_batch(1, memoryview(cont), rx)
        assert n == len(rx)
        for _src, _tag, sub in rx:
            stats["sub_frames"] += 1
            try:
                codec.loads(bytes(sub))
                stats["sub_decoded"] += 1
            except Exception:  # noqa: BLE001 — sub-frame garbage is fine
                _C_HOSTILE.inc()
        _account(stats, op, malformed.value > before)
    stats["accounted"] = (stats["consumed"] + stats["rejected"]
                          == containers)
    stats["ok"] = stats["accounted"]
    return stats


def fuzz_pump(frames: int = 2000, seed: int = 0,
              n: int = 4) -> Dict[str, Any]:
    """Hammer the C round-pump template parser (rt_pump_feed /
    rt_pump_insert) on a live native node: a real payload's template is
    registered and a lane armed, then mutated frames are fed as if from
    every peer.  Gate: the native node survives every frame, a template
    MISS never touches the mailbox, a template HIT only ever writes the
    registered hole bytes, and frames == consumed + rejected.  Returns
    ``{"skipped": True}`` without the native library."""
    from round_tpu.runtime.transport import HostTransport, native_available

    if not native_available():
        return {"skipped": True, "ok": True}
    payload = {"x": np.arange(4, dtype=np.int32), "y": np.float64(2.5)}
    clean = codec.encode(payload)
    tmpl, holes = codec.array_layout(payload)
    mut = HostileMutator(seed, corpus=[payload])
    tr = HostTransport(0)
    stats: Dict[str, Any] = {"frames": frames, "consumed": 0,
                             "rejected": 0, "by_op": {}}
    try:
        pump = tr.enable_pump(1, n, 1, 0)
        if pump is None:
            return {"skipped": True, "ok": True}
        stacked = [np.zeros((n, 4), dtype=np.int32),
                   np.zeros((n,), dtype=np.float64)]
        mask = np.zeros((1, n), dtype=np.uint8)
        count = np.zeros((1,), dtype=np.int64)
        pump.set_class(0, 0, tmpl, holes, stacked, mask=mask[0],
                       count=count, per_lane=False)
        pump.open_lane(0, 1)
        rnd = 0
        pump.arm(0, rnd, 0, n + 1, 0, 60_000, 0)
        for i in range(frames):
            if count[0] >= n - 1 or i % 64 == 63:
                # keep the lane armed at a fresh round so template HITS
                # stay possible (a full mailbox dups everything)
                rnd += 1
                pump.arm(0, rnd, 0, n + 1, 0, 60_000, 0)
            frame, op = mut.next_frame()
            sender = int(mut.rng.integers(0, n + 2))  # incl. out-of-range
            tag = Tag(instance=1, round=rnd, flag=FLAG_NORMAL)
            rc = pump.feed(sender, tag, frame)
            if rc == 1:
                _account(stats, op, False)
            else:
                # not consumed natively (template miss / bad sender):
                # the production path would decode + coerce in Python —
                # here the reject IS the accounting
                _account(stats, op, True)
        # the registered mailbox only ever held registered-hole bytes:
        # a clean frame still templates and ingests after the barrage
        pump.arm(0, rnd + 1, 0, n + 1, 0, 60_000, 0)
        rc = pump.feed(1, Tag(instance=1, round=rnd + 1,
                              flag=FLAG_NORMAL), clean)
        stats["clean_after"] = rc == 1 and bool(mask[0, 1])
        np.testing.assert_array_equal(stacked[0][1],
                                      np.arange(4, dtype=np.int32))
    finally:
        tr.close()
    stats["accounted"] = stats["consumed"] + stats["rejected"] == frames
    stats["ok"] = stats["accounted"] and stats.get("clean_after", False)
    return stats


def run_gate(frames: int = 10_000, seed: int = 0) -> Dict[str, Any]:
    """The whole gate: codec + splitter + native pump, frames split
    across the three surfaces.  ``ok`` iff every surface accounted every
    frame and no gadget fired."""
    per = max(1, frames // 3)
    out = {
        "codec": fuzz_codec(per, seed),
        "split": fuzz_split(per, seed + 1),
        # never negative (frames < 3 would hand the remainder -1 to the
        # pump, whose empty loop then fails its own accounting): every
        # surface gets at least one frame
        "pump": fuzz_pump(max(1, frames - 2 * per), seed + 2),
        "hostile_rejected": _C_HOSTILE.value,
    }
    out["ok"] = all(s.get("ok", False) for s in
                    (out["codec"], out["split"], out["pump"]))
    return out
