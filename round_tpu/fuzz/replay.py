"""Schedule artifacts: a TPU/CPU-sim finding as a portable, replayable file.

The artifact is a small JSON document naming exactly the (round, dst, src)
link events a minimized schedule drops, the VALUE events a byzantine
sender forges (schema v2, round_tpu/byz), the proposals, and the
RECORDED outcome on both worlds:

  {
    "kind": "round_tpu.fuzz.schedule", "version": 2,
    "protocol": "otr", "n": 4, "rounds": 12, "seed": 0,
    "values": [0, 1, 2, 3],
    "drops": [[r, dst, src], ...],          # off-diagonal, deliver=False
    "value_subs": [[r, dst, src, v], ...],  # v2: claimed-value forgeries
    "stale_subs": [[r, dst, src], ...],     # v2: stale-round replays
    "expected": {
      "engine": {"decided": [...], "decision": [...],
                 "decided_round": [...]},
      "host":   {"decided": [...], "decision": [...], "rounds": [...]}
    },
    "meta": {...}                            # provenance (free-form)
  }

Version 1 artifacts (drops only) load unchanged; an artifact is written
as v1 unless it carries value events, so the PR-8 regression bank stays
byte-compatible with older readers.

Replay surfaces:
  * engine — `scenarios.from_schedule` through the SAME batched evaluator
    the search used (bit-exact by construction);
  * host   — `runtime.chaos.FaultyTransport` in explicit-schedule mode
    over real sockets: in-process thread clusters (replay_host_threads,
    the fast regression form) or true multi-process clusters of
    apps/host_replica subprocesses (run_schedule_cluster).

Rounds past the schedule clamp to the LAST row on every surface (the
`from_schedule` convention), so a short artifact pins a steady-state tail.
"""

from __future__ import annotations

import functools as _functools
import json
import os
import sys
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from round_tpu.obs.metrics import METRICS

ARTIFACT_KIND = "round_tpu.fuzz.schedule"
ARTIFACT_VERSION = 2


# ---------------------------------------------------------------------------
# Schema
# ---------------------------------------------------------------------------


def make_artifact(*, protocol: str, schedule: np.ndarray,
                  values: np.ndarray, seed: int = 0,
                  value_plan: Optional[np.ndarray] = None,
                  engine_outcome: Optional[Dict[str, Any]] = None,
                  host_outcome: Optional[Dict[str, Any]] = None,
                  meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    from round_tpu.byz.adversary import VP_STALE, plan_is_trivial

    schedule = np.asarray(schedule, dtype=bool)
    T, n, n2 = schedule.shape
    if n != n2:
        raise ValueError(f"schedule must be [T, n, n], got {schedule.shape}")
    eye = np.eye(n, dtype=bool)
    if not schedule[:, eye].all():
        raise ValueError("self-delivery must be True in every round "
                         "(the engines' HO convention)")
    drops = np.argwhere(~schedule & ~eye[None, :, :])
    has_values = value_plan is not None and not plan_is_trivial(value_plan)
    art: Dict[str, Any] = {
        "kind": ARTIFACT_KIND,
        # v1 unless the artifact actually carries value events: the PR-8
        # drop-only bank keeps its wire format
        "version": ARTIFACT_VERSION if has_values else 1,
        "protocol": protocol,
        "n": int(n),
        "rounds": int(T),
        "seed": int(seed),
        "values": [int(v) for v in np.asarray(values).reshape(-1)],
        "drops": [[int(r), int(d), int(s)] for r, d, s in drops],
        "expected": {},
    }
    if has_values:
        plan = np.asarray(value_plan, dtype=np.int32)
        if plan.shape != schedule.shape:
            raise ValueError(
                f"value plan {plan.shape} != schedule {schedule.shape}")
        if np.any(plan[:, eye] != -1):
            raise ValueError("value events must be off-diagonal "
                             "(a process cannot lie to itself)")
        subs = np.argwhere(plan >= 0)
        stale = np.argwhere(plan == VP_STALE)
        art["value_subs"] = [
            [int(r), int(d), int(s), int(plan[r, d, s])]
            for r, d, s in subs]
        art["stale_subs"] = [[int(r), int(d), int(s)]
                             for r, d, s in stale]
    if engine_outcome is not None:
        art["expected"]["engine"] = engine_outcome
    if host_outcome is not None:
        art["expected"]["host"] = host_outcome
    if meta:
        art["meta"] = meta
    return art


def dump_artifact(path: str, art: Dict[str, Any]) -> None:
    if art.get("kind") != ARTIFACT_KIND:
        raise ValueError(f"not a fuzz schedule artifact: {art.get('kind')!r}")
    # write-then-rename: several replicas of one cluster can dump the
    # SAME violation path concurrently (rv/dump.py names artifacts by
    # (protocol, inst, label), not by node) — a plain open(path, "w")
    # interleaves and a reader sees torn JSON; with replace() readers
    # only ever see one writer's complete document
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as fh:
        json.dump(art, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    METRICS.counter("fuzz.exports").inc()


def load_artifact(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        art = json.load(fh)
    if art.get("kind") != ARTIFACT_KIND:
        raise ValueError(
            f"{path}: kind {art.get('kind')!r} != {ARTIFACT_KIND!r}")
    if int(art.get("version", -1)) > ARTIFACT_VERSION:
        raise ValueError(f"{path}: artifact version {art['version']} is "
                         f"newer than this tree ({ARTIFACT_VERSION})")
    n, T = int(art["n"]), int(art["rounds"])
    if len(art.get("values", [])) != n:
        raise ValueError(f"{path}: values must have n={n} entries")
    for r, d, s in art.get("drops", []):
        if not (0 <= r < T and 0 <= d < n and 0 <= s < n and d != s):
            raise ValueError(f"{path}: bad drop event {(r, d, s)}")
    for r, d, s, v in art.get("value_subs", []):
        if not (0 <= r < T and 0 <= d < n and 0 <= s < n and d != s
                and v >= 0):
            raise ValueError(f"{path}: bad value event {(r, d, s, v)}")
    for r, d, s in art.get("stale_subs", []):
        if not (0 <= r < T and 0 <= d < n and 0 <= s < n and d != s):
            raise ValueError(f"{path}: bad stale event {(r, d, s)}")
    return art


def schedule_from_artifact(art: Dict[str, Any]) -> np.ndarray:
    """[rounds, n, n] bool deliver schedule (deliver[r, dst, src])."""
    n, T = int(art["n"]), int(art["rounds"])
    sched = np.ones((T, n, n), dtype=bool)
    for r, d, s in art.get("drops", []):
        sched[r, d, s] = False
    return sched


def value_plan_from_artifact(art: Dict[str, Any]) -> Optional[np.ndarray]:
    """[rounds, n, n] int32 substitution plan (byz/adversary.py opcodes),
    or None for a drops-only (v1) artifact."""
    from round_tpu.byz.adversary import VP_NONE, VP_STALE

    subs = art.get("value_subs", [])
    stale = art.get("stale_subs", [])
    if not subs and not stale:
        return None
    n, T = int(art["n"]), int(art["rounds"])
    plan = np.full((T, n, n), VP_NONE, dtype=np.int32)
    for r, d, s, v in subs:
        plan[r, d, s] = v
    for r, d, s in stale:
        plan[r, d, s] = VP_STALE
    return plan


def _outcome_json(decided, decision, rounds_key: str, rounds) -> Dict:
    """Normalize an outcome to the artifact form: decision is null where
    undecided (never state garbage)."""
    decided = [bool(x) for x in decided]
    return {
        "decided": decided,
        "decision": [int(v) if d else None
                     for d, v in zip(decided, decision)],
        rounds_key: [int(x) for x in rounds],
    }


# ---------------------------------------------------------------------------
# Engine replay
# ---------------------------------------------------------------------------


def _target_for(art: Dict[str, Any], seed: Optional[int] = None):
    from round_tpu.fuzz.search import make_target

    return make_target(
        art["protocol"], n=int(art["n"]), horizon=int(art["rounds"]),
        seed=int(art["seed"] if seed is None else seed),
        values=np.asarray(art["values"], dtype=np.int32))


def replay_engine(art: Dict[str, Any]) -> Dict[str, Any]:
    """Run the artifact's schedule (and value plan, for v2) through the
    batched engine; returns the outcome in artifact form
    (expected.engine's schema)."""
    target = _target_for(art)
    vplan = value_plan_from_artifact(art)
    out = target.evaluate_schedules(
        schedule_from_artifact(art)[None],
        None if vplan is None else vplan[None])
    METRICS.counter("fuzz.replays").inc()
    return _outcome_json(
        np.asarray(out["decided"][0]), np.asarray(out["decision"][0]),
        "decided_round", np.asarray(out["decided_round"][0]))


def check_engine(art: Dict[str, Any]) -> tuple:
    """(ok, got): engine replay vs the recorded expected.engine outcome —
    EXACT equality; a banked artifact that stops reproducing is a
    regression (tools/soak.py fuzz rung gates on this)."""
    got = replay_engine(art)
    want = art.get("expected", {}).get("engine")
    return (want is not None and got == want), got


# ---------------------------------------------------------------------------
# Host-wire replay
# ---------------------------------------------------------------------------


@_functools.lru_cache(maxsize=None)
def _shared_algo(protocol: str):
    """ONE Algorithm object per protocol for every in-process replay: the
    host jit trio caches on the Round objects (HostRunner._round_fns), so
    sharing the instance shares the compiles across replay calls."""
    from round_tpu.apps.selector import select

    return select(protocol)


def _warm_host_round_fns(algo, n: int) -> None:
    """Compile every round class's host jit trio BEFORE the replay
    cluster starts.  In-thread replicas burning their first round
    deadlines on first-use jit compiles (serialized by the shared build
    lock) skew the early rounds, and a timing-SENSITIVE schedule then
    replays unfaithfully — observed: a 2-link LastVoting schedule that
    decides at round 7 on the engine decided at round 11 in a cold
    thread cluster.  One clean mini-cluster (one phase, generous
    deadline) pays the compiles; the jits cache on the shared Round
    objects, so the replay proper starts warm and rounds run at wire
    latency."""
    import threading as _threading

    from round_tpu.runtime.chaos import alloc_ports
    from round_tpu.runtime.host import HostRunner
    from round_tpu.runtime.transport import HostTransport

    # warm = every round class's cached trio was built at THIS group size
    # (the cache on a Round object holds one n at a time)
    if all((getattr(r, "_host_jit", None) or (None,))[0] == n
           for r in algo.rounds):
        return
    ports = alloc_ports(n)
    peers = {i: ("127.0.0.1", ports[i]) for i in range(n)}

    def node(i):
        with HostTransport(i, peers[i][1]) as tr:
            HostRunner(algo, i, peers, tr, timeout_ms=2000).run(
                {"initial_value": np.int32(0)},
                max_rounds=algo.rounds_per_phase)

    threads = [_threading.Thread(target=node, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)


def replay_host_threads(art: Dict[str, Any], *, timeout_ms: int = 250,
                        proto: str = "tcp") -> Dict[str, Any]:
    """Replay on REAL sockets in-process: n HostRunner threads, each
    behind a FaultyTransport carrying the artifact's explicit schedule.
    Returns the outcome in artifact form (expected.host's schema: per-
    replica decided / decision / rounds-to-exit)."""
    from round_tpu.runtime.chaos import FaultPlan, FaultyTransport, alloc_ports
    from round_tpu.runtime.host import HostRunner
    from round_tpu.runtime.transport import HostTransport

    n = int(art["n"])
    schedule = schedule_from_artifact(art)
    vplan = value_plan_from_artifact(art)
    algo = _shared_algo(art["protocol"])
    _warm_host_round_fns(algo, n)
    ports = alloc_ports(n)
    peers = {i: ("127.0.0.1", ports[i]) for i in range(n)}
    results: Dict[int, Any] = {}
    errors: Dict[int, BaseException] = {}

    def node(i):
        tr0 = HostTransport(i, peers[i][1], proto=proto)
        tr = FaultyTransport(tr0, FaultPlan(), n, schedule=schedule,
                             value_plan=vplan,
                             protocol=art["protocol"],
                             rounds_per_phase=algo.rounds_per_phase)
        try:
            runner = HostRunner(algo, i, peers, tr, timeout_ms=timeout_ms)
            results[i] = runner.run(
                {"initial_value": np.int32(art["values"][i])},
                max_rounds=int(art["rounds"]))
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors[i] = e
            raise
        finally:
            tr0.close()

    threads = [threading.Thread(target=node, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    if any(t.is_alive() for t in threads):
        raise RuntimeError("schedule-replay replica thread wedged")
    if errors:
        raise RuntimeError(f"schedule-replay replica errors: {errors}")
    METRICS.counter("fuzz.replays").inc()
    decided = [bool(results[i].decided) for i in range(n)]
    decision = [int(np.asarray(results[i].decision).reshape(-1)[0])
                for i in range(n)]
    rounds = [int(results[i].rounds_run) for i in range(n)]
    return _outcome_json(decided, decision, "rounds", rounds)


def run_schedule_cluster(workdir: str, artifact_path: str, *,
                         timeout_ms: int = 250, proto: str = "tcp",
                         join_timeout: float = 150.0,
                         rv: Optional[str] = None,
                         rv_gossip=False,
                         algo_opts: Optional[Dict[str, Any]] = None
                         ) -> Dict[str, Any]:
    """Replay on a REAL MULTI-PROCESS cluster: n apps/host_replica
    subprocesses, each wrapping its wire in the explicit-schedule
    FaultyTransport (--chaos-schedule; a v2 artifact's value-fault plan
    rides along automatically).  With ``rv``, each replica additionally
    runs the runtime-verification monitors at that policy (the artifact's
    proposal vector is the validity witness set) — the adversarial
    workout for round_tpu/rv: an equivocating peer must TRIP the
    agreement monitor, never crash the driver.  Returns the outcome in
    artifact form plus the raw per-replica summaries."""
    import subprocess

    from round_tpu.runtime.chaos import alloc_ports, cluster_env

    art = load_artifact(artifact_path)
    n = int(art["n"])
    os.makedirs(workdir, exist_ok=True)
    ports = alloc_ports(n)
    peer_arg = ",".join(f"127.0.0.1:{p}" for p in ports)
    env = cluster_env()

    def argv(i: int):
        a = [sys.executable, "-m", "round_tpu.apps.host_replica",
             "--id", str(i), "--peers", peer_arg,
             "--algo", art["protocol"],
             "--value", str(int(art["values"][i])),
             "--timeout-ms", str(timeout_ms),
             "--max-rounds", str(int(art["rounds"])),
             "--proto", proto,
             "--chaos-schedule", artifact_path]
        for k, v in (algo_opts or {}).items():
            a += ["--algo-opt", f"{k}={v}"]
        if rv:
            a += ["--rv", rv,
                  "--rv-dir", os.path.join(workdir, f"rv-{i}")]
            # rv_gossip: True = every replica gossips decisions; a
            # collection of node ids scopes it (the byz workout keeps
            # the equivocation VICTIM silent so its early decision
            # cannot convert the honest camp before it decides)
            if rv_gossip is True or (rv_gossip and i in rv_gossip):
                a += ["--rv-gossip"]
        return a

    procs = [subprocess.Popen(argv(i), stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, env=env)
             for i in range(n)]
    outs = []
    try:
        for i, p in enumerate(procs):
            stdout, stderr = p.communicate(timeout=join_timeout)
            if p.returncode != 0:
                raise RuntimeError(
                    f"replica {i} failed (rc={p.returncode}): "
                    f"{stderr[-2000:]}")
            outs.append(json.loads(stdout.strip().splitlines()[-1]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                try:
                    p.communicate(timeout=10)
                except Exception:  # noqa: BLE001 - best-effort reap
                    pass
    METRICS.counter("fuzz.replays").inc()
    got = _outcome_json(
        [o["decided"] for o in outs],
        [o["decision"] if o["decision"] is not None else -1 for o in outs],
        "rounds", [o["rounds"] for o in outs])
    got_raw: Dict[str, Any] = dict(got)
    got_raw["summaries"] = outs
    return got_raw


def check_host(art: Dict[str, Any], *, threads: bool = True,
               workdir: Optional[str] = None, timeout_ms: int = 250
               ) -> tuple:
    """(ok, got): host-wire replay vs the recorded expected.host outcome —
    EXACT equality on decided/decision/rounds."""
    if threads:
        got = replay_host_threads(art, timeout_ms=timeout_ms)
    else:
        if workdir is None:
            raise ValueError("multi-process replay needs a workdir")
        res = run_schedule_cluster(
            workdir, _artifact_tmp(art, workdir), timeout_ms=timeout_ms)
        got = {k: res[k] for k in ("decided", "decision", "rounds")}
    want = art.get("expected", {}).get("host")
    return (want is not None and got == want), got


def _artifact_tmp(art: Dict[str, Any], workdir: str) -> str:
    path = os.path.join(workdir, "artifact.json")
    dump_artifact(path, art)
    return path
