"""Coverage-guided fault-schedule search on the batched engine.

The ROADMAP item-5 subsystem: treat the per-(seed, src, dst, round) fault
schedule as a GENOME, evaluate thousands of candidate schedules per jitted
dispatch as engine scenario lanes, score them by spec-derived objectives
(undecided-at-horizon, agreement margin, rounds-to-decide, validity slack),
and evolve toward the schedules that hurt.  A winning schedule is
delta-debugged down to a minimal reproducer and exported as a portable JSON
artifact that replays byte-identically on the real multi-process host wire
(runtime/chaos.FaultyTransport explicit-schedule mode) — a finding made on
TPU/CPU-sim becomes a deterministic host regression test.

Modules:
  genome     — schedule tensors + per-family mutation/crossover operators
  objectives — lane scores computed inside the jitted evaluation step
  search     — the generational loop with coverage/novelty bookkeeping
  minimize   — batched delta-debugging down to a minimal link set
  replay     — artifact schema + engine / host-wire replay harnesses

Entry point: ``python -m round_tpu.apps.fuzz_cli`` (docs/FUZZING.md).
"""

from round_tpu.fuzz.genome import Population  # noqa: F401
from round_tpu.fuzz.search import FuzzResult, make_target, search  # noqa: F401
