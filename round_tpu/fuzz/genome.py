"""Fault-schedule genomes: the fault model as an evolvable tensor.

A genome is one structured fault schedule — exactly the per-scenario
parameterisation the fused engine already batches (`engine.fast.FaultMix`:
crash sets, partition sides, a rotating suppressed coordinator, an
iid-omission threshold, hash salts) plus a byzantine-silence membership
mask.  Because every field is data, a POPULATION of genomes is one pytree
with a leading [P] axis, and evaluating all P candidates is one vmapped
engine dispatch over the scenario axis (fuzz/search.py).

Three invariants make any genome portable across the whole system:

  * engine-runnable: `row_sampler` extends `scenarios.from_fault_params`
    (the FaultMix replay bridge) with the byzantine-silence term, so a
    genome runs under the general engine's `run_phases` unchanged;
  * schedule-expressible: `row_schedule` materializes the genome into an
    explicit ``[T, n, n]`` HO schedule, bit-identical to what the sampler
    draws (`scenarios.from_schedule` replays it) — the form fuzz/minimize.py
    delta-debugs and fuzz/replay.py exports;
  * host-replayable: the materialized schedule drives
    `runtime.chaos.FaultyTransport` in explicit-schedule mode, dropping the
    same (src, dst, round) frames on a real multi-process wire.

Mutation/crossover operate PER FAULT FAMILY (omission, crash, partition,
coordinator-down, byzantine-silence, link salts) so recombination keeps
families coherent instead of splicing unrelated tensor rows.
"""

from __future__ import annotations

import dataclasses
import functools as _functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from round_tpu.engine import scenarios
from round_tpu.engine.fast import FaultMix

# Byzantine-silence stream constant: the per-(round, link) "is this
# receiver in the silenced half" Bernoulli(1/2) draws from the SAME
# counter-based link hash as every other family (scenarios.link_bernoulli's
# mix), under a stream constant disjoint from runtime/chaos.py's so one
# salt pair yields independent schedules per family.
STREAM_BYZ = 0xB5F0D1E3

# omission mutation cap: p8 < 232 (~91% loss) keeps the all-drop schedule
# out of the search space — "drop everything" degrades every protocol and
# teaches nothing; the interesting schedules are sparse (see severity)
P8_CAP = 232


def value_cap_default(n: int) -> int:
    """The byzantine-VALUE mutation envelope: at most ``(n - 1) // 3``
    liars (the classic n > 3f budget).  Protocols whose declared
    envelope is BENIGN (crash/omission — OTR, LastVoting) get cap 0 in
    the cross-check's in-envelope sweeps (byz/crosscheck.py): a value
    adversary is outside their fault model by definition."""
    return max(0, (n - 1) // 3)


#: the family blocks crossover inherits wholesale (field name -> leaves)
FAMILIES: Dict[str, tuple] = {
    "omission": ("p8",),
    "crash": ("crashed", "crash_round"),
    "partition": ("side", "heal_round"),
    "rotate": ("rotate_down",),
    "byz": ("byz",),
    "byzval": ("byz_value", "equiv_p8", "stale_p8"),
    "salts": ("salt0", "salt1"),
}

_FIELDS = ("crashed", "crash_round", "side", "heal_round", "rotate_down",
           "p8", "salt0", "salt1", "byz", "byz_value", "equiv_p8",
           "stale_p8")

#: value-adversary fields absent from a (pre-value-genome) row dict get
#: these zero defaults — PR-8 rows, banked artifacts and hand-written
#: test rows stay valid currency
_VALUE_FIELDS = ("byz_value", "equiv_p8", "stale_p8")


@dataclasses.dataclass
class Population:
    """[P] fault-schedule genomes as host-side numpy arrays.

    Leaves mirror engine.fast.FaultMix (leading axis [P]) plus
    ``byz [P, n] bool`` — byzantine-silence membership (a byzantine process
    is silent toward a hash-drawn half of the receivers each round:
    scenarios.byzantine_silence's mask family, made replayable).
    Genetic operators live host-side (numpy); evaluation converts to jnp
    leaves once per dispatch (`leaves()`).
    """

    crashed: np.ndarray      # [P, n] bool
    crash_round: np.ndarray  # [P] int32
    side: np.ndarray         # [P, n] int32
    heal_round: np.ndarray   # [P] int32
    rotate_down: np.ndarray  # [P] int32
    p8: np.ndarray           # [P] int32
    salt0: np.ndarray        # [P] int32
    salt1: np.ndarray        # [P] int32
    byz: np.ndarray          # [P, n] bool
    byz_value: np.ndarray    # [P, n] bool — value adversaries (byz/)
    equiv_p8: np.ndarray     # [P] int32 — equivocation threshold /256
    stale_p8: np.ndarray     # [P] int32 — stale-replay threshold /256

    @property
    def size(self) -> int:
        return self.crashed.shape[0]

    @property
    def n(self) -> int:
        return self.crashed.shape[1]

    def mix(self) -> FaultMix:
        """The FaultMix view (drops byz-silence; carries the value
        tensors) — what engine.fast consumes."""
        return FaultMix(
            crashed=jnp.asarray(self.crashed),
            crash_round=jnp.asarray(self.crash_round),
            side=jnp.asarray(self.side),
            heal_round=jnp.asarray(self.heal_round),
            rotate_down=jnp.asarray(self.rotate_down),
            p8=jnp.asarray(self.p8),
            salt0=jnp.asarray(self.salt0),
            salt1=jnp.asarray(self.salt1),
            byz_value=jnp.asarray(self.byz_value),
            equiv_p8=jnp.asarray(self.equiv_p8),
            stale_p8=jnp.asarray(self.stale_p8),
        )

    def leaves(self) -> tuple:
        """The per-field tuple vmapped evaluation maps over (axis 0)."""
        return tuple(getattr(self, f) for f in _FIELDS)

    def row(self, i: int) -> Dict[str, np.ndarray]:
        """Genome i as a field dict (artifact/minimizer currency)."""
        return {f: np.asarray(getattr(self, f)[i]) for f in _FIELDS}

    def take(self, idx) -> "Population":
        idx = np.asarray(idx)
        return Population(**{f: np.asarray(getattr(self, f))[idx]
                             for f in _FIELDS})

    @classmethod
    def from_rows(cls, rows) -> "Population":
        rows = [dict(r) for r in rows]
        for r in rows:
            _fill_value_fields(r)
        return cls(**{f: np.stack([np.asarray(r[f]) for r in rows])
                      for f in _FIELDS})

    @classmethod
    def from_mix(cls, mix: FaultMix, byz: Optional[np.ndarray] = None
                 ) -> "Population":
        # np.array(copy=True): jax device arrays view as read-only numpy,
        # and the genetic operators mutate in place
        kw = {f: np.array(getattr(mix, f))
              for f in _FIELDS
              if f != "byz" and getattr(mix, f, None) is not None}
        P, n = kw["crashed"].shape
        kw["byz"] = (np.zeros((P, n), dtype=bool) if byz is None
                     else np.asarray(byz, dtype=bool))
        kw.setdefault("byz_value", np.zeros((P, n), dtype=bool))
        kw.setdefault("equiv_p8", np.zeros((P,), dtype=np.int32))
        kw.setdefault("stale_p8", np.zeros((P,), dtype=np.int32))
        return cls(**kw)


def _fill_value_fields(row: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """In-place: default the value-adversary fields of a row dict to
    zeros (the truthful adversary) — pre-value-genome rows stay valid."""
    n = int(np.asarray(row["crashed"]).shape[-1])
    row.setdefault("byz_value", np.zeros((n,), dtype=bool))
    row.setdefault("equiv_p8", np.int32(0))
    row.setdefault("stale_p8", np.int32(0))
    return row


# ---------------------------------------------------------------------------
# Engine bridge: genome -> HO sampler / explicit schedule
# ---------------------------------------------------------------------------


def byz_silence(byz, salt0, salt1, r, n: int) -> jnp.ndarray:
    """[n(recv), n(send)] bool — True where a byzantine sender is silent
    toward this receiver in round r: byz membership AND the per-(round,
    link) hash half (p = 1/2), diagonal excluded (a process always hears
    itself — the engines' self-delivery convention)."""
    i = jnp.arange(n, dtype=jnp.uint32)
    idx = i[:, None] * jnp.uint32(n) + i[None, :]
    z = idx * jnp.uint32(scenarios.LINK_GOLD) + jnp.asarray(salt0).astype(
        jnp.uint32)
    z = z ^ (jnp.asarray(r).astype(jnp.uint32)
             * jnp.uint32(scenarios.LINK_RMIX)
             + jnp.asarray(salt1).astype(jnp.uint32)
             + jnp.uint32(STREAM_BYZ))
    half = (scenarios._mix32(z) & jnp.uint32(0xFF)) < jnp.uint32(128)
    eye = jnp.eye(n, dtype=bool)
    return jnp.asarray(byz)[None, :] & half & ~eye


def row_sampler(n: int, crashed, crash_round, side, heal_round, rotate_down,
                p8, salt0, salt1, byz=None):
    """HO sampler ``(key, r) -> [n, n] bool`` for ONE genome — the
    engine-runnable form.  Exactly `scenarios.from_fault_params` (the
    FaultMix hash-mode replay formula) with the byzantine-silence term
    ANDed in; every argument may be a traced leaf, so `jax.vmap` over a
    population's leaves evaluates all P genomes in one dispatch."""
    base = scenarios.from_fault_params(
        n, crashed, crash_round, side, heal_round, rotate_down, p8,
        salt0, salt1)

    def sample(key, r):
        ho = base(key, r)
        if byz is not None:
            ho = ho & ~byz_silence(byz, salt0, salt1, r, n)
        return ho

    return sample


def schedule_fn(n: int, rounds: int):
    """Jittable ``leaves -> [rounds, n, n] bool`` materializer: the genome
    as an explicit HO schedule (what `scenarios.from_schedule` replays and
    fuzz/minimize.py shrinks).  Bit-identical to `row_sampler`'s draws —
    both go through the one ho_link_mask formula."""

    def materialize(crashed, crash_round, side, heal_round, rotate_down,
                    p8, salt0, salt1, byz):
        samp = row_sampler(n, crashed, crash_round, side, heal_round,
                           rotate_down, p8, salt0, salt1, byz)
        return jax.vmap(lambda r: samp(None, r))(
            jnp.arange(rounds, dtype=jnp.int32))

    return materialize


#: the fields schedule_fn consumes — the DELIVERY half of the genome;
#: the value-adversary fields materialize separately (row_value_plan)
_SCHEDULE_FIELDS = ("crashed", "crash_round", "side", "heal_round",
                    "rotate_down", "p8", "salt0", "salt1", "byz")


@_functools.lru_cache(maxsize=None)
def _jitted_schedule_fn(n: int, rounds: int):
    return jax.jit(schedule_fn(n, rounds))


def row_schedule(row: Dict[str, np.ndarray], rounds: int) -> np.ndarray:
    """Materialize one genome row dict into a numpy [rounds, n, n] bool
    deliver schedule (jit cached per (n, rounds))."""
    n = int(np.asarray(row["crashed"]).shape[-1])
    out = _jitted_schedule_fn(n, rounds)(
        *[jnp.asarray(row[f]) for f in _SCHEDULE_FIELDS])
    return np.asarray(out)


def row_value_plan(row: Dict[str, np.ndarray], rounds: int,
                   num_values: int) -> np.ndarray:
    """Materialize one genome row's VALUE-fault fields into the explicit
    [rounds, n, n] int32 substitution plan (byz/adversary.py opcodes) —
    bit-identical to the hash-mode draws the vmapped evaluation makes,
    exactly as row_schedule is for the delivery mask."""
    from round_tpu.byz import adversary as _adv

    row = _fill_value_fields(dict(row))
    return _adv.value_plan(row, rounds, num_values)


# ---------------------------------------------------------------------------
# Severity: how much fault a genome spends
# ---------------------------------------------------------------------------


def severity(pop: Population, horizon: int) -> np.ndarray:
    """[P] float — normalized fault intensity, the search's spending
    meter.  The objective subtracts a small multiple of this, so of two
    schedules that hurt equally the search prefers the SPARSER one (and
    the trivial "break everything" corner scores below a surgical
    schedule) — the same pressure fuzz/minimize.py applies exhaustively."""
    h = max(1, horizon)
    n = pop.n
    crash_frac = pop.crashed.mean(axis=1) * np.clip(
        (h - pop.crash_round) / h, 0.0, 1.0)
    # a partition only costs while it is active and actually splits
    split = (pop.side.max(axis=1) != pop.side.min(axis=1))
    part_frac = split * np.clip(pop.heal_round / h, 0.0, 1.0)
    # value adversaries: rent scales with membership AND lie intensity —
    # a surgical one-liar/one-round equivocation must outscore a
    # spray-everything liar that hurts equally (the minimizer pressure)
    value_frac = pop.byz_value.mean(axis=1) * np.clip(
        (pop.equiv_p8 + pop.stale_p8) / 256.0, 0.0, 1.0)
    return (pop.p8 / 256.0
            + crash_frac
            + 0.5 * part_frac
            + 0.25 * (pop.rotate_down > 0)
            + 0.5 * pop.byz.mean(axis=1)
            + 0.75 * value_frac).astype(np.float64)


# ---------------------------------------------------------------------------
# Seeding, mutation, crossover
# ---------------------------------------------------------------------------


def seed_population(seed: int, P: int, n: int, horizon: int,
                    p_drop: float = 0.25) -> Population:
    """The initial population: `engine.fast.standard_mix`'s four-family
    split (the hardened flagship workload) with byz off and every 8th row
    zeroed to fault-free — elites must EARN their faults against a clean
    baseline present in every generation's gene pool."""
    from round_tpu.engine.fast import standard_mix

    key = jax.random.PRNGKey(seed)
    mix = standard_mix(key, P, n, p_drop=p_drop,
                       heal_round=min(5, max(1, horizon // 2)))
    pop = Population.from_mix(mix)
    clean = np.arange(P) % 8 == 7
    pop.crashed[clean] = False
    pop.side[clean] = 0
    pop.heal_round[clean] = 0
    pop.rotate_down[clean] = 0
    pop.p8[clean] = 0
    return pop


def _flip_one_capped(rng: np.random.Generator, mask_rows: np.ndarray,
                     rows: np.ndarray, cap: int) -> None:
    """Toggle one random bit per selected row of a [P, n] bool matrix,
    refusing toggles that would push the row's popcount past `cap` (the
    resilience envelope: mass-crash/mass-byzantine rows are trivial
    findings, not interesting ones)."""
    n = mask_rows.shape[1]
    for i in rows:
        j = int(rng.integers(n))
        if mask_rows[i, j] or mask_rows[i].sum() < cap:
            mask_rows[i, j] = ~mask_rows[i, j]


def mutate(rng: np.random.Generator, pop: Population, horizon: int,
           rate: float = 0.9,
           value_cap: Optional[int] = None) -> Population:
    """Per-family point mutations: each row draws ~1-2 of the seven
    family operators.  ``value_cap`` bounds the byzantine-VALUE
    membership per row (default ``(n-1)//3`` — the envelope cap; 0 keeps
    the value adversary OUT of the gene pool entirely, the benign-model
    in-envelope sweeps of byz/crosscheck.py).  Returns a NEW population
    (inputs untouched)."""
    P, n = pop.size, pop.n
    out = pop.take(np.arange(P))  # deep copy via fancy-index
    h = max(1, horizon)
    if value_cap is None:
        value_cap = value_cap_default(n)
    ops = rng.random((P, 7)) < (rate / 3.0)

    r = np.flatnonzero(ops[:, 0])      # omission intensity
    out.p8[r] = np.clip(out.p8[r] + rng.integers(-48, 49, r.size),
                        0, P8_CAP).astype(np.int32)

    r = np.flatnonzero(ops[:, 1])      # crash set / onset
    _flip_one_capped(rng, out.crashed, r, cap=max(1, n // 3))
    out.crash_round[r] = np.clip(
        out.crash_round[r] + rng.integers(-2, 3, r.size), 0, h - 1
    ).astype(np.int32)

    r = np.flatnonzero(ops[:, 2])      # partition side / heal horizon
    for i in r:
        out.side[i, int(rng.integers(n))] ^= 1
    out.heal_round[r] = np.clip(
        out.heal_round[r] + rng.integers(-3, 4, r.size), 0, h
    ).astype(np.int32)

    r = np.flatnonzero(ops[:, 3])      # coordinator-down period
    choices = np.array([0, 1, 2, 4], dtype=np.int32)
    out.rotate_down[r] = rng.choice(choices, r.size)

    r = np.flatnonzero(ops[:, 4])      # byzantine-silence membership
    _flip_one_capped(rng, out.byz, r, cap=max(1, n // 3))

    r = np.flatnonzero(ops[:, 5])      # link-pattern reroll
    out.salt0[r] = rng.integers(0, 2**32, r.size, dtype=np.uint32) \
        .astype(np.int64).astype(np.int32)
    out.salt1[r] = rng.integers(0, 2**32, r.size, dtype=np.uint32) \
        .astype(np.int64).astype(np.int32)

    r = np.flatnonzero(ops[:, 6])      # value-adversary family
    if value_cap > 0:
        _flip_one_capped(rng, out.byz_value, r, cap=value_cap)
        out.equiv_p8[r] = np.clip(
            out.equiv_p8[r] + rng.integers(-64, 65, r.size), 0, P8_CAP
        ).astype(np.int32)
        out.stale_p8[r] = np.clip(
            out.stale_p8[r] + rng.integers(-48, 49, r.size), 0, P8_CAP
        ).astype(np.int32)
    else:
        # cap 0 = the benign fault model: the family stays OFF, and any
        # inherited value genes are scrubbed (crossover with a capped
        # parent must not smuggle lies into an in-envelope sweep)
        out.byz_value[:] = False
        out.equiv_p8[:] = 0
        out.stale_p8[:] = 0
    # over-cap rows (a raised-then-lowered cap, hand-seeded rows) are
    # trimmed back to the envelope, highest-index members first
    over = np.flatnonzero(out.byz_value.sum(axis=1) > max(value_cap, 0))
    for i in over:
        members = np.flatnonzero(out.byz_value[i])
        out.byz_value[i, members[max(value_cap, 0):]] = False
    return out


def crossover(rng: np.random.Generator, pop: Population,
              parents_a: np.ndarray, parents_b: np.ndarray) -> Population:
    """Family-block recombination: each child inherits every leaf of a
    fault family wholesale from parent A or B (coin per family) — the
    partition's (side, heal_round) pair, the crash family's (set, onset)
    pair etc. stay coherent across recombination."""
    a, b = pop.take(parents_a), pop.take(parents_b)
    child = a.take(np.arange(a.size))
    for fam, fields in FAMILIES.items():
        from_b = rng.random(a.size) < 0.5
        for f in fields:
            arr = getattr(child, f)
            arr[from_b] = getattr(b, f)[from_b]
    return child
