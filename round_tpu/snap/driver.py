"""SnapDriver: the one object the serving drivers hold.

LaneDriver (runtime/lanes.py) and the HostRunner instance loop
(runtime/host.py) each construct ONE SnapDriver per run and touch it at
exactly three seams:

  * ``after_round(inst, r, leaves)`` — a round boundary completed on
    this replica: sample if the deterministic policy says so (all
    replicas agree on the rounds, snap/sample.py), ship or join
    locally;
  * ``on_frame(sender, tag, raw)`` — a FLAG_SNAP frame arrived: the
    collector replica joins it; anyone else drops it (a mis-addressed
    sample is wire noise, not an error);
  * ``flush()`` — the serving loop's housekeeping tick on the collector
    replica: expire part-cut deadlines, run the batched audit dispatch
    over assembled cuts, and hand back the instance ids the POLICY says
    to shed (halt raises SnapViolation out of here; log returns
    nothing).

Everything else — policy, budget, digests, epoch fencing, audit
compilation, artifact dumping — lives behind those three calls, so the
drivers' wiring stays the rv-hook size.
"""

from __future__ import annotations

import time as _time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from round_tpu.runtime.log import get_logger
from round_tpu.snap.audit import (
    CutAuditor, SnapConfig, SnapRuntime, audit_program,
)
from round_tpu.snap.collect import SnapCollector, envelope_f_max
from round_tpu.snap.sample import SampleEmitter, SnapPolicy

log = get_logger("snap")

# flush cadence: deadlines and audit batching are coarse-grained — a
# serving tick is not.  The driver calls flush() every loop iteration;
# this floor keeps the poll/audit machinery off the hot path between
# samples (assembled cuts still audit promptly: the interval is well
# under the cut deadline).
_FLUSH_INTERVAL_S = 0.05


class SnapDriver:
    """One replica's snapshot subsystem (module docstring)."""

    def __init__(self, cfg: SnapConfig, algo, *, node: int, n: int,
                 seed: int, max_rounds: int, transport,
                 value_schedule: str = "mixed", base_value: int = 0,
                 admission=None, view=None):
        self.cfg = cfg
        self.algo = algo
        self.node, self.n = node, n
        self.view = view
        self._removed = False
        self.is_collector = (node == cfg.collector)
        self.runtime = SnapRuntime(cfg, node=node, n=n, seed=seed,
                                   max_rounds=max_rounds)
        policy = SnapPolicy(every_k=cfg.every_k, seed=seed,
                            budget_bytes_per_s=cfg.budget_bytes_per_s)
        self.collector: Optional[SnapCollector] = None
        self.auditor: Optional[CutAuditor] = None
        if self.is_collector:
            self.collector = SnapCollector(
                n, envelope_f=envelope_f_max(algo, n),
                deadline_ms=cfg.cut_deadline_ms,
                epoch=(view.epoch if view is not None else 0),
                bank_dir=cfg.bank_dir, protocol=cfg.protocol)
            self.auditor = CutAuditor(self._compile_program(n),
                                      self.runtime, self.collector)
        if view is not None:
            # epoch fencing + resize recompile ride the SAME observer
            # fan-out as PeerHealth.resize and the fleet rebalance —
            # one view move, every subscriber (view.py add_observer).
            # Registered on EVERY replica: the emitters' proposal-row
            # width tracks n too, not just the collector's join state.
            view.add_observer(self.on_view_change)
        self.emitter = SampleEmitter(
            node, policy, transport, cfg.collector,
            sink=self.collector, admission=admission)
        self._value_rows: Dict[int, List[int]] = {}
        self._value_args = (value_schedule, base_value)
        self._last_flush = 0.0

    # -- emission ----------------------------------------------------------

    def _epoch(self) -> int:
        return self.view.epoch if self.view is not None else 0

    def note_client_value(self, inst: int, scalar: int) -> None:
        """A client-proposed instance (the fleet's uniform-proposal
        contract): the proposal row is the client scalar at every pid —
        deterministic cluster-wide, like the schedule."""
        self._value_rows[inst & 0xFFFF] = [int(scalar)] * self.n
        while len(self._value_rows) > 8192:
            # oldest-first eviction (the _DONE_CAP discipline), never a
            # wholesale clear: a live instance's row must survive the
            # cap — a cleared row falls back to the schedule value,
            # which DIFFERS from the client's proposal and would record
            # values-mismatch divergences on a clean serve shard.  The
            # driver forgets rows on lane retire, so the map is bounded
            # by live lanes in steady state; this cap is the backstop.
            self._value_rows.pop(next(iter(self._value_rows)))

    def forget_value(self, inst: int) -> None:
        """The instance retired: its proposal row is dead bookkeeping
        (emission only happens for live lanes, always before retire)."""
        self._value_rows.pop(inst & 0xFFFF, None)

    def due(self, inst: int, r: int) -> bool:
        """Cheap policy pre-check for callers whose sample EXTRACTION
        itself costs (the lane driver's per-lane state-row copies):
        emit() re-checks, so skipping the call on a not-due round is
        pure savings, never a behavior change."""
        return self.emitter.policy.due(inst, r)

    def _values(self, inst: int) -> List[int]:
        row = self._value_rows.get(inst & 0xFFFF)
        if row is not None:
            return row
        from round_tpu.runtime.host import _schedule_value

        vs, bv = self._value_args
        return [_schedule_value(vs, bv, pid, inst)
                for pid in range(self.n)]

    def after_round(self, inst: int, r: int,
                    leaves: Sequence[np.ndarray]) -> None:
        """One completed round boundary on this replica (post-update
        state rows, zero extra dispatches — engine/executor.py
        lane_sample_rows is the lane driver's extraction contract)."""
        if self._removed:
            return  # left the group: this pid now names someone else
        self.emitter.emit(inst, r, self._epoch(), list(leaves),
                          self._values(inst))

    # -- collection --------------------------------------------------------

    def on_frame(self, sender: int, tag, raw) -> None:
        if self.collector is not None:
            self.collector.on_frame(sender, tag, raw)

    def on_view_change(self, renames, n: int) -> None:
        """One membership move (auto-registered on the ViewManager when
        one exists; callable manually by driver-less tests): track the
        new n on the emitter side, follow this replica's RENAME (a
        remove compacts the surviving pids — a sample stamped the old
        pid while the transport speaks the new one is refused by the
        collector's sender check as a forged row), and on the collector
        replica sync the epoch fence to the MANAGER'S epoch (an
        adopt_wire catch-up can jump it by more than one move — a bare
        increment would refuse every sample forever), re-derive the
        envelope tolerance, and RECOMPILE the audit program at the new
        n — a program compiled at the old n would silently skip every
        post-resize cut through the auditor's geometry guard while
        cuts_audited kept counting."""
        self.n = n
        self.runtime.n = n   # violation artifacts record the CUT's n
        if renames:
            new_node = renames.get(self.node, self.node)
            if new_node is None:
                # this replica left the group: nothing further to emit
                # (the loop unwinds; a late after_round must not stamp
                # a pid that now names someone else)
                self._removed = True
            else:
                self.node = new_node
                self.emitter.node = new_node
                self.runtime.node = new_node
        # the collector ROLE rides the pid, not the process: whoever
        # holds cfg.collector in the CURRENT view assembles cuts
        if self.collector is None and not self._removed \
                and self.node == self.cfg.collector:
            self.is_collector = True
            self.collector = SnapCollector(
                n, envelope_f=envelope_f_max(self.algo, n),
                deadline_ms=self.cfg.cut_deadline_ms,
                epoch=(self.view.epoch if self.view is not None else 0),
                bank_dir=self.cfg.bank_dir, protocol=self.cfg.protocol)
            self.auditor = CutAuditor(self._compile_program(n),
                                      self.runtime, self.collector)
            self.emitter.sink = self.collector
            return
        if self.collector is not None \
                and (self._removed or self.node != self.cfg.collector):
            # lost the role: flush nothing (the epoch fence would drop
            # the part-cuts anyway) and go back to shipping samples to
            # whoever holds the collector pid now
            self.is_collector = False
            self.collector = None
            self.auditor = None
            self.emitter.sink = None
            return
        if self.collector is not None:
            self.collector.on_view_change(
                renames, n,
                epoch=(self.view.epoch if self.view is not None
                       else None),
                envelope_f=envelope_f_max(self.algo, n))
        if self.auditor is not None:
            # swap in place: the auditor's counters and the runtime's
            # violation bank survive the resize
            self.auditor.program = self._compile_program(n)

    def _compile_program(self, n: int):
        program = audit_program(self.algo, n)
        if program is None:
            log.info("snap: %s carries no cut-auditable formulas — "
                     "digest/divergence layer only",
                     type(self.algo).__name__)
        elif program.skipped:
            log.info("snap: auditing %s; not cut-evaluable: %s",
                     program.labels, program.skipped)
        return program

    # -- audit -------------------------------------------------------------

    def flush(self, force: bool = False) -> List[int]:
        """Collector housekeeping: expire deadlines, audit assembled
        cuts, return instance ids to shed.  Cheap no-op off the
        collector replica and between flush intervals.  ALWAYS ships
        buffered samples first (every replica; covers the pump-send
        path, whose native round flush bypasses the Python per-peer
        buffers the emitter coalesces into)."""
        self.emitter.flush()
        if self.collector is None:
            return []
        now = _time.monotonic()
        if not force and now - self._last_flush < _FLUSH_INTERVAL_S \
                and not self.collector._ready:
            return []
        self._last_flush = now
        if force:
            # end-of-run: resolve every pending part-cut NOW (the
            # envelope tolerance decides partial vs dropped)
            self.collector.poll(now + self.cfg.cut_deadline_ms / 1000.0
                                + 1.0)
        else:
            self.collector.poll(now)
        return self.auditor.audit(self.collector.take())

    # -- stats -------------------------------------------------------------

    def fill_stats(self, stats_out: Optional[Dict[str, Any]]) -> None:
        if stats_out is None:
            return
        self.runtime.fill_stats(stats_out)
        stats_out["snap_samples"] = stats_out.get("snap_samples", 0) \
            + self.emitter.samples
        stats_out["snap_sample_bytes"] = \
            stats_out.get("snap_sample_bytes", 0) \
            + self.emitter.sample_bytes
        stats_out["snap_skipped"] = stats_out.get("snap_skipped", 0) \
            + self.emitter.skipped
        if self.collector is not None:
            stats_out["snap_cuts"] = stats_out.get("snap_cuts", 0) \
                + self.collector.cuts
            stats_out["snap_partial_cuts"] = \
                stats_out.get("snap_partial_cuts", 0) \
                + self.collector.partial
            stats_out.setdefault("snap_divergences", []).extend(
                self.collector.divergences)
            if self.auditor is not None:
                stats_out["snap_cuts_audited"] = \
                    stats_out.get("snap_cuts_audited", 0) \
                    + self.auditor.cuts_audited
