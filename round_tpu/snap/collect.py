"""Cut assembly: round-aligning per-replica samples into global states.

A CUT is the global state of one consensus instance at one round
boundary of one view epoch: the n replicas' sampled state rows stacked
into ``[n, ...]`` leaves.  Round-consistency is free (snap/sample.py
module docstring); what this module adds is the bookkeeping that keeps
it sound on a real wire:

  * ROUND ALIGNMENT — samples join a cut only on an exact
    ``(epoch, instance, round)`` match.  There is no "close enough":
    a sample from round r+1 is a different global state.
  * EPOCH FENCING — the collector tracks the CURRENT view epoch (wired
    to ``ViewManager.add_observer``): samples stamped another epoch are
    refused (``snap.stale_epoch``) and every pending partial cut is
    flushed on a membership change (``snap.epoch_flushes``) — renames
    and resizes must never mis-join rows from two different groups.
  * MISSING-CONTRIBUTOR TOLERANCE — a cut whose deadline passes with at
    least ``n - f`` contributors (f from the protocol's declared fault
    envelope, the rv/license.py parser) is kept as a PARTIAL cut: its
    digests are banked and its divergence checks run, but the
    full-state formula audit is SKIPPED (``snap.partial_unaudited``) —
    a quantified threshold formula over n processes is not evaluable
    from n-1 rows, and a weaker substitute would false-positive or
    false-negative.  Below n - f the cut is dropped
    (``snap.incomplete_cuts``).
  * DIVERGENCE FORENSICS — every sample's digest is re-verified against
    its decoded state (in-flight corruption) and against any duplicate
    claim for the same (epoch, inst, round, node) coordinate
    (equivocation: one node, two states, one round).  Assembled cuts
    bank their digest vector, and a bounded per-instance digest history
    feeds the violation artifacts — the round a replica's state started
    diverging is in the dump, before the decision plane ever disagrees.

Cuts can also be BANKED to disk (``bank_dir``) as codec-encoded
``.snapcut`` files for offline audit (apps/snap_cli.py).
"""

from __future__ import annotations

import dataclasses
import os
import time as _time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from round_tpu.obs.metrics import METRICS
from round_tpu.obs.trace import TRACE
from round_tpu.runtime import codec
from round_tpu.runtime.log import get_logger
from round_tpu.snap.sample import blob_digest, decode_sample

log = get_logger("snap")

_C_CUTS = METRICS.counter("snap.cuts")
_C_PARTIAL = METRICS.counter("snap.partial_cuts")
_C_PARTIAL_UNAUDITED = METRICS.counter("snap.partial_unaudited")
_C_INCOMPLETE = METRICS.counter("snap.incomplete_cuts")
_C_STALE_EPOCH = METRICS.counter("snap.stale_epoch")
_C_EPOCH_FLUSH = METRICS.counter("snap.epoch_flushes")
_C_DIVERGENCE = METRICS.counter("snap.divergences")
_C_BANKED = METRICS.counter("snap.cuts_banked")

# bounded per-(inst) digest-history depth for forensics: enough rounds
# to see where a divergence started, small enough to never matter
_HISTORY_ROUNDS = 32
# and bounded ACROSS instances (oldest-first): a serve shard processes
# an unbounded instance stream — per-instance forensics state must not
# accumulate for the lifetime of the collector
_HISTORY_INSTANCES = 256
# pending part-cut cap: a hostile peer spraying novel (inst, round)
# coordinates must exhaust a counter, not the collector's memory
_PENDING_CAP = 4096


@dataclasses.dataclass
class Cut:
    """One assembled global state: ``state`` leaves are [n, ...] stacked
    in pid order; ``present`` marks contributors (a partial cut's absent
    rows are zero-filled and MUST NOT be audited); ``digests`` is the
    per-replica digest vector (None where absent)."""

    epoch: int
    inst: int
    round: int
    n: int
    state: List[np.ndarray]
    present: np.ndarray               # [n] bool
    digests: List[Optional[bytes]]
    values: np.ndarray                # [n] int64 proposal row
    wall: float

    @property
    def full(self) -> bool:
        return bool(self.present.all())

    @property
    def missing(self) -> int:
        return int(self.n - self.present.sum())


class SnapCollector:
    """Assemble samples into cuts; the audit side drains ``take()``.

    ``envelope_f`` is the missing-contributor tolerance (derive it from
    the protocol's fault envelope via ``envelope_f_max``); ``epoch`` is
    the CURRENT view epoch, advanced by ``on_view_change`` (registered
    on ViewManager.add_observer by the drivers)."""

    def __init__(self, n: int, *, envelope_f: int = 0,
                 deadline_ms: int = 3000, epoch: int = 0,
                 bank_dir: Optional[str] = None,
                 protocol: Optional[str] = None):
        self.n = n
        self.envelope_f = envelope_f
        self.deadline_ms = deadline_ms
        self.epoch = epoch
        self.bank_dir = bank_dir
        self.protocol = protocol
        # (inst, round) -> {node: (leaves, digest, values)} + first-seen
        self._pending: Dict[Tuple[int, int], Dict[int, Any]] = {}
        self._first_seen: Dict[Tuple[int, int], float] = {}
        self._ready: List[Cut] = []
        # divergence forensics: inst -> {round: {node: digest}}, bounded
        self._history: Dict[int, Dict[int, Dict[int, bytes]]] = {}
        self.divergences: List[Dict[str, Any]] = []
        self.cuts = 0
        self.partial = 0

    # -- ingest ------------------------------------------------------------

    def on_frame(self, sender: int, tag, raw) -> bool:
        """One FLAG_SNAP wire frame: decode, verify, join.  Returns True
        when the sample joined a cut slot."""
        s = decode_sample(raw)
        if s is None:
            return False
        if s["node"] != sender:
            # a sample must speak for its own sender — a forged node id
            # would let one peer fabricate another's state row
            _C_DIVERGENCE.inc()
            self._note_divergence(tag.instance, tag.round, sender,
                                  "sender-mismatch",
                                  claimed=s["node"])
            return False
        # in-flight integrity: the digest was computed over the blob
        # bytes at the emitter; re-digest the blob that ACTUALLY arrived
        # (no re-encode — the check covers exactly the wire bytes)
        got = blob_digest(s["blob"])
        if got != s["digest"]:
            _C_DIVERGENCE.inc()
            self._note_divergence(tag.instance, tag.round, sender,
                                  "digest-mismatch")
            return False
        return self.add_sample(sender, tag.instance, tag.round,
                               tag.call_stack & 0xFF, s["state"],
                               s["values"], s["digest"])

    def add_sample(self, node: int, inst: int, r: int, epoch_byte: int,
                   leaves: List[np.ndarray], values: np.ndarray,
                   digest: bytes, local: bool = False) -> bool:
        """Join one verified sample.  ``local`` marks the collector
        replica's own contribution (already canonical — no re-verify)."""
        if epoch_byte != (self.epoch & 0xFF):
            # cross-epoch fencing: this sample belongs to another group
            _C_STALE_EPOCH.inc()
            return False
        if not 0 <= node < self.n:
            return False
        # duplicate-claim check against the HISTORY, not just the
        # pending slot: a conflicting re-send arriving AFTER the cut
        # assembled (slot popped) is still equivocation — checking only
        # pending state would let it open a fresh part-cut and quietly
        # expire as "incomplete" (forensics keeps the first claim; the
        # conflict is the finding)
        seen = self._history.get(int(inst), {}).get(int(r), {}).get(node)
        if seen is not None:
            if seen != digest:
                _C_DIVERGENCE.inc()
                self._note_divergence(inst, r, node, "equivocation")
            return False
        key = (int(inst), int(r))
        slot = self._pending.get(key)
        if slot is None:
            if len(self._pending) >= _PENDING_CAP:
                self._expire_oldest()
            slot = self._pending[key] = {}
            self._first_seen[key] = _time.monotonic()
        slot[node] = (leaves, digest, np.asarray(values, dtype=np.int64))
        self._bank_history(int(inst), int(r), node, digest)
        if TRACE.enabled:
            TRACE.emit("snap_sample", node=node, inst=int(inst),
                       round=int(r), epoch=self.epoch, local=local)
        if len(slot) == self.n:
            self._assemble(key, partial=False)
        return True

    # -- lifecycle ---------------------------------------------------------

    def poll(self, now: Optional[float] = None) -> None:
        """Expire pending part-cuts past the deadline: enough
        contributors (>= n - f) becomes a PARTIAL cut, fewer is dropped."""
        now = _time.monotonic() if now is None else now
        expired = [k for k, t0 in self._first_seen.items()
                   if (now - t0) * 1000.0 >= self.deadline_ms]
        for key in expired:
            self._expire(key)

    def on_view_change(self, renames: Dict[int, Optional[int]], n: int,
                       *, epoch: Optional[int] = None,
                       envelope_f: Optional[int] = None) -> None:
        """ViewManager observer: a membership change fences the epoch —
        every pending part-cut is flushed (its group no longer exists as
        sampled) and the expected contributor count re-derives.

        ``epoch`` is the MANAGER'S epoch after the move (SnapDriver
        passes it): an adopt_wire catch-up can jump the view by more
        than one epoch in a single notification, so a bare increment
        would permanently desync this fence from the emitters' stamps
        and refuse every sample thereafter.  Without a manager
        (driver-less callers) the increment is exact — one call, one
        move.  ``envelope_f`` re-derives the missing-contributor
        tolerance at the new n (SnapDriver recomputes it from the
        protocol's declared envelope)."""
        flushed = len(self._pending)
        if flushed:
            _C_EPOCH_FLUSH.inc(flushed)
            log.info("snap: view change flushed %d pending part-cut(s)",
                     flushed)
        self._pending.clear()
        self._first_seen.clear()
        self._history.clear()
        self.n = n
        if envelope_f is not None:
            self.envelope_f = envelope_f
        self.epoch = self.epoch + 1 if epoch is None else int(epoch)

    def take(self) -> List[Cut]:
        """Drain assembled cuts (the auditor's intake)."""
        out, self._ready = self._ready, []
        return out

    def pending_count(self) -> int:
        return len(self._pending)

    # -- internals ---------------------------------------------------------

    def _bank_history(self, inst: int, r: int, node: int,
                      digest: bytes) -> None:
        hist = self._history.setdefault(inst, {})
        # first claim wins, forever: a later overwrite would let an
        # equivocator scrub its honest digest out of the forensics
        # trajectory after the cut assembled
        hist.setdefault(r, {}).setdefault(node, digest)
        while len(hist) > _HISTORY_ROUNDS:
            del hist[min(hist)]
        while len(self._history) > _HISTORY_INSTANCES:
            # oldest-first across instances (dict insertion order) —
            # bounded forensics on an unbounded serve stream
            del self._history[next(iter(self._history))]

    def digest_history(self, inst: int) -> List[Dict[str, Any]]:
        """The bounded digest trajectory of one instance — the forensics
        block violation artifacts carry: per sampled round, each
        contributor's digest hex."""
        hist = self._history.get(int(inst), {})
        return [{"round": r,
                 "digests": {str(n): d.hex()
                             for n, d in sorted(hist[r].items())}}
                for r in sorted(hist)]

    def _note_divergence(self, inst, r, node, kind, **extra) -> None:
        rec = {"inst": int(inst), "round": int(r), "node": int(node),
               "kind": kind, **extra}
        self.divergences.append(rec)
        if TRACE.enabled:
            TRACE.emit("snap_divergence", node=int(node), inst=int(inst),
                       round=int(r), kind=kind)
        log.warning("snap: DIVERGENCE %s at inst=%s round=%s node=%s",
                    kind, inst, r, node)

    def _expire_oldest(self) -> None:
        key = min(self._first_seen, key=self._first_seen.get)
        self._expire(key)

    def _expire(self, key) -> None:
        slot = self._pending.get(key)
        if slot is None:
            return
        if len(slot) >= self.n - self.envelope_f and len(slot) > 0:
            self._assemble(key, partial=True)
        else:
            del self._pending[key]
            del self._first_seen[key]
            _C_INCOMPLETE.inc()
            log.debug("snap: dropped incomplete cut %s (%d/%d rows)",
                      key, len(slot), self.n)

    def _assemble(self, key, partial: bool) -> None:
        inst, r = key
        slot = self._pending.pop(key)
        self._first_seen.pop(key, None)
        # the proposal row is deterministic cluster-wide (the schedule /
        # the uniform client value), so contributors must agree on it —
        # but the BASELINE must be the majority row, never whichever
        # sample arrived first: a liar controls its own send timing, so
        # first-wins would let it win the race and have every honest
        # contributor recorded as the "mismatching" node
        by_row: Dict[bytes, List[int]] = {}
        for node, (_leaves, _digest, vals) in slot.items():
            by_row.setdefault(
                np.asarray(vals, dtype=np.int64).tobytes(), []
            ).append(node)
        majority = max(by_row.values(), key=len)
        if 2 * len(majority) <= len(slot):
            # no strict majority: attribution is impossible — drop the
            # cut as one unattributed divergence, never audit it
            _C_DIVERGENCE.inc()
            self._note_divergence(inst, r, -1, "values-split",
                                  rows=len(by_row))
            _C_INCOMPLETE.inc()
            return
        values = slot[majority[0]][2]
        some_node = majority[0]
        like = slot[some_node][0]
        present = np.zeros((self.n,), dtype=bool)
        digests: List[Optional[bytes]] = [None] * self.n
        state = [np.zeros((self.n,) + x.shape, dtype=x.dtype)
                 for x in like]
        ok = True
        for node, (leaves, digest, vals) in slot.items():
            if len(leaves) != len(like) or any(
                    a.shape != b.shape or a.dtype != b.dtype
                    for a, b in zip(leaves, like)):
                # a structurally alien row cannot stack — count it as a
                # divergence (same coordinate, incompatible state) and
                # drop the whole cut rather than audit garbage
                _C_DIVERGENCE.inc()
                self._note_divergence(inst, r, node, "shape-mismatch")
                ok = False
                break
            present[node] = True
            digests[node] = digest
            for dst, src in zip(state, leaves):
                dst[node] = src
            if not np.array_equal(vals, values):
                _C_DIVERGENCE.inc()
                self._note_divergence(inst, r, node, "values-mismatch")
                ok = False
                break
        if not ok:
            _C_INCOMPLETE.inc()
            return
        cut = Cut(epoch=self.epoch, inst=int(inst), round=int(r),
                  n=self.n, state=state, present=present,
                  digests=digests, values=values,
                  wall=_time.time())
        self.cuts += 1
        _C_CUTS.inc()
        if partial:
            self.partial += 1
            _C_PARTIAL.inc()
        if TRACE.enabled:
            TRACE.emit("snap_cut", node=-1, inst=int(inst),
                       round=int(r), epoch=self.epoch,
                       missing=cut.missing, partial=partial)
        if self.bank_dir is not None:
            try:
                bank_cut(self.bank_dir, cut, protocol=self.protocol)
                _C_BANKED.inc()
            except Exception as e:  # noqa: BLE001 — banking is forensics,
                log.warning("snap: cut bank failed: %s", e)  # not serving
        self._ready.append(cut)


# ---------------------------------------------------------------------------
# banked cut files (apps/snap_cli.py offline audit)
# ---------------------------------------------------------------------------


def bank_cut(bank_dir: str, cut: Cut, protocol: Optional[str] = None
             ) -> str:
    """Write one cut as a ``.snapcut`` file — the codec encoding itself
    (dogfooding the wire format: the offline reader IS codec.decode),
    write-then-rename like every artifact in this tree."""
    os.makedirs(bank_dir, exist_ok=True)
    path = os.path.join(
        bank_dir, f"cut-e{cut.epoch}-i{cut.inst}-r{cut.round}.snapcut")
    doc = codec.encode({
        "kind": "round_tpu.snap.cut",
        "protocol": protocol or "",
        "epoch": cut.epoch, "inst": cut.inst, "round": cut.round,
        "n": cut.n,
        "present": np.asarray(cut.present),
        "digests": [d if d is not None else b"" for d in cut.digests],
        "values": cut.values,
        "state": cut.state,
        "wall": float(cut.wall),
    })
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        fh.write(doc)
    os.replace(tmp, path)
    return path


def load_cut(path: str) -> Tuple[Cut, str]:
    """Read one banked ``.snapcut`` back; returns (cut, protocol)."""
    with open(path, "rb") as fh:
        doc = codec.decode(fh.read())
    if doc.get("kind") != "round_tpu.snap.cut":
        raise ValueError(f"{path}: not a snapcut file")
    cut = Cut(
        epoch=int(doc["epoch"]), inst=int(doc["inst"]),
        round=int(doc["round"]), n=int(doc["n"]),
        state=[np.array(x) for x in doc["state"]],
        present=np.array(doc["present"], dtype=bool),
        digests=[bytes(d) if len(d) else None for d in doc["digests"]],
        values=np.array(doc["values"], dtype=np.int64),
        wall=float(doc["wall"]),
    )
    if cut.present.shape != (cut.n,) or len(cut.digests) != cut.n:
        raise ValueError(f"{path}: inconsistent cut geometry")
    return cut, str(doc.get("protocol", ""))


def envelope_f_max(algo, n: int) -> int:
    """The missing-contributor tolerance from the protocol's DECLARED
    fault envelope (core/algorithm.py fault_envelope, parsed by the
    rv/license.py grammar): f_max = (n-1)//K for ``n > K·f``.  No
    declared envelope = zero tolerance (refuse to guess)."""
    env = getattr(algo, "fault_envelope", None)
    if not env:
        return 0
    try:
        from round_tpu.rv.license import parse_envelope

        return max(0, (n - 1) // parse_envelope(env))
    except ValueError:
        return 0
