"""A deliberately broken round INVISIBLE to every per-lane monitor.

The rv/fixtures.py idea one tier up: the runtime-verification fixtures
break the decision plane (agreement / validity / irrevocability), which
the fused lane monitors catch.  This fixture breaks a FULL-STATE
invariant while keeping the decision plane spotless — the exact class
of bug PR 12 classified offline and round_tpu/snap exists to catch on
live traffic:

  ``snap-broken-conservation`` — OTR's shape, but from round 1 on every
  process silently corrupts its ESTIMATE ``x`` to a fabricated value no
  process ever proposed (9900 + pid: outside the mod-5 schedule domain
  and distinct per pid, so no accidental quorum forms), and NOBODY EVER
  DECIDES.  Every decision-plane monitor is vacuously satisfied — no
  decision means agreement, validity and irrevocability hold by
  implication — while OTR's invariant chain (Otr.scala:94-120) is
  system-wide false: ``keep_init`` ("every estimate is some process's
  initial value") fails in every chain member the moment the corruption
  lands.  Only an evaluator holding the GLOBAL state can see it; a
  round-consistent cut is exactly that (tests/test_snap.py pins the
  end-to-end catch with the rv monitors provably silent on the same
  run).

Selector-registered (``snap-broken-conservation``) so violation
artifacts replay through the standard fuzz_cli surfaces.  A test
fixture, not a protocol: never deploy it.
"""

from __future__ import annotations

import jax.numpy as jnp

from round_tpu.core.rounds import Round, RoundCtx, broadcast
from round_tpu.models.otr import OtrState
from round_tpu.ops.mailbox import Mailbox
from round_tpu.rv.fixtures import _BrokenConsensus


class _ConservationBreakRound(Round):
    """OTR's send, a corrupting update, no decisions ever."""

    def send(self, ctx: RoundCtx, state: OtrState):
        return broadcast(ctx, state.x)

    def update(self, ctx: RoundCtx, state: OtrState,
               mbox: Mailbox) -> OtrState:
        # from round 1 on: the estimate silently becomes a value NO
        # process proposed — keep_init breaks, nothing else moves
        fabricated = (9900 + ctx.id).astype(state.x.dtype)
        x = jnp.where(ctx.r >= 1, fabricated, state.x)
        # never decide, never exit early: the decision plane stays
        # spotless (and vacuously monitor-clean) for the whole horizon
        return state.replace(x=x)


FIXTURES = {
    "snap-broken-conservation": _ConservationBreakRound,
}


def select_fixture(name: str):
    return _BrokenConsensus(FIXTURES[name]())
