"""Batched full-state auditing: the offline formulas, pointed at
production cuts.

PR 12's monitor compiler deliberately classifies the full-state Spec
formulas (invariants, safety_predicate, round_invariants — everything
``spec_formulas`` scopes "offline") OUT of the live lane monitors: no
single replica can evaluate a formula that quantifies over all n
processes' state.  A round-consistent cut (snap/collect.py) IS that
global state, so this module compiles the offline formulas into ONE
jitted vmapped evaluator over batches of cuts — the PR 8 fuzz-evaluator
trick (evaluate a population per dispatch) pointed at live serving
state instead of fuzz genomes.

What is auditable on a single cut is narrower than on a recorded trace,
and the compiler is explicit about the split (the rv/compile.py
discipline):

  * formulas over ``state`` (+ ``init``, reconstructed below) — YES:
    the invariant chain, offline safety properties (OTR's Integrity);
  * formulas needing ``old`` (the previous round's state) or the HO
    matrix (safety_predicate constrains the executing round's HO) — NO:
    a cut holds one instant; these stay with check_trace and the fuzz
    objectives, and the program records each exclusion with its reason
    (``AuditProgram.skipped``) so docs and stats can say exactly what a
    clean audit does NOT cover.

``init`` reconstruction: the init snapshot is deterministic in the
proposal row every sample carries (the same determinism the rv validity
witness and the chaos harness lean on) — ``make_init_state`` per pid,
cached per proposal row, so formulas like OTR's ``keep_init`` audit
without any extra wire traffic.

The invariant chain audits as ONE slot — the disjunction, matching
check_trace's ``any_invariant`` steady state (chain progress means
individual invariants legitimately fail; NO invariant holding is the
violation).  Verdicts are pinned against the eager reference twin
``spec/check.py:check_cut`` in tests/test_snap.py.

Violations flow through the PR 12 pipeline (rv/dump.py): a fuzz-replay
artifact with ``meta.rv`` naming the formula — ``fuzz_cli replay``
reproduces it bit-exactly — plus the digest-trajectory forensics block,
honoring the same halt | shed | log policies.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from round_tpu.obs.metrics import METRICS
from round_tpu.obs.trace import TRACE
from round_tpu.runtime.log import get_logger
from round_tpu.rv.dump import (
    POLICIES, RvConfig, RvViolation, dump_violation,
)
from round_tpu.spec.check import _eval_formula, spec_formulas
from round_tpu.spec.dsl import Env

log = get_logger("snap")

_C_AUDITED = METRICS.counter("snap.cuts_audited")
_C_DISPATCHES = METRICS.counter("snap.audit_dispatches")
_C_VIOLATIONS = METRICS.counter("snap.violations")
_C_DUMPS = METRICS.counter("snap.dumps")
_C_CHECKS = METRICS.counter("snap.checks")


class SnapViolation(RvViolation):
    """A full-state formula failed on a live cut under the ``halt``
    policy.  Subclasses RvViolation so every existing halt surface
    (host_replica's exit-3 path, the fleet's failure drain) handles a
    snapshot halt identically."""


@dataclasses.dataclass
class SnapConfig:
    """Driver-facing snapshot switches (host_replica --snap /
    fleet serve --snap).

    policy:     halt | shed | log — what a cut violation does (the rv
                vocabulary; shed retires the violating instance on the
                collector replica, where the verdict lives).
    protocol:   selector name, so violation artifacts replay
                (None = events/counters only).
    dump_dir:   artifact directory (None = no artifacts).
    schedule_path: the --chaos-schedule artifact in force, copied into
                dumps so replays run the same wire (rv/dump.py).
    every_k:    sampling period in rounds (snap/sample.py policy).
    collector:  the pid that assembles and audits cuts (its own samples
                join locally; everyone else ships FLAG_SNAP frames).
    budget_bytes_per_s: sample-traffic token bucket (0 = unbudgeted).
    cut_deadline_ms: how long a part-cut waits for missing contributors
                before the envelope tolerance resolves it.
    bank_dir:   directory for banked ``.snapcut`` files (offline audit
                via apps/snap_cli.py; None = no banking).
    bank_engine: record expected.engine into violation artifacts at
                dump time (the rv bank_engine semantics).
    max_dumps:  artifact cap per driver.
    """

    policy: str = "log"
    protocol: Optional[str] = None
    dump_dir: Optional[str] = None
    schedule_path: Optional[str] = None
    every_k: int = 4
    collector: int = 0
    budget_bytes_per_s: int = 256 << 10
    cut_deadline_ms: int = 3000
    bank_dir: Optional[str] = None
    bank_engine: bool = True
    max_dumps: int = 8

    def __post_init__(self):
        if self.policy not in POLICIES:
            raise ValueError(
                f"snap policy must be one of {POLICIES}, "
                f"got {self.policy!r}")
        if self.every_k < 1:
            raise ValueError(f"every_k must be >= 1, got {self.every_k}")

    def rv_dump_config(self) -> RvConfig:
        """The dump-pipeline view of this config: snap shares rv's
        artifact writer verbatim (ONE schema, ONE replay path)."""
        return RvConfig(
            policy="log",  # the POLICY is acted on here, never in dump
            protocol=self.protocol, dump_dir=self.dump_dir,
            schedule_path=self.schedule_path,
            bank_engine=self.bank_engine, max_dumps=self.max_dumps)


@dataclasses.dataclass(frozen=True)
class _Slot:
    """One audit verdict slot: the chain disjunction, one offline
    property, or one round-invariant group (phase-gated)."""

    label: str
    kind: str            # "chain" | "property" | "round_invariant"
    formulas: Tuple
    group: int = -1


@dataclasses.dataclass
class AuditProgram:
    """One algorithm's compiled cut-audit set at group size ``n``:
    verdict slots + the exclusions, the state treedef the cut leaves
    unflatten through, and whether any slot needs the reconstructed
    init snapshot."""

    algo: Any
    n: int
    rounds_per_phase: int
    treedef: Any
    n_leaves: int
    slots: Tuple[_Slot, ...]
    skipped: Dict[str, str]
    needs_init: bool
    _jit_cache: Dict[int, Callable] = dataclasses.field(
        default_factory=dict, repr=False)
    _init_cache: Dict[Tuple, List[np.ndarray]] = dataclasses.field(
        default_factory=dict, repr=False)

    @property
    def labels(self) -> List[str]:
        return [s.label for s in self.slots]

    # -- evaluation --------------------------------------------------------

    def _check_one(self, state_tree, init_tree, r):
        """ok[F] on ONE cut — the fused verdict term the batched
        evaluator vmaps (the same comparisons as the eager
        spec/check.py:check_cut, slot for slot)."""
        env = Env(state=state_tree, n=self.n, old=None, init0=init_tree,
                  ho=None, r=jnp.asarray(r, jnp.int32) + 1)
        oks = []
        for s in self.slots:
            if s.kind == "chain":
                oks.append(jnp.any(jnp.stack([
                    _eval_formula(f, env, lab)
                    for lab, f in s.formulas])))
            elif s.kind == "round_invariant":
                applies = (jnp.asarray(r, jnp.int32)
                           % self.rounds_per_phase) == s.group
                ok = jnp.all(jnp.stack([
                    _eval_formula(f, env, lab) for lab, f in s.formulas]))
                oks.append(jnp.where(applies, ok, True))
            else:
                lab, f = s.formulas[0]
                oks.append(jnp.asarray(_eval_formula(f, env, lab)))
        return jnp.stack(oks)

    def _batch_fn(self, c: int) -> Callable:
        """The jitted evaluator for a pow2-padded batch of ``c`` cuts —
        ONE dispatch audits every formula over every cut (the fuzz
        evaluator discipline; cache per padded size so steady-state
        serving never recompiles)."""
        fn = self._jit_cache.get(c)
        if fn is None:
            def run(state_leaves, init_leaves, rs):
                def one(leaves, inits, r):
                    st = jax.tree_util.tree_unflatten(self.treedef,
                                                      leaves)
                    init = (jax.tree_util.tree_unflatten(self.treedef,
                                                         inits)
                            if self.needs_init else None)
                    return self._check_one(st, init, r)
                return jax.vmap(one)(state_leaves, init_leaves, rs)

            fn = self._jit_cache[c] = jax.jit(run)
        return fn

    def init_rows(self, values: np.ndarray) -> List[np.ndarray]:
        """The [n, ...] init snapshot reconstructed from one proposal
        row (deterministic; cached per row — schedules draw from a tiny
        domain and fleet clients propose uniformly)."""
        from round_tpu.core.rounds import RoundCtx
        from round_tpu.runtime.host import instance_io

        key = tuple(int(v) for v in values)
        got = self._init_cache.get(key)
        if got is None:
            if len(self._init_cache) >= 256:
                self._init_cache.clear()
            rows = []
            for pid in range(self.n):
                ctx = RoundCtx(id=np.int32(pid), n=self.n, r=np.int32(0))
                st = self.algo.make_init_state(
                    ctx, instance_io(self.algo, int(values[pid])))
                rows.append([np.asarray(x)
                             for x in jax.tree_util.tree_leaves(st)])
            got = [np.stack([rows[p][i] for p in range(self.n)])
                   for i in range(len(rows[0]))]
            self._init_cache[key] = got
        return got

    def check_batch(self, states: List[List[np.ndarray]],
                    inits: List[Optional[List[np.ndarray]]],
                    rs: List[int]) -> np.ndarray:
        """ok[C, F] over ``C`` cuts in one (pow2-padded) dispatch."""
        c = len(states)
        pad = 1
        while pad < c:
            pad *= 2
        idx = list(range(c)) + [0] * (pad - c)
        stacked = [np.stack([states[i][leaf] for i in idx])
                   for leaf in range(self.n_leaves)]
        if self.needs_init:
            init_stacked = [np.stack([inits[i][leaf] for i in idx])
                            for leaf in range(self.n_leaves)]
        else:
            # zero-footprint placeholder: the jitted fn never touches it
            init_stacked = [np.zeros((pad, 0)) for _ in
                            range(self.n_leaves)]
        r_arr = np.asarray([rs[i] for i in idx], dtype=np.int32)
        ok = np.asarray(self._batch_fn(pad)(stacked, init_stacked,
                                            r_arr))
        _C_DISPATCHES.inc()
        return ok[:c]


def audit_program(algo, n: int) -> Optional[AuditProgram]:
    """Compile ``algo``'s cut-audit program, or None when there is
    nothing to audit (no Spec, or no offline formula is cut-evaluable —
    lvb's spec=None byte workload still gets the digest/divergence layer,
    just no formula dispatch).

    Classification is by ABSTRACT PROBE (the roundlint discipline):
    each offline formula is eval_shape'd against the [n, ...] abstract
    state — a formula that reaches for ``old`` or the HO matrix raises
    the dsl's explicit ValueError and is excluded WITH its reason; one
    that reaches for ``init`` is retried with the reconstructed init
    snapshot and marks the program ``needs_init``."""
    spec = getattr(algo, "spec", None)
    if spec is None:
        return None
    enum = spec_formulas(spec)
    offline = [e for e in enum if e.scope == "offline"
               and e.kind != "safety_predicate"]
    skipped: Dict[str, str] = {}
    for e in enum:
        if e.kind == "safety_predicate":
            # constrains the EXECUTING round's HO (check_trace evaluates
            # it against the pre-state and that round's matrix): not a
            # statement about one instant, never cut-evaluable
            skipped[e.label] = "safety_predicate constrains the " \
                "executing round's HO matrix (trace-only)"
    if not offline:
        return None
    try:
        state_abs, treedef, n_leaves = _abstract_state(algo, n)
    except Exception as e:  # noqa: BLE001 — no probeable state, no audit
        log.warning("snap: cannot probe %s state for auditing: %s",
                    type(algo).__name__, e)
        return None

    def probe(e) -> Tuple[bool, bool, str]:
        """(auditable, needs_init, reason)."""
        for with_init in (False, True):
            try:
                jax.eval_shape(
                    lambda st, r: jnp.asarray(_eval_formula(
                        e.formula,
                        Env(state=st, n=n, old=None,
                            init0=st if with_init else None,
                            ho=None, r=r + 1),
                        e.label)),
                    state_abs, jnp.int32(0))
                return True, with_init, ""
            except ValueError as err:
                if not with_init and "init snapshot" in str(err):
                    continue  # retry with the reconstructed init
                return False, False, str(err)
            except Exception as err:  # noqa: BLE001 — field typos etc.
                return False, False, str(err)
        return False, False, "unreachable"

    slots: List[_Slot] = []
    needs_init = False
    inv = [e for e in offline if e.kind == "invariant"]
    if inv:
        probes = [probe(e) for e in inv]
        if all(p[0] for p in probes):
            needs_init |= any(p[1] for p in probes)
            slots.append(_Slot(
                label="invariants (chain)", kind="chain",
                formulas=tuple((e.label, e.formula) for e in inv)))
        else:
            why = next(p[2] for p in probes if not p[0])
            skipped["invariants (chain)"] = (
                f"chain member not cut-evaluable: {why}")
    for e in offline:
        if e.kind == "property":
            ok, ni, why = probe(e)
            if ok:
                needs_init |= ni
                slots.append(_Slot(label=e.label, kind="property",
                                   formulas=((e.label, e.formula),)))
            else:
                skipped[e.label] = why
    groups = sorted({e.group for e in offline
                     if e.kind == "round_invariant"})
    for g in groups:
        members = [e for e in offline
                   if e.kind == "round_invariant" and e.group == g]
        probes = [probe(e) for e in members]
        if all(p[0] for p in probes):
            needs_init |= any(p[1] for p in probes)
            slots.append(_Slot(
                label=f"round_invariants[{g}]", kind="round_invariant",
                formulas=tuple((e.label, e.formula) for e in members),
                group=g))
        else:
            why = next(p[2] for p in probes if not p[0])
            skipped[f"round_invariants[{g}]"] = why
    if not slots:
        return None
    return AuditProgram(
        algo=algo, n=n, rounds_per_phase=algo.rounds_per_phase,
        treedef=treedef, n_leaves=n_leaves, slots=tuple(slots),
        skipped=skipped, needs_init=needs_init)


def _abstract_state(algo, n: int):
    """The [n, ...] abstract global state + treedef from one eager
    init-state probe (the instance_io contract, rv/compile._probe_shapes'
    sibling)."""
    from round_tpu.core.rounds import RoundCtx
    from round_tpu.runtime.host import instance_io

    ctx = RoundCtx(id=np.int32(0), n=n, r=np.int32(0))
    st = algo.make_init_state(ctx, instance_io(algo, 0))
    leaves, treedef = jax.tree_util.tree_flatten(st)
    abs_leaves = [jax.ShapeDtypeStruct((n,) + np.asarray(x).shape,
                                       np.asarray(x).dtype)
                  for x in leaves]
    return (jax.tree_util.tree_unflatten(treedef, abs_leaves), treedef,
            len(leaves))


class SnapRuntime:
    """Per-driver violation bookkeeping for the snapshot tier — the
    RvRuntime shape under the snap.* vocabulary, sharing rv/dump.py's
    artifact writer (ONE schema, ONE replay path, meta.rv naming the
    formula with a snapshot ``surface`` marker and the digest-trajectory
    forensics block)."""

    def __init__(self, cfg: SnapConfig, *, node: int, n: int, seed: int,
                 max_rounds: int):
        self.cfg = cfg
        self.dump_cfg = cfg.rv_dump_config()
        self.node, self.n = node, n
        self.seed, self.max_rounds = seed, max_rounds
        self.checks = 0
        self.violations: List[Dict[str, Any]] = []
        self.artifacts: List[str] = []
        self._dumped: set = set()

    def note_checks(self, k: int) -> None:
        self.checks += k
        _C_CHECKS.inc(k)

    def violate(self, *, inst: int, round_: int, label: str,
                values: List[int], observed: Dict[str, Any]) -> str:
        """Record one failed cut formula; raises SnapViolation under
        ``halt`` (artifact attached), else returns 'shed' | 'log'."""
        _C_VIOLATIONS.inc()
        rec = {"inst": int(inst), "round": int(round_), "formula": label,
               "where": "snapshot-audit", "policy": self.cfg.policy}
        if TRACE.enabled:
            TRACE.emit("snap_violation", node=self.node, inst=int(inst),
                       round=int(round_), formula=label,
                       policy=self.cfg.policy)
        log.error("node %d: SNAP VIOLATION inst=%d round=%d %s",
                  self.node, inst, round_, label)
        key = (int(inst), label)
        artifact = None
        if key not in self._dumped and len(self.artifacts) \
                < self.cfg.max_dumps:
            self._dumped.add(key)
            artifact = dump_violation(
                self.dump_cfg, n=self.n, seed=self.seed,
                rounds=self.max_rounds, values=values, node=self.node,
                inst=inst, round_=round_, label=label, observed=observed)
            if artifact is not None:
                rec["artifact"] = artifact
                self.artifacts.append(artifact)
                _C_DUMPS.inc()
        self.violations.append(rec)
        if self.cfg.policy == "halt":
            raise SnapViolation(
                label, inst, round_,
                artifact if artifact is not None
                else (self.artifacts[-1] if self.artifacts else None))
        return self.cfg.policy

    def fill_stats(self, stats_out: Optional[Dict[str, Any]]) -> None:
        if stats_out is None:
            return
        stats_out["snap_checks"] = stats_out.get("snap_checks", 0) \
            + self.checks
        stats_out.setdefault("snap_violations", []).extend(
            self.violations)
        stats_out.setdefault("snap_artifacts", []).extend(self.artifacts)


class CutAuditor:
    """Drain assembled cuts through the batched evaluator and act on
    failures.  ``audit`` returns the instance ids the caller must SHED
    (the policy verdicts it cannot act on itself); halt raises out of
    the runtime."""

    def __init__(self, program: Optional[AuditProgram],
                 runtime: SnapRuntime, collector):
        self.program = program
        self.rt = runtime
        self.collector = collector
        self.cuts_audited = 0

    def audit(self, cuts: List) -> List[int]:
        shed: List[int] = []
        if not cuts:
            return shed
        from round_tpu.snap.collect import _C_PARTIAL_UNAUDITED

        full = [c for c in cuts if c.full]
        for c in cuts:
            # every consumed cut counts — partial cuts engage the
            # digest/divergence layer even though the formula dispatch
            # must skip them (collect.py module docstring)
            self.cuts_audited += 1
            _C_AUDITED.inc()
            if not c.full:
                _C_PARTIAL_UNAUDITED.inc()
        if not full or self.program is None:
            return shed
        prog = self.program
        states, inits, rs, kept = [], [], [], []
        for c in full:
            if len(c.state) != prog.n_leaves or c.n != prog.n:
                continue  # alien geometry (a pre-resize leftover that
                # outlived the epoch fence): not auditable
            states.append(c.state)
            inits.append(prog.init_rows(c.values)
                         if prog.needs_init else None)
            rs.append(c.round)
            kept.append(c)
        if not kept:
            return shed
        ok = prog.check_batch(states, inits, rs)
        self.rt.note_checks(ok.size)
        for c, row in zip(kept, ok):
            for fidx in np.nonzero(~row)[0]:
                observed = {
                    "surface": "snapshot-audit",
                    "epoch": c.epoch,
                    "digests": {str(i): (d.hex() if d else None)
                                for i, d in enumerate(c.digests)},
                    "divergence": self.collector.digest_history(c.inst)
                    if self.collector is not None else [],
                }
                action = self.rt.violate(
                    inst=c.inst, round_=c.round,
                    label=prog.labels[int(fidx)],
                    values=[int(v) for v in c.values],
                    observed=observed)
                if action == "shed":
                    shed.append(c.inst)
        return shed
