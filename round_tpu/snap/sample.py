"""Snapshot sampling: the per-replica half of round-consistent cuts.

The HO model's communication-closed rounds are the whole trick
(docs/SNAPSHOTS.md): a replica's state at the END of round r reflects
exactly the rounds 0..r — no in-flight message can straddle the
boundary, because round r's messages are either folded into round r's
update or dropped as late.  So n per-replica samples stamped the SAME
``(instance, round, epoch)`` coordinate ARE a consistent global state,
with no marker protocol, no channel recording, no coordination beyond
the round structure the protocol already runs ("Reducing asynchrony to
synchronized rounds", PAPERS.md).

This module owns the per-replica side:

  * the DETERMINISTIC sampling policy — every replica must sample the
    same (instance, round) pairs or no cut ever assembles, so the policy
    is a pure function of (instance, seed): round r of instance i is
    sampled iff ``r % every_k == jitter(i)``, the per-instance jitter
    spreading sample waves across rounds instead of aligning every
    instance on the same wave;
  * the wire form — a codec-typed dict payload (runtime/codec.py: zero
    pickle, structurally validated on decode) under the new FLAG_SNAP
    oob flag, the (instance, round, epoch) coordinate riding the Tag;
  * the state DIGEST — blake2b over the canonical codec encoding of the
    state rows, banked in every sample so divergence forensics
    (snap/collect.py) can compare replicas' state trajectories without
    shipping full state twice;
  * the byte budget — a token bucket plus the PR 10 admission signal:
    audit traffic is strictly lower-priority than serving, so a replica
    that is shedding load (or out of budget) SKIPS samples (counted,
    never queued) rather than competing with the decision plane.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time as _time
from typing import List, Optional, Sequence

import numpy as np

from round_tpu.obs.metrics import METRICS
from round_tpu.runtime import codec
from round_tpu.runtime.log import get_logger
from round_tpu.runtime.oob import FLAG_SNAP, Tag

log = get_logger("snap")

_C_SAMPLES = METRICS.counter("snap.samples")
_C_SAMPLE_BYTES = METRICS.counter("snap.sample_bytes")
_C_SKIP_BUDGET = METRICS.counter("snap.skipped_budget")
_C_SKIP_OVERLOAD = METRICS.counter("snap.skipped_overload")
_C_MALFORMED = METRICS.counter("snap.malformed")

# digest width: 16 bytes of blake2b — collision-resistant enough for
# forensics (a divergence detector, not a security boundary), small
# enough to bank per (node, round) without budget pressure
_DIGEST_SIZE = 16


def state_blob(leaves: Sequence[np.ndarray]) -> bytes:
    """The CANONICAL codec encoding of the state rows — the same bytes
    every replica would produce for this state: C-contiguous arrays
    through codec's fixed-header array encoding (dtype code + dims +
    raw data), so dtype and shape are part of the encoding and two
    states encode equal iff their wire forms are byte-identical.

    This blob IS the sample's wire form for the state (encode_sample
    embeds it as one bytes field): the state is encoded ONCE per sample
    and the digest is computed over those exact bytes — the collector
    re-digests the RECEIVED blob directly, so in-flight corruption of
    the actual wire bytes is what the check detects, with no re-encode
    on either side.

    Shapes are preserved exactly (0-d rows stay 0-d — never
    ascontiguousarray here, which promotes scalars to [1]); the codec
    makes its own contiguous copy when a leaf needs one."""
    return codec.encode([np.asarray(x) for x in leaves])


def blob_digest(blob) -> bytes:
    """blake2b-16 over a canonical state blob — the divergence-
    forensics anchor: computed at the emitter, re-verified at the
    collector, and compared across duplicate claims for one coordinate
    (equivocation — one node, two states, one round)."""
    return hashlib.blake2b(bytes(blob),
                           digest_size=_DIGEST_SIZE).digest()


def state_digest(leaves: Sequence[np.ndarray]) -> bytes:
    """Digest of a state given as decoded rows (the local-join path and
    the offline tools; the wire path digests its blob directly)."""
    return blob_digest(state_blob(leaves))


def sample_jitter(inst: int, seed: int, every_k: int) -> int:
    """The per-instance sampling phase: deterministic in (inst, seed) so
    every replica of a cluster (same seed by the harness contract, the
    chaos/value-schedule determinism) picks the SAME rounds, jittered so
    concurrent instances do not all sample on the same wave."""
    h = hashlib.blake2b(b"snap-jitter" + int(inst).to_bytes(8, "little")
                        + int(seed).to_bytes(8, "little", signed=True),
                        digest_size=4).digest()
    return int.from_bytes(h, "little") % max(1, every_k)


@dataclasses.dataclass
class SnapPolicy:
    """When to sample, and how many bytes sampling may spend.

    every_k:  sample round r of instance i iff r % every_k == jitter(i).
    seed:     the cluster seed (shared across replicas — determinism).
    budget_bytes_per_s: token-bucket refill rate; 0 disables the budget.
              The bucket starts FULL (one burst is free) and is sized at
              one second of refill — audit traffic is smoothed, never
              queued.
    """

    every_k: int = 8
    seed: int = 0
    budget_bytes_per_s: int = 256 << 10

    def __post_init__(self):
        if self.every_k < 1:
            raise ValueError(f"every_k must be >= 1, got {self.every_k}")
        self._tokens = float(self.budget_bytes_per_s)
        self._last = _time.monotonic()
        # jitter memo: due() sits on the per-lane per-round serving hot
        # path and the blake2b phase is constant per instance — hash
        # once, not once per round (bounded like the other id maps)
        self._jitter: dict = {}

    def due(self, inst: int, r: int) -> bool:
        j = self._jitter.get(inst)
        if j is None:
            if len(self._jitter) > 8192:
                self._jitter.clear()
            j = self._jitter[inst] = sample_jitter(inst, self.seed,
                                                   self.every_k)
        return r % self.every_k == j

    def _refill(self) -> None:
        now = _time.monotonic()
        self._tokens = min(
            float(self.budget_bytes_per_s),
            self._tokens + (now - self._last) * self.budget_bytes_per_s)
        self._last = now

    def affordable(self, nbytes: int) -> bool:
        """Peek: would ``nbytes`` fit the bucket right now?  No charge —
        the emitter's pre-gate, so a broke bucket skips a sample BEFORE
        paying the state encode (the budget exists to protect serving;
        it must not cost serving the most while refusing)."""
        if self.budget_bytes_per_s <= 0:
            return True
        self._refill()
        return self._tokens >= nbytes

    def spend(self, nbytes: int) -> bool:
        """True when the byte budget covers ``nbytes`` (and charges it);
        False = skip this sample.  Zero-rate budget always allows."""
        if self.budget_bytes_per_s <= 0:
            return True
        self._refill()
        if self._tokens < nbytes:
            return False
        self._tokens -= nbytes
        return True


def encode_sample(node: int, blob: bytes,
                  values: Sequence[int], digest: bytes) -> bytes:
    """The FLAG_SNAP payload: a codec dict — the state rows as ONE
    canonical blob (state_blob — already encoded for the digest, never
    re-encoded), the instance's proposal row (the artifact ``values``
    vector and the auditor's init-snapshot seed), and the emitter-side
    digest over exactly those blob bytes."""
    return codec.encode({
        "node": int(node),
        "state": bytes(blob),
        "values": np.asarray(values, dtype=np.int64),
        "digest": bytes(digest),
    })


def decode_sample(raw) -> Optional[dict]:
    """Parse one FLAG_SNAP payload; None on anything malformed (the
    socket is unauthenticated — garbage is counted and dropped, the
    codec/hostile-wire discipline).  Returns the received state blob
    alongside the decoded rows so the collector can digest the ACTUAL
    wire bytes (in-flight corruption check) without re-encoding."""
    try:
        p = codec.loads(raw)
        node = int(p["node"])
        blob = bytes(p["state"])
        # OWNING copies: the decoded leaves are zero-copy views into
        # the blob; np.array detaches them so a pending part-cut never
        # pins the payload (nor the transport's reused receive buffer)
        state = [np.array(x) for x in codec.decode(blob)]
        values = np.asarray(p["values"], dtype=np.int64)
        digest = bytes(p["digest"])
        if node < 0 or len(digest) != _DIGEST_SIZE or not state:
            raise ValueError("snap sample out of range")
        return {"node": node, "state": state, "values": values,
                "digest": digest, "blob": blob}
    except Exception as e:  # noqa: BLE001 — hostile bytes must not raise
        _C_MALFORMED.inc()
        log.debug("snap: dropping malformed sample: %s", e)
        return None


class SampleEmitter:
    """One replica's sample source: policy + budget + wire-out.

    ``sink`` is either the local SnapCollector (the collector replica
    samples itself with no wire round-trip) or None; non-local samples
    ship to ``collector_pid`` over ``transport`` as FLAG_SNAP frames.
    ``admission`` is the PR 10 AdmissionControl (or None): while the
    driver sheds load, sampling stops — audit traffic can never starve
    serving."""

    __slots__ = ("node", "policy", "transport", "collector_pid", "sink",
                 "admission", "samples", "sample_bytes", "skipped",
                 "_sendb", "_flushfn", "_unflushed", "_last_payload")

    def __init__(self, node: int, policy: SnapPolicy, transport,
                 collector_pid: int, sink=None, admission=None):
        self.node = node
        self.policy = policy
        self.transport = transport
        self.collector_pid = collector_pid
        self.sink = sink
        self.admission = admission
        self.samples = 0
        self.sample_bytes = 0
        self.skipped = 0
        # samples COALESCE into the per-peer FLAG_BATCH containers the
        # round traffic already ships (PR 5 send_buffered/flush): a raw
        # per-sample send would interrupt the collector's native pump
        # wait once PER FRAME — the same wake-storm cost PR 12 measured
        # for rv decision gossip — while a buffered sample rides the
        # next wave's container and costs one already-happening wake
        self._sendb = getattr(transport, "send_buffered", None)
        self._flushfn = getattr(transport, "flush", None)
        if self._flushfn is None:
            self._sendb = None
        self._unflushed = False
        self._last_payload = 0

    def emit(self, inst: int, r: int, epoch: int,
             leaves: List[np.ndarray], values: Sequence[int]) -> bool:
        """Sample (inst, r) if due under the policy and budget; returns
        True when a sample left this replica (locally or on the wire)."""
        if not self.policy.due(inst, r):
            return False
        if self.admission is not None and self.admission.shedding:
            self.skipped += 1
            _C_SKIP_OVERLOAD.inc()
            return False
        if self.sink is not None:
            # the collector replica's own contribution: no wire, but the
            # SAME digest/values path as a remote sample (one code path
            # for verification — only transport differs)
            self.samples += 1
            _C_SAMPLES.inc()
            # OWNING copies, shapes preserved: the collector holds the
            # rows past this wave, while the driver's leaves are reused
            # in place (np.array, never ascontiguousarray — the latter
            # promotes 0-d rows to [1] and desyncs the wire shape)
            # the cut coordinate space is (inst & 0xFFFF, epoch & 0xFF)
            # — what the Tag carries on the wire — so the local join
            # masks IDENTICALLY or a wrapped id would strand the
            # collector's own row in a slot its peers never match
            self.sink.add_sample(self.node, inst & 0xFFFF, r,
                                 epoch & 0xFF,
                                 [np.array(x) for x in leaves],
                                 np.asarray(values, dtype=np.int64),
                                 state_digest(leaves), local=True)
            return True
        # broke-bucket pre-gate BEFORE the state encode: under sustained
        # refusal the skip must cost ~nothing (the last payload's size
        # is the estimate — sample sizes are stable within a workload;
        # halved so a marginal bucket still reaches the exact check)
        if self._last_payload and not self.policy.affordable(
                self._last_payload // 2):
            self.skipped += 1
            _C_SKIP_BUDGET.inc()
            return False
        blob = state_blob(leaves)
        payload = encode_sample(self.node, blob, values,
                                blob_digest(blob))
        self._last_payload = len(payload)
        if not self.policy.spend(len(payload)):
            self.skipped += 1
            _C_SKIP_BUDGET.inc()
            return False
        tag = Tag(instance=inst & 0xFFFF, round=r, flag=FLAG_SNAP,
                  call_stack=epoch & 0xFF)
        try:
            if self._sendb is not None:
                self._sendb(self.collector_pid, tag, payload)
                self._unflushed = True
            else:
                self.transport.send(self.collector_pid, tag, payload)
        except Exception as e:  # noqa: BLE001 — a dead collector must
            # never cost the serving path more than the skipped sample
            log.debug("snap: sample send failed: %s", e)
            return False
        self.samples += 1
        self.sample_bytes += len(payload)
        _C_SAMPLES.inc()
        _C_SAMPLE_BYTES.inc(len(payload))
        return True

    def flush(self) -> None:
        """Ship any buffered samples.  The drivers' own send waves flush
        the shared per-peer buffers anyway; this covers the idle tail
        (a driver with no send pending must not strand a sample)."""
        if self._unflushed and self._flushfn is not None:
            self._unflushed = False
            try:
                self._flushfn()
            except Exception as e:  # noqa: BLE001 — best-effort
                log.debug("snap: sample flush failed: %s", e)
