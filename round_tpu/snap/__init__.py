"""round_tpu.snap — round-consistent global snapshots (docs/SNAPSHOTS.md).

Fleet-wide cut capture at round boundaries (communication-closed rounds
make a round-aligned cut a consistent global state by construction — no
marker protocol), batched full-state invariant auditing (the offline
half of the Spec, jitted and vmapped over cuts), and divergence
forensics (blake2b state digests banked per replica per sampled round).

Surfaces:
  sample.py  — deterministic sampling policy, FLAG_SNAP payloads,
               digests, the byte-budgeted emitter
  collect.py — cut assembly: round alignment, epoch fencing,
               envelope-tolerated partial cuts, .snapcut banking
  audit.py   — the batched offline-formula evaluator + the rv-shared
               halt/shed/log violation pipeline (SnapConfig)
  driver.py  — SnapDriver, the three-seam facade the serving drivers
               hold (after_round / on_frame / flush)
  fixtures.py — snap-broken-conservation, the monitor-invisible
               full-state violation (tests/test_snap.py)
"""

from round_tpu.snap.audit import (  # noqa: F401
    AuditProgram, CutAuditor, SnapConfig, SnapRuntime, SnapViolation,
    audit_program,
)
from round_tpu.snap.collect import (  # noqa: F401
    Cut, SnapCollector, bank_cut, envelope_f_max, load_cut,
)
from round_tpu.snap.driver import SnapDriver  # noqa: F401
from round_tpu.snap.sample import (  # noqa: F401
    SampleEmitter, SnapPolicy, decode_sample, encode_sample,
    sample_jitter, state_digest,
)
