"""Runtime verification: wire-speed monitors fused into serving.

The composition ROADMAP item 4 asked for: the same Spec formulas
spec/check.py evaluates offline (and fuzz/objectives.py evaluates
vmapped inside jitted dispatches) become LIVE monitors on the serving
tier — every instance a LaneDriver or HostRunner advances is
invariant-checked at marginal cost ~0, violations halt-and-dump a
replayable artifact (the PR 8 fuzz/replay.py schedule format), and
ViewManager membership changes are licensed by the PR 9 parameterized
proofs instead of by hope.

Modules:
  compile.py  — the monitor compiler: Spec → jitted per-lane monitor
                term (fused into the LaneDriver mega-step; a Python-path
                equivalent drives HostRunner) via the SHARED formula
                enumeration of spec/check.py:spec_formulas.
  dump.py     — the violation pipeline: obs events + rv.* counters +
                halt-and-dump artifacts that `fuzz_cli replay`
                reproduces bit-exactly on engine and host wire.
  license.py  — proof-licensed reconfiguration: the parameterized-proof
                registry consulted by ViewManager before a membership op
                commits.
  fixtures.py — deliberately broken rounds (selector-registered) that
                trip each monitor: the injected-violation end-to-end
                pins of tests/test_rv.py.

See docs/RUNTIME_VERIFICATION.md for monitor semantics, the dump
artifact schema, and the licensing state machine.
"""

from round_tpu.rv.compile import (  # noqa: F401
    InstanceMonitor, MonitorProgram, monitor_program,
)
from round_tpu.rv.dump import RvConfig, RvRuntime, RvViolation  # noqa: F401
from round_tpu.rv.license import License, ProofLicenseRegistry  # noqa: F401
