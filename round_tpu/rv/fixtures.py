"""Deliberately broken rounds: one fixture per wire monitor.

The analysis/fixtures.py idea applied to the runtime-verification tier:
each fixture is a tiny OTR-shaped consensus whose update is broken in
exactly one way, so the injected-violation end-to-end tests
(tests/test_rv.py) can pin that the RIGHT monitor trips, under the lane
driver AND HostRunner, and that the dumped artifact replays to the same
violating state on the engine.

All three are selector-registered (``rv-broken-agreement`` /
``rv-broken-validity`` / ``rv-broken-revoke``) so the dump artifacts are
replayable through the standard fuzz_cli surfaces — an rv dump names its
protocol, and replay resolves it like any other model.  They are test
fixtures, not protocols: never deploy one.
"""

from __future__ import annotations

import jax.numpy as jnp

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.rounds import Round, RoundCtx, broadcast
from round_tpu.models.common import ghost_decide
from round_tpu.models.otr import OtrSpec, OtrState
from round_tpu.ops.mailbox import Mailbox

# rounds a fixture keeps participating after deciding: long enough for
# FLAG_DECISION gossip to land while the lane is still live (the
# agreement monitor's deterministic trip window)
_AFTER = 6


class _BrokenOtrRound(Round):
    """OtrRound's shape with a pluggable (wrong) decision rule."""

    def send(self, ctx: RoundCtx, state: OtrState):
        return broadcast(ctx, state.x)

    def _decide_value(self, ctx: RoundCtx, state: OtrState,
                      mbox: Mailbox):
        raise NotImplementedError

    def update(self, ctx: RoundCtx, state: OtrState,
               mbox: Mailbox) -> OtrState:
        quorum = mbox.size() > (2 * ctx.n) // 3
        v = self._decide_value(ctx, state, mbox)
        state = ghost_decide(state, quorum, v)
        after = jnp.where(state.decided, state.after - 1, state.after)
        ctx.exit_at_end_of_round(state.decided & (after <= 0))
        # x is deliberately NOT overwritten (plain OTR converges x onto
        # the decision): the fixtures keep the heterogeneous proposals
        # flowing every round, so min != max stays observable after the
        # (broken) decisions land
        return state.replace(after=after)


class _AgreementBreakRound(_BrokenOtrRound):
    """Even pids decide the MIN received value, odd pids the MAX — both
    are received (hence proposed) values, so validity holds while
    agreement is broken system-wide the moment proposals differ."""

    def _decide_value(self, ctx, state, mbox):
        lo = mbox.masked_min()
        hi = mbox.masked_max()
        return jnp.where(ctx.id % 2 == 0, lo, hi).astype(state.x.dtype)


class _ValidityBreakRound(_BrokenOtrRound):
    """Decides a FABRICATED value no process proposed (the schedule
    domain is mod 5; 99 is unreachable)."""

    def _decide_value(self, ctx, state, mbox):
        return jnp.asarray(99, dtype=state.x.dtype)


class _RevokeRound(_BrokenOtrRound):
    """Decides the MIN received value, then REVOKES it: from round 2 on,
    a decided lane's decision silently flips to the MAX proposal it
    heard at decision time — another proposed value, so validity holds
    while irrevocability is broken."""

    def _decide_value(self, ctx, state, mbox):
        return mbox.masked_min().astype(state.x.dtype)

    def update(self, ctx, state, mbox):
        hi = mbox.masked_max().astype(state.x.dtype)
        state = super().update(ctx, state, mbox)
        revoke = state.decided & (ctx.r >= 2) & (hi > state.decision)
        return state.replace(
            decision=jnp.where(revoke, hi, state.decision))


class _BrokenConsensus(Algorithm):
    """The shared Algorithm shell: OTR's state/init/accessors (and Spec,
    so the monitors carry the Spec's own property labels) around one
    broken round."""

    fault_envelope = "n > 3f"

    def __init__(self, rnd: Round):
        self.rounds = (rnd,)
        self.spec = OtrSpec()

    def make_init_state(self, ctx: RoundCtx, io) -> OtrState:
        return OtrState(
            x=jnp.asarray(io["initial_value"], dtype=jnp.int32),
            decided=jnp.asarray(False),
            decision=jnp.asarray(-1, dtype=jnp.int32),
            after=jnp.asarray(_AFTER, dtype=jnp.int32),
        )

    def decided(self, state: OtrState):
        return state.decided

    def decision(self, state: OtrState):
        return state.decision

    def adopt_decision(self, state, decision):
        # oob adoption would HEAL the injected violation
        # nondeterministically: a replica that adopts the first peer
        # decision it hears never produces its OWN broken one, and on a
        # loaded box the adoption can win the race against the lane's
        # ready update wave.  The fixtures refuse adoption (a legitimate
        # Algorithm choice — None = "cannot adopt") so every replica's
        # broken update runs and its monitor trips deterministically.
        return None


FIXTURES = {
    "rv-broken-agreement": _AgreementBreakRound,
    "rv-broken-validity": _ValidityBreakRound,
    "rv-broken-revoke": _RevokeRound,
}


def select_fixture(name: str) -> Algorithm:
    return _BrokenConsensus(FIXTURES[name]())
