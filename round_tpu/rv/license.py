"""Proof-licensed reconfiguration: membership ops gated on all-n proofs.

PR 9 left the parameterized proofs (verify/param.py: threshold automata
extracted from the live round jaxprs, all-n VCs discharged through the
solver stack, VC-hash result cache) sitting NEXT to the runtime.  This
module closes them into the ViewManager loop: before a membership op is
proposed (and when one decided elsewhere is adopted), the manager asks
this registry whether resizing the group to the op's n is LICENSED —
i.e. whether the serving protocol carries an all-n safety proof and the
new size still admits a nonzero fault budget under the protocol's
declared ``fault_envelope`` (``n > K·f``).

Verdict vocabulary (License.status):
  licensed          — an all-n parameterized suite covers the model, the
                      proof is PROVED (cache-warm re-verify ~2 s for a
                      suite, sub-ms on a cache hit), and the target n
                      admits f >= 1 under the envelope.
  outside-envelope  — the model HAS an all-n proof but the target n
                      does not tolerate a single fault under its
                      envelope (e.g. OTR at n=3 under n > 3f).
  unlicensed        — the model carries only fixed-n proofs (or none):
                      no parameterized suite is registered for it, or
                      the suite did not verify.

ViewManager (runtime/view.py) maps non-licensed verdicts to REFUSED (the
op is not proposed) or, under the --view-unlicensed-ok escape hatch, to
DEGRADED (the op proceeds, flagged in obs + the replica summary).  See
docs/MEMBERSHIP.md "proof-licensed resizing".
"""

from __future__ import annotations

import dataclasses
import re
import time as _time
from typing import Callable, Dict, Optional

from round_tpu.obs.metrics import METRICS
from round_tpu.runtime.log import get_logger

log = get_logger("rv.license")

_C_CHECKS = METRICS.counter("license.checks")
_C_GRANTED = METRICS.counter("license.granted")
_C_DENIED = METRICS.counter("license.denied")

# serving-tier algorithm names -> parameterized-proof model names
# (verify/param.py PARAM_SUITES keys are suite names; values name the
# registry model).  LastVotingBytes licenses against the proved
# lastvoting automaton: the byte variant INHERITS the four rounds
# unchanged (models/lastvoting.py LastVotingBytes — the value is opaque
# to every quorum/timestamp test the automaton abstracts, so the
# extracted transition structure is the same object; only the int-domain
# trace Spec does not apply, which licensing never consults).  Variants
# that RESTRUCTURE the phases (slv/mlv) are deliberately absent: their
# resizes stay unlicensed until they carry their own extraction.
MODEL_ALIASES: Dict[str, str] = {
    "otr": "otr",
    "lv": "lastvoting",
    "lastvoting": "lastvoting",
    "lvb": "lastvoting",
    "lastvoting-bytes": "lastvoting",
    "lastvotingbytes": "lastvoting",
}


@dataclasses.dataclass(frozen=True)
class License:
    """One resize verdict: ``ok`` is True only for status 'licensed'."""

    status: str                 # licensed | outside-envelope | unlicensed
    reason: str
    model: Optional[str] = None
    suite: Optional[str] = None
    envelope: Optional[str] = None
    f_max: int = 0
    cached: Optional[bool] = None
    seconds: float = 0.0

    @property
    def ok(self) -> bool:
        return self.status == "licensed"

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)


def parse_envelope(envelope: str) -> int:
    """The K of a declared ``n > K·f`` resilience envelope
    (core/algorithm.py Algorithm.fault_envelope)."""
    m = re.fullmatch(r"\s*n\s*>\s*(\d+)\s*\*?\s*f\s*", envelope or "")
    if not m:
        raise ValueError(f"unparseable fault envelope {envelope!r} "
                         "(expected 'n > Kf')")
    return int(m.group(1))


def _default_prover(suite: str, cache_dir: Optional[str], solve: bool):
    """(proved, cached): discharge (or cache-look-up) one parameterized
    suite through the verifier_cli registry — the same VC-hash cache
    the CLI uses, so a nightly ``verifier_cli --all --cache`` run makes
    every runtime license check a warm hit."""
    from round_tpu.apps import verifier_cli as vcli

    if not solve:
        # cache-only: never stall a live view move on a cold solver run
        if not cache_dir:
            return False, None
        _digest, hit = vcli._cache_lookup(cache_dir, suite)
        if hit is None:
            return False, False
        return bool(hit.get("ok")), True
    rec = vcli.run_suite_cached(suite, cache_dir=cache_dir)
    return bool(rec.get("ok")), bool(rec.get("cached"))


class ProofLicenseRegistry:
    """The runtime face of the parameterized-proof registry.

    ``prover(suite, cache_dir, solve) -> (proved, cached)`` is
    injectable (tests swap in a scripted verdict; deployments keep the
    default verifier_cli path).  Only PROVED verdicts are memoized per
    (model, solve) — a proof does not decay within a process, so a view
    change never re-pays even the warm re-verify; a negative (or
    crashed) verdict is re-asked next time, since a transient solver
    timeout or a not-yet-populated nightly cache must not refuse every
    later op for the process lifetime (the same sticky-NOT-PROVED bug
    class the verifier_cli cache fixed in PR 9)."""

    def __init__(self, cache_dir: Optional[str] = None,
                 solve: bool = True,
                 prover: Optional[Callable] = None):
        self.cache_dir = cache_dir
        self.solve = solve
        self.prover = prover or _default_prover
        self._proved: Dict = {}

    def _suite_for(self, model: str) -> Optional[str]:
        from round_tpu.verify.param import PARAM_SUITES

        for suite, (m, _cross) in PARAM_SUITES.items():
            if m == model:
                return suite
        return None

    def check(self, algo_name: str, new_n: int,
              solve: Optional[bool] = None) -> License:
        """License verdict for resizing ``algo_name``'s serving group to
        ``new_n`` members.  ``solve`` overrides the registry default
        (ViewManager passes solve=False on the ADOPT path: an op decided
        elsewhere is already committed — the check may flag, never
        stall)."""
        t0 = _time.monotonic()
        _C_CHECKS.inc()
        solve = self.solve if solve is None else solve
        model = MODEL_ALIASES.get((algo_name or "").lower())
        if model is None:
            _C_DENIED.inc()
            return License(
                status="unlicensed", model=algo_name,
                reason=f"{algo_name!r} carries no parameterized proof "
                       "(fixed-n verification only)",
                seconds=_time.monotonic() - t0)
        suite = self._suite_for(model)
        if suite is None:
            _C_DENIED.inc()
            return License(
                status="unlicensed", model=model,
                reason=f"no parameterized suite registered for {model}",
                seconds=_time.monotonic() - t0)
        from round_tpu.apps.selector import select

        envelope = getattr(select(algo_name), "fault_envelope", None)
        try:
            k = parse_envelope(envelope)
        except ValueError as e:
            _C_DENIED.inc()
            return License(status="unlicensed", model=model, suite=suite,
                           reason=str(e),
                           seconds=_time.monotonic() - t0)
        f_max = max(0, (new_n - 1) // k)
        if f_max < 1:
            _C_DENIED.inc()
            return License(
                status="outside-envelope", model=model, suite=suite,
                envelope=envelope, f_max=f_max,
                reason=f"n={new_n} admits no fault under {envelope} "
                       f"(needs n >= {k + 1})",
                seconds=_time.monotonic() - t0)
        memo = self._proved.get((model, solve))
        if memo is None:
            try:
                memo = self.prover(suite, self.cache_dir, solve)
            except Exception as e:  # noqa: BLE001 — a prover crash is a
                # denial with a reason, never a view-manager crash
                log.warning("license prover failed for %s: %s", suite, e)
                memo = (False, None)
            if memo[0]:
                # PROVED verdicts only — a negative is re-asked, never
                # latched (class docstring)
                self._proved[(model, solve)] = memo
        proved, cached = memo
        if not proved:
            _C_DENIED.inc()
            return License(
                status="unlicensed", model=model, suite=suite,
                envelope=envelope, f_max=f_max, cached=cached,
                reason=(f"suite {suite} not PROVED"
                        + ("" if solve else
                           " in the cache (adopt-path check is "
                           "cache-only; run verifier_cli --cache)")),
                seconds=_time.monotonic() - t0)
        _C_GRANTED.inc()
        return License(
            status="licensed", model=model, suite=suite,
            envelope=envelope, f_max=f_max, cached=cached,
            reason=f"all-n proof {suite} PROVED; n={new_n} tolerates "
                   f"f <= {f_max} under {envelope}",
            seconds=_time.monotonic() - t0)
