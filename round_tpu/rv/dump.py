"""The violation pipeline: typed obs events, rv.* counters, halt-and-dump.

A tripped monitor must leave three things behind (docs/
RUNTIME_VERIFICATION.md "violation pipeline"):

  1. a typed trace event (``rv_violation``) + counters (``rv.checks``,
     ``rv.violations``, ``rv.dumps``, per-policy ``rv.halts`` /
     ``rv.sheds`` / ``rv.logged``) — the observability record;
  2. a halt-and-dump ARTIFACT in the PR 8 fuzz/replay.py schedule-JSON
     format — protocol, n, seed, per-process proposals, the fault
     schedule in force (the --chaos-schedule artifact's drops, or a
     clean all-deliver wire), and an ``meta.rv`` block naming the
     tripped formula (spec/check.py:formula_label vocabulary), the
     replica, instance, round and observed decision plane.  Because the
     format IS the fuzz artifact format, ``fuzz_cli replay`` reproduces
     it bit-exactly on the batched engine and on the real host wire;
  3. the configured policy's action: ``halt`` raises RvViolation out of
     the driver (the artifact path rides the exception), ``shed``
     retires the instance undecided (accounted like an admission shed),
     ``log`` records and keeps serving.
"""

from __future__ import annotations

import dataclasses
import os
import re
import time as _time
from typing import Any, Dict, List, Optional

import numpy as np

from round_tpu.obs.metrics import METRICS
from round_tpu.obs.trace import TRACE
from round_tpu.runtime.log import get_logger

log = get_logger("rv")

POLICIES = ("halt", "shed", "log")

_C_CHECKS = METRICS.counter("rv.checks")
_C_VIOLATIONS = METRICS.counter("rv.violations")
_C_DUMPS = METRICS.counter("rv.dumps")
_C_POLICY = {p: METRICS.counter(f"rv.{p}s" if p != "log" else "rv.logged")
             for p in POLICIES}


class RvViolation(RuntimeError):
    """A monitor tripped under the ``halt`` policy.  Carries the formula
    label and the dump artifact path (None when dumping was off or
    failed)."""

    def __init__(self, label: str, inst: int, round_: int,
                 artifact: Optional[str]):
        self.label, self.inst, self.round = label, inst, round_
        self.artifact = artifact
        at = f" -> {artifact}" if artifact else ""
        super().__init__(
            f"runtime-verification violation: {label} "
            f"(instance {inst}, round {round_}){at}")


@dataclasses.dataclass
class RvConfig:
    """Driver-facing rv switches (host_replica --rv / fleet --rv).

    policy:        halt | shed | log (what a violation does).
    protocol:      the selector name, so dump artifacts are replayable
                   (None = events/counters only, no artifact).
    dump_dir:      artifact directory (None = no artifact).
    schedule_path: the --chaos-schedule artifact in force, copied into
                   the dump's drops so the replay runs the same wire.
    bank_engine:   record expected.engine into the artifact at dump time
                   (one jitted engine replay — acceptable while halting;
                   turn off for latency-sensitive shed/log serving).
    gossip:        broadcast FLAG_DECISION on local decide, widening the
                   agreement monitor's observability to peers that are
                   NOT lagging (a laggard already learns decisions via
                   the TooLate/decision-reply recovery path, which the
                   monitor taps for free).  Off by default: the n²
                   decision fan-out interrupts the native pump's wait
                   per frame and measurably costs dps on fast-round
                   workloads — turn it on for adversarial deployments
                   (and the injected-violation tests) where decided
                   replicas must cross-check each other.
    max_dumps:     artifact cap per driver (a wedged monitor must not
                   fill the disk).
    """

    policy: str = "log"
    protocol: Optional[str] = None
    dump_dir: Optional[str] = None
    schedule_path: Optional[str] = None
    bank_engine: bool = True
    gossip: bool = False
    max_dumps: int = 8

    def __post_init__(self):
        if self.policy not in POLICIES:
            raise ValueError(
                f"rv policy must be one of {POLICIES}, got {self.policy!r}")


def _slug(label: str) -> str:
    return re.sub(r"[^a-zA-Z0-9]+", "-", label).strip("-")[:48] or "rv"


def dump_violation(cfg: RvConfig, *, n: int, seed: int, rounds: int,
                   values: List[int], node: int, inst: int, round_: int,
                   label: str, observed: Dict[str, Any]) -> Optional[str]:
    """Write one violation artifact (fuzz/replay.py schema + meta.rv);
    returns its path, or None when artifacts are not configured or the
    write failed (the obs record still stands either way)."""
    if cfg.protocol is None or cfg.dump_dir is None:
        return None
    from round_tpu.fuzz import replay

    vplan = None
    if cfg.schedule_path is not None:
        src = replay.load_artifact(cfg.schedule_path)
        sched = replay.schedule_from_artifact(src)
        # a v2 source's VALUE plan rides into the dump too — a
        # lie-caused violation replays only if the lies replay
        # (round_tpu/byz; the same last-row clamp semantics)
        vplan = replay.value_plan_from_artifact(src)
        # the dump pins the VIOLATING run's horizon; the source schedule
        # clamps to its last row past its own horizon on every replay
        # surface, so truncation/extension below is outcome-neutral
        if sched.shape[0] >= rounds:
            sched = sched[:rounds]
            vplan = None if vplan is None else vplan[:rounds]
        else:
            pad = rounds - sched.shape[0]
            sched = np.concatenate(
                [sched, np.repeat(sched[-1:], pad, axis=0)])
            if vplan is not None:
                vplan = np.concatenate(
                    [vplan, np.repeat(vplan[-1:], pad, axis=0)])
    else:
        sched = np.ones((rounds, n, n), dtype=bool)
    try:
        art = replay.make_artifact(
            protocol=cfg.protocol, schedule=sched, value_plan=vplan,
            values=np.asarray(values, dtype=np.int64), seed=seed,
            meta={"rv": {
                "formula": label,
                "node": int(node),
                "instance": int(inst),
                "round": int(round_),
                "observed": observed,
                "wall": _time.time(),
            }})
        if cfg.bank_engine:
            art["expected"]["engine"] = replay.replay_engine(art)
        os.makedirs(cfg.dump_dir, exist_ok=True)
        path = os.path.join(
            cfg.dump_dir,
            f"rv-{cfg.protocol}-i{inst}-{_slug(label)}.json")
        replay.dump_artifact(path, art)
        _C_DUMPS.inc()
        return path
    except Exception as e:  # noqa: BLE001 — a failed dump must never
        # turn one violation into a second failure mode; the trace
        # event + counters already recorded the trip
        log.warning("rv: violation dump failed: %s", e)
        return None


class RvRuntime:
    """Per-driver violation bookkeeping, shared by LaneDriver and the
    HostRunner loop: counters, events, the dump rate limit, and the
    policy verdict the caller acts on."""

    def __init__(self, cfg: RvConfig, *, node: int, n: int, seed: int,
                 max_rounds: int):
        self.cfg = cfg
        self.node, self.n = node, n
        self.seed, self.max_rounds = seed, max_rounds
        self.checks = 0
        self.violations: List[Dict[str, Any]] = []
        self.artifacts: List[str] = []
        self._dumped: set = set()

    def note_checks(self, k: int) -> None:
        self.checks += k
        _C_CHECKS.inc(k)

    def violate(self, *, inst: int, round_: int, label: str,
                values: List[int], observed: Dict[str, Any],
                where: str) -> str:
        """Record one tripped monitor.  Under the ``halt`` policy this
        RAISES RvViolation (artifact attached) after the record is
        banked — the ONE place the halt exception is built, so the
        drivers' sites cannot drift; otherwise returns the action the
        caller must take ('shed' | 'log')."""
        _C_VIOLATIONS.inc()
        _C_POLICY[self.cfg.policy].inc()
        rec = {"inst": int(inst), "round": int(round_), "formula": label,
               "where": where, "policy": self.cfg.policy}
        if TRACE.enabled:
            TRACE.emit("rv_violation", node=self.node, inst=int(inst),
                       round=int(round_), formula=label, where=where,
                       policy=self.cfg.policy)
        log.error("node %d: RV VIOLATION inst=%d round=%d %s (%s)",
                  self.node, inst, round_, label, where)
        key = (int(inst), label)
        artifact = None
        if key not in self._dumped and len(self.artifacts) \
                < self.cfg.max_dumps:
            self._dumped.add(key)
            artifact = dump_violation(
                self.cfg, n=self.n, seed=self.seed,
                rounds=self.max_rounds, values=values, node=self.node,
                inst=inst, round_=round_, label=label, observed=observed)
            if artifact is not None:
                rec["artifact"] = artifact
                self.artifacts.append(artifact)
        self.violations.append(rec)
        if self.cfg.policy == "halt":
            raise RvViolation(
                label, inst, round_,
                artifact if artifact is not None
                else (self.artifacts[-1] if self.artifacts else None))
        return self.cfg.policy

    def fill_stats(self, stats_out: Optional[Dict[str, Any]]) -> None:
        if stats_out is None:
            return
        stats_out["rv_checks"] = stats_out.get("rv_checks", 0) \
            + self.checks
        stats_out.setdefault("rv_violations", []).extend(self.violations)
        stats_out.setdefault("rv_artifacts", []).extend(self.artifacts)
