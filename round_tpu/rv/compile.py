"""The monitor compiler: a protocol's Spec → wire-speed lane monitors.

What is soundly checkable AT A REPLICA differs from what is checkable
over a recorded full-system trace, and this module is explicit about the
split (docs/RUNTIME_VERIFICATION.md "monitor semantics"):

  * The full-state formulas — invariants, safety_predicate,
    round_invariants — quantify over all n processes' state, which no
    single replica holds.  They stay with the offline/engine surface
    (spec/check.py:check_trace, fuzz/objectives.py:spec_holds), and the
    compiler CLASSIFIES them (``MonitorProgram.offline``) so the dump
    pipeline and docs can say exactly which formulas a live verdict does
    NOT cover.

  * The decision-plane properties — Agreement, Validity, Irrevocability
    — have exact locally-checkable forms over what a replica genuinely
    observes: its own decision history (irrevocability needs one carried
    (prior decided, prior decision) pair per lane), the instance's
    initial-value vector (deterministic from the shared value schedule,
    or the uniform client proposal — validity's witness set), and
    peer decisions learned over the wire (FLAG_DECISION gossip/replies —
    agreement's observability channel).  These compile into the jitted
    per-lane monitor term: the ``spec_holds`` evaluation lifted to the
    ``[L, ...]`` lane axis and FUSED into the LaneDriver mega-step
    (engine/executor.py LaneStep — one extra output alongside decisions,
    no second dispatch), with the eager numpy equivalent
    (InstanceMonitor) driving HostRunner so both drivers report the same
    verdict vector under the same labels.

Labels/ordering come from the ONE shared enumeration
(spec/check.py:spec_formulas): a Spec edit moves the offline checker and
the live monitors together or not at all (tests/test_rv.py pins this).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# the decision-plane monitor slots, in verdict-vector order — the SHARED
# classification (spec/check.py WIRE_MONITORS / SpecFormula.scope): a
# formula is a wire monitor here iff spec_formulas labels it scope
# "live", so this compiler and the snapshot auditor (snap/audit.py,
# which takes the "offline" side) partition the enumeration with no
# formula claimed twice and none dropped.  Matched case-insensitively
# against Spec property names so a protocol's own "Agreement" keeps its
# check_trace label on the live verdict.
from round_tpu.spec.check import (  # noqa: F401 — WIRE_MONITORS re-export
    WIRE_MONITORS, SpecFormula, spec_formulas,
)


@dataclasses.dataclass(frozen=True)
class MonitorProgram:
    """One algorithm's compiled monitor set at group size ``n``.

    labels:        verdict-vector labels, index-aligned with the ok[F]
                   output of ``check_lane`` (and InstanceMonitor).
    slots:         the WIRE_MONITORS slot each verdict implements.
    offline:       the Spec formulas NOT live-checkable at a replica
                   (full-state invariants etc.) — check_trace territory.
    decision_*:    shape/dtype of ``algo.decision`` (the carried monitor
                   state rides these).
    init_*:        shape/dtype of one process's initial value (validity's
                   witness rows are [n, *init_shape]).
    check_lane:    pure jit-safe per-lane term
                   (state_row, prev_dec, prev_val, ext_dec, ext_val,
                   init_vals) -> (ok[F] bool, decided, decision);
                   engine/executor.py vmaps it over the lane axis inside
                   the update mega-step.
    """

    algo: Any
    n: int
    labels: Tuple[str, ...]
    slots: Tuple[str, ...]
    offline: Tuple[SpecFormula, ...]
    decision_shape: Tuple[int, ...]
    decision_dtype: Any
    init_shape: Tuple[int, ...]
    init_dtype: Any
    validity_comparable: bool = True
    check_lane: Callable = dataclasses.field(repr=False, default=None)

    @property
    def n_monitors(self) -> int:
        return len(self.labels)

    def slot_index(self, slot: str) -> Optional[int]:
        try:
            return self.slots.index(slot)
        except ValueError:
            return None

    def zeros(self, lanes: int):
        """Fresh carried monitor state for ``lanes`` slots:
        (prev_decided, prev_decision, ext_decided, ext_decision,
        init_values) — the pytree threaded through the lane driver."""
        return (
            np.zeros((lanes,), dtype=bool),
            np.zeros((lanes,) + self.decision_shape,
                     dtype=self.decision_dtype),
            np.zeros((lanes,), dtype=bool),
            np.zeros((lanes,) + self.decision_shape,
                     dtype=self.decision_dtype),
            np.zeros((lanes, self.n) + self.init_shape,
                     dtype=self.init_dtype),
        )


def _probe_shapes(algo, n: int):
    """(decision shape/dtype, init shape/dtype) from one eager init-state
    probe — the instance_io contract both host loops build from."""
    from round_tpu.core.rounds import RoundCtx
    from round_tpu.runtime.host import instance_io

    io = instance_io(algo, 0)
    iv = np.asarray(io["initial_value"])
    ctx = RoundCtx(id=np.int32(0), n=n, r=np.int32(0))
    st = algo.make_init_state(ctx, io)
    dec = np.asarray(algo.decision(st))
    bool(np.asarray(algo.decided(st)).reshape(()))  # must be scalar bool
    return (tuple(dec.shape), dec.dtype, tuple(iv.shape), iv.dtype)


def _same(a, b):
    return jnp.all(jnp.asarray(a) == jnp.asarray(b))


def _impl(cond, then):
    return jnp.logical_or(jnp.logical_not(cond), then)


def monitor_program(algo, n: int) -> Optional[MonitorProgram]:
    """Compile ``algo``'s monitor set, or None when there is nothing to
    soundly monitor: no decision plane (decided/decision accessors —
    e.g. the cellular-automaton models), or a Spec that names none of
    the decision-plane properties.

    THE SPEC IS THE CONTRACT: a wire monitor compiles ONLY for the
    slots the algorithm's own Spec names (case-insensitive match on
    WIRE_MONITORS).  Guessing built-ins for unnamed slots mis-fires on
    protocols whose contract is legitimately weaker — k-set agreement
    decides up to k DISTINCT values (an exact-equality agreement
    monitor would trip on correct runs), ε-agreement decides averages
    no process proposed (a proposal-membership validity monitor would
    trip).  What the Spec does not claim, the wire does not check."""
    try:
        dshape, ddtype, ishape, idtype = _probe_shapes(algo, n)
    except Exception:  # noqa: BLE001 — no decision plane, no monitors
        return None

    enum = spec_formulas(algo.spec) if getattr(algo, "spec", None) \
        else ()
    # scope "live" IS the wire-monitor predicate (spec/check.py
    # formula_scope) — one labeling, shared with the snapshot auditor
    by_name: Dict[str, SpecFormula] = {
        e.name.lower(): e for e in enum if e.scope == "live"}
    named = [slot for slot in WIRE_MONITORS if slot in by_name]
    if not named:
        return None
    # the live labels ARE the check_trace labels — both sides read the
    # one shared enumeration (the desync-proof contract)
    labels = [by_name[slot].label for slot in named]

    # validity needs decision and initial values to be comparable; for
    # algorithms where they are not (a digest-decision protocol, say),
    # the slot degrades to vacuous-True rather than mis-firing.  EXACT
    # shape equality, not broadcastability: the fused term compares via
    # jnp broadcast while the eager twin uses np.array_equal, and only
    # identical shapes keep the two paths' verdicts identical (the
    # lanes-vs-host parity contract)
    validity_comparable = dshape == ishape

    decided_fn, decision_fn = algo.decided, algo.decision

    def check_lane(state_row, prev_dec, prev_val, ext_dec, ext_val,
                   init_vals):
        decided = jnp.asarray(decided_fn(state_row)).reshape(())
        decision = jnp.asarray(decision_fn(state_row))
        oks = []
        for slot in named:
            if slot == "agreement":
                oks.append(_impl(jnp.logical_and(decided, ext_dec),
                                 _same(decision, ext_val)))
            elif slot == "validity":
                if validity_comparable:
                    witness = jax.vmap(
                        lambda iv: _same(decision, iv))(init_vals)
                    oks.append(_impl(decided, jnp.any(witness)))
                else:
                    oks.append(jnp.asarray(True))
            else:  # irrevocability
                oks.append(_impl(prev_dec, jnp.logical_and(
                    decided, _same(decision, prev_val))))
        return jnp.stack(oks), decided, decision

    offline = tuple(e for e in enum if e.scope != "live")
    return MonitorProgram(
        algo=algo, n=n, labels=tuple(labels), slots=tuple(named),
        offline=offline, decision_shape=dshape, decision_dtype=ddtype,
        init_shape=ishape, init_dtype=idtype,
        validity_comparable=validity_comparable, check_lane=check_lane)


def schedule_init_values(algo, n: int, value_schedule: str,
                         base_value: int, inst: int) -> np.ndarray:
    """The [n, *init_shape] initial-value matrix of one SCHEDULED
    instance — deterministic in (schedule, base, pid, inst), so every
    replica computes the same validity witness set without any wire
    traffic (the same determinism the chaos harness leans on)."""
    from round_tpu.runtime.host import _schedule_value, instance_io

    rows = [np.asarray(instance_io(
        algo, _schedule_value(value_schedule, base_value, pid, inst)
    )["initial_value"]) for pid in range(n)]
    return np.stack(rows)


def eager_verdicts(p: MonitorProgram, state, prev_dec, prev_val,
                   ext_dec, ext_val, init_vals):
    """Numpy evaluation of the verdict vector on ONE lane/instance —
    the same comparisons as the fused jnp term, slot for slot, for the
    cold paths that never reach an update dispatch (HostRunner rounds,
    oob-adopted lanes).  Returns (tripped indices, decided, decision)."""
    decided = bool(np.asarray(p.algo.decided(state)).reshape(()))
    decision = np.asarray(p.algo.decision(state))
    same = np.array_equal
    ok = []
    for slot in p.slots:
        if slot == "agreement":
            ok.append(not (decided and ext_dec)
                      or same(decision, ext_val))
        elif slot == "validity":
            ok.append((not decided) or not p.validity_comparable
                      or bool(np.any([same(decision, iv)
                                      for iv in init_vals])))
        else:  # irrevocability
            ok.append((not prev_dec)
                      or (decided and same(decision, prev_val)))
    return [i for i in range(p.n_monitors) if not ok[i]], decided, \
        decision


class InstanceMonitor:
    """The Python-path monitor equivalent: one instance, one lane —
    eager numpy evaluation of EXACTLY the fused term's math, driving
    HostRunner (runtime/host.py).  Both drivers report the same verdict
    vector under the same labels (tests/test_rv.py pins lanes-vs-host
    verdict parity on the broken fixtures)."""

    __slots__ = ("program", "prev_dec", "prev_val", "ext_dec", "ext_val",
                 "init_vals")

    def __init__(self, program: MonitorProgram, init_values: np.ndarray):
        self.program = program
        self.prev_dec = False
        self.prev_val = np.zeros(program.decision_shape,
                                 dtype=program.decision_dtype)
        self.ext_dec = False
        self.ext_val = np.zeros_like(self.prev_val)
        self.init_vals = np.asarray(init_values)

    def note_ext(self, value) -> None:
        """Record a peer decision learned over the wire (FLAG_DECISION
        gossip / TooLate reply) — agreement's observability channel."""
        try:
            v = np.asarray(value, dtype=self.prev_val.dtype).reshape(
                self.prev_val.shape)
        except Exception:  # noqa: BLE001 — a garbage decision frame is
            return         # the transport's problem, not the monitor's
        self.ext_dec = True
        self.ext_val = v

    def check(self, state) -> List[int]:
        """Evaluate the verdict vector on a post-update state; returns
        the indices of TRIPPED monitors (empty = all held) and advances
        the carried (prev decided, prev decision) pair.  Pure numpy —
        same comparisons as the fused jnp term, with no per-round
        device dispatch on the Python driver's hot loop."""
        tripped, decided, decision = eager_verdicts(
            self.program, state, self.prev_dec, self.prev_val,
            self.ext_dec, self.ext_val, self.init_vals)
        self.prev_dec, self.prev_val = decided, decision
        return tripped


class HostRv:
    """One instance's monitor driver for the sequential HostRunner: the
    Python-path equivalent of the fused lane term (same verdict vector,
    same labels, same carried state), plus the violation-policy glue.
    ``values`` is the artifact proposals row the dump pipeline records.
    """

    __slots__ = ("rt", "program", "inst", "values", "mon", "shed",
                 "just_decided", "gossip")

    def __init__(self, runtime, program: MonitorProgram, inst: int,
                 init_values: np.ndarray, values, gossip: bool = True):
        self.rt = runtime
        self.program = program
        self.inst = inst
        self.values = list(values)
        self.mon = InstanceMonitor(program, init_values)
        self.shed = False
        self.just_decided = False
        self.gossip = gossip

    def _act(self, tripped: List[int], r: int, where: str) -> None:
        for fidx in tripped:
            observed = {
                "decided": bool(self.mon.prev_dec),
                "decision": _scalar(self.mon.prev_val),
                "ext_decided": bool(self.mon.ext_dec),
                "ext_decision": _scalar(self.mon.ext_val),
            }
            # violate() RAISES RvViolation itself under the halt policy
            action = self.rt.violate(
                inst=self.inst, round_=r,
                label=self.program.labels[fidx], values=self.values,
                observed=observed, where=where)
            if action == "shed":
                self.shed = True

    def after_update(self, state, r: int) -> None:
        """One completed round's verdicts (the fused term's site)."""
        was = self.mon.prev_dec
        self.rt.note_checks(self.program.n_monitors)
        tripped = self.mon.check(state)
        self.just_decided = self.mon.prev_dec and not was
        self._act(tripped, r, "round")

    def on_decision_frame(self, state, payload, r: int) -> None:
        """A FLAG_DECISION arrived mid-instance: record it for the
        agreement term and re-check NOW — the adoption that follows
        overwrites the state the conflict lives in."""
        self.mon.note_ext(payload)
        self._act(self.mon.check(state), r, "decision-adopt")


def _scalar(v) -> int:
    from round_tpu.runtime.host import decision_scalar

    return decision_scalar(np.asarray(v))


# -- leader-lease staleness bounds (round_tpu/kv, docs/KV.md) --------------
#
# The KV tier's lease reads are LICENSED by the same observability
# argument as the agreement monitor above: a replica that keeps hearing
# a quorum of its group inside a bounded window cannot have missed a
# decision wave (communication-closed rounds — every decided instance
# ran a wave this replica's quorum participated in), so its applied
# state is at most one in-flight wave stale.  The bound is therefore
# expressed in ROUNDS and converted to wall time by the driver's round
# deadline — the monitor's carried-state staleness bound, not an
# unrelated wall-clock lease.  A replica that stops hearing a quorum
# (partition, chaos drops) must REFUSE lease reads until the quorum
# returns; a tripped agreement monitor revokes the lease permanently
# (carried state is no longer trustworthy at any staleness).


def lease_bound_ms(timeout_ms: float, rounds: int = 2) -> float:
    """The lease validity window in wall time: ``rounds`` round
    deadlines.  Two rounds is the carried-state argument's minimum — one
    full wave may be in flight past the last quorum heard, and one more
    deadline bounds how long that wave can linger before this replica's
    own timeout fires and it re-observes the quorum (or stops serving)."""
    return float(rounds) * float(timeout_ms)


class LeaseClock:
    """Quorum-heard staleness clock for lease reads (one per driver).

    ``note_peer(pid)`` records round traffic from a consensus peer; the
    lease is VALID while at least ``quorum`` distinct peers (self
    included) have been heard within ``bound_ms``.  ``revoke()`` kills
    the lease for good — the agreement monitor's carried state tripped,
    so no staleness window makes local reads safe again."""

    def __init__(self, n: int, my_id: int, bound_ms: float,
                 quorum: Optional[int] = None):
        import time as _time

        self.n = n
        self.id = my_id
        self.bound_ms = float(bound_ms)
        self.quorum = quorum if quorum is not None else n // 2 + 1
        self._now = _time.monotonic
        self._heard: Dict[int, float] = {my_id: self._now()}
        self._last_quorum = float("-inf")
        self.revoked = False
        self.refusals = 0
        self.grants = 0

    def note_peer(self, pid: int) -> None:
        if 0 <= pid < self.n:
            self._heard[pid] = self._now()

    def note_quorum(self) -> None:
        """A round advanced by THRESHOLD (not deadline): the driver just
        heard >= n-f distinct peers inside one round trip, which is the
        strongest freshness evidence there is.  This is the signal the
        native round pump feeds (per-peer frames never surface to
        Python there — only round progress does)."""
        self._last_quorum = self._now()

    def valid(self, now: Optional[float] = None) -> bool:
        """One lease check: quorum heard inside the staleness bound and
        the agreement monitor never tripped.  Counts grants/refusals —
        the kv.lease_* observability surface reads them."""
        if self.revoked:
            self.refusals += 1
            return False
        t = self._now() if now is None else now
        self._heard[self.id] = t  # self is always current
        horizon = t - self.bound_ms / 1000.0
        fresh = sum(1 for ts in self._heard.values() if ts >= horizon)
        if fresh >= self.quorum or self._last_quorum >= horizon:
            self.grants += 1
            return True
        self.refusals += 1
        return False

    def revoke(self) -> None:
        self.revoked = True
