"""Eager reliable broadcast (one instance = one broadcast).

Protocol (reference: example/EagerReliableBroadcast.scala:13-47): the
originator starts with Some(v); every process that knows the value
rebroadcasts it once, delivers, and exits; processes that receive it adopt
it (``head`` of a non-empty mailbox); a process that hears nothing for 10
rounds gives up (the originator crashed before anyone got it).

In the reference each broadcast runs as its own instance started lazily by
the defaultHandler on the first incoming message (ERBRunner.defaultHandler);
here that multiplexing is the InstancePool batch axis.
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.rounds import Round, RoundCtx, broadcast
from round_tpu.ops.mailbox import Mailbox

# a process that hears nothing for this many rounds gives up (the
# originator crashed before anyone got the value) — ONE constant shared
# with the fused path (engine.fast.ErbHist) so the engines cannot drift
GIVE_UP_ROUND = 10


@flax.struct.dataclass
class ErbState:
    x_val: jnp.ndarray      # int32 (the broadcast value, if known)
    x_def: jnp.ndarray      # bool — x.isDefined
    delivered: jnp.ndarray  # bool ghost (deliver callback fired)
    delivery: jnp.ndarray   # int32 ghost

    @classmethod
    def fresh(cls, io: dict, S: int, n: int) -> "ErbState":
        """[S, n]-batched undelivered state from a broadcast_io dict — the
        one constructor every fused/sharded/soak call site shares."""
        return cls(
            x_val=jnp.broadcast_to(
                jnp.asarray(io["value"], jnp.int32), (S, n)),
            x_def=jnp.broadcast_to(jnp.asarray(io["is_origin"], bool), (S, n)),
            delivered=jnp.zeros((S, n), bool),
            delivery=jnp.full((S, n), -1, jnp.int32),
        )


class ErbRound(Round):
    def send(self, ctx: RoundCtx, state: ErbState):
        return broadcast(ctx, state.x_val, guard=state.x_def)

    def update(self, ctx: RoundCtx, state: ErbState, mbox: Mailbox):
        got_any = mbox.size() > 0
        adopted = mbox.any_value()

        delivering = state.x_def
        give_up = ~state.x_def & ~got_any & (ctx.r > GIVE_UP_ROUND)
        ctx.exit_at_end_of_round(delivering | give_up)
        newly = delivering & ~state.delivered
        return state.replace(
            x_val=jnp.where(~state.x_def & got_any, adopted, state.x_val),
            x_def=state.x_def | got_any,
            delivered=state.delivered | delivering,
            delivery=jnp.where(newly, state.x_val, state.delivery),
        )


class EagerReliableBroadcast(Algorithm):
    """Uniform reliable broadcast: if any correct process delivers v, every
    correct process delivers v."""

    def __init__(self):
        self.rounds = (ErbRound(),)

    def make_init_state(self, ctx: RoundCtx, io) -> ErbState:
        return ErbState(
            x_val=jnp.asarray(io["value"], dtype=jnp.int32),
            x_def=jnp.asarray(io["is_origin"], dtype=bool),
            delivered=jnp.asarray(False),
            delivery=jnp.asarray(-1, dtype=jnp.int32),
        )

    def decided(self, state: ErbState):
        return state.delivered

    def decision(self, state: ErbState):
        return state.delivery


def broadcast_io(origin: int, value: int, n: int) -> dict:
    """io: process ``origin`` broadcasts ``value`` (BroadcastIO semantics:
    Some(v) at the origin, None elsewhere)."""
    ids = jnp.arange(n)
    return {
        "value": jnp.where(ids == origin, value, 0).astype(jnp.int32),
        "is_origin": ids == origin,
    }
