"""Θ-model round synchronizer (Widder & Schmid clock sync).

Reference: example/ThetaModel.scala:34-105 — Θ bounds the ratio of longest to
shortest end-to-end delays; the algorithm builds synchronized logical rounds
on top: a process fires logical round ``round`` when the physical round
counter hits ``nextRoundAt`` (3Θ(round+1)+1 for known Θ, the triangular
schedule for unknown Θ), sending Some(payload) then; otherwise it broadcasts
None.  Receivers deliver defined payloads and advance on n-f messages.

Payload here is the sender's logical round (the reference ships an opaque A
from TmIO.getMessage); deliveries are recorded as the highest logical round
heard per peer — enough to state the Θ-model sync property (logical clocks
within 1 of each other under bounded-delay HO families).
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.rounds import Round, RoundCtx, broadcast
from round_tpu.ops.mailbox import Mailbox


@flax.struct.dataclass
class ThetaState:
    round: jnp.ndarray         # int32 logical round
    next_round_at: jnp.ndarray # int32 physical round of the next fire
    heard: jnp.ndarray         # [n] int32 — highest logical round heard per peer


def _next_round_at(theta: float, round_):
    if theta >= 1:
        return (3 * theta * (round_ + 1)).astype(jnp.int32) + 1
    # unknown theta: triangular schedule (ThetaModel.scala:49-51)
    return (round_ + 1) * (round_ + 2) // 2


class ThetaRound(Round):
    def __init__(self, f: int, theta: float):
        self.f = f
        self.theta = float(theta)

    def send(self, ctx: RoundCtx, state: ThetaState):
        firing = ctx.r == state.next_round_at
        return broadcast(ctx, {"defined": firing, "round": state.round})

    def update(self, ctx: RoundCtx, state: ThetaState, mbox: Mailbox):
        defined = mbox.mask & mbox.values["defined"]
        heard = jnp.where(
            defined,
            jnp.maximum(state.heard, mbox.values["round"]),
            state.heard,
        )
        firing = ctx.r == state.next_round_at
        new_round = jnp.where(firing, state.round + 1, state.round)
        nra = jnp.where(
            firing,
            _next_round_at(self.theta, new_round),
            state.next_round_at,
        )
        return state.replace(round=new_round, next_round_at=nra, heard=heard)


class ThetaModel(Algorithm):
    """Logical rounds synchronized by the Θ delay-ratio assumption."""

    def __init__(self, f: int = 1, theta: float = 2.0):
        self.f = f
        self.theta = theta
        self.rounds = (ThetaRound(f, theta),)

    def make_init_state(self, ctx: RoundCtx, io) -> ThetaState:
        r0 = jnp.asarray(0, dtype=jnp.int32)
        return ThetaState(
            round=r0,
            next_round_at=jnp.asarray(_next_round_at(self.theta, r0), jnp.int32),
            heard=jnp.full((ctx.n,), -1, dtype=jnp.int32),
        )
