"""Two-Phase Commit with event rounds: blocking / timeout / all-or-quorum.

Protocol (reference: example/TwoPhaseCommitEvent.scala:26-114): the same
3-round 2PC as the closed model, but with the reference's two progress
modes per round:

  blocking=True  → Progress.waitMessage: the round cannot end until its
    goAhead condition fires.  In the lockstep HO model a lane whose
    condition never fires is DEADLOCKED (the reference process waits
    forever); it freezes — ``blocked`` ghost set, lane exits undecided.
  blocking=False → Progress.timeout: the round ends anyway and the handler
    sees didTimeout (the reference default; decisions may then be taken on
    partial information, exactly as in the reference).

  ``all``: round 2's coordinator waits for ALL n votes before committing;
  with all=False it short-circuits to abort on the first NO
  (TwoPhaseCommitEvent.scala:64-66: (!all && !ok) || nMsg == n).

Rounds:
  1: coord broadcasts PrepareCommit; any message → goAhead (:36-48).
  2: everyone votes to coord; coord folds ok &= vote (:54-75); decision is
     set from the heard votes even on timeout (finishRound, :69-74).
  3: coord broadcasts the decision; receivers decide it; a lane that heard
     nothing decides None (-1, coordinator suspected); everyone exits
     (finishRound returns false, :95-101).

Decision encoding matches models/tpc.py: {-1 = None, 0 = abort, 1 = commit}.
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.rounds import FoldRound, RoundCtx, broadcast, unicast
from round_tpu.models.tpc import DEC_ABORT, DEC_COMMIT, DEC_NONE


@flax.struct.dataclass
class TpcEState:
    coord: jnp.ndarray     # int32, fixed coordinator id
    vote: jnp.ndarray      # bool, this process's canCommit
    decision: jnp.ndarray  # int32 in {-1, 0, 1}
    decided: jnp.ndarray   # bool (ghost: callback fired)
    blocked: jnp.ndarray   # bool (ghost: waitMessage deadlock)


class _TpcERound(FoldRound):
    def __init__(self, blocking: bool, all_votes: bool):
        self.blocking = blocking
        self.all_votes = all_votes

    def _block_or_pass(self, ctx, state, ok_to_proceed):
        """waitMessage semantics: a lane whose condition did not fire
        freezes (deadlock ghost) instead of timing out."""
        if not self.blocking:
            return state
        newly_blocked = ~ok_to_proceed & ~state.blocked
        ctx.exit_at_end_of_round(newly_blocked)
        return state.replace(blocked=state.blocked | newly_blocked)


class TpcEPrepare(_TpcERound):
    """Round 1: PrepareCommit broadcast; heard anything → goAhead."""

    def send(self, ctx: RoundCtx, state: TpcEState):
        return broadcast(ctx, jnp.asarray(True), guard=ctx.id == state.coord)

    def zero(self, ctx: RoundCtx, state: TpcEState):
        return jnp.asarray(False)

    def lift(self, ctx: RoundCtx, state: TpcEState, sender, payload):
        return jnp.asarray(True)

    def combine(self, a, b):
        return a | b

    def reduce(self, ctx: RoundCtx, state: TpcEState, lifted, mask):
        # OR-monoid: the tree fold is any() over the present senders
        return jnp.any(jnp.where(mask, lifted, False))

    def go_ahead(self, ctx: RoundCtx, state: TpcEState, m, count):
        return m

    def post(self, ctx: RoundCtx, state: TpcEState, m, count, did_timeout):
        return self._block_or_pass(ctx, state, ~did_timeout)


class TpcEVote(_TpcERound):
    """Round 2: votes to coord; ok &= payload; decision from heard votes."""

    def send(self, ctx: RoundCtx, state: TpcEState):
        return unicast(ctx, state.coord, state.vote)

    def zero(self, ctx: RoundCtx, state: TpcEState):
        return jnp.asarray(True)

    def lift(self, ctx: RoundCtx, state: TpcEState, sender, payload):
        return payload

    def combine(self, a, b):
        return a & b

    def reduce(self, ctx: RoundCtx, state: TpcEState, lifted, mask):
        # AND-monoid: the tree fold is all() over the present senders
        return jnp.all(jnp.where(mask, lifted, True))

    def go_ahead(self, ctx: RoundCtx, state: TpcEState, m, count):
        nonc = ctx.id != state.coord
        full = count == ctx.n
        early_no = (~m) if not self.all_votes else jnp.asarray(False)
        return nonc | full | early_no

    def post(self, ctx: RoundCtx, state: TpcEState, m, count, did_timeout):
        is_coord = ctx.id == state.coord
        dec = jnp.where(m, DEC_COMMIT, DEC_ABORT).astype(jnp.int32)
        # timeout mode: finishRound runs even on timeout (:69-74) — the
        # coordinator judges the votes it heard.  blocking mode: a starved
        # lane never reaches finishRound (waitMessage), so no decision is
        # stamped before the freeze.
        act = is_coord & ~state.blocked
        if self.blocking:
            act = act & ~did_timeout
        state = state.replace(
            decision=jnp.where(act, dec, state.decision)
        )
        return self._block_or_pass(ctx, state, ~did_timeout)


class TpcECommit(_TpcERound):
    """Round 3: decision broadcast; decide whatever arrived (None if
    nothing); everyone exits."""

    def send(self, ctx: RoundCtx, state: TpcEState):
        return broadcast(
            ctx, state.decision == DEC_COMMIT,
            guard=(ctx.id == state.coord) & ~state.blocked,
        )

    def zero(self, ctx: RoundCtx, state: TpcEState):
        return {"got": jnp.asarray(False), "v": jnp.asarray(False)}

    def lift(self, ctx: RoundCtx, state: TpcEState, sender, payload):
        return {"got": jnp.asarray(True), "v": payload}

    def combine(self, a, b):
        return {"got": a["got"] | b["got"],
                "v": jnp.where(b["got"], b["v"], a["v"])}

    def reduce(self, ctx: RoundCtx, state: TpcEState, lifted, mask):
        # last-sender-wins fold: the winner is the highest-id present
        # sender (sender-id fold order) — an argmax over masked ids
        # (mask.shape, not ctx.n: n may be traced under extraction)
        got = jnp.any(mask)
        idx = jnp.argmax(jnp.where(mask, jnp.arange(mask.shape[0]), -1))
        return {"got": got, "v": jnp.where(got, lifted["v"][idx], False)}

    def go_ahead(self, ctx: RoundCtx, state: TpcEState, m, count):
        return m["got"]

    def post(self, ctx: RoundCtx, state: TpcEState, m, count, did_timeout):
        # blocking: a lane that missed the decision broadcast waits forever
        # (waitMessage) — it freezes instead of deciding None
        state = self._block_or_pass(ctx, state, ~did_timeout)
        dec = jnp.where(
            m["got"],
            jnp.where(m["v"], DEC_COMMIT, DEC_ABORT),
            DEC_NONE,
        ).astype(jnp.int32)
        live = ~state.blocked
        state = state.replace(
            decision=jnp.where(live, dec, state.decision),
            decided=state.decided | live,
        )
        ctx.exit_at_end_of_round(True)  # finishRound returns false (:101)
        return state


class TwoPhaseCommitEvent(Algorithm):
    """Event-round 2PC (TwoPhaseCommitEvent.scala:26-114).

    blocking: waitMessage mode (lanes freeze on missing messages).
    all_votes: coordinator needs all n votes (no early abort short-circuit).
    """

    def __init__(self, blocking: bool = False, all_votes: bool = False):
        self.blocking = blocking
        self.all_votes = all_votes
        self.rounds = (
            TpcEPrepare(blocking, all_votes),
            TpcEVote(blocking, all_votes),
            TpcECommit(blocking, all_votes),
        )

    def make_init_state(self, ctx: RoundCtx, io) -> TpcEState:
        return TpcEState(
            coord=jnp.asarray(io["coord"], dtype=jnp.int32),
            vote=jnp.asarray(io["can_commit"], dtype=bool),
            decision=jnp.asarray(DEC_NONE, dtype=jnp.int32),
            decided=jnp.asarray(False),
            blocked=jnp.asarray(False),
        )

    def decided(self, state: TpcEState):
        return state.decided

    def decision(self, state: TpcEState):
        return state.decision
