"""◇S eventually-strong failure detector (heartbeat + hysteresis).

Protocol (reference: example/EventuallyStrongFailureDetector.scala:10-58):
every period each process bumps a per-peer ``lastSeen`` counter (capped at
hysteresis+1), broadcasts its suspected set {p : lastSeen(p) > hysteresis},
zeroes the counter of every sender it hears, and adopts others' suspicions
(a suspected peer it did not hear this round jumps straight past the
hysteresis threshold).

The reference's per-message EventRound receive loop is order-insensitive in
aggregate (a present sender always ends unsuspected; an absent peer suspected
by any present sender trips the threshold), so the update vectorizes to three
masked writes.
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.rounds import Round, RoundCtx, broadcast
from round_tpu.ops.mailbox import Mailbox


@flax.struct.dataclass
class EsfdState:
    last_seen: jnp.ndarray  # [n] int32, capped at hysteresis+1


class EsfdRound(Round):
    def __init__(self, hysteresis: int):
        self.h = hysteresis

    def suspected(self, state: EsfdState) -> jnp.ndarray:
        return state.last_seen > self.h

    def send(self, ctx: RoundCtx, state: EsfdState):
        return broadcast(ctx, self.suspected(state))

    def update(self, ctx: RoundCtx, state: EsfdState, mbox: Mailbox):
        h = self.h
        present = mbox.mask            # [n] senders heard this round
        sus = mbox.values              # [n, n] suspected sets

        # init slot: lastSeen := min(lastSeen + 1, h + 1)
        ls = jnp.minimum(state.last_seen + 1, h + 1)
        # adopt suspicions of peers we did not hear this round...
        accused = jnp.any(present[:, None] & sus, axis=0)
        ls = jnp.where(accused & ~present, h + 1, ls)
        # ...and zero the counter of everyone we heard (wins over adoption)
        ls = jnp.where(present, 0, ls)
        return state.replace(last_seen=ls)


class Esfd(Algorithm):
    """◇S: eventually every crashed process is suspected by all correct
    processes and some correct process is never suspected."""

    def __init__(self, hysteresis: int = 5):
        self.hysteresis = hysteresis
        self.rounds = (EsfdRound(hysteresis),)

    def make_init_state(self, ctx: RoundCtx, io) -> EsfdState:
        return EsfdState(last_seen=jnp.zeros((ctx.n,), dtype=jnp.int32))

    def suspected(self, state: EsfdState) -> jnp.ndarray:
        """[n_lanes, n] suspicion matrix accessor."""
        return state.last_seen > self.hysteresis
