"""PBFT-style single-decision byzantine consensus (pre-prepare / prepare /
commit with digest checks).

Reference: example/byzantine/test/Consensus.scala:26-165 (``Bcp``): 3-round
phases with coordinator ``coord = (r/3) % n``:

  pre-prepare: coord broadcasts (request, digest); receivers adopt the
    request, recompute the digest and null out on mismatch; a lane that
    fails to get a valid request decides null and stops.
  prepare: broadcast your digest; more than 2n/3 matches -> prepared.
  commit: the prepared broadcast the digest; more than 2n/3 matches ->
    decide(x), else decide(null).  The instance terminates either way.

Digests here are an int32 mixing hash of the int request (SHA-256 in the
reference); byzantine payload corruption that breaks the (request, digest)
pair is caught exactly like a failed MessageDigest.isEqual.  Run under
``scenarios.byzantine_silence`` + ``sync_k_filter(n - f)`` masks and/or the
``utils.byzantine`` payload adversary; tolerates f < n/3.

Decision encoding: int32, -1 = null (aborted / suspected coordinator).
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.rounds import Round, RoundCtx, broadcast
from round_tpu.models.common import ghost_decide
from round_tpu.ops.mailbox import Mailbox

DECIDE_NULL = -1


def digest(x: jnp.ndarray) -> jnp.ndarray:
    """Cheap int32 mixing hash standing in for SHA-256 (collision-resistance
    is not the point of the *model*; pair-consistency checking is)."""
    h = x.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    return (h ^ (h >> 13)).astype(jnp.int32)


@flax.struct.dataclass
class BcpState:
    x: jnp.ndarray         # int32 request
    dig: jnp.ndarray       # int32 digest of x
    valid: jnp.ndarray     # bool — x is non-null and digest-consistent
    prepared: jnp.ndarray  # bool
    decided: jnp.ndarray
    decision: jnp.ndarray  # int32, -1 = null


def _coord(ctx: RoundCtx):
    return (ctx.r // 3) % ctx.n


class BcpPrePrepare(Round):
    def send(self, ctx: RoundCtx, state: BcpState):
        return broadcast(
            ctx,
            {"req": state.x, "dig": state.dig},
            guard=ctx.id == _coord(ctx),
        )

    def update(self, ctx: RoundCtx, state: BcpState, mbox: Mailbox):
        coord = _coord(ctx)
        got = mbox.contains(coord)
        req = mbox.values["req"][coord]
        claimed = mbox.values["dig"][coord]
        recomputed = digest(req)

        is_coord = ctx.id == coord
        adopt = got & ~is_coord
        x = jnp.where(adopt, req, state.x)
        dig = jnp.where(adopt, recomputed, state.dig)
        valid = jnp.where(adopt, recomputed == claimed, state.valid)

        # finishRound: abort on no/invalid request (Consensus.scala:90-97)
        fail = ~got | ~valid
        ctx.exit_at_end_of_round(fail)
        state = ghost_decide(state, fail, jnp.asarray(DECIDE_NULL))
        return state.replace(x=x, dig=dig, valid=valid)


class BcpPrepare(Round):
    def send(self, ctx: RoundCtx, state: BcpState):
        return broadcast(ctx, {"dig": state.dig, "ok": state.valid})

    def update(self, ctx: RoundCtx, state: BcpState, mbox: Mailbox):
        confirmed = mbox.count(
            lambda m: m["ok"] & (m["dig"] == state.dig)
        )
        return state.replace(prepared=confirmed > 2 * ctx.n // 3)


class BcpCommit(Round):
    def send(self, ctx: RoundCtx, state: BcpState):
        return broadcast(ctx, state.dig, guard=state.prepared)

    def update(self, ctx: RoundCtx, state: BcpState, mbox: Mailbox):
        confirmed = mbox.count(lambda d: d == state.dig)
        committed = confirmed > 2 * ctx.n // 3
        ctx.exit_at_end_of_round(True)  # terminate either way (:160)
        return ghost_decide(
            state, jnp.asarray(True), jnp.where(committed, state.x, DECIDE_NULL)
        )


# ---------------------------------------------------------------------------
# View change (example/byzantine/pbft/ViewChange.scala — the reference ships
# only this unsigned SKETCH and never wires it to its consensus; here the
# round family is executable and composed with the 3-phase decision)
# ---------------------------------------------------------------------------

def cert_digest(req: jnp.ndarray, pv: jnp.ndarray) -> jnp.ndarray:
    """Digest of a (request, prepared-view) certificate — the
    ViewChangeAck's per-sender confirmation token (ViewChange.scala:20-22:
    `d` is the digest of the message being acknowledged)."""
    return digest(req.astype(jnp.int32) * jnp.int32(31) + pv.astype(jnp.int32))


@flax.struct.dataclass
class PbftVcState:
    # consensus core (BcpState semantics, at the CURRENT view)
    x: jnp.ndarray          # int32 request
    dig: jnp.ndarray        # int32 digest of x
    valid: jnp.ndarray      # bool
    prepared: jnp.ndarray   # bool (this view)
    decided: jnp.ndarray
    decision: jnp.ndarray
    # view bookkeeping
    view: jnp.ndarray       # int32 current view; coord = view % n
    next_view: jnp.ndarray  # int32 target view while vc_active
    vc_active: jnp.ndarray  # bool — participating in a view change
    # prepared certificate (survives across views; ViewChange.scala 𝓟)
    prep_req: jnp.ndarray   # int32
    prep_view: jnp.ndarray  # int32, -1 = none
    # the reference's distributedState (ViewChange.scala:73): the VC1
    # messages this lane holds, as [n] vectors (every lane accumulates —
    # the new primary selects from them, receivers confirm acks with them)
    vc_heard: jnp.ndarray   # [n] bool
    vc_req: jnp.ndarray     # [n] int32
    vc_pv: jnp.ndarray      # [n] int32
    # VC2 outcome at the would-be new primary
    sel_req: jnp.ndarray    # int32 — the new view's request
    nv_ok: jnp.ndarray      # bool — confirmed-certificate quorum reached

    @classmethod
    def fresh(cls, x0: jnp.ndarray, S: int, n: int) -> "PbftVcState":
        """The batched [S, n] initial state (the OtrState.fresh precedent):
        ONE constructor shared by the fused engine's callers — tests, the
        soak, benches — so a field added here cannot desynchronize them."""
        i32 = jnp.int32
        return cls(
            x=jnp.broadcast_to(x0, (S, n)),
            dig=jnp.broadcast_to(digest(x0), (S, n)),
            valid=jnp.ones((S, n), bool),
            prepared=jnp.zeros((S, n), bool),
            decided=jnp.zeros((S, n), bool),
            decision=jnp.full((S, n), DECIDE_NULL, i32),
            view=jnp.zeros((S, n), i32),
            next_view=jnp.zeros((S, n), i32),
            vc_active=jnp.zeros((S, n), bool),
            prep_req=jnp.zeros((S, n), i32),
            prep_view=jnp.full((S, n), -1, i32),
            vc_heard=jnp.zeros((S, n, n), bool),
            vc_req=jnp.zeros((S, n, n), i32),
            vc_pv=jnp.full((S, n, n), -1, i32),
            sel_req=jnp.zeros((S, n), i32),
            nv_ok=jnp.zeros((S, n), bool),
        )


def _vc_coord(state: PbftVcState, ctx: RoundCtx):
    """Primary of the CURRENT view (PBFT rotation: view mod n)."""
    return (state.view % ctx.n).astype(jnp.int32)


class VcPrePrepare(Round):
    """Pre-prepare at the current view; failure starts a view change
    instead of deciding null (the composition the reference sketch never
    does)."""

    def send(self, ctx: RoundCtx, state: PbftVcState):
        return broadcast(
            ctx,
            {"req": state.x, "dig": state.dig, "view": state.view},
            guard=(ctx.id == _vc_coord(state, ctx)) & ~state.vc_active,
        )

    def update(self, ctx: RoundCtx, state: PbftVcState, mbox: Mailbox):
        coord = _vc_coord(state, ctx)
        got = mbox.contains(coord) & (mbox.values["view"][coord] == state.view)
        req = mbox.values["req"][coord]
        claimed = mbox.values["dig"][coord]
        recomputed = digest(req)

        active = ~state.vc_active & ~state.decided
        is_coord = ctx.id == coord
        adopt = got & ~is_coord & active
        x = jnp.where(adopt, req, state.x)
        dig = jnp.where(adopt, recomputed, state.dig)
        valid = jnp.where(adopt, recomputed == claimed, state.valid)

        # no/invalid request: this primary is suspect — trigger view change
        fail = active & (~got | ~valid)
        return state.replace(
            x=x, dig=dig, valid=valid,
            vc_active=state.vc_active | fail,
            next_view=jnp.where(fail, state.view + 1, state.next_view),
        )


class VcPrepare(Round):
    def send(self, ctx: RoundCtx, state: PbftVcState):
        return broadcast(
            ctx,
            {"dig": state.dig, "ok": state.valid, "view": state.view},
            guard=~state.vc_active,
        )

    def update(self, ctx: RoundCtx, state: PbftVcState, mbox: Mailbox):
        confirmed = mbox.count(
            lambda m: m["ok"] & (m["dig"] == state.dig)
            & (m["view"] == state.view)
        )
        prepared = (confirmed > 2 * ctx.n // 3) & ~state.vc_active \
            & ~state.decided
        # the prepared CERTIFICATE outlives the view (ViewChange.scala 𝓟)
        return state.replace(
            prepared=prepared,
            prep_req=jnp.where(prepared, state.x, state.prep_req),
            prep_view=jnp.where(prepared, state.view, state.prep_view),
        )


class VcCommit(Round):
    def send(self, ctx: RoundCtx, state: PbftVcState):
        return broadcast(
            ctx,
            {"dig": state.dig, "view": state.view},
            guard=state.prepared & ~state.vc_active,
        )

    def update(self, ctx: RoundCtx, state: PbftVcState, mbox: Mailbox):
        confirmed = mbox.count(
            lambda m: (m["dig"] == state.dig) & (m["view"] == state.view)
        )
        active = ~state.vc_active & ~state.decided
        committed = (confirmed > 2 * ctx.n // 3) & active
        state = ghost_decide(state, committed, state.x)
        ctx.exit_at_end_of_round(state.decided)
        # an uncommitted phase rotates the primary (PBFT liveness), it
        # does NOT abort the instance like the reference's 3-phase test
        fail = active & ~committed
        return state.replace(
            vc_active=state.vc_active | fail,
            next_view=jnp.where(fail, state.view + 1, state.next_view),
        )


class VcViewChange(Round):
    """ViewChange.scala round 1: broadcast the prepared certificate for
    next_view; every lane accumulates certificates (distributedState)."""

    def send(self, ctx: RoundCtx, state: PbftVcState):
        return broadcast(
            ctx,
            {"nv": state.next_view, "pr": state.prep_req,
             "pv": state.prep_view},
            guard=state.vc_active,
        )

    def update(self, ctx: RoundCtx, state: PbftVcState, mbox: Mailbox):
        match = mbox.mask & (mbox.values["nv"] == state.next_view)
        keep = state.vc_active & ~state.decided
        return state.replace(
            vc_heard=jnp.where(keep, match, jnp.zeros_like(state.vc_heard)),
            vc_req=jnp.where(keep, mbox.values["pr"], state.vc_req),
            vc_pv=jnp.where(keep & match, mbox.values["pv"],
                            jnp.full_like(state.vc_pv, -1)),
        )


class VcViewChangeAck(Round):
    """ViewChange.scala round 2: ack the held certificates by digest; the
    new primary keeps certificates confirmed by > n/3 acks (at least one
    correct witness) and, on a > 2n/3 confirmed quorum, selects the
    max-prepared-view request (the PBFT new-view computation collapsed to
    the single-decision case: no checkpoints, L = 1)."""

    def send(self, ctx: RoundCtx, state: PbftVcState):
        ackd = jnp.where(
            state.vc_heard, cert_digest(state.vc_req, state.vc_pv),
            jnp.int32(-1),
        )
        return broadcast(
            ctx,
            {"nv": state.next_view, "ackd": ackd},
            guard=state.vc_active,
        )

    def update(self, ctx: RoundCtx, state: PbftVcState, mbox: Mailbox):
        n = ctx.n
        my_cert = cert_digest(state.vc_req, state.vc_pv)        # [n]
        acker_ok = mbox.mask & (mbox.values["nv"] == state.next_view)
        # confirm[j] = #{ ackers i : ackd[i, j] matches my cert j }
        matches = (mbox.values["ackd"] == my_cert[None, :]) \
            & acker_ok[:, None]                                  # [n, n]
        confirm = jnp.sum(matches.astype(jnp.int32), axis=0)
        confirmed = state.vc_heard & (confirm > n // 3)
        quorum = jnp.sum(confirmed.astype(jnp.int32)) > 2 * n // 3

        # select max prepared view among confirmed certificates; ties go
        # to the smallest sender id; no prepared certificate -> own x
        # (the null-request branch of the new-view computation)
        has_prep = confirmed & (state.vc_pv >= 0)
        key = jnp.where(has_prep, state.vc_pv, jnp.int32(-2))
        best = jnp.argmax(
            key == jnp.max(key)
        )
        any_prep = jnp.any(has_prep)
        sel = jnp.where(any_prep, state.vc_req[best], state.x)

        keep = state.vc_active & ~state.decided
        return state.replace(
            sel_req=jnp.where(keep, sel, state.sel_req),
            nv_ok=jnp.where(keep, quorum, state.nv_ok),
        )


class VcNewView(Round):
    """ViewChange.scala round 3: the new primary broadcasts the new view;
    receivers install it (view := nv, x := selected request) and resume
    consensus; lanes that miss it retry at next_view + 1 (finishRound)."""

    def send(self, ctx: RoundCtx, state: PbftVcState):
        is_new_coord = ctx.id == (state.next_view % ctx.n).astype(jnp.int32)
        return broadcast(
            ctx,
            {"nv": state.next_view, "sel": state.sel_req},
            guard=state.vc_active & is_new_coord & state.nv_ok,
        )

    def update(self, ctx: RoundCtx, state: PbftVcState, mbox: Mailbox):
        nc = (state.next_view % ctx.n).astype(jnp.int32)
        got = mbox.contains(nc) & (mbox.values["nv"][nc] == state.next_view)
        sel = mbox.values["sel"][nc]

        keep = state.vc_active & ~state.decided
        install = keep & got
        retry = keep & ~got
        return state.replace(
            view=jnp.where(install, state.next_view, state.view),
            x=jnp.where(install, sel, state.x),
            dig=jnp.where(install, digest(sel), state.dig),
            valid=jnp.where(install, True, state.valid),
            prepared=jnp.where(install, False, state.prepared),
            vc_active=jnp.where(install, False, state.vc_active),
            next_view=jnp.where(retry, state.next_view + 1,
                                state.next_view),
        )


class PbftViewChange(Algorithm):
    """PBFT consensus WITH primary rotation: 6-round phases — pre-prepare /
    prepare / commit (failure starts a view change instead of deciding
    null), then view-change / ack / new-view (ViewChange.scala's three
    EventRounds, executable and composed).  Decides through a faulty
    primary; f < n/3."""

    # byzantine-grade envelope: f counts VALUE adversaries (liars), not
    # just crashes — the round_tpu/byz cross-check budgets (n-1)//3
    # liars INSIDE this envelope
    fault_envelope = "n > 3f"
    adversary_model = "byzantine"
    decision_null = DECIDE_NULL

    def __init__(self):
        self.rounds = (
            VcPrePrepare(), VcPrepare(), VcCommit(),
            VcViewChange(), VcViewChangeAck(), VcNewView(),
        )

    def make_init_state(self, ctx: RoundCtx, io) -> PbftVcState:
        x = jnp.asarray(io["initial_value"], dtype=jnp.int32)
        n = ctx.n
        i32 = jnp.int32
        return PbftVcState(
            x=x,
            dig=digest(x),
            valid=jnp.asarray(True),
            prepared=jnp.asarray(False),
            decided=jnp.asarray(False),
            decision=jnp.asarray(DECIDE_NULL, dtype=i32),
            view=jnp.asarray(0, dtype=i32),
            next_view=jnp.asarray(0, dtype=i32),
            vc_active=jnp.asarray(False),
            prep_req=jnp.asarray(0, dtype=i32),
            prep_view=jnp.asarray(-1, dtype=i32),
            vc_heard=jnp.zeros((n,), dtype=bool),
            vc_req=jnp.zeros((n,), dtype=i32),
            vc_pv=jnp.full((n,), -1, dtype=i32),
            sel_req=jnp.asarray(0, dtype=i32),
            nv_ok=jnp.asarray(False),
        )

    def decided(self, state: PbftVcState):
        return state.decided

    def decision(self, state: PbftVcState):
        return state.decision


class PbftConsensus(Algorithm):
    """Single-decision PBFT-style consensus, f < n/3 byzantine."""

    fault_envelope = "n > 3f"      # see PbftViewChange: byzantine-grade
    adversary_model = "byzantine"
    decision_null = DECIDE_NULL

    def __init__(self, synchronized: bool = False):
        rounds = (BcpPrePrepare(), BcpPrepare(), BcpCommit())
        if synchronized:
            from round_tpu.utils.byzantine import synchronize

            rounds = synchronize(rounds)
        self.rounds = rounds

    def make_init_state(self, ctx: RoundCtx, io) -> BcpState:
        x = jnp.asarray(io["initial_value"], dtype=jnp.int32)
        return BcpState(
            x=x,
            dig=digest(x),
            valid=jnp.asarray(True),
            prepared=jnp.asarray(False),
            decided=jnp.asarray(False),
            decision=jnp.asarray(DECIDE_NULL, dtype=jnp.int32),
        )

    def decided(self, state: BcpState):
        return state.decided

    def decision(self, state: BcpState):
        return state.decision
