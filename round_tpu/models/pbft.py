"""PBFT-style single-decision byzantine consensus (pre-prepare / prepare /
commit with digest checks).

Reference: example/byzantine/test/Consensus.scala:26-165 (``Bcp``): 3-round
phases with coordinator ``coord = (r/3) % n``:

  pre-prepare: coord broadcasts (request, digest); receivers adopt the
    request, recompute the digest and null out on mismatch; a lane that
    fails to get a valid request decides null and stops.
  prepare: broadcast your digest; more than 2n/3 matches -> prepared.
  commit: the prepared broadcast the digest; more than 2n/3 matches ->
    decide(x), else decide(null).  The instance terminates either way.

Digests here are an int32 mixing hash of the int request (SHA-256 in the
reference); byzantine payload corruption that breaks the (request, digest)
pair is caught exactly like a failed MessageDigest.isEqual.  Run under
``scenarios.byzantine_silence`` + ``sync_k_filter(n - f)`` masks and/or the
``utils.byzantine`` payload adversary; tolerates f < n/3.

Decision encoding: int32, -1 = null (aborted / suspected coordinator).
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.rounds import Round, RoundCtx, broadcast
from round_tpu.models.common import ghost_decide
from round_tpu.ops.mailbox import Mailbox

DECIDE_NULL = -1


def digest(x: jnp.ndarray) -> jnp.ndarray:
    """Cheap int32 mixing hash standing in for SHA-256 (collision-resistance
    is not the point of the *model*; pair-consistency checking is)."""
    h = x.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    return (h ^ (h >> 13)).astype(jnp.int32)


@flax.struct.dataclass
class BcpState:
    x: jnp.ndarray         # int32 request
    dig: jnp.ndarray       # int32 digest of x
    valid: jnp.ndarray     # bool — x is non-null and digest-consistent
    prepared: jnp.ndarray  # bool
    decided: jnp.ndarray
    decision: jnp.ndarray  # int32, -1 = null


def _coord(ctx: RoundCtx):
    return (ctx.r // 3) % ctx.n


class BcpPrePrepare(Round):
    def send(self, ctx: RoundCtx, state: BcpState):
        return broadcast(
            ctx,
            {"req": state.x, "dig": state.dig},
            guard=ctx.id == _coord(ctx),
        )

    def update(self, ctx: RoundCtx, state: BcpState, mbox: Mailbox):
        coord = _coord(ctx)
        got = mbox.contains(coord)
        req = mbox.values["req"][coord]
        claimed = mbox.values["dig"][coord]
        recomputed = digest(req)

        is_coord = ctx.id == coord
        adopt = got & ~is_coord
        x = jnp.where(adopt, req, state.x)
        dig = jnp.where(adopt, recomputed, state.dig)
        valid = jnp.where(adopt, recomputed == claimed, state.valid)

        # finishRound: abort on no/invalid request (Consensus.scala:90-97)
        fail = ~got | ~valid
        ctx.exit_at_end_of_round(fail)
        state = ghost_decide(state, fail, jnp.asarray(DECIDE_NULL))
        return state.replace(x=x, dig=dig, valid=valid)


class BcpPrepare(Round):
    def send(self, ctx: RoundCtx, state: BcpState):
        return broadcast(ctx, {"dig": state.dig, "ok": state.valid})

    def update(self, ctx: RoundCtx, state: BcpState, mbox: Mailbox):
        confirmed = mbox.count(
            lambda m: m["ok"] & (m["dig"] == state.dig)
        )
        return state.replace(prepared=confirmed > 2 * ctx.n // 3)


class BcpCommit(Round):
    def send(self, ctx: RoundCtx, state: BcpState):
        return broadcast(ctx, state.dig, guard=state.prepared)

    def update(self, ctx: RoundCtx, state: BcpState, mbox: Mailbox):
        confirmed = mbox.count(lambda d: d == state.dig)
        committed = confirmed > 2 * ctx.n // 3
        ctx.exit_at_end_of_round(True)  # terminate either way (:160)
        return ghost_decide(
            state, jnp.asarray(True), jnp.where(committed, state.x, DECIDE_NULL)
        )


class PbftConsensus(Algorithm):
    """Single-decision PBFT-style consensus, f < n/3 byzantine."""

    def __init__(self, synchronized: bool = False):
        rounds = (BcpPrePrepare(), BcpPrepare(), BcpCommit())
        if synchronized:
            from round_tpu.utils.byzantine import synchronize

            rounds = synchronize(rounds)
        self.rounds = rounds

    def make_init_state(self, ctx: RoundCtx, io) -> BcpState:
        x = jnp.asarray(io["initial_value"], dtype=jnp.int32)
        return BcpState(
            x=x,
            dig=digest(x),
            valid=jnp.asarray(True),
            prepared=jnp.asarray(False),
            decided=jnp.asarray(False),
            decision=jnp.asarray(DECIDE_NULL, dtype=jnp.int32),
        )

    def decided(self, state: BcpState):
        return state.decided

    def decision(self, state: BcpState):
        return state.decision
