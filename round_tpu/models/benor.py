"""Ben-Or — randomized binary consensus (two-round phases, coin flips).

Protocol (reference: example/BenOr.scala:11-88, after Ben-Or PODC'83 with the
termination tweak of Aguilera-Toueg):

  phase round 1: broadcast (x, canDecide).  If canDecide: decide(x) and exit.
    Else vote := Some(true) if >n/2 say true or someone who canDecide says
    true; symmetric for false; else None.  canDecide := anyone canDecide.
  phase round 2: broadcast vote.  If >n/2 vote Some(b): x := b, canDecide.
    Else if more than one vote Some(b): x := b.  Else x := coin flip.

The coin is the per-(scenario, process, round) PRNG key threaded through
RoundCtx.rng — reproducible across shardings (reference uses
util.Random.nextBoolean, BenOr.scala:77).

Option[Boolean] on the wire is a (tag, value) pair of int32s here: vote in
{-1 = None, 0 = Some(false), 1 = Some(true)}.
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.rounds import Round, RoundCtx, broadcast
from round_tpu.models.common import ghost_decide
from round_tpu.ops.mailbox import Mailbox
from round_tpu.spec.dsl import Spec, implies

VOTE_NONE = -1
VOTE_FALSE = 0
VOTE_TRUE = 1


@flax.struct.dataclass
class BenOrState:
    x: jnp.ndarray           # bool estimate
    can_decide: jnp.ndarray  # bool
    vote: jnp.ndarray        # int32 in {-1, 0, 1}
    decided: jnp.ndarray     # bool (ghost)
    decision: jnp.ndarray    # bool (ghost)


class BenOrRound1(Round):
    def send(self, ctx: RoundCtx, state: BenOrState):
        return broadcast(ctx, {"x": state.x, "can": state.can_decide})

    def update(self, ctx: RoundCtx, state: BenOrState, mbox: Mailbox):
        n = ctx.n
        t_cnt = mbox.count(lambda m: m["x"])
        f_cnt = mbox.count(lambda m: ~m["x"])
        t_dec = mbox.exists(lambda m: m["x"] & m["can"])
        f_dec = mbox.exists(lambda m: ~m["x"] & m["can"])

        vote = jnp.where(
            (t_cnt > n // 2) | t_dec,
            VOTE_TRUE,
            jnp.where((f_cnt > n // 2) | f_dec, VOTE_FALSE, VOTE_NONE),
        ).astype(jnp.int32)
        can = mbox.exists(lambda m: m["can"])

        # the canDecide branch decides and freezes (exit at end of round);
        # its vote/can updates never matter afterwards but are masked anyway
        deciding = state.can_decide
        ctx.exit_at_end_of_round(deciding)
        state = ghost_decide(state, deciding, state.x)
        return state.replace(
            vote=jnp.where(deciding, state.vote, vote),
            can_decide=jnp.where(deciding, state.can_decide, can),
        )


class BenOrRound2(Round):
    def __init__(self, coin_salt=None):
        # coin_salt = (salt0, salt1): use the deterministic hash coin
        # (ops.fused.hash_coin) instead of ctx.rng — the differential-parity
        # bridge to the fused engine, same role as hash-mode link masks
        self.coin_salt = coin_salt

    def send(self, ctx: RoundCtx, state: BenOrState):
        return broadcast(ctx, state.vote)

    def update(self, ctx: RoundCtx, state: BenOrState, mbox: Mailbox):
        n = ctx.n
        t = mbox.count(lambda v: v == VOTE_TRUE)
        f = mbox.count(lambda v: v == VOTE_FALSE)
        if self.coin_salt is None:
            coin = jax.random.bernoulli(ctx.rng)
        else:
            from round_tpu.ops.fused import hash_coin

            coin = hash_coin(
                self.coin_salt[0], self.coin_salt[1], ctx.r, ctx.id
            )

        x = jnp.where(
            t > n // 2,
            True,
            jnp.where(
                f > n // 2,
                False,
                jnp.where(t > 1, True, jnp.where(f > 1, False, coin)),
            ),
        )
        can = (t > n // 2) | (f > n // 2) | state.can_decide

        # decided lanes already exited in round 1 of this phase, but keep the
        # update masked for the phase in which they decide
        frozen = state.decided
        return state.replace(
            x=jnp.where(frozen, state.x, x),
            can_decide=jnp.where(frozen, state.can_decide, can),
        )


class BenOrSpec(Spec):
    """BenOr.scala:92-119, checked on traces.

    Safety needs every receiver to hear a majority each round (the spec's
    safetyPredicate, BenOr.scala:96) — under that assumption the invariant
    says: either nobody is committed yet, or a majority holds some value v
    and every decision/defined vote is on v.
    """

    def _safety(self, e):
        return e.P.forall(lambda p: p.HO.size > e.n // 2)

    def _inv0(self, e):
        P = e.P
        V = e.values(jnp.asarray([False, True]))
        fresh = P.forall(lambda i: ~i.decided & ~i.can_decide)
        locked = V.exists(
            lambda v: (P.filter(lambda i: i.x == v).size > e.n // 2)
            & P.forall(
                lambda i: implies(i.decided, i.decision == v)
                & implies(i.vote != VOTE_NONE, i.vote == v.astype(jnp.int32))
            )
        )
        return fresh | locked

    def _vote_majority(self, e):
        # roundInvariants[0]: a defined vote names a majority value
        # (BenOr.scala:112-114); holds after the first round of a phase.
        P = e.P
        return P.forall(
            lambda p: implies(
                p.vote != VOTE_NONE,
                P.filter(lambda i: i.x == (p.vote == VOTE_TRUE)).size > e.n // 2,
            )
        )

    def __init__(self):
        self.safety_predicate = self._safety
        self.invariants = (self._inv0,)
        self.round_invariants = ((self._vote_majority,),)
        self.properties = (
            (
                "Agreement",
                lambda e: e.P.forall(
                    lambda i: e.P.forall(
                        lambda j: implies(
                            i.decided & j.decided, i.decision == j.decision
                        )
                    )
                ),
            ),
            (
                "Irrevocability",
                lambda e: e.P.forall(
                    lambda i: implies(
                        i.old.decided, i.decided & (i.old.decision == i.decision)
                    )
                ),
            ),
        )


class BenOr(Algorithm):
    """Randomized binary consensus; terminates with probability 1.

    ``coin_salt=(salt0, salt1)`` switches round 2 to the deterministic hash
    coin so a FaultMix scenario replays bit-exactly against the fused
    engine (see BenOrRound2)."""

    def __init__(self, coin_salt=None):
        self.rounds = (BenOrRound1(), BenOrRound2(coin_salt=coin_salt))
        self.spec = BenOrSpec()

    def make_init_state(self, ctx: RoundCtx, io) -> BenOrState:
        return BenOrState(
            x=jnp.asarray(io["initial_value"], dtype=bool),
            can_decide=jnp.asarray(False),
            vote=jnp.asarray(VOTE_NONE, dtype=jnp.int32),
            decided=jnp.asarray(False),
            decision=jnp.asarray(False),
        )

    def decided(self, state: BenOrState):
        return state.decided

    def decision(self, state: BenOrState):
        return state.decision
