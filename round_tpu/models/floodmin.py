"""FloodMin — synchronous min-flooding consensus under f crash faults.

Protocol (reference: example/FloodMin.scala:22-33): every round broadcast x;
fold the received values into x with min; after f+1 rounds (``r > f``) decide
x and exit.  Tolerates f crash-stop faults in the synchronous model.
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.rounds import Round, RoundCtx, broadcast
from round_tpu.models.common import ghost_decide
from round_tpu.ops.mailbox import Mailbox


@flax.struct.dataclass
class FloodMinState:
    x: jnp.ndarray         # current min estimate (int32)
    decided: jnp.ndarray   # bool (ghost; reference decides via callback)
    decision: jnp.ndarray  # int32, -1 until decided


class FloodMinRound(Round):
    def __init__(self, f: int):
        self.f = f

    def send(self, ctx: RoundCtx, state: FloodMinState):
        return broadcast(ctx, state.x)

    def update(self, ctx: RoundCtx, state: FloodMinState, mbox: Mailbox):
        # x = mailbox.foldLeft(x)(min)   (FloodMin.scala:26)
        x = mbox.fold_min(state.x)
        deciding = ctx.r > self.f
        ctx.exit_at_end_of_round(deciding)
        return ghost_decide(state.replace(x=x), deciding, x)


class FloodMin(Algorithm):
    """f-crash-tolerant min-flooding (decide after round f)."""

    def __init__(self, f: int = 2):
        self.f = f
        self.rounds = (FloodMinRound(f),)

    def make_init_state(self, ctx: RoundCtx, io) -> FloodMinState:
        return FloodMinState(
            x=jnp.asarray(io["initial_value"], dtype=jnp.int32),
            decided=jnp.asarray(False),
            decision=jnp.asarray(-1, dtype=jnp.int32),
        )

    def decided(self, state: FloodMinState):
        return state.decided

    def decision(self, state: FloodMinState):
        return state.decision
