"""LastVoting — Paxos in the HO model (Charron-Bost & Schiper).

Protocol (reference: example/LastVoting.scala:80-212): 4-round phases with a
rotating coordinator ``coord = (r / 4) % n`` (LastVoting.scala:95):

  round 0: everyone sends (x, ts) to coord; coord with a majority picks the
           value with the highest timestamp as vote, commits.
  round 1: coord broadcasts vote if committed; receivers adopt x := vote,
           ts := current phase.
  round 2: processes with ts == phase ack to coord; coord with majority acks
           becomes ready.
  round 3: coord broadcasts vote if ready; receivers decide it.  ready and
           commit reset for the next phase.

The reference asserts initial values != 0 (vote=0 means "unset",
LastVoting.scala:133); we keep ts = -1 as "never adopted" and use the mailbox
presence mask instead of sentinel values, so 0 is a legal input.

Liveness needs one phase whose coordinator hears a majority and is heard by
everyone (the livenessPredicate, LastVoting.scala:20-22) — exercised in tests
via the coordinator_down / quorum families.
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.rounds import Round, RoundCtx, SendSpec, broadcast, unicast
from round_tpu.models.common import ghost_decide
from round_tpu.ops.mailbox import Mailbox
from round_tpu.spec.dsl import Spec, implies


@flax.struct.dataclass
class LVState:
    x: jnp.ndarray         # int32 estimate
    ts: jnp.ndarray        # int32 timestamp (phase of adoption), -1 initially
    ready: jnp.ndarray     # bool (coordinator)
    commit: jnp.ndarray    # bool (coordinator)
    vote: jnp.ndarray      # int32 (coordinator's proposal)
    decided: jnp.ndarray   # bool
    decision: jnp.ndarray  # int32, -1 until decided


def _coord(ctx: RoundCtx):
    return (ctx.r // 4) % ctx.n


class LVCollect(Round):
    """Round 0: send (x, ts) to coord; coord picks highest-ts value."""

    def send(self, ctx: RoundCtx, state: LVState):
        return unicast(ctx, _coord(ctx), {"x": state.x, "ts": state.ts})

    def update(self, ctx: RoundCtx, state: LVState, mbox: Mailbox):
        n = ctx.n
        is_coord = ctx.id == _coord(ctx)
        first_phase = ctx.r == 0
        have = mbox.size()
        act = is_coord & ((have > n // 2) | (first_phase & (have > 0)))
        # vote := the x of one of the largest ts received (maxBy over ts,
        # ties -> smallest sender id; LastVoting.scala:132)
        best = mbox.best_by(mbox.values["ts"])
        return state.replace(
            vote=jnp.where(act, best["x"], state.vote),
            commit=state.commit | act,
        )


class LVPropose(Round):
    """Round 1: committed coord broadcasts vote; receivers adopt it."""

    def send(self, ctx: RoundCtx, state: LVState):
        return broadcast(ctx, state.vote, guard=(ctx.id == _coord(ctx)) & state.commit)

    def update(self, ctx: RoundCtx, state: LVState, mbox: Mailbox):
        coord = _coord(ctx)
        got = mbox.contains(coord)
        return state.replace(
            x=jnp.where(got, mbox.get(coord), state.x),
            ts=jnp.where(got, ctx.r // 4, state.ts),
        )


class LVAck(Round):
    """Round 2: adopters ack to coord; coord with majority acks is ready."""

    def send(self, ctx: RoundCtx, state: LVState):
        return unicast(ctx, _coord(ctx), state.x, guard=state.ts == ctx.r // 4)

    def update(self, ctx: RoundCtx, state: LVState, mbox: Mailbox):
        n = ctx.n
        act = (ctx.id == _coord(ctx)) & (mbox.size() > n // 2)
        return state.replace(ready=state.ready | act)


class LVDecide(Round):
    """Round 3: ready coord broadcasts vote; receivers decide."""

    def send(self, ctx: RoundCtx, state: LVState):
        return broadcast(ctx, state.vote, guard=(ctx.id == _coord(ctx)) & state.ready)

    def update(self, ctx: RoundCtx, state: LVState, mbox: Mailbox):
        coord = _coord(ctx)
        got = mbox.contains(coord)
        ctx.exit_at_end_of_round(got)
        state = ghost_decide(state, got, mbox.get(coord))
        return state.replace(ready=jnp.asarray(False), commit=jnp.asarray(False))


class LVSpec(Spec):
    """LastVoting.scala:19-70, checked on traces at phase boundaries.

    The phase invariant (``safetyInv``): either nothing is decided/ready yet,
    or some value v backed by a majority of timestamps ≥ t locks every
    decision, commit and ready vote to v.  Evaluate with the engine's
    post-state round convention (env.r = recorded round + 1), at steps where
    env.r % 4 == 0 — i.e. between phases, where the reference states it.
    """

    def _liveness(self, e):
        def good_coord(p):
            return e.P.forall(
                lambda q: (p.id == (e.r // 4) % e.n)
                & p.HO.contains(q)
                & (p.HO.size > e.n // 2)
            )

        return e.P.exists(good_coord)

    def _no_decision(self, e):
        return e.P.forall(lambda i: ~i.decided & ~i.ready)

    def _majority(self, e):
        P = e.P
        V = e.values(e.state.x, e.state.vote)
        T_dom = e.values(e.state.ts)
        coord = e.proc((e.r // 4) % e.n)

        def with_v_t(v, t):
            A = P.filter(lambda i: i.ts >= t)
            return (
                (A.size > e.n // 2)
                & (e.r > 0)
                & (t <= e.r // 4)
                & P.forall(
                    lambda i: implies(A.contains(i), i.x == v)
                    & implies(i.decided, i.decision == v)
                    & implies(i.commit, i.vote == v)
                    & implies(i.ready, i.vote == v)
                    & implies(i.ts == e.r // 4, coord.commit)
                )
            )

        return V.exists(lambda v: T_dom.exists(lambda t: with_v_t(v, t)))

    def _keep_init(self, e):
        return e.P.forall(lambda i: e.P.exists(lambda j: i.x == j.init.x))

    def _inv0(self, e):
        return self._keep_init(e) & (self._no_decision(e) | self._majority(e))

    def _inv1(self, e):
        return e.P.exists(
            lambda j: e.P.forall(lambda i: i.decided & (i.decision == j.init.x))
        )

    def __init__(self):
        self.liveness_predicate = (self._liveness,)
        self.invariants = (self._inv0, self._inv1)
        self.properties = (
            ("Termination", lambda e: e.P.forall(lambda i: i.decided)),
            (
                "Agreement",
                lambda e: e.P.forall(
                    lambda i: e.P.forall(
                        lambda j: implies(
                            i.decided & j.decided, i.decision == j.decision
                        )
                    )
                ),
            ),
            (
                "Validity",
                lambda e: e.P.forall(
                    lambda i: implies(
                        i.decided, e.P.exists(lambda j: j.init.x == i.decision)
                    )
                ),
            ),
            (
                "Integrity",
                lambda e: e.P.exists(
                    lambda j: e.P.forall(
                        lambda i: implies(i.decided, i.decision == j.init.x)
                    )
                ),
            ),
            (
                "Irrevocability",
                lambda e: e.P.forall(
                    lambda i: implies(
                        i.old.decided, i.decided & (i.old.decision == i.decision)
                    )
                ),
            ),
        )


class LastVoting(Algorithm):
    """Paxos-style consensus with rotating coordinator (4-round phases)."""

    # Paxos resilience: majority quorums intersect, and a correct majority
    # exists whenever n > 2f (LastVoting.scala's benign-crash envelope;
    # verify/param.py proves both for all n under this condition)
    fault_envelope = "n > 2f"

    def __init__(self):
        self.rounds = (LVCollect(), LVPropose(), LVAck(), LVDecide())
        self.spec = LVSpec()

    def make_init_state(self, ctx: RoundCtx, io) -> LVState:
        return LVState(
            x=jnp.asarray(io["initial_value"], dtype=jnp.int32),
            ts=jnp.asarray(-1, dtype=jnp.int32),
            ready=jnp.asarray(False),
            commit=jnp.asarray(False),
            vote=jnp.asarray(0, dtype=jnp.int32),
            decided=jnp.asarray(False),
            decision=jnp.asarray(-1, dtype=jnp.int32),
        )

    def decided(self, state: LVState):
        return state.decided

    def decision(self, state: LVState):
        return state.decision


class LastVotingBytes(LastVoting):
    """LastVoting over OPAQUE fixed-width byte payloads — the LastVotingB
    role (example/LastVotingB.scala: consensus on Array[Byte] command
    batches).  The reference ships variable-length byte arrays through its
    serializer; the TPU-first form is a FIXED lane width ``payload_bytes``
    (uint8[B] vectors ride the engines as any vector payload; fixed width
    is what keeps the batch jittable — pad short commands, the SMR's
    batching already works in fixed-size batches).

    The four rounds are inherited UNCHANGED: they touch the value only
    through gathers and masked selects, which are payload-polymorphic.
    The trace spec is int-domain and does not apply here."""

    def __init__(self, payload_bytes: int = 16):
        super().__init__()
        self.payload_bytes = payload_bytes
        self.spec = None

    def make_init_state(self, ctx: RoundCtx, io) -> LVState:
        x = jnp.asarray(io["initial_value"], dtype=jnp.uint8)
        assert x.shape == (self.payload_bytes,), x.shape
        zeros = jnp.zeros((self.payload_bytes,), dtype=jnp.uint8)
        return LVState(
            x=x,
            ts=jnp.asarray(-1, dtype=jnp.int32),
            ready=jnp.asarray(False),
            commit=jnp.asarray(False),
            vote=zeros,
            decided=jnp.asarray(False),
            # no -1 sentinel in the byte domain: `decided` is the truth
            decision=zeros,
        )
