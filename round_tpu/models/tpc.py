"""Two-Phase Commit in the HO model.

Protocol (reference: example/TwoPhaseCommit.scala:16-81): a fixed coordinator
(from the IO, not rotating):

  round 0: coord broadcasts PrepareCommit (placeholder payload).
  round 1: everyone sends its vote (canCommit) to coord; coord decides
           Some(true) iff it heard *all n* votes and all are yes, else
           Some(false).
  round 2: coord broadcasts the decision; receivers adopt it if present and
           decide — deciding None means the coordinator is suspected of a
           crash (TpcIO.decide doc, TwoPhaseCommit.scala:13).

Decision encoding: int32 {-1 = None (suspect), 0 = abort, 1 = commit}.
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

from round_tpu.core.algorithm import Algorithm
from round_tpu.core.rounds import Round, RoundCtx, broadcast, unicast
from round_tpu.ops.mailbox import Mailbox

DEC_NONE = -1
DEC_ABORT = 0
DEC_COMMIT = 1


@flax.struct.dataclass
class TpcState:
    coord: jnp.ndarray     # int32, fixed coordinator id
    vote: jnp.ndarray      # bool, this process's canCommit
    decision: jnp.ndarray  # int32 in {-1, 0, 1}
    decided: jnp.ndarray   # bool (ghost: callback fired)


class TpcPrepare(Round):
    def send(self, ctx: RoundCtx, state: TpcState):
        return broadcast(ctx, jnp.asarray(True), guard=ctx.id == state.coord)

    def update(self, ctx: RoundCtx, state: TpcState, mbox: Mailbox):
        return state  # nothing to do (TwoPhaseCommit.scala:42-44)


class TpcVote(Round):
    def send(self, ctx: RoundCtx, state: TpcState):
        return unicast(ctx, state.coord, state.vote)

    def update(self, ctx: RoundCtx, state: TpcState, mbox: Mailbox):
        n = ctx.n
        is_coord = ctx.id == state.coord
        all_yes = (mbox.size() == n) & mbox.forall(lambda v: v)
        dec = jnp.where(all_yes, DEC_COMMIT, DEC_ABORT).astype(jnp.int32)
        return state.replace(decision=jnp.where(is_coord, dec, state.decision))


class TpcCommit(Round):
    def send(self, ctx: RoundCtx, state: TpcState):
        return broadcast(
            ctx, state.decision == DEC_COMMIT, guard=ctx.id == state.coord
        )

    def update(self, ctx: RoundCtx, state: TpcState, mbox: Mailbox):
        got = mbox.size() > 0
        v = jnp.where(mbox.any_value(), DEC_COMMIT, DEC_ABORT).astype(jnp.int32)
        ctx.exit_at_end_of_round(True)
        return state.replace(
            decision=jnp.where(got, v, state.decision),
            decided=jnp.asarray(True),
        )


class TwoPhaseCommit(Algorithm):
    """2PC with a fixed coordinator; one 3-round phase, always terminates."""

    def __init__(self):
        self.rounds = (TpcPrepare(), TpcVote(), TpcCommit())

    def make_init_state(self, ctx: RoundCtx, io) -> TpcState:
        return TpcState(
            coord=jnp.asarray(io["coord"], dtype=jnp.int32),
            vote=jnp.asarray(io["can_commit"], dtype=bool),
            decision=jnp.asarray(DEC_NONE, dtype=jnp.int32),
            decided=jnp.asarray(False),
        )

    def decided(self, state: TpcState):
        return state.decided

    def decision(self, state: TpcState):
        return state.decision


def tpc_io(coord, can_commit) -> dict:
    cc = jnp.asarray(can_commit)
    n = cc.shape[-1]
    return {
        "coord": jnp.broadcast_to(jnp.asarray(coord, dtype=jnp.int32), cc.shape[:-1] + (n,)),
        "can_commit": cc,
    }
